"""RACE reading-comprehension dataset (4-way multiple choice).

Reference: ``tasks/race/data.py`` — each *.txt file holds jsonl records
{article, questions, options, answers}; every question becomes one sample
of NUM_CHOICES stacked [CLS] qa [SEP] article [SEP] sequences.
"""

from __future__ import annotations

import glob
import json
import os

import numpy as np

from tasks.data_utils import (
    build_tokens_types_paddings_from_ids,
    clean_text,
)

NUM_CHOICES = 4
MAX_QA_LENGTH = 128


class RaceDataset:
    def __init__(self, dataset_name, datapaths, tokenizer, max_seq_length,
                 max_qa_length: int = MAX_QA_LENGTH):
        self.dataset_name = dataset_name
        self.sample_multiplier = NUM_CHOICES
        self.samples = []
        for path in datapaths:
            self.samples.extend(_process_path(path, tokenizer, max_qa_length,
                                              max_seq_length))
        print(f" > RACE/{dataset_name}: {len(self.samples)} samples",
              flush=True)

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        return self.samples[idx]


def _process_path(datapath, tokenizer, max_qa_length, max_seq_length):
    samples = []
    uid = 0
    for filename in sorted(glob.glob(os.path.join(datapath, "*.txt"))):
        with open(filename) as f:
            for line in f:
                record = json.loads(line)
                context_ids = tokenizer.tokenize(clean_text(record["article"]))
                for q, opts, ans in zip(record["questions"],
                                        record["options"],
                                        record["answers"]):
                    label = ord(ans) - ord("A")
                    assert 0 <= label < NUM_CHOICES == len(opts)
                    ids_c, types_c, pads_c = [], [], []
                    for choice in opts:
                        # cloze-style questions substitute the blank
                        qa = (q.replace("_", choice) if "_" in q
                              else f"{q} {choice}")
                        qa_ids = tokenizer.tokenize(clean_text(qa))
                        qa_ids = qa_ids[:max_qa_length]
                        ids, types, pads = build_tokens_types_paddings_from_ids(
                            qa_ids, list(context_ids), max_seq_length,
                            tokenizer.cls, tokenizer.sep, tokenizer.pad)
                        ids_c.append(ids)
                        types_c.append(types)
                        pads_c.append(pads)
                    samples.append({
                        "text": np.asarray(ids_c, np.int64),          # [C, s]
                        "types": np.asarray(types_c, np.int64),
                        "padding_mask": np.asarray(pads_c, np.int64),
                        "label": np.int64(label),
                        "uid": np.int64(uid),
                    })
                    uid += 1
    return samples
