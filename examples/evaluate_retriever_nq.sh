#!/bin/bash
# Zero-shot retriever evaluation on Natural Questions
# (reference: examples/evaluate_retriever_nq.sh): embed questions with the
# trained query tower, retrieve from the precomputed block index, report
# answer recall@k.
set -euo pipefail
CHECKPOINT=${1:?ICT checkpoint}
EVIDENCE=${2:?evidence data prefix}
TITLES=${3:?titles data prefix}
EMBEDDINGS=${4:?block embeddings .pkl (from the IndexBuilder)}
QA_FILE=${5:?nq dev jsonl/tsv}
VOCAB=${6:-bert-vocab.txt}

exec python tasks/main.py --task ICT-ZEROSHOT-NQ \
  --load "$CHECKPOINT" --use_checkpoint_args \
  --data_path "$EVIDENCE" --titles_data_path "$TITLES" \
  --embedding_path "$EMBEDDINGS" --qa_data_dev "$QA_FILE" \
  --micro_batch_size 32 --global_batch_size 32 --train_iters 0 --lr 0.0 \
  --seq_length 256 --max_position_embeddings 512 \
  --biencoder_projection_dim 128 \
  --retriever_report_topk_accuracies 1 5 20 100 \
  --tokenizer_type BertWordPieceLowerCase --vocab_file "$VOCAB"
