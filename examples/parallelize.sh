#!/bin/bash
# Reshard a checkpoint to a different (tp, pp) layout
# (reference: examples/parallelize.sh -> tools/checkpoint_util.py).
set -euo pipefail
LOAD=${1:?source checkpoint}
SAVE=${2:?target checkpoint dir}
TP=${3:-8}
PP=${4:-1}

exec python tools/checkpoint_util.py \
  --load_dir "$LOAD" --save_dir "$SAVE" \
  --target_tensor_parallel_size "$TP" \
  --target_pipeline_parallel_size "$PP"
