#!/bin/bash
# Single-host GPT-2 345M pretraining (reference: examples/pretrain_gpt.sh).
set -euo pipefail
DATA_PATH=${1:?usage: $0 <data prefix> [vocab.json] [merges.txt]}
VOCAB=${2:-gpt2-vocab.json}
MERGES=${3:-gpt2-merges.txt}

exec python pretrain_gpt.py \
  --num_layers 24 --hidden_size 1024 --num_attention_heads 16 \
  --seq_length 1024 --max_position_embeddings 1024 \
  --micro_batch_size 4 --global_batch_size 8 \
  --train_iters 500000 --lr_decay_iters 320000 \
  --lr 0.00015 --min_lr 1e-5 --lr_decay_style cosine \
  --lr_warmup_fraction 0.01 --weight_decay 0.01 --clip_grad 1.0 \
  --bf16 --data_path "$DATA_PATH" --split 949,50,1 \
  --tokenizer_type GPT2BPETokenizer \
  --vocab_file "$VOCAB" --merge_file "$MERGES" \
  --log_interval 100 --save_interval 10000 --eval_interval 1000 \
  --eval_iters 10 --save checkpoints/gpt_345m
