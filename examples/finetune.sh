#!/bin/bash
# Finetune a llama/mistral/falcon/gpt model on TPU.
# Mirrors the reference recipe (examples/finetune.sh) with TPU-native
# launch: no torchrun — one process per host; multi-host runs set
# RANK/WORLD_SIZE/MASTER_ADDR/MASTER_PORT (jax.distributed bootstrap).
#
# Usage: examples/finetune.sh <gpt/llama/llama2/llama3/codellama/falcon/mistral/mixtral>
#        [--tp=8] [--pp=1] [--micro-batch=1] [--global-batch=12]
#        [--iters=1000] [--checkpoint=...] [--data=...] [--out=...]
#        [--seq-len=...] [--instruct] [--wandb]

set -euo pipefail

MODEL=${1:?model name required}; shift || true
TP=8; PP=1; MICRO=1; GLOBAL=12; ITERS=1000
CKPT=none; DATA=none; OUT=checkpoints; SEQ=none
INSTRUCT=0; WANDB=0; LR="3e-4"; MIN_LR="3e-5"; LOSS_MASK=0.0

for arg in "$@"; do
  case $arg in
    --tp=*) TP=${arg#*=};;
    --pp=*) PP=${arg#*=};;
    --micro-batch=*) MICRO=${arg#*=};;
    --global-batch=*) GLOBAL=${arg#*=};;
    --iters=*) ITERS=${arg#*=};;
    --checkpoint=*) CKPT=${arg#*=};;
    --data=*) DATA=${arg#*=};;
    --out=*) OUT=${arg#*=};;
    --seq-len=*) SEQ=${arg#*=};;
    --lr=*) LR=${arg#*=};;
    --min-lr=*) MIN_LR=${arg#*=};;
    --loss-mask=*) LOSS_MASK=${arg#*=};;
    --instruct) INSTRUCT=1;;
    --wandb) WANDB=1;;
    *) echo "unknown arg $arg"; exit 1;;
  esac
done

# per-model defaults (reference: examples/finetune.sh model cases)
case $MODEL in
  llama|llama2|codellama)
    SEQ_DEFAULT=4096
    EXTRA=(--use_rms_norm --glu_activation swiglu --no_tie_embed_logits
           --position_embedding_type rotary --no_bias_gelu_fusion)
    TOKENIZER=SentencePieceTokenizer;;
  llama3)
    SEQ_DEFAULT=8192
    EXTRA=(--use_rms_norm --glu_activation swiglu --no_tie_embed_logits
           --position_embedding_type rotary --rope_theta 500000
           --no_bias_gelu_fusion)
    # llama-3.1+ context extension: add --rope_llama3_scaling 8 1 4 8192
    TOKENIZER=HFAutoTokenizer;;
  mistral)
    SEQ_DEFAULT=8192
    EXTRA=(--use_rms_norm --glu_activation swiglu --no_tie_embed_logits
           --position_embedding_type rotary --sliding_window_size 4096)
    TOKENIZER=SentencePieceTokenizer;;
  mixtral)
    SEQ_DEFAULT=8192
    EXTRA=(--use_rms_norm --glu_activation swiglu --no_tie_embed_logits
           --position_embedding_type rotary --num_experts 8 --moe_top_k 2
           --rope_theta 1e6)
    TOKENIZER=SentencePieceTokenizer;;
  falcon)
    SEQ_DEFAULT=2048
    EXTRA=(--parallel_attn --num_attention_heads_kv 1
           --position_embedding_type rotary)
    TOKENIZER=FalconTokenizer;;
  gpt)
    SEQ_DEFAULT=2048
    EXTRA=(--num_layers 12 --hidden_size 768 --num_attention_heads 12)
    TOKENIZER=GPT2BPETokenizer;;
  *) echo "unknown model $MODEL"; exit 1;;
esac
[ "$SEQ" = none ] && SEQ=$SEQ_DEFAULT

ARGS=(--model_name="$MODEL"
      --tensor_model_parallel_size="$TP"
      --pipeline_model_parallel_size="$PP"
      --micro_batch_size="$MICRO" --global_batch_size="$GLOBAL"
      --train_iters="$ITERS" --seq_length="$SEQ"
      --max_position_embeddings="$SEQ"
      --lr "$LR" --min_lr "$MIN_LR" --lr_decay_style cosine
      --lr_warmup_iters 100 --weight_decay 0.1 --clip_grad 1.0
      --bf16 --sequence_parallel --use_flash_attn
      --log_interval 1 --save_interval 200 --eval_interval 200
      --save "$OUT" --tokenizer_type "$TOKENIZER"
      "${EXTRA[@]}")

[ "$CKPT" != none ] && ARGS+=(--load "$CKPT" --use_checkpoint_args)
[ "$DATA" != none ] && ARGS+=(--data_path "$DATA")
[ "$INSTRUCT" = 1 ] && ARGS+=(--data_type instruction
                              --variable_seq_lengths
                              --scalar_loss_mask="$LOSS_MASK")
[ "$WANDB" = 1 ] && ARGS+=(--wandb_logger)

exec python finetune.py "${ARGS[@]}"
