#!/bin/bash
# REST generation server (reference: examples/run_text_generation_server_345M.sh).
set -euo pipefail
CHECKPOINT=${1:?checkpoint dir required}
TOKENIZER_MODEL=${2:?tokenizer model/vocab required}

exec python tools/run_text_generation_server.py \
  --model_name=llama2 --load "$CHECKPOINT" --use_checkpoint_args \
  --tokenizer_type SentencePieceTokenizer --vocab_file "$TOKENIZER_MODEL" \
  --bf16 --micro_batch_size 1 --train_iters 0 --lr 0.0 \
  --port 5000
