#!/bin/bash
# Multi-host GPT pretraining with tensor + pipeline parallelism
# (reference: examples/pretrain_gpt_distributed_with_mp.sh).
#
# Launch ONE copy per host with the bootstrap env set:
#   WORLD_SIZE=<n hosts> RANK=<this host> MASTER_ADDR=<host0> \
#   MASTER_PORT=8476 examples/pretrain_gpt_distributed_with_mp.sh <data>
# jax.distributed.initialize picks these up (topology.initialize_distributed).
set -euo pipefail
DATA_PATH=${1:?data prefix required}

exec python pretrain_gpt.py \
  --tensor_model_parallel_size 8 --pipeline_model_parallel_size 2 \
  --sequence_parallel \
  --num_layers 24 --hidden_size 1024 --num_attention_heads 16 \
  --seq_length 1024 --max_position_embeddings 1024 \
  --micro_batch_size 2 --global_batch_size 16 \
  --train_iters 500000 --lr 0.00015 --min_lr 1e-5 \
  --lr_decay_style cosine --lr_warmup_fraction 0.01 \
  --weight_decay 0.01 --clip_grad 1.0 --bf16 --use_flash_attn \
  --use_distributed_optimizer \
  --data_path "$DATA_PATH" --split 949,50,1 \
  --tokenizer_type GPT2BPETokenizer \
  --vocab_file gpt2-vocab.json --merge_file gpt2-merges.txt \
  --log_interval 100 --save_interval 10000 --save checkpoints/gpt_mp
