#!/bin/bash
# Convert HuggingFace (or Meta-format) weights into a TPU release
# checkpoint (reference: examples/hf_to_megatron.sh).
set -euo pipefail
MODEL=${1:?gpt/llama/llama2/codellama/falcon/mistral}
SRC=${2:?HF id / local path / Meta dir}
OUT=${3:-checkpoints/${MODEL}-release}

if [ -f "$SRC/params.json" ]; then
  exec python weights_conversion/hf_to_megatron.py "$MODEL" \
    --model_path "$SRC" --meta_weights --out "$OUT" --dtype bf16
fi
exec python weights_conversion/hf_to_megatron.py "$MODEL" \
  --model_path "$SRC" --out "$OUT" --dtype bf16
