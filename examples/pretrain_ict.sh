#!/bin/bash
# BiEncoder inverse-cloze-task pretraining (reference: examples/pretrain_ict.sh).
# Needs a sentence-level evidence corpus + a one-title-per-document dataset.
set -euo pipefail
DATA_PATH=${1:?evidence data prefix required}
TITLES_PATH=${2:?titles data prefix required}
VOCAB=${3:-bert-vocab.txt}

exec python pretrain_ict.py \
  --num_layers 12 --hidden_size 768 --num_attention_heads 12 \
  --seq_length 256 --max_position_embeddings 512 \
  --micro_batch_size 32 --global_batch_size 128 \
  --train_iters 100000 --lr 0.0001 --min_lr 1e-5 \
  --lr_decay_style linear --lr_warmup_fraction 0.01 \
  --weight_decay 0.01 --clip_grad 1.0 --bf16 \
  --data_path "$DATA_PATH" --titles_data_path "$TITLES_PATH" \
  --split 100,0,0 \
  --tokenizer_type BertWordPieceLowerCase --vocab_file "$VOCAB" \
  --query_in_block_prob 0.1 --biencoder_projection_dim 128 \
  --retriever_score_scaling \
  --log_interval 100 --save_interval 10000 --save checkpoints/ict
