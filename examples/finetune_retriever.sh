#!/bin/bash
# Biencoder retriever finetune + evidence indexing + NQ eval
# (reference: examples/finetune_retriever_distributed.sh +
# evaluate_retriever_nq.sh).  TPU single-controller: no torchrun; tp/dp
# come from the flags.
set -euo pipefail
WIKI_TSV=${1:?usage: $0 <wiki-evidence.tsv> <nq-dev.jsonl> <vocab.txt> [ckpt]}
QA_DEV=${2:?}
VOCAB=${3:?}
CKPT=${4:-}

ARGS=(
  --num_layers 12 --hidden_size 768 --num_attention_heads 12
  --seq_length 512 --max_position_embeddings 512
  --retriever_seq_length 256
  --micro_batch_size 8
  --tokenizer_type BertWordPieceLowerCase --vocab_file "$VOCAB"
  --biencoder_projection_dim 128
)
[ -n "$CKPT" ] && ARGS+=(--load "$CKPT")

# 1. embed the evidence corpus with the context tower (skipped if the
#    store exists), 2. report retriever recall@k on NQ dev
exec python tasks/main.py --task RETRIEVER-EVAL \
  "${ARGS[@]}" \
  --evidence_data_path "$WIKI_TSV" \
  --embedding_path wiki_evidence_emb.pkl \
  --qa_data_dev "$QA_DEV" \
  --retriever_report_topk_accuracies 1 5 20 100
