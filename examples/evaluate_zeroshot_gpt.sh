#!/bin/bash
# Zero-shot LM evaluation: WIKITEXT103 ppl or LAMBADA accuracy
# (reference: examples/evaluate_zeroshot_gpt.sh).
set -euo pipefail
TASK=${1:?WIKITEXT103 or LAMBADA}
VALID_DATA=${2:?validation file}
CHECKPOINT=${3:?checkpoint dir}

exec python tasks/main.py --task "$TASK" \
  --valid_data "$VALID_DATA" --load "$CHECKPOINT" --use_checkpoint_args \
  --micro_batch_size 8 --global_batch_size 8 --train_iters 0 --lr 0.0 \
  --overlapping_eval 32 --log_interval 10 \
  --tokenizer_type GPT2BPETokenizer \
  --vocab_file gpt2-vocab.json --merge_file gpt2-merges.txt
