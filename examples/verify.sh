#!/bin/bash
# Correctness check of a converted checkpoint vs the HF reference weights
# (reference: examples/verify.sh -> verify_correctness.py).
set -euo pipefail
MODEL=${1:?model name}
CKPT=${2:?converted checkpoint}
HF_PATH=${3:?HF baseline path}

exec python verify_correctness.py --model_name="$MODEL" \
  --load "$CKPT" --huggingface_path "$HF_PATH" \
  --iters 10 --batch 2 --seq_length 512
