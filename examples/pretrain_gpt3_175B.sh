#!/bin/bash
# GPT-3 175B pretraining at scale (reference: examples/pretrain_gpt3_175B.sh,
# a 128-node SLURM/A100 recipe).  TPU version: one process per host over a
# v5p pod slice; jax.distributed rendezvous uses the torchrun-style env
# (RANK / WORLD_SIZE / MASTER_ADDR / MASTER_PORT) on every host.
#
# Layout: tp=8 (intra-host ICI) x pp=16 x dp=(chips/128); ZeRO-1 shards
# optimizer state over dp.  Sanity-check the per-chip HBM of a layout
# without hardware first:
#   python tools/aot_memcheck.py --list   (add a config with these shapes)
set -euo pipefail
DATA_PATH=${1:?usage: $0 <blended data spec...>}

exec python pretrain_gpt.py \
  --tensor_model_parallel_size 8 \
  --pipeline_model_parallel_size 16 \
  --num_layers 96 --hidden_size 12288 --num_attention_heads 96 \
  --seq_length 2048 --max_position_embeddings 2048 \
  --micro_batch_size 1 --global_batch_size 1536 \
  --rampup_batch_size 16 16 5859375 \
  --train_samples 146484375 \
  --lr_decay_samples 126953125 \
  --lr_warmup_samples 183105 \
  --lr 6.0e-5 --min_lr 6.0e-6 --lr_decay_style cosine \
  --weight_decay 0.1 --clip_grad 1.0 \
  --adam_beta1 0.9 --adam_beta2 0.95 --init_method_std 0.006 \
  --bf16 --sequence_parallel --use_distributed_optimizer \
  --recompute_granularity selective \
  --data_path "$DATA_PATH" --split 949,50,1 \
  --tokenizer_type GPT2BPETokenizer \
  --vocab_file gpt2-vocab.json --merge_file gpt2-merges.txt \
  --log_interval 10 --save_interval 1000 --eval_interval 1000 \
  --eval_iters 10 --save checkpoints/gpt3_175b
