#!/bin/bash
# RACE multiple-choice finetune (reference: examples/finetune_race_distributed.sh).
set -euo pipefail
TRAIN_DATA=${1:?RACE/train/middle (dir)}
VALID_DATA=${2:?RACE/dev/middle (dir)}
PRETRAINED=${3:?pretrained BERT checkpoint}
VOCAB=${4:-bert-vocab.txt}

exec python tasks/main.py --task RACE \
  --train_data "$TRAIN_DATA" --valid_data "$VALID_DATA" \
  --pretrained_checkpoint "$PRETRAINED" --epochs 3 \
  --num_layers 24 --hidden_size 1024 --num_attention_heads 16 \
  --seq_length 512 --max_position_embeddings 512 \
  --micro_batch_size 4 --global_batch_size 32 --train_iters 0 \
  --lr 1e-5 --min_lr 0 --lr_decay_style linear --weight_decay 1e-2 \
  --clip_grad 1.0 --bf16 \
  --tokenizer_type BertWordPieceLowerCase --vocab_file "$VOCAB" \
  --log_interval 10 --save checkpoints/bert_race
