#!/bin/bash
# BERT-large MLM+SOP pretraining (reference: examples/pretrain_bert.sh).
# The data prefix must be a SENTENCE-LEVEL corpus: build it with
#   tools/preprocess_data.py --split_sentences
set -euo pipefail
DATA_PATH=${1:?data prefix required}
VOCAB=${2:-bert-vocab.txt}

exec python pretrain_bert.py \
  --num_layers 24 --hidden_size 1024 --num_attention_heads 16 \
  --seq_length 512 --max_position_embeddings 512 \
  --micro_batch_size 4 --global_batch_size 32 \
  --train_iters 1000000 --lr 0.0001 --min_lr 1e-5 \
  --lr_decay_style linear --lr_warmup_fraction 0.01 \
  --weight_decay 0.01 --clip_grad 1.0 --bf16 \
  --data_path "$DATA_PATH" --split 949,50,1 \
  --tokenizer_type BertWordPieceLowerCase --vocab_file "$VOCAB" \
  --masked_lm_prob 0.15 --short_seq_prob 0.1 \
  --log_interval 100 --save_interval 10000 --save checkpoints/bert_large
