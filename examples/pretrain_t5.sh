#!/bin/bash
# T5 span-corruption pretraining (reference: examples/pretrain_t5.sh).
# Sentence-level corpus (tools/preprocess_data.py --split_sentences) and a
# tokenizer with --vocab_extra_ids sentinels.
set -euo pipefail
DATA_PATH=${1:?data prefix required}
VOCAB=${2:-bert-vocab.txt}

exec python pretrain_t5.py \
  --num_layers 12 --hidden_size 768 --num_attention_heads 12 \
  --kv_channels 64 --ffn_hidden_size 3072 \
  --seq_length 512 --decoder_seq_length 128 \
  --max_position_embeddings 512 \
  --micro_batch_size 16 --global_batch_size 128 \
  --train_iters 1000000 --lr 0.0001 --min_lr 1e-5 \
  --lr_decay_style linear --lr_warmup_fraction 0.01 \
  --weight_decay 0.01 --clip_grad 1.0 --bf16 \
  --data_path "$DATA_PATH" --split 949,50,1 \
  --tokenizer_type BertWordPieceLowerCase --vocab_file "$VOCAB" \
  --vocab_extra_ids 100 --masked_lm_prob 0.15 --short_seq_prob 0.1 \
  --log_interval 100 --save_interval 10000 --save checkpoints/t5_base
