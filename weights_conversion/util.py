"""Weight-layout transforms shared by the HF <-> TPU converters.

Reference: ``weights_conversion/hf_to_megatron.py:117-258`` (rotary QKV
permutation + GQA packing) and ``megatron_to_hf.py:47-79`` (inverse).

Layout facts:

* HF applies RoPE with rotate-half (feature halves), Meta/this framework
  with interleaved even/odd pairs — converting requires permuting the
  rows of the q/k projections per head: meta_row[2p + h] = hf_row[p + h*d/2].
* This framework packs QKV column-parallel in Megatron's grouped-GQA
  layout ``[ng, q_per_group + 2, d]`` over the output dim
  (models/transformer.py:_qkv_out_dim), kernels stored [in, out]
  (HF Linear stores [out, in]).
"""

from __future__ import annotations

import numpy as np


def rotary_hf_to_interleaved(w: np.ndarray, head_dim: int) -> np.ndarray:
    """Permute rows of an HF q/k projection [n_heads*d, hidden] from
    rotate-half to interleaved layout."""
    out_dim, hidden = w.shape
    n_heads = out_dim // head_dim
    w = w.reshape(n_heads, 2, head_dim // 2, hidden)
    w = np.transpose(w, (0, 2, 1, 3))  # [nh, d/2, 2, hid]
    return w.reshape(out_dim, hidden)


def rotary_interleaved_to_hf(w: np.ndarray, head_dim: int) -> np.ndarray:
    """Inverse of rotary_hf_to_interleaved."""
    out_dim, hidden = w.shape
    n_heads = out_dim // head_dim
    w = w.reshape(n_heads, head_dim // 2, 2, hidden)
    w = np.transpose(w, (0, 2, 1, 3))  # [nh, 2, d/2, hid]
    return w.reshape(out_dim, hidden)


def pack_qkv(
    q: np.ndarray, k: np.ndarray, v: np.ndarray,
    num_heads: int, num_kv_heads: int, head_dim: int,
) -> np.ndarray:
    """[*, hidden] HF projections -> packed grouped kernel [hidden, qkv_out].

    q: [nh*d, hid], k/v: [ng*d, hid] ->
    kernel [hid, ng*(qpg+2)*d] with per-group [q_0..q_{qpg-1}, k, v].
    """
    ng, qpg = num_kv_heads, num_heads // num_kv_heads
    d = head_dim
    hid = q.shape[1]
    qg = q.reshape(ng, qpg, d, hid)
    kg = k.reshape(ng, 1, d, hid)
    vg = v.reshape(ng, 1, d, hid)
    packed = np.concatenate([qg, kg, vg], axis=1)  # [ng, qpg+2, d, hid]
    packed = packed.reshape(ng * (qpg + 2) * d, hid)
    return np.ascontiguousarray(packed.T)  # [hid, out]


def unpack_qkv(
    kernel: np.ndarray, num_heads: int, num_kv_heads: int, head_dim: int,
):
    """Inverse of pack_qkv: kernel [hid, out] -> (q, k, v) HF-shaped
    [*, hidden]."""
    ng, qpg = num_kv_heads, num_heads // num_kv_heads
    d = head_dim
    hid = kernel.shape[0]
    w = np.ascontiguousarray(kernel.T).reshape(ng, qpg + 2, d, hid)
    q = w[:, :qpg].reshape(ng * qpg * d, hid)
    k = w[:, qpg].reshape(ng * d, hid)
    v = w[:, qpg + 1].reshape(ng * d, hid)
    return q, k, v


def pack_glu_ffn(gate: np.ndarray, up: np.ndarray) -> np.ndarray:
    """HF gate_proj/up_proj [ffn, hid] -> dense_h_to_4h kernel
    [hid, 2*ffn] with (a=gate | b=up) halves matching
    ops/activations.swiglu's chunk order."""
    return np.ascontiguousarray(np.concatenate([gate, up], axis=0).T)


def unpack_glu_ffn(kernel: np.ndarray):
    w = np.ascontiguousarray(kernel.T)
    ffn = w.shape[0] // 2
    return w[:ffn], w[ffn:]


def rotary_hf_to_interleaved_bias(b: np.ndarray, head_dim: int) -> np.ndarray:
    """Bias analogue of ``rotary_hf_to_interleaved`` ([out_dim] vector)."""
    return rotary_hf_to_interleaved(b[:, None], head_dim)[:, 0]


def pack_qkv_bias(
    qb: np.ndarray, kb: np.ndarray, vb: np.ndarray,
    num_heads: int, num_kv_heads: int, head_dim: int,
) -> np.ndarray:
    """Bias analogue of ``pack_qkv``: [*] vectors -> packed
    [ng*(qpg+2)*d] matching the grouped kernel column order."""
    ng, qpg = num_kv_heads, num_heads // num_kv_heads
    d = head_dim
    qg = qb.reshape(ng, qpg, d)
    kg = kb.reshape(ng, 1, d)
    vg = vb.reshape(ng, 1, d)
    return np.ascontiguousarray(
        np.concatenate([qg, kg, vg], axis=1).reshape(ng * (qpg + 2) * d))


def rotary_interleaved_to_hf_bias(b: np.ndarray, head_dim: int) -> np.ndarray:
    """Inverse of ``rotary_hf_to_interleaved_bias``."""
    return rotary_interleaved_to_hf(b[:, None], head_dim)[:, 0]


def unpack_qkv_bias(
    packed: np.ndarray, num_heads: int, num_kv_heads: int, head_dim: int,
):
    """Inverse of ``pack_qkv_bias``: [ng*(qpg+2)*d] -> (qb, kb, vb)."""
    ng, qpg = num_kv_heads, num_heads // num_kv_heads
    d = head_dim
    w = packed.reshape(ng, qpg + 2, d)
    qb = w[:, :qpg].reshape(ng * qpg * d)
    kb = w[:, qpg].reshape(ng * d)
    vb = w[:, qpg + 1].reshape(ng * d)
    return qb, kb, vb
