#!/usr/bin/env python
"""TPU-framework checkpoint -> HuggingFace conversion.

Reference: ``weights_conversion/megatron_to_hf.py`` — inverse QKV/FFN
un-packing (:47-79) and per-architecture writers (:80-572).

Usage:
    python weights_conversion/megatron_to_hf.py \
        --input_dir /ckpts/llama2-7b --output_dir /out/hf \
        --model llama2
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from weights_conversion.util import (
    rotary_interleaved_to_hf,
    rotary_interleaved_to_hf_bias,
    unpack_glu_ffn,
    unpack_qkv,
    unpack_qkv_bias,
)


def _dense_glu_mlp_writer(sd, p, g, t):
    gate, up = unpack_glu_ffn(g("mlp", "dense_h_to_4h", "kernel"))
    sd[p + "mlp.gate_proj.weight"] = t(gate)
    sd[p + "mlp.up_proj.weight"] = t(up)
    sd[p + "mlp.down_proj.weight"] = t(
        np.ascontiguousarray(g("mlp", "dense_4h_to_h", "kernel").T))


def llama_family_state_dict(params, config, *, mlp_writer=None):
    """param pytree -> HF LlamaForCausalLM/MistralForCausalLM state dict.

    ``mlp_writer(sd, prefix, g, t)``: per-layer mlp emitter hook — defaults
    to the dense GLU mlp; mixtral_state_dict swaps in the MoE one."""
    import torch

    nh = config["num_attention_heads"]
    ng = config.get("num_attention_heads_kv") or nh
    # gemma decouples head_dim from hidden/heads
    d = config.get("kv_channels") or config["hidden_size"] // nh
    L = config["num_layers"]
    t = lambda a: torch.tensor(np.asarray(a, np.float32))
    mlp_writer = mlp_writer or _dense_glu_mlp_writer

    sd = {
        "model.embed_tokens.weight": t(
            params["embedding"]["word"]["embedding"]),
        "model.norm.weight": t(params["transformer"]["final_norm"]["scale"]),
    }
    if "lm_head" in params:
        sd["lm_head.weight"] = t(params["lm_head"]["weight"])
    else:
        # tied head (Qwen2-0.5B/1.5B): HF re-ties from the embedding
        sd["lm_head.weight"] = sd["model.embed_tokens.weight"]
    layers = params["transformer"]["layers"]
    has_qkv_bias = "bias" in layers["attention"]["query_key_value"]
    for i in range(L):
        g = lambda *path: np.asarray(_index(layers, path, i), np.float32)
        p = f"model.layers.{i}."
        q, k, v = unpack_qkv(g("attention", "query_key_value", "kernel"),
                             nh, ng, d)
        sd[p + "self_attn.q_proj.weight"] = t(rotary_interleaved_to_hf(q, d))
        sd[p + "self_attn.k_proj.weight"] = t(rotary_interleaved_to_hf(k, d))
        sd[p + "self_attn.v_proj.weight"] = t(v)
        if has_qkv_bias:
            qb, kb, vb = unpack_qkv_bias(
                g("attention", "query_key_value", "bias"), nh, ng, d)
            sd[p + "self_attn.q_proj.bias"] = t(
                rotary_interleaved_to_hf_bias(qb, d))
            sd[p + "self_attn.k_proj.bias"] = t(
                rotary_interleaved_to_hf_bias(kb, d))
            sd[p + "self_attn.v_proj.bias"] = t(vb)
        sd[p + "self_attn.o_proj.weight"] = t(
            np.ascontiguousarray(g("attention", "dense", "kernel").T))
        mlp_writer(sd, p, g, t)
        sd[p + "input_layernorm.weight"] = t(g("input_norm", "scale"))
        sd[p + "post_attention_layernorm.weight"] = t(
            g("post_attention_norm", "scale"))
    return sd


def gemma_state_dict(params, config):
    """param pytree -> HF GemmaForCausalLM state dict: the llama-family
    writer with the stored ``1 + w`` RMSNorm scales converted back to
    HF's zero-centered weights; the tied head is re-tied by HF."""
    sd = llama_family_state_dict(params, config)
    for k in list(sd):
        if k.endswith("layernorm.weight") or k == "model.norm.weight":
            sd[k] = sd[k] - 1.0
    return sd


def falcon_state_dict(params, config):
    """param pytree -> HF FalconForCausalLM state dict (inverse of
    hf_to_megatron.convert_falcon; reference writer:
    megatron_to_hf.py:333-475)."""
    import torch

    nh = config["num_attention_heads"]
    ng = config.get("num_attention_heads_kv") or nh
    d = config["hidden_size"] // nh
    qpg = nh // ng
    L = config["num_layers"]
    new_arch = bool(config.get("parallel_layernorm"))
    t = lambda a: torch.tensor(np.asarray(a, np.float32))

    emb = np.asarray(params["embedding"]["word"]["embedding"], np.float32)
    sd = {
        "transformer.word_embeddings.weight": t(emb),
        "transformer.ln_f.weight": t(
            params["transformer"]["final_norm"]["scale"]),
        "transformer.ln_f.bias": t(
            params["transformer"]["final_norm"]["bias"]),
        "lm_head.weight": t(emb),          # falcon ties head to embeddings
    }
    layers = params["transformer"]["layers"]
    for i in range(L):
        g = lambda *path: np.asarray(_index(layers, path, i), np.float32)
        p = f"transformer.h.{i}."
        # grouped qkv with per-(q|k)-head rotary de-interleave, v untouched
        w = np.ascontiguousarray(
            g("attention", "query_key_value", "kernel").T)
        hid = w.shape[-1]
        w = w.reshape(ng, qpg + 2, d, hid)
        for grp in range(ng):
            for h in range(qpg + 1):
                w[grp, h] = rotary_interleaved_to_hf(
                    w[grp, h].reshape(d, hid), d).reshape(d, hid)
        sd[p + "self_attention.query_key_value.weight"] = t(
            w.reshape(ng * (qpg + 2) * d, hid))
        sd[p + "self_attention.dense.weight"] = t(
            np.ascontiguousarray(g("attention", "dense", "kernel").T))
        sd[p + "mlp.dense_h_to_4h.weight"] = t(
            np.ascontiguousarray(g("mlp", "dense_h_to_4h", "kernel").T))
        sd[p + "mlp.dense_4h_to_h.weight"] = t(
            np.ascontiguousarray(g("mlp", "dense_4h_to_h", "kernel").T))
        if new_arch:
            sd[p + "ln_attn.weight"] = t(g("input_norm", "scale"))
            sd[p + "ln_attn.bias"] = t(g("input_norm", "bias"))
            sd[p + "ln_mlp.weight"] = t(g("mlp_norm", "scale"))
            sd[p + "ln_mlp.bias"] = t(g("mlp_norm", "bias"))
        else:
            sd[p + "input_layernorm.weight"] = t(g("input_norm", "scale"))
            sd[p + "input_layernorm.bias"] = t(g("input_norm", "bias"))
    return sd


def mixtral_state_dict(params, config):
    """param pytree -> HF MixtralForCausalLM state dict (inverse of
    hf_to_megatron.convert_mixtral): trunk shared with the llama family,
    MoE MLP back to block_sparse_moe gate/w1/w2/w3."""
    E = config["num_experts"]

    def moe_writer(sd, p, g, t):
        moe = p + "block_sparse_moe."
        sd[moe + "gate.weight"] = t(
            np.ascontiguousarray(g("mlp", "router", "kernel").T))
        w_in = g("mlp", "experts", "w_in")      # [E, h, 2f]
        w_out = g("mlp", "experts", "w_out")    # [E, f, h]
        for e in range(E):
            gate, up = unpack_glu_ffn(w_in[e])
            sd[f"{moe}experts.{e}.w1.weight"] = t(gate)
            sd[f"{moe}experts.{e}.w3.weight"] = t(up)
            sd[f"{moe}experts.{e}.w2.weight"] = t(
                np.ascontiguousarray(w_out[e].T))

    return llama_family_state_dict(params, config, mlp_writer=moe_writer)


def _index(tree, path, i):
    for k in path:
        tree = tree[k]
    return tree[i]


def _rope_scaling_dict(config: dict):
    """framework rope fields -> HF ``rope_scaling`` (or None): the
    llama3 NTK-by-parts tuple wins, else a non-1.0 linear factor."""
    l3 = config.get("rope_llama3_scaling")
    if l3:
        return {
            "rope_type": "llama3", "factor": l3[0],
            "low_freq_factor": l3[1], "high_freq_factor": l3[2],
            "original_max_position_embeddings": int(l3[3]),
        }
    if config.get("rope_scaling_factor", 1.0) != 1.0:
        return {"rope_type": "linear",
                "factor": config["rope_scaling_factor"]}
    return None


def hf_config_for(model_name: str, config: dict):
    rope_scaling = _rope_scaling_dict(config)
    if model_name in ("llama", "llama2", "llama3", "codellama"):
        from transformers import LlamaConfig

        return LlamaConfig(
            vocab_size=config["padded_vocab_size"],
            hidden_size=config["hidden_size"],
            intermediate_size=config["ffn_hidden_size"],
            num_hidden_layers=config["num_layers"],
            num_attention_heads=config["num_attention_heads"],
            num_key_value_heads=config.get("num_attention_heads_kv"),
            max_position_embeddings=config["max_position_embeddings"],
            rms_norm_eps=config.get("layernorm_epsilon", 1e-5),
            rope_theta=config.get("rope_theta", 10000.0),
            rope_scaling=rope_scaling,
            tie_word_embeddings=False,
        )
    if model_name == "mistral":
        from transformers import MistralConfig

        return MistralConfig(
            vocab_size=config["padded_vocab_size"],
            hidden_size=config["hidden_size"],
            intermediate_size=config["ffn_hidden_size"],
            num_hidden_layers=config["num_layers"],
            num_attention_heads=config["num_attention_heads"],
            num_key_value_heads=config.get("num_attention_heads_kv"),
            max_position_embeddings=config["max_position_embeddings"],
            rms_norm_eps=config.get("layernorm_epsilon", 1e-5),
            rope_theta=config.get("rope_theta", 10000.0),
            rope_scaling=rope_scaling,
            sliding_window=config.get("sliding_window_size", 4096),
            tie_word_embeddings=False,
        )
    if model_name == "mixtral":
        from transformers import MixtralConfig

        return MixtralConfig(
            vocab_size=config["padded_vocab_size"],
            hidden_size=config["hidden_size"],
            intermediate_size=config["ffn_hidden_size"],
            num_hidden_layers=config["num_layers"],
            num_attention_heads=config["num_attention_heads"],
            num_key_value_heads=config.get("num_attention_heads_kv"),
            max_position_embeddings=config["max_position_embeddings"],
            rms_norm_eps=config.get("layernorm_epsilon", 1e-5),
            rope_theta=config.get("rope_theta", 1e6),
            rope_scaling=rope_scaling,
            sliding_window=config.get("sliding_window_size"),
            num_local_experts=config["num_experts"],
            num_experts_per_tok=config.get("moe_top_k", 2),
            tie_word_embeddings=False,
        )
    if model_name == "falcon":
        from transformers import FalconConfig

        ng = config.get("num_attention_heads_kv") \
            or config["num_attention_heads"]
        new_arch = bool(config.get("parallel_layernorm"))
        return FalconConfig(
            vocab_size=config["padded_vocab_size"],
            hidden_size=config["hidden_size"],
            num_hidden_layers=config["num_layers"],
            num_attention_heads=config["num_attention_heads"],
            num_kv_heads=ng,
            new_decoder_architecture=new_arch,
            multi_query=(ng == 1 and not new_arch),
            parallel_attn=bool(config.get("parallel_attn", True)),
            bias=bool(config.get("add_bias_linear", False)),
            layer_norm_epsilon=config.get("layernorm_epsilon", 1e-5),
            tie_word_embeddings=True,
        )
    if model_name == "gemma":
        from transformers import GemmaConfig

        return GemmaConfig(
            vocab_size=config["padded_vocab_size"],
            hidden_size=config["hidden_size"],
            intermediate_size=config["ffn_hidden_size"],
            num_hidden_layers=config["num_layers"],
            num_attention_heads=config["num_attention_heads"],
            num_key_value_heads=config.get("num_attention_heads_kv"),
            head_dim=config.get("kv_channels"),
            max_position_embeddings=config["max_position_embeddings"],
            rms_norm_eps=config.get("layernorm_epsilon", 1e-6),
            rope_theta=config.get("rope_theta", 10000.0),
            hidden_act="gelu_pytorch_tanh",
            tie_word_embeddings=True,
        )
    if model_name == "qwen2":
        from transformers import Qwen2Config

        return Qwen2Config(
            vocab_size=config["padded_vocab_size"],
            hidden_size=config["hidden_size"],
            intermediate_size=config["ffn_hidden_size"],
            num_hidden_layers=config["num_layers"],
            num_attention_heads=config["num_attention_heads"],
            num_key_value_heads=config.get("num_attention_heads_kv"),
            max_position_embeddings=config["max_position_embeddings"],
            rms_norm_eps=config.get("layernorm_epsilon", 1e-6),
            rope_theta=config.get("rope_theta", 1e6),
            rope_scaling=rope_scaling,
            use_sliding_window=config.get("sliding_window_size") is not None,
            sliding_window=config.get("sliding_window_size"),
            tie_word_embeddings=bool(config.get("tie_embed_logits", False)),
        )
    raise NotImplementedError(f"HF export for {model_name!r}")


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--input_dir", "--input-dir", dest="input_dir",
                   required=True)
    p.add_argument("--output_dir", "--output-dir", dest="output_dir",
                   required=True)
    p.add_argument("--model", default=None,
                   help="override model family (else read from ckpt args)")
    args = p.parse_args()

    from transformers import AutoModelForCausalLM

    from megatron_llm_tpu import checkpointing

    params, _, meta = checkpointing.load_checkpoint(args.input_dir,
                                                    finetune=True)
    if params is None:
        # release checkpoint
        params, _, meta = checkpointing.load_checkpoint(
            args.input_dir, release=True, finetune=True
        )
    config = meta["args"]
    model_name = args.model or config.get("model_name", "llama2")

    hf_cfg = hf_config_for(model_name, config)
    hf = AutoModelForCausalLM.from_config(hf_cfg)
    writer = {"falcon": falcon_state_dict,
              "mixtral": mixtral_state_dict,
              "gemma": gemma_state_dict}.get(
        model_name, llama_family_state_dict)
    sd = writer(params, config)
    missing, unexpected = hf.load_state_dict(sd, strict=False)
    if missing or unexpected:
        print(f" note: missing={missing} unexpected={unexpected}")
    hf.save_pretrained(args.output_dir, safe_serialization=True)
    print(f" exported {args.input_dir} -> {args.output_dir}")


if __name__ == "__main__":
    main()
