"""Megatron ``mp_rank`` checkpoint interop (torch format, both ways).

Reference layout (``megatron/checkpointing.py:77-140,340-411``)::

    <dir>/latest_checkpointed_iteration.txt        ("release" or an int)
    <dir>/iter_XXXXXXX/mp_rank_TT/model_optim_rng.pt          (pp == 1)
    <dir>/iter_XXXXXXX/mp_rank_TT_PPP/model_optim_rng.pt      (pp > 1)

with the payload::

    sd['model']['language_model']['embedding']['word_embeddings']['weight']
    sd['model']['language_model']['encoder']['layers.N.<module>.weight']
    sd['model']['language_model']['lm_head']
    sd['checkpoint_version']  (0 / 1.0 / 2.0 / 3.0)
    sd['iteration'], sd['args']

Import merges TP shards (column-parallel dim 0, row-parallel dim 1,
GLU halves re-interleaved per shard) and PP stages (local layer indices
offset by stage), applies the v<2.0 query_key_value row-reordering fixups
(``fix_query_key_value_ordering`` / ``_transpose_first_dim``,
checkpointing.py:340-411), and converts the reference's weight layout to
this framework's param pytree:

* kernels here are stored ``[in, out]`` (flax convention) — transpose;
* the reference packs GLU ``dense_h_to_4h`` as ``[up(w3); gate(w1)]``
  (``weights_conversion/hf_to_megatron.py:162-165``) while this framework
  packs ``[gate; up]`` (``util.pack_glu_ffn``) — halves swap;
* the grouped-GQA QKV layout and interleaved rotary rows are identical on
  both sides, so QKV needs only the transpose.

This goes beyond the reference's own converters, which require
``checkpoint_util.py`` unsharding before any conversion
(``megatron_to_hf.py:95``): TP/PP-sharded checkpoints import directly.

Covers the llama family (llama/llama2/codellama/mistral — the reference's
headline finetune workflow).  Falcon/GPT reference checkpoints differ only
in key names and can be added to ``_LAYER_KEYS``.
"""

from __future__ import annotations

import os
import re
from types import SimpleNamespace
from typing import Dict, List, Optional, Tuple

import numpy as np

CHECKPOINT_VERSION = 3.0


# ---------------------------------------------------------------------------
# shared helpers
# ---------------------------------------------------------------------------

def _tracker_path(root: str) -> str:
    return os.path.join(root, "latest_checkpointed_iteration.txt")


def read_tracker(root: str) -> str:
    with open(_tracker_path(root)) as f:
        return f.read().strip()


def _iter_dirname(iteration) -> str:
    if iteration == "release":
        return "release"
    return f"iter_{int(iteration):07d}"


def _rank_dirs(iter_dir: str) -> List[str]:
    out = sorted(d for d in os.listdir(iter_dir)
                 if re.fullmatch(r"mp_rank_\d\d(_\d\d\d)?", d))
    if not out:
        raise FileNotFoundError(f"no mp_rank_* dirs under {iter_dir}")
    return out


def _parse_rank(name: str) -> Tuple[int, int]:
    parts = name.split("_")           # mp, rank, TT[, PPP]
    tp = int(parts[2])
    pp = int(parts[3]) if len(parts) > 3 else 0
    return tp, pp


def _np32(t) -> np.ndarray:
    return t.detach().to("cpu").float().numpy().copy()


# ---------------------------------------------------------------------------
# v<2.0 fixups (reference checkpointing.py:340-411)
# ---------------------------------------------------------------------------

def fix_qkv_ordering(w: np.ndarray, version: float, num_heads: int,
                     num_heads_kv: int, head_dim: int) -> np.ndarray:
    """Reorder the first dim of a query_key_value weight/bias saved by a
    v<2.0 reference build into the v2 grouped layout [np, 3, hn, ...].

    Multi-query/grouped attention checkpoints never need the fixup
    (reference fix_query_key_value_ordering skips when
    num_attention_heads_kv != num_attention_heads)."""
    if version >= 2.0 or num_heads != num_heads_kv:
        return w
    trailing = w.shape[1:]
    if version == 0:
        # [3*np*hn, ...] -> [3, np, hn, ...] -> [np, 3, hn, ...]
        x = w.reshape((3, num_heads, head_dim) + trailing)
        x = np.swapaxes(x, 0, 1)
    elif version == 1.0:
        # [np*hn*3, ...] -> [np, hn, 3, ...] -> [np, 3, hn, ...]
        x = w.reshape((num_heads, head_dim, 3) + trailing)
        x = np.swapaxes(x, 1, 2)
    else:
        raise ValueError(f"invalid checkpoint version {version}")
    return np.ascontiguousarray(x.reshape(w.shape))


# ---------------------------------------------------------------------------
# import: reference mp_rank checkpoint -> framework pytree
# ---------------------------------------------------------------------------

def _merge_tp(tensors: List[np.ndarray], kind: str) -> np.ndarray:
    """Merge TP shards of one weight.  kind: column (dim 0), row (dim 1),
    glu (per-shard [up_i; gate_i] halves re-grouped), replicated."""
    if len(tensors) == 1 and kind != "glu":
        return tensors[0]
    if kind == "column":
        return np.concatenate(tensors, axis=0)
    if kind == "row":
        return np.concatenate(tensors, axis=1)
    if kind == "glu":
        ups, gates = [], []
        for t in tensors:
            half = t.shape[0] // 2
            ups.append(t[:half])
            gates.append(t[half:])
        return np.concatenate(ups + gates, axis=0)
    return tensors[0]                  # replicated


_LAYER_KEYS = {
    # megatron encoder key suffix -> (our path, tp kind); the qkv bias key
    # is optional (qwen2-style models only)
    "attention.query_key_value.weight": (
        ("attention", "query_key_value", "kernel"), "column"),
    "attention.query_key_value.bias": (
        ("attention", "query_key_value", "bias"), "column"),
    "attention.dense.weight": (("attention", "dense", "kernel"), "row"),
    "mlp.dense_h_to_4h.weight": (
        ("mlp", "dense_h_to_4h", "kernel"), "glu"),
    "mlp.dense_4h_to_h.weight": (("mlp", "dense_4h_to_h", "kernel"), "row"),
    "input_layernorm.weight": (("input_norm", "scale"), "replicated"),
    "post_attention_layernorm.weight": (
        ("post_attention_norm", "scale"), "replicated"),
}


def _language_model(sd: dict) -> dict:
    lm = sd["model"]["language_model"]
    if "encoder" not in lm and "transformer" in lm:
        lm = dict(lm)
        lm["encoder"] = lm["transformer"]
    return lm


def _word_embeddings(lm: dict) -> np.ndarray:
    emb = lm["embedding"]
    if "word_embeddings" in emb:
        return _np32(emb["word_embeddings"]["weight"])
    return _np32(emb["word_embeddings.weight"])


def load_reference_checkpoint(load_dir: str,
                              iteration: Optional[int] = None,
                              dtype=None):
    """Read a reference-layout checkpoint tree -> (params, config, meta).

    params is this framework's llama-family pytree (what
    ``models.llama.LlamaModel.init`` produces); config is a dict of
    TransformerConfig overrides recovered from the checkpoint args; meta
    carries {'iteration', 'checkpoint_version', 'args'}.
    """
    import torch

    import jax.numpy as jnp

    if dtype is None:
        dtype = jnp.float32
    if iteration is None:
        iteration = read_tracker(load_dir)
    iter_dir = os.path.join(load_dir, _iter_dirname(iteration))
    ranks = _rank_dirs(iter_dir)
    by_pp: Dict[int, Dict[int, dict]] = {}
    version = None
    args = None
    for name in ranks:
        tp, pp = _parse_rank(name)
        sd = torch.load(os.path.join(iter_dir, name, "model_optim_rng.pt"),
                        map_location="cpu", weights_only=False)
        by_pp.setdefault(pp, {})[tp] = sd
        if version is None:
            version = float(sd.get("checkpoint_version", 0))
            args = sd.get("args")

    nh = getattr(args, "num_attention_heads", None)
    ng = getattr(args, "num_attention_heads_kv", nh)
    hidden = getattr(args, "hidden_size", None)

    # merged[key] = full tensor, with layer indices made global across pp
    pp_stages = sorted(by_pp)
    merged: Dict[str, np.ndarray] = {}
    layer_offset = 0
    layer_re = re.compile(r"layers\.(\d+)\.(.+)")
    for stage in pp_stages:
        shards = [by_pp[stage][tp] for tp in sorted(by_pp[stage])]
        lms = [_language_model(s) for s in shards]
        encs = [lm["encoder"] for lm in lms]
        stage_layers = set()
        suffixes = set()
        for key in encs[0]:
            m = layer_re.fullmatch(key)
            if m:
                stage_layers.add(int(m.group(1)))
                suffixes.add(m.group(2))
        for li in sorted(stage_layers):
            for suffix in suffixes:
                if suffix not in _LAYER_KEYS:
                    continue
                _, kind = _LAYER_KEYS[suffix]
                shards = [_np32(e[f"layers.{li}.{suffix}"]) for e in encs]
                if suffix in ("attention.query_key_value.weight",
                              "attention.query_key_value.bias") and nh:
                    # the v<2.0 reordering is per-rank (each shard holds
                    # nh/tp heads in the old layout), so fix before
                    # merging; applies to weight AND bias (the reference
                    # fixes both, checkpointing.py:388-391)
                    nh_local = nh // len(shards)
                    # GQA (ng != nh) skips the fixup entirely; signal that
                    # by passing unequal local head counts
                    ng_local = nh_local if ng == nh else 0
                    if shards[0].ndim > 1:       # weight [3*nh_l*d, hid]
                        d_fix = (hidden or shards[0].shape[1]) // nh
                    else:                        # bias [3*nh_l*d]
                        d_fix = shards[0].shape[0] // (3 * nh_local)
                    shards = [fix_qkv_ordering(
                        s, version, nh_local, ng_local, d_fix)
                        for s in shards]
                merged[f"layers.{layer_offset + li}.{suffix}"] = _merge_tp(
                    shards, kind)
        if stage == pp_stages[0] and "embedding" in lms[0]:
            merged["word_embeddings"] = _merge_tp(
                [_word_embeddings(lm) for lm in lms], "column")
        if stage == pp_stages[-1]:
            if "final_layernorm.weight" in encs[0]:
                merged["final_layernorm"] = _np32(
                    encs[0]["final_layernorm.weight"])
            if "lm_head" in lms[0]:
                merged["lm_head"] = _merge_tp(
                    [_np32(lm["lm_head"]) for lm in lms], "column")
        layer_offset += len(stage_layers)

    num_layers = layer_offset

    def stack(suffix, transform):
        return jnp.asarray(np.stack([
            transform(merged[f"layers.{i}.{suffix}"])
            for i in range(num_layers)
        ]), dtype)

    def to_kernel(w):                   # torch [out, in] -> kernel [in, out]
        return np.ascontiguousarray(w.T)

    def glu_to_kernel(w):               # [up; gate] -> kernel of [gate; up]
        half = w.shape[0] // 2
        return np.ascontiguousarray(
            np.concatenate([w[half:], w[:half]], axis=0).T)

    params = {
        "embedding": {"word": {"embedding": jnp.asarray(
            merged["word_embeddings"], dtype)}},
        "transformer": {
            "layers": {
                "input_norm": {
                    "scale": stack("input_layernorm.weight", lambda w: w)},
                "attention": {
                    "query_key_value": {
                        "kernel": stack(
                            "attention.query_key_value.weight", to_kernel),
                        # optional (qwen2-style models)
                        **({"bias": stack(
                            "attention.query_key_value.bias", lambda w: w)}
                           if "layers.0.attention.query_key_value.bias"
                           in merged else {}),
                    },
                    "dense": {"kernel": stack(
                        "attention.dense.weight", to_kernel)},
                },
                "post_attention_norm": {
                    "scale": stack("post_attention_layernorm.weight",
                                   lambda w: w)},
                "mlp": {
                    "dense_h_to_4h": {"kernel": stack(
                        "mlp.dense_h_to_4h.weight", glu_to_kernel)},
                    "dense_4h_to_h": {"kernel": stack(
                        "mlp.dense_4h_to_h.weight", to_kernel)},
                },
            },
            "final_norm": {"scale": jnp.asarray(
                merged["final_layernorm"], dtype)},
        },
    }
    if "lm_head" in merged:
        params["lm_head"] = {"weight": jnp.asarray(merged["lm_head"], dtype)}

    ffn = merged["layers.0.mlp.dense_h_to_4h.weight"].shape[0] // 2
    config = {
        "num_layers": num_layers,
        "hidden_size": merged["layers.0.attention.dense.weight"].shape[0],
        "padded_vocab_size": merged["word_embeddings"].shape[0],
        "ffn_hidden_size": ffn,
        "tie_embed_logits": "lm_head" not in merged,
        "add_qkv_bias":
            "layers.0.attention.query_key_value.bias" in merged,
    }
    for field, attr in [
        ("num_attention_heads", "num_attention_heads"),
        ("num_attention_heads_kv", "num_attention_heads_kv"),
        ("seq_length", "seq_length"),
        ("max_position_embeddings", "max_position_embeddings"),
        ("layernorm_epsilon", "layernorm_epsilon"),
        ("rope_theta", "rope_theta"),
    ]:
        val = getattr(args, attr, None)
        if val is not None:
            config[field] = val
    meta = {"iteration": iteration, "checkpoint_version": version,
            "args": args}
    return params, config, meta


# ---------------------------------------------------------------------------
# export: framework pytree -> reference mp_rank checkpoint
# ---------------------------------------------------------------------------

def _split_tp(w: np.ndarray, tp: int, kind: str) -> List[np.ndarray]:
    if tp == 1:
        return [w]
    if kind == "column":
        return [np.ascontiguousarray(s) for s in np.split(w, tp, axis=0)]
    if kind == "row":
        return [np.ascontiguousarray(s) for s in np.split(w, tp, axis=1)]
    if kind == "glu":
        half = w.shape[0] // 2
        ups = np.split(w[:half], tp, axis=0)
        gates = np.split(w[half:], tp, axis=0)
        return [np.ascontiguousarray(np.concatenate([u, g], axis=0))
                for u, g in zip(ups, gates)]
    return [w] * tp                     # replicated


def save_reference_checkpoint(save_dir: str, iteration, params, cfg,
                              tensor_parallel: int = 1):
    """Write the param pytree as a reference-layout torch checkpoint.

    cfg: anything exposing num_layers / hidden_size / num_attention_heads /
    num_attention_heads_kv / ffn_hidden_size / padded_vocab_size (the
    framework's TransformerConfig qualifies).  ``tensor_parallel`` > 1
    writes TP-sharded mp_rank_00..NN files the reference can load rank-wise.
    """
    import torch

    def get(attr, default=None):
        if isinstance(cfg, dict):
            return cfg.get(attr, default)
        return getattr(cfg, attr, default)

    tp = tensor_parallel
    layers = params["transformer"]["layers"]
    if "experts" in layers["mlp"]:
        raise NotImplementedError(
            "MoE params cannot be exported to a reference Megatron "
            "checkpoint: the reference has no MoE layout (its mlp is "
            "dense_h_to_4h/dense_4h_to_h)")
    # .shape on the stacked kernel directly — np.asarray here would pull
    # the largest tensor in the model to host just to read one dim
    num_layers = int(
        layers["attention"]["query_key_value"]["kernel"].shape[0])

    def kernel_to_w(k):                # kernel [in, out] -> torch [out, in]
        return np.ascontiguousarray(np.asarray(k, np.float32).T)

    def glu_kernel_to_w(k):            # kernel of [gate; up] -> [up; gate]
        w = np.ascontiguousarray(np.asarray(k, np.float32).T)
        half = w.shape[0] // 2
        return np.ascontiguousarray(np.concatenate([w[half:], w[:half]]))

    encoders = [dict() for _ in range(tp)]
    for li in range(num_layers):
        per_key = {
            "attention.query_key_value.weight": _split_tp(
                kernel_to_w(layers["attention"]["query_key_value"]["kernel"][li]),
                tp, "column"),
            **({"attention.query_key_value.bias": _split_tp(
                np.asarray(
                    layers["attention"]["query_key_value"]["bias"][li],
                    np.float32), tp, "column")}
               if "bias" in layers["attention"]["query_key_value"] else {}),
            "attention.dense.weight": _split_tp(
                kernel_to_w(layers["attention"]["dense"]["kernel"][li]),
                tp, "row"),
            "mlp.dense_h_to_4h.weight": _split_tp(
                glu_kernel_to_w(layers["mlp"]["dense_h_to_4h"]["kernel"][li]),
                tp, "glu"),
            "mlp.dense_4h_to_h.weight": _split_tp(
                kernel_to_w(layers["mlp"]["dense_4h_to_h"]["kernel"][li]),
                tp, "row"),
            "input_layernorm.weight": _split_tp(
                np.asarray(layers["input_norm"]["scale"][li], np.float32),
                tp, "replicated"),
            "post_attention_layernorm.weight": _split_tp(
                np.asarray(layers["post_attention_norm"]["scale"][li],
                           np.float32), tp, "replicated"),
        }
        for suffix, shards in per_key.items():
            for r, s in enumerate(shards):
                # np.array: jnp->np conversions are read-only views, which
                # torch.from_numpy warns about
                encoders[r][f"layers.{li}.{suffix}"] = torch.from_numpy(
                    np.array(s))

    final_norm = np.asarray(
        params["transformer"]["final_norm"]["scale"], np.float32)
    emb = np.asarray(params["embedding"]["word"]["embedding"], np.float32)
    emb_shards = _split_tp(np.ascontiguousarray(emb), tp, "column")
    head_shards = None
    if "lm_head" in params:
        head = np.ascontiguousarray(
            np.asarray(params["lm_head"]["weight"], np.float32))
        head_shards = _split_tp(head, tp, "column")

    args = SimpleNamespace(
        num_layers=get("num_layers", num_layers),
        hidden_size=get("hidden_size"),
        num_attention_heads=get("num_attention_heads"),
        num_attention_heads_kv=get("num_attention_heads_kv",
                                   get("num_attention_heads")),
        ffn_hidden_size=get("ffn_hidden_size"),
        padded_vocab_size=get("padded_vocab_size"),
        seq_length=get("seq_length"),
        max_position_embeddings=get("max_position_embeddings"),
        layernorm_epsilon=get("layernorm_epsilon", 1e-5),
        rope_theta=get("rope_theta", 10000.0),
        tensor_model_parallel_size=tp,
        pipeline_model_parallel_size=1,
        use_distributed_optimizer=False,
    )

    iter_dir = os.path.join(save_dir, _iter_dirname(iteration))
    for r in range(tp):
        lm = {
            "embedding": {"word_embeddings": {
                "weight": torch.from_numpy(np.array(emb_shards[r]))}},
            "encoder": dict(encoders[r]),
        }
        lm["encoder"]["final_layernorm.weight"] = torch.from_numpy(
            np.array(final_norm))
        if head_shards is not None:
            lm["lm_head"] = torch.from_numpy(np.array(head_shards[r]))
        sd = {
            "model": {"language_model": lm},
            "checkpoint_version": CHECKPOINT_VERSION,
            "iteration": 0 if iteration == "release" else int(iteration),
            "args": args,
        }
        rank_dir = os.path.join(iter_dir, f"mp_rank_{r:02d}")
        os.makedirs(rank_dir, exist_ok=True)
        torch.save(sd, os.path.join(rank_dir, "model_optim_rng.pt"))
    with open(_tracker_path(save_dir), "w") as f:
        f.write("release" if iteration == "release" else str(int(iteration)))


# ---------------------------------------------------------------------------
# CLI: convert between reference mp_rank checkpoints and native (orbax)
# ---------------------------------------------------------------------------

def main():
    import argparse
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from megatron_llm_tpu import checkpointing

    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("direction", choices=["from-megatron", "to-megatron"])
    p.add_argument("--load", required=True)
    p.add_argument("--out", required=True)
    p.add_argument("--tp", type=int, default=1,
                   help="TP shards to write (to-megatron only)")
    p.add_argument("--iteration", type=int, default=None)
    args = p.parse_args()

    if args.direction == "from-megatron":
        params, config, meta = load_reference_checkpoint(
            args.load, iteration=args.iteration)
        release = meta["iteration"] == "release"
        it = 0 if release else int(meta["iteration"])
        checkpointing.save_checkpoint(args.out, it, params, args=config,
                                      release=release)
        print(f" imported reference checkpoint {args.load} "
              f"(version {meta['checkpoint_version']}) -> {args.out}")
    else:
        # not finetune=True: that zeroes meta['iteration'], which names the
        # exported iter_XXXXXXX dir (optimizer state is skipped anyway
        # because no template is passed)
        params, _, meta = checkpointing.load_checkpoint(args.load)
        cfg = (meta or {}).get("args") or {}
        it = args.iteration if args.iteration is not None else \
            (meta or {}).get("iteration", 0)
        save_reference_checkpoint(args.out, it, params, cfg,
                                  tensor_parallel=args.tp)
        print(f" exported {args.load} -> reference layout at {args.out} "
              f"(tp={args.tp})")


if __name__ == "__main__":
    main()
