"""Merge Meta-distributed Llama shards (consolidated.XX.pth) into one
unsharded state dict.

Reference: ``weights_conversion/utils/merge_llama.py`` — Meta ships TP-style
shards; each parameter concatenates along a per-key axis (column-parallel
weights along 0, row-parallel along -1, norms replicated).  The merged dict
feeds ``hf_to_megatron.py`` (or is exported to HF format first).

Torch is CPU-only in this image; tensors are loaded with
``torch.load(map_location='cpu')`` and merged in numpy.
"""

from __future__ import annotations

import glob
import os
from typing import Dict

import numpy as np

# which axis each Meta parameter concatenates along across shards
# (None = replicated, take shard 0)
MERGE_DIM = {
    "wq": 0, "wk": 0, "wv": 0, "wo": -1,
    "w1": 0, "w2": -1, "w3": 0,
    "output": 0,
    "tok_embeddings": -1,
    "attention_norm": None, "ffn_norm": None, "norm": None,
    "rope": None,
}


def _short_name(param_name: str) -> str:
    # e.g. layers.3.attention.wq.weight -> wq
    parts = param_name.split(".")
    return parts[-2] if len(parts) >= 2 else parts[0]


def merge_llama(model_dir: str, dtype=np.float32) -> Dict[str, np.ndarray]:
    """Returns {meta param name: merged array} from consolidated.*.pth."""
    import torch

    shards = sorted(glob.glob(os.path.join(model_dir, "consolidated.*.pth")))
    if not shards:
        raise FileNotFoundError(
            f"no consolidated.*.pth shards under {model_dir!r}")
    merged: Dict[str, list] = {}
    for path in shards:
        sd = torch.load(path, map_location="cpu", weights_only=True)
        for name, tensor in sd.items():
            arr = tensor.to(torch.float32).numpy().astype(dtype)
            merged.setdefault(name, []).append(arr)
        del sd
    out = {}
    for name in list(merged):
        parts = merged.pop(name)  # free shard parts as we go (70B ~ 280GB)
        dim = MERGE_DIM.get(_short_name(name))
        if dim is None or name.endswith("inv_freq") or len(parts) == 1:
            out[name] = parts[0]
        else:
            out[name] = np.concatenate(parts, axis=dim)
    return out


def meta_to_hf_names(merged: Dict[str, np.ndarray],
                     n_heads: int, n_kv_heads: int) -> Dict[str, np.ndarray]:
    """Rename Meta keys to the HF LlamaForCausalLM convention — AND convert
    wq/wk from Meta's interleaved rotary layout to HF's half-split layout —
    so the merged dict can flow through hf_to_megatron's llama converter
    (which applies rotary_hf_to_interleaved assuming HF-layout input)."""
    from weights_conversion.util import rotary_interleaved_to_hf

    out = {}
    mapping = {
        "tok_embeddings.weight": "model.embed_tokens.weight",
        "norm.weight": "model.norm.weight",
        "output.weight": "lm_head.weight",
    }
    per_layer = {
        "attention.wq.weight": "self_attn.q_proj.weight",
        "attention.wk.weight": "self_attn.k_proj.weight",
        "attention.wv.weight": "self_attn.v_proj.weight",
        "attention.wo.weight": "self_attn.o_proj.weight",
        "feed_forward.w1.weight": "mlp.gate_proj.weight",
        "feed_forward.w2.weight": "mlp.down_proj.weight",
        "feed_forward.w3.weight": "mlp.up_proj.weight",
        "attention_norm.weight": "input_layernorm.weight",
        "ffn_norm.weight": "post_attention_layernorm.weight",
    }
    for name, arr in merged.items():
        if name.endswith("rope.freqs") or name.endswith("inv_freq"):
            continue
        if name in mapping:
            out[mapping[name]] = arr
            continue
        if name.startswith("layers."):
            _, idx, rest = name.split(".", 2)
            if rest in per_layer:
                if rest.endswith(("wq.weight", "wk.weight")):
                    nh = n_heads if "wq" in rest else n_kv_heads
                    head_dim = arr.shape[0] // nh
                    arr = rotary_interleaved_to_hf(arr, head_dim)
                out[f"model.layers.{idx}.{per_layer[rest]}"] = arr
                continue
        raise KeyError(f"unrecognized Meta parameter {name!r}")
    return out
