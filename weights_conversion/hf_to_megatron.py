#!/usr/bin/env python
"""HuggingFace -> TPU-framework checkpoint conversion.

Reference: ``weights_conversion/hf_to_megatron.py`` — downloads/loads the
HF model, permutes the rotary QKV interleaving, packs the GQA layout, and
writes a TP=PP=1 ``release`` checkpoint with args (:259-449).

Here the output is the framework's layout-independent orbax checkpoint
(any later mesh re-sharding is free), written with
``checkpointing.save_checkpoint(..., release=True)``.

Usage:
    python weights_conversion/hf_to_megatron.py llama2 \
        --model-path /path/or/hub-id --out /ckpts/llama2-7b
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from weights_conversion.util import (
    pack_glu_ffn,
    pack_qkv,
    pack_qkv_bias,
    rotary_hf_to_interleaved,
    rotary_hf_to_interleaved_bias,
)


def stack_layer_tree(layers, dtype):
    """Per-layer param dicts (identical structure) -> one stacked pytree
    with a leading [num_layers] axis on every leaf."""
    import jax.numpy as jnp

    def rec(template, *path):
        if isinstance(template, dict):
            return {k: rec(v, *path, k) for k, v in template.items()}

        def get(lp, keys):
            for kk in keys:
                lp = lp[kk]
            return lp
        return jnp.asarray(np.stack([get(l, path) for l in layers]), dtype)

    return rec(layers[0])


def _np(t):
    # .copy() is load-bearing: .float() on an fp32 tensor is a no-op view,
    # so without it the numpy array aliases the live HF parameter and the
    # in-place rotary permutation would corrupt the source model.
    return t.detach().to("cpu").float().numpy().copy()


def _dense_glu_mlp(sd, p):
    """HF llama/mistral mlp.{gate,up,down}_proj -> dense GLU mlp subtree."""
    return {
        "dense_h_to_4h": {
            "kernel": pack_glu_ffn(
                _np(sd[p + "mlp.gate_proj.weight"]),
                _np(sd[p + "mlp.up_proj.weight"]),
            )
        },
        "dense_4h_to_h": {
            "kernel": np.ascontiguousarray(
                _np(sd[p + "mlp.down_proj.weight"]).T)
        },
    }


def convert_llama_family(hf_model, dtype=np.float32, *, layer_mlp=None,
                         qkv_bias=False, norm_add_one=False):
    """LlamaForCausalLM / MistralForCausalLM -> param pytree + config dict.

    reference: hf_to_megatron.py:117-258 (llama), :185-258 (mistral).
    ``layer_mlp(sd, prefix)``: per-layer mlp-subtree converter hook —
    defaults to the dense GLU mlp; convert_mixtral swaps in the MoE one.
    ``qkv_bias``: pack the per-projection biases too (Qwen2).
    ``norm_add_one``: store RMSNorm scales as ``1 + hf_weight`` (Gemma's
    zero-centered convention folded into the weights — identical math).
    """
    hf_cfg = hf_model.config
    nh = hf_cfg.num_attention_heads
    ng = getattr(hf_cfg, "num_key_value_heads", nh)
    # gemma decouples head_dim from hidden/heads
    d = getattr(hf_cfg, "head_dim", None) or hf_cfg.hidden_size // nh
    norm = (lambda w: w + 1.0) if norm_add_one else (lambda w: w)
    sd = dict(hf_model.state_dict())
    layer_mlp = layer_mlp or _dense_glu_mlp

    layers = []
    for i in range(hf_cfg.num_hidden_layers):
        p = f"model.layers.{i}."
        q = rotary_hf_to_interleaved(_np(sd[p + "self_attn.q_proj.weight"]), d)
        k = rotary_hf_to_interleaved(_np(sd[p + "self_attn.k_proj.weight"]), d)
        v = _np(sd[p + "self_attn.v_proj.weight"])
        qkv = {"kernel": pack_qkv(q, k, v, nh, ng, d)}
        if qkv_bias:
            qb = rotary_hf_to_interleaved_bias(
                _np(sd[p + "self_attn.q_proj.bias"]), d)
            kb = rotary_hf_to_interleaved_bias(
                _np(sd[p + "self_attn.k_proj.bias"]), d)
            vb = _np(sd[p + "self_attn.v_proj.bias"])
            qkv["bias"] = pack_qkv_bias(qb, kb, vb, nh, ng, d)
        layers.append({
            "input_norm": {
                "scale": norm(_np(sd[p + "input_layernorm.weight"]))
            },
            "attention": {
                "query_key_value": qkv,
                "dense": {
                    "kernel": np.ascontiguousarray(
                        _np(sd[p + "self_attn.o_proj.weight"]).T)
                },
            },
            "post_attention_norm": {
                "scale": norm(_np(sd[p + "post_attention_layernorm.weight"]))
            },
            "mlp": layer_mlp(sd, p),
        })

    import jax.numpy as jnp

    layer_tree = stack_layer_tree(layers, dtype)
    tied = bool(getattr(hf_cfg, "tie_word_embeddings", False))
    params = {
        "embedding": {
            "word": {"embedding": jnp.asarray(
                _np(sd["model.embed_tokens.weight"]), dtype)}
        },
        "transformer": {
            "layers": layer_tree,
            "final_norm": {"scale": jnp.asarray(
                norm(_np(sd["model.norm.weight"])), dtype)},
        },
    }
    if not tied:
        # tied HF models (Qwen2-0.5B/1.5B, small llamas) share the head
        # with the embedding — the pytree must match the tied fresh-init
        # structure (no lm_head leaf) or checkpoints won't line up
        params["lm_head"] = {"weight": jnp.asarray(
            _np(sd["lm_head.weight"]), dtype)}
    config = {
        "num_layers": hf_cfg.num_hidden_layers,
        "hidden_size": hf_cfg.hidden_size,
        "num_attention_heads": nh,
        "num_attention_heads_kv": ng,
        "kv_channels": d,
        "ffn_hidden_size": hf_cfg.intermediate_size,
        "padded_vocab_size": hf_cfg.vocab_size,
        "seq_length": getattr(hf_cfg, "max_position_embeddings", 4096),
        "max_position_embeddings": getattr(hf_cfg, "max_position_embeddings",
                                           4096),
        "position_embedding_type": "rotary",
        "glu_activation": "swiglu",
        "normalization": "rmsnorm",
        "add_bias_linear": False,
        "tie_embed_logits": tied,
        "layernorm_epsilon": hf_cfg.rms_norm_eps,
        "rope_theta": getattr(hf_cfg, "rope_theta", 10000.0),
        "sliding_window_size": getattr(hf_cfg, "sliding_window", None),
        "add_qkv_bias": qkv_bias,
        "hidden_dropout": 0.0,
        "attention_dropout": 0.0,
    }
    # HF rope_scaling: {'rope_type': 'llama3', ...} (Llama-3.1+) or
    # {'rope_type'/'type': 'linear', 'factor': f}
    rs = getattr(hf_cfg, "rope_scaling", None)
    if rs:
        kind = rs.get("rope_type") or rs.get("type")
        if kind == "llama3":
            config["rope_llama3_scaling"] = (
                float(rs.get("factor", 8.0)),
                float(rs.get("low_freq_factor", 1.0)),
                float(rs.get("high_freq_factor", 4.0)),
                int(rs.get("original_max_position_embeddings", 8192)),
            )
        elif kind == "linear":
            config["rope_scaling_factor"] = float(rs.get("factor", 1.0))
        elif kind not in (None, "default"):
            # yarn/dynamic/longrope/...: converting with plain rope would
            # silently diverge from HF — fail loud instead
            raise NotImplementedError(
                f"unsupported HF rope_scaling type {kind!r} "
                f"(supported: llama3, linear)")
    return params, config


def convert_gemma(hf_model, dtype=np.float32):
    """GemmaForCausalLM -> param pytree + config dict: llama-family path
    with the ``1 + w`` RMSNorm convention folded into the stored scales,
    GeGLU activation, decoupled head_dim, tied head, and the
    sqrt(hidden) embedding multiplier recorded in the config."""
    import math

    params, config = convert_llama_family(hf_model, dtype,
                                          norm_add_one=True)
    config["glu_activation"] = "geglu"
    config["embedding_multiplier"] = math.sqrt(config["hidden_size"])
    return params, config


def convert_gpt_neox(hf_model, dtype=np.float32):
    """GPTNeoXForCausalLM (Pythia) -> param pytree + config dict.

    HF packs QKV rows per head as [nh, 3, d] — identical to this
    framework's grouped layout at ng == nh — so only the rotate-half ->
    interleaved permutation of each head's ROTARY dims (rotary_pct of d)
    is needed, applied to the q and k sub-blocks of weights and biases."""
    import jax.numpy as jnp

    hf_cfg = hf_model.config
    if not getattr(hf_cfg, "use_parallel_residual", True):
        raise NotImplementedError(
            "GPT-NeoX with use_parallel_residual=False maps to the "
            "sequential layer layout; convert is only wired for the "
            "parallel-residual (Pythia) form")
    act = getattr(hf_cfg, "hidden_act", "gelu")
    if act == "gelu":
        gelu_variant = "exact"
    elif act in ("gelu_new", "gelu_fast", "gelu_pytorch_tanh"):
        gelu_variant = "tanh"
    else:
        raise NotImplementedError(f"gpt-neox hidden_act {act!r}")
    nh = hf_cfg.num_attention_heads
    h = hf_cfg.hidden_size
    d = h // nh
    rot_d = int(d * hf_cfg.rotary_pct)
    rot_d -= rot_d % 2
    sd = dict(hf_model.state_dict())

    def permute_qkv(w):
        """w [3*h(, hid)] in [nh, 3, d] row layout: permute the first
        rot_d dims of the q and k sub-blocks."""
        vec = w.ndim == 1
        if vec:
            w = w[:, None]
        x = w.reshape(nh, 3, d, w.shape[1]).copy()
        for j in (0, 1):                      # q and k, not v
            blk = x[:, j, :rot_d].reshape(nh * rot_d, w.shape[1])
            x[:, j, :rot_d] = rotary_hf_to_interleaved(
                blk, rot_d).reshape(nh, rot_d, w.shape[1])
        out = x.reshape(3 * h, w.shape[1])
        return out[:, 0] if vec else out

    layers = []
    for i in range(hf_cfg.num_hidden_layers):
        p = f"gpt_neox.layers.{i}."
        qkv_w = permute_qkv(_np(sd[p + "attention.query_key_value.weight"]))
        qkv_b = permute_qkv(_np(sd[p + "attention.query_key_value.bias"]))
        layers.append({
            "input_norm": {
                "scale": _np(sd[p + "input_layernorm.weight"]),
                "bias": _np(sd[p + "input_layernorm.bias"]),
            },
            "mlp_norm": {
                "scale": _np(sd[p + "post_attention_layernorm.weight"]),
                "bias": _np(sd[p + "post_attention_layernorm.bias"]),
            },
            "attention": {
                "query_key_value": {
                    "kernel": np.ascontiguousarray(qkv_w.T),
                    "bias": qkv_b,
                },
                "dense": {
                    "kernel": np.ascontiguousarray(
                        _np(sd[p + "attention.dense.weight"]).T),
                    "bias": _np(sd[p + "attention.dense.bias"]),
                },
            },
            "mlp": {
                "dense_h_to_4h": {
                    "kernel": np.ascontiguousarray(
                        _np(sd[p + "mlp.dense_h_to_4h.weight"]).T),
                    "bias": _np(sd[p + "mlp.dense_h_to_4h.bias"]),
                },
                "dense_4h_to_h": {
                    "kernel": np.ascontiguousarray(
                        _np(sd[p + "mlp.dense_4h_to_h.weight"]).T),
                    "bias": _np(sd[p + "mlp.dense_4h_to_h.bias"]),
                },
            },
        })

    params = {
        "embedding": {"word": {"embedding": jnp.asarray(
            _np(sd["gpt_neox.embed_in.weight"]), dtype)}},
        "transformer": {
            "layers": stack_layer_tree(layers, dtype),
            "final_norm": {
                "scale": jnp.asarray(
                    _np(sd["gpt_neox.final_layer_norm.weight"]), dtype),
                "bias": jnp.asarray(
                    _np(sd["gpt_neox.final_layer_norm.bias"]), dtype),
            },
        },
        "lm_head": {"weight": jnp.asarray(
            _np(sd["embed_out.weight"]), dtype)},
    }
    config = {
        "num_layers": hf_cfg.num_hidden_layers,
        "hidden_size": h,
        "num_attention_heads": nh,
        "ffn_hidden_size": hf_cfg.intermediate_size,
        "padded_vocab_size": hf_cfg.vocab_size,
        "seq_length": hf_cfg.max_position_embeddings,
        "max_position_embeddings": hf_cfg.max_position_embeddings,
        "position_embedding_type": "rotary",
        "glu_activation": None,
        "gelu_variant": gelu_variant,
        "normalization": "layernorm",
        "add_bias_linear": True,
        "parallel_attn": bool(hf_cfg.use_parallel_residual),
        "parallel_layernorm": bool(hf_cfg.use_parallel_residual),
        "tie_embed_logits": False,
        "rotary_percent": hf_cfg.rotary_pct,
        # transformers renamed rotary_emb_base -> rope_theta across
        # versions; chain the lookup so neither spelling silently falls
        # back to 10000 for models trained with a different base.
        "rope_theta": (getattr(hf_cfg, "rotary_emb_base", None)
                       or getattr(hf_cfg, "rope_theta", 10000.0)),
        "layernorm_epsilon": hf_cfg.layer_norm_eps,
        "hidden_dropout": 0.0,
        "attention_dropout": 0.0,
    }
    return params, config


def convert_qwen2(hf_model, dtype=np.float32):
    """Qwen2ForCausalLM -> param pytree + config dict: the llama-family
    path with QKV biases packed (weights_conversion/util.pack_qkv_bias).
    Qwen2Config carries a sliding_window value even when
    use_sliding_window is False (the default) — honor the switch."""
    hf_cfg = hf_model.config
    if getattr(hf_cfg, "use_sliding_window", False) and \
            getattr(hf_cfg, "max_window_layers", 0) < hf_cfg.num_hidden_layers:
        raise NotImplementedError(
            "Qwen2 per-layer sliding windows (max_window_layers < "
            "num_hidden_layers) are not supported — a global window would "
            "silently change the lower layers' attention")
    params, config = convert_llama_family(hf_model, dtype, qkv_bias=True)
    if not getattr(hf_cfg, "use_sliding_window", False):
        config["sliding_window_size"] = None
    return params, config


def convert_mixtral(hf_model, dtype=np.float32):
    """MixtralForCausalLM -> param pytree + config dict.

    The trunk (embeddings, norms, GQA attention, lm_head) converts exactly
    like the llama family (shared code path); the ``block_sparse_moe``
    block maps to the MoE MLP layout of ``models/moe.py``:

    * ``gate.weight`` [E, h]      -> router kernel [h, E]
    * per expert ``w1`` (gate) and ``w3`` (up), both [f, h]
                                  -> w_in [E, h, 2f] (same GLU halves as
                                     ``pack_glu_ffn``)
    * per expert ``w2`` [h, f]    -> w_out [E, f, h]
    """
    hf_cfg = hf_model.config
    E = hf_cfg.num_local_experts

    def moe_mlp(sd, p):
        moe = p + "block_sparse_moe."
        return {
            "router": {"kernel": np.ascontiguousarray(
                _np(sd[moe + "gate.weight"]).T)},
            "experts": {
                "w_in": np.stack([
                    pack_glu_ffn(_np(sd[f"{moe}experts.{e}.w1.weight"]),
                                 _np(sd[f"{moe}experts.{e}.w3.weight"]))
                    for e in range(E)
                ]),
                "w_out": np.stack([
                    np.ascontiguousarray(
                        _np(sd[f"{moe}experts.{e}.w2.weight"]).T)
                    for e in range(E)
                ]),
            },
        }

    params, config = convert_llama_family(hf_model, dtype, layer_mlp=moe_mlp)
    top_k = hf_cfg.num_experts_per_tok
    config.update({
        "num_experts": E,
        "moe_top_k": top_k,
        # HF Mixtral routing is dropless; E/top_k makes the per-row expert
        # buffers cover every token so converted models reproduce HF logits
        # exactly.  Training users who want capacity-style dropping can
        # lower this (1.25 is the framework default for from-scratch MoE).
        "moe_capacity_factor": float(E) / top_k,
    })
    return params, config


def convert_falcon(hf_model, dtype=np.float32):
    """FalconForCausalLM -> param pytree (reference: hf_to_megatron.py:60-116).

    Falcon HF already packs QKV in grouped layout
    [ng*(qpg+2)*d, hidden]; only the rotary permutation (per (q|k) head
    inside each group) is needed."""
    hf_cfg = hf_model.config
    nh = hf_cfg.num_attention_heads
    ng = getattr(hf_cfg, "num_kv_heads", None) or (
        hf_cfg.num_attention_heads if not hf_cfg.multi_query else 1
    )
    if getattr(hf_cfg, "new_decoder_architecture", False):
        ng = hf_cfg.num_kv_heads
    d = hf_cfg.hidden_size // nh
    qpg = nh // ng
    sd = dict(hf_model.state_dict())

    import jax.numpy as jnp

    layers = []
    for i in range(hf_cfg.num_hidden_layers):
        p = f"transformer.h.{i}."
        qkv = _np(sd[p + "self_attention.query_key_value.weight"])
        # per-(q|k) head rotary permutation, leave v rows alone
        w = qkv.reshape(ng, qpg + 2, d, -1)
        hid = w.shape[-1]
        for g in range(ng):
            for h in range(qpg + 1):   # q heads + k
                w[g, h] = rotary_hf_to_interleaved(
                    w[g, h].reshape(d, hid), d
                ).reshape(d, hid)
        qkv = w.reshape(ng * (qpg + 2) * d, hid)

        entry = {
            "attention": {
                "query_key_value": {
                    "kernel": np.ascontiguousarray(qkv.T)},
                "dense": {"kernel": np.ascontiguousarray(
                    _np(sd[p + "self_attention.dense.weight"]).T)},
            },
            "mlp": {
                "dense_h_to_4h": {"kernel": np.ascontiguousarray(
                    _np(sd[p + "mlp.dense_h_to_4h.weight"]).T)},
                "dense_4h_to_h": {"kernel": np.ascontiguousarray(
                    _np(sd[p + "mlp.dense_4h_to_h.weight"]).T)},
            },
        }
        if getattr(hf_cfg, "new_decoder_architecture", False):
            entry["input_norm"] = {
                "scale": _np(sd[p + "ln_attn.weight"]),
                "bias": _np(sd[p + "ln_attn.bias"]),
            }
            entry["mlp_norm"] = {
                "scale": _np(sd[p + "ln_mlp.weight"]),
                "bias": _np(sd[p + "ln_mlp.bias"]),
            }
        else:
            entry["input_norm"] = {
                "scale": _np(sd[p + "input_layernorm.weight"]),
                "bias": _np(sd[p + "input_layernorm.bias"]),
            }
        layers.append(entry)

    def stack(*path):
        def get(lp, keys):
            for kk in keys:
                lp = lp[kk]
            return lp
        return jnp.asarray(np.stack([get(l, path) for l in layers]), dtype)

    layer_tree = {
        "input_norm": {"scale": stack("input_norm", "scale"),
                       "bias": stack("input_norm", "bias")},
        "attention": {
            "query_key_value": {
                "kernel": stack("attention", "query_key_value", "kernel")},
            "dense": {"kernel": stack("attention", "dense", "kernel")},
        },
        "mlp": {
            "dense_h_to_4h": {
                "kernel": stack("mlp", "dense_h_to_4h", "kernel")},
            "dense_4h_to_h": {
                "kernel": stack("mlp", "dense_4h_to_h", "kernel")},
        },
    }
    if "mlp_norm" in layers[0]:
        layer_tree["mlp_norm"] = {"scale": stack("mlp_norm", "scale"),
                                  "bias": stack("mlp_norm", "bias")}
    params = {
        "embedding": {"word": {"embedding": jnp.asarray(
            _np(sd["transformer.word_embeddings.weight"]), dtype)}},
        "transformer": {
            "layers": layer_tree,
            "final_norm": {
                "scale": jnp.asarray(_np(sd["transformer.ln_f.weight"]), dtype),
                "bias": jnp.asarray(_np(sd["transformer.ln_f.bias"]), dtype),
            },
        },
    }
    config = {
        "num_layers": hf_cfg.num_hidden_layers,
        "hidden_size": hf_cfg.hidden_size,
        "num_attention_heads": nh,
        "num_attention_heads_kv": ng,
        "ffn_hidden_size": 4 * hf_cfg.hidden_size,
        "padded_vocab_size": hf_cfg.vocab_size,
        "position_embedding_type": "rotary",
        "normalization": "layernorm",
        "parallel_attn": True,
        "parallel_layernorm": bool(
            getattr(hf_cfg, "new_decoder_architecture", False)),
        "gelu_variant": "exact",
        "add_bias_linear": False,
        "tie_embed_logits": True,
        "hidden_dropout": 0.0,
        "attention_dropout": 0.0,
    }
    return params, config


CONVERTERS = {
    "llama": convert_llama_family,
    "llama2": convert_llama_family,
    "llama3": convert_llama_family,
    "codellama": convert_llama_family,
    "mistral": convert_llama_family,
    "mixtral": convert_mixtral,
    "qwen2": convert_qwen2,
    "gemma": convert_gemma,
    "gpt_neox": convert_gpt_neox,
    "pythia": convert_gpt_neox,
    "falcon": convert_falcon,
}


class MetaLlamaShim:
    """Duck-types the (config, state_dict) surface the converters read, fed
    from merged Meta shards (reference: hf_to_megatron downloads/merges Meta
    weights via utils/merge_llama.py before converting)."""

    # Meta params.json has no max_seq_len (it's a runtime arg in Meta's
    # code); trained context depends on the release
    MODEL_CONTEXT = {"llama": 2048, "llama2": 4096, "codellama": 16384}

    def __init__(self, model_dir: str, model: str = "llama2"):
        import json
        import os
        from types import SimpleNamespace

        import torch

        from weights_conversion.merge_llama import (
            merge_llama,
            meta_to_hf_names,
        )

        with open(os.path.join(model_dir, "params.json")) as f:
            meta_cfg = json.load(f)
        n_heads = meta_cfg["n_heads"]
        n_kv = meta_cfg.get("n_kv_heads", n_heads)
        merged = merge_llama(model_dir)
        sd = meta_to_hf_names(merged, n_heads, n_kv)
        self._sd = {k: torch.from_numpy(v) for k, v in sd.items()}
        hidden = meta_cfg["dim"]
        vocab = sd["model.embed_tokens.weight"].shape[0]
        ffn = sd["model.layers.0.mlp.gate_proj.weight"].shape[0]
        self.config = SimpleNamespace(
            num_attention_heads=n_heads,
            num_key_value_heads=n_kv,
            num_hidden_layers=meta_cfg["n_layers"],
            hidden_size=hidden,
            intermediate_size=ffn,
            vocab_size=vocab,
            rms_norm_eps=meta_cfg.get("norm_eps", 1e-5),
            max_position_embeddings=meta_cfg.get(
                "max_seq_len", self.MODEL_CONTEXT.get(model, 4096)),
            rope_theta=meta_cfg.get("rope_theta", 10000.0),
        )

    def state_dict(self):
        return self._sd


def main():
    p = argparse.ArgumentParser()
    p.add_argument("model", choices=sorted(CONVERTERS))
    p.add_argument("--model-path", "--model_path", dest="model_path",
                   required=True,
                   help="HF hub id / local path, or a Meta llama release "
                        "dir (consolidated.*.pth + params.json) with "
                        "--meta_weights")
    p.add_argument("--meta_weights", action="store_true",
                   help="treat --model_path as Meta-format llama shards")
    p.add_argument("--out", required=True)
    p.add_argument("--dtype", default="fp32",
                   choices=["fp32", "bf16", "fp16"])
    args = p.parse_args()

    import torch
    from transformers import AutoModelForCausalLM

    import jax.numpy as jnp

    from megatron_llm_tpu import checkpointing

    if args.meta_weights:
        assert args.model in ("llama", "llama2", "codellama"), \
            "--meta_weights only applies to the llama family"
        hf = MetaLlamaShim(args.model_path, args.model)
    else:
        hf = AutoModelForCausalLM.from_pretrained(
            args.model_path, torch_dtype=torch.float32,
            trust_remote_code=False
        )
    dtype = {"fp32": jnp.float32, "bf16": jnp.bfloat16,
             "fp16": jnp.float16}[args.dtype]
    params, config = CONVERTERS[args.model](hf, dtype)
    config["model_name"] = args.model
    checkpointing.save_checkpoint(
        args.out, 0, params, args=config, release=True
    )
    print(f" converted {args.model_path} -> {args.out} (release checkpoint)")


if __name__ == "__main__":
    main()
