#!/usr/bin/env python
"""HuggingFace -> TPU-framework checkpoint conversion.

Reference: ``weights_conversion/hf_to_megatron.py`` — downloads/loads the
HF model, permutes the rotary QKV interleaving, packs the GQA layout, and
writes a TP=PP=1 ``release`` checkpoint with args (:259-449).

Here the output is the framework's layout-independent orbax checkpoint
(any later mesh re-sharding is free), written with
``checkpointing.save_checkpoint(..., release=True)``.

Usage:
    python weights_conversion/hf_to_megatron.py llama2 \
        --model-path /path/or/hub-id --out /ckpts/llama2-7b
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from weights_conversion.util import (
    pack_glu_ffn,
    pack_qkv,
    rotary_hf_to_interleaved,
)


def _np(t):
    # .copy() is load-bearing: .float() on an fp32 tensor is a no-op view,
    # so without it the numpy array aliases the live HF parameter and the
    # in-place rotary permutation would corrupt the source model.
    return t.detach().to("cpu").float().numpy().copy()


def convert_llama_family(hf_model, dtype=np.float32):
    """LlamaForCausalLM / MistralForCausalLM -> param pytree + config dict.

    reference: hf_to_megatron.py:117-258 (llama), :185-258 (mistral).
    """
    hf_cfg = hf_model.config
    nh = hf_cfg.num_attention_heads
    ng = getattr(hf_cfg, "num_key_value_heads", nh)
    d = hf_cfg.hidden_size // nh
    sd = dict(hf_model.state_dict())

    layers = []
    for i in range(hf_cfg.num_hidden_layers):
        p = f"model.layers.{i}."
        q = rotary_hf_to_interleaved(_np(sd[p + "self_attn.q_proj.weight"]), d)
        k = rotary_hf_to_interleaved(_np(sd[p + "self_attn.k_proj.weight"]), d)
        v = _np(sd[p + "self_attn.v_proj.weight"])
        layers.append({
            "input_norm": {
                "scale": _np(sd[p + "input_layernorm.weight"])
            },
            "attention": {
                "query_key_value": {"kernel": pack_qkv(q, k, v, nh, ng, d)},
                "dense": {
                    "kernel": np.ascontiguousarray(
                        _np(sd[p + "self_attn.o_proj.weight"]).T)
                },
            },
            "post_attention_norm": {
                "scale": _np(sd[p + "post_attention_layernorm.weight"])
            },
            "mlp": {
                "dense_h_to_4h": {
                    "kernel": pack_glu_ffn(
                        _np(sd[p + "mlp.gate_proj.weight"]),
                        _np(sd[p + "mlp.up_proj.weight"]),
                    )
                },
                "dense_4h_to_h": {
                    "kernel": np.ascontiguousarray(
                        _np(sd[p + "mlp.down_proj.weight"]).T)
                },
            },
        })

    import jax.numpy as jnp

    stacked = {}
    def stack(*path):
        def get(lp, keys):
            for kk in keys:
                lp = lp[kk]
            return lp
        return jnp.asarray(np.stack([get(l, path) for l in layers]), dtype)

    layer_tree = {
        "input_norm": {"scale": stack("input_norm", "scale")},
        "attention": {
            "query_key_value": {
                "kernel": stack("attention", "query_key_value", "kernel")},
            "dense": {"kernel": stack("attention", "dense", "kernel")},
        },
        "post_attention_norm": {
            "scale": stack("post_attention_norm", "scale")},
        "mlp": {
            "dense_h_to_4h": {
                "kernel": stack("mlp", "dense_h_to_4h", "kernel")},
            "dense_4h_to_h": {
                "kernel": stack("mlp", "dense_4h_to_h", "kernel")},
        },
    }
    params = {
        "embedding": {
            "word": {"embedding": jnp.asarray(
                _np(sd["model.embed_tokens.weight"]), dtype)}
        },
        "transformer": {
            "layers": layer_tree,
            "final_norm": {"scale": jnp.asarray(
                _np(sd["model.norm.weight"]), dtype)},
        },
        "lm_head": {"weight": jnp.asarray(
            _np(sd["lm_head.weight"]), dtype)},
    }
    config = {
        "num_layers": hf_cfg.num_hidden_layers,
        "hidden_size": hf_cfg.hidden_size,
        "num_attention_heads": nh,
        "num_attention_heads_kv": ng,
        "ffn_hidden_size": hf_cfg.intermediate_size,
        "padded_vocab_size": hf_cfg.vocab_size,
        "seq_length": getattr(hf_cfg, "max_position_embeddings", 4096),
        "max_position_embeddings": getattr(hf_cfg, "max_position_embeddings",
                                           4096),
        "position_embedding_type": "rotary",
        "glu_activation": "swiglu",
        "normalization": "rmsnorm",
        "add_bias_linear": False,
        "tie_embed_logits": False,
        "layernorm_epsilon": hf_cfg.rms_norm_eps,
        "rope_theta": getattr(hf_cfg, "rope_theta", 10000.0),
        "sliding_window_size": getattr(hf_cfg, "sliding_window", None),
        "hidden_dropout": 0.0,
        "attention_dropout": 0.0,
    }
    return params, config


def convert_falcon(hf_model, dtype=np.float32):
    """FalconForCausalLM -> param pytree (reference: hf_to_megatron.py:60-116).

    Falcon HF already packs QKV in grouped layout
    [ng*(qpg+2)*d, hidden]; only the rotary permutation (per (q|k) head
    inside each group) is needed."""
    hf_cfg = hf_model.config
    nh = hf_cfg.num_attention_heads
    ng = getattr(hf_cfg, "num_kv_heads", None) or (
        hf_cfg.num_attention_heads if not hf_cfg.multi_query else 1
    )
    if getattr(hf_cfg, "new_decoder_architecture", False):
        ng = hf_cfg.num_kv_heads
    d = hf_cfg.hidden_size // nh
    qpg = nh // ng
    sd = dict(hf_model.state_dict())

    import jax.numpy as jnp

    layers = []
    for i in range(hf_cfg.num_hidden_layers):
        p = f"transformer.h.{i}."
        qkv = _np(sd[p + "self_attention.query_key_value.weight"])
        # per-(q|k) head rotary permutation, leave v rows alone
        w = qkv.reshape(ng, qpg + 2, d, -1)
        hid = w.shape[-1]
        for g in range(ng):
            for h in range(qpg + 1):   # q heads + k
                w[g, h] = rotary_hf_to_interleaved(
                    w[g, h].reshape(d, hid), d
                ).reshape(d, hid)
        qkv = w.reshape(ng * (qpg + 2) * d, hid)

        entry = {
            "attention": {
                "query_key_value": {
                    "kernel": np.ascontiguousarray(qkv.T)},
                "dense": {"kernel": np.ascontiguousarray(
                    _np(sd[p + "self_attention.dense.weight"]).T)},
            },
            "mlp": {
                "dense_h_to_4h": {"kernel": np.ascontiguousarray(
                    _np(sd[p + "mlp.dense_h_to_4h.weight"]).T)},
                "dense_4h_to_h": {"kernel": np.ascontiguousarray(
                    _np(sd[p + "mlp.dense_4h_to_h.weight"]).T)},
            },
        }
        if getattr(hf_cfg, "new_decoder_architecture", False):
            entry["input_norm"] = {
                "scale": _np(sd[p + "ln_attn.weight"]),
                "bias": _np(sd[p + "ln_attn.bias"]),
            }
            entry["mlp_norm"] = {
                "scale": _np(sd[p + "ln_mlp.weight"]),
                "bias": _np(sd[p + "ln_mlp.bias"]),
            }
        else:
            entry["input_norm"] = {
                "scale": _np(sd[p + "input_layernorm.weight"]),
                "bias": _np(sd[p + "input_layernorm.bias"]),
            }
        layers.append(entry)

    def stack(*path):
        def get(lp, keys):
            for kk in keys:
                lp = lp[kk]
            return lp
        return jnp.asarray(np.stack([get(l, path) for l in layers]), dtype)

    layer_tree = {
        "input_norm": {"scale": stack("input_norm", "scale"),
                       "bias": stack("input_norm", "bias")},
        "attention": {
            "query_key_value": {
                "kernel": stack("attention", "query_key_value", "kernel")},
            "dense": {"kernel": stack("attention", "dense", "kernel")},
        },
        "mlp": {
            "dense_h_to_4h": {
                "kernel": stack("mlp", "dense_h_to_4h", "kernel")},
            "dense_4h_to_h": {
                "kernel": stack("mlp", "dense_4h_to_h", "kernel")},
        },
    }
    if "mlp_norm" in layers[0]:
        layer_tree["mlp_norm"] = {"scale": stack("mlp_norm", "scale"),
                                  "bias": stack("mlp_norm", "bias")}
    params = {
        "embedding": {"word": {"embedding": jnp.asarray(
            _np(sd["transformer.word_embeddings.weight"]), dtype)}},
        "transformer": {
            "layers": layer_tree,
            "final_norm": {
                "scale": jnp.asarray(_np(sd["transformer.ln_f.weight"]), dtype),
                "bias": jnp.asarray(_np(sd["transformer.ln_f.bias"]), dtype),
            },
        },
    }
    config = {
        "num_layers": hf_cfg.num_hidden_layers,
        "hidden_size": hf_cfg.hidden_size,
        "num_attention_heads": nh,
        "num_attention_heads_kv": ng,
        "ffn_hidden_size": 4 * hf_cfg.hidden_size,
        "padded_vocab_size": hf_cfg.vocab_size,
        "position_embedding_type": "rotary",
        "normalization": "layernorm",
        "parallel_attn": True,
        "parallel_layernorm": bool(
            getattr(hf_cfg, "new_decoder_architecture", False)),
        "gelu_variant": "exact",
        "add_bias_linear": False,
        "tie_embed_logits": True,
        "hidden_dropout": 0.0,
        "attention_dropout": 0.0,
    }
    return params, config


CONVERTERS = {
    "llama": convert_llama_family,
    "llama2": convert_llama_family,
    "codellama": convert_llama_family,
    "mistral": convert_llama_family,
    "falcon": convert_falcon,
}


def main():
    p = argparse.ArgumentParser()
    p.add_argument("model", choices=sorted(CONVERTERS))
    p.add_argument("--model-path", "--model_path", dest="model_path",
                   required=True, help="HF hub id or local path")
    p.add_argument("--out", required=True)
    p.add_argument("--dtype", default="fp32",
                   choices=["fp32", "bf16", "fp16"])
    args = p.parse_args()

    import torch
    from transformers import AutoModelForCausalLM

    import jax.numpy as jnp

    from megatron_llm_tpu import checkpointing

    hf = AutoModelForCausalLM.from_pretrained(
        args.model_path, torch_dtype=torch.float32, trust_remote_code=False
    )
    dtype = {"fp32": jnp.float32, "bf16": jnp.bfloat16,
             "fp16": jnp.float16}[args.dtype]
    params, config = CONVERTERS[args.model](hf, dtype)
    config["model_name"] = args.model
    checkpointing.save_checkpoint(
        args.out, 0, params, args=config, release=True
    )
    print(f" converted {args.model_path} -> {args.out} (release checkpoint)")


if __name__ == "__main__":
    main()
