#!/usr/bin/env python
"""Pretrain / finetune / instruct-tune GPT-family models on TPU.

Reference: ``/root/reference/finetune.py`` — the fork's primary entry
point: ``--model_name={gpt,llama,llama2,codellama,falcon,mistral,mixtral,qwen2}``
selects architecture defaults, data comes from packed GPT or instruction
datasets, and the loop runs under 3-way parallelism.

Usage mirrors the reference (``docs/guide/getting_started.md``):

    python finetune.py --model_name=llama2 \
        --tensor_model_parallel_size=8 --pipeline_model_parallel_size=1 \
        --data_path=/data/corpus --tokenizer_type=SentencePieceTokenizer \
        --vocab_file=tokenizer.model --bf16 --use_flash_attn \
        --micro_batch_size=2 --global_batch_size=128 --train_iters=1000 \
        --lr=1e-5 --lr_decay_style=cosine --save=ckpts --load=ckpts
"""

from __future__ import annotations

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np

from megatron_llm_tpu import checkpointing, topology
from megatron_llm_tpu.data.data_samplers import place_host_batch
from megatron_llm_tpu.arguments import (
    parallel_config_from_args,
    train_config_from_args,
    transformer_config_from_args,
)
from megatron_llm_tpu.dist_signal_handler import DistributedSignalHandler
from megatron_llm_tpu.global_vars import get_counters
from megatron_llm_tpu.initialize import initialize_megatron
from megatron_llm_tpu.models import MODEL_REGISTRY
from megatron_llm_tpu.optimizer import (
    MegatronOptimizer,
    OptimizerParamScheduler,
)
from megatron_llm_tpu.parallel import sharding as sh
from megatron_llm_tpu.training import pretrain
from jax.sharding import NamedSharding, PartitionSpec as P

MODEL_DEFAULTS = {
    # reference: finetune.py model_provider asserts + weights tables
    "llama": dict(position_embedding_type="rotary", glu_activation="swiglu",
                  use_rms_norm=True, use_bias=False, tie_embed_logits=False,
                  hidden_dropout=0.0, attention_dropout=0.0),
    "llama2": dict(position_embedding_type="rotary", glu_activation="swiglu",
                   use_rms_norm=True, use_bias=False, tie_embed_logits=False,
                   hidden_dropout=0.0, attention_dropout=0.0),
    "llama3": dict(position_embedding_type="rotary", glu_activation="swiglu",
                   use_rms_norm=True, use_bias=False, tie_embed_logits=False,
                   rope_theta=500000.0,
                   hidden_dropout=0.0, attention_dropout=0.0),
    "codellama": dict(position_embedding_type="rotary", glu_activation="swiglu",
                      use_rms_norm=True, use_bias=False,
                      tie_embed_logits=False, rope_theta=1e6,
                      hidden_dropout=0.0, attention_dropout=0.0),
    "falcon": dict(position_embedding_type="rotary", parallel_attn=True,
                   use_bias=False, hidden_dropout=0.0, attention_dropout=0.0),
    "mistral": dict(position_embedding_type="rotary", glu_activation="swiglu",
                    use_rms_norm=True, use_bias=False, tie_embed_logits=False,
                    sliding_window_size=4096,
                    hidden_dropout=0.0, attention_dropout=0.0),
    # sparse-MoE mistral (TPU-native extension; the reference has no MoE)
    "mixtral": dict(position_embedding_type="rotary", glu_activation="swiglu",
                    use_rms_norm=True, use_bias=False, tie_embed_logits=False,
                    num_experts=8, moe_top_k=2, rope_theta=1e6,
                    hidden_dropout=0.0, attention_dropout=0.0),
    "qwen2": dict(position_embedding_type="rotary", glu_activation="swiglu",
                  use_rms_norm=True, use_bias=False, add_qkv_bias=True,
                  tie_embed_logits=False, rope_theta=1e6,
                  hidden_dropout=0.0, attention_dropout=0.0),
    "gemma": dict(position_embedding_type="rotary", glu_activation="geglu",
                  use_rms_norm=True, use_bias=False, layernorm_epsilon=1e-6,
                  hidden_dropout=0.0, attention_dropout=0.0),
    "gpt_neox": dict(position_embedding_type="rotary", use_bias=True,
                     parallel_attn=True, parallel_layernorm=True,
                     rotary_percent=0.25, tie_embed_logits=False,
                     gelu_variant="exact",
                     hidden_dropout=0.0, attention_dropout=0.0),
    "pythia": dict(position_embedding_type="rotary", use_bias=True,
                   parallel_attn=True, parallel_layernorm=True,
                   rotary_percent=0.25, tie_embed_logits=False,
                   gelu_variant="exact",
                   hidden_dropout=0.0, attention_dropout=0.0),
    "gpt": dict(),
}


def extra_args(parser):
    g = parser.add_argument_group("finetune")
    g.add_argument("--model_name", required=True,
                   choices=sorted(MODEL_DEFAULTS))
    g.add_argument("--model_type", default=None)  # compat
    # LoRA (megatron_llm_tpu/lora.py): train low-rank adapters over a
    # frozen base — Adam state and grads shrink to the adapter size.
    # Checkpoints are exported MERGED (standard format); continuing a
    # LoRA run means re-finetuning from the merged weights.
    g.add_argument("--lora_rank", type=int, default=0,
                   help="enable LoRA with this rank (0 = off)")
    g.add_argument("--lora_alpha", type=float, default=None,
                   help="LoRA scaling numerator (default 2*rank)")
    g.add_argument("--lora_targets",
                   default="query_key_value,dense",
                   help="comma-separated linear names to adapt")
    return parser


def model_provider(args):
    if args.model_name == "gemma" and \
            getattr(args, "embedding_multiplier", None) is None:
        # gemma's sqrt(hidden) embedding normalizer depends on the
        # parsed hidden size, so the static preset table can't carry it
        import math

        args.embedding_multiplier = math.sqrt(args.hidden_size)
    cfg = transformer_config_from_args(args, args.model_name)
    return MODEL_REGISTRY[args.model_name](cfg)


def build_data_iterator(args, mesh, num_micro, consumed_samples=0):
    """Packed GPT or instruction dataset -> global-batch iterator with dp
    sharding applied (reference: build_train_valid_test_data_iterators,
    training.py:877; data only needs loading once per process).

    ``consumed_samples`` (from the checkpoint meta) drives the sampler's
    deterministic skip so an elastic resume — possibly at a different
    dp x slice product — continues the same global sample order."""
    # total data parallelism: the batch dim spans ('slice', 'dp')
    total_dp = args.data_parallel_size * getattr(args, "num_slices", 1)
    if args.data_path is None:
        # synthetic data (smoke/bench runs)
        rng = np.random.RandomState(args.seed)
        mb = args.micro_batch_size * total_dp

        def synth():
            while True:
                toks = rng.randint(
                    0, args.padded_vocab_size,
                    (num_micro, mb, args.seq_length),
                ).astype(np.int32)
                yield {
                    "tokens": toks,
                    "labels": np.roll(toks, -1, axis=-1),
                    "loss_mask": np.ones_like(toks, np.float32),
                }
        host_iter, eval_iter = synth(), None
    elif args.data_type == "instruction":
        from megatron_llm_tpu.data.data_samplers import (
            build_pretraining_data_loader,
        )
        from megatron_llm_tpu.data.instruction_dataset import (
            InstructionDataset,
            build_instruction_collator,
        )
        from megatron_llm_tpu.global_vars import get_tokenizer

        ds = InstructionDataset(
            args.data_path[0],
            num_samples=args.train_iters * args.global_batch_size,
            seed=args.seed,
        )
        collate = build_instruction_collator(
            args.seq_length, get_tokenizer().pad,
            variable_seq_lengths=args.variable_seq_lengths,
            scalar_loss_mask=args.scalar_loss_mask,
        )
        host_iter = iter(build_pretraining_data_loader(
            ds, consumed_samples, args.micro_batch_size, total_dp,
            num_micro, args.dataloader_type, args.seed, collate_fn=collate,
        ))
        eval_iter = None
    else:
        from megatron_llm_tpu.data.data_samplers import (
            build_pretraining_data_loader,
        )
        from megatron_llm_tpu.data.gpt_dataset import (
            build_train_valid_test_datasets,
        )

        n_train = args.train_iters * args.global_batch_size
        n_eval = args.eval_iters * args.global_batch_size
        train_ds, valid_ds, _ = build_train_valid_test_datasets(
            args.data_path, args.split,
            [n_train, n_eval, 0],
            args.seq_length, args.seed, args.data_impl,
        )
        host_iter = iter(build_pretraining_data_loader(
            train_ds, consumed_samples, args.micro_batch_size, total_dp,
            num_micro, args.dataloader_type, args.seed,
        ))
        eval_iter = (iter(build_pretraining_data_loader(
            valid_ds, 0, args.micro_batch_size, total_dp,
            num_micro, args.dataloader_type, args.seed,
        )) if valid_ds is not None else None)

    dsh = NamedSharding(mesh, P(None, topology.data_axes(), None))

    def shard(it):
        if it is None:
            return None
        def gen():
            for b in it:
                yield {k: place_host_batch(v, dsh) for k, v in b.items()}
        return gen()

    return shard(host_iter), shard(eval_iter)


_INVERTED_FLAGS = {
    "use_bias": "--no_bias",
    "tie_embed_logits": "--no_tie_embed_logits",
}


def _apply_model_defaults(args, argv):
    """Model presets fill any flag the user didn't pass explicitly
    (reference: finetune.py passes args_defaults + the model classes
    assert; here the presets make the CLI self-sufficient)."""
    for k, v in MODEL_DEFAULTS[args.model_name].items():
        flags = [f"--{k}"]
        if k in _INVERTED_FLAGS:
            flags.append(_INVERTED_FLAGS[k])
        explicitly_set = any(
            a == flag or a.startswith(flag + "=")
            for a in argv for flag in flags
        )
        if not explicitly_set:
            setattr(args, k, v)


# checkpoint-args field -> CLI args attribute (reference checkpointing.py
# _set_arg list; config_to_args writes the config-field spellings)
_CKPT_ARG_MAP = {
    "num_layers": "num_layers",
    "hidden_size": "hidden_size",
    "ffn_hidden_size": "ffn_hidden_size",
    "num_attention_heads": "num_attention_heads",
    "num_attention_heads_kv": "num_attention_heads_kv",
    "kv_channels": "kv_channels",
    "seq_length": "seq_length",
    "max_position_embeddings": "max_position_embeddings",
    "padded_vocab_size": "padded_vocab_size",
    "position_embedding_type": "position_embedding_type",
    "glu_activation": "glu_activation",
    "tie_embed_logits": "tie_embed_logits",
    "add_bias_linear": "use_bias",
    "use_post_ln": "use_post_ln",
    "parallel_attn": "parallel_attn",
    "parallel_layernorm": "parallel_layernorm",
    "sliding_window_size": "sliding_window_size",
    "layernorm_epsilon": "layernorm_epsilon",
    "rope_theta": "rope_theta",
    "rope_scaling_factor": "rope_scaling_factor",
    "rope_llama3_scaling": "rope_llama3_scaling",
    # MoE architecture fields: a dense rebuild of an MoE checkpoint (or
    # vice versa) fails orbax restore on the param-tree mismatch
    "num_experts": "num_experts",
    "moe_top_k": "moe_top_k",
    "moe_capacity_factor": "moe_capacity_factor",
    "moe_min_capacity": "moe_min_capacity",
    # qwen2's QKV-only bias changes the param tree like the MoE fields do
    "add_qkv_bias": "add_qkv_bias",
    # gemma's embedding normalizer changes forward math, not the tree
    "embedding_multiplier": "embedding_multiplier",
    # forward-math fields for the NeoX family
    "rotary_percent": "rotary_percent",
    "gelu_variant": "gelu_variant",
}


def _apply_checkpoint_args(args):
    """--use_checkpoint_args: the architecture recorded in the checkpoint
    overrides the CLI (reference checkpointing.py:520-560)."""
    ckpt_args = checkpointing.load_checkpoint_args(
        args.load, getattr(args, "load_iters", None))
    if not ckpt_args:
        print(" > WARNING: --use_checkpoint_args but the checkpoint "
              "records no args", flush=True)
        return
    for src, dst in _CKPT_ARG_MAP.items():
        # no is-not-None filter: a recorded null is a real override
        # (e.g. glu_activation=None must clear a model preset's swiglu,
        # or the restored MLP shapes mismatch the checkpoint)
        if src in ckpt_args:
            setattr(args, dst, ckpt_args[src])
    if ckpt_args.get("normalization") is not None:
        args.use_rms_norm = ckpt_args["normalization"] == "rmsnorm"
    print(" > using architecture args from the checkpoint", flush=True)


def main():
    args = initialize_megatron(extra_args_provider=extra_args)
    _apply_model_defaults(args, sys.argv[1:])
    if args.use_checkpoint_args and args.load:
        _apply_checkpoint_args(args)
        # re-derive and re-assert everything validate_args computed from
        # the CLI architecture (vpp divisibility, encoder_* backfills...)
        # against the overridden values
        from megatron_llm_tpu.arguments import validate_args
        validate_args(args)
    if args.padded_vocab_size is None:
        raise SystemExit("need --vocab_size/--padded_vocab_size or a tokenizer")

    # hardened checkpoint IO knobs + fault-tolerance runtime
    # (docs/guide/fault_tolerance.md)
    checkpointing.configure_save(
        total_limit=getattr(args, "save_total_limit", 0),
        retries=getattr(args, "save_retries", 2),
        retry_backoff=getattr(args, "save_retry_backoff", 0.25))
    from megatron_llm_tpu.resilience import build_resilience
    resilience = build_resilience(args)

    mesh = topology.get_mesh()
    model = model_provider(args)
    # built before the checkpoint load so the startup restore lands in
    # the trace (--trace_dir opens a checkpoint_load span)
    from megatron_llm_tpu.telemetry import build_telemetry

    telemetry = build_telemetry(args, model)
    tc = train_config_from_args(args)
    pc = parallel_config_from_args(args)
    num_micro = args.global_batch_size // (
        args.micro_batch_size * args.data_parallel_size * args.num_slices
    )

    # params: fresh init or checkpoint
    params = None
    start_iteration = 0
    opt_state = None
    consumed_samples = 0
    if args.load:
        # abstract template (shapes + current-mesh shardings, no device
        # memory) makes the orbax restore direct-to-device on THIS mesh —
        # i.e. load-time resharding even when the checkpoint was written
        # under a different topology
        try:
            abstract = jax.eval_shape(
                lambda: model.init(jax.random.PRNGKey(args.seed)))
            shardings = sh.make_shardings(model.param_specs(abstract))
            params_template = jax.tree_util.tree_map(
                lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                                  sharding=s),
                abstract, shardings)
        except Exception:
            params_template = None      # fall back to host-side restore
        params, opt_state, meta = checkpointing.load_checkpoint(
            args.load, finetune=args.finetune,
            iteration=getattr(args, "load_iters", None),
            params_template=params_template,
        )
        if params is not None:
            start_iteration = meta["iteration"]
            print(f" loaded checkpoint at iteration {start_iteration}")
            if not args.finetune:
                # elastic resume: continue the cumulative sample count and
                # the deterministic data order from where the checkpoint
                # left off (the resharding restore above already handled a
                # different dp x slice mesh); announce + JSONL-log a fleet
                # shape change against the saved run_shape.json
                consumed_samples = int(meta.get("consumed_samples", 0) or 0)
                get_counters()["samples"] = consumed_samples
                from megatron_llm_tpu import multislice
                multislice.announce_elastic_resume(
                    args.load, args, start_iteration, consumed_samples,
                    stream=getattr(telemetry, "stream", None))
    if params is None:
        params = model.init(jax.random.PRNGKey(args.seed))

    # interleaved VPP trains with the layer stack in stage-major order;
    # checkpoints stay in natural order (see pipeline.permute_layer_stack)
    vpp = pc.virtual_pipeline_model_parallel_size or 1
    from megatron_llm_tpu.parallel.pipeline import (
        convert_opt_state_layout,
        convert_params_layout,
    )
    params = convert_params_layout(
        params, args.num_layers, pc.pipeline_model_parallel_size, vpp,
        to_stage_major=True)
    opt_state = convert_opt_state_layout(
        opt_state, args.num_layers, pc.pipeline_model_parallel_size, vpp,
        to_stage_major=True)
    params = sh.shard_params(params, model.param_specs(params))

    def save_natural(save_dir, it_, params_, opt_state_, scheduler_=None):
        if lora_base is not None:
            # export MERGED weights in the standard checkpoint format
            # (loadable anywhere a base checkpoint is); the lora-shaped
            # optimizer state is fresh-start-only, so drop it
            from megatron_llm_tpu.lora import merge_lora
            params_ = merge_lora(lora_base, params_)
            opt_state_ = None
        checkpointing.save_checkpoint(
            save_dir, it_,
            convert_params_layout(
                params_, args.num_layers, pc.pipeline_model_parallel_size,
                vpp, to_stage_major=False),
            convert_opt_state_layout(
                opt_state_, args.num_layers, pc.pipeline_model_parallel_size,
                vpp, to_stage_major=False),
            # closure fallback: `scheduler` is bound by call time, after
            # main builds it
            scheduler_ if scheduler_ is not None else scheduler,
            args=checkpointing.config_to_args(getattr(model, "cfg", None)),
            consumed_samples=get_counters().get("samples", 0),
            async_save=getattr(args, "async_save", False),
        )

    if args.fp16 or args.bf16:
        dt = jnp.float16 if args.fp16 else jnp.bfloat16
        params = jax.tree_util.tree_map(lambda p: p.astype(dt), params)

    # LoRA: swap the trainable tree for low-rank adapters over the
    # frozen (already sharded + cast) base
    lora_base = None
    if args.lora_rank:
        if pc.pipeline_model_parallel_size > 1:
            raise SystemExit("--lora_rank supports pp=1 on the CLI path "
                             "(the lora.py library composes manually)")
        if args.load and not args.finetune:
            raise SystemExit(
                "--lora_rank with --load requires --finetune: LoRA runs "
                "start fresh from base weights (checkpoints are exported "
                "merged; there is no LoRA-shaped optimizer state to "
                "resume)")
        from megatron_llm_tpu.lora import LoraAdapter
        lora_base = params
        model = LoraAdapter(model, lora_base)
        lora = model.init_lora(
            args.lora_rank, jax.random.PRNGKey(args.seed + 1),
            alpha=args.lora_alpha,
            targets=tuple(t for t in args.lora_targets.split(",") if t))
        params = sh.shard_params(lora, model.param_specs(lora))
        n_ad = model.num_params(params)
        print(f" > LoRA rank {args.lora_rank}: {n_ad/1e6:.2f}M adapter "
              f"params trainable, base frozen", flush=True)

    train_iter, eval_iter = build_data_iterator(
        args, mesh, num_micro, consumed_samples=consumed_samples)

    optimizer = MegatronOptimizer(
        tc, params_dtype=jax.tree_util.tree_leaves(params)[0].dtype
    )
    scheduler = OptimizerParamScheduler(
        max_lr=tc.lr, min_lr=tc.min_lr,
        lr_warmup_steps=tc.lr_warmup_iters,
        lr_decay_steps=tc.lr_decay_iters or max(tc.train_iters, 1),
        lr_decay_style=tc.lr_decay_style,
        # `is not None`, not `or`: explicit 0.0 means ramp from zero
        start_wd=(tc.start_weight_decay
                  if tc.start_weight_decay is not None else tc.weight_decay),
        end_wd=(tc.end_weight_decay
                if tc.end_weight_decay is not None else tc.weight_decay),
        wd_incr_steps=max(tc.train_iters, 1),
        wd_incr_style=tc.weight_decay_incr_style,
    )
    scheduler.num_steps = start_iteration

    # phase-2 resume: optimizer + scheduler state (params came in phase 1;
    # the optimizer had to exist first to provide the restore template).
    # The template is abstract (jax.eval_shape) — materializing a real
    # optimizer state just to read shapes would transiently double the
    # optimizer-state footprint on exactly the large-model resumes that
    # need direct-to-device restore.
    if args.load and start_iteration and not args.finetune:
        opt_template = jax.eval_shape(
            lambda p: optimizer.init(convert_params_layout(
                p, args.num_layers, pc.pipeline_model_parallel_size, vpp,
                to_stage_major=False)),
            params)
        _, loaded_opt, _ = checkpointing.load_checkpoint(
            args.load, load_params=False,
            opt_state_template=opt_template, scheduler=scheduler,
        )
        if loaded_opt is not None:
            staged = convert_opt_state_layout(
                loaded_opt, args.num_layers,
                pc.pipeline_model_parallel_size, vpp, to_stage_major=True)
            # re-place restored leaves where a fresh init would put them:
            # param-shaped moments/masters follow the params' shardings
            # (zeros_like preserves sharding); scalar step / grad-scaler
            # state replicates across the mesh
            from jax.sharding import NamedSharding, PartitionSpec

            def _replicated(t):
                return jax.device_put(t, NamedSharding(
                    mesh, PartitionSpec(*([None] * t.ndim))))

            psh = jax.tree_util.tree_map(lambda p: p.sharding, params)

            def _like_params(tree):
                if tree is None:
                    return None
                return jax.tree_util.tree_map(jax.device_put, tree, psh)

            opt_state = staged._replace(
                step=_replicated(staged.step),
                master_params=_like_params(staged.master_params),
                exp_avg=_like_params(staged.exp_avg),
                exp_avg_sq=_like_params(staged.exp_avg_sq),
                grad_scaler=jax.tree_util.tree_map(
                    _replicated, staged.grad_scaler),
            )
            print(" restored optimizer + scheduler state")

    handler = DistributedSignalHandler() if args.exit_signal_handler else None
    if handler:
        handler.install()

    # pp > 1 drives the pipelined engine through the same pretrain() loop
    # (custom train_step); eval needs a forward-only program, which the
    # pipelined step doesn't provide
    pipelined = pc.pipeline_model_parallel_size > 1
    custom_step = None
    if pipelined:
        from megatron_llm_tpu.parallel.pipeline import (
            build_pipeline_train_step,
        )
        custom_step = build_pipeline_train_step(
            model, optimizer, pc, num_micro,
            layer_stats=args.log_layer_stats_interval > 0)
        opt_state = opt_state or optimizer.init(params)
    from megatron_llm_tpu.timers import Timers

    # metrics writer: wandb (or its JSONL offline fallback) and/or a
    # tensorboard-dir JSONL stream — one add_scalar code path either way
    writer = None
    if args.wandb_logger or args.tensorboard_dir:
        from megatron_llm_tpu.wandb_logger import WandbTBShim

        fallback = (os.path.join(args.tensorboard_dir, "metrics.jsonl")
                    if args.tensorboard_dir else "wandb_offline.jsonl")
        if args.tensorboard_dir:
            os.makedirs(args.tensorboard_dir, exist_ok=True)
        writer = WandbTBShim(
            config=checkpointing.config_to_args(getattr(model, "cfg", None)),
            project=args.wandb_project, entity=args.wandb_entity,
            name=args.wandb_name, run_id=args.wandb_id,
            api_key=args.wandb_api_key, fallback_path=fallback,
            resume="must" if args.wandb_resume else "allow",
            force_offline=not args.wandb_logger)

    if args.eval_only:
        # reference --eval_only: no training, one evaluation pass
        if pipelined:
            raise SystemExit(
                "--eval_only is not supported with pipeline parallelism "
                "(no forward-only program for the pipelined engine)")
        if eval_iter is None:
            raise SystemExit("--eval_only requires validation data")
        from megatron_llm_tpu.training import build_train_step
        eval_step = build_train_step(model, optimizer, pc, num_micro,
                                     forward_only=True)
        losses = [float(eval_step(params, next(eval_iter), None))
                  for _ in range(args.eval_iters)]
        print(f" eval_only: validation loss "
              f"{sum(losses) / len(losses):.6E}")
        telemetry.close()
        return

    try:
        params, opt_state, it = pretrain(
            model, params, tc, pc, train_iter,
            optimizer=optimizer,
            scheduler=scheduler,
            train_step=custom_step,
            save_fn=save_natural,
            resilience=resilience,
            telemetry=telemetry,
            timers=Timers(log_level=args.timing_log_level,
                          log_option=args.timing_log_option),
            log_params_norm=args.log_params_norm,
            log_num_zeros_in_grad=args.log_num_zeros_in_grad,
            log_layer_stats_interval=args.log_layer_stats_interval,
            writer=writer,
            tensorboard_log_interval=args.tensorboard_log_interval,
            log_timers=args.log_timers_to_tensorboard,
            log_memory=args.log_memory_to_tensorboard,
            log_batch_size=args.log_batch_size_to_tensorboard,
            log_world_size=args.log_world_size_to_tensorboard,
            log_validation_ppl=args.log_validation_ppl_to_tensorboard,
            log_interval=args.log_interval,
            save_interval=args.save_interval,
            async_save=getattr(args, "async_save", False),
            save_dir=args.save,
            eval_iterator=None if pipelined else eval_iter,
            eval_interval=(args.eval_interval
                           if eval_iter and not pipelined else None),
            eval_iters=args.eval_iters,
            exit_signal_handler=handler,
            start_iteration=start_iteration,
            opt_state=opt_state,
            skip_iters=getattr(args, "skip_iters", ()) or (),
            exit_interval=getattr(args, "exit_interval", None),
            exit_duration_in_mins=getattr(args, "exit_duration_in_mins",
                                          None),
            preempt_exit_code=getattr(args, "preempt_exit_code", 0) or 0,
        )
    finally:
        # stop the watchdog thread + uninstall the fault hook on every
        # exit path (signal-save exits via SystemExit mid-pretrain)
        if resilience is not None:
            resilience.close()
        # close after resilience: a crash path above may still want to
        # dump the flight recorder through the installed stream
        telemetry.close()

    if args.save:
        save_natural(args.save, it, params, opt_state)
        # flush a final --async_save before the interpreter starts tearing
        # down orbax's executor (a dangling dispatch races shutdown)
        checkpointing.finalize_async_saves()
        print(f" saved final checkpoint at iteration {it}")


if __name__ == "__main__":
    main()
