"""Fused (vocab-chunked) linear + cross entropy: parity with the
materialize-the-logits path, op-level and through the model."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megatron_llm_tpu.ops.cross_entropy import (
    fused_linear_cross_entropy,
    vocab_parallel_cross_entropy,
)


def _inputs(n=48, h=64, v=96, dtype=jnp.float32, seed=0):
    rng = np.random.RandomState(seed)
    hid = jnp.asarray(rng.randn(n, h) * 0.3, dtype)
    w = jnp.asarray(rng.randn(v, h) * 0.3, dtype)
    labels = jnp.asarray(rng.randint(0, v, (n,)))
    return hid, w, labels


def _ref_loss(hid, w, labels):
    logits = (hid @ w.T).astype(jnp.float32)
    return vocab_parallel_cross_entropy(logits, labels)


@pytest.mark.parametrize("chunk", [96, 32, 13, 8192])
def test_fused_ce_forward_parity(chunk):
    hid, w, labels = _inputs()
    ref = _ref_loss(hid, w, labels)
    out = fused_linear_cross_entropy(hid, w, labels, chunk_size=chunk)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5)


def test_fused_ce_gradient_parity():
    hid, w, labels = _inputs()
    mask = jnp.asarray(
        np.random.RandomState(1).rand(labels.shape[0]) > 0.3, jnp.float32)

    def loss_ref(hid, w):
        return jnp.sum(_ref_loss(hid, w, labels) * mask)

    def loss_fused(hid, w):
        return jnp.sum(fused_linear_cross_entropy(
            hid, w, labels, chunk_size=32) * mask)

    g_ref = jax.grad(loss_ref, argnums=(0, 1))(hid, w)
    g_fused = jax.jit(jax.grad(loss_fused, argnums=(0, 1)))(hid, w)
    for a, b in zip(g_fused, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_fused_ce_bf16_and_batched_shape():
    rng = np.random.RandomState(2)
    hid = jnp.asarray(rng.randn(2, 16, 32) * 0.3, jnp.bfloat16)
    w = jnp.asarray(rng.randn(64, 32) * 0.3, jnp.bfloat16)
    labels = jnp.asarray(rng.randint(0, 64, (2, 16)))
    out = fused_linear_cross_entropy(hid, w, labels, chunk_size=16)
    ref = vocab_parallel_cross_entropy(
        jnp.einsum("bsh,vh->bsv", hid, w).astype(jnp.float32), labels)
    assert out.shape == (2, 16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=3e-2)


def test_model_loss_parity_fused_vs_unfused(utils):
    """GPTModel with fused_lm_cross_entropy on vs off: identical loss and
    gradients on the tp=1 path."""
    import dataclasses

    from megatron_llm_tpu.models.llama import LlamaModel, llama_config

    utils.initialize_model_parallel(tp=1)
    cfg = llama_config("tiny", num_layers=2, hidden_size=64,
                       num_attention_heads=4, ffn_hidden_size=96,
                       padded_vocab_size=128, seq_length=32,
                       max_position_embeddings=32)
    model_f = LlamaModel(dataclasses.replace(
        cfg, fused_lm_cross_entropy=True))
    model_u = LlamaModel(cfg)               # default: unfused
    params = model_f.init(jax.random.PRNGKey(0))
    toks = jnp.asarray(np.random.RandomState(0).randint(0, 128, (8, 32)))
    labels = jnp.roll(toks, -1, axis=-1)

    loss_f = model_f(params, toks, labels=labels, train=False)
    loss_u = model_u(params, toks, labels=labels, train=False)
    np.testing.assert_allclose(np.asarray(loss_f), np.asarray(loss_u),
                               atol=1e-5)

    gf = jax.grad(lambda p: jnp.mean(
        model_f(p, toks, labels=labels, train=False)))(params)
    gu = jax.grad(lambda p: jnp.mean(
        model_u(p, toks, labels=labels, train=False)))(params)
    for a, b in zip(jax.tree_util.tree_leaves(gf),
                    jax.tree_util.tree_leaves(gu)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_pick_chunk_guards():
    from megatron_llm_tpu.ops.cross_entropy import _flce_pick_chunk

    assert _flce_pick_chunk(32000, 8192) == 8000
    assert _flce_pick_chunk(96, 200) == 96        # chunk > vocab: whole vocab
    with pytest.raises(ValueError, match=">= 1"):
        _flce_pick_chunk(32000, 0)
    with pytest.raises(ValueError, match=">= 1"):
        _flce_pick_chunk(32000, -3)
    # vocab with no divisor near the request (2 * 16001): refuse rather
    # than silently serializing the scan into ~16k steps
    with pytest.raises(ValueError, match="no divisor"):
        _flce_pick_chunk(32002, 8192)
