"""Corpus-curation suite e2e: synthetic jsonl in -> filtered/deduped out.

Covers the pipeline the reference ships in ``tools/openwebtext/``
(README workflow): URL blacklist, cleanup (mojibake/language/length),
MinHash-LSH dedup (find -> group -> remove), and task-ngram
decontamination.  Pure Python/numpy — no jax, no tunnel.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OWT = os.path.join(REPO, "tools", "openwebtext")
sys.path.insert(0, OWT)

from blacklist_urls import (classify, domain_is_blacklisted,  # noqa: E402
                            extension_is_blacklisted, registered_domain,
                            url_is_malformed)
from cleanup_dataset import (filter_corpus, fix_text,  # noqa: E402
                             is_english, word_count)
from find_duplicates import main as find_duplicates_main  # noqa: E402
from group_duplicate_urls import group_pairs  # noqa: E402
from remove_group_duplicates import ids_to_remove  # noqa: E402
from filter_ngrams import build_ngrams, scrub_text  # noqa: E402
from minhash_lsh import LSHCache, MinHasher, jaccard, shingles  # noqa: E402

import numpy as np  # noqa: E402


# ---------------------------------------------------------------- helpers

_EN = ("The quick brown fox jumps over the lazy dog and then it runs to "
       "the forest where all of the other animals have been waiting for "
       "a long time because they wanted to see what the fox would do ")


def _en_doc(salt="", words=200):
    base = (_EN + salt + " ") * (words // len(_EN.split()) + 1)
    return " ".join(base.split()[:words])


# ---------------------------------------------------------------- minhash

class TestMinHashLSH:
    def test_identical_fingerprints(self):
        h = MinHasher(seeds=np.arange(1, 101))
        a = h.fingerprint(_en_doc())
        b = h.fingerprint(_en_doc())
        assert np.array_equal(a, b)

    def test_similar_docs_share_buckets(self):
        h = MinHasher(seeds=np.arange(1, 101))
        cache = LSHCache(num_bands=10, hasher=h)
        doc = _en_doc()
        near = doc.replace("fox", "cat")  # high jaccard
        far = ("completely different content about tensor meshes and "
               "sharded collectives on many chips ") * 10
        cache.add_doc(doc, "a")
        cache.add_doc(near, "b")
        cache.add_doc(far, "c")
        pairs = cache.candidate_pairs()
        assert ("a", "b") in pairs
        assert ("a", "c") not in pairs and ("b", "c") not in pairs

    def test_jaccard_modes(self):
        sa, sb = {1, 2, 3, 4}, {3, 4, 5}
        assert jaccard(sa, sb, "union") == pytest.approx(2 / 5)
        assert jaccard(sa, sb, "min") == pytest.approx(2 / 3)
        assert jaccard(sa, sb, "max") == pytest.approx(2 / 4)
        assert jaccard(set(), sb) == 0.0

    def test_worker_params_round_trip(self):
        h = MinHasher(seeds=np.arange(1, 101))
        h2 = MinHasher.from_params(*h.params())
        assert np.array_equal(h.fingerprint(_en_doc()),
                              h2.fingerprint(_en_doc()))

    def test_shingles(self):
        assert shingles("abcdef", 5) == {"abcde", "bcdef"}
        assert shingles("abc", 5) == set()


# ------------------------------------------------------------------- urls

class TestBlacklistUrls:
    def test_domain(self):
        assert domain_is_blacklisted("https://www.youtube.com/watch?v=x")
        assert domain_is_blacklisted("http://imgur.com/a/b")
        assert not domain_is_blacklisted("https://arxiv.org/abs/1909.08053")

    def test_two_level_suffix(self):
        assert registered_domain("https://www.youtube.co.uk/x") == "youtube"
        assert registered_domain("https://news.bbc.co.uk/") == "bbc"
        assert registered_domain("https://example.com/") == "example"
        assert registered_domain("http://10.0.0.1/x") == ""

    def test_extension(self):
        assert extension_is_blacklisted("http://x.org/file.JPG?dl=1")
        assert extension_is_blacklisted("http://x.org/a.tar.gz")
        assert extension_is_blacklisted("http://x.org/photo.jpg#section")
        assert not extension_is_blacklisted("http://x.org/article.html")

    def test_malformed(self):
        assert url_is_malformed("notaurl")
        assert url_is_malformed("ftp://x.org/a")
        assert not url_is_malformed("https://example.org/path?q=1")

    def test_classify_order_and_dupes(self):
        seen = set()
        url = "https://example.org/article-one"
        assert classify(url, seen) is None
        seen.add(url)
        assert classify(url, seen) == "duplicate"
        assert classify("http://x", seen) == "short"  # len <= 8


# ---------------------------------------------------------------- cleanup

class TestCleanup:
    def test_fix_mojibake(self):
        broken = "Itâ€™s a test â€“ really"
        fixed = fix_text(broken)
        assert "’s" in fixed and "–" in fixed

    def test_fix_double_mojibake(self):
        once = "café".encode("utf-8").decode("cp1252")
        twice = once.encode("utf-8").decode("cp1252")
        assert fix_text(twice) == "café"

    def test_fix_controls_and_newlines(self):
        assert fix_text("a\r\nb\x00c") == "a\nbc"

    def test_clean_text_unchanged(self):
        assert fix_text(_en_doc()) == _en_doc()

    def test_is_english(self):
        assert is_english(_en_doc())
        assert not is_english(
            "Der schnelle braune Fuchs springt über den faulen Hund "
            "und dann läuft er schnell weg weil er etwas gesehen hat "
            "das ihm große Angst gemacht hat und niemand wusste warum")
        assert not is_english("快速の茅色狐" * 30)

    def test_filter_corpus(self, tmp_path):
        src = tmp_path / "in.jsonl"
        docs = [
            {"url": "u1", "text": _en_doc(words=200)},          # keep
            {"url": "u2", "text": _en_doc(words=40)},           # small
            {"url": "u3", "text": "El rápido zorro marrón salta "
             "sobre el perro perezoso y luego corre hacia el bosque donde "
             "todos los animales esperaban desde hace mucho tiempo " * 5},
        ]
        with open(src, "w") as f:
            for d in docs:
                f.write(json.dumps(d) + "\n")
        out = tmp_path / "out.jsonl"
        counts = filter_corpus(str(src), str(out), min_words=128)
        kept = [json.loads(l) for l in open(out)]
        assert [d["url"] for d in kept] == ["u1"]
        assert counts["small"] == 1 and counts["non_english"] == 1

    def test_word_count(self):
        assert word_count("a b  c\nd") == 4


class TestCleanupFixDataset:
    def test_task_flags(self, tmp_path):
        from cleanup_fix_dataset import process_doc

        long_en = _en_doc(words=200)
        assert process_doc("short", ["remove_512"])[1] == "remove_512"
        assert process_doc(long_en, ["remove_512"])[1] is None
        assert process_doc("tiny javascript snippet",
                           ["remove_256_javascript"])[1] \
            == "remove_256_javascript"
        assert process_doc("ein kurzer deutscher text ohne englisch "
                           "und noch ein paar mehr worte dazu",
                           ["remove_512_non_english"])[1] \
            == "remove_512_non_english"
        fixed, reason = process_doc("Itâ€™s fine. " + long_en,
                                    ["ftfy_fix_text"])
        assert reason is None and "’s" in fixed
        cleaned, _ = process_doc("a  b   c", ["general_cleaning"])
        assert cleaned == "a b c"
        # newline-adjacent space runs and post-punctuation newlines too
        assert process_doc("a\n  b", ["general_cleaning"])[0] == "a b"
        assert process_doc("end.\n\nNext",
                           ["general_cleaning"])[0] == "end. Next"

    def test_tasks_apply_in_cli_order(self):
        from cleanup_fix_dataset import process_doc

        # ~520 chars of mojibake that shrinks under 512 once fixed:
        # fix-first drops it, filter-first keeps it
        moji = ("Itâ€™s x " * 65).strip()      # 519 chars raw
        assert len(moji) >= 512
        from cleanup_dataset import fix_text
        assert len(fix_text(moji)) < 512
        _, reason = process_doc(moji, ["ftfy_fix_text", "remove_512"])
        assert reason == "remove_512"
        _, reason = process_doc(moji, ["remove_512", "ftfy_fix_text"])
        assert reason is None

    def test_cli_splits_kept_and_filtered(self, tmp_path):
        src = tmp_path / "in.jsonl"
        docs = [{"text": _en_doc(words=200)}, {"text": "too short"}]
        with open(src, "w") as f:
            for d in docs:
                f.write(json.dumps(d) + "\n")
        kept = tmp_path / "kept.jsonl"
        filt = tmp_path / "filtered.jsonl"
        r = subprocess.run(
            [sys.executable, os.path.join(OWT, "cleanup_fix_dataset.py"),
             str(src), str(kept), str(filt),
             "--tasks", "remove_512", "ftfy_fix_text"],
            capture_output=True, text=True)
        assert r.returncode == 0, r.stderr
        assert len(kept.read_text().splitlines()) == 1
        assert len(filt.read_text().splitlines()) == 1


# ------------------------------------------------------- dedup end-to-end

class TestDedupE2E:
    def test_find_group_remove(self, tmp_path):
        corpus = tmp_path / "corpus.jsonl"
        doc = _en_doc(words=300)
        near = doc.replace("fox", "wolf")
        docs = [
            {"url": "http://a.org/1", "text": doc},
            {"url": "http://b.org/2", "text": near},
            {"url": "http://c.org/3", "text": "all about pallas kernels "
             "and mesh shardings on tpu pods with ring collectives " * 20},
        ]
        with open(corpus, "w") as f:
            for d in docs:
                f.write(json.dumps(d) + "\n")

        pairs = tmp_path / "pairs.jsonl"
        find_duplicates_main([
            "--inputs", str(corpus), "url",
            "--output", str(pairs),
            "--heuristic_iter", "-1",
        ])
        pair_lines = [l for l in open(pairs)]
        assert pair_lines, "near-duplicate pair not detected"
        flagged = set()
        for line in pair_lines:
            rec = json.loads(line)
            for main_id, dups in rec.items():
                flagged.add(main_id)
                for e in dups:
                    flagged.update(e)
        assert flagged == {"http://a.org/1", "http://b.org/2"}

        groups = group_pairs(pair_lines, threshold=0.7)
        assert len(groups) == 1 and len(groups[0]) == 2

        group_lines = [json.dumps({"0": groups[0]})]
        remove = ids_to_remove(group_lines)
        assert len(remove) == 1 and remove < flagged

        survivors = [d["url"] for d in docs if d["url"] not in remove]
        assert "http://c.org/3" in survivors and len(survivors) == 2

    def test_union_find_long_chain(self):
        # A chained pair file thousands of links deep must not hit the
        # recursion limit (boilerplate pages produce such chains).
        lines = [json.dumps({str(i + 1): [{str(i): 0.9}]})
                 for i in range(3000)]
        groups = group_pairs(lines, threshold=0.7)
        assert len(groups) == 1 and len(groups[0]) == 3001

    def test_parallel_modes_match_sequential(self, tmp_path):
        corpus = tmp_path / "c.jsonl"
        doc = _en_doc(words=300)
        with open(corpus, "w") as f:
            for i, text in enumerate([doc, doc.replace("fox", "wolf"),
                                      "pallas mesh kernels " * 60]):
                f.write(json.dumps({"url": f"u{i}", "text": text}) + "\n")

        def edges(path):
            out = set()
            for line in open(path):
                for m, dups in json.loads(line).items():
                    for e in dups:
                        out.add(frozenset([m, next(iter(e))]))
            return out

        seq, par = tmp_path / "seq.jsonl", tmp_path / "par.jsonl"
        find_duplicates_main(["--inputs", str(corpus), "url",
                              "--output", str(seq),
                              "--heuristic_iter", "-1"])
        find_duplicates_main(["--inputs", str(corpus), "url",
                              "--num_workers", "2",
                              "--output", str(par), "--jaccard_parallel",
                              "--heuristic_iter", "-1"])
        assert edges(seq) == edges(par) == {frozenset(["u0", "u1"])}

    def test_fingerprint_save_load_cross_process(self, tmp_path):
        # Save and load run in SEPARATE interpreters (different hash
        # randomization salts): catches any process-salted state in the
        # pickled LSH index, which in-process round trips can't see.
        script = os.path.join(OWT, "find_duplicates.py")
        corpus = tmp_path / "c.jsonl"
        with open(corpus, "w") as f:
            f.write(json.dumps({"url": "u1", "text": _en_doc()}) + "\n")
        fp = tmp_path / "fp.pkl"
        r = subprocess.run(
            [sys.executable, script, "--inputs", str(corpus), "url",
             "--save_fingerprints", str(fp)],
            capture_output=True, text=True, env={**os.environ,
                                                 "PYTHONHASHSEED": "11"})
        assert r.returncode == 0, r.stderr
        corpus2 = tmp_path / "c2.jsonl"
        with open(corpus2, "w") as f:
            f.write(json.dumps(
                {"url": "u2", "text": _en_doc().replace("fox", "cat")})
                + "\n")
        pairs = tmp_path / "p.jsonl"
        # Dedup the NEW shard against the saved fingerprints (recurrent
        # dedup: the reference's load_fingerprints workflow).
        r = subprocess.run(
            [sys.executable, script, "--load_fingerprints", str(fp),
             "--inputs", str(corpus2), "url", "--output", str(pairs),
             "--heuristic_iter", "-1"],
            capture_output=True, text=True, env={**os.environ,
                                                 "PYTHONHASHSEED": "22"})
        assert r.returncode == 0, r.stderr
        flagged = set()
        for line in open(pairs):
            rec = json.loads(line)
            for k, dups in rec.items():
                flagged.add(k)
                for e in dups:
                    flagged.update(e)
        assert flagged == {"u1", "u2"}


# ----------------------------------------------------------- filter_ngrams

class TestFilterNgrams:
    def test_scrub_hit_splits_doc(self):
        secret = ("alpha beta gamma delta epsilon zeta eta theta iota "
                  "kappa lam mu nu")  # 13 words
        ngrams = build_ngrams([secret], max_ngram_size=13)
        assert len(ngrams) == 1
        text = (_en_doc(words=150) + ". " + secret + " tail words here. "
                + _en_doc(words=150))
        pieces, matches = scrub_text(text, ngrams, 13,
                                     remove_char_each_side=10,
                                     filter_text_char_len=50)
        assert matches == 1
        assert len(pieces) >= 1
        for p in pieces:
            assert secret not in p.lower()

    def test_short_task_text_whole_seq(self):
        ngrams = build_ngrams(["tiny task answer"], max_ngram_size=13)
        assert "tiny task answer" in ngrams
        pieces, matches = scrub_text(
            _en_doc(words=120) + ". tiny task answer! " + _en_doc(words=120),
            ngrams, 13, remove_char_each_side=5, filter_text_char_len=20)
        assert matches == 1
        for p in pieces:
            assert "tiny task answer" not in p.lower()

    def test_clean_doc_untouched(self):
        ngrams = build_ngrams(["some unrelated evaluation text here that "
                               "never appears in the training data at all "
                               "okay good"], max_ngram_size=13)
        doc = _en_doc(words=200)
        pieces, matches = scrub_text(doc, ngrams, 13)
        assert matches == 0 and pieces == [doc]

    def test_final_hit_past_cap_still_drops(self):
        # The over-cap check must also fire when the LAST match leaves no
        # pending tail (cap check after the loop, not only at its top).
        secret = "one two three four five"
        ngrams = build_ngrams([secret], max_ngram_size=13)
        # 4 hits, max_splits=3; final piece ends exactly at the last hit
        # with nothing re-appended to pending.
        text = (". aa " + secret + " bb. ") * 4
        pieces, matches = scrub_text(text, ngrams, 13,
                                     remove_char_each_side=1,
                                     filter_text_char_len=3, max_splits=3)
        assert matches > 3
        assert pieces == []

    def test_shredded_doc_dropped(self):
        secret = "one two three four five"
        ngrams = build_ngrams([secret], max_ngram_size=13)
        text = (". " + secret + " filler. ") * 30
        pieces, matches = scrub_text(text, ngrams, 13,
                                     remove_char_each_side=1,
                                     filter_text_char_len=5, max_splits=10)
        assert pieces == [] and matches > 10


# ------------------------------------------------------------- CLI smoke

class TestCLIs:
    def test_blacklist_cli(self, tmp_path):
        urls = tmp_path / "urls.txt"
        urls.write_text("\n".join([
            "https://example.org/good-article",
            "https://www.youtube.com/watch?v=1",
            "http://x.org/file.zip",
            "bad",
            "https://example.org/good-article",
        ]) + "\n")
        out = tmp_path / "clean.txt"
        r = subprocess.run(
            [sys.executable, os.path.join(OWT, "blacklist_urls.py"),
             str(urls), str(out), "--quiet"],
            capture_output=True, text=True)
        assert r.returncode == 0, r.stderr
        assert out.read_text().split() == ["https://example.org/good-article"]

    def test_add_id_and_merge(self, tmp_path):
        a = tmp_path / "a.jsonl"
        a.write_text(json.dumps({"text": "x"}) + "\n")
        b = tmp_path / "b.jsonl"
        b.write_text(json.dumps({"text": "y"}) + "\n")
        merged = tmp_path / "m.jsonl"
        r = subprocess.run(
            [sys.executable, os.path.join(OWT, "merge_jsons.py"),
             "--json_path", str(tmp_path), "--output_file", str(merged)],
            capture_output=True, text=True)
        assert r.returncode == 0, r.stderr
        assert len(merged.read_text().splitlines()) == 2

        out = tmp_path / "ids.jsonl"
        r = subprocess.run(
            [sys.executable, os.path.join(OWT, "add_id.py"),
             "--input_file", str(merged), "--output_file", str(out),
             "--id_prefix", "owt"],
            capture_output=True, text=True)
        assert r.returncode == 0, r.stderr
        recs = [json.loads(l) for l in out.read_text().splitlines()]
        assert [r["adlr_id"] for r in recs] == ["owt-0000000001",
                                                "owt-0000000002"]
