"""Mini GLUE finetune end to end (VERDICT r3 #7): tasks/main.py --task
MNLI on a tiny separable corpus must (a) run the REAL
train_step/optimizer/scheduler path, (b) improve dev accuracy over
random init, (c) report per-split accuracy for two dev files, and
(d) dump per-sample predictions + a best/ checkpoint."""

import json
import os
import re
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORDS = ["yes", "no", "maybe", "dogs", "cats", "run", "sleep", "fast",
         "slow", "happy"]


def _write_vocab(path):
    toks = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"] + WORDS
    path.write_text("\n".join(toks) + "\n")


def _write_mnli_tsv(path, n, seed):
    """Separable toy MNLI: label fully determined by the first word of
    the hypothesis (yes->entailment, no->contradiction, maybe->neutral).
    11-column TSV, premise col 8, hypothesis col 9, label last."""
    import numpy as np

    rng = np.random.RandomState(seed)
    first = {"entailment": "yes", "contradiction": "no", "neutral": "maybe"}
    lines = ["\t".join(f"c{i}" for i in range(11))]
    for uid in range(n):
        label = ["contradiction", "entailment", "neutral"][uid % 3]
        filler = " ".join(rng.choice(WORDS[3:], 3))
        premise = f"dogs {filler}"
        hyp = f"{first[label]} {filler}"
        row = [str(uid)] + ["x"] * 7 + [premise, hyp, label]
        lines.append("\t".join(row))
    path.write_text("\n".join(lines) + "\n")


@pytest.fixture(scope="module")
def finetune_run(tmp_path_factory):
    tmp_path = tmp_path_factory.mktemp("glue")
    vocab = tmp_path / "vocab.txt"
    _write_vocab(vocab)
    train = tmp_path / "train.tsv"
    _write_mnli_tsv(train, 96, seed=0)
    dev_m = tmp_path / "dev_matched.tsv"
    _write_mnli_tsv(dev_m, 24, seed=1)
    dev_mm = tmp_path / "dev_mismatched.tsv"
    _write_mnli_tsv(dev_mm, 24, seed=2)
    save = tmp_path / "out"

    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tasks", "main.py"),
         "--task", "MNLI",
         "--train_data", str(train),
         "--valid_data", str(dev_m), str(dev_mm),
         "--tokenizer_type", "BertWordPieceLowerCase",
         "--vocab_file", str(vocab),
         "--num_layers", "2", "--hidden_size", "32",
         "--num_attention_heads", "4", "--ffn_hidden_size", "64",
         "--seq_length", "16", "--max_position_embeddings", "16",
         "--micro_batch_size", "8", "--lr", "5e-3",
         "--lr_warmup_fraction", "0.1",
         "--epochs", "6", "--log_interval", "10",
         "--save", str(save), "--save_interval", "1000",
         "--seed", "42"],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=900)
    return proc, save


def test_finetune_improves_dev_accuracy(finetune_run):
    proc, _ = finetune_run
    assert proc.returncode == 0, proc.stderr[-3000:]
    accs = [float(m) for m in re.findall(
        r"validation accuracy ([0-9.]+)%", proc.stdout)]
    assert accs, proc.stdout[-2000:]
    # 3-class random init ~33%; the toy task is linearly separable on
    # the first hypothesis token, so training must clearly beat chance
    assert max(accs) > 60.0, f"accuracies {accs}"


def test_per_split_accuracy_reported(finetune_run):
    proc, _ = finetune_run
    assert "metrics for dev_matched" in proc.stdout
    assert "metrics for dev_mismatched" in proc.stdout
    assert re.search(r">> \|epoch: \d+\| overall: correct / total",
                     proc.stdout)


def test_predictions_dumped_and_best_checkpoint(finetune_run):
    proc, save = finetune_run
    dumps = sorted(p for p in os.listdir(save)
                   if p.startswith("predictions_epoch"))
    assert dumps, os.listdir(save)
    with open(os.path.join(save, dumps[-1])) as f:
        preds = json.load(f)
    assert set(preds) == {"dev_matched", "dev_mismatched"}
    p = preds["dev_matched"]
    assert len(p["softmaxes"]) == 24 and len(p["labels"]) == 24
    assert len(p["softmaxes"][0]) == 3  # 3-class distribution
    assert abs(sum(p["softmaxes"][0]) - 1.0) < 1e-3
    assert len(set(p["ids"])) == 24  # uids, not positions
    # checkpoint-best exists and records an iteration
    best = os.path.join(save, "best")
    assert os.path.isdir(best), os.listdir(save)
    assert os.path.exists(
        os.path.join(best, "latest_checkpointed_iteration.txt"))
