"""Standalone tiny-model serving replica for router tests.

Spawned as a subprocess (one real engine process per replica, like a
production fleet):

    python tests/_serve_replica.py

Prints ``PORT <n>`` on stdout once the HTTP server is accepting, then
serves until killed.  Uses the same tiny llama + numeric fake tokenizer
as tests/test_serving_http.py, so prompts are space-separated ints and
greedy outputs are deterministic across replicas.

``--paged_kernel {auto,on,off}`` selects the paged-attention decode
path and ``--prefill_kernel {auto,on,off}`` the chunked-prefill path;
``on`` additionally flips the Pallas kernels into interpret mode so the
kernel-vs-XLA serve_bench A/Bs run end-to-end on CPU.
"""

import argparse
import os
import signal
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from megatron_llm_tpu.models.llama import LlamaModel, llama_config  # noqa: E402
from megatron_llm_tpu.serving import EngineConfig, InferenceEngine  # noqa: E402
from megatron_llm_tpu.text_generation_server import (  # noqa: E402
    MegatronServer, build_server_alerts)


class _FakeTokenizer:
    vocab_size = 64
    eod = 63
    pad = 0

    def tokenize(self, text):
        return [int(t) % 64 for t in text.split()]

    def detokenize(self, ids):
        return " ".join(str(i) for i in ids)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--paged_kernel", choices=["auto", "on", "off"],
                   default="auto")
    p.add_argument("--prefill_kernel", choices=["auto", "on", "off"],
                   default="auto")
    p.add_argument("--structured_log_dir", default=None,
                   help="stream request_done JSONL (trace-id e2e tests)")
    p.add_argument("--trace_dir", default=None,
                   help="write Chrome trace spans with trace ids")
    p.add_argument("--serve_fault_inject", default="",
                   help="chaos spec (e.g. 'nan@12,hang@20:5'); see "
                        "serving/resilience.py")
    p.add_argument("--serve_watchdog_secs", type=float, default=0.0,
                   help="engine watchdog timeout; 0 disables")
    p.add_argument("--serve_num_blocks", type=int, default=0,
                   help="KV pool pages; 0 = full per-slot backing")
    p.add_argument("--serve_host_cache_bytes", type=int, default=0,
                   help="host-RAM spill tier budget; 0 disables")
    p.add_argument("--serve_max_queue_depth", type=int, default=32,
                   help="admission queue bound (fleet-autoscale tests "
                        "raise it so a spike backlogs instead of 429s)")
    p.add_argument("--serve_deadline_secs", type=float, default=60.0,
                   help="default per-request deadline")
    p.add_argument("--serve_speculative", type=int, default=0,
                   help="1 = prompt-lookup speculative decoding "
                        "(fixed-shape K+1 verify step)")
    p.add_argument("--serve_draft_k", type=int, default=4,
                   help="max draft tokens per slot per verify step")
    p.add_argument("--serve_alerts", type=int, default=0,
                   help="1 = run the SLO sentinel (serving/alerts.py); "
                        "off by default so router tests stay quiet")
    p.add_argument("--alert_rules", default=None,
                   help="inline JSON or path overriding the built-in "
                        "alert rules (chaos tests use tight windows)")
    p.add_argument("--alert_webhook", default=None,
                   help="POST firing/resolved transitions to this URL")
    args = p.parse_args()
    if args.structured_log_dir:
        from megatron_llm_tpu import telemetry
        telemetry.install_stream(
            telemetry.TelemetryStream(args.structured_log_dir))
    if args.trace_dir:
        from megatron_llm_tpu import tracing
        bundle = tracing.Tracing(tracer=tracing.SpanTracer(),
                                 trace_dir=args.trace_dir)
        tracing.install_tracing(bundle)
        tracing.start_trace_flusher(bundle, interval_secs=0.5)
    if args.paged_kernel == "on" or args.prefill_kernel == "on":
        # no TPU in the test environment: run the Pallas kernels in
        # interpret mode so *_kernel_available() is true on CPU
        from megatron_llm_tpu.ops.pallas import paged_attention
        paged_attention._INTERPRET = True
    cfg = llama_config("tiny", num_layers=2, seq_length=64,
                       max_position_embeddings=64, padded_vocab_size=64,
                       use_flash_attn=False)
    model = LlamaModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    engine = InferenceEngine(model, params, EngineConfig(
        num_slots=4, block_size=8, prefill_chunk=16, max_model_len=64,
        num_blocks=args.serve_num_blocks,
        host_cache_bytes=args.serve_host_cache_bytes,
        max_queue_depth=args.serve_max_queue_depth,
        default_deadline_secs=args.serve_deadline_secs,
        paged_kernel=args.paged_kernel,
        prefill_kernel=args.prefill_kernel,
        speculative=bool(args.serve_speculative),
        draft_k=args.serve_draft_k,
        watchdog_secs=args.serve_watchdog_secs,
        fault_spec=args.serve_fault_inject,
        restart_backoff_secs=0.0))
    engine.warmup()
    engine.start()
    server = MegatronServer(model, params, _FakeTokenizer(),
                            engine=engine, max_prompts=4, max_tokens=32)
    if args.serve_alerts:
        build_server_alerts(server, engine=engine,
                            structured_log_dir=args.structured_log_dir,
                            alert_rules=args.alert_rules,
                            alert_webhook=args.alert_webhook)
    # run() lives on a worker thread here, so the server can't install
    # its own SIGTERM hook — wire the graceful drain from the main thread
    signal.signal(signal.SIGTERM, lambda *_: server.begin_drain("SIGTERM"))
    t = threading.Thread(target=server.run,
                         kwargs={"host": "127.0.0.1", "port": 0},
                         daemon=True)
    t.start()
    for _ in range(200):
        if getattr(server, "httpd", None) is not None:
            break
        time.sleep(0.05)
    assert server.httpd is not None
    # single buffered write + flush → one atomic os.write: the server
    # thread prints its banner concurrently, and print()'s separate
    # text/newline writes can interleave with it mid-line
    sys.stdout.write(f"PORT {server.httpd.server_address[1]}\n")
    sys.stdout.flush()
    t.join()


if __name__ == "__main__":
    sys.exit(main())
