"""Drive bench.py's TPU branch on CPU via BENCH_SIMULATE_TPU.

The real TPU branch gets one shot per tunnel window; these tests execute
the same code path (primary seq-4096-analog, flash-fallback guard,
secondary block, record schema, cache-persist guard) at a tiny shape so
a bug there is caught in CI, not on-chip.  Crucially: a simulated
record must NEVER be persisted as an on-chip measurement — round 5
caught exactly that overwrite in manual testing.
"""

import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_sim(tmp_path, extra_env):
    env = dict(os.environ, BENCH_SIMULATE_TPU="1", JAX_PLATFORMS="cpu",
               **extra_env)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    r = subprocess.run([sys.executable, os.path.join(ROOT, "bench.py")],
                       capture_output=True, text=True, timeout=900,
                       cwd=ROOT, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    line = [l for l in r.stdout.splitlines() if l.startswith("{")][-1]
    return json.loads(line)


def test_sim_flash_ok_runs_primary_and_secondary(tmp_path):
    cache = os.path.join(ROOT, ".bench_cache", "latest_tpu.json")
    before = open(cache).read() if os.path.exists(cache) else None
    # snapshot so a guard REGRESSION can't destroy the real on-chip
    # record (irreplaceable during a tunnel outage) — the assertion
    # below still catches the bug, the artifact survives it
    if before is not None:
        (tmp_path / "cache_snapshot.json").write_text(before)
    try:
        rec = _run_sim(tmp_path, {"BENCH_SIM_FLASH_OK": "1"})
    finally:
        after = open(cache).read() if os.path.exists(cache) else None
        if before is None and after is not None:
            # CI has no cache: a file APPEARING during the run is the
            # guard regression; remove the pollution, then fail below
            polluted = after
            os.unlink(cache)
        elif before is not None and after != before:
            polluted = after
            with open(cache, "w") as f:
                f.write(before)
        else:
            polluted = None
    assert rec["simulated"] is True
    assert rec["model"] == "llama-sim"
    # primary at the sim's "4096-analog", secondary block at half
    assert rec["seq_length"] == 256
    assert rec["seq2048"] is not None
    assert rec["seq2048"]["seq_length"] == 128
    # a real training loss, not an out-of-range-embedding NaN
    assert rec["loss"] == rec["loss"] and rec["loss"] < 7.0
    # the cache-persist guard: simulated records never reach the cache
    # (the finally above already restored the artifact if not)
    assert polluted is None, \
        f"simulated record polluted the TPU cache: {polluted[:200]}"
    _check_goodput_fields(rec)


def _check_goodput_fields(rec):
    """The BENCH json carries the tracing diagnostics: goodput share of
    the child's wall-clock, warmup compile seconds, and the recompile /
    straggler counts (both zero in a healthy fixed-shape run)."""
    assert 0.0 < rec["goodput_pct"] <= 100.0
    assert rec["compile_secs"] >= 0.0
    assert rec["recompiles"] == 0
    assert rec["straggler_events"] == 0
    # the layer-stats secondary ran (simulated TPU branch): overhead is a
    # measured number, not the None placeholder
    assert isinstance(rec["layer_stats_overhead_pct"], (int, float))


def test_sim_flash_fail_falls_back(tmp_path):
    rec = _run_sim(tmp_path, {})
    # no flash -> primary drops to the secondary seq and mb; no secondary
    assert rec["seq_length"] == 128
    assert rec["micro_batch"] == 4
    assert rec["seq2048"] is None
    assert rec["attention"] == "xla"
    assert rec["simulated"] is True
    _check_goodput_fields(rec)
