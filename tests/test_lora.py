"""LoRA finetuning (megatron_llm_tpu/lora.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megatron_llm_tpu.config import ParallelConfig, TrainConfig
from megatron_llm_tpu.lora import (
    LoraAdapter,
    attach_lora,
    init_lora,
    merge_lora,
)
from megatron_llm_tpu.models.llama import LlamaModel, llama_config
from megatron_llm_tpu.optimizer import MegatronOptimizer
from megatron_llm_tpu.training import build_train_step


@pytest.fixture(scope="module")
def model_and_params():
    cfg = llama_config("tiny", num_layers=2, seq_length=32,
                       max_position_embeddings=32, padded_vocab_size=64,
                       use_flash_attn=False)
    model = LlamaModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    return model, params


def _tok(b=2, s=16):
    return jnp.asarray(
        np.random.RandomState(0).randint(0, 64, (b, s)), jnp.int32)


def test_zero_init_is_identity(model_and_params):
    """B starts at zero: the adapted model IS the base model."""
    model, params = model_and_params
    lora = init_lora(model, params, rank=4, key=jax.random.PRNGKey(1))
    toks = _tok()
    base = model(params, toks, train=False)
    adapted = model(attach_lora(params, lora), toks, train=False)
    np.testing.assert_allclose(np.asarray(base), np.asarray(adapted),
                               atol=0, rtol=0)


def test_low_rank_path_matches_merged(model_and_params):
    """y = xW + (xA)B*s  ==  x(W + sAB): the forward's two-thin-matmul
    path agrees with the merged-kernel export."""
    model, params = model_and_params
    lora = init_lora(model, params, rank=4, key=jax.random.PRNGKey(1))
    # make B nonzero so the test means something
    lora = jax.tree_util.tree_map(
        lambda x: (jax.random.normal(jax.random.PRNGKey(2), x.shape,
                                     x.dtype) * 0.02
                   if x.ndim >= 2 else x), lora)
    toks = _tok()
    via_path = model(attach_lora(params, lora), toks, train=False)
    via_merge = model(merge_lora(params, lora), toks, train=False)
    np.testing.assert_allclose(np.asarray(via_path, np.float32),
                               np.asarray(via_merge, np.float32),
                               atol=5e-2)


def test_train_step_updates_only_adapters(model_and_params):
    """build_train_step over a LoraAdapter: loss falls, adapters move,
    the frozen base never changes, and the Adam state is adapter-sized."""
    model, params = model_and_params
    adapter = LoraAdapter(model, params)
    lora = adapter.init_lora(
        8, jax.random.PRNGKey(1),
        targets=("query_key_value", "dense",
                 "dense_h_to_4h", "dense_4h_to_h"))
    n_lora = adapter.num_params(lora)
    n_base = sum(int(x.size) for x in jax.tree_util.tree_leaves(params))
    assert n_lora < 0.35 * n_base  # tiny model; real ratios are ~1%

    tc = TrainConfig(micro_batch_size=2, global_batch_size=2,
                     train_iters=0, lr=0.0, optimizer="adam",
                     clip_grad=1.0)
    opt = MegatronOptimizer(tc)
    opt_state = opt.init(lora)
    assert sum(int(x.size) for x in
               jax.tree_util.tree_leaves(opt_state.exp_avg)) == n_lora
    step = build_train_step(adapter, opt, ParallelConfig(), 1)

    toks = _tok()[None]  # [num_micro, b, s]
    batch = {"tokens": toks, "labels": jnp.roll(toks, -1, -1),
             "loss_mask": jnp.ones_like(toks, jnp.float32)}
    base_before = jax.tree_util.tree_map(lambda x: np.asarray(x).copy(),
                                         params)
    key = jax.random.PRNGKey(2)
    losses = []
    for _ in range(40):
        lora, opt_state, m = step(lora, opt_state, batch, key, 5e-2, 0.0)
        losses.append(float(m["lm loss"]))
    # learning through a FROZEN RANDOM base is capacity-bound (the LM
    # head never trains), so expect a real drop, not memorization.
    # Per-step losses oscillate several percent and the whole trajectory
    # shifts with XLA CPU thread count (measured last/first window
    # ratios 0.79-0.92 across boxes), so compare window means with a
    # tolerant factor rather than the last-vs-first samples.
    assert (sum(losses[-8:]) / 8) < 0.95 * (sum(losses[:8]) / 8), losses
    # base params are untouched (closure constants)
    for a, b in zip(jax.tree_util.tree_leaves(base_before),
                    jax.tree_util.tree_leaves(adapter.base_params)):
        np.testing.assert_array_equal(a, np.asarray(b))


def test_tp2_parity(model_and_params, utils):
    """LoRA forward under tp=2 (sharded base + sharded adapters via
    lora_param_specs) matches unsharded."""
    from megatron_llm_tpu.parallel import sharding as sh
    model, params = model_and_params
    adapter = LoraAdapter(model, params)
    lora = adapter.init_lora(4, jax.random.PRNGKey(1))
    lora = jax.tree_util.tree_map(
        lambda x: (jax.random.normal(jax.random.PRNGKey(3), x.shape,
                                     x.dtype) * 0.02
                   if x.ndim >= 2 else x), lora)
    toks = _tok(b=4)  # divisible by dp=4 on the tp=2 8-device mesh
    want = model(attach_lora(params, lora), toks, train=False)
    utils.initialize_model_parallel(tp=2)
    try:
        p_sh = sh.shard_params(params, model.param_specs(params))
        l_sh = sh.shard_params(lora, adapter.param_specs(lora))
        got = model(attach_lora(p_sh, l_sh), toks, train=False)
    finally:
        utils.destroy_model_parallel()
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32), atol=3e-2)
