"""Worker for tests/test_multihost_cpu.py multi-slice scenarios — one of
two REAL processes (jax.distributed over localhost gloo, one CPU device
each), with the ``slice`` mesh axis spanning the process boundary: each
process IS one slice, so the second reduction hop in the hierarchical
all-reduce crosses a genuine process (DCN-analogue) link.

Modes (MULTISLICE_MODE env):
  step     — hierarchical vs flat all-reduce checksum + train-step loss
             parity across the slice boundary (default)
  preempt  — run pretrain under DistributedSignalHandler; the parent
             SIGTERMs ONE process mid-run and both must reach boundary
             consensus, make the rescue save, and exit PREEMPT_EXIT_CODE.

Not collected by pytest (underscore prefix)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _setup(M=2):
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from megatron_llm_tpu import topology
    from megatron_llm_tpu.data.data_samplers import place_host_batch
    from megatron_llm_tpu.models.llama import LlamaModel, llama_config
    from megatron_llm_tpu.parallel import sharding as sh

    topology.initialize_distributed()
    rank = jax.process_index()
    assert jax.process_count() == 2

    mesh = topology.initialize_model_parallel(num_slices=2)
    assert dict(mesh.shape)["slice"] == 2 and dict(mesh.shape)["dp"] == 1
    assert topology.slice_id() == rank, (topology.slice_id(), rank)
    assert topology.data_axes() == ("slice", "dp")

    cfg = llama_config("tiny", num_layers=2, seq_length=32,
                       max_position_embeddings=32, padded_vocab_size=128)
    model = LlamaModel(cfg)
    params = model.init(jax.random.PRNGKey(0))     # same seed -> identical
    params = sh.shard_params(params, model.param_specs(params))

    # every process builds the SAME global batch; leading data dim
    # spans ('slice', 'dp') so each process holds its slice's half
    gb = 2
    rng = np.random.RandomState(0)
    toks = rng.randint(0, 128, (M, gb, 32)).astype(np.int32)
    dsh = NamedSharding(mesh, P(None, ("slice", "dp"), None))
    batch = {
        "tokens": place_host_batch(toks, dsh),
        "labels": place_host_batch(np.roll(toks, -1, axis=-1), dsh),
        "loss_mask": place_host_batch(np.ones_like(toks, np.float32), dsh),
    }
    return rank, mesh, model, params, batch, M


def mode_step():
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from megatron_llm_tpu import multislice, topology
    from megatron_llm_tpu.config import ParallelConfig, TrainConfig
    from megatron_llm_tpu.optimizer import MegatronOptimizer
    from megatron_llm_tpu.training import build_train_step

    rank, mesh, model, params, batch, M = _setup()

    # staged ICI-then-DCN reduction vs one flat psum: the second hop
    # crosses the process boundary; integer values make both exact
    x = np.arange(2 * 3, dtype=np.float32).reshape(2, 3)
    xs = jax.device_put(x, NamedSharding(mesh, P(("slice", "dp"))))
    hier = np.asarray(multislice.hierarchical_allreduce(xs))
    flat = np.asarray(multislice.flat_allreduce(xs))
    np.testing.assert_array_equal(hier, flat)
    np.testing.assert_array_equal(hier, x.sum(0))
    print(f"RANK{rank} HIERARCHICAL_ALLREDUCE_OK {hier.tolist()}",
          flush=True)

    from megatron_llm_tpu.parallel import sharding as sh

    tc = TrainConfig(micro_batch_size=1, global_batch_size=2, lr=1e-3)
    opt = MegatronOptimizer(tc)
    losses = {}
    for name, hier_fwd in (("hier", True), ("flat", False)):
        pc = ParallelConfig(data_parallel_size=1, num_slices=2,
                            multislice_hierarchical=hier_fwd)
        # fresh params each path: the train step donates its inputs
        p = model.init(jax.random.PRNGKey(0))
        p = sh.shard_params(p, model.param_specs(p))
        opt_state = opt.init(p)
        step = build_train_step(model, opt, pc, M)
        _, _, metrics = step(p, opt_state, batch,
                             jax.random.PRNGKey(0), 1e-3, 0.0)
        losses[name] = float(metrics["lm loss"])
        assert np.isfinite(losses[name])
    print(f"RANK{rank} LOSS {losses['hier']:.6f}", flush=True)
    assert abs(losses["hier"] - losses["flat"]) < 1e-6, losses
    print(f"RANK{rank} HIER_FLAT_PARITY_OK", flush=True)


def mode_preempt():
    import jax

    from megatron_llm_tpu import multislice
    from megatron_llm_tpu.config import ParallelConfig, TrainConfig
    from megatron_llm_tpu.dist_signal_handler import DistributedSignalHandler
    from megatron_llm_tpu.training import pretrain

    # pretrain derives num_micro = gbs / (mbs * dp * slices) = 1
    rank, mesh, model, params, batch, M = _setup(M=1)
    save_dir = os.environ["MULTISLICE_SAVE_DIR"]

    def it():
        while True:
            yield batch

    tc = TrainConfig(micro_batch_size=1, global_batch_size=2, lr=1e-3,
                     train_iters=5000)
    pc = ParallelConfig(data_parallel_size=1, num_slices=2,
                        multislice_hierarchical=True)

    def on_metrics(i, m):
        # the parent watches for these to know when to deliver SIGTERM
        print(f"RANK{rank} STEP {i}", flush=True)

    with DistributedSignalHandler() as handler:
        # log_interval=1: every iteration is a consensus boundary, so the
        # rescue triggers promptly after the signal lands on one slice
        pretrain(model, params, tc, pc, it(), log_interval=1,
                 save_dir=save_dir, exit_signal_handler=handler,
                 on_metrics=on_metrics,
                 preempt_exit_code=multislice.PREEMPT_EXIT_CODE)
    # unreachable on the preemption path (pretrain sys.exits 17); reaching
    # here means the signal never arrived
    print(f"RANK{rank} NO_PREEMPTION", flush=True)
    sys.exit(3)


if __name__ == "__main__":
    if os.environ.get("MULTISLICE_MODE", "step") == "preempt":
        mode_preempt()
    else:
        mode_step()
