"""Checkpoint restore with an abstract template: orbax must place leaves
directly onto the *current* mesh's shardings, even when the checkpoint was
saved under a different topology (kills the 'unsafe when restoring on a
different topology' path — VERDICT r2 #5)."""

import warnings

import jax
import numpy as np

from megatron_llm_tpu import checkpointing
from megatron_llm_tpu.models.llama import LlamaModel, llama_config
from megatron_llm_tpu.parallel import sharding as sh


def test_restore_on_different_mesh(utils, tmp_path):
    cfg = llama_config("tiny", seq_length=16, max_position_embeddings=16,
                       padded_vocab_size=64)
    model = LlamaModel(cfg)
    params = model.init(jax.random.PRNGKey(0))

    # save under a tp=2 topology
    utils.initialize_model_parallel(tp=2)
    params = sh.shard_params(params, model.param_specs(params))
    checkpointing.save_checkpoint(str(tmp_path), 5, params)
    baseline = [np.asarray(l) for l in jax.tree_util.tree_leaves(params)]

    # restore under tp=4 with an abstract template carrying the new mesh's
    # shardings
    utils.initialize_model_parallel(tp=4)
    shardings = sh.make_shardings(model.param_specs(params))
    template = jax.tree_util.tree_map(
        lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
        params, shardings)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        loaded, _, meta = checkpointing.load_checkpoint(
            str(tmp_path), params_template=template)
    assert meta["iteration"] == 5
    topo = [w for w in caught if "topology" in str(w.message)]
    assert not topo, f"orbax topology warning still fired: {topo[0].message}"

    for got, want_sharding, want_val in zip(
            jax.tree_util.tree_leaves(loaded),
            jax.tree_util.tree_leaves(shardings), baseline):
        assert got.sharding.is_equivalent_to(want_sharding, got.ndim), (
            f"restored {got.sharding} != requested {want_sharding}")
        np.testing.assert_array_equal(np.asarray(got), want_val)
