"""Checkpoint restore with an abstract template: orbax must place leaves
directly onto the *current* mesh's shardings, even when the checkpoint was
saved under a different topology (kills the 'unsafe when restoring on a
different topology' path — VERDICT r2 #5)."""

import warnings

import jax
import numpy as np

from megatron_llm_tpu import checkpointing
from megatron_llm_tpu.models.llama import LlamaModel, llama_config
from megatron_llm_tpu.parallel import sharding as sh


def test_restore_on_different_mesh(utils, tmp_path):
    cfg = llama_config("tiny", seq_length=16, max_position_embeddings=16,
                       padded_vocab_size=64)
    model = LlamaModel(cfg)
    params = model.init(jax.random.PRNGKey(0))

    # save under a tp=2 topology
    utils.initialize_model_parallel(tp=2)
    params = sh.shard_params(params, model.param_specs(params))
    checkpointing.save_checkpoint(str(tmp_path), 5, params)
    baseline = [np.asarray(l) for l in jax.tree_util.tree_leaves(params)]

    # restore under tp=4 with an abstract template carrying the new mesh's
    # shardings
    utils.initialize_model_parallel(tp=4)
    shardings = sh.make_shardings(model.param_specs(params))
    template = jax.tree_util.tree_map(
        lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
        params, shardings)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        loaded, _, meta = checkpointing.load_checkpoint(
            str(tmp_path), params_template=template)
    assert meta["iteration"] == 5
    topo = [w for w in caught if "topology" in str(w.message)]
    assert not topo, f"orbax topology warning still fired: {topo[0].message}"

    for got, want_sharding, want_val in zip(
            jax.tree_util.tree_leaves(loaded),
            jax.tree_util.tree_leaves(shardings), baseline):
        assert got.sharding.is_equivalent_to(want_sharding, got.ndim), (
            f"restored {got.sharding} != requested {want_sharding}")
        np.testing.assert_array_equal(np.asarray(got), want_val)


def test_async_save_tracker_deferred_until_finalize(tmp_path):
    """async_save: tensorstore writes go to the background; the tracker
    file appears ONLY at finalize (crash mid-save can never point the
    tracker at an incomplete checkpoint), and the loaded tree matches."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from megatron_llm_tpu import checkpointing as ck

    params = {"w": jnp.arange(8.0), "b": jnp.ones((3, 4))}
    d = str(tmp_path / "async_ck")
    ck.save_checkpoint(d, 5, params, async_save=True)
    tracker = ck.get_checkpoint_tracker_filename(d)
    import os

    assert not os.path.exists(tracker), \
        "tracker must not exist before finalize"
    ck.finalize_async_saves()
    assert os.path.exists(tracker)
    loaded, _, meta = ck.load_checkpoint(d)
    np.testing.assert_array_equal(np.asarray(loaded["w"]),
                                  np.asarray(params["w"]))
    assert int(meta["iteration"]) == 5

    # a second async save finalizes the first automatically
    params2 = {"w": jnp.arange(8.0) * 2, "b": jnp.zeros((3, 4))}
    ck.save_checkpoint(d, 6, params2, async_save=True)
    ck.save_checkpoint(d, 7, params2, async_save=True)
    with open(tracker) as f:
        assert f.read().strip() == "6"   # first save finalized by second
    ck.finalize_async_saves()
    with open(tracker) as f:
        assert f.read().strip() == "7"
