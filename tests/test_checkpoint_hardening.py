"""Hardened checkpoint IO: atomic publish, retry-with-backoff, manifest
validation, corrupt-tracker / corrupt-checkpoint fallback, keep-last-N GC."""

import json
import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from megatron_llm_tpu import checkpointing, global_vars
from megatron_llm_tpu.resilience import set_save_fault_hook


@pytest.fixture(autouse=True)
def _clean_save_state():
    global_vars.reset_counters()
    checkpointing.configure_save(total_limit=0, retries=2,
                                 retry_backoff=0.01)
    yield
    set_save_fault_hook(None)
    global_vars.reset_counters()
    checkpointing.configure_save(total_limit=0, retries=2,
                                 retry_backoff=0.25)


def _params(seed=0, scale=1.0):
    rng = np.random.RandomState(seed)
    return {
        "w": jnp.asarray(rng.randn(4, 4).astype(np.float32) * scale),
        "b": jnp.asarray(rng.randn(4).astype(np.float32) * scale),
    }


def _tracker(d):
    return checkpointing.get_checkpoint_tracker_filename(str(d))


# ---------------------------------------------------------------------------
# Atomic publish + manifest
# ---------------------------------------------------------------------------

def test_save_is_atomic_and_validates(tmp_path):
    checkpointing.save_checkpoint(str(tmp_path), 7, _params())
    assert (tmp_path / "iter_0000007").is_dir()
    assert not list(tmp_path.glob("*.tmp"))
    ok, reason = checkpointing.validate_checkpoint_dir(
        tmp_path / "iter_0000007")
    assert ok, reason
    pl, _, meta = checkpointing.load_checkpoint(str(tmp_path))
    assert meta["iteration"] == 7
    np.testing.assert_array_equal(np.asarray(pl["w"]),
                                  np.asarray(_params()["w"]))


def test_manifest_checksum_detects_tampering(tmp_path):
    checkpointing.save_checkpoint(str(tmp_path), 1, _params())
    meta_path = tmp_path / "iter_0000001" / "meta.json"
    meta = json.loads(meta_path.read_text())
    meta["manifest"]["model"]["['w']"]["shape"] = [9, 9]
    meta_path.write_text(json.dumps(meta))
    ok, reason = checkpointing.validate_checkpoint_dir(
        tmp_path / "iter_0000001")
    assert not ok and "checksum" in reason


def test_load_rejects_shape_mismatch(tmp_path):
    """A manifest that passes its checksum but disagrees with the restored
    tensors (bit rot, wrong-file copy) fails loudly instead of training on
    garbage."""
    checkpointing.save_checkpoint(str(tmp_path), 1, _params())
    meta_path = tmp_path / "iter_0000001" / "meta.json"
    meta = json.loads(meta_path.read_text())
    meta["manifest"]["model"]["['w']"]["shape"] = [9, 9]
    meta["manifest_sha256"] = checkpointing._manifest_sha256(
        meta["manifest"])
    meta_path.write_text(json.dumps(meta))
    with pytest.raises(ValueError, match="mismatches its manifest"):
        checkpointing.load_checkpoint(str(tmp_path))


# ---------------------------------------------------------------------------
# Retry
# ---------------------------------------------------------------------------

def test_save_retries_transient_ioerror(tmp_path):
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] <= 2:
            raise IOError("transient")

    set_save_fault_hook(flaky)
    checkpointing.configure_save(retries=3, retry_backoff=0.01)
    checkpointing.save_checkpoint(str(tmp_path), 4, _params())
    assert global_vars.get_counters()["save_retries"] == 2
    assert (tmp_path / "iter_0000004").is_dir()
    ok, reason = checkpointing.validate_checkpoint_dir(
        tmp_path / "iter_0000004")
    assert ok, reason


def test_save_raises_after_retry_exhaustion(tmp_path):
    def always_fail():
        raise IOError("storage is gone")

    set_save_fault_hook(always_fail)
    checkpointing.configure_save(retries=1, retry_backoff=0.01)
    with pytest.raises(IOError):
        checkpointing.save_checkpoint(str(tmp_path), 4, _params())
    assert global_vars.get_counters()["save_retries"] == 1
    # nothing published: no final dir, no tracker
    assert not (tmp_path / "iter_0000004").exists()
    assert not os.path.exists(_tracker(tmp_path))


# ---------------------------------------------------------------------------
# Corruption fallback
# ---------------------------------------------------------------------------

def test_corrupt_tracker_returns_absent():
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        it, release = checkpointing.read_tracker(d)       # no tracker
        assert it is None and not release
        with open(_tracker(d), "w") as f:
            f.write("")                                   # killed mid-write
        assert checkpointing.read_tracker(d) == (None, False)
        with open(_tracker(d), "w") as f:
            f.write("garbage\n")
        assert checkpointing.read_tracker(d) == (None, False)
        with open(_tracker(d), "w") as f:
            f.write(" 12 \n")
        assert checkpointing.read_tracker(d) == (12, False)
        with open(_tracker(d), "w") as f:
            f.write("release")
        assert checkpointing.read_tracker(d) == (None, True)


def test_corrupt_tracker_falls_back_to_newest_valid(tmp_path):
    checkpointing.save_checkpoint(str(tmp_path), 1, _params(1))
    checkpointing.save_checkpoint(str(tmp_path), 2, _params(2))
    with open(_tracker(tmp_path), "w") as f:
        f.write("not-a-number")
    pl, _, meta = checkpointing.load_checkpoint(str(tmp_path))
    assert meta["iteration"] == 2
    np.testing.assert_array_equal(np.asarray(pl["w"]),
                                  np.asarray(_params(2)["w"]))


def test_corrupt_latest_falls_back_to_previous(tmp_path):
    checkpointing.save_checkpoint(str(tmp_path), 1, _params(1))
    checkpointing.save_checkpoint(str(tmp_path), 2, _params(2))
    # iter 2's payload rots away; the tracker still points at it
    (tmp_path / "iter_0000002" / "meta.json").write_text("{ truncated")
    pl, _, meta = checkpointing.load_checkpoint(str(tmp_path))
    assert meta["iteration"] == 1
    np.testing.assert_array_equal(np.asarray(pl["w"]),
                                  np.asarray(_params(1)["w"]))


def test_no_valid_checkpoint_returns_none(tmp_path):
    with open(_tracker(tmp_path), "w") as f:
        f.write("5")                    # dangling tracker, no payload
    assert checkpointing.load_checkpoint(str(tmp_path)) == (None, None, None)


def test_explicit_iteration_never_substituted(tmp_path):
    checkpointing.save_checkpoint(str(tmp_path), 1, _params(1))
    checkpointing.save_checkpoint(str(tmp_path), 2, _params(2))
    (tmp_path / "iter_0000002" / "meta.json").unlink()
    # implicit load falls back; an explicit request must not
    _, _, meta = checkpointing.load_checkpoint(str(tmp_path))
    assert meta["iteration"] == 1
    with pytest.raises(FileNotFoundError):
        checkpointing.load_checkpoint(str(tmp_path), iteration=2)


# ---------------------------------------------------------------------------
# GC
# ---------------------------------------------------------------------------

def test_save_total_limit_keeps_last_n(tmp_path):
    checkpointing.configure_save(total_limit=2)
    for i in range(1, 5):
        checkpointing.save_checkpoint(str(tmp_path), i, _params(i))
    kept = sorted(p.name for p in tmp_path.glob("iter_*"))
    assert kept == ["iter_0000003", "iter_0000004"]
    _, _, meta = checkpointing.load_checkpoint(str(tmp_path))
    assert meta["iteration"] == 4


def test_total_limit_zero_keeps_everything(tmp_path):
    checkpointing.configure_save(total_limit=0)
    for i in range(1, 4):
        checkpointing.save_checkpoint(str(tmp_path), i, _params(i))
    assert len(list(tmp_path.glob("iter_*"))) == 3
