"""Inference tests: KV-cache decode == full forward, greedy generation,
ragged prompts, sampling filters, beam search, REST server contract."""

import json
import threading
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megatron_llm_tpu.models.llama import LlamaModel, llama_config
from megatron_llm_tpu.text_generation.generation import (
    beam_search,
    generate_tokens,
    greedy_generate,
    init_kv_caches,
    _forward_with_cache,
)
from megatron_llm_tpu.text_generation.sampling import modify_logits, sample


@pytest.fixture(scope="module")
def model_and_params():
    cfg = llama_config("tiny", num_layers=2, seq_length=64,
                       max_position_embeddings=64, padded_vocab_size=64,
                       use_flash_attn=False)
    model = LlamaModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    return model, params


def test_kv_cache_matches_full_forward(model_and_params):
    """Incremental decode logits == one-shot causal forward logits
    (the core inference-correctness property; reference verifies this
    implicitly through generation quality)."""
    model, params = model_and_params
    rng = np.random.RandomState(0)
    toks = jnp.asarray(rng.randint(0, 64, (2, 10)))

    full_logits = model(params, toks, train=False)

    caches = init_kv_caches(model.cfg, 2, 16)
    # prefill 4, then 6 single-token steps
    logits_p, caches = _forward_with_cache(model, params, toks[:, :4],
                                           caches, 0)
    parts = [logits_p]
    for t in range(4, 10):
        lg, caches = _forward_with_cache(model, params, toks[:, t:t + 1],
                                         caches, t)
        parts.append(lg)
    inc_logits = jnp.concatenate(parts, axis=1)
    np.testing.assert_allclose(np.asarray(inc_logits),
                               np.asarray(full_logits), atol=2e-4)


def test_greedy_generation_deterministic(model_and_params):
    model, params = model_and_params
    toks = jnp.asarray([[1, 2, 3, 4]])
    lens = jnp.asarray([4])
    out1, _, _ = greedy_generate(model, params, toks, lens, 8)
    out2, _, _ = greedy_generate(model, params, toks, lens, 8)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
    assert out1.shape == (1, 12)
    np.testing.assert_array_equal(np.asarray(out1)[0, :4], [1, 2, 3, 4])


def test_ragged_prompts_keep_prompt_tokens(model_and_params):
    """Rows with longer prompts must keep their prompt tokens while shorter
    rows are already generating (reference: generation.py:160+)."""
    model, params = model_and_params
    toks = jnp.asarray([[1, 2, 0, 0], [5, 6, 7, 8]])
    lens = jnp.asarray([2, 4])
    out, _, _ = greedy_generate(model, params, toks, lens, 4)
    np.testing.assert_array_equal(np.asarray(out)[1, :4], [5, 6, 7, 8])
    np.testing.assert_array_equal(np.asarray(out)[0, :2], [1, 2])


def test_top_k_filter():
    logits = jnp.asarray([[1.0, 5.0, 3.0, 2.0]])
    out = modify_logits(logits, top_k=2)
    assert out[0, 1] == 5.0 and out[0, 2] == 3.0
    assert out[0, 0] < -1e9 and out[0, 3] < -1e9


def test_top_p_filter():
    logits = jnp.log(jnp.asarray([[0.5, 0.3, 0.15, 0.05]]))
    out = modify_logits(logits, top_p=0.7)
    # 0.5 + 0.3 >= 0.7 -> keep first two only
    assert np.isfinite(out[0, 0]) and out[0, 1] > -1e9
    assert out[0, 2] < -1e9 and out[0, 3] < -1e9


def test_sample_greedy_matches_argmax():
    logits = jnp.asarray([[0.1, 2.0, -1.0]])
    assert int(sample(logits, jax.random.PRNGKey(0), greedy=True)[0]) == 1


def test_beam_search_returns_sorted(model_and_params):
    model, params = model_and_params
    toks = jnp.asarray([[1, 2, 3]])
    beams, scores = beam_search(model, params, toks, beam_size=3,
                                max_new_tokens=5, eod_id=63)
    assert beams.shape[0] == 3
    s = np.asarray(scores)
    assert np.all(s[:-1] >= s[1:])  # descending


class _FakeTokenizer:
    vocab_size = 64
    eod = 63
    pad = 0

    def tokenize(self, text):
        return [int(t) % 64 for t in text.split()]

    def detokenize(self, ids):
        return " ".join(str(i) for i in ids)


def test_server_contract(model_and_params):
    from megatron_llm_tpu.text_generation_server import MegatronServer

    model, params = model_and_params
    server = MegatronServer(model, params, _FakeTokenizer())
    import http.server

    httpd_holder = {}

    def run():
        # bind to an ephemeral port
        gen = server.generator

        class H(http.server.BaseHTTPRequestHandler):
            def do_PUT(self):
                n = int(self.headers.get("Content-Length", 0))
                code, body = gen.handle(json.loads(self.rfile.read(n)))
                data = json.dumps(body).encode()
                self.send_response(code)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def log_message(self, *a):
                pass

        httpd = http.server.HTTPServer(("127.0.0.1", 0), H)
        httpd_holder["port"] = httpd.server_address[1]
        httpd_holder["srv"] = httpd
        httpd.serve_forever()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    import time

    for _ in range(100):
        if "port" in httpd_holder:
            break
        time.sleep(0.05)
    port = httpd_holder["port"]

    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/api",
        data=json.dumps({"prompts": ["1 2 3"],
                         "tokens_to_generate": 4}).encode(),
        method="PUT",
    )
    with urllib.request.urlopen(req) as resp:
        out = json.loads(resp.read())
    assert "text" in out and len(out["text"]) == 1

    # validation error path
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/api",
        data=json.dumps({"prompts": [], "tokens_to_generate": 4}).encode(),
        method="PUT",
    )
    try:
        urllib.request.urlopen(req)
        assert False, "expected 400"
    except urllib.error.HTTPError as e:
        assert e.code == 400
    httpd_holder["srv"].shutdown()


def test_server_demo_page_and_real_handler(model_and_params):
    """The REAL MegatronServer.run handler (not a test stub): GET /
    serves the demo page (reference serves megatron/static/index.html),
    PUT /api generates, unknown paths 404."""
    from megatron_llm_tpu.text_generation_server import MegatronServer

    model, params = model_and_params
    server = MegatronServer(model, params, _FakeTokenizer())
    t = threading.Thread(
        target=server.run, kwargs={"host": "127.0.0.1", "port": 0},
        daemon=True)
    t.start()
    import time

    for _ in range(100):
        if getattr(server, "httpd", None) is not None:
            break
        time.sleep(0.05)
    assert getattr(server, "httpd", None) is not None, \
        "server.run() never bound (thread died during startup?)"
    port = server.httpd.server_address[1]

    try:
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/") as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"].startswith("text/html")
            page = resp.read().decode()
        assert "playground" in page and '"api"' in page

        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/api",
            data=json.dumps({"prompts": ["1 2 3"],
                             "tokens_to_generate": 4}).encode(),
            method="PUT")
        with urllib.request.urlopen(req) as resp:
            out = json.loads(resp.read())
        assert "text" in out and len(out["text"]) == 1

        # a null knob (cleared UI field) must be a 400, not a dead socket
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/api",
            data=json.dumps({"prompts": ["1 2 3"], "top_k": None}).encode(),
            method="PUT")
        try:
            urllib.request.urlopen(req)
            assert False, "expected 400"
        except urllib.error.HTTPError as e:
            assert e.code == 400

        try:
            urllib.request.urlopen(f"http://127.0.0.1:{port}/nope")
            assert False, "expected 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        server.httpd.shutdown()


def test_extra_stop_ids_and_pairs(model_and_params):
    """stop_on_eol/double-eol semantics: a row stops at an extra stop id
    or a (prev, cur) bigram exactly like eod."""
    model, params = model_and_params
    toks = jnp.asarray([[1, 2, 3, 4]])
    lens = jnp.asarray([4])
    base, n_base, _ = generate_tokens(
        model, params, toks, lens, jax.random.PRNGKey(0),
        max_new_tokens=8, min_prompt_len=4, greedy=True)
    base_row = np.asarray(base)[0]
    first_gen = int(base_row[4])
    assert first_gen != 0

    # stopping on the first generated token: generation freezes there
    out, n_stop, _ = generate_tokens(
        model, params, toks, lens, jax.random.PRNGKey(0),
        max_new_tokens=8, min_prompt_len=4, greedy=True,
        extra_stop_ids=(first_gen,))
    row = np.asarray(out)[0]
    assert int(row[4]) == first_gen
    # generation stopped right after the stop token: the rest of the row
    # is never written (stays at the zero initialization)
    assert int(n_stop) == 5 and all(int(t) == 0 for t in row[5:])

    # bigram stop: (prompt-last, first-gen) matches immediately
    out2, n2, _ = generate_tokens(
        model, params, toks, lens, jax.random.PRNGKey(0),
        max_new_tokens=8, min_prompt_len=4, greedy=True,
        stop_pairs=((4, first_gen),))
    row2 = np.asarray(out2)[0]
    assert int(n2) == 5 and all(int(t) == 0 for t in row2[5:])


def test_ban_pairs_changes_sampling(model_and_params):
    """prevent_newline_after_colon semantics: the banned token can never
    follow the trigger token."""
    model, params = model_and_params
    toks = jnp.asarray([[1, 2, 3, 4]])
    lens = jnp.asarray([4])
    base, _, _ = generate_tokens(
        model, params, toks, lens, jax.random.PRNGKey(0),
        max_new_tokens=8, min_prompt_len=4, greedy=True)
    row = np.asarray(base)[0]
    first_gen = int(row[4])
    # ban exactly what greedy would pick after the prompt's last token
    out, _, _ = generate_tokens(
        model, params, toks, lens, jax.random.PRNGKey(0),
        max_new_tokens=8, min_prompt_len=4, greedy=True,
        ban_pairs=((4, first_gen),))
    assert int(np.asarray(out)[0][4]) != first_gen


def test_top_p_decay_runs_and_bounds():
    """Dynamic (traced) top_p filter: decayed top_p must floor at bound
    and still produce valid samples."""
    from megatron_llm_tpu.text_generation.sampling import modify_logits

    logits = jnp.asarray(np.random.RandomState(0).randn(2, 16), jnp.float32)
    # tiny traced top_p keeps exactly the top-1 token per row
    out = jax.jit(lambda l, p: modify_logits(l, top_p=p))(
        logits, jnp.float32(1e-6))
    kept = (np.asarray(out) > -1e9).sum(axis=-1)
    np.testing.assert_array_equal(kept, [1, 1])
    # a permissive traced top_p (0.9) keeps more than greedy but not all
    out9 = jax.jit(lambda l, p: modify_logits(l, top_p=p))(
        logits, jnp.float32(0.9))
    kept9 = (np.asarray(out9) > -1e9).sum(axis=-1)
    assert (kept9 >= 1).all() and (kept9 < 16).all()
    # inactive traced top_p (0.0) leaves logits unchanged
    out0 = jax.jit(lambda l, p: modify_logits(l, top_p=p))(
        logits, jnp.float32(0.0))
    np.testing.assert_allclose(np.asarray(out0), np.asarray(logits))


def test_top_p_decay_through_decode(model_and_params):
    """top_p_decay/bound wired through the while-loop body: the decode
    must run, produce valid ids, and differ structurally from no-decay
    only in sampling (shapes/lengths identical)."""
    model, params = model_and_params
    toks = jnp.asarray([[1, 2, 3, 4]])
    lens = jnp.asarray([4])
    out, n, _ = generate_tokens(
        model, params, toks, lens, jax.random.PRNGKey(1),
        max_new_tokens=6, min_prompt_len=4,
        top_p=0.9, top_p_decay=0.8, top_p_bound=0.2)
    row = np.asarray(out)[0]
    assert int(n) == 10 and ((row >= 0) & (row < 64)).all()


@pytest.mark.parametrize("tp,sp", [(2, False), (4, True)])
def test_sharded_generation_matches_unsharded(model_and_params, utils,
                                              tp, sp):
    """Decode with tp-sharded params (vocab-sharded head, heads-sharded
    attention, tp-sharded KV caches) must produce the same tokens as the
    unsharded loop (reference serves under TP x PP:
    megatron/text_generation/forward_step.py:17-204)."""
    model, params = model_and_params
    toks = jnp.asarray([[1, 2, 3, 4], [5, 6, 7, 0]])
    lens = jnp.asarray([4, 3])

    want, want_n, _ = generate_tokens(
        model, params, toks, lens, jax.random.PRNGKey(0),
        max_new_tokens=8, min_prompt_len=3, greedy=True)

    from megatron_llm_tpu.parallel import sharding as sh

    utils.initialize_model_parallel(tp=tp)
    try:
        params_sh = sh.shard_params(params, model.param_specs(params))
        got, got_n, _ = generate_tokens(
            model, params_sh, toks, lens, jax.random.PRNGKey(0),
            max_new_tokens=8, min_prompt_len=3, greedy=True)
        spec = params_sh["lm_head"]["weight"].sharding.spec
        assert "tp" in spec, f"head not vocab-sharded: {spec}"
    finally:
        utils.destroy_model_parallel()
    assert int(got_n) == int(want_n)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.parametrize("tp", [2])
def test_sharded_beam_search_matches_unsharded(model_and_params, utils, tp):
    """Beam search with tp-sharded params must return the same beams and
    scores as the unsharded run (the reference serves beams through the
    same TP x PP path as sampling: megatron/text_generation/api.py:147-201
    -> forward_step.py)."""
    model, params = model_and_params
    toks = jnp.asarray([[1, 2, 3]])

    want_beams, want_scores = beam_search(
        model, params, toks, beam_size=3, max_new_tokens=5, eod_id=63)

    from megatron_llm_tpu.parallel import sharding as sh

    utils.initialize_model_parallel(tp=tp)
    try:
        params_sh = sh.shard_params(params, model.param_specs(params))
        got_beams, got_scores = beam_search(
            model, params_sh, toks, beam_size=3, max_new_tokens=5,
            eod_id=63)
        spec = params_sh["lm_head"]["weight"].sharding.spec
        assert "tp" in spec, f"head not vocab-sharded: {spec}"
    finally:
        utils.destroy_model_parallel()
    np.testing.assert_array_equal(np.asarray(got_beams),
                                  np.asarray(want_beams))
    np.testing.assert_allclose(np.asarray(got_scores),
                               np.asarray(want_scores), atol=2e-5)


def test_microbatched_prefill_matches_monolithic(model_and_params):
    """batch_times_seqlen_threshold splits the prefill forward into
    micro-batches (reference forward_step.py:17-204); the generated
    tokens and log-probs must be identical to the monolithic path."""
    model, params = model_and_params
    rng = np.random.RandomState(1)
    toks = jnp.asarray(rng.randint(1, 64, (4, 8)))
    lens = jnp.asarray([8, 8, 8, 8], jnp.int32)
    kw = dict(max_new_tokens=6, min_prompt_len=8, greedy=True,
              return_log_probs=True)
    out_a, len_a, lp_a = generate_tokens(
        model, params, toks, lens, jax.random.PRNGKey(0), **kw)
    # 4*8=32 > 8 -> 4 chunks of batch 1
    out_b, len_b, lp_b = generate_tokens(
        model, params, toks, lens, jax.random.PRNGKey(0),
        batch_times_seqlen_threshold=8, **kw)
    np.testing.assert_array_equal(np.asarray(out_a), np.asarray(out_b))
    np.testing.assert_array_equal(np.asarray(len_a), np.asarray(len_b))
    np.testing.assert_allclose(np.asarray(lp_a), np.asarray(lp_b),
                               atol=2e-5)


def test_cache_len_padding_is_invisible(model_and_params):
    """A padded KV cache (cache_len > prompt+max_new) is masked out:
    tokens, lengths, and log-probs match the exact-size cache bit for
    bit (the knob behind tools/decode_bench.py's equal-cost
    differencing)."""
    from megatron_llm_tpu.text_generation.generation import generate_tokens
    model, params = model_and_params
    toks = jnp.array([[3, 5, 7, 9], [2, 4, 0, 0]], jnp.int32)
    lens = jnp.array([4, 2], jnp.int32)
    key = jax.random.PRNGKey(1)
    kw = dict(max_new_tokens=6, min_prompt_len=2, greedy=True,
              return_log_probs=True)
    t0, l0, p0 = generate_tokens(model, params, toks, lens, key, **kw)
    t1, l1, p1 = generate_tokens(model, params, toks, lens, key,
                                 cache_len=4 + 6 + 17, **kw)
    np.testing.assert_array_equal(np.asarray(t0), np.asarray(t1))
    np.testing.assert_array_equal(np.asarray(l0), np.asarray(l1))
    np.testing.assert_allclose(np.asarray(p0), np.asarray(p1), atol=1e-5)
