"""serving/drafter.py prompt-lookup drafting edge cases + the scheduler's
speculative +K worst-case page reservation — pure host-side (no model,
no jax device work)."""

import pytest

from megatron_llm_tpu.serving.drafter import draft_budget, lookup_draft
from megatron_llm_tpu.serving.kv_blocks import BlockManager
from megatron_llm_tpu.serving.request import (
    Request,
    RequestQueue,
    SamplingParams,
)
from megatron_llm_tpu.serving.scheduler import Scheduler


# ---------------------------------------------------------------------------
# lookup_draft
# ---------------------------------------------------------------------------

def test_draft_basic_bigram_continuation():
    # bigram (1, 2) last occurred at position 3 -> continuation [5, 1, 2]
    assert lookup_draft([1, 2, 9, 1, 2, 5, 1, 2], 2) == [5, 1]


def test_draft_prefers_most_recent_match():
    # (1, 2) occurs at 0 (-> 9) and at 3 (-> 5): the recent one wins
    assert lookup_draft([1, 2, 9, 1, 2, 5, 1, 2], 1) == [5]


def test_draft_match_at_position_zero():
    # the ONLY earlier occurrence of (7, 8) starts the history
    assert lookup_draft([7, 8, 5, 7, 8], 3) == [5, 7, 8]


def test_draft_empty_and_short_history():
    assert lookup_draft([], 4) == []
    assert lookup_draft([1], 4) == []
    assert lookup_draft([1, 2], 4) == []        # bigram, no continuation


def test_draft_no_earlier_occurrence():
    assert lookup_draft([1, 2, 3, 4, 5], 4) == []
    # the current bigram itself is not a match (j + 2 < n excluded)
    assert lookup_draft([9, 9, 1, 2], 4) == []


def test_draft_k_zero_slot():
    # sampled-temperature slots pass k=0: always no proposal
    assert lookup_draft([1, 2, 1, 2, 1, 2], 0) == []
    assert lookup_draft([1, 2, 1, 2, 1, 2], -1) == []


def test_draft_truncates_at_history_end():
    # match at 0, continuation [3, 1, 2] — only 3 known tokens, never
    # padded up to k
    assert lookup_draft([1, 2, 3, 1, 2], 4) == [3, 1, 2]


def test_draft_never_exceeds_k():
    d = lookup_draft([1, 2, 3, 4, 5, 6, 1, 2], 3)
    assert d == [3, 4, 5]


def test_draft_budget_clamps_to_remaining_tokens():
    # a verify step commits up to draft_len + 1 tokens, so the budget
    # leaves room for the bonus: never overshoot max_new_tokens
    assert draft_budget(4, 16, 0) == 4          # plenty left
    assert draft_budget(4, 16, 11) == 4
    assert draft_budget(4, 16, 12) == 3         # 4 left -> draft 3
    assert draft_budget(4, 16, 14) == 1
    assert draft_budget(4, 16, 15) == 0         # 1 left: plain decode
    assert draft_budget(4, 16, 16) == 0
    for gen in range(17):
        k = draft_budget(4, 16, gen)
        assert k + 1 + gen <= 16 or k == 0


# ---------------------------------------------------------------------------
# scheduler +K reservation (the ride-along bugfix): a drafting slot's
# verify step writes KV up to K tokens past the committed context, so a
# near-full pool must NOT admit a request whose base reservation fits
# but whose first verify step would write into unreserved blocks
# ---------------------------------------------------------------------------

def _sched(num_blocks, draft_k, block_size=4, max_model_len=64):
    bm = BlockManager(num_blocks=num_blocks, block_size=block_size,
                      num_slots=2, max_blocks_per_slot=16,
                      prefix_cache=False)
    return Scheduler(RequestQueue(8), bm, max_model_len, draft_k=draft_k)


GREEDY8 = SamplingParams(max_new_tokens=8, temperature=0.0)


def test_reservation_counts_draft_tokens():
    # prompt 8 + max_new 8 = 16 tokens = 4 blocks base; +K=4 -> 5 blocks
    req = Request([1] * 8, GREEDY8)
    assert _sched(9, 0).total_tokens(req) == 16
    assert _sched(9, 4).total_tokens(req) == 20


def test_near_full_pool_rejects_drafting_request():
    # 5 pool blocks = 4 usable (block 0 is the garbage block): exactly
    # the base need.  Without the corrected reservation this admits and
    # the first verify step scatters into blocks it never reserved.
    sched = _sched(5, 4)
    sched.queue.put(Request([1] * 8, GREEDY8))
    assert sched.admit() == []
    # one more usable block covers the +K worst case: admits
    sched = _sched(6, 4)
    req = Request([1] * 8, GREEDY8)
    sched.queue.put(req)
    assert sched.admit() == [req]


def test_sampled_request_keeps_base_reservation():
    # a sampled-temperature request never drafts: the near-full pool
    # that refuses the greedy request still admits it
    sched = _sched(5, 4)
    req = Request([1] * 8, SamplingParams(max_new_tokens=8,
                                          temperature=0.9))
    sched.queue.put(req)
    assert sched.admit() == [req]


def test_boundary_request_stays_admittable_with_speculation():
    # prompt + max_new == max_model_len: the +K reservation caps at
    # max_model_len (the engine's draft budget clamp keeps every write
    # below it), so speculation must not 400-reject or starve it
    sched = _sched(32, 4, block_size=4, max_model_len=32)
    req = Request([1] * 16, SamplingParams(max_new_tokens=16,
                                           temperature=0.0))
    sched.validate(req)                          # no ValueError
    assert sched.total_tokens(req) == 32
    sched.queue.put(req)
    assert sched.admit() == [req]


def test_over_length_still_rejected_with_speculation():
    sched = _sched(32, 4, block_size=4, max_model_len=32)
    with pytest.raises(ValueError):
        sched.validate(Request([1] * 17,
                               SamplingParams(max_new_tokens=16,
                                              temperature=0.0)))
