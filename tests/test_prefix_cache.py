"""Prefix cache (serving/kv_blocks.py) + engine integration.

Covers the PR-6 acceptance criteria: refcounted page sharing, LRU
eviction, copy-on-write isolation, mid-prefill release accounting,
invariants under random churn, greedy parity with caching on vs off,
and near-zero prefill on repeated prompts.
"""

import json
import random
import threading

import jax
import numpy as np
import pytest

from megatron_llm_tpu import telemetry
from megatron_llm_tpu.models.llama import LlamaModel, llama_config
from megatron_llm_tpu.serving import (
    BlockManager,
    EngineConfig,
    InferenceEngine,
    NoCapacity,
    SamplingParams,
    chain_block_digests,
)
from megatron_llm_tpu.serving.kv_blocks import GARBAGE_BLOCK


# ---------------------------------------------------------------------------
# block manager (pure host-side)
# ---------------------------------------------------------------------------

BS = 4


def _bm(num_blocks=17, num_slots=4, **kw):
    kw.setdefault("prefix_cache", True)
    return BlockManager(num_blocks=num_blocks, block_size=BS,
                        num_slots=num_slots, max_blocks_per_slot=8, **kw)


def test_chain_digests_commit_to_whole_prefix():
    a = chain_block_digests(list(range(12)), BS, 3)
    b = chain_block_digests(list(range(12)), BS, 3)
    assert a == b and len(a) == 3
    # changing an EARLY token changes every later digest (chained)
    c = chain_block_digests([99] + list(range(1, 12)), BS, 3)
    assert all(x != y for x, y in zip(a, c))
    # same block content after a different prefix != same digest
    d = chain_block_digests(list(range(4, 12)), BS, 2)
    assert d[1] != a[2]


def test_prefix_sharing_refcounts_and_hit_tokens():
    bm = _bm()
    prompt = list(range(1, 11))             # 10 toks: 2 full blocks + tail
    s0 = bm.alloc(16, prompt_tokens=prompt)
    assert bm.slot_cached_tokens(s0) == 0   # cold cache
    bm.commit_prefix(s0, prompt, n_written=10)
    s1 = bm.alloc(16, prompt_tokens=prompt)
    assert bm.slot_cached_tokens(s1) == 8   # 2 shared blocks
    # physical sharing: first two table entries identical, tails private
    assert bm.tables[s0][:2].tolist() == bm.tables[s1][:2].tolist()
    assert bm.tables[s0][2] != bm.tables[s1][2]
    st = bm.stats()
    assert st["prefix_cache_hits"] == 2
    assert st["prefix_cache_hit_tokens"] == 8
    bm.check_invariants()
    # releasing one owner keeps the pages for the other
    bm.free(s0, token_ids=prompt, n_written=10)
    bm.check_invariants()
    s2 = bm.alloc(16, prompt_tokens=prompt)
    assert bm.slot_cached_tokens(s2) == 8
    bm.free(s1)
    bm.free(s2)
    bm.check_invariants()


def test_full_prompt_match_capped_one_token_short():
    """A prompt that is entirely cached still prefills >= 1 token (the
    engine needs real logits for the first sampled token)."""
    bm = _bm()
    prompt = list(range(1, 9))              # exactly 2 blocks
    s0 = bm.alloc(12, prompt_tokens=prompt)
    bm.commit_prefix(s0, prompt, n_written=8)
    s1 = bm.alloc(12, prompt_tokens=prompt)
    assert bm.slot_cached_tokens(s1) == 4   # capped at (8-1)//4 = 1 block
    bm.free(s0)
    bm.free(s1)


def test_disabled_mode_never_shares():
    bm = _bm(prefix_cache=False)
    prompt = list(range(1, 11))
    s0 = bm.alloc(16, prompt_tokens=prompt)
    bm.commit_prefix(s0, prompt, n_written=10)
    bm.free(s0, token_ids=prompt, n_written=10)
    s1 = bm.alloc(16, prompt_tokens=prompt)
    assert bm.slot_cached_tokens(s1) == 0
    st = bm.stats()
    assert st["prefix_cache_hits"] == 0
    assert st["blocks_cached_reusable"] == 0


def test_released_pages_park_in_lru_and_evict_in_order():
    bm = _bm(num_blocks=9)                  # 8 usable blocks
    pa = list(range(1, 9))                  # 2 full blocks
    pb = list(range(11, 19))
    sa = bm.alloc(8, prompt_tokens=pa)
    bm.free(sa, token_ids=pa, n_written=8)  # a's 2 pages -> LRU (older)
    sb = bm.alloc(8, prompt_tokens=pb)
    bm.free(sb, token_ids=pb, n_written=8)  # b's 2 pages -> LRU (newer)
    st = bm.stats()
    assert st["blocks_cached_reusable"] == 4
    assert st["blocks_free"] == 4
    # demand 6 fresh blocks: 4 free + 2 evicted, LRU (a's) evicted first
    s = bm.alloc(24, prompt_tokens=list(range(90, 96)))
    assert bm.stats()["prefix_cache_evictions"] == 2
    # a's chain is gone, b's survives
    s2 = bm.alloc(8, prompt_tokens=pb)
    assert bm.slot_cached_tokens(s2) == 4
    bm.free(s)
    bm.free(s2)
    bm.check_invariants()
    bm2 = _bm(num_blocks=9)
    s = bm2.alloc(32)                       # all 8 blocks, no cache help
    with pytest.raises(NoCapacity):
        bm2.alloc(4)
    bm2.free(s)


def test_cow_ensure_writable_isolates_shared_pages():
    bm = _bm()
    prompt = list(range(1, 11))
    s0 = bm.alloc(16, prompt_tokens=prompt)
    bm.commit_prefix(s0, prompt, n_written=10)
    s1 = bm.alloc(16, prompt_tokens=prompt)
    shared = bm.tables[s1][0]
    res = bm.ensure_writable(s1, 0)         # refcount 2 -> private copy
    assert res is not None
    new_b, src_b = res
    assert src_b == shared and new_b != shared
    assert bm.tables[s1][0] == new_b
    assert bm.tables[s0][0] == shared       # owner untouched
    assert bm.stats()["cow_copies"] == 1
    bm.check_invariants()
    # sole-owner registered page: unregistered in place, no copy
    assert bm.ensure_writable(s0, 0) is None
    # the digest chain for block 0 is gone -> future allocs miss it
    s2 = bm.alloc(16, prompt_tokens=prompt)
    assert bm.slot_cached_tokens(s2) == 0
    bm.free(s0)
    bm.free(s1)
    bm.free(s2)
    bm.check_invariants()


def test_mid_prefill_release_returns_unwritten_pages_immediately():
    bm = _bm(num_blocks=9)
    prompt = list(range(1, 17))
    s = bm.alloc(32, prompt_tokens=prompt)  # reserves all 8 blocks
    assert bm.stats()["blocks_free"] == 0
    # released after writing only 1 full block of prefill
    bm.free(s, token_ids=prompt, n_written=4)
    st = bm.stats()
    assert st["blocks_cached_reusable"] == 1    # the written page
    assert st["blocks_free"] == 7               # the rest, immediately
    bm.check_invariants()


def test_refcount_eviction_invariants_under_random_churn():
    rng = random.Random(0)
    bm = _bm(num_blocks=13, num_slots=3)
    # a small prompt universe so prefixes genuinely collide
    prompts = [[rng.randrange(1, 6) for _ in range(rng.randrange(3, 17))]
               for _ in range(6)]
    live = {}
    for step in range(400):
        op = rng.random()
        if op < 0.45 and len(live) < 3:
            p = rng.choice(prompts)
            total = len(p) + rng.randrange(1, 8)
            try:
                s = bm.alloc(total, prompt_tokens=p)
            except NoCapacity:
                continue
            live[s] = (p, bm.slot_cached_tokens(s))
        elif op < 0.65 and live:
            s = rng.choice(list(live))
            p, cached = live[s]
            n_written = rng.randrange(cached, len(p) + 1)
            bm.commit_prefix(s, p, n_written)
        elif op < 0.8 and live:
            s = rng.choice(list(live))
            p, _ = live[s]
            bm.ensure_writable(s, rng.randrange(0, bm.blocks_needed(len(p))))
        elif live:
            s = rng.choice(list(live))
            p, cached = live[s]
            bm.free(s, token_ids=p,
                    n_written=rng.randrange(0, len(p) + 1))
            del live[s]
        bm.check_invariants()
    for s, (p, _) in list(live.items()):
        bm.free(s, token_ids=p, n_written=len(p))
    bm.check_invariants()
    st = bm.stats()
    assert st["blocks_in_use"] == 0
    assert st["blocks_free"] + st["blocks_cached_reusable"] == 12


# ---------------------------------------------------------------------------
# engine integration (tiny model)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def model_and_params():
    cfg = llama_config("tiny", num_layers=2, seq_length=64,
                       max_position_embeddings=64, padded_vocab_size=64,
                       use_flash_attn=False)
    model = LlamaModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    return model, params


def _engine(model_and_params, prefix_cache):
    model, params = model_and_params
    eng = InferenceEngine(model, params, EngineConfig(
        num_slots=4, block_size=8, prefill_chunk=16, max_model_len=64,
        max_queue_depth=32, default_deadline_secs=0.0,
        prefix_cache=prefix_cache))
    eng.warmup()
    return eng


@pytest.fixture(scope="module")
def eng_on(model_and_params):
    eng = _engine(model_and_params, True).start()
    yield eng
    eng.stop()


@pytest.fixture(scope="module")
def eng_off(model_and_params):
    eng = _engine(model_and_params, False).start()
    yield eng
    eng.stop()


GREEDY = dict(temperature=0.0, eod_id=63)
PROMPT = [(3 * i + 1) % 60 + 1 for i in range(24)]       # 3 full blocks


def _greedy(eng, prompt, n=8):
    r = eng.submit(prompt, SamplingParams(max_new_tokens=n, **GREEDY))
    return r.result(timeout=180)


def test_greedy_parity_cache_on_off(eng_on, eng_off):
    """Acceptance: token-identical outputs with caching on vs off, on
    both the cold (miss) and warm (hit) paths."""
    cold_on = _greedy(eng_on, PROMPT).tokens
    cold_off = _greedy(eng_off, PROMPT).tokens
    assert cold_on == cold_off
    warm_on = _greedy(eng_on, PROMPT)
    assert warm_on.cached_prompt_tokens > 0          # really the hit path
    assert warm_on.tokens == cold_off
    assert _greedy(eng_off, PROMPT).tokens == cold_off


def test_repeat_prompt_near_zero_prefill(eng_on):
    """Acceptance: computed prefill tokens on a repeated prompt ≪
    submitted (only the uncached tail runs)."""
    prompt = [(5 * i + 2) % 60 + 1 for i in range(33)]   # 4 blocks + 1
    first = _greedy(eng_on, prompt)
    c0 = eng_on.prefill_tokens_computed
    second = _greedy(eng_on, prompt)
    assert second.cached_prompt_tokens == 32
    assert eng_on.prefill_tokens_computed - c0 == 1      # tail only
    assert second.tokens == first.tokens
    st = eng_on.stats()
    assert st["prefill_tokens_cached"] >= 32
    assert st["prefix_cache_hit_tokens"] >= 32


def test_mid_block_divergence_cow_isolation(eng_on, eng_off):
    """Acceptance: requests sharing 20 tokens then diverging mid-block
    don't corrupt each other — each matches its cache-off baseline."""
    common = [(7 * i + 3) % 60 + 1 for i in range(20)]
    a = common + [11, 12, 13, 14]
    b = common + [21, 22, 23, 24]
    base_a = _greedy(eng_off, a).tokens
    base_b = _greedy(eng_off, b).tokens
    assert _greedy(eng_on, a).tokens == base_a
    got_b = _greedy(eng_on, b)
    assert got_b.cached_prompt_tokens == 16      # 2 shared full blocks
    assert got_b.tokens == base_b
    assert _greedy(eng_on, a).tokens == base_a   # a unharmed by b
    # concurrent divergent-pair storm: outputs stay isolated
    outs = [None] * 6

    def client(i):
        p = a if i % 2 == 0 else b
        outs[i] = _greedy(eng_on, p).tokens

    threads = [threading.Thread(target=client, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for i, o in enumerate(outs):
        assert o == (base_a if i % 2 == 0 else base_b)


def test_zero_recompiles_on_warm_hit_path(model_and_params):
    """The cache-hit prefill (nonzero start over adopted pages) and the
    COW copy program run inside the steady-state compile set."""
    from megatron_llm_tpu import tracing

    eng = _engine(model_and_params, True)
    prompt = [(9 * i + 4) % 60 + 1 for i in range(24)]
    tracer = tracing.SpanTracer()
    det = tracing.RecompileDetector(tracer)
    tr = tracing.Tracing(tracer=tracer, recompile=det)
    tracing.install_tracing(tr)
    eng.start()
    try:
        _greedy(eng, prompt)
        det.mark_steady()
        _greedy(eng, prompt)                 # warm: cached-prefix prefill
        _greedy(eng, prompt[:20] + [31, 32, 33, 34])
        assert det.recompiles == 0, \
            f"cache-hit path recompiled: {list(det.events)}"
    finally:
        eng.stop()
        tracing.install_tracing(None)


def test_request_done_jsonl_carries_cache_and_pool_fields(
        eng_on, tmp_path):
    stream = telemetry.TelemetryStream(str(tmp_path))
    old = telemetry.get_stream()
    telemetry.install_stream(stream)
    try:
        _greedy(eng_on, PROMPT)
    finally:
        telemetry.install_stream(old)
    stream.close()
    records = []
    for f in tmp_path.glob("*.jsonl"):
        with open(f) as fh:
            records += [json.loads(line) for line in fh if line.strip()]
    done = [r for r in records if r.get("event") == "request_done"]
    assert done, f"no request_done in {records}"
    rec = done[-1]
    for key in ("cached_prompt_tokens", "blocks_free", "blocks_in_use",
                "blocks_cached_reusable", "queue_depth", "ttft_secs"):
        assert key in rec, key
    assert rec["cached_prompt_tokens"] > 0       # PROMPT is warm by now
