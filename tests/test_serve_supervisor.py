"""Fleet supervisor (serving/supervisor.py).

Fast tier only — two layers, zero subprocesses:

* **Pure policy** — :class:`ScalingPolicy` decisions are functions of a
  :class:`FleetSnapshot` whose ``now`` the test injects, so the breach /
  cooldown / hysteresis / respawn-backoff timelines are driven with a
  fake clock and asserted exactly.
* **Supervisor + router** — :class:`FleetSupervisor` over an in-process
  fake :class:`ReplicaBackend` (stub HTTP replicas standing in for
  engines) covers lifecycle registration, death->respawn healing,
  scale-up brownout wiring, coldest-replica drain, the fleet-stats hook
  on the router snapshot, and the JSONL event log.

The chaos end-to-end (real engine subprocesses, SIGKILL mid-burst) is
tests/test_serve_fleet.py, slow tier.
"""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from megatron_llm_tpu.serving.router import (
    AllBackendsThrottled,
    NoBackendAvailable,
    ReplicaRouter,
)
from megatron_llm_tpu.serving.supervisor import (
    FleetSnapshot,
    FleetSupervisor,
    PolicyConfig,
    ReplicaBackend,
    ReplicaInfo,
    Respawn,
    ScaleDown,
    ScaleUp,
    ScalingPolicy,
    _hist_delta,
    _histogram_percentile,
)


# ---------------------------------------------------------------------------
# pure policy: injectable clock, no IO
# ---------------------------------------------------------------------------

def _cfg(**kw):
    base = dict(ttft_p95_slo_secs=1.0, queue_depth_high=10,
                breach_secs=2.0, scale_cooldown_secs=30.0,
                scale_down_idle_secs=60.0, scale_down_ttft_frac=0.5,
                min_replicas=1, max_replicas=3,
                respawn_backoff_secs=1.0, respawn_backoff_max_secs=8.0,
                respawn_storm_window_secs=60.0,
                dead_confirmation_secs=3.0)
    base.update(kw)
    return PolicyConfig(**base)


def _ready(slot, affinity=0, in_flight=0):
    return ReplicaInfo(slot=slot, url=f"http://x/{slot}", state="ready",
                       in_flight=in_flight, affinity_entries=affinity)


def _snap(now, replicas, p95=None, queue=0, spawns=0):
    return FleetSnapshot(now=now, replicas=replicas, ttft_p95_secs=p95,
                         queue_depth=queue, spawns_in_flight=spawns)


def test_scale_up_requires_sustained_breach():
    pol = ScalingPolicy(_cfg())
    reps = [_ready("replica-0")]
    assert pol.decide(_snap(0.0, reps, p95=2.0)) == []
    assert pol.decide(_snap(1.0, reps, p95=2.0)) == []
    assert pol.decide(_snap(2.0, reps, p95=2.0)) == \
        [ScaleUp(reason="ttft_p95")]


def test_breach_blip_resets_timer():
    pol = ScalingPolicy(_cfg())
    reps = [_ready("replica-0")]
    pol.decide(_snap(0.0, reps, p95=2.0))
    pol.decide(_snap(1.0, reps, p95=0.8))     # back in band: reset
    assert pol.decide(_snap(2.0, reps, p95=2.0)) == []
    assert pol.decide(_snap(3.0, reps, p95=2.0)) == []
    assert pol.decide(_snap(4.0, reps, p95=2.0)) == \
        [ScaleUp(reason="ttft_p95")]


def test_queue_depth_breach_reason():
    pol = ScalingPolicy(_cfg())
    reps = [_ready("replica-0")]
    pol.decide(_snap(0.0, reps, queue=50))
    assert pol.decide(_snap(2.0, reps, queue=50)) == \
        [ScaleUp(reason="queue_depth")]


def test_scale_up_suppressed_while_spawn_in_flight():
    pol = ScalingPolicy(_cfg())
    reps = [_ready("replica-0")]
    pol.decide(_snap(0.0, reps, p95=2.0, spawns=1))
    assert pol.decide(_snap(5.0, reps, p95=2.0, spawns=1)) == []
    # spawn landed: the (still-running) breach timer fires at once
    assert pol.decide(_snap(6.0, reps + [_ready("replica-1")],
                            p95=2.0)) == [ScaleUp(reason="ttft_p95")]


def test_scale_up_capped_at_max_replicas():
    pol = ScalingPolicy(_cfg(max_replicas=2))
    reps = [_ready("replica-0"), _ready("replica-1")]
    pol.decide(_snap(0.0, reps, p95=2.0))
    assert pol.decide(_snap(10.0, reps, p95=2.0)) == []


def test_cooldown_suppresses_second_scale_up():
    pol = ScalingPolicy(_cfg())
    reps = [_ready("replica-0")]
    pol.decide(_snap(0.0, reps, p95=2.0))
    assert pol.decide(_snap(2.0, reps, p95=2.0)) == \
        [ScaleUp(reason="ttft_p95")]
    reps2 = reps + [_ready("replica-1")]
    pol.decide(_snap(3.0, reps2, p95=2.0))   # breach resumes at t=3
    assert pol.decide(_snap(10.0, reps2, p95=2.0)) == []   # not cooled
    assert pol.decide(_snap(31.0, reps2, p95=2.0)) == []   # 31-2 < 30
    assert pol.decide(_snap(33.0, reps2, p95=2.0)) == \
        [ScaleUp(reason="ttft_p95")]


def test_hysteresis_band_never_flaps():
    """p95 oscillating inside (frac*SLO, SLO] runs neither timer, and an
    oscillation crossing both thresholds faster than the sustain windows
    keeps resetting them — no action either way."""
    pol = ScalingPolicy(_cfg(scale_cooldown_secs=0.0))
    reps = [_ready("replica-0"), _ready("replica-1")]
    for t in range(200):
        p95 = 0.95 if t % 2 else 0.6      # inside the band
        assert pol.decide(_snap(float(t), reps, p95=p95)) == []
    pol2 = ScalingPolicy(_cfg(scale_cooldown_secs=0.0))
    for t in range(200):
        p95 = 1.5 if t % 2 else 0.3       # crossing, but never sustained
        assert pol2.decide(_snap(float(t), reps, p95=p95)) == []


def test_scale_down_picks_coldest_ready_replica():
    pol = ScalingPolicy(_cfg(scale_down_idle_secs=10.0,
                             scale_cooldown_secs=0.0))
    reps = [_ready("replica-0", affinity=5),
            _ready("replica-1", affinity=1),
            _ready("replica-2", affinity=3)]
    assert pol.decide(_snap(0.0, reps, p95=0.1)) == []
    assert pol.decide(_snap(10.0, reps, p95=0.1)) == \
        [ScaleDown(victim="replica-1")]
    # affinity ties break toward the replica with least in-flight
    pol2 = ScalingPolicy(_cfg(scale_down_idle_secs=10.0,
                              scale_cooldown_secs=0.0))
    tied = [_ready("replica-0", affinity=1, in_flight=2),
            _ready("replica-1", affinity=1, in_flight=0)]
    pol2.decide(_snap(0.0, tied))
    assert pol2.decide(_snap(10.0, tied)) == \
        [ScaleDown(victim="replica-1")]


def test_scale_down_respects_min_replicas():
    pol = ScalingPolicy(_cfg(scale_down_idle_secs=10.0,
                             scale_cooldown_secs=0.0, min_replicas=1))
    reps = [_ready("replica-0")]
    pol.decide(_snap(0.0, reps))
    assert pol.decide(_snap(100.0, reps)) == []


def test_respawn_backoff_doubles_in_storm_and_resets_outside():
    pol = ScalingPolicy(_cfg())
    dead = [ReplicaInfo(slot="replica-0", state="dead",
                        process_dead=True)]
    assert pol.decide(_snap(0.0, dead)) == [Respawn("replica-0", 1.0)]
    # next_allowed gates the retry; then each storm respawn doubles
    assert pol.decide(_snap(0.5, dead)) == []
    assert pol.decide(_snap(1.5, dead)) == [Respawn("replica-0", 2.0)]
    assert pol.decide(_snap(4.0, dead)) == [Respawn("replica-0", 4.0)]
    assert pol.decide(_snap(8.5, dead)) == [Respawn("replica-0", 8.0)]
    assert pol.decide(_snap(17.0, dead)) == \
        [Respawn("replica-0", 8.0)]                       # capped
    # a death after a quiet storm-window resets to the base backoff
    assert pol.decide(_snap(17.0 + 60.0, dead)) == \
        [Respawn("replica-0", 1.0)]


def test_breaker_death_needs_confirmation_window():
    pol = ScalingPolicy(_cfg(dead_confirmation_secs=3.0))
    brk = [ReplicaInfo(slot="replica-0", state="dead", dead_since=99.0)]
    assert pol.decide(_snap(100.0, brk)) == []    # 1s open: not yet
    assert pol.decide(_snap(102.0, brk)) == [Respawn("replica-0", 1.0)]


def test_retiring_and_starting_replicas_never_respawned():
    pol = ScalingPolicy(_cfg())
    reps = [ReplicaInfo(slot="replica-0", state="retiring",
                        process_dead=True),
            ReplicaInfo(slot="replica-1", state="starting")]
    assert pol.decide(_snap(0.0, reps)) == []


def test_hist_delta_windowed_p95_sees_recovery():
    """Lifetime percentiles latch after a spike; the per-poll bucket
    delta is what lets the scaler observe recovery."""
    calm = {"buckets": {"0.5": 100, "1.0": 0, "+Inf": 0},
            "count": 100, "sum": 10.0}
    spike = {"buckets": {"0.5": 100, "1.0": 0, "+Inf": 50},
             "count": 150, "sum": 300.0}
    after = {"buckets": {"0.5": 200, "1.0": 0, "+Inf": 50},
             "count": 250, "sum": 330.0}
    w1 = _hist_delta(spike, calm)
    assert w1["count"] == 50 and w1["buckets"]["+Inf"] == 50
    assert _histogram_percentile(w1, 0.95) == pytest.approx(1.0)
    # lifetime after recovery still reads past the SLO ...
    assert _histogram_percentile(after, 0.95) == pytest.approx(1.0)
    # ... while the last window has recovered
    w2 = _hist_delta(after, spike)
    assert _histogram_percentile(w2, 0.95) <= 0.5
    # degenerate shapes answer None / pass-through
    assert _hist_delta(None, calm) is None
    assert _hist_delta(spike, None) is spike
    assert _histogram_percentile(None, 0.95) is None
    assert _histogram_percentile({"buckets": {}, "count": 0}, 0.95) \
        is None


# ---------------------------------------------------------------------------
# supervisor over an in-process fake backend
# ---------------------------------------------------------------------------

class _MiniReplica:
    """Engine-replica lookalike for supervisor tests: /api, /health,
    /metrics (configurable engine queue depth), POST /drain."""

    def __init__(self, name, queue_depth=0, throttle_body=None):
        self.name = name
        self.queue_depth = queue_depth
        self.throttle_body = throttle_body
        self.hits = []
        self.drained = threading.Event()
        stub = self

        class Handler(BaseHTTPRequestHandler):
            def _json(self, code, body):
                data = json.dumps(body).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_PUT(self):
                n = int(self.headers.get("Content-Length", 0))
                self.rfile.read(n)
                if self.path == "/drain":
                    stub.drained.set()
                    self._json(200, {"status": "draining"})
                    return
                stub.hits.append(self.path)
                if stub.throttle_body is not None:
                    self._json(429, stub.throttle_body)
                    return
                self._json(200, {"backend": stub.name, "text": ["ok"],
                                 "tokens": [[1, 2, 3]]})

            do_POST = do_PUT

            def do_GET(self):
                if self.path == "/health":
                    self._json(200, {"status": "draining"
                                     if stub.drained.is_set() else "ok"})
                else:
                    self._json(200, {
                        "requests": len(stub.hits),
                        "engine": {"queue_depth": stub.queue_depth}})

            def log_message(self, *a):
                pass

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


class _FakeHandle:
    def __init__(self, stub):
        self.stub = stub
        self.dead = False


class _FakeBackend(ReplicaBackend):
    """In-process ReplicaBackend: spawn starts a stub HTTP server,
    kill marks the handle dead (what poll then reports) — the whole
    lifecycle without a subprocess."""

    spawn_eta_secs = 5.0

    def __init__(self, queue_depth=0):
        self.queue_depth = queue_depth
        self.handles = []

    def spawn(self):
        h = _FakeHandle(_MiniReplica(f"fake-{len(self.handles)}",
                                     queue_depth=self.queue_depth))
        self.handles.append(h)
        return h

    def poll(self, handle):
        if handle.dead:
            return "dead", None
        return "ready", handle.stub.url

    def kill(self, handle):
        if not handle.dead:
            handle.dead = True
            handle.stub.close()


def _quiet_cfg(**kw):
    """Policy knobs that keep the scaler inert unless a test arms it."""
    base = dict(ttft_p95_slo_secs=1e9, queue_depth_high=10 ** 9,
                breach_secs=3600.0, scale_cooldown_secs=3600.0,
                scale_down_idle_secs=3600.0, min_replicas=1,
                max_replicas=4, respawn_backoff_secs=0.0,
                dead_confirmation_secs=3600.0)
    base.update(kw)
    return PolicyConfig(**base)


def _payload(prompt):
    return json.dumps({"prompts": [prompt],
                       "tokens_to_generate": 4}).encode()


@pytest.fixture()
def fleet():
    """(router, backend, make_supervisor) with teardown."""
    sups = []
    router = ReplicaRouter([], health_interval_secs=3600.0)
    backend = _FakeBackend()

    def make(**kw):
        sup = FleetSupervisor(router, backend, **kw)
        sups.append(sup)
        return sup

    yield router, backend, make
    for sup in sups:
        sup.stop(kill_replicas=True)
    router.stop()
    for h in backend.handles:
        backend.kill(h)


def test_supervisor_registers_and_reports_fleet_stats(fleet, tmp_path):
    router, backend, make = fleet
    log = tmp_path / "fleet.jsonl"
    sup = make(config=_quiet_cfg(), event_log_path=str(log))
    sup.spawn_initial(2)
    assert router.snapshot()["backends_total"] == 0   # not yet polled
    sup.run_once()
    snap = router.snapshot()
    assert snap["backends_total"] == 2
    # supervisor counters ride the router snapshot via the stats hook
    assert snap["fleet"]["replicas_ready"] == 2
    assert snap["fleet"]["spawns_total"] == 2
    assert snap["fleet"]["respawns_total"] == 0
    # requests actually route to supervisor-registered replicas
    status, _, body = router.dispatch("PUT", "/api", _payload("1 2 3"))
    assert status == 200 and json.loads(body)["text"] == ["ok"]
    # structured JSONL event log: schema-stamped fleet events
    events = [json.loads(line) for line in
              log.read_text().splitlines()]
    assert [e["event"] for e in events] == \
        ["replica_spawned", "replica_spawned"]
    for e in events:
        from megatron_llm_tpu.telemetry import TELEMETRY_SCHEMA_VERSION
        assert e["kind"] == "fleet" and \
            e["schema"] == TELEMETRY_SCHEMA_VERSION
        assert e["slot"].startswith("replica-")
        assert e["url"].startswith("http://127.0.0.1:")


def test_dead_replica_is_respawned_under_same_slot(fleet):
    router, backend, make = fleet
    sup = make(config=_quiet_cfg())
    sup.spawn_initial(2)
    sup.run_once()
    backend.kill(sup.replicas["replica-0"].handle)    # SIGKILL stand-in
    acts = sup.run_once()
    # death observed -> deregistered -> respawn decided in the same turn
    assert any(isinstance(a, Respawn) and a.slot == "replica-0"
               for a in acts)
    assert sup.counters["deaths_total"] == 1
    sup.run_once()                       # replacement reports ready
    assert router.snapshot()["backends_total"] == 2
    assert sup.counters["respawns_total"] == 1
    assert sup.replicas["replica-0"].state == "ready"
    names = [e["event"] for e in sup.events]
    assert "replica_died" in names and "replica_respawned" in names


def test_scale_up_opens_brownout_until_replica_ready(fleet):
    router, backend, make = fleet
    backend.queue_depth = 50             # every stub reports a backlog
    sup = make(config=_quiet_cfg(queue_depth_high=10, breach_secs=0.0,
                                 max_replicas=2))
    sup.spawn_initial(1)
    acts = sup.run_once()
    assert [a for a in acts if isinstance(a, ScaleUp)] == \
        [ScaleUp(reason="queue_depth")]
    assert sup.counters["scale_ups_total"] == 1
    assert sup.counters["brownouts_total"] == 1
    snap = router.snapshot()
    assert snap["brownout_active"] == 1
    assert snap["brownout_remaining_secs"] > 0
    names = [e["event"] for e in sup.events]
    assert "scale_up" in names and "brownout" in names
    # a throttled 429 during the brownout carries the spawn-ETA floor
    for h in backend.handles:
        h.stub.throttle_body = {"message": "throttled",
                                "retry_after_secs": 0.25,
                                "queue_depth": 7,
                                "estimated_wait_secs": 0.5}
    with pytest.raises(AllBackendsThrottled) as ei:
        router.dispatch("PUT", "/api", _payload("1 2 3"))
    assert ei.value.body["brownout"] is True
    assert ei.value.body["retry_after_secs"] > 0.25
    assert router.snapshot()["brownout_429s_total"] == 1
    for h in backend.handles:
        h.stub.throttle_body = None
    # the new replica registering closes the brownout window
    sup.run_once()
    snap = router.snapshot()
    assert snap["backends_total"] == 2
    assert snap["brownout_active"] == 0
    assert router.brownout_remaining() == 0.0


def test_scale_down_drains_coldest_and_reaps_without_healing(fleet):
    router, backend, make = fleet
    sup = make(config=_quiet_cfg(scale_cooldown_secs=0.0,
                                 max_replicas=2))
    sup.spawn_initial(2)
    sup.run_once()
    # pin a sticky prefix on one replica: the OTHER one is coldest
    router.dispatch("PUT", "/api", _payload("7 7 7"))
    hot = [h.stub.url for h in backend.handles if h.stub.hits][0]
    sup.config.scale_down_idle_secs = 0.0    # arm the scaler
    acts = sup.run_once()
    downs = [a for a in acts if isinstance(a, ScaleDown)]
    assert len(downs) == 1
    victim = sup.replicas[downs[0].victim]
    assert victim.url != hot
    assert victim.state == "retiring"
    assert sup.counters["scale_downs_total"] == 1
    cold = [h for h in backend.handles if h.stub.url == victim.url][0]
    assert cold.stub.drained.wait(5.0)       # got POST /drain
    # drained replica exits; the supervisor reaps it, no healing
    sup.config.scale_down_idle_secs = 3600.0
    backend.kill(cold)
    sup.run_once()
    assert router.snapshot()["backends_total"] == 1
    assert victim.slot not in sup.replicas
    assert sup.counters["deaths_total"] == 0
    assert "replica_died" not in [e["event"] for e in sup.events]


def test_router_runtime_membership_and_affinity_remap():
    a, b = _MiniReplica("a"), _MiniReplica("b")
    router = ReplicaRouter([], health_interval_secs=3600.0)
    try:
        with pytest.raises(NoBackendAvailable):
            router.dispatch("PUT", "/api", _payload("1 2 3"))
        first = router.add_backend(a.url)
        assert router.add_backend(a.url) is first    # idempotent on URL
        status, _, _ = router.dispatch("PUT", "/api", _payload("1 2 3"))
        assert status == 200
        assert router.affinity_counts()[a.url] == 1
        router.add_backend(b.url)
        assert router.snapshot()["backends_total"] == 2
        assert router.remove_backend(a.url) is True
        assert router.remove_backend(a.url) is False     # unknown now
        # sticky keys remap by rendezvous onto the survivors — nothing
        # ever points at the removed address again
        assert router.affinity_counts() == {b.url: 1}
        status, _, body = router.dispatch("PUT", "/api",
                                          _payload("1 2 3"))
        assert status == 200
        assert json.loads(body)["backend"] == "b"
    finally:
        router.stop()
        a.close()
        b.close()


def test_brownout_ends_restore_optimistic_429():
    stub = _MiniReplica("t", throttle_body={
        "message": "throttled", "retry_after_secs": 0.25,
        "queue_depth": 7, "estimated_wait_secs": 0.5})
    router = ReplicaRouter([stub.url], health_interval_secs=3600.0)
    try:
        router.begin_brownout(30.0)
        with pytest.raises(AllBackendsThrottled) as ei:
            router.dispatch("PUT", "/api", _payload("1 2 3"))
        assert ei.value.body["brownout"] is True
        assert ei.value.body["retry_after_secs"] >= 25.0
        router.end_brownout()
        with pytest.raises(AllBackendsThrottled) as ei2:
            router.dispatch("PUT", "/api", _payload("1 2 3"))
        assert "brownout" not in ei2.value.body
        assert ei2.value.body["retry_after_secs"] == 0.25
        snap = router.snapshot()
        assert snap["throttled_total"] == 2
        assert snap["brownout_429s_total"] == 1
    finally:
        router.stop()
        stub.close()
