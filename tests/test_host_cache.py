"""Hierarchical KV cache: the host-RAM spill tier (serving/host_cache.py).

Covers the PR-19 acceptance criteria: asynchronous spill off the HBM
LRU with epoch-validated lost-race drops, two-tier admission matching
with pinning, swap-in re-registration into the HBM cache, cross-tier
``check_invariants()`` under 400-step random churn, engine-level
host-hit rescue with greedy token parity and zero steady-state
recompiles, and restart semantics (tier survives, queued spills drop).
"""

import random
import threading
import time

import jax
import numpy as np
import pytest

from megatron_llm_tpu import tracing
from megatron_llm_tpu.models.llama import LlamaModel, llama_config
from megatron_llm_tpu.serving import (
    BlockManager,
    EngineConfig,
    HostKVCache,
    InferenceEngine,
    NoCapacity,
    SamplingParams,
    chain_block_digests,
)

BS = 4


def _fake_fetch(manager, block):
    """Host-side stand-in for the engine's device→host page gather:
    returns a recognizable token so tests can assert which physical
    page a host entry was copied from."""
    return ("page", block)


def _host(capacity_blocks=8, **kw):
    # block_bytes=1 makes capacity_bytes the block capacity directly
    return HostKVCache(capacity_blocks, 1, fetch=_fake_fetch, **kw)


def _bm(num_blocks=13, num_slots=3, host_cache=None, **kw):
    kw.setdefault("prefix_cache", True)
    return BlockManager(num_blocks=num_blocks, block_size=BS,
                        num_slots=num_slots, max_blocks_per_slot=8,
                        host_cache=host_cache, **kw)


def _consume_swap_ins(bm, host, slot):
    """What the engine's _swap_in step does, minus the device scatter:
    pop the slot's pending swap-ins, take each host entry, register the
    pages back into the HBM cache."""
    pending = bm.take_pending_swap_ins(slot)
    loaded = []
    for _idx, b, d in pending:
        data = host.take_for_swap_in(d)
        assert data is not None, "pinned entry vanished"
        loaded.append((b, d))
    bm.complete_swap_ins(slot, loaded)
    if loaded:
        host.note_swap_in(len(loaded), 0.0)
    return loaded


# ---------------------------------------------------------------------------
# spill path (block manager + spill thread)
# ---------------------------------------------------------------------------

def test_spill_then_evict_then_host_hit_and_swap_in():
    host = _host(capacity_blocks=64).start()
    bm = _bm(host_cache=host)
    try:
        prompt = list(range(1, 10))          # 9 toks: cap = 2 full blocks
        s0 = bm.alloc(12, prompt_tokens=prompt)
        bm.commit_prefix(s0, prompt, n_written=9)
        digests = chain_block_digests(prompt, BS, 2)
        bm.free(s0, token_ids=prompt, n_written=9)
        assert host.drain(), "spill queue did not drain"
        assert all(host.contains(d) for d in digests)
        st = host.stats()
        assert st["spills_completed"] >= 2
        bm.check_invariants()
        # cycle the HBM LRU until the prompt's pages are gone
        filler_id = 100
        while any(bm.host_spill_check(d) for d in digests):
            f = [filler_id + i for i in range(9)]
            filler_id += 10
            s = bm.alloc(12, prompt_tokens=f)
            _consume_swap_ins(bm, host, s)
            bm.free(s, token_ids=f, n_written=9)
        assert host.drain()
        bm.check_invariants()
        # the host tier rescues what the HBM LRU evicted
        s1 = bm.alloc(12, prompt_tokens=prompt)
        assert bm.slot_cached_tokens(s1) == 8       # 2 host-tier blocks
        assert bm.slot_host_hits(s1) == 2
        loaded = _consume_swap_ins(bm, host, s1)
        assert len(loaded) == 2
        # swapped-in pages are registered: a second admission shares by
        # reference (a plain HBM hit, no new swap-in)
        s2 = bm.alloc(12, prompt_tokens=prompt)
        assert bm.slot_cached_tokens(s2) == 8
        assert bm.slot_host_hits(s2) == 0
        assert bm.take_pending_swap_ins(s2) == []
        assert bm.tables[s1][:2].tolist() == bm.tables[s2][:2].tolist()
        st = bm.stats()
        assert st["prefix_cache_host_hits"] == 2
        assert host.stats()["swap_in_blocks"] == 2
        bm.free(s1, token_ids=prompt, n_written=9)
        bm.free(s2, token_ids=prompt, n_written=9)
        bm.check_invariants()
    finally:
        host.close()


def test_spill_lost_race_is_dropped_by_epoch_validation():
    host = _host()                  # no thread: we drive spills by hand
    bm = _bm(host_cache=host)
    prompt = list(range(1, 10))
    s0 = bm.alloc(12, prompt_tokens=prompt)
    bm.commit_prefix(s0, prompt, n_written=9)
    bm.free(s0, token_ids=prompt, n_written=9)
    item = host._queue.get_nowait()     # (manager, digest, block, epoch)
    host._queue.task_done()
    _, digest, block, epoch = item
    assert bm.host_spill_check(digest) == (block, epoch)
    # evict the page before the spill runs: the digest unregisters and
    # the block's epoch bumps when it is handed to a new owner
    filler_id = 100
    while bm.host_spill_check(digest) is not None:
        f = [filler_id + i for i in range(9)]
        filler_id += 10
        s = bm.alloc(12, prompt_tokens=f)
        bm.free(s, token_ids=f, n_written=9)
    dropped_before = host.stats()["spills_dropped"]
    host._process_spill(bm, digest, block, epoch)
    assert not host.contains(digest)
    assert host.stats()["spills_dropped"] == dropped_before + 1
    host.check_invariants()


def test_host_lru_eviction_spares_pinned_entries():
    host = _host(capacity_blocks=2)
    # install three entries by hand through the spill path machinery
    bm = _bm(host_cache=host)
    prompts = [[10 * k + i for i in range(5)] for k in range(1, 4)]
    digests = []
    for p in prompts:
        s = bm.alloc(8, prompt_tokens=p)
        bm.commit_prefix(s, p, n_written=5)
        digests.append(chain_block_digests(p, BS, 1)[0])
        bm.free(s, token_ids=p, n_written=5)
    # drive the queued spills synchronously: capacity 2 evicts the LRU
    while True:
        try:
            item = host._queue.get_nowait()
        except Exception:
            break
        host._queue.task_done()
        host._process_spill(*item)
    assert host.stats()["entries"] == 2
    assert not host.contains(digests[0])        # LRU head evicted
    assert host.stats()["evictions"] == 1
    # pin the survivor pair: a further spill must drop, not evict them
    assert host.match_and_pin([digests[1]]) == [digests[1]]
    assert host.match_and_pin([digests[2]]) == [digests[2]]
    p = [77, 78, 79, 80, 81]
    s = bm.alloc(8, prompt_tokens=p)
    bm.commit_prefix(s, p, n_written=5)
    bm.free(s, token_ids=p, n_written=5)
    dropped_before = host.stats()["spills_dropped"]
    while True:
        try:
            item = host._queue.get_nowait()
        except Exception:
            break
        host._queue.task_done()
        host._process_spill(*item)
    assert host.stats()["spills_dropped"] > dropped_before
    assert host.contains(digests[1]) and host.contains(digests[2])
    host.unpin([digests[1], digests[2]])
    host.check_invariants()
    bm.check_invariants()


def test_nocapacity_after_host_match_unpins():
    host = _host(capacity_blocks=64).start()
    bm = _bm(num_blocks=13, num_slots=1, host_cache=host)
    try:
        prompt = list(range(1, 10))
        s0 = bm.alloc(12, prompt_tokens=prompt)
        bm.commit_prefix(s0, prompt, n_written=9)
        bm.free(s0, token_ids=prompt, n_written=9)
        assert host.drain()
        digests = chain_block_digests(prompt, BS, 2)
        filler_id = 100
        while any(bm.host_spill_check(d) for d in digests):
            f = [filler_id + i for i in range(9)]
            filler_id += 10
            s = bm.alloc(12, prompt_tokens=f)
            _consume_swap_ins(bm, host, s)
            bm.free(s, token_ids=f, n_written=9)
        assert host.drain()
        # occupy the only slot: the next admission matches the host
        # tier (pins 2 entries) and then fails on slot exhaustion — the
        # pins must be released on the way out
        blocker = bm.alloc(12, prompt_tokens=[50, 51, 52])
        _consume_swap_ins(bm, host, blocker)
        with pytest.raises(NoCapacity):
            bm.alloc(12, prompt_tokens=prompt)
        assert host.stats()["pinned"] == 0, \
            "NoCapacity admission leaked host pins"
        bm.free(blocker)
        bm.check_invariants()
    finally:
        host.close()


def test_free_with_unconsumed_swap_ins_unpins():
    host = _host(capacity_blocks=64).start()
    bm = _bm(host_cache=host)
    try:
        prompt = list(range(1, 10))
        s0 = bm.alloc(12, prompt_tokens=prompt)
        bm.commit_prefix(s0, prompt, n_written=9)
        bm.free(s0, token_ids=prompt, n_written=9)
        assert host.drain()
        digests = chain_block_digests(prompt, BS, 2)
        filler_id = 100
        while any(bm.host_spill_check(d) for d in digests):
            f = [filler_id + i for i in range(9)]
            filler_id += 10
            s = bm.alloc(12, prompt_tokens=f)
            _consume_swap_ins(bm, host, s)
            bm.free(s, token_ids=f, n_written=9)
        assert host.drain()
        s1 = bm.alloc(12, prompt_tokens=prompt)
        assert bm.slot_host_hits(s1) == 2
        assert host.stats()["pinned"] == 2
        # aborted before the engine consumed the swap-ins
        bm.free(s1)
        assert host.stats()["pinned"] == 0
        bm.check_invariants()
    finally:
        host.close()


def test_on_pool_reset_clears_pins_and_queue():
    host = _host()
    bm = _bm(host_cache=host)
    prompt = list(range(1, 10))
    s0 = bm.alloc(12, prompt_tokens=prompt)
    bm.commit_prefix(s0, prompt, n_written=9)   # spills queued, no thread
    assert host._queue.qsize() > 0
    queued_before = host.stats()["spills_queued"]
    host.on_pool_reset()
    st = host.stats()
    assert st["pool_resets"] == 1
    assert st["pinned"] == 0
    assert host._queue.qsize() == 0
    # dropped spills stay accounted: completed + dropped <= queued holds
    assert st["spills_dropped"] > 0
    assert st["spills_queued"] == queued_before
    host.check_invariants()


# ---------------------------------------------------------------------------
# cross-tier invariants under churn (the PR-6 churn test, two-tier)
# ---------------------------------------------------------------------------

def test_two_tier_invariants_under_random_churn():
    rng = random.Random(0)
    host = _host(capacity_blocks=6).start()
    bm = _bm(num_blocks=13, num_slots=3, host_cache=host)
    try:
        prompts = [[rng.randrange(1, 6)
                    for _ in range(rng.randrange(3, 17))]
                   for _ in range(6)]
        live = {}
        for step in range(400):
            op = rng.random()
            if op < 0.45 and len(live) < 3:
                p = rng.choice(prompts)
                total = len(p) + rng.randrange(1, 8)
                try:
                    s = bm.alloc(total, prompt_tokens=p)
                except NoCapacity:
                    continue
                _consume_swap_ins(bm, host, s)
                live[s] = (p, bm.slot_cached_tokens(s))
            elif op < 0.65 and live:
                s = rng.choice(list(live))
                p, cached = live[s]
                n_written = rng.randrange(cached, len(p) + 1)
                bm.commit_prefix(s, p, n_written)
            elif op < 0.8 and live:
                s = rng.choice(list(live))
                p, _ = live[s]
                try:
                    bm.ensure_writable(
                        s, rng.randrange(0, bm.blocks_needed(len(p))))
                except NoCapacity:
                    # COW with every page live: the engine preempts the
                    # slot here; the churn just skips the write
                    pass
            elif live:
                s = rng.choice(list(live))
                p, cached = live[s]
                bm.free(s, token_ids=p,
                        n_written=rng.randrange(0, len(p) + 1))
                del live[s]
            if step % 20 == 0:
                assert host.drain()
            bm.check_invariants()       # cross-tier: observatory + host
        for s, (p, _) in list(live.items()):
            bm.free(s, token_ids=p, n_written=len(p))
        assert host.drain()
        bm.check_invariants()
        st = bm.stats()
        assert st["blocks_in_use"] == 0
        assert st["blocks_free"] + st["blocks_cached_reusable"] == 12
        hs = host.stats()
        assert hs["spills_completed"] > 0, "churn never exercised spill"
        assert st["prefix_cache_host_hits"] > 0, \
            "churn never exercised a host-tier rescue"
    finally:
        host.close()


# ---------------------------------------------------------------------------
# engine integration (tiny model, CPU)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def model_and_params():
    cfg = llama_config("tiny", num_layers=2, seq_length=64,
                       max_position_embeddings=64, padded_vocab_size=64,
                       use_flash_attn=False)
    model = LlamaModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    return model, params


def _engine(model_and_params, host_cache_bytes, num_blocks=13):
    model, params = model_and_params
    return InferenceEngine(model, params, EngineConfig(
        num_slots=4, block_size=8, prefill_chunk=16, max_model_len=64,
        num_blocks=num_blocks, max_queue_depth=32,
        default_deadline_secs=0.0, host_cache_bytes=host_cache_bytes))


GREEDY = dict(temperature=0.0, eod_id=63)
PROMPT = [1, 2, 3, 4, 5, 6, 7, 8] * 4 + [9]     # 33 toks: 4 full blocks


def _evict_prompt_from_hbm(eng, prompt):
    """Run distinct filler prompts until none of the prompt's prefix
    digests remain in the HBM cache (they survive in the host tier)."""
    digests = chain_block_digests(
        prompt, eng.config.block_size,
        (len(prompt) - 1) // eng.config.block_size)
    for i in range(40):
        if not any(eng.blocks.host_spill_check(d) for d in digests):
            return
        filler = [10 + i] * 25 + [i % 7 + 1]
        eng.submit(filler, SamplingParams(max_new_tokens=2, **GREEDY)
                   ).result(timeout=120)
        assert eng.host_cache.drain()
    raise AssertionError("fillers never evicted the prompt from HBM")


def test_engine_host_hit_after_hbm_eviction_token_parity(model_and_params):
    eng = _engine(model_and_params, host_cache_bytes=64 << 20)
    eng.warmup()
    tracer = tracing.SpanTracer()
    det = tracing.RecompileDetector(tracer)
    tracing.install_tracing(tracing.Tracing(tracer=tracer, recompile=det))
    eng.start()
    try:
        det.mark_steady()
        sp = SamplingParams(max_new_tokens=4, **GREEDY)
        r1 = eng.submit(PROMPT, sp)
        r1.result(timeout=120)
        assert eng.host_cache.drain(), "spills did not drain"
        assert eng.host_cache.stats()["spills_completed"] >= 4
        _evict_prompt_from_hbm(eng, PROMPT)
        # the re-submission misses HBM, hits the host tier, swaps in
        r2 = eng.submit(PROMPT, sp)
        r2.result(timeout=120)
        assert r2.host_hit_blocks == 4, \
            f"expected 4 host-tier blocks, got {r2.host_hit_blocks}"
        assert r2.cached_prompt_tokens == 32
        assert r2.swap_in_secs > 0
        # greedy parity: swapped-in KV is a byte copy of the pages the
        # first run computed, so the continuation is token-identical
        assert r2.out_tokens == r1.out_tokens
        assert det.recompiles == 0, \
            f"{det.recompiles} recompiles: {list(det.events)}"
        st = eng.stats()
        assert st["cache"]["host_hits"] >= 4
        assert st["cache"]["host"]["swap_in_blocks"] >= 4
        assert st["cache"]["swap_in_blocks"] >= 4
        assert st["swap_in_blocks_reserved"] >= 4
        assert st["prefix_cache_host_hits"] >= 4
        eng.blocks.check_invariants()
    finally:
        tracing.install_tracing(None)
        eng.stop()


def test_engine_restart_carries_host_counters(model_and_params):
    eng = _engine(model_and_params, host_cache_bytes=64 << 20)
    eng.warmup()
    eng.start()
    try:
        sp = SamplingParams(max_new_tokens=3, **GREEDY)
        eng.submit(PROMPT, sp).result(timeout=120)
        assert eng.host_cache.drain()
        entries_before = eng.host_cache.stats()["entries"]
        assert entries_before > 0
        hits_before = eng.blocks.stats()["prefix_cache_host_hits"]
        eng.restart("test")
        # the tier and its residency survive the pool swap
        assert eng.host_cache.stats()["entries"] == entries_before
        assert eng.host_cache.stats()["pool_resets"] == 1
        assert eng.blocks.stats()["prefix_cache_host_hits"] == hits_before
        # the fresh (empty) HBM pool rescues the prompt from host RAM
        r = eng.submit(PROMPT, sp)
        r.result(timeout=120)
        assert r.host_hit_blocks == 4
        eng.blocks.check_invariants()
    finally:
        eng.stop()


def test_engine_without_host_cache_unchanged(model_and_params):
    eng = _engine(model_and_params, host_cache_bytes=0)
    assert eng.host_cache is None
    eng.warmup()
    eng.start()
    try:
        r = eng.submit(PROMPT, SamplingParams(max_new_tokens=3, **GREEDY))
        r.result(timeout=120)
        assert r.host_hit_blocks == 0 and r.swap_in_secs == 0.0
        st = eng.stats()
        assert st["cache"]["host"] == {"enabled": 0}
        assert st["cache"]["host_hits"] == 0
        eng.blocks.check_invariants()
    finally:
        eng.stop()


# ---------------------------------------------------------------------------
# bookkeeping overhead gate (PR 17/18 convention)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_host_cache_overhead_under_2pct():
    """Per-request host-tier bookkeeping (two-tier match with pinning,
    swap-in consume/complete, spill enqueue, free-time unpin) must cost
    < 2% of a real CPU dispatch of the tiny engine.  The device copies
    themselves are off the hot path (spill thread) or replace prefill
    compute (swap-in), so the gate prices the pure accounting."""
    from megatron_llm_tpu import telemetry

    cfg = llama_config("tiny", num_layers=2, seq_length=64,
                       max_position_embeddings=64, padded_vocab_size=64,
                       use_flash_attn=False)
    model = LlamaModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    eng = InferenceEngine(model, params, EngineConfig(
        num_slots=4, block_size=8, prefill_chunk=16, max_model_len=64,
        max_queue_depth=32, default_deadline_secs=0.0))
    eng.warmup()
    eng.start()
    try:
        reqs = [eng.submit([1 + i, 2, 3, 4],
                           SamplingParams(max_new_tokens=12,
                                          temperature=0.0, eod_id=63))
                for i in range(8)]
        for r in reqs:
            r.result(timeout=180)
        loop = eng.stats()["loop"]
    finally:
        eng.stop()
    assert loop["dispatches"] > 0
    mean_dispatch_secs = loop["wall_secs"] / loop["dispatches"]

    # arm B: one full two-tier request lifecycle per iteration over a
    # warm host tier (match+pin -> alloc -> consume swap-ins -> free),
    # with a live (null-file) telemetry stream — the worst-case path
    stream = telemetry.TelemetryStream(None)
    telemetry.install_stream(stream)
    try:
        host = _host(capacity_blocks=32)        # no thread: pure host cost
        bm = _bm(num_blocks=13, num_slots=3, host_cache=host)
        prompt = list(range(1, 10))
        s = bm.alloc(12, prompt_tokens=prompt)
        bm.commit_prefix(s, prompt, n_written=9)
        bm.free(s, token_ids=prompt, n_written=9)
        while True:             # drive queued spills synchronously
            try:
                item = host._queue.get_nowait()
            except Exception:
                break
            host._queue.task_done()
            host._process_spill(*item)
        n = 2000
        t0 = time.perf_counter()
        for _ in range(n):
            s = bm.alloc(12, prompt_tokens=prompt)
            _consume_swap_ins(bm, host, s)
            bm.free(s, token_ids=prompt, n_written=9)
        cost_per_alloc = (time.perf_counter() - t0) / n
    finally:
        telemetry.install_stream(None)
        stream.close()
    frac = cost_per_alloc / mean_dispatch_secs
    assert frac < 0.02, (
        f"host-tier bookkeeping {cost_per_alloc * 1e6:.1f}us/request = "
        f"{frac * 100:.2f}% of a {mean_dispatch_secs * 1e3:.2f}ms dispatch")
