"""Behavioral train-loop flags + timers (reference training.py:397-399,
500-525, 731-767): skip_iters runs forward-only, exit_interval /
exit_duration_in_mins save + exit, per-phase timers accumulate."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from megatron_llm_tpu.config import ParallelConfig, TrainConfig
from megatron_llm_tpu.models.llama import LlamaModel, llama_config
from megatron_llm_tpu.timers import Timers
from megatron_llm_tpu.training import pretrain


def _setup(utils):
    cfg = llama_config("tiny", seq_length=16, max_position_embeddings=16,
                       padded_vocab_size=64, num_layers=1, hidden_size=32,
                       num_attention_heads=4, ffn_hidden_size=64)
    model = LlamaModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    utils.initialize_model_parallel(tp=1)
    rng = np.random.RandomState(0)
    fixed = jnp.asarray(rng.randint(0, 64, size=(1, 8, 16)))

    def it():
        while True:
            yield {
                "tokens": fixed,
                "labels": jnp.roll(fixed, -1, axis=-1),
                "loss_mask": jnp.ones_like(fixed, jnp.float32),
            }

    return model, params, it


def _tc(iters):
    return TrainConfig(micro_batch_size=8, global_batch_size=8,
                       train_iters=iters, lr=1e-2, optimizer="adam", seed=3)


def _flat(params):
    return np.concatenate([np.asarray(l).ravel()
                           for l in jax.tree_util.tree_leaves(params)])


def test_skip_iters_runs_forward_only(utils):
    model, params, it = _setup(utils)
    pc = ParallelConfig()
    base = _flat(params)  # snapshot: train_step donates param buffers

    # every iteration skipped -> parameters must be bit-identical
    p_skip, _, n = pretrain(model, params, _tc(3), pc, it(),
                            log_interval=0, skip_iters=[1, 2, 3])
    assert n == 3
    np.testing.assert_array_equal(_flat(p_skip), base)

    # partial skip still trains on the non-skipped iterations
    p_part, _, _ = pretrain(model, p_skip, _tc(3), pc, it(),
                            log_interval=0, skip_iters=[2])
    assert not np.array_equal(_flat(p_part), base)


def test_exit_interval_saves_and_exits(utils, tmp_path):
    model, params, it = _setup(utils)
    pc = ParallelConfig()
    with pytest.raises(SystemExit):
        pretrain(model, params, _tc(10), pc, it(), log_interval=0,
                 save_dir=str(tmp_path), exit_interval=2)
    # exited at iteration 2, with a checkpoint written there
    assert (tmp_path / "iter_0000002").exists()
    assert not (tmp_path / "iter_0000003").exists()


def test_exit_duration_saves_and_exits(utils, tmp_path, monkeypatch):
    import megatron_llm_tpu.training as T

    model, params, it = _setup(utils)
    pc = ParallelConfig()
    # fake clock: every perf_counter() call advances one minute, so the
    # duration budget (5 min) trips after a handful of iterations
    t = {"now": 0.0}

    def fake_clock():
        t["now"] += 60.0
        return t["now"]

    monkeypatch.setattr(T.time, "perf_counter", fake_clock)
    with pytest.raises(SystemExit):
        pretrain(model, params, _tc(1000), pc, it(), log_interval=0,
                 save_dir=str(tmp_path), exit_duration_in_mins=5)
    saved = sorted(p.name for p in tmp_path.glob("iter_*"))
    assert len(saved) == 1  # saved exactly once, on exit


def test_timers_accumulate_phases(utils):
    model, params, it = _setup(utils)
    pc = ParallelConfig()
    timers = Timers(log_level=2)
    pretrain(model, params, _tc(2), pc, it(), log_interval=0, timers=timers)
    elapsed = timers.get_elapsed(reset=False)
    assert elapsed.get("batch-generator", 0) > 0
    assert elapsed.get("train-step", 0) > 0
    assert timers("train-step").count == 2


def test_timers_logged_at_log_interval(utils, capsys):
    model, params, it = _setup(utils)
    pc = ParallelConfig()
    pretrain(model, params, _tc(2), pc, it(), log_interval=1)
    out = capsys.readouterr().out
    assert "time (ms)" in out
    assert "train-step" in out


def test_writer_receives_metrics_and_extras(utils):
    """The tensorboard/wandb writer path (reference training.py:509-589):
    per-iteration scalars, the --log_*_to_tensorboard extras, and timer
    values (written before the log-reset) all reach add_scalar."""
    model, params, it = _setup(utils)
    pc = ParallelConfig()

    class FakeWriter:
        def __init__(self):
            self.rows = {}

        def add_scalar(self, key, value, iteration):
            self.rows.setdefault(iteration, {})[key] = float(value)

        def flush(self):
            pass

    w = FakeWriter()
    pretrain(model, params, _tc(2), pc, it(), log_interval=1, writer=w,
             log_batch_size=True, log_world_size=True, log_memory=True)
    assert set(w.rows) == {1, 2}
    row = w.rows[1]
    assert row["batch-size"] == 8.0
    assert "world-size" in row and "mem-bytes-in-use" in row
    assert "lm loss" in row and "learning_rate" in row
    assert row.get("train-step-time", 0) > 0   # written before the reset
