"""Ulysses all-to-all context parallelism: op-level exactness vs full
attention, gradients, GQA/window handling, model-level parity with
--context_parallel_algo=ulysses, and the heads-indivisible ring
fallback.  (Both cp algorithms are TPU-native extensions; the reference
has no sequence/context parallelism — SURVEY §5.7.)"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from megatron_llm_tpu.models.llama import LlamaModel, llama_config
from megatron_llm_tpu.ops.pallas.flash_attention import _reference_attention
from megatron_llm_tpu.parallel import sharding as sh
from megatron_llm_tpu.parallel.ulysses import (
    ulysses_context_attention,
    ulysses_supported,
)


def _qkv(b=2, s=128, nh=4, ng=4, d=32, seed=0):
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(b, s, nh, d).astype(np.float32)) * 0.3
    k = jnp.asarray(rng.randn(b, s, ng, d).astype(np.float32)) * 0.3
    v = jnp.asarray(rng.randn(b, s, ng, d).astype(np.float32)) * 0.3
    return q, k, v


@pytest.mark.parametrize("window", [None, 48])
def test_ulysses_matches_full_attention(utils, window):
    utils.initialize_model_parallel(tp=1, pp=1, cp=4)
    q, k, v = _qkv()
    ref = _reference_attention(q, k, v, True, window, 0.125)
    out = jax.jit(
        lambda q, k, v: ulysses_context_attention(
            q, k, v, causal=True, sliding_window=window, softmax_scale=0.125
        )
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ulysses_gqa(utils):
    """GQA with ng = cp: each device ends up with exactly one KV head."""
    utils.initialize_model_parallel(tp=1, pp=1, cp=4)
    q, k, v = _qkv(nh=8, ng=4)
    ref = _reference_attention(q, k, v, True, None, 0.125)
    out = jax.jit(
        lambda q, k, v: ulysses_context_attention(
            q, k, v, causal=True, softmax_scale=0.125))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ulysses_gradients(utils):
    utils.initialize_model_parallel(tp=1, pp=1, cp=4)
    q, k, v = _qkv(s=64)

    def loss_ref(q, k, v):
        return (_reference_attention(q, k, v, True, None, 0.125) ** 2).sum()

    def loss_uly(q, k, v):
        return (ulysses_context_attention(
            q, k, v, causal=True, softmax_scale=0.125) ** 2).sum()

    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    gg = jax.jit(jax.grad(loss_uly, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(gr, gg):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-5)


def test_model_loss_parity_ulysses(utils):
    """Full llama forward with context_parallel_algo='ulysses' under
    cp=4 equals the unsharded loss."""
    cfg = llama_config("tiny", seq_length=64, max_position_embeddings=64,
                       padded_vocab_size=128,
                       context_parallel_algo="ulysses")
    model = LlamaModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, 128, (2, 64)))
    labels = jnp.roll(tokens, -1, axis=1)
    base = model(params, tokens, labels=labels, train=False)

    mesh = utils.initialize_model_parallel(tp=1, pp=1, cp=4)
    ps = sh.shard_params(params, model.param_specs(params))
    dsh = NamedSharding(mesh, P("dp", "cp"))
    out = jax.jit(lambda p, t, l: model(p, t, labels=l, train=False))(
        ps, jax.device_put(tokens, dsh), jax.device_put(labels, dsh))
    np.testing.assert_allclose(np.asarray(out), np.asarray(base), atol=3e-5)


def test_indivisible_heads_fall_back_to_ring(utils, monkeypatch):
    """nh=2 < cp=4: ulysses_supported is False and the dispatch must
    route to ring attention (still numerically correct)."""
    import megatron_llm_tpu.parallel.ring_attention as ring

    assert not ulysses_supported(2, 2, 4)
    called = {}
    real = ring.context_parallel_attention

    def spy(*a, **kw):
        called["ring"] = True
        return real(*a, **kw)

    monkeypatch.setattr(
        "megatron_llm_tpu.parallel.ring_attention."
        "context_parallel_attention", spy)

    cfg = llama_config("tiny", num_layers=2, hidden_size=64,
                       num_attention_heads=2, ffn_hidden_size=176,
                       seq_length=64, max_position_embeddings=64,
                       padded_vocab_size=128,
                       context_parallel_algo="ulysses")
    model = LlamaModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, 128, (2, 64)))
    labels = jnp.roll(tokens, -1, axis=1)
    base = model(params, tokens, labels=labels, train=False)

    mesh = utils.initialize_model_parallel(tp=1, pp=1, cp=4)
    ps = sh.shard_params(params, model.param_specs(params))
    dsh = NamedSharding(mesh, P("dp", "cp"))
    out = jax.jit(lambda p, t, l: model(p, t, labels=l, train=False))(
        ps, jax.device_put(tokens, dsh), jax.device_put(labels, dsh))
    assert called.get("ring")
    np.testing.assert_allclose(np.asarray(out), np.asarray(base), atol=3e-5)


def test_pipeline_with_ulysses_cp(utils):
    """pp=2 x cp=2 x dp=2 with the Ulysses algorithm: the cp all-to-all
    nests inside the pp-manual region (abstract context mesh via
    topology.nesting_mesh) and matches the unpipelined, unsharded loss
    — the same composition guarantee the ring algorithm has
    (tests/test_pipeline.py::test_pipeline_with_context_parallelism)."""
    from megatron_llm_tpu.parallel.pipeline import build_pipeline_loss_fn
    from tests.test_pipeline import _batch, _unpiped_loss

    cfg = llama_config("tiny", num_layers=4, seq_length=64,
                       max_position_embeddings=64, padded_vocab_size=128,
                       context_parallel_algo="ulysses")
    model = LlamaModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    batch = _batch(2, 2, 64, 128)
    base = float(_unpiped_loss(model, params, batch))

    mesh = utils.initialize_model_parallel(tp=1, pp=2, cp=2)
    ps = sh.shard_params(params, model.param_specs(params))
    dsh = NamedSharding(mesh, P(None, "dp", "cp"))
    batch_s = {k: jax.device_put(v, dsh) for k, v in batch.items()}
    loss_fn = build_pipeline_loss_fn(model, 2, 2)
    out = jax.jit(lambda p, b, k: loss_fn(p, b, k, train=False)[1])(
        ps, batch_s, jax.random.PRNGKey(0))
    assert abs(float(out) - base) < 1e-3


def test_ulysses_train_step(utils):
    """One full training step with ulysses cp (dp x cp mesh): finite loss
    and grads flow."""
    from megatron_llm_tpu.config import ParallelConfig, TrainConfig
    from megatron_llm_tpu.optimizer import MegatronOptimizer
    from megatron_llm_tpu.training import build_train_step

    mesh = utils.initialize_model_parallel(tp=1, pp=1, cp=2)
    cfg = llama_config("tiny", num_layers=2, seq_length=64,
                       max_position_embeddings=64, padded_vocab_size=128,
                       context_parallel_algo="ulysses")
    model = LlamaModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    params = sh.shard_params(params, model.param_specs(params))
    M, dp = 2, 4
    tc = TrainConfig(micro_batch_size=1, global_batch_size=M * dp, lr=1e-3)
    pc = ParallelConfig(context_parallel_size=2, data_parallel_size=dp)
    opt = MegatronOptimizer(tc)
    opt_state = opt.init(params)
    rng = np.random.RandomState(0)
    toks = jnp.asarray(rng.randint(0, 128, (M, dp, 64)))
    dsh = NamedSharding(mesh, P(None, "dp", "cp"))
    batch = {
        "tokens": jax.device_put(toks, dsh),
        "labels": jax.device_put(jnp.roll(toks, -1, axis=-1), dsh),
        "loss_mask": jax.device_put(jnp.ones_like(toks, jnp.float32), dsh),
    }
    step = build_train_step(model, opt, pc, M)
    _, _, metrics = step(params, opt_state, batch, jax.random.PRNGKey(0),
                         1e-3, 0.0)
    assert np.isfinite(float(metrics["lm loss"]))


def test_ulysses_nested_pallas_tp(utils):
    """The round-5 motivating case: inside ulysses' cp-manual region,
    tp is still auto, and the INNER pallas flash must nest its own
    shard_map (interpret mode engages the real kernel path on CPU) —
    parity with full reference attention."""
    import megatron_llm_tpu.ops.pallas.flash_attention as F

    utils.initialize_model_parallel(tp=2, pp=1, cp=2)
    q, k, v = _qkv(nh=4, ng=2, d=64)
    ref = _reference_attention(q, k, v, True, None, 0.125)
    F._INTERPRET = True
    try:
        out = jax.jit(
            lambda q, k, v: ulysses_context_attention(
                q, k, v, causal=True, softmax_scale=0.125))(q, k, v)
    finally:
        F._INTERPRET = False
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
