"""Sharded front door: rendezvous affinity + peer awareness.

Property coverage for the HRW (highest-random-weight) routing that lets
N stateless routers agree on prefix affinity with no shared state:

* the router's stdlib digest twins are byte-identical to the
  kv_blocks chained-blake2b construction they mirror
* removing a backend moves ~1/N of the keyspace and ONLY the removed
  backend's keys; adding one steals only what it wins
* independent routers (different membership list order, no
  communication) send the same prompt to the same replica
* the keyspace spreads near-uniformly across backends
* the health-probe period is jittered so N routers don't probe the
  fleet in lockstep
* any single router answers a fleet-wide /metrics by merging its
  siblings' histograms bucket-wise (percentiles recomputed, never
  summed)
* serve_bench's client half: multi --url failover on transport errors

Pure-function tests run with zero sockets; the peer/bench tests reuse
the stub replicas of tests/test_serve_router.py.
"""

import json
import socket
import sys
import threading
import time
import urllib.request

import pytest

from megatron_llm_tpu.serving.kv_blocks import (
    digest_link,
    prompt_affinity_digest,
)
from megatron_llm_tpu.serving.router import (
    Backend,
    ReplicaRouter,
    RouterServer,
    _digest_link,
    _prompt_affinity_digest,
    rendezvous_order,
)

# the stub replicas (and their factory fixture) from the router tests
from test_serve_router import (  # noqa: F401  (stubs is a fixture)
    _free_port,
    _payload,
    _prompt_on,
    stubs,
)


# ---------------------------------------------------------------------------
# digest twins: the stdlib-pure router must hash exactly like kv_blocks
# ---------------------------------------------------------------------------

def test_digest_twins_match_kv_blocks():
    prev = b""
    for payload in (b"", b"a", b"chunk-1", b"\x00" * 64):
        assert _digest_link(prev, payload) == digest_link(prev, payload)
        prev = digest_link(prev, payload)
    for prompt in ("", "7 7 7 session-x", "x" * 300, "é" * 70):
        assert _prompt_affinity_digest(prompt) \
            == prompt_affinity_digest(prompt)
    # the digest keys the *prefix*: tails beyond max_chars don't matter
    assert _prompt_affinity_digest("a" * 256 + "x") \
        == _prompt_affinity_digest("a" * 256 + "y")
    assert _prompt_affinity_digest("a") != _prompt_affinity_digest("b")


# ---------------------------------------------------------------------------
# rendezvous properties (pure, no sockets)
# ---------------------------------------------------------------------------

def _urls(n):
    return [f"http://10.0.0.{i + 1}:5000" for i in range(n)]


def _digests(n):
    return [_prompt_affinity_digest(f"prompt {i}") for i in range(n)]


def test_rendezvous_total_order_and_determinism():
    urls = _urls(4)
    d = _digests(1)[0]
    order = rendezvous_order(d, urls)
    assert sorted(order) == sorted(urls)        # a permutation
    assert order == rendezvous_order(d, list(reversed(urls)))
    assert order == rendezvous_order(d, urls)   # stable across calls


def test_rendezvous_remove_moves_only_the_victims_keys():
    urls = _urls(5)
    digests = _digests(2000)
    before = {d: rendezvous_order(d, urls)[0] for d in digests}
    victim = urls[2]
    survivors = [u for u in urls if u != victim]
    moved = 0
    for d in digests:
        after = rendezvous_order(d, survivors)[0]
        if before[d] == victim:
            moved += 1
        else:
            # keys NOT owned by the victim never move: their survivor
            # scores are untouched by the removal
            assert after == before[d]
    # the victim owned ~1/5 of the keyspace
    assert 0.10 < moved / len(digests) < 0.30


def test_rendezvous_add_steals_only_what_it_wins():
    urls = _urls(4)
    digests = _digests(2000)
    before = {d: rendezvous_order(d, urls)[0] for d in digests}
    grown = urls + ["http://10.0.0.99:5000"]
    stolen = 0
    for d in digests:
        after = rendezvous_order(d, grown)[0]
        if after != before[d]:
            assert after == grown[-1]   # only the newcomer takes keys
            stolen += 1
    # ~1/5 of the keyspace lands on the 5th backend
    assert 0.10 < stolen / len(digests) < 0.30


def test_rendezvous_distribution_uniformity():
    urls = _urls(3)
    counts = {u: 0 for u in urls}
    for d in _digests(3000):
        counts[rendezvous_order(d, urls)[0]] += 1
    for u, c in counts.items():
        frac = c / 3000
        assert 0.23 < frac < 0.44, f"{u} got {frac:.3f} of the keyspace"


def test_independent_routers_agree_on_affinity(stubs):
    """Two routers with no shared state and different membership list
    ORDER still route the same prompt to the same replica."""
    a, b, c = stubs("a"), stubs("b"), stubs("c")
    r1 = ReplicaRouter([a.url, b.url, c.url], health_interval_secs=999)
    r2 = ReplicaRouter([c.url, a.url, b.url], health_interval_secs=999)
    for i in range(8):
        prompt = f"session {i} prompt"
        r1.dispatch("PUT", "/api", _payload(prompt))
        r2.dispatch("PUT", "/api", _payload(prompt))
    for stub in (a, b, c):
        assert len(stub.hits) % 2 == 0, \
            f"routers disagreed: {stub.name} saw {len(stub.hits)} hits"
    assert len(a.hits) + len(b.hits) + len(c.hits) == 16


# ---------------------------------------------------------------------------
# jittered health probing
# ---------------------------------------------------------------------------

class _RecordingStop:
    """Event stand-in: records each wait interval, releases the loop
    after ``n`` periods."""

    def __init__(self, n):
        self.waits = []
        self.n = n

    def wait(self, timeout):
        self.waits.append(timeout)
        return len(self.waits) >= self.n

    def set(self):
        self.n = 0

    def is_set(self):
        return len(self.waits) >= self.n


def test_health_probe_interval_is_jittered():
    router = ReplicaRouter([], health_interval_secs=2.0)
    stop = _RecordingStop(12)
    router._health_stop = stop
    router.start_health_thread()
    router._health_thread.join(timeout=10.0)
    assert not router._health_thread.is_alive()
    router._health_thread = None
    assert len(stop.waits) == 12
    # every period inside the +/-50% band, and not phase-locked: N
    # routers probing every replica must not form a thundering herd
    for w in stop.waits:
        assert 1.0 <= w <= 3.0
    assert len(set(stop.waits)) > 1, "no jitter: identical periods"


# ---------------------------------------------------------------------------
# peer awareness: fleet /metrics at any router
# ---------------------------------------------------------------------------

def _start_server(router):
    srv = RouterServer(router)
    t = threading.Thread(target=srv.run,
                         kwargs={"host": "127.0.0.1", "port": 0},
                         daemon=True)
    t.start()
    for _ in range(100):
        if srv.httpd is not None:
            break
        time.sleep(0.05)
    assert srv.httpd is not None
    return srv, f"http://127.0.0.1:{srv.httpd.server_address[1]}"


def test_fleet_metrics_merge_across_peers(stubs):
    a, b = stubs("a"), stubs("b")
    backends = [a.url, b.url]
    r1 = ReplicaRouter(backends, health_interval_secs=999,
                       router_id="router-one")
    r2 = ReplicaRouter(backends, health_interval_secs=999,
                       router_id="router-two")
    s1, url1 = _start_server(r1)
    s2, url2 = _start_server(r2)
    try:
        r1.set_peers([url2])
        r2.set_peers([url1])
        # independent traffic through each router
        for i in range(3):
            r1.dispatch("PUT", "/api", _payload(f"via r1 {i}"))
        for i in range(5):
            r2.dispatch("PUT", "/api", _payload(f"via r2 {i}"))

        for url, here in ((url1, r1), (url2, r2)):
            with urllib.request.urlopen(url + "/metrics",
                                        timeout=30) as resp:
                m = json.loads(resp.read())
            tier = m["router_tier"]
            assert tier["routers_total"] == 2
            assert tier["routers_reporting"] == 2
            merged = tier["merged"]
            # counters sum across the tier...
            assert merged["requests_total"] == 8
            # ...histograms merge bucket-wise...
            hist = merged["histograms"]["router_dispatch_secs"]
            assert hist["count"] == 8
            assert sum(hist["buckets"].values()) == 8
            # ...and tier percentiles are recomputed from the merged
            # buckets, never summed: the p95 must sit inside the
            # observed latency range, not at ~2x of it
            p95 = merged["slo"]["router_dispatch_secs_p95"]
            assert p95 is not None and 0 < p95 <= hist["sum"]
            # the replica aggregate stays the LOCAL fleet view (every
            # router probes every replica; merging would double-count)
            assert m["aggregate"]["requests"] == 8
            assert here.router_id in str(tier["per_router"])
    finally:
        for srv, r in ((s1, r1), (s2, r2)):
            r.stop()
            srv.httpd.shutdown()


def test_one_hop_scope_router_never_fans_out(stubs):
    """?scope=router answers from the local snapshot only — the peer
    query a sibling makes must not recurse into another fan-out."""
    a = stubs("a")
    router = ReplicaRouter([a.url], health_interval_secs=999)
    # a peer pointing at a dead port: a recursive fan-out would hang or
    # shrink reporting; one-hop must not even try to reach it
    router.set_peers([f"http://127.0.0.1:{_free_port()}"])
    srv, url = _start_server(router)
    try:
        t0 = time.monotonic()
        with urllib.request.urlopen(url + "/metrics?scope=router",
                                    timeout=30) as resp:
            m = json.loads(resp.read())
        assert time.monotonic() - t0 < 5.0
        assert set(m) == {"router"}     # snapshot only: no aggregate,
        assert "router_tier" not in m   # no tier merge, no fan-out
    finally:
        router.stop()
        srv.httpd.shutdown()


# ---------------------------------------------------------------------------
# serve_bench: the client half of the crash contract
# ---------------------------------------------------------------------------

def test_serve_bench_multi_url_failover(stubs):
    import os
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools"))
    import serve_bench

    live = stubs("live")
    dead_url = f"http://127.0.0.1:{_free_port()}"
    live_url = f"http://{live.url}"
    r = serve_bench.run_bench([dead_url, live_url], clients=2,
                              requests=6, tokens=2, timeout=30.0)
    # every request completed exactly once despite half the front door
    # being down: transport errors fail over to the sibling URL
    assert r["ok"] == 6 and r["errors"] == 0
    assert len(live.hits) == 6
    assert r["urls"] == [dead_url, live_url]
    assert r["per_url_requests"][live_url] == 6
    assert r["per_url_requests"][dead_url] == 0
    # the ~half of tickets that started at the dead URL needed a retry
    assert r["failovers"] >= 3
    # schema keys hold for multi-URL runs too
    for key in serve_bench.JSON_SCHEMA_KEYS:
        assert key in r, f"missing {key}"


def test_serve_bench_http_errors_are_not_failed_over(stubs):
    """A 429 is an answer (brownout with honest retry_after), not a
    transport error — the bench must not hammer the sibling with it."""
    import serve_bench

    throttled = stubs("throttled",
                      throttle_body={"message": "busy",
                                     "retry_after_secs": 1})
    ok = stubs("ok")
    r = serve_bench.run_bench(
        [f"http://{throttled.url}", f"http://{ok.url}"],
        clients=1, requests=2, tokens=2, timeout=30.0)
    # ticket 0 starts at the throttled router and keeps its 429;
    # ticket 1 starts at the ok router and succeeds
    assert r["ok"] == 1 and r["errors"] == 1
    assert r["status_counts"].get("429") == 1
    assert r["failovers"] == 0


# ---------------------------------------------------------------------------
# serve_router CLI: empty fleet is a usage error unless --dynamic
# ---------------------------------------------------------------------------

def test_router_cli_zero_backends_exit_code(capsys):
    import os
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools"))
    import serve_router as tool

    with pytest.raises(SystemExit) as exc:
        tool.main(["--backends", " ,  ,", "--port", "0"])
    assert exc.value.code == 2
    assert "--dynamic" in capsys.readouterr().err

    # the new tier flags parse (serve_fleet spawns routers with these)
    a = tool.parse_args(["--dynamic", "--peers", "h:1, h:2,",
                         "--router_id", "router-7", "--port", "0"])
    assert a.dynamic and a.router_id == "router-7"
    assert a.backends == ""
