"""REAL multi-host integration: two OS processes with one CPU device
each, rendezvoused by jax.distributed over localhost (gloo collectives)
through the torchrun-style env contract — upgrading the multi-host
evidence from single-process fakes to an actual 2-process run of
initialize_distributed -> mesh -> place_host_batch -> dp=2 train step
-> cross-host checksum (incl. a real divergence catch)."""

import os
import re
import signal
import socket
import subprocess
import sys
import tempfile
import time

import pytest

REPO =os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "_multihost_worker.py")
MS_WORKER = os.path.join(REPO, "tests", "_multislice_worker.py")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _two_process_env():
    base = dict(os.environ)
    base.pop("PALLAS_AXON_POOL_IPS", None)
    base["JAX_PLATFORMS"] = "cpu"
    # one device per process: drop the 8-virtual-device conftest flags
    base["XLA_FLAGS"] = " ".join(
        f for f in base.get("XLA_FLAGS", "").split()
        if "host_platform_device_count" not in f)
    base.update(MASTER_ADDR="127.0.0.1", MASTER_PORT=str(_free_port()),
                WORLD_SIZE="2")
    return base


@pytest.mark.slow
def test_two_process_dp_train_step():
    base = _two_process_env()

    procs = []
    for rank in range(2):
        env = dict(base, RANK=str(rank))
        procs.append(subprocess.Popen(
            [sys.executable, WORKER], env=env, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append((p.returncode, out, err))

    for rank, (rc, out, err) in enumerate(outs):
        assert rc == 0, f"rank {rank} rc={rc}\n{err[-3000:]}"
        assert f"RANK{rank} CHECKSUM_OK" in out
        assert f"RANK{rank} DIVERGENCE_CAUGHT" in out

    # data-parallel consistency: both processes computed the same loss
    losses = [re.search(r"LOSS ([0-9.]+)", out).group(1)
              for _, out, _ in outs]
    assert losses[0] == losses[1], losses


@pytest.mark.slow
def test_two_process_slice_axis_hierarchical_reduce():
    """The ``slice`` mesh axis spans the process boundary (each process
    is one slice), so the second hop of the hierarchical all-reduce
    crosses a real process link — and must stay checksum-identical to
    the flat psum, with train-step loss parity between the two paths."""
    base = _two_process_env()

    procs = []
    for rank in range(2):
        env = dict(base, RANK=str(rank), MULTISLICE_MODE="step")
        procs.append(subprocess.Popen(
            [sys.executable, MS_WORKER], env=env, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append((p.returncode, out, err))

    for rank, (rc, out, err) in enumerate(outs):
        assert rc == 0, f"rank {rank} rc={rc}\n{err[-3000:]}"
        assert f"RANK{rank} HIERARCHICAL_ALLREDUCE_OK" in out
        assert f"RANK{rank} HIER_FLAT_PARITY_OK" in out

    losses = [re.search(r"LOSS ([0-9.]+)", out).group(1)
              for _, out, _ in outs]
    assert losses[0] == losses[1], losses


@pytest.mark.slow
def test_two_process_preemption_rescue():
    """SIGTERM delivered to ONE slice mid-run: boundary consensus makes
    BOTH processes save the rescue checkpoint and exit with code 17, and
    the checkpoint (plus run_shape.json) is loadable afterwards."""
    base = _two_process_env()
    save_dir = tempfile.mkdtemp()

    procs = []
    for rank in range(2):
        env = dict(base, RANK=str(rank), MULTISLICE_MODE="preempt",
                   MULTISLICE_SAVE_DIR=save_dir)
        procs.append(subprocess.Popen(
            [sys.executable, MS_WORKER], env=env, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))

    # watch rank 0's output for training progress, then preempt ONLY it
    lines = []
    deadline = time.monotonic() + 300
    try:
        for line in procs[0].stdout:
            lines.append(line)
            if re.search(r"RANK0 STEP [3-9]", line):
                procs[0].send_signal(signal.SIGTERM)
                break
            if time.monotonic() > deadline:
                raise TimeoutError("no training progress:\n"
                                   + "".join(lines)[-3000:])
        outs = []
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append((p.returncode, "".join(lines) + out
                         if p is procs[0] else out))
    except Exception:
        for q in procs:
            q.kill()
        raise

    # the whole fleet honored the consensus: rescue save + exit 17
    for rank, (rc, out) in enumerate(outs):
        assert rc == 17, f"rank {rank} rc={rc}\n{out[-3000:]}"
        assert "exiting on termination signal" in out, out[-3000:]

    # rescue checkpoint is loadable (and records the fleet shape)
    from megatron_llm_tpu import checkpointing, multislice
    it, release = checkpointing.read_tracker(save_dir)
    assert it and it >= 1 and not release
    params, _, meta = checkpointing.load_checkpoint(save_dir)
    assert meta["iteration"] == it
    assert params is not None
    shape = multislice.read_run_shape(save_dir)
    assert shape is not None
    assert shape["num_slices"] == 2 and shape["processes"] == 2
