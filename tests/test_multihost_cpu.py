"""REAL multi-host integration: two OS processes with one CPU device
each, rendezvoused by jax.distributed over localhost (gloo collectives)
through the torchrun-style env contract — upgrading the multi-host
evidence from single-process fakes to an actual 2-process run of
initialize_distributed -> mesh -> place_host_batch -> dp=2 train step
-> cross-host checksum (incl. a real divergence catch)."""

import os
import re
import socket
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "_multihost_worker.py")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_dp_train_step():
    port = _free_port()
    base = dict(os.environ)
    base.pop("PALLAS_AXON_POOL_IPS", None)
    base["JAX_PLATFORMS"] = "cpu"
    # one device per process: drop the 8-virtual-device conftest flags
    base["XLA_FLAGS"] = " ".join(
        f for f in base.get("XLA_FLAGS", "").split()
        if "host_platform_device_count" not in f)
    base.update(MASTER_ADDR="127.0.0.1", MASTER_PORT=str(port),
                WORLD_SIZE="2")

    procs = []
    for rank in range(2):
        env = dict(base, RANK=str(rank))
        procs.append(subprocess.Popen(
            [sys.executable, WORKER], env=env, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append((p.returncode, out, err))

    for rank, (rc, out, err) in enumerate(outs):
        assert rc == 0, f"rank {rank} rc={rc}\n{err[-3000:]}"
        assert f"RANK{rank} CHECKSUM_OK" in out
        assert f"RANK{rank} DIVERGENCE_CAUGHT" in out

    # data-parallel consistency: both processes computed the same loss
    losses = [re.search(r"LOSS ([0-9.]+)", out).group(1)
              for _, out, _ in outs]
    assert losses[0] == losses[1], losses
