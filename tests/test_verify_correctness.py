"""End-to-end run of the verify_correctness harness (reference:
verify_correctness.py + tests/test_llama_weights.py): HF golden model ->
converted release checkpoint -> CLI comparison passes within tolerance."""

import os
import subprocess
import sys

import numpy as np
import pytest

torch = pytest.importorskip("torch")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_verify_correctness_cli(tmp_path):
    from transformers import LlamaConfig, LlamaForCausalLM

    from megatron_llm_tpu import checkpointing
    from weights_conversion.hf_to_megatron import convert_llama_family

    torch.manual_seed(0)
    hf_cfg = LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=176,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rms_norm_eps=1e-5,
        tie_word_embeddings=False)
    hf = LlamaForCausalLM(hf_cfg).eval()
    hf_dir = tmp_path / "hf"
    hf.save_pretrained(str(hf_dir))

    params, config = convert_llama_family(hf)
    config["model_name"] = "llama2"
    ck_dir = tmp_path / "ck"
    checkpointing.save_checkpoint(str(ck_dir), 0, params, args=config,
                                  release=True)

    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "verify_correctness.py"),
         "--model_name=llama2", f"--load={ck_dir}",
         f"--huggingface_path={hf_dir}", "--iters=2", "--batch=1",
         "--seq_length=16"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert " OK" in proc.stdout
    # the harness actually measured something
    assert "mean max-abs logits error" in proc.stdout
