"""Context-parallel ring attention tests: exactness vs full attention at
the op level and full-model loss/grad parity under a cp>1 mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from megatron_llm_tpu import topology
from megatron_llm_tpu.models.llama import LlamaModel, llama_config
from megatron_llm_tpu.models.mistral import mistral_config
from megatron_llm_tpu.models.gpt import GPTModel
from megatron_llm_tpu.ops.pallas.flash_attention import _reference_attention
from megatron_llm_tpu.parallel import sharding as sh
from megatron_llm_tpu.parallel.ring_attention import context_parallel_attention


def _qkv(b=2, s=128, nh=4, ng=2, d=32, seed=0):
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(b, s, nh, d).astype(np.float32)) * 0.3
    k = jnp.asarray(rng.randn(b, s, ng, d).astype(np.float32)) * 0.3
    v = jnp.asarray(rng.randn(b, s, ng, d).astype(np.float32)) * 0.3
    return q, k, v


@pytest.mark.parametrize("window", [None, 48])
def test_ring_matches_full_attention(utils, window):
    utils.initialize_model_parallel(tp=1, pp=1, cp=4)
    q, k, v = _qkv()
    ref = _reference_attention(q, k, v, True, window, 0.125)
    out = jax.jit(
        lambda q, k, v: context_parallel_attention(
            q, k, v, causal=True, sliding_window=window, softmax_scale=0.125
        )
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_gradients(utils):
    utils.initialize_model_parallel(tp=1, pp=1, cp=4)
    q, k, v = _qkv(s=64)

    def loss_ref(q, k, v):
        return (_reference_attention(q, k, v, True, None, 0.125) ** 2).sum()

    def loss_ring(q, k, v):
        return (context_parallel_attention(
            q, k, v, causal=True, softmax_scale=0.125) ** 2).sum()

    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    gg = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(gr, gg):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-5)


def test_model_loss_parity_under_cp(utils):
    """Full llama forward under cp=4 (+dp=2) equals the unsharded loss —
    sequence sharding + ring attention end to end."""
    cfg = llama_config("tiny", seq_length=64, max_position_embeddings=64,
                       padded_vocab_size=128)
    model = LlamaModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, 128, (2, 64)))
    labels = jnp.roll(tokens, -1, axis=1)

    base = model(params, tokens, labels=labels, train=False)

    mesh = utils.initialize_model_parallel(tp=1, pp=1, cp=4)
    ps = sh.shard_params(params, model.param_specs(params))
    dsh = NamedSharding(mesh, P("dp", "cp"))
    t = jax.device_put(tokens, dsh)
    l = jax.device_put(labels, dsh)
    out = jax.jit(lambda p, t, l: model(p, t, labels=l, train=False))(ps, t, l)
    np.testing.assert_allclose(np.asarray(out), np.asarray(base), atol=3e-5)


def test_model_cp_with_tp(utils):
    """cp=2 x tp=2 x dp=2 with sliding window (mistral-style)."""
    cfg = mistral_config("tiny", seq_length=64, max_position_embeddings=64,
                         padded_vocab_size=128, sliding_window_size=32)

    class _M(GPTModel):
        pass

    model = _M(cfg)
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(1)
    tokens = jnp.asarray(rng.randint(0, 128, (2, 64)))
    labels = jnp.roll(tokens, -1, axis=1)
    base = model(params, tokens, labels=labels, train=False)

    mesh = utils.initialize_model_parallel(tp=2, pp=1, cp=2)
    ps = sh.shard_params(params, model.param_specs(params))
    dsh = NamedSharding(mesh, P("dp", "cp"))
    out = jax.jit(lambda p, t, l: model(p, t, labels=l, train=False))(
        ps, jax.device_put(tokens, dsh), jax.device_put(labels, dsh)
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(base), atol=3e-5)


@pytest.mark.parametrize("window", [None, 48])
def test_ring_q_chunked_matches_full(utils, window):
    """q_chunk_size < s_local (the long-context memory mode: per-step
    scores shrink from [s,s] to [qc,s]) is bit-for-math identical."""
    utils.initialize_model_parallel(tp=1, pp=1, cp=4)
    q, k, v = _qkv()                                  # local s = 32
    ref = _reference_attention(q, k, v, True, window, 0.125)
    out = jax.jit(
        lambda q, k, v: context_parallel_attention(
            q, k, v, causal=True, sliding_window=window,
            softmax_scale=0.125, q_chunk_size=8)
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_q_chunked_gradients(utils):
    utils.initialize_model_parallel(tp=1, pp=1, cp=4)
    q, k, v = _qkv(s=64)                              # local s = 16

    def loss_ref(q, k, v):
        return (_reference_attention(q, k, v, True, None, 0.125) ** 2).sum()

    def loss_ring(q, k, v):
        return (context_parallel_attention(
            q, k, v, causal=True, softmax_scale=0.125,
            q_chunk_size=4) ** 2).sum()

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)
