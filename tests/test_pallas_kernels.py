"""Pallas TPU kernel tests (interpret mode on CPU).

The reference tests its CUDA kernels against torch reference math
(``megatron/fused_kernels/tests/test_fused_kernels.py``); same strategy
here: each kernel vs the jnp reference implementation, fwd and bwd.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import megatron_llm_tpu.ops.pallas.flash_attention as F
import megatron_llm_tpu.ops.pallas.layernorm as LN
import megatron_llm_tpu.ops.pallas.rmsnorm as R
from megatron_llm_tpu.ops.layernorm import layer_norm, rms_norm


@pytest.fixture(autouse=True)
def _interpret():
    F._INTERPRET = True
    R._INTERPRET = True
    LN._INTERPRET = True
    yield
    F._INTERPRET = False
    R._INTERPRET = False
    LN._INTERPRET = False


def _qkv(b=2, s=128, nh=4, ng=2, d=64, seed=0):
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(b, s, nh, d).astype(np.float32)) * 0.3
    k = jnp.asarray(rng.randn(b, s, ng, d).astype(np.float32)) * 0.3
    v = jnp.asarray(rng.randn(b, s, ng, d).astype(np.float32)) * 0.3
    return q, k, v


@pytest.mark.parametrize("window", [None, 32])
def test_flash_attention_fwd(window):
    q, k, v = _qkv()
    ref = F._reference_attention(q, k, v, True, window, 0.125)
    out = F.flash_attention(q, k, v, causal=True, sliding_window=window,
                            softmax_scale=0.125, block_q=64, block_k=64)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


@pytest.mark.parametrize("window", [None, 32])
def test_flash_attention_bwd(window):
    q, k, v = _qkv()

    def loss(fn):
        return lambda *a: (fn(*a) ** 2).sum()

    ref_fn = loss(lambda q, k, v: F._reference_attention(
        q, k, v, True, window, 0.125))
    fa_fn = loss(lambda q, k, v: F.flash_attention(
        q, k, v, causal=True, sliding_window=window, softmax_scale=0.125,
        block_q=64, block_k=64))
    gr = jax.grad(ref_fn, argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(fa_fn, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gf):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


@pytest.mark.parametrize("window", [None, 32])
def test_flash_bwd_fused_matches_two_kernel(window, monkeypatch):
    """The fused single-pass backward must agree with the two-kernel
    structure bit-for-bit-ish on every input grad (GQA grouping incl.)."""
    q, k, v = _qkv(ng=2)

    def grads():
        fn = lambda q, k, v: (F.flash_attention(
            q, k, v, causal=True, sliding_window=window,
            softmax_scale=0.125, block_q=64, block_k=64) ** 2).sum()
        return jax.grad(fn, argnums=(0, 1, 2))(q, k, v)

    monkeypatch.setattr(F, "FUSED_BACKWARD", True)
    g_fused = grads()
    monkeypatch.setattr(F, "FUSED_BACKWARD", False)
    g_two = grads()
    for a, b in zip(g_fused, g_two):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_flash_bwd_non_divisible_uses_fallback():
    """seq % block != 0 routes to the two-kernel backward (the fused dq
    slab assumes complete q blocks) and still matches reference grads."""
    q, k, v = _qkv(s=96)
    fn = lambda q, k, v: (F.flash_attention(
        q, k, v, causal=True, softmax_scale=0.125,
        block_q=64, block_k=64) ** 2).sum()
    ref = lambda q, k, v: (F._reference_attention(
        q, k, v, True, None, 0.125) ** 2).sum()
    gf = jax.grad(fn, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_flash_attention_non_divisible_seq():
    q, k, v = _qkv(s=96)
    ref = F._reference_attention(q, k, v, True, None, 0.125)
    out = F.flash_attention(q, k, v, causal=True, softmax_scale=0.125,
                            block_q=64, block_k=64)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_flash_attention_mqa():
    q, k, v = _qkv(ng=1)
    ref = F._reference_attention(q, k, v, True, None, 0.125)
    out = F.flash_attention(q, k, v, causal=True, softmax_scale=0.125,
                            block_q=64, block_k=64)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_flash_attention_bf16():
    q, k, v = (t.astype(jnp.bfloat16) for t in _qkv())
    ref = F._reference_attention(q, k, v, True, None, 0.125)
    out = F.flash_attention(q, k, v, causal=True, softmax_scale=0.125,
                            block_q=64, block_k=64)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=2e-2
    )


def test_fused_rmsnorm_fwd_bwd():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(4, 16, 128).astype(np.float32))
    s = jnp.asarray(rng.randn(128).astype(np.float32))
    np.testing.assert_allclose(
        np.asarray(R.fused_rms_norm(x, s)), np.asarray(rms_norm(x, s)),
        atol=1e-6,
    )
    g_ref = jax.grad(lambda a, b: (rms_norm(a, b) ** 2).sum(),
                     argnums=(0, 1))(x, s)
    g = jax.grad(lambda a, b: (R.fused_rms_norm(a, b) ** 2).sum(),
                 argnums=(0, 1))(x, s)
    np.testing.assert_allclose(np.asarray(g[0]), np.asarray(g_ref[0]),
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(g[1]), np.asarray(g_ref[1]),
                               atol=2e-4)


def test_fused_rmsnorm_bf16_io():
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(8, 128).astype(np.float32)).astype(jnp.bfloat16)
    s = jnp.ones((128,), jnp.float32)
    out = R.fused_rms_norm(x, s)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32),
        np.asarray(rms_norm(x, s), np.float32), atol=2e-2,
    )


@pytest.mark.parametrize("n,h", [(256, 128), (100, 256)])
def test_fused_layer_norm_fwd_bwd(n, h):
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(n, h).astype(np.float32))
    s = jnp.asarray(1.0 + 0.1 * rng.randn(h).astype(np.float32))
    b = jnp.asarray(0.1 * rng.randn(h).astype(np.float32))
    g = jnp.asarray(rng.randn(n, h).astype(np.float32))

    y = LN.fused_layer_norm(x, s, b)
    ref = layer_norm(x, s, b, eps=1e-5, fp32_compute=True)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), atol=1e-5)

    gp = jax.grad(lambda x, s, b: (LN.fused_layer_norm(x, s, b) * g).sum(),
                  argnums=(0, 1, 2))(x, s, b)
    gr = jax.grad(
        lambda x, s, b: (layer_norm(x, s, b, eps=1e-5,
                                    fp32_compute=True) * g).sum(),
        argnums=(0, 1, 2))(x, s, b)
    for a, r in zip(gp, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r), atol=2e-4)


class TestShardedFlashAttention:
    """sharded_flash_attention: the Mosaic kernel under a mesh must run
    inside an explicit shard_map (GSPMD cannot auto-partition custom
    calls — surfaced by the round-5 AOT compiles); logits must match the
    unsharded kernel exactly."""

    def test_tp_sharded_matches_plain(self, utils):
        q, k, v = _qkv(b=2, s=128, nh=4, ng=2, d=64)
        want = F.flash_attention(q, k, v, causal=True, softmax_scale=0.125,
                                 block_q=64, block_k=64)
        utils.initialize_model_parallel(tp=2)
        try:
            # jit: subset-manual shard_map (tp manual, dp/pp/cp auto)
            # requires a jit tracing context, which the model always has
            got = jax.jit(lambda q, k, v: F.sharded_flash_attention(
                q, k, v, causal=True, softmax_scale=0.125,
                block_q=64, block_k=64))(q, k, v)
        finally:
            utils.destroy_model_parallel()
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-6)

    def test_tp_sharded_grads_match(self, utils):
        q, k, v = _qkv(b=2, s=128, nh=4, ng=2, d=64)

        def loss(fn):
            return lambda *a: (fn(*a) ** 2).sum()

        plain = jax.grad(loss(lambda q, k, v: F.flash_attention(
            q, k, v, causal=True, softmax_scale=0.125,
            block_q=64, block_k=64)), argnums=(0, 1, 2))(q, k, v)
        utils.initialize_model_parallel(tp=2)
        try:
            sharded = jax.jit(jax.grad(
                loss(lambda q, k, v: F.sharded_flash_attention(
                    q, k, v, causal=True, softmax_scale=0.125,
                    block_q=64, block_k=64)),
                argnums=(0, 1, 2)))(q, k, v)
        finally:
            utils.destroy_model_parallel()
        for a, b in zip(sharded, plain):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5)

    def test_mqa_replicated_kv(self, utils):
        # MQA (ng=1): q heads shard over tp, kv replicate — the local
        # q-per-group ratio stays consistent
        q, k, v = _qkv(b=2, s=128, nh=4, ng=1, d=64)
        want = F.flash_attention(q, k, v, causal=True, softmax_scale=0.125,
                                 block_q=64, block_k=64)
        utils.initialize_model_parallel(tp=2)
        try:
            got = jax.jit(lambda q, k, v: F.sharded_flash_attention(
                q, k, v, causal=True, softmax_scale=0.125,
                block_q=64, block_k=64))(q, k, v)
        finally:
            utils.destroy_model_parallel()
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-6)

    def test_indivisible_dims_xla_fallback(self, utils, monkeypatch):
        # nh=6 on tp=4 AND b=3 on dp=2: neither heads nor batch can
        # shard, but auto axes exist — the wrapper must route to the
        # partitionable XLA path (NOT the raw pallas call, which GSPMD
        # can't partition) and stay numerically exact.  A spy pins the
        # routing: parity alone can't distinguish the paths.
        q, k, v = _qkv(b=3, s=128, nh=6, ng=2, d=64)
        want = F._reference_attention(q, k, v, True, None, 0.125)
        called = {}
        real_ref = F._reference_attention

        def spy(*a, **kw):
            called["ref"] = True
            return real_ref(*a, **kw)

        monkeypatch.setattr(F, "_reference_attention", spy)
        utils.initialize_model_parallel(tp=4)
        try:
            got = jax.jit(lambda q, k, v: F.sharded_flash_attention(
                q, k, v, causal=True, softmax_scale=0.125))(q, k, v)
        finally:
            utils.destroy_model_parallel()
        assert called.get("ref"), "xla fallback path was not taken"
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-5)

    def test_no_mesh_plain_path(self):
        q, k, v = _qkv()
        want = F.flash_attention(q, k, v, causal=True, softmax_scale=0.125,
                                 block_q=64, block_k=64)
        got = F.sharded_flash_attention(
            q, k, v, causal=True, softmax_scale=0.125,
            block_q=64, block_k=64)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-7)
