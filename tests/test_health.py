"""Model-health observatory (megatron_llm_tpu/health.py): per-group
grad/param/update norms vs a hand-computed NumPy reference, offender
diagnosis, the derived --log_params_norm partition, zero recompiles after
warmup with stats enabled (mixed with eval), nan@k localization naming
the poisoned group in the rewind log + flight-recorder dump,
pipeline-parallel stats parity with the single-program path, and the
tools/health_report.py summarizer."""

import argparse
import json
import math
import os
import subprocess
import sys

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from megatron_llm_tpu import global_vars, health, telemetry
from megatron_llm_tpu.config import ParallelConfig, TrainConfig
from megatron_llm_tpu.global_vars import get_counters
from megatron_llm_tpu.models.llama import LlamaModel, llama_config
from megatron_llm_tpu.optimizer import MegatronOptimizer
from megatron_llm_tpu.optimizer.optimizer import global_grad_norm
from megatron_llm_tpu.parallel import sharding as sh
from megatron_llm_tpu.parallel.pipeline import (
    build_pipeline_grad_fn,
    build_pipeline_train_step,
)
from megatron_llm_tpu.resilience import (
    FaultInjector,
    ResilienceConfig,
    ResilienceManager,
    recovery_counters,
)
from megatron_llm_tpu.telemetry import (
    TELEMETRY_SCHEMA_VERSION,
    build_telemetry,
)
from megatron_llm_tpu.training import pretrain

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_telemetry_state():
    global_vars.reset_counters()
    telemetry.install_stream(None)
    yield
    telemetry.install_stream(None)
    global_vars.reset_counters()


def _setup(utils):
    cfg = llama_config("tiny", seq_length=16, max_position_embeddings=16,
                       padded_vocab_size=64, num_layers=2, hidden_size=32,
                       num_attention_heads=4, ffn_hidden_size=64)
    model = LlamaModel(cfg)
    utils.initialize_model_parallel(tp=1)
    params = model.init(jax.random.PRNGKey(0))
    params = sh.shard_params(params, model.param_specs(params))

    def it():
        rng = np.random.RandomState(0)
        while True:
            toks = jnp.asarray(rng.randint(0, 64, size=(1, 8, 16)))
            yield {
                "tokens": toks,
                "labels": jnp.roll(toks, -1, axis=-1),
                "loss_mask": jnp.ones_like(toks, jnp.float32),
            }

    return model, params, it


def _tc(iters):
    return TrainConfig(micro_batch_size=8, global_batch_size=8,
                       train_iters=iters, lr=1e-2, optimizer="adam", seed=3)


def _telemetry_args(**kw):
    """A parsed-args stand-in with the telemetry group's fields."""
    base = dict(structured_log_dir=None, flight_recorder_size=64,
                profile=False, profile_step_start=2, profile_step_end=3,
                profile_dir=None, profiler_port=None, trace_dir=None,
                trace_buffer_size=100_000, straggler_threshold=1.5)
    base.update(kw)
    return argparse.Namespace(**base)


# ---------------------------------------------------------------------------
# Grouping + on-device stats vs a NumPy reference
# ---------------------------------------------------------------------------

def test_layer_group_names_synthetic_and_model():
    # synthetic tree with the canonical top-level layout
    tree = {
        "embedding": {"w": jnp.zeros((4, 5))},
        "lm_head": {"w": jnp.zeros((5, 4))},
        "transformer": {
            "final_norm": {"scale": jnp.zeros((5,))},
            "layers": {"w": jnp.zeros((3, 5, 5))},
        },
    }
    assert health.layer_group_names(tree) == [
        "embedding", "layer_000", "layer_001", "layer_002",
        "lm_head", "final_norm"]

    # a real model's param tree: embedding first, one group per layer row
    cfg = llama_config("tiny", seq_length=16, max_position_embeddings=16,
                       padded_vocab_size=64, num_layers=2, hidden_size=32,
                       num_attention_heads=4, ffn_hidden_size=64)
    params = LlamaModel(cfg).init(jax.random.PRNGKey(0))
    names = health.layer_group_names(params)
    assert names[:3] == ["embedding", "layer_000", "layer_001"]
    assert "final_norm" in names
    assert len(names) == len(set(names))


def test_compute_layer_stats_matches_numpy():
    rng = np.random.RandomState(7)

    def tree(scale=1.0):
        return {
            "embedding": {"w": rng.randn(4, 5).astype(np.float32) * scale},
            "transformer": {
                "final_norm": {"s": rng.randn(5).astype(np.float32) * scale},
                "layers": {
                    "a": rng.randn(3, 2, 5).astype(np.float32) * scale,
                    "b": rng.randn(3, 4).astype(np.float32) * scale,
                },
            },
        }

    params, grads, updates = tree(), tree(0.1), tree(0.01)
    grads["embedding"]["w"][0, 0] = np.inf       # 1 bad entry in embedding
    grads["transformer"]["layers"]["a"][1, 0, :2] = np.nan   # 2 in layer_001

    names = health.layer_group_names(params)
    assert names == ["embedding", "layer_000", "layer_001", "layer_002",
                     "final_norm"]
    stats = jax.jit(health.compute_layer_stats)(
        jax.tree_util.tree_map(jnp.asarray, params),
        jax.tree_util.tree_map(jnp.asarray, grads),
        jax.tree_util.tree_map(jnp.asarray, updates))

    def ref_norm(t, group):
        if group == "embedding":
            arrs = [t["embedding"]["w"]]
        elif group == "final_norm":
            arrs = [t["transformer"]["final_norm"]["s"]]
        else:
            i = int(group.split("_")[1])
            arrs = [t["transformer"]["layers"]["a"][i],
                    t["transformer"]["layers"]["b"][i]]
        return math.sqrt(sum(float(np.sum(np.square(a.astype(np.float64))))
                             for a in arrs))

    for i, g in enumerate(names):
        np.testing.assert_allclose(float(stats["param_norm"][i]),
                                   ref_norm(params, g), rtol=1e-5,
                                   err_msg=f"param_norm[{g}]")
        np.testing.assert_allclose(float(stats["update_norm"][i]),
                                   ref_norm(updates, g), rtol=1e-5,
                                   err_msg=f"update_norm[{g}]")
    # grad norms: poisoned groups go non-finite, the rest match the ref
    assert not math.isfinite(float(stats["grad_norm"][0]))    # embedding
    assert math.isnan(float(stats["grad_norm"][2]))           # layer_001
    for i in (1, 3, 4):
        np.testing.assert_allclose(float(stats["grad_norm"][i]),
                                   ref_norm(grads, names[i]), rtol=1e-5,
                                   err_msg=f"grad_norm[{names[i]}]")
    assert [int(v) for v in stats["nonfinite_grads"]] == [1, 0, 2, 0, 0]


def test_record_encoding_and_offender_diagnosis():
    names = ["embedding", "layer_000", "layer_001", "lm_head"]
    stats = {
        "grad_norm": np.array([1.0, 1.0, np.nan, 100.0]),
        "param_norm": np.array([10.0, 10.0, 10.0, 0.0]),
        "update_norm": np.array([0.01, 0.02, np.inf, 0.5]),
        "nonfinite_grads": np.array([0, 0, 3, 0]),
    }
    rec = health.to_record(names, stats)
    assert rec["groups"] == names
    assert rec["grad_norm"][2] == "nan" and rec["update_norm"][2] == "inf"
    json.dumps(rec)    # plain JSON despite the non-finites
    assert rec["update_ratio"][0] == pytest.approx(1e-3)
    assert rec["update_ratio"][2] is None      # non-finite update norm
    assert rec["update_ratio"][3] is None      # zero param norm
    assert math.isnan(health.record_value("nan"))
    assert health.record_value("-inf") == -math.inf
    assert health.record_value(2.5) == 2.5
    assert health.derived_params_norm(rec) == pytest.approx(
        math.sqrt(3 * 10.0 ** 2))

    off = health.find_offenders(rec)
    assert off["first_nonfinite"] == "layer_001"
    assert off["nonfinite"] == ["layer_001"]
    assert [o["group"] for o in off["outliers"]] == ["lm_head"]
    assert off["outliers"][0]["ratio_to_median"] == pytest.approx(100.0)
    desc = health.describe_offenders(off)
    assert "layer_001" in desc and "lm_head" in desc
    # a clean record diagnoses nothing
    clean = health.to_record(names, {
        "grad_norm": np.ones(4), "param_norm": np.ones(4),
        "nonfinite_grads": np.zeros(4, np.int32)})
    assert health.describe_offenders(health.find_offenders(clean)) is None


def test_derived_params_norm_partitions_global_norm():
    cfg = llama_config("tiny", seq_length=16, max_position_embeddings=16,
                       padded_vocab_size=64, num_layers=2, hidden_size=32,
                       num_attention_heads=4, ffn_hidden_size=64)
    params = LlamaModel(cfg).init(jax.random.PRNGKey(1))
    names = health.layer_group_names(params)
    stats = jax.jit(health.compute_layer_stats)(params, params)
    rec = health.to_record(names, jax.device_get(stats))
    assert health.derived_params_norm(rec) == pytest.approx(
        float(global_grad_norm(params)), rel=1e-5)


# ---------------------------------------------------------------------------
# In-loop: zero recompiles, JSONL schema, nan@k localization
# ---------------------------------------------------------------------------

def test_pretrain_layer_stats_zero_recompiles(utils, tmp_path):
    """The acceptance run: stats on (interval 2), --log_params_norm
    derived from the partition, eval mixed in — after warmup the step
    never recompiles, and the JSONL stream carries the per-group record
    exactly at stats boundaries."""
    model, params, it = _setup(utils)
    d = str(tmp_path)
    tel = build_telemetry(
        _telemetry_args(structured_log_dir=d, trace_dir=d), model)
    seen = {}
    try:
        pretrain(model, params, _tc(6), ParallelConfig(), it(),
                 log_interval=1, log_layer_stats_interval=2,
                 log_params_norm=True, telemetry=tel,
                 eval_iterator=it(), eval_interval=3, eval_iters=2,
                 on_metrics=lambda i, m: seen.setdefault(i, m))
    finally:
        tel.close()
    assert int(get_counters().get("recompiles", 0)) == 0

    records = [json.loads(l) for l in
               open(os.path.join(d, "telemetry.jsonl"))]
    assert [r["iteration"] for r in records] == [1, 2, 3, 4, 5, 6]
    for r in records:
        assert r["schema"] == TELEMETRY_SCHEMA_VERSION
        assert r["recompiles"] == 0
        ls = r.get("layer_stats")
        assert (ls is not None) == (r["iteration"] % 2 == 0)
        if ls is None:
            continue
        G = len(ls["groups"])
        assert ls["groups"][:3] == ["embedding", "layer_000", "layer_001"]
        for key in ("grad_norm", "param_norm", "update_norm",
                    "update_ratio", "nonfinite_grads"):
            assert len(ls[key]) == G
        assert all(n == 0 for n in ls["nonfinite_grads"])
        assert all(health.record_value(v) > 0 for v in ls["param_norm"])
        # the LR schedule decays to 0 at the final iteration, so the last
        # boundary's update ratios are legitimately 0.0
        assert all(r is None or r >= 0 for r in ls["update_ratio"])
    # --log_params_norm was served every boundary (derived, no extra jit)
    for i, m in seen.items():
        pn = float(m["params norm"])
        assert math.isfinite(pn) and pn > 0


def test_nan_injection_names_offending_layer(utils, tmp_path, capsys):
    """nan@3 poisons every group's grads (via the loss mask): the bad
    check announces suspect layers, the rewind message names them, and
    the flight-recorder dump carries the health record + diagnosis."""
    model, params, it = _setup(utils)
    d = str(tmp_path)
    tel = build_telemetry(_telemetry_args(structured_log_dir=d), model)
    rm = ResilienceManager(
        ResilienceConfig(snapshot_interval=1, patience=1, spike_factor=0),
        injector=FaultInjector.from_spec("nan@3"))
    try:
        pretrain(model, params, _tc(6), ParallelConfig(), it(),
                 log_interval=1, log_layer_stats_interval=1,
                 telemetry=tel, resilience=rm)
    finally:
        rm.close()
        tel.close()
    assert recovery_counters()["rewinds"] == 1
    out = capsys.readouterr().out
    assert "suspect layers at iteration 3" in out
    assert "first: embedding" in out
    assert "suspect layers:" in out    # the rewind line repeats the blame

    dump = os.path.join(d, "flight_recorder.json")
    assert os.path.exists(dump)
    payload = json.loads(open(dump).read())
    assert payload["reason"].startswith("rewind #1")
    assert "embedding" in payload["reason"]
    healths = [r for r in payload["records"] if r.get("kind") == "health"]
    assert healths and healths[-1]["iteration"] == 3
    assert healths[-1]["offenders"]["first_nonfinite"] == "embedding"
    assert healths[-1]["layer_stats"]["groups"][0] == "embedding"


# ---------------------------------------------------------------------------
# Pipeline-parallel parity
# ---------------------------------------------------------------------------

def test_pipeline_layer_stats_parity(utils):
    """Per-group stats computed on the pipeline grad fn's gradients match
    the single-program reference, and the pipelined train step emits the
    same fixed-shape stats pytree as build_train_step."""
    cfg = llama_config("tiny", num_layers=4, seq_length=32,
                       max_position_embeddings=32, padded_vocab_size=128)
    model = LlamaModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    toks = jnp.asarray(rng.randint(0, 128, (2, 2, 32)))
    batch = {"tokens": toks, "labels": jnp.roll(toks, -1, axis=-1),
             "loss_mask": jnp.ones((2, 2, 32), jnp.float32)}

    def unpiped_loss(p):
        tot, den = 0.0, 0.0
        for i in range(2):
            lt = model(p, batch["tokens"][i], labels=batch["labels"][i],
                       train=False)
            tot, den = tot + lt.sum(), den + lt.size
        return tot / den

    g_base = jax.grad(unpiped_loss)(params)
    names = health.layer_group_names(params)
    ref = jax.device_get(jax.jit(health.compute_layer_stats)(params, g_base))

    utils.initialize_model_parallel(tp=1, pp=2)
    ps = sh.shard_params(params, model.param_specs(params))
    grad_fn = build_pipeline_grad_fn(model, 2, 2)
    _, g_pipe = jax.jit(lambda p, b, k: grad_fn(p, b, k, train=False))(
        ps, batch, jax.random.PRNGKey(0))
    got = jax.device_get(jax.jit(health.compute_layer_stats)(ps, g_pipe))
    assert names[:5] == ["embedding", "layer_000", "layer_001",
                         "layer_002", "layer_003"]
    np.testing.assert_allclose(got["grad_norm"], ref["grad_norm"],
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(got["param_norm"], ref["param_norm"],
                               rtol=1e-5)
    assert [int(v) for v in got["nonfinite_grads"]] == [0] * len(names)

    # the pipelined train step surfaces the same pytree shape
    tc = TrainConfig(micro_batch_size=2, global_batch_size=4, lr=1e-3)
    pc = ParallelConfig(pipeline_model_parallel_size=2,
                        data_parallel_size=4)
    opt = MegatronOptimizer(tc)
    opt_state = opt.init(ps)
    step = build_pipeline_train_step(model, opt, pc, 2, layer_stats=True)
    _, _, m = step(ps, opt_state, batch, jax.random.PRNGKey(0), 1e-3, 0.0)
    ls = jax.device_get(m["layer_stats"])
    for key in ("grad_norm", "param_norm", "update_norm",
                "nonfinite_grads"):
        assert ls[key].shape == (len(names),)
    rec = health.to_record(names, ls)
    assert health.derived_params_norm(rec) > 0
    assert all(n == 0 for n in rec["nonfinite_grads"])


# ---------------------------------------------------------------------------
# tools/health_report.py + telemetry_report layer-stats aggregates
# ---------------------------------------------------------------------------

def _synthetic_stream(path):
    groups = ["embedding", "layer_000", "layer_001", "lm_head"]

    def rec(it, **ls):
        return {"schema": 3, "kind": "log", "iteration": it,
                "lm_loss": 2.0, "step_time_secs": 0.01,
                "layer_stats": {"groups": groups, **ls}}

    records = [
        # schema-2-era record (no layer_stats) parses alongside
        {"schema": 2, "kind": "log", "iteration": 5, "lm_loss": 2.1,
         "step_time_secs": 0.01},
        {"kind": "dispatch", "iteration": 9},    # non-log records skipped
        rec(10, grad_norm=[1.0, 1.1, 0.9, 1.05],
            param_norm=[10.0, 10.0, 10.0, 10.0],
            update_norm=[0.01, 0.01, 0.01, 0.01],
            update_ratio=[1e-3, 1e-3, 1e-3, 1e-3],
            nonfinite_grads=[0, 0, 0, 0]),
        rec(20, grad_norm=[1.0, 50.0, "nan", 1.0],
            param_norm=[10.0, 10.0, 10.0, 10.0],
            update_norm=[0.5, 0.01, "inf", 0.01],
            update_ratio=[0.05, 1e-3, None, 1e-3],
            nonfinite_grads=[0, 0, 4, 0]),
    ]
    with open(path, "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")
        f.write("{truncated\n")    # crash-torn final line is tolerated


def test_health_report_cli(tmp_path):
    stream = tmp_path / "telemetry.jsonl"
    _synthetic_stream(stream)

    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "health_report.py"),
         str(tmp_path)],
        capture_output=True, text=True, timeout=60, cwd=ROOT)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "layer-stats boundaries: 2" in r.stdout
    assert "NONFINITE" in r.stdout
    assert "GRAD>4xMED" in r.stdout
    assert "UPD-RATIO" in r.stdout
    assert "iteration 20: layer_001 (first: layer_001)" in r.stdout

    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "health_report.py"),
         str(stream), "--json"],
        capture_output=True, text=True, timeout=60, cwd=ROOT)
    assert r.returncode == 0, r.stdout + r.stderr
    doc = json.loads(r.stdout)
    assert doc["nan_events"] == [{"iteration": 20, "groups": ["layer_001"]}]
    by_group = {e["group"]: e for e in doc["table"]}
    assert by_group["layer_001"]["flags"] == ["NONFINITE"]
    assert "GRAD>4xMED" in by_group["layer_000"]["flags"]
    assert "UPD-RATIO" in by_group["embedding"]["flags"]
    assert by_group["lm_head"]["flags"] == []
    assert by_group["embedding"]["update_ratio_median"] == pytest.approx(
        0.5 * (1e-3 + 0.05))

    # --last trims to the newest boundaries
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "health_report.py"),
         str(stream), "--json", "--last", "1"],
        capture_output=True, text=True, timeout=60, cwd=ROOT)
    assert json.loads(r.stdout)["boundaries"] == 1

    r2 = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "health_report.py"),
         str(tmp_path / "missing.jsonl")],
        capture_output=True, text=True, timeout=60, cwd=ROOT)
    assert r2.returncode == 2

    # a stream with no layer_stats records exits 2 with a pointer
    bare = tmp_path / "bare.jsonl"
    bare.write_text(json.dumps({"kind": "log", "iteration": 1}) + "\n")
    r3 = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "health_report.py"),
         str(bare)],
        capture_output=True, text=True, timeout=60, cwd=ROOT)
    assert r3.returncode == 2
    assert "log_layer_stats_interval" in r3.stderr


def test_telemetry_report_layer_stats_aggregates(tmp_path):
    stream = tmp_path / "telemetry.jsonl"
    _synthetic_stream(stream)
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "telemetry_report.py"),
         str(stream)],
        capture_output=True, text=True, timeout=60, cwd=ROOT)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "worst update ratio 0.05" in r.stdout
    assert "NaN-layer events: 1" in r.stdout

    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "telemetry_report.py"),
         str(stream), "--json"],
        capture_output=True, text=True, timeout=60, cwd=ROOT)
    agg = json.loads(r.stdout)["aggregates"]
    assert agg["worst_update_ratio"] == pytest.approx(0.05)
    assert agg["nan_layer_events"] == 1
