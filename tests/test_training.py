"""End-to-end training smoke: loss decreases; checkpoint save/resume
reproduces the exact state (reference analogue: getting-started run +
checkpointing.py semantics)."""

import dataclasses
import shutil
import tempfile

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from megatron_llm_tpu import checkpointing, topology
from megatron_llm_tpu.config import ParallelConfig, TrainConfig
from megatron_llm_tpu.models.llama import LlamaModel, llama_config
from megatron_llm_tpu.optimizer import MegatronOptimizer
from megatron_llm_tpu.parallel import sharding as sh
from megatron_llm_tpu.training import build_train_step, pretrain


def _setup(utils, tp=2):
    cfg = llama_config("tiny", seq_length=32, max_position_embeddings=32,
                       padded_vocab_size=128)
    model = LlamaModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    mesh = utils.initialize_model_parallel(tp=tp)
    params = sh.shard_params(params, model.param_specs(params))
    rng = np.random.RandomState(0)
    fixed = jnp.asarray(rng.randint(0, 128, size=(2, 8, 32)))
    dsh = NamedSharding(mesh, P(None, "dp", None))

    def it():
        while True:
            toks = jax.device_put(fixed, dsh)
            yield {
                "tokens": toks,
                "labels": jnp.roll(toks, -1, axis=-1),
                "loss_mask": jax.device_put(jnp.ones_like(fixed, jnp.float32), dsh),
            }

    return cfg, model, params, mesh, it


def test_loss_decreases(utils):
    cfg, model, params, mesh, it = _setup(utils)
    tc = TrainConfig(micro_batch_size=2, global_batch_size=16, train_iters=12,
                     lr=1e-2, optimizer="adam", seed=3)
    pc = ParallelConfig(tensor_model_parallel_size=2, data_parallel_size=4,
                        sequence_parallel=True)
    losses = []
    params, opt_state, _ = pretrain(
        model, params, tc, pc, it(), log_interval=0,
        on_metrics=lambda i, m: losses.append(float(m["lm loss"])),
    )
    opt = MegatronOptimizer(tc)
    step = build_train_step(model, opt, pc, 2, forward_only=True)
    final = float(step(params, next(it()), None))
    assert final < 2.0, f"loss did not decrease: {final}"


def test_checkpoint_resume_exact(utils):
    cfg, model, params, mesh, it = _setup(utils)
    tc = TrainConfig(micro_batch_size=2, global_batch_size=16, train_iters=4,
                     lr=1e-3, optimizer="adam", seed=5)
    pc = ParallelConfig(tensor_model_parallel_size=2, data_parallel_size=4,
                        sequence_parallel=True)

    d = tempfile.mkdtemp()
    try:
        # run 2 iters, save, run 2 more
        p2, o2, _ = pretrain(model, params, dataclasses.replace(tc, train_iters=2),
                             pc, it(), log_interval=0)
        checkpointing.save_checkpoint(d, 2, p2, o2)
        p4a, _, _ = pretrain(model, p2, tc, pc, it(), log_interval=0,
                             start_iteration=2, opt_state=o2)

        # load from checkpoint and run the same 2 iters (abstract template:
        # shape/dtype/sharding metadata survives donation of p2's buffers)
        tmpl = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                           sharding=x.sharding), p2)
        pl, ol, meta = checkpointing.load_checkpoint(
            d, params_template=tmpl, opt_state_template=o2)
        assert meta["iteration"] == 2
        pl = sh.shard_params(pl, model.param_specs(pl))
        p4b, _, _ = pretrain(model, pl, tc, pc, it(), log_interval=0,
                             start_iteration=2, opt_state=ol)

        for a, b in zip(jax.tree_util.tree_leaves(p4a),
                        jax.tree_util.tree_leaves(p4b)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(jnp.asarray(b)),
                                       atol=1e-6)
    finally:
        shutil.rmtree(d)
