"""Multi-slice elastic runtime (multislice.py): slice mesh axis,
hierarchical ICI-then-DCN reduction parity, elastic resume across
dp x slice shapes, run-shape detection, and per-slice attribution.

Single-process coverage on the 8-virtual-device mesh; the slice axis
spanning a real process boundary is tests/test_multihost_cpu.py's job.
"""

import argparse
import json
import os
import shutil
import sys
import tempfile

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from megatron_llm_tpu import checkpointing, multislice, topology
from megatron_llm_tpu.config import ParallelConfig, TrainConfig
from megatron_llm_tpu.models.llama import LlamaModel, llama_config
from megatron_llm_tpu.optimizer import MegatronOptimizer
from megatron_llm_tpu.parallel import sharding as sh
from megatron_llm_tpu.tracing import StragglerDetector
from megatron_llm_tpu.training import build_train_step


# ---------------------------------------------------------------------------
# topology: the slice mesh axis
# ---------------------------------------------------------------------------

def test_slice_mesh_axis(utils):
    mesh = utils.initialize_model_parallel(num_slices=2)
    assert dict(mesh.shape) == {"slice": 2, "pp": 1, "dp": 4, "cp": 1,
                                "tp": 1}
    assert topology.get_num_slices() == 2
    assert topology.get_world_size() == 8
    assert topology.data_axes() == ("slice", "dp")
    assert multislice.host_slice_map(1, 2) == [0]   # one host, all slices


def test_single_slice_is_default(utils):
    utils.initialize_model_parallel()
    assert topology.get_num_slices() == 1
    assert topology.data_axes() == ("dp",)


def test_slice_divisibility_validated(utils):
    with pytest.raises(RuntimeError):
        utils.initialize_model_parallel(num_slices=3)    # 8 % 3 != 0
    with pytest.raises(RuntimeError):
        utils.initialize_model_parallel(tp=2, pp=2, num_slices=4)


def test_slice_with_model_parallel(utils):
    mesh = utils.initialize_model_parallel(tp=2, num_slices=2)
    assert mesh.shape["slice"] == 2
    assert mesh.shape["tp"] == 2
    assert mesh.shape["dp"] == 2


# ---------------------------------------------------------------------------
# hierarchical (ICI-then-DCN) reduction
# ---------------------------------------------------------------------------

def test_hierarchical_allreduce_matches_flat(utils):
    utils.initialize_model_parallel(num_slices=2)
    mesh = topology.get_mesh()
    # integer-valued floats: both reduction orders are exact, so the
    # staged result must be bit-identical to the flat one
    x = np.arange(8 * 5, dtype=np.float32).reshape(8, 5)
    xs = jax.device_put(x, NamedSharding(mesh, P(("slice", "dp"))))
    hier = np.asarray(multislice.hierarchical_allreduce(xs))
    flat = np.asarray(multislice.flat_allreduce(xs))
    np.testing.assert_array_equal(hier, flat)
    np.testing.assert_array_equal(hier, x.sum(0))


def _tiny_model():
    cfg = llama_config("tiny", num_layers=2, seq_length=32,
                       max_position_embeddings=32, padded_vocab_size=128)
    return LlamaModel(cfg)


def _global_batch(mesh, num_micro=2, gb=8, seed=0):
    rng = np.random.RandomState(seed)
    toks = jnp.asarray(
        rng.randint(0, 128, (num_micro, gb, 32)).astype(np.int32))
    dsh = NamedSharding(mesh, P(None, topology.data_axes(), None))
    return {
        "tokens": jax.device_put(toks, dsh),
        "labels": jax.device_put(jnp.roll(toks, -1, axis=-1), dsh),
        "loss_mask": jax.device_put(jnp.ones(toks.shape, jnp.float32), dsh),
    }


def test_train_step_parity_hierarchical_vs_flat(utils):
    """The staged slice-vmap forward must reproduce the flat GSPMD
    reduction: same loss, same grad norm, same updated params (up to
    reduction-order float noise)."""
    utils.initialize_model_parallel(num_slices=2)   # slice=2 x dp=4
    mesh = topology.get_mesh()
    model = _tiny_model()
    tc = TrainConfig(micro_batch_size=1, global_batch_size=16, lr=1e-3,
                     optimizer="adam")
    opt = MegatronOptimizer(tc)
    batch = _global_batch(mesh)
    key = jax.random.PRNGKey(0)

    results = {}
    for name, hier in (("hier", True), ("flat", False)):
        pc = ParallelConfig(data_parallel_size=4, num_slices=2,
                            multislice_hierarchical=hier)
        params = _fresh(model, mesh)
        opt_state = opt.init(params)
        step = build_train_step(model, opt, pc, 2)
        p, _, m = step(params, opt_state, batch, key, 1e-3, 0.0)
        results[name] = (jax.device_get(p), float(m["lm loss"]),
                         float(m["grad_norm"]))

    (p_h, loss_h, gn_h), (p_f, loss_f, gn_f) = results["hier"], results["flat"]
    assert abs(loss_h - loss_f) < 1e-6
    assert abs(gn_h - gn_f) < 1e-5
    for a, b in zip(jax.tree_util.tree_leaves(p_h),
                    jax.tree_util.tree_leaves(p_f)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# elastic resume: different dp x slice product from the same checkpoint
# ---------------------------------------------------------------------------

def _run_steps(model, params, opt, opt_state, pc, mesh, n, start=0,
               num_micro=1):
    step = build_train_step(model, opt, pc, num_micro)
    key = jax.random.PRNGKey(7)
    losses = []
    for i in range(start, start + n):
        batch = _global_batch(mesh, num_micro=num_micro, gb=4, seed=100 + i)
        params, opt_state, m = step(params, opt_state, batch,
                                    jax.random.fold_in(key, i), 1e-3, 0.0)
        losses.append(float(m["lm loss"]))
    return params, opt_state, losses


def _fresh(model, mesh):
    params = model.init(jax.random.PRNGKey(0))
    return sh.shard_params(params, model.param_specs(params))


def _resume(model, opt, d, mesh):
    """Two-phase cross-mesh restore (the finetune.py pattern): params via
    a template carrying THIS mesh's shardings, then the optimizer state
    against a freshly-initialized template."""
    tmpl = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding),
        _fresh(model, mesh))
    params, _, meta = checkpointing.load_checkpoint(d, params_template=tmpl)
    params = sh.shard_params(params, model.param_specs(params))
    opt_tmpl = opt.init(params)
    _, opt_state, _ = checkpointing.load_checkpoint(
        d, load_params=False, opt_state_template=opt_tmpl)
    return params, opt_state, meta


@pytest.mark.parametrize("resume_shape", [
    pytest.param(dict(devices=1, dp=1, slices=1), marks=pytest.mark.slow),
    pytest.param(dict(devices=4, dp=4, slices=1), marks=pytest.mark.slow),
    # tier-1 keeps the slice-count change — the headline elastic case
    dict(devices=4, dp=2, slices=2),
])
def test_elastic_resume_parity(resume_shape):
    """Train at dp=2/slice=1, save, resume at a different dp x slice
    product — the loss trajectory and final params must match the
    uninterrupted run."""
    model = _tiny_model()
    tc = TrainConfig(micro_batch_size=1, global_batch_size=4, lr=1e-3,
                     optimizer="adam")
    opt = MegatronOptimizer(tc)
    d = tempfile.mkdtemp()
    try:
        # --- reference run: 4 uninterrupted steps at dp=2 ---
        topology.destroy_model_parallel()
        mesh = topology.initialize_model_parallel(
            devices=jax.devices()[:2])
        pc = ParallelConfig(data_parallel_size=2)
        params = _fresh(model, mesh)
        opt_state = opt.init(params)
        params, opt_state, l12 = _run_steps(model, params, opt, opt_state,
                                            pc, mesh, 2)
        checkpointing.save_checkpoint(d, 2, params, opt_state,
                                      consumed_samples=8)
        _, _, ref_losses = _run_steps(model, params, opt, opt_state, pc,
                                      mesh, 2, start=2)

        # --- elastic resume at a different shape ---
        topology.destroy_model_parallel()
        n = resume_shape["devices"]
        sl = resume_shape["slices"]
        mesh2 = topology.initialize_model_parallel(
            devices=jax.devices()[:n], num_slices=sl)
        pc2 = ParallelConfig(data_parallel_size=resume_shape["dp"],
                             num_slices=sl,
                             multislice_hierarchical=sl > 1)
        params2, opt_state2, meta = _resume(model, opt, d, mesh2)
        assert meta["iteration"] == 2
        assert meta["consumed_samples"] == 8
        _, _, res_losses = _run_steps(model, params2, opt, opt_state2, pc2,
                                      mesh2, 2, start=2)

        np.testing.assert_allclose(res_losses, ref_losses, rtol=2e-5,
                                   atol=2e-6)

        # the save recorded the producing shape; the resumed shape is a
        # detectable change
        old = multislice.read_run_shape(d)
        assert old is not None and old["data_parallel_size"] == 2 \
            and old["num_slices"] == 1
        args = argparse.Namespace(
            world_size=n, num_slices=sl,
            data_parallel_size=resume_shape["dp"],
            tensor_model_parallel_size=1, pipeline_model_parallel_size=1,
            context_parallel_size=1, global_batch_size=4,
            micro_batch_size=1)
        ev = multislice.detect_elastic_resume(d, args)
        assert ev is not None and ev["kind"] == "elastic_resume"
        changed = ev["changed"]
        assert "data_parallel_size" in changed or "num_slices" in changed
    finally:
        topology.destroy_model_parallel()
        shutil.rmtree(d)


# ---------------------------------------------------------------------------
# run-shape persistence + announcement
# ---------------------------------------------------------------------------

def _shape_args(**kw):
    base = dict(world_size=8, num_slices=2, data_parallel_size=4,
                tensor_model_parallel_size=1, pipeline_model_parallel_size=1,
                context_parallel_size=1, global_batch_size=8,
                micro_batch_size=1)
    base.update(kw)
    return argparse.Namespace(**base)


def test_run_shape_roundtrip(tmp_path):
    shape = multislice.run_shape_from_args(_shape_args())
    path = multislice.write_run_shape(str(tmp_path), shape)
    assert path and os.path.exists(path)
    assert multislice.read_run_shape(str(tmp_path)) == shape
    # same shape -> no event
    assert multislice.detect_elastic_resume(str(tmp_path),
                                            _shape_args()) is None
    # changed dp x slice -> event with the delta
    ev = multislice.detect_elastic_resume(
        str(tmp_path), _shape_args(num_slices=1, data_parallel_size=8))
    assert ev["changed"]["num_slices"] == {"from": 2, "to": 1}
    assert ev["changed"]["data_parallel_size"] == {"from": 4, "to": 8}


def test_run_shape_absent_is_not_a_change(tmp_path):
    assert multislice.read_run_shape(str(tmp_path)) is None
    assert multislice.detect_elastic_resume(str(tmp_path),
                                            _shape_args()) is None


def test_announce_elastic_resume_emits_jsonl(tmp_path):
    multislice.write_run_shape(
        str(tmp_path), multislice.run_shape_from_args(_shape_args()))

    class FakeStream:
        def __init__(self):
            self.records = []

        def emit(self, rec):
            self.records.append(rec)

    stream = FakeStream()
    ev = multislice.announce_elastic_resume(
        str(tmp_path), _shape_args(num_slices=4, data_parallel_size=2),
        iteration=10, consumed_samples=80, stream=stream)
    assert ev is not None
    assert stream.records and stream.records[0]["kind"] == "elastic_resume"
    assert stream.records[0]["iteration"] == 10
    assert stream.records[0]["consumed_samples"] == 80


# ---------------------------------------------------------------------------
# per-slice attribution
# ---------------------------------------------------------------------------

def test_host_slice_map_contiguous_blocks():
    assert multislice.host_slice_map(8, 2) == [0, 0, 0, 0, 1, 1, 1, 1]
    assert multislice.host_slice_map(4, 4) == [0, 1, 2, 3]
    assert multislice.host_slice_map(2, 1) == [0, 0]
    assert multislice.host_slice_map(1, 4) == [0]   # virtual-device run


def test_slice_times_and_worst_slice():
    # hosts 0-1 are slice 0, hosts 2-3 slice 1; slice 1's host 3 lags
    times = multislice.slice_times([0.10, 0.11, 0.10, 0.35], [0, 0, 1, 1])
    assert times == {0: 0.11, 1: 0.35}
    ws = multislice.worst_slice(times)
    assert ws["slice"] == 1
    assert ws["secs"] == pytest.approx(0.35)
    assert ws["lag_secs"] == pytest.approx(0.24)
    assert multislice.worst_slice({0: 0.1}) is None   # nothing to compare


def test_straggler_detector_names_slice():
    printed = []
    det = StragglerDetector(threshold=1.5, min_secs=0.001,
                            printer=printed.append,
                            host_slice_map=[0, 0, 1, 1])
    events = det.check({"train-step": [0.10, 0.10, 0.10, 0.40]},
                       iteration=20)
    assert len(events) == 1
    assert events[0]["host"] == 3
    assert events[0]["slice"] == 1
    assert any("slice 1 host 3" in line for line in printed)
    # without a map the event carries no slice field (single-job runs)
    det2 = StragglerDetector(threshold=1.5, min_secs=0.001,
                             printer=lambda *_: None)
    ev2 = det2.check({"train-step": [0.10, 0.10, 0.10, 0.40]}, iteration=21)
    assert "slice" not in ev2[0]


# ---------------------------------------------------------------------------
# offline aggregation: tools/telemetry_report.py + tools/trace_report.py
# ---------------------------------------------------------------------------

def _load_tool(name):
    import importlib.util
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(root, "tools", name + ".py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _slice_stream(path):
    """Synthetic schema-4 stream: slice 1 is the chronic straggler."""
    with open(path, "w") as f:
        f.write(json.dumps({
            "kind": "elastic_resume", "iteration": 10,
            "consumed_samples": 80,
            "changed": {"num_slices": {"from": 1, "to": 2}},
        }) + "\n")
        for i in (10, 20, 30):
            f.write(json.dumps({
                "schema": 4, "kind": "log", "iteration": i,
                "lm_loss": 2.0, "step_time_secs": 0.2,
                "slice_times": {"0": 0.10, "1": 0.10 + 0.05 * (i // 10)},
                "worst_slice": {"slice": 1, "secs": 0.10 + 0.05 * (i // 10),
                                "median_other_secs": 0.10,
                                "lag_secs": 0.05 * (i // 10),
                                "ratio": 1.0 + 0.5 * (i // 10)},
                "goodput": {"goodput_pct": 90.0,
                            "slice_stall_secs": {"1": 0.5 * (i // 10)}},
            }) + "\n")
        f.write(json.dumps({
            "kind": "preempt_rescue", "iteration": 30, "exit_code": 17,
            "saved": True,
        }) + "\n")


def test_telemetry_report_per_slice_aggregation(tmp_path):
    stream = tmp_path / "telemetry.jsonl"
    _slice_stream(str(stream))
    tr = _load_tool("telemetry_report")

    records = tr.load_records(str(tmp_path))
    slices = tr.slice_aggregates(records)
    assert set(slices) == {"0", "1"}
    assert slices["1"]["times_worst"] == 3
    assert slices["1"]["stall_secs"] == pytest.approx(1.5)   # cumulative
    assert slices["1"]["max_step_secs"] == pytest.approx(0.25)
    assert slices["0"]["times_worst"] == 0
    table = tr.slice_table(slices)
    assert "slice" in table and "stall secs" in table

    fleet = tr.fleet_events(str(tmp_path))
    assert [e["kind"] for e in fleet] == ["elastic_resume",
                                         "preempt_rescue"]

    # single-slice stream: no slice section, graceful
    assert tr.slice_aggregates(
        [{"kind": "log", "iteration": 1, "step_time_secs": 0.1}]) is None

    # end to end through the CLI (human + json modes)
    import subprocess
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, os.path.join(root, "tools", "telemetry_report.py"),
         str(tmp_path)], capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "per-slice attribution" in r.stdout
    assert "elastic resume at iteration 10" in r.stdout
    assert "preemption rescue at iteration 30" in r.stdout
    r = subprocess.run(
        [sys.executable, os.path.join(root, "tools", "telemetry_report.py"),
         str(tmp_path), "--json"], capture_output=True, text=True,
        timeout=120)
    doc = json.loads(r.stdout)
    assert doc["slices"]["1"]["times_worst"] == 3
    assert len(doc["fleet_events"]) == 2


def test_trace_report_slice_column(tmp_path):
    tr = _load_tool("trace_report")
    trace = {"traceEvents": [
        {"ph": "i", "name": "straggler", "ts": 1_000_000.0,
         "args": {"iteration": 20, "host": 3, "slice": 1,
                  "section": "train-step", "secs": 0.4,
                  "median_secs": 0.1, "ratio": 4.0}},
        {"ph": "i", "name": "straggler", "ts": 2_000_000.0,
         "args": {"iteration": 30, "host": 0,
                  "section": "train-step", "secs": 0.3,
                  "median_secs": 0.1, "ratio": 3.0}},
    ]}
    timeline = tr.straggler_timeline(trace)
    assert timeline[0]["slice"] == 1
    assert timeline[1]["slice"] is None     # single-job event: no slice
    out = tr.render(trace, top_n=5, trend=[])
    assert "slice 1 host 3" in out
    assert "host 0" in out


def test_goodput_slice_stall_in_summary():
    from megatron_llm_tpu.tracing import GoodputAccounter
    clock = [0.0]
    g = GoodputAccounter(clock=lambda: clock[0])
    clock[0] = 10.0
    g.add("step", 8.0)
    g.add_slice_stall(1, 0.75)
    g.add_slice_stall(1, 0.25)
    s = g.summary()
    assert s["slice_stall_secs"] == {"1": 1.0}
    # no stalls recorded -> key absent (single-job schema unchanged)
    assert "slice_stall_secs" not in GoodputAccounter(
        clock=lambda: 1.0).summary()
