"""Instruction-tuning CLI end to end (VERDICT r4 #3): tiny chat jsonl
-> ``tools/preprocess_instruct_data.py`` -> ``finetune.py
--data_type=instruction`` must (a) run the real train loop with the
assistant-masked loss falling, and (b) produce text/role datasets whose
collated loss mask is 1.0 exactly on assistant tokens, the
``--scalar_loss_mask`` value on system/user tokens, and 0 on padding —
the reference's marquee workflow (finetune.py:155-166 +
instruction_dataset.py:321-355), proven here at the CLI level the way
``test_glue_finetune_e2e.py`` proves GLUE."""

import json
import os
import re
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORDS = ["yes", "no", "maybe", "dogs", "cats", "run", "sleep", "fast",
         "slow", "happy", "you", "are", "helpful", "what", "do", "like",
         "tell", "me", "about", "animals"]

ANSWER = "dogs run fast yes"


def _cpu_env(n_devices=1):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={n_devices}"
    return env


@pytest.fixture(scope="module")
def instruct_run(tmp_path_factory):
    tmp_path = tmp_path_factory.mktemp("instr")
    vocab = tmp_path / "vocab.txt"
    vocab.write_text("\n".join(
        ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"] + WORDS) + "\n")

    chat = tmp_path / "chat.jsonl"
    with open(chat, "w") as f:
        for i in range(32):
            f.write(json.dumps({"conversations": [
                {"role": "system", "content": "you are helpful"},
                {"role": "user",
                 "content": f"tell me about {WORDS[5 + i % 10]} animals"},
                {"role": "assistant", "content": ANSWER},
            ]}) + "\n")

    prefix = str(tmp_path / "instr")
    r = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "tools", "preprocess_instruct_data.py"),
         "--input", str(chat), "--output_prefix", prefix,
         "--tokenizer_type", "BertWordPieceLowerCase",
         "--vocab_file", str(vocab), "--append_eod"],
        env=_cpu_env(), cwd=REPO, capture_output=True, text=True,
        timeout=300)
    assert r.returncode == 0, r.stderr[-3000:]

    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "finetune.py"),
         "--model_name=llama2",
         "--num_layers=2", "--hidden_size=64", "--num_attention_heads=4",
         "--ffn_hidden_size=128", "--seq_length=32",
         "--max_position_embeddings=32",
         "--micro_batch_size=4", "--global_batch_size=4",
         "--train_iters=25", "--lr=1e-2", "--lr_decay_style=constant",
         "--log_interval=1",
         "--data_type=instruction", "--data_path", prefix,
         "--scalar_loss_mask", "0.1",
         "--tokenizer_type", "BertWordPieceLowerCase",
         "--vocab_file", str(vocab), "--seed", "42"],
        env=_cpu_env(), cwd=REPO, capture_output=True, text=True,
        timeout=900)
    return proc, prefix, vocab


def test_assistant_masked_loss_falls(instruct_run):
    proc, _, _ = instruct_run
    assert proc.returncode == 0, proc.stderr[-3000:]
    losses = [float(m) for m in re.findall(
        r"lm loss: ([0-9.E+-]+)", proc.stdout)]
    assert len(losses) == 25, proc.stdout[-2000:]
    # The assistant reply is constant: the masked LM objective must
    # collapse far below the initial ~log(vocab) loss.
    assert losses[-1] < 0.5 * losses[0], f"first {losses[0]}, last {losses[-1]}"
    assert losses[-1] < 1.0, f"final loss {losses[-1]}"


def test_loss_mask_role_semantics(instruct_run):
    """The CLI-built -text/-role datasets collate into the documented
    loss mask: 1.0 on assistant label positions, --scalar_loss_mask on
    system/user, 0.0 on pad."""
    _, prefix, vocab = instruct_run
    from megatron_llm_tpu.data.instruction_dataset import (
        ROLES,
        InstructionDataset,
        build_instruction_collator,
    )

    ds = InstructionDataset(prefix, shuffle=False)
    assert len(ds) == 32
    sample = ds[0]
    assert len(sample["text"]) == len(sample["role"])
    # the conversation layout survives the round trip: a system span,
    # then user, then assistant (plus the appended eod as assistant)
    roles = sample["role"]
    assert roles[0] == ROLES["system"]
    assert roles[-1] == ROLES["assistant"]
    assert set(np.unique(roles)) == {ROLES["system"], ROLES["user"],
                                     ROLES["assistant"]}

    seq = 32
    collate = build_instruction_collator(seq, pad_token_id=0,
                                         scalar_loss_mask=0.1)
    batch = collate([[ds[0], ds[1]]])
    mask = batch["loss_mask"][0]      # [batch, seq]
    label_roles = np.full_like(batch["labels"][0], ROLES["pad"])
    for r in range(2):
        t = ds[r]["role"][:seq + 1]
        label_roles[r, : len(t) - 1] = t[1:]
    np.testing.assert_array_equal(mask == 1.0,
                                  label_roles == ROLES["assistant"])
    np.testing.assert_array_equal(mask == 0.0, label_roles == ROLES["pad"])
    scalar = (label_roles == ROLES["system"]) | (label_roles == ROLES["user"])
    np.testing.assert_allclose(mask[scalar], 0.1)
    assert scalar.any() and (mask == 1.0).any() and (mask == 0.0).any()
