"""AOT scale-proof gate (VERDICT r3 #3): the Llama-2-7B TP=8 milestone
config must AOT-compile against a virtual v5e-8 topology via the local
libtpu compiler and fit 16 GB HBM per chip.  The larger configs
(Falcon-40B, 70B 3D on v5p-256) run through the same tool
(docs/scale_aot.md records their numbers); compiling them here would add
~15 min to CI, so the gate covers the smallest config, which exercises
every code path (abstract sharded params/opt state, topology mesh,
memory_analysis)."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_llama7b_tp8_fits_v5e():
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["TPU_ACCELERATOR_TYPE"] = "v5litepod-8"
    env.setdefault("TPU_WORKER_HOSTNAMES", "localhost")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "aot_memcheck.py"),
         "--child", "llama2-7b-tp8"],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=850)
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(
        [l for l in proc.stdout.splitlines() if l.startswith("{")][-1])
    assert rec["fits"] is True
    assert rec["devices"] == 8 and rec["tp"] == 8
    assert rec["n_params"] > 6.5e9
    # the compiled step must actually be tensor-parallel: TP emits
    # collectives (all-reduce/all-gather/permute), not a replicated program
    assert sum(v for v in rec["collectives"].values()
               if isinstance(v, int)) > 0
    assert rec["per_device_gb"] <= 16
