"""Zigzag ring attention (cp algorithm #3): redistribution round trip,
op-level exactness vs full attention, GQA/window, gradients, and parity
with the plain ring.  (Load-balanced causal CP — the reference has no
context parallelism at all; SURVEY §5.7.)"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from megatron_llm_tpu import topology
from megatron_llm_tpu.ops.pallas.flash_attention import _reference_attention
from megatron_llm_tpu.parallel.ring_attention import (
    context_parallel_attention,
)
from megatron_llm_tpu.parallel.zigzag_ring import (
    _from_zigzag,
    _to_zigzag,
    zigzag_context_attention,
)


def _qkv(b=2, s=128, nh=4, ng=4, d=32, seed=0):
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(b, s, nh, d).astype(np.float32)) * 0.3
    k = jnp.asarray(rng.randn(b, s, ng, d).astype(np.float32)) * 0.3
    v = jnp.asarray(rng.randn(b, s, ng, d).astype(np.float32)) * 0.3
    return q, k, v


def test_zigzag_redistribution_round_trip(utils):
    """to_zigzag places half-chunk pair (r, 2P-1-r) on rank r, and
    from_zigzag restores the contiguous layout exactly."""
    utils.initialize_model_parallel(tp=1, pp=1, cp=4)
    x = jnp.arange(2 * 64 * 1 * 1, dtype=jnp.float32).reshape(2, 64, 1, 1)

    def body(xl):
        low, high = _to_zigzag(xl, topology.CP_AXIS, 4)
        g = jax.lax.axis_index(topology.CP_AXIS)
        # low must be global half-chunk g, high chunk 2P-1-g (cs = 8)
        cs = 8
        lo_ok = jnp.all(low[:, :, 0, 0] == xl_global_chunk(x, g, cs))
        hi_ok = jnp.all(high[:, :, 0, 0]
                        == xl_global_chunk(x, 2 * 4 - 1 - g, cs))
        back = _from_zigzag(low, high, topology.CP_AXIS, 4)
        return back, jnp.stack([lo_ok, hi_ok])

    def xl_global_chunk(x_full, c, cs):
        return jax.lax.dynamic_slice_in_dim(
            x_full[:, :, 0, 0], c * cs, cs, axis=1)

    mesh = topology.get_mesh()
    spec = P(None, "cp", None, None)
    back, oks = jax.jit(topology.shard_map(
        body, mesh=mesh, in_specs=spec,
        out_specs=(spec, P("cp")), check_vma=False))(x)
    assert bool(jnp.all(oks)), np.asarray(oks)
    np.testing.assert_array_equal(np.asarray(back), np.asarray(x))


@pytest.mark.parametrize("window", [None, 48])
def test_zigzag_matches_full_attention(utils, window):
    utils.initialize_model_parallel(tp=1, pp=1, cp=4)
    q, k, v = _qkv()
    ref = _reference_attention(q, k, v, True, window, 0.125)
    out = jax.jit(
        lambda q, k, v: zigzag_context_attention(
            q, k, v, causal=True, sliding_window=window,
            softmax_scale=0.125))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_zigzag_gqa(utils):
    utils.initialize_model_parallel(tp=1, pp=1, cp=4)
    q, k, v = _qkv(nh=8, ng=2)
    ref = _reference_attention(q, k, v, True, None, 0.125)
    out = jax.jit(
        lambda q, k, v: zigzag_context_attention(
            q, k, v, causal=True, softmax_scale=0.125))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_zigzag_matches_ring(utils):
    utils.initialize_model_parallel(tp=1, pp=1, cp=4)
    q, k, v = _qkv(seed=3)
    ring = jax.jit(
        lambda q, k, v: context_parallel_attention(
            q, k, v, causal=True, softmax_scale=0.125))(q, k, v)
    zig = jax.jit(
        lambda q, k, v: zigzag_context_attention(
            q, k, v, causal=True, softmax_scale=0.125))(q, k, v)
    np.testing.assert_allclose(np.asarray(zig), np.asarray(ring),
                               atol=2e-5)


def test_zigzag_grads_match_reference(utils):
    utils.initialize_model_parallel(tp=1, pp=1, cp=4)
    q, k, v = _qkv(s=64)

    def loss(fn):
        return lambda q, k, v: (fn(q, k, v) ** 2).sum()

    g_ref = jax.grad(loss(lambda q, k, v: _reference_attention(
        q, k, v, True, None, 0.125)), argnums=(0, 1, 2))(q, k, v)
    g_zig = jax.jit(jax.grad(loss(lambda q, k, v: zigzag_context_attention(
        q, k, v, causal=True, softmax_scale=0.125)),
        argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_zig, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5)


def test_zigzag_model_loss_matches_ring(utils):
    """Model-level: --context_parallel_algo=zigzag trains to the same
    loss as ring on identical weights/batch (cp=2 x dp=2 x tp=2)."""
    from megatron_llm_tpu.config import ParallelConfig, TrainConfig
    from megatron_llm_tpu.models.llama import LlamaModel, llama_config
    from megatron_llm_tpu.optimizer import MegatronOptimizer
    from megatron_llm_tpu.parallel import sharding as sh
    from megatron_llm_tpu.training import build_train_step

    def run(algo):
        utils.initialize_model_parallel(tp=2, pp=1, cp=2)
        try:
            cfg = llama_config(
                "tiny", num_layers=2, seq_length=32,
                max_position_embeddings=32, padded_vocab_size=128,
                params_dtype="bf16", compute_dtype="bf16",
                context_parallel_algo=algo)
            model = LlamaModel(cfg)
            params = model.init(jax.random.PRNGKey(0))
            params = sh.shard_params(params, model.param_specs(params))
            tc = TrainConfig(micro_batch_size=1, global_batch_size=2,
                             train_iters=0, lr=1e-3, optimizer="adam",
                             bf16=True, clip_grad=1.0)
            opt = MegatronOptimizer(tc, params_dtype=jnp.bfloat16)
            os_ = opt.init(params)
            pc = ParallelConfig(tensor_model_parallel_size=2,
                                data_parallel_size=2,
                                context_parallel_size=2,
                                sequence_parallel=True)
            step = build_train_step(model, opt, pc, 1)
            rng = np.random.RandomState(0)
            toks = jnp.asarray(rng.randint(0, 128, (1, 2, 32)))
            batch = {"tokens": toks,
                     "labels": jnp.roll(toks, -1, -1),
                     "loss_mask": jnp.ones_like(toks, jnp.float32)}
            _, _, metrics = step(params, os_, batch,
                                 jax.random.PRNGKey(0), 1e-3, 0.0)
            return float(metrics["lm loss"])
        finally:
            utils.destroy_model_parallel()

    loss_ring = run("ring")
    loss_zig = run("zigzag")
    assert np.isfinite(loss_zig)
    assert abs(loss_zig - loss_ring) < 1e-3, (loss_zig, loss_ring)


def test_zigzag_q_chunked_exact(utils):
    """Interior q-chunking (qc < half-chunk) stays exact — the memory
    bound that lets zigzag run at long local sequences."""
    utils.initialize_model_parallel(tp=1, pp=1, cp=4)
    q, k, v = _qkv(seed=5)
    ref = _reference_attention(q, k, v, True, None, 0.125)
    out = jax.jit(
        lambda q, k, v: zigzag_context_attention(
            q, k, v, causal=True, softmax_scale=0.125,
            q_chunk_size=8))(q, k, v)   # cs=16 -> 2 chunks per sub-block
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
