"""Mini RACE finetune end to end: tasks/main.py --task RACE on a tiny
separable 4-way multiple-choice corpus through the real
train_step/optimizer/scheduler path, with per-split reporting and
prediction dumps (same contract as the MNLI e2e test)."""

import json
import os
import re
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORDS = ["good", "bad", "where", "what", "city", "food", "blue", "red",
         "big", "small", "answer", "choose"]


def _write_vocab(path):
    toks = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"] + WORDS
    path.write_text("\n".join(toks) + "\n")


def _write_race_dir(d, n_articles, seed):
    """Separable toy RACE: the correct option always contains the word
    'good'; distractors contain 'bad'."""
    import numpy as np

    rng = np.random.RandomState(seed)
    d.mkdir(parents=True, exist_ok=True)
    recs = []
    for i in range(n_articles):
        correct = int(rng.randint(4))
        opts = []
        for c in range(4):
            filler = " ".join(rng.choice(WORDS[4:10], 2))
            opts.append(("good " if c == correct else "bad ") + filler)
        recs.append({
            "article": "the city food " + " ".join(rng.choice(WORDS[4:], 4)),
            "questions": ["what to choose _"],
            "options": [opts],
            "answers": [chr(ord("A") + correct)],
        })
    (d / "part.txt").write_text(
        "\n".join(json.dumps(r) for r in recs) + "\n")


@pytest.fixture(scope="module")
def race_run(tmp_path_factory):
    tmp_path = tmp_path_factory.mktemp("race")
    vocab = tmp_path / "vocab.txt"
    _write_vocab(vocab)
    train = tmp_path / "train"
    _write_race_dir(train, 48, seed=0)
    dev = tmp_path / "dev"
    _write_race_dir(dev, 16, seed=1)
    save = tmp_path / "out"

    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tasks", "main.py"),
         "--task", "RACE",
         "--train_data", str(train),
         "--valid_data", str(dev),
         "--tokenizer_type", "BertWordPieceLowerCase",
         "--vocab_file", str(vocab),
         "--num_layers", "2", "--hidden_size", "32",
         "--num_attention_heads", "4", "--ffn_hidden_size", "64",
         "--seq_length", "32", "--max_position_embeddings", "32",
         "--micro_batch_size", "8", "--lr", "5e-3",
         "--epochs", "6", "--log_interval", "10",
         "--save", str(save), "--save_interval", "1000",
         "--seed", "42"],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=900)
    return proc, save


def test_race_finetune_beats_chance(race_run):
    proc, _ = race_run
    assert proc.returncode == 0, proc.stderr[-3000:]
    accs = [float(m) for m in re.findall(
        r"validation accuracy ([0-9.]+)%", proc.stdout)]
    assert accs, proc.stdout[-2000:]
    # 4-way chance is 25%; 'good'-marked answers are fully separable
    assert max(accs) > 50.0, f"accuracies {accs}"


def test_race_predictions_dumped(race_run):
    proc, save = race_run
    dumps = [p for p in os.listdir(save) if p.startswith("predictions_")]
    assert dumps, os.listdir(save)
    with open(os.path.join(save, sorted(dumps)[-1])) as f:
        preds = json.load(f)
    (split,) = preds
    assert len(preds[split]["softmaxes"][0]) == 4  # 4-way distribution
