"""MSDP: F1 metric, file evaluation, WoW preprocessing, prompt building."""

import json

import numpy as np
import pytest

from tasks.msdp.metrics import F1Metric, normalize_answer, token_f1


def test_normalize_answer():
    assert normalize_answer("The Cat, sat!") == "cat sat"
    assert normalize_answer("A  b   c") == "b c"


def test_token_f1():
    p, r, f = token_f1("the cat sat", "a cat sat down")
    assert p == pytest.approx(2 / 2)  # "cat sat" of "cat sat"
    assert r == pytest.approx(2 / 3)
    assert f == pytest.approx(2 * 1 * (2 / 3) / (1 + 2 / 3))
    assert token_f1("anything", "") == (None, None, None)
    assert token_f1("", "gold") == (0.0, 0.0, 0.0)
    assert token_f1("zebra", "yak")[2] == 0.0


def test_f1_all_pairs():
    p, r, f = F1Metric.compute_all_pairs(
        ["cat sat", "dog ran", "x"], ["cat sat", "", "x"])
    # middle pair skipped (empty answer)
    assert f == pytest.approx((1.0 + 1.0) / 2)


def test_evaluate_f1_files(tmp_path):
    from tasks.msdp.evaluate import evaluate_f1

    g = tmp_path / "guess.txt"
    a = tmp_path / "answer.txt"
    g.write_text("the cat<|endoftext|>\nhello world\n")
    a.write_text("cat\nno_passages_used\n")
    p, r, f = evaluate_f1(str(g), str(a))
    assert f == pytest.approx(1.0)  # only the first pair counts


def test_process_wow(tmp_path):
    from tasks.msdp.preprocessing import process_wow_dataset

    raw = [{
        "chosen_topic": "Cats",
        "dialog": [
            {"speaker": "0_Apprentice", "text": "tell me about cats"},
            {"speaker": "1_Wizard", "text": "cats are felines",
             "checked_sentence": {"k": "A cat is a feline."}},
            {"speaker": "0_Apprentice", "text": "cool"},
            {"speaker": "1_Wizard", "text": "indeed",
             "checked_sentence": {}},
        ],
    }]
    rawf = tmp_path / "wow.json"
    rawf.write_text(json.dumps(raw))
    out = tmp_path / "processed.tsv"
    kref = tmp_path / "knwl.txt"
    rref = tmp_path / "resp.txt"
    n = process_wow_dataset(str(rawf), str(out), str(kref), str(rref))
    assert n == 2
    lines = out.read_text().splitlines()
    topic, dialogue, knowledge, resp = lines[0].split("\t")
    assert topic == "Cats" and knowledge == "A cat is a feline."
    assert resp == "cats are felines"
    assert lines[1].split("\t")[2] == "no_passages_used"
    assert kref.read_text().splitlines()[0] == "A cat is a feline."


def test_prompt_building(tmp_path):
    from tasks.msdp.preprocessing import (
        build_knowledge_prompts,
        build_response_prompts,
    )
    from tasks.msdp.prompt import (
        build_input,
        read_knowledge_prompts,
        read_response_prompt,
    )

    train = tmp_path / "train.tsv"
    train.write_text(
        "Cats\thi [SEP] tell me about cats\tA cat is a feline.\tfelines!\n"
        "Dogs\thello [SEP] dogs?\tDogs bark.\twoof\n")
    # the prompt keys must come from the file generation will run on
    test = tmp_path / "test.tsv"
    test.write_text("Cats\tyo [SEP] what about cats\n")
    kp = tmp_path / "kprompts.jsonl"
    build_knowledge_prompts(str(train), str(kp), n_examples=2,
                            test_file=str(test))
    prompts = read_knowledge_prompts(str(kp))
    # keyed by the TEST sample's topic + last turn (regression: train-keyed
    # prompts never matched at generation time)
    assert "Cats what about cats" in prompts
    assert "A cat is a feline." in prompts["Cats what about cats"]

    rp = tmp_path / "rprompts.txt"
    build_response_prompts(str(train), str(rp), n_examples=2)
    fixed = read_response_prompt(str(rp), 2)
    assert "Response:" in fixed

    line = "Cats\tyo [SEP] what about cats"
    knowledge_input = build_input(line, "knowledge", prompts, "")
    assert knowledge_input.endswith("( what about cats ) Cats =>")
    # the few-shot examples actually made it into the input
    assert "A cat is a feline." in knowledge_input
    resp_line = "Cats\thi [SEP] tell me about cats\tA cat is a feline."
    resp_input = build_input(resp_line, "response", None, fixed)
    assert resp_input.endswith("Response:")
    assert "Knowledge: A cat is a feline." in resp_input


def test_prepare_response_inputs(tmp_path):
    from tasks.msdp.preprocessing import (
        prepare_input_for_response_generation,
    )

    test = tmp_path / "test.tsv"
    test.write_text("Cats\thi [SEP] q\tgold knowledge\tgold resp\n")
    gen = tmp_path / "gen.txt"
    gen.write_text("generated knowledge\n")
    out = tmp_path / "resp_in.tsv"
    prepare_input_for_response_generation(str(test), str(gen), str(out))
    assert out.read_text().strip() == \
        "Cats\thi [SEP] q\tgenerated knowledge"
