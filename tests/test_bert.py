"""BERT model family tests.

Mirrors the reference's coverage for ``megatron/model/bert_model.py`` and
the classification/multiple-choice heads (no direct reference tests exist;
shapes, masking semantics and a train-step smoke are what
``tests/test_layernorm_order.py`` / integration tests cover upstream).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megatron_llm_tpu.config import ParallelConfig, TrainConfig
from megatron_llm_tpu.models.bert import BertModel, bert_config
from megatron_llm_tpu.models.classification import (
    ClassificationModel,
    MultipleChoiceModel,
)

VOCAB = 128


def tiny_cfg(**kw):
    return bert_config(
        num_layers=2, hidden_size=64, num_attention_heads=4,
        ffn_hidden_size=128, padded_vocab_size=VOCAB, seq_length=32,
        hidden_dropout=0.0, attention_dropout=0.0, **kw,
    )


def test_bert_forward_shapes():
    cfg = tiny_cfg()
    model = BertModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    tokens = jnp.asarray(np.random.RandomState(0).randint(0, VOCAB, (2, 32)))
    lm_logits, binary_logits = model(params, tokens)
    assert lm_logits.shape == (2, 32, VOCAB)
    assert binary_logits.shape == (2, 2)


def test_bert_loss_path():
    cfg = tiny_cfg()
    model = BertModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    rs = np.random.RandomState(1)
    tokens = jnp.asarray(rs.randint(0, VOCAB, (2, 32)))
    labels = jnp.asarray(rs.randint(0, VOCAB, (2, 32)))
    order = jnp.asarray(rs.randint(0, 2, (2,)))
    lm_loss, sop_loss = model(
        params, tokens, labels=labels, sentence_order=order
    )
    assert lm_loss.shape == (2, 32)
    assert sop_loss.shape == (2,)
    assert np.isfinite(np.asarray(lm_loss)).all()
    # CE of a fresh init should be near log(V)
    assert abs(float(lm_loss.mean()) - np.log(VOCAB)) < 1.0


def test_bert_padding_mask_blocks_attention():
    """Output at kept positions must not depend on padded-out tokens."""
    cfg = tiny_cfg()
    model = BertModel(cfg, add_binary_head=False)
    params = model.init(jax.random.PRNGKey(0))
    rs = np.random.RandomState(2)
    tokens = rs.randint(0, VOCAB, (1, 32))
    mask = np.ones((1, 32), np.int32)
    mask[0, 16:] = 0  # pad out the tail
    out1, _ = model(params, jnp.asarray(tokens), attention_mask=jnp.asarray(mask))
    tokens2 = tokens.copy()
    tokens2[0, 20] = (tokens2[0, 20] + 7) % VOCAB  # change a padded token
    out2, _ = model(params, jnp.asarray(tokens2), attention_mask=jnp.asarray(mask))
    np.testing.assert_allclose(
        np.asarray(out1[0, :16]), np.asarray(out2[0, :16]), rtol=1e-5, atol=1e-5
    )


def test_bert_not_causal():
    """Bidirectional: early positions see late tokens (unlike GPT)."""
    cfg = tiny_cfg()
    model = BertModel(cfg, add_binary_head=False)
    params = model.init(jax.random.PRNGKey(0))
    rs = np.random.RandomState(3)
    tokens = rs.randint(0, VOCAB, (1, 32))
    out1, _ = model(params, jnp.asarray(tokens))
    tokens2 = tokens.copy()
    tokens2[0, 31] = (tokens2[0, 31] + 7) % VOCAB
    out2, _ = model(params, jnp.asarray(tokens2))
    assert not np.allclose(np.asarray(out1[0, 0]), np.asarray(out2[0, 0]))


def test_bert_tokentype_changes_output():
    cfg = tiny_cfg()
    model = BertModel(cfg, add_binary_head=False)
    params = model.init(jax.random.PRNGKey(0))
    tokens = jnp.asarray(np.random.RandomState(4).randint(0, VOCAB, (1, 32)))
    out1, _ = model(params, tokens, tokentype_ids=jnp.zeros((1, 32), jnp.int32))
    out2, _ = model(params, tokens, tokentype_ids=jnp.ones((1, 32), jnp.int32))
    assert not np.allclose(np.asarray(out1), np.asarray(out2))


def test_bert_train_step():
    """One optimizer step through build_train_step with the BERT loss."""
    from pretrain_bert import bert_loss_func
    from megatron_llm_tpu.optimizer import MegatronOptimizer
    from megatron_llm_tpu.training import build_train_step

    cfg = tiny_cfg()
    model = BertModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    tc = TrainConfig(micro_batch_size=2, global_batch_size=4, train_iters=2,
                     lr=1e-4)
    pc = ParallelConfig()
    opt = MegatronOptimizer(tc, params_dtype=jnp.float32)
    opt_state = opt.init(params)
    step = build_train_step(model, opt, pc, num_microbatches=2,
                            loss_func=bert_loss_func)
    rs = np.random.RandomState(5)
    batch = {
        "tokens": jnp.asarray(rs.randint(0, VOCAB, (2, 2, 32)), jnp.int32),
        "labels": jnp.asarray(rs.randint(0, VOCAB, (2, 2, 32)), jnp.int32),
        "loss_mask": jnp.asarray(rs.rand(2, 2, 32) < 0.15, jnp.float32),
        "attention_mask": jnp.ones((2, 2, 32), jnp.int32),
        "tokentype_ids": jnp.zeros((2, 2, 32), jnp.int32),
        "sentence_order": jnp.asarray(rs.randint(0, 2, (2, 2)), jnp.int32),
    }
    before = np.asarray(jax.tree_util.tree_leaves(params)[0])  # pre-donation
    new_params, _, metrics = step(
        params, opt_state, batch, jax.random.PRNGKey(1), 1e-4, 0.0
    )
    assert np.isfinite(float(metrics["lm loss"]))
    after = np.asarray(jax.tree_util.tree_leaves(new_params)[0])
    assert not np.allclose(before, after)


def test_classification_model():
    cfg = tiny_cfg()
    model = ClassificationModel(cfg, num_classes=3)
    params = model.init(jax.random.PRNGKey(0))
    rs = np.random.RandomState(6)
    tokens = jnp.asarray(rs.randint(0, VOCAB, (4, 32)))
    logits = model(params, tokens)
    assert logits.shape == (4, 3)
    labels = jnp.asarray(rs.randint(0, 3, (4,)))
    loss = model(params, tokens, labels=labels)
    assert loss.shape == (4,)
    assert np.isfinite(np.asarray(loss)).all()


def test_multiple_choice_model():
    cfg = tiny_cfg()
    model = MultipleChoiceModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    rs = np.random.RandomState(7)
    tokens = jnp.asarray(rs.randint(0, VOCAB, (2, 4, 32)))
    logits = model(params, tokens)
    assert logits.shape == (2, 4)
    labels = jnp.asarray(rs.randint(0, 4, (2,)))
    loss = model(params, tokens, labels=labels)
    assert loss.shape == (2,)
