"""Rolling (ring-buffer) KV cache for sliding-window models: O(window)
decode memory with logits identical to the full-length cache (positions
outside the window are masked in both)."""

import jax
import jax.numpy as jnp
import numpy as np

from megatron_llm_tpu.models.mistral import mistral_config
from megatron_llm_tpu.models.gpt import GPTModel
from megatron_llm_tpu.text_generation.generation import (
    _forward_with_cache,
    init_kv_caches,
)

WINDOW = 8


def _model():
    cfg = mistral_config(
        "tiny", num_layers=2, hidden_size=64, num_attention_heads=4,
        ffn_hidden_size=176, padded_vocab_size=64, seq_length=64,
        max_position_embeddings=64, sliding_window_size=WINDOW,
        use_flash_attn=False)
    model = GPTModel(cfg)
    return model, model.init(jax.random.PRNGKey(0))


def test_rolling_cache_matches_full_cache():
    """Decode 24 positions (3x the window) step by step: every step's
    logits from the W-slot ring buffer equal the full-length cache's."""
    model, params = _model()
    total = 24
    rng = np.random.RandomState(0)
    toks = jnp.asarray(rng.randint(0, 64, (2, total)))

    full = init_kv_caches(model.cfg, 2, total)
    ring = init_kv_caches(model.cfg, 2, total, rolling=True)
    assert ring[0]["k"].shape[1] == WINDOW          # O(window) memory
    assert full[0]["k"].shape[1] == total

    # prefill 4 (<= window), then single-token steps
    lf, full = _forward_with_cache(model, params, toks[:, :4], full, 0)
    lr, ring = _forward_with_cache(model, params, toks[:, :4], ring, 0)
    np.testing.assert_allclose(np.asarray(lr), np.asarray(lf), atol=2e-4)

    for t in range(4, total):
        lf, full = _forward_with_cache(model, params, toks[:, t:t + 1],
                                       full, t)
        lr, ring = _forward_with_cache(model, params, toks[:, t:t + 1],
                                       ring, t)
        np.testing.assert_allclose(np.asarray(lr), np.asarray(lf),
                                   atol=2e-4, err_msg=f"step {t}")


def test_rolling_cache_multi_token_chunks():
    """Chunked writes (n > 1, n <= window) wrap correctly across the
    ring boundary."""
    model, params = _model()
    total = 20
    rng = np.random.RandomState(1)
    toks = jnp.asarray(rng.randint(0, 64, (1, total)))

    full = init_kv_caches(model.cfg, 1, total)
    ring = init_kv_caches(model.cfg, 1, total, rolling=True)
    # chunks of 5: boundaries at 5, 10, 15 cross the 8-slot ring wrap
    for lo in range(0, total, 5):
        lf, full = _forward_with_cache(model, params, toks[:, lo:lo + 5],
                                       full, lo)
        lr, ring = _forward_with_cache(model, params, toks[:, lo:lo + 5],
                                       ring, lo)
        np.testing.assert_allclose(np.asarray(lr), np.asarray(lf),
                                   atol=2e-4, err_msg=f"chunk@{lo}")


def test_rolling_requires_sliding_window():
    from megatron_llm_tpu.models.llama import llama_config, LlamaModel

    cfg = llama_config("tiny", num_layers=1, hidden_size=64,
                       num_attention_heads=4, ffn_hidden_size=176,
                       padded_vocab_size=64, seq_length=16,
                       max_position_embeddings=16)
    model = LlamaModel(cfg)
    try:
        init_kv_caches(model.cfg, 1, 16, rolling=True)
        assert False, "expected AssertionError"
    except AssertionError:
        pass


def test_rolling_chunk_longer_than_window():
    """n > W single forward: output exact, and the ring afterwards holds
    only the last W positions (no duplicate-scatter corruption) so
    subsequent decode steps stay exact."""
    model, params = _model()
    total = 20
    rng = np.random.RandomState(2)
    toks = jnp.asarray(rng.randint(0, 64, (1, total)))

    full = init_kv_caches(model.cfg, 1, total)
    ring = init_kv_caches(model.cfg, 1, total, rolling=True)
    # one 12-token prefill (12 > W=8), then single-token decode
    lf, full = _forward_with_cache(model, params, toks[:, :12], full, 0)
    lr, ring = _forward_with_cache(model, params, toks[:, :12], ring, 0)
    np.testing.assert_allclose(np.asarray(lr), np.asarray(lf), atol=2e-4)
    for t in range(12, total):
        lf, full = _forward_with_cache(model, params, toks[:, t:t + 1],
                                       full, t)
        lr, ring = _forward_with_cache(model, params, toks[:, t:t + 1],
                                       ring, t)
        np.testing.assert_allclose(np.asarray(lr), np.asarray(lf),
                                   atol=2e-4, err_msg=f"step {t}")


def test_generate_tokens_rolling_matches_linear():
    """End-to-end greedy decode with rolling_cache=True equals the
    full-cache decode."""
    from megatron_llm_tpu.text_generation.generation import generate_tokens

    model, params = _model()
    toks = jnp.asarray([[1, 2, 3, 4]])
    lens = jnp.asarray([4])
    want, n_want, _ = generate_tokens(
        model, params, toks, lens, jax.random.PRNGKey(0),
        max_new_tokens=16, min_prompt_len=4, greedy=True)
    got, n_got, _ = generate_tokens(
        model, params, toks, lens, jax.random.PRNGKey(0),
        max_new_tokens=16, min_prompt_len=4, greedy=True,
        rolling_cache=True)
    assert int(n_got) == int(n_want)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_api_generate_auto_enables_rolling(monkeypatch):
    """api.generate auto-enables the ring cache exactly when a
    sliding-window model decodes past its window, and output text is
    unchanged."""
    import megatron_llm_tpu.text_generation.generation as G
    from megatron_llm_tpu.text_generation.api import generate

    model, params = _model()

    class Tok:
        vocab_size = 64
        eod = 63
        pad = 0

        def tokenize(self, text):
            return [int(t) % 64 for t in text.split()]

        def detokenize(self, ids):
            return " ".join(str(i) for i in ids)

    seen = {}
    real = G.generate_tokens

    def spy(*a, **kw):
        seen["rolling"] = kw.get("rolling_cache")
        return real(*a, **kw)

    import megatron_llm_tpu.text_generation.api as api_mod

    monkeypatch.setattr(api_mod, "generate_tokens", spy)

    # 4-token prompt + 16 new > window 8 -> rolling auto-on
    texts_r, _, _ = generate(model, params, Tok(), ["1 2 3 4"], 16,
                             greedy=True)
    assert seen["rolling"] is True
    # 2 new tokens stays within the window -> off
    generate(model, params, Tok(), ["1 2 3 4"], 2, greedy=True)
    assert seen["rolling"] is False
    # and the auto-on output equals the explicit full-cache decode
    texts_f, _, _ = generate(model, params, Tok(), ["1 2 3 4"], 16,
                             greedy=True, rolling_cache=False)
    assert texts_r == texts_f
