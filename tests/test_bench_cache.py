"""bench.py TPU-result persistence: a successful on-chip measurement is
cached and replayed (clearly marked) when later live TPU attempts fail —
the axon tunnel outage mode that ate the round-1..3 round-end artifacts."""

import importlib.util
import json
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_save_and_replay_cached_tpu(tmp_path, monkeypatch):
    bench = _load_bench()
    monkeypatch.setattr(bench, "TPU_CACHE", str(tmp_path / "latest.json"))

    bench._save_tpu_result({
        "metric": "train_tokens_per_sec_per_chip", "value": 25600.0,
        "unit": "tokens/s", "mfu": 0.516, "vs_baseline": 1.10,
        "device": "TPU v5 lite", "backend": "axon",
    })
    saved = json.loads((tmp_path / "latest.json").read_text())
    assert saved["measured_at_unix"] > 0
    assert saved["device"] == "TPU v5 lite"

    out = bench._load_cached_tpu(["attempt 1: init timeout"])
    rec = json.loads(out)
    assert rec["measured_live"] is False
    assert rec["mfu"] == 0.516
    assert "persisted ON-CHIP" in rec["tpu_fallback_reason"]
    assert "attempt 1: init timeout" in rec["tpu_fallback_reason"]


def test_no_cache_returns_none(tmp_path, monkeypatch):
    bench = _load_bench()
    monkeypatch.setattr(bench, "TPU_CACHE", str(tmp_path / "missing.json"))
    assert bench._load_cached_tpu(["x"]) is None


def test_force_cpu_never_replays_cache(tmp_path, monkeypatch):
    bench = _load_bench()
    monkeypatch.setattr(bench, "TPU_CACHE", str(tmp_path / "latest.json"))
    bench._save_tpu_result({"mfu": 0.5, "device": "TPU v5 lite"})
    monkeypatch.setenv("BENCH_FORCE_CPU", "1")
    assert bench._emit_cached(["x"]) is False
    monkeypatch.delenv("BENCH_FORCE_CPU")
    assert bench._emit_cached(["x"]) is True
