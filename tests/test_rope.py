"""Rotary embedding tests: parity with the reference's complex-multiply
formulation (``megatron/model/positional_embeddings.py:7-51``), RoPE
scaling, position_ids."""

import jax.numpy as jnp
import numpy as np
import pytest

from megatron_llm_tpu.ops.rope import apply_rotary_emb, precompute_freqs_cis


def reference_complex_rope(x, end, theta=10000.0, scaling=1.0, position_ids=None):
    """Numpy re-derivation of the reference math: freqs_cis complex,
    interleaved pairs viewed as complex, elementwise multiply."""
    x = np.asarray(x, np.float32)
    b, s, h, d = x.shape
    freqs = 1.0 / (theta ** (np.arange(0, d, 2)[: d // 2] / d))
    t = np.arange(end) / scaling
    freqs_cis = np.exp(1j * np.outer(t, freqs))  # [end, d/2]
    if position_ids is None:
        fc = freqs_cis[:s][None, :, None, :]
    else:
        fc = freqs_cis[position_ids][:, :, None, :]
    xc = x.reshape(b, s, h, d // 2, 2)
    xc = xc[..., 0] + 1j * xc[..., 1]
    out = xc * fc
    res = np.stack([out.real, out.imag], axis=-1).reshape(b, s, h, d)
    return res.astype(np.float32)


def _x():
    rng = np.random.RandomState(7)
    return rng.randn(2, 16, 4, 8).astype(np.float32)


def test_matches_complex_reference():
    x = _x()
    cos, sin = precompute_freqs_cis(8, 32)
    out = apply_rotary_emb(jnp.asarray(x), cos, sin)
    np.testing.assert_allclose(out, reference_complex_rope(x, 32), atol=1e-5)


def test_rope_scaling():
    x = _x()
    cos, sin = precompute_freqs_cis(8, 32, scaling_factor=4.0)
    out = apply_rotary_emb(jnp.asarray(x), cos, sin)
    np.testing.assert_allclose(
        out, reference_complex_rope(x, 32, scaling=4.0), atol=1e-5
    )


def test_position_ids():
    x = _x()
    rng = np.random.RandomState(3)
    pos = rng.randint(0, 32, size=(2, 16))
    cos, sin = precompute_freqs_cis(8, 32)
    out = apply_rotary_emb(jnp.asarray(x), cos, sin, jnp.asarray(pos))
    np.testing.assert_allclose(
        out, reference_complex_rope(x, 32, position_ids=pos), atol=1e-5
    )


def test_norm_preserved():
    # rotation must preserve pairwise norms
    x = _x()
    cos, sin = precompute_freqs_cis(8, 32)
    out = np.asarray(apply_rotary_emb(jnp.asarray(x), cos, sin))
    n_in = np.linalg.norm(x.reshape(2, 16, 4, 4, 2), axis=-1)
    n_out = np.linalg.norm(out.reshape(2, 16, 4, 4, 2), axis=-1)
    np.testing.assert_allclose(n_in, n_out, atol=1e-4)


def test_llama3_scale_freqs_matches_hf():
    """ops.rope.llama3_scale_freqs reproduces HF's llama3 rope init
    (transformers.modeling_rope_utils._compute_llama3_parameters) over
    all three bands: untouched high-freq, /factor low-freq, and the
    smooth interpolation between."""
    pytest.importorskip("transformers")
    from transformers import LlamaConfig
    from transformers.modeling_rope_utils import ROPE_INIT_FUNCTIONS

    from megatron_llm_tpu.ops.rope import llama3_scale_freqs

    hf_cfg = LlamaConfig(
        rope_theta=500000.0, hidden_size=256, num_attention_heads=2,
        max_position_embeddings=65536,
        rope_scaling={"rope_type": "llama3", "factor": 8.0,
                      "low_freq_factor": 1.0, "high_freq_factor": 4.0,
                      "original_max_position_embeddings": 8192})
    hf_inv, _ = ROPE_INIT_FUNCTIONS["llama3"](hf_cfg, "cpu")
    base = 1.0 / (500000.0
                  ** (np.arange(0, 128, 2, dtype=np.float32) / 128))
    mine = np.asarray(llama3_scale_freqs(jnp.asarray(base),
                                         8.0, 1.0, 4.0, 8192))
    np.testing.assert_allclose(mine, hf_inv.numpy(), rtol=1e-6)
    # all three bands actually exercised
    ratio = mine / base
    assert (np.isclose(ratio, 1.0)).any(), "no untouched high-freq band"
    assert (np.isclose(ratio, 1 / 8.0)).any(), "no /factor low-freq band"
    assert ((ratio > 1 / 8.0 + 1e-3) & (ratio < 1.0 - 1e-3)).any(), \
        "no interpolation band"
