"""Rotary embedding tests: parity with the reference's complex-multiply
formulation (``megatron/model/positional_embeddings.py:7-51``), RoPE
scaling, position_ids."""

import jax.numpy as jnp
import numpy as np

from megatron_llm_tpu.ops.rope import apply_rotary_emb, precompute_freqs_cis


def reference_complex_rope(x, end, theta=10000.0, scaling=1.0, position_ids=None):
    """Numpy re-derivation of the reference math: freqs_cis complex,
    interleaved pairs viewed as complex, elementwise multiply."""
    x = np.asarray(x, np.float32)
    b, s, h, d = x.shape
    freqs = 1.0 / (theta ** (np.arange(0, d, 2)[: d // 2] / d))
    t = np.arange(end) / scaling
    freqs_cis = np.exp(1j * np.outer(t, freqs))  # [end, d/2]
    if position_ids is None:
        fc = freqs_cis[:s][None, :, None, :]
    else:
        fc = freqs_cis[position_ids][:, :, None, :]
    xc = x.reshape(b, s, h, d // 2, 2)
    xc = xc[..., 0] + 1j * xc[..., 1]
    out = xc * fc
    res = np.stack([out.real, out.imag], axis=-1).reshape(b, s, h, d)
    return res.astype(np.float32)


def _x():
    rng = np.random.RandomState(7)
    return rng.randn(2, 16, 4, 8).astype(np.float32)


def test_matches_complex_reference():
    x = _x()
    cos, sin = precompute_freqs_cis(8, 32)
    out = apply_rotary_emb(jnp.asarray(x), cos, sin)
    np.testing.assert_allclose(out, reference_complex_rope(x, 32), atol=1e-5)


def test_rope_scaling():
    x = _x()
    cos, sin = precompute_freqs_cis(8, 32, scaling_factor=4.0)
    out = apply_rotary_emb(jnp.asarray(x), cos, sin)
    np.testing.assert_allclose(
        out, reference_complex_rope(x, 32, scaling=4.0), atol=1e-5
    )


def test_position_ids():
    x = _x()
    rng = np.random.RandomState(3)
    pos = rng.randint(0, 32, size=(2, 16))
    cos, sin = precompute_freqs_cis(8, 32)
    out = apply_rotary_emb(jnp.asarray(x), cos, sin, jnp.asarray(pos))
    np.testing.assert_allclose(
        out, reference_complex_rope(x, 32, position_ids=pos), atol=1e-5
    )


def test_norm_preserved():
    # rotation must preserve pairwise norms
    x = _x()
    cos, sin = precompute_freqs_cis(8, 32)
    out = np.asarray(apply_rotary_emb(jnp.asarray(x), cos, sin))
    n_in = np.linalg.norm(x.reshape(2, 16, 4, 4, 2), axis=-1)
    n_out = np.linalg.norm(out.reshape(2, 16, 4, 4, 2), axis=-1)
    np.testing.assert_allclose(n_in, n_out, atol=1e-4)
