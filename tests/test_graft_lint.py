"""graft-lint (megatron_llm_tpu/analysis + tools/graft_lint.py):
per-checker positive/negative fixtures over tiny synthetic repos,
baseline round-trip with mandatory justifications, and the tier-1
acceptance gate — the linter must be green over THIS repo at HEAD.

The fixtures recreate the canonical paths each checker targets
(megatron_llm_tpu/arguments.py, megatron_llm_tpu/serving/engine.py,
tools/serve_report.py, tests/conftest.py, ...) inside tmp_path;
checkers degrade gracefully when a target file is absent, so each
fixture only writes the files its checker reads."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from megatron_llm_tpu.analysis import (
    Baseline,
    BaselineError,
    Repo,
    flags,
    locks,
    markers,
    recompile,
    run_checkers,
    stdlib_gate,
    telemetry_schema,
    threads,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LINT_CLI = os.path.join(REPO_ROOT, "tools", "graft_lint.py")


def _mk(tmp_path, files):
    """Write a synthetic repo: {relpath: source} -> Repo."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return Repo(str(tmp_path))


def _codes(violations):
    return sorted(v.code for v in violations)


def _cli(root, *extra):
    return subprocess.run(
        [sys.executable, LINT_CLI, "--root", str(root), *extra],
        capture_output=True, text=True, timeout=120)


# ---------------------------------------------------------------------------
# recompile
# ---------------------------------------------------------------------------

_JIT_HOT = """\
    import jax
    import jax.numpy as jnp

    @jax.jit
    def step(x):
        return _helper(x)

    def _helper(x):
        y = jnp.sum(x)
        return {body}
"""


def test_recompile_flags_item_reachable_from_jit_root(tmp_path):
    repo = _mk(tmp_path, {"megatron_llm_tpu/ops/hot.py":
                          _JIT_HOT.format(body="y.item()")})
    vs = recompile.check(repo)
    assert "RC001" in _codes(vs)
    assert any(v.path == "megatron_llm_tpu/ops/hot.py" for v in vs)


def test_recompile_clean_on_pure_math(tmp_path):
    repo = _mk(tmp_path, {"megatron_llm_tpu/ops/hot.py":
                          _JIT_HOT.format(body="y * 2")})
    assert recompile.check(repo) == []


def test_recompile_ignores_cold_functions(tmp_path):
    # .item() in a function no jit root reaches is host-side code — fine
    repo = _mk(tmp_path, {"megatron_llm_tpu/ops/cold.py": """\
        import jax.numpy as jnp

        def host_summary(x):
            return jnp.sum(x).item()
    """})
    assert recompile.check(repo) == []


# ---------------------------------------------------------------------------
# flags
# ---------------------------------------------------------------------------

_FLAGS_REPO = {
    "megatron_llm_tpu/arguments.py": """\
        def _add_training_args(parser):
            g = parser.add_argument_group("training")
            g.add_argument("--alpha", type=int, default=1)
            g.add_argument("--dead_flag", action="store_true")

        def _add_compat_noop_args(parser):
            g = parser.add_argument_group("compat")
            g.add_argument("--noop_thing", action="store_true")
    """,
    "megatron_llm_tpu/training.py": """\
        def run(args):
            return args.alpha + args.phantom
    """,
    "megatron_llm_tpu/config.py": """\
        class TransformerConfig:
            live: int = 1
            dead_knob: int = 0

        def use(cfg):
            return cfg.live
    """,
}


def test_flags_dead_phantom_and_dead_field(tmp_path):
    repo = _mk(tmp_path, _FLAGS_REPO)
    vs = flags.check(repo)
    by_code = {v.code: v for v in vs}
    assert set(by_code) == {"FW001", "FW002", "FW003"}
    assert by_code["FW001"].symbol == "dead_flag"       # --alpha is read
    assert by_code["FW002"].symbol == "phantom"
    assert by_code["FW003"].symbol == "TransformerConfig.dead_knob"
    # the documented noop group is exempt by design
    assert not any(v.symbol == "noop_thing" for v in vs)


def test_flags_clean_when_everything_is_wired(tmp_path):
    fixed = dict(_FLAGS_REPO)
    fixed["megatron_llm_tpu/training.py"] = """\
        def run(args, cfg):
            return args.alpha + int(args.dead_flag) + cfg.dead_knob
    """
    repo = _mk(tmp_path, fixed)
    assert flags.check(repo) == []


# ---------------------------------------------------------------------------
# telemetry schema
# ---------------------------------------------------------------------------

def _telemetry_repo(tmp_path, writer_keys, golden_keys, module_version=3,
                    pinned_version=3):
    writer = "\n".join(f'                "{k}": 1,' for k in writer_keys)
    golden = ", ".join(f'"{k}"' for k in golden_keys)
    return _mk(tmp_path, {
        "megatron_llm_tpu/serving/engine.py": f"""\
            class InferenceEngine:
                def _retire(self, req):
                    record = {{
            {writer}
                    }}
                    return record
        """,
        "megatron_llm_tpu/telemetry.py": f"""\
            TELEMETRY_SCHEMA_VERSION = {module_version}
        """,
        "tests/test_serving_engine.py": f"""\
            from megatron_llm_tpu import telemetry

            def test_request_done_schema_golden():
                rec = {{}}
                assert telemetry.TELEMETRY_SCHEMA_VERSION == {pinned_version}
                assert frozenset(rec) == frozenset(({golden},))
        """,
    })


def test_telemetry_writer_golden_drift_is_ts001(tmp_path):
    repo = _telemetry_repo(tmp_path, ["event", "sneaky_new_key"], ["event"])
    vs = telemetry_schema.check(repo)
    assert _codes(vs) == ["TS001"]
    assert "sneaky_new_key" in vs[0].message


def test_telemetry_key_change_without_version_bump_is_ts004(tmp_path):
    repo = _telemetry_repo(tmp_path, ["event", "added"], ["event", "added"])
    snap = Baseline(telemetry_schema={"version": 3,
                                      "request_done_keys": ["event"]})
    vs = telemetry_schema.check(repo, snap)
    assert _codes(vs) == ["TS004"]
    # bumping the version turns TS004 into TS005 (stale snapshot)
    repo2 = _telemetry_repo(tmp_path, ["event", "added"], ["event", "added"],
                            module_version=4, pinned_version=4)
    assert _codes(telemetry_schema.check(repo2, snap)) == ["TS005"]


def test_telemetry_pinned_version_drift_is_ts006(tmp_path):
    repo = _telemetry_repo(tmp_path, ["event"], ["event"],
                           module_version=4, pinned_version=3)
    assert _codes(telemetry_schema.check(repo)) == ["TS006"]


def test_telemetry_agreement_is_clean(tmp_path):
    repo = _telemetry_repo(tmp_path, ["event", "kind"], ["event", "kind"])
    snap = Baseline(telemetry_schema={"version": 3,
                                      "request_done_keys": ["event", "kind"]})
    assert telemetry_schema.check(repo, snap) == []


def test_telemetry_record_snapshot_roundtrip(tmp_path):
    repo = _telemetry_repo(tmp_path, ["event", "kind"], ["event", "kind"])
    b = Baseline()
    snap = telemetry_schema.record_snapshot(repo, b)
    assert snap == {"version": 3, "request_done_keys": ["event", "kind"]}
    assert telemetry_schema.check(repo, b) == []


# ---------------------------------------------------------------------------
# stdlib gate
# ---------------------------------------------------------------------------

def test_stdlib_gate_flags_jax_in_gated_tool(tmp_path):
    repo = _mk(tmp_path, {"tools/serve_report.py": """\
        import json
        import jax
    """})
    vs = stdlib_gate.check(repo)
    assert _codes(vs) == ["SG001"]
    assert vs[0].symbol == "jax"


def test_stdlib_gate_allows_stdlib_and_guarded_imports(tmp_path):
    repo = _mk(tmp_path, {"tools/serve_report.py": """\
        import argparse
        import json

        try:
            import numpy as np
        except ImportError:
            np = None
    """})
    assert stdlib_gate.check(repo) == []


def test_stdlib_gate_only_applies_to_gated_files(tmp_path):
    repo = _mk(tmp_path, {"tools/random_helper.py": "import jax\n"})
    assert stdlib_gate.check(repo) == []


# ---------------------------------------------------------------------------
# locks
# ---------------------------------------------------------------------------

_LOCKS_REPO = {"megatron_llm_tpu/serving/engine.py": """\
    import threading
    import time

    class Manager:
        _lock_protected_ = ("count",)

        def __init__(self):
            self._lock = threading.Lock()
            self.count = 0          # __init__ is exempt

        def bad_sleep(self):
            with self._lock:
                time.sleep(1)       # LD001

        def bad_write(self):
            self.count += 1         # LD002

        def good_write(self):
            with self._lock:
                self.count += 1

        def bump_locked(self):
            self.count += 1         # *_locked: caller holds the lock
"""}


def test_locks_blocking_and_unlocked_write(tmp_path):
    repo = _mk(tmp_path, _LOCKS_REPO)
    vs = locks.check(repo)
    assert _codes(vs) == ["LD001", "LD002"]
    ld2 = next(v for v in vs if v.code == "LD002")
    assert "bad_write" in ld2.symbol


def test_locks_clean_class_without_annotation(tmp_path):
    # no _lock_protected_ declaration -> LD002 never fires; LD001 still
    # guards any with-lock block
    repo = _mk(tmp_path, {"megatron_llm_tpu/serving/engine.py": """\
        import threading

        class Plain:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0

            def write(self):
                self.count += 1
    """})
    assert locks.check(repo) == []


# ---------------------------------------------------------------------------
# threads (graft-race)
# ---------------------------------------------------------------------------

_TH001_REPO = {"megatron_llm_tpu/shared.py": """\
    import threading

    class Shared:
        def __init__(self):
            self.count = 0
            threading.Thread(target=self._a, name="writer-a",
                             daemon=True).start()
            threading.Thread(target=self._b, name="writer-b",
                             daemon=True).start()

        def _a(self):
            while True:
                self.count += 1

        def _b(self):
            while True:
                self.count += 1
"""}


def test_th001_two_roots_no_lock(tmp_path):
    repo = _mk(tmp_path, _TH001_REPO)
    vs = threads.check(repo)
    assert "TH001" in _codes(vs)
    v = next(v for v in vs if v.code == "TH001")
    assert v.symbol == "Shared.count"
    assert "writer-a" in v.message and "writer-b" in v.message
    # the fix-hint is a paste-able annotation
    assert '_lock_protected_ = {"count": "_lock"}' in v.message


def test_th001_clean_under_common_lock(tmp_path):
    repo = _mk(tmp_path, {"megatron_llm_tpu/shared.py": """\
        import threading

        class Shared:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0
                threading.Thread(target=self._a, name="writer-a",
                                 daemon=True).start()
                threading.Thread(target=self._b, name="writer-b",
                                 daemon=True).start()

            def _a(self):
                while True:
                    with self._lock:
                        self.count += 1

            def _b(self):
                while True:
                    with self._lock:
                        self.count += 1
    """})
    assert [v for v in threads.check(repo) if v.code == "TH001"] == []


def test_th001_single_writer_root_is_clean(tmp_path):
    # one thread publishes, others only read: scalar publish is fine
    repo = _mk(tmp_path, {"megatron_llm_tpu/shared.py": """\
        import threading

        class Shared:
            def __init__(self):
                self.count = 0
                threading.Thread(target=self._a, name="writer-a",
                                 daemon=True).start()
                threading.Thread(target=self._b, name="reader-b",
                                 daemon=True).start()

            def _a(self):
                while True:
                    self.count += 1

            def _b(self):
                while True:
                    print(self.count)
    """})
    assert [v for v in threads.check(repo) if v.code == "TH001"] == []


def test_th002_deliberate_lock_order_cycle(tmp_path):
    repo = _mk(tmp_path, {"megatron_llm_tpu/ab.py": """\
        import threading

        class AB:
            def __init__(self):
                self._alock = threading.Lock()
                self._block = threading.Lock()
                threading.Thread(target=self.fwd, name="fwd",
                                 daemon=True).start()
                threading.Thread(target=self.rev, name="rev",
                                 daemon=True).start()

            def fwd(self):
                with self._alock:
                    with self._block:
                        pass

            def rev(self):
                with self._block:
                    with self._alock:
                        pass
    """})
    vs = [v for v in threads.check(repo) if v.code == "TH002"]
    assert vs, "lock-order inversion not detected"
    assert "AB._alock" in vs[0].symbol and "AB._block" in vs[0].symbol


def test_th002_nonreentrant_self_acquire(tmp_path):
    repo = _mk(tmp_path, {"megatron_llm_tpu/ab.py": """\
        import threading

        class AB:
            def __init__(self):
                self._alock = threading.Lock()
                threading.Thread(target=self.outer, name="w",
                                 daemon=True).start()

            def outer(self):
                with self._alock:
                    self.inner()

            def inner(self):
                with self._alock:
                    pass
    """})
    vs = [v for v in threads.check(repo) if v.code == "TH002"]
    assert vs and "AB._alock->AB._alock" in vs[0].symbol
    # an RLock makes the same shape legal
    repo2 = _mk(tmp_path / "r", {"megatron_llm_tpu/ab.py": """\
        import threading

        class AB:
            def __init__(self):
                self._alock = threading.RLock()
                threading.Thread(target=self.outer, name="w",
                                 daemon=True).start()

            def outer(self):
                with self._alock:
                    self.inner()

            def inner(self):
                with self._alock:
                    pass
    """})
    assert [v for v in threads.check(repo2) if v.code == "TH002"] == []


def test_th002_consistent_order_is_clean(tmp_path):
    repo = _mk(tmp_path, {"megatron_llm_tpu/ab.py": """\
        import threading

        class AB:
            def __init__(self):
                self._alock = threading.Lock()
                self._block = threading.Lock()
                threading.Thread(target=self.fwd, name="fwd",
                                 daemon=True).start()
                threading.Thread(target=self.fwd2, name="fwd2",
                                 daemon=True).start()

            def fwd(self):
                with self._alock:
                    with self._block:
                        pass

            def fwd2(self):
                with self._alock:
                    with self._block:
                        pass
    """})
    assert [v for v in threads.check(repo) if v.code == "TH002"] == []


def test_th003_blocking_under_contested_lock(tmp_path):
    repo = _mk(tmp_path, {"megatron_llm_tpu/svc.py": """\
        import threading
        import time

        class Svc:
            def __init__(self):
                self._lock = threading.Lock()
                threading.Thread(target=self.worker, name="worker",
                                 daemon=True).start()
                threading.Thread(target=self.poller, name="poller",
                                 daemon=True).start()

            def worker(self):
                with self._lock:
                    time.sleep(1.0)

            def poller(self):
                while True:
                    with self._lock:
                        pass
    """})
    vs = [v for v in threads.check(repo) if v.code == "TH003"]
    assert vs, "blocking under contested lock not detected"
    assert "time.sleep" in vs[0].message
    assert "poller" in vs[0].message


def test_th003_clean_when_sleep_is_outside_lock(tmp_path):
    repo = _mk(tmp_path, {"megatron_llm_tpu/svc.py": """\
        import threading
        import time

        class Svc:
            def __init__(self):
                self._lock = threading.Lock()
                threading.Thread(target=self.worker, name="worker",
                                 daemon=True).start()
                threading.Thread(target=self.poller, name="poller",
                                 daemon=True).start()

            def worker(self):
                with self._lock:
                    pass
                time.sleep(1.0)

            def poller(self):
                while True:
                    with self._lock:
                        pass
    """})
    assert [v for v in threads.check(repo) if v.code == "TH003"] == []


def test_th004_use_after_drain_daemon(tmp_path):
    repo = _mk(tmp_path, {"megatron_llm_tpu/pump.py": """\
        import threading
        import time

        class Pump:
            _lock_protected_ = {"total": "_lock"}

            def __init__(self):
                self._lock = threading.Lock()
                self._stop = False
                self.total = 0
                threading.Thread(target=self._run, name="pump",
                                 daemon=True).start()

            def _run(self):
                while not self._stop:
                    time.sleep(0.05)
                    self.total += 1
    """})
    vs = [v for v in threads.check(repo) if v.code == "TH004"]
    assert vs, "use-after-drain not detected"
    assert "total" in vs[0].symbol
    assert "time.sleep" in vs[0].message


def test_th004_clean_when_flag_rechecked(tmp_path):
    repo = _mk(tmp_path, {"megatron_llm_tpu/pump.py": """\
        import threading
        import time

        class Pump:
            _lock_protected_ = {"total": "_lock"}

            def __init__(self):
                self._lock = threading.Lock()
                self._stop = False
                self.total = 0
                threading.Thread(target=self._run, name="pump",
                                 daemon=True).start()

            def _run(self):
                while not self._stop:
                    time.sleep(0.05)
                    if self._stop:
                        return
                    self.total += 1
    """})
    assert [v for v in threads.check(repo) if v.code == "TH004"] == []


def test_threads_baseline_roundtrip(tmp_path):
    repo = _mk(tmp_path, _TH001_REPO)
    vs = [v for v in threads.check(repo) if v.code == "TH001"]
    assert vs
    b = Baseline()
    for v in vs:
        b.add(v.fingerprint, "fixture: deliberate race for the test")
    path = str(tmp_path / ".graftlint.json")
    b.save(path)
    loaded = Baseline.load(path)
    unsuppressed, suppressed, stale = run_checkers(repo, loaded,
                                                   names=["threads"])
    assert unsuppressed == []
    assert len(suppressed) == len(vs)
    assert stale == []


def test_threads_fingerprint_is_line_number_free(tmp_path):
    repo = _mk(tmp_path, _TH001_REPO)
    fp1 = {v.fingerprint for v in threads.check(repo)}
    shifted = {"megatron_llm_tpu/shared.py":
               "# comment pushing every line down\n\n"
               + textwrap.dedent(_TH001_REPO["megatron_llm_tpu/shared.py"])}
    repo2 = _mk(tmp_path / "shifted", shifted)
    assert fp1 == {v.fingerprint for v in threads.check(repo2)}


def test_suggest_locks_emits_annotation(tmp_path):
    repo = _mk(tmp_path, _TH001_REPO)
    text = threads.suggest_locks(repo)
    assert "class Shared" in text
    assert '"count": "_lock"' in text
    assert "writer-a" in text


# ---------------------------------------------------------------------------
# the real concurrency fixes are regression-guarded by the checker:
# a synthetic copy of the drain-counter pattern with the fix deleted
# must turn graft_lint red (TH001), and the fixed shape stays green
# ---------------------------------------------------------------------------

_DRAIN_FIXED = """\
    import signal
    import threading
    from http.server import BaseHTTPRequestHandler

    class Metrics:
        _lock_protected_ = {"drained": "_lock"}

        def __init__(self):
            self._lock = threading.Lock()
            self.drained = 0

        def note_drained(self):
            with self._lock:
                self.drained += 1

    class Server:
        def __init__(self):
            self.metrics = Metrics()

        def begin_drain(self):
            self.metrics.note_drained()

        def run(self):
            server = self

            class Handler(BaseHTTPRequestHandler):
                def do_PUT(self):
                    server.begin_drain()

            signal.signal(signal.SIGTERM,
                          lambda *_: server.begin_drain())
"""


def test_deleting_the_drain_fix_turns_lint_red(tmp_path):
    # fixed shape (mirrors ServerMetrics.note_drained): green
    _mk(tmp_path, {"megatron_llm_tpu/server_sim.py": _DRAIN_FIXED})
    res = _cli(tmp_path, "--checkers", "threads")
    assert res.returncode == 0, res.stdout + res.stderr
    # delete the fix: bump the counter directly, without the lock —
    # the signal and HTTP-handler roots now race on Metrics.drained
    broken = _DRAIN_FIXED.replace("self.metrics.note_drained()",
                                  "self.metrics.drained += 1")
    _mk(tmp_path / "broken",
        {"megatron_llm_tpu/server_sim.py": broken})
    res = _cli(tmp_path / "broken", "--checkers", "threads")
    assert res.returncode == 1, res.stdout + res.stderr
    assert "TH001" in res.stdout
    assert "Metrics.drained" in res.stdout


# ---------------------------------------------------------------------------
# markers
# ---------------------------------------------------------------------------

_MARKERS_REPO = {
    "tests/conftest.py": """\
        def pytest_configure(config):
            config.addinivalue_line("markers", "slow: long-running")
    """,
    "tests/test_x.py": """\
        import pytest

        @pytest.mark.slow
        def test_registered():
            pass

        @pytest.mark.solw
        def test_typo():
            pass

        @pytest.mark.parametrize("n", [1])
        def test_builtin(n):
            pass
    """,
}


def test_markers_typo_is_pm001(tmp_path):
    repo = _mk(tmp_path, _MARKERS_REPO)
    vs = markers.check(repo)
    assert _codes(vs) == ["PM001"]
    assert vs[0].symbol == "solw"


def test_markers_registered_and_builtin_are_clean(tmp_path):
    fixed = dict(_MARKERS_REPO)
    fixed["tests/test_x.py"] = fixed["tests/test_x.py"].replace("solw",
                                                                "slow")
    repo = _mk(tmp_path, fixed)
    assert markers.check(repo) == []


# ---------------------------------------------------------------------------
# baseline round-trip
# ---------------------------------------------------------------------------

def test_baseline_suppression_roundtrip(tmp_path):
    repo = _mk(tmp_path, _LOCKS_REPO)
    vs = locks.check(repo)
    assert len(vs) == 2
    b = Baseline()
    for v in vs:
        b.add(v.fingerprint, "fixture: intentionally bad on purpose")
    path = str(tmp_path / ".graftlint.json")
    b.save(path)

    loaded = Baseline.load(path)
    unsuppressed, suppressed, stale = run_checkers(repo, loaded,
                                                   names=["locks"])
    assert unsuppressed == []
    assert len(suppressed) == 2
    assert stale == []


def test_baseline_stale_suppression_is_reported(tmp_path):
    repo = _mk(tmp_path, _LOCKS_REPO)
    b = Baseline()
    b.add("locks:LD001:megatron_llm_tpu/serving/gone.py:Ghost.f/time.sleep",
          "excuses a violation that no longer exists")
    _un, _sup, stale = run_checkers(repo, b, names=["locks"])
    assert stale == ["locks:LD001:megatron_llm_tpu/serving/gone.py:"
                     "Ghost.f/time.sleep"]
    # a suppression for a checker that did NOT run is never "stale"
    _un, _sup, stale = run_checkers(repo, b, names=["markers"])
    assert stale == []


def test_baseline_requires_justification(tmp_path):
    path = tmp_path / ".graftlint.json"
    path.write_text(json.dumps({
        "version": 1,
        "suppressions": [{"id": "locks:LD001:x.py:f", "justification": ""}],
    }))
    with pytest.raises(BaselineError, match="justification"):
        Baseline.load(str(path))
    with pytest.raises(BaselineError):
        Baseline().add("locks:LD001:x.py:f", "   ")


def test_baseline_rejects_unknown_keys(tmp_path):
    path = tmp_path / ".graftlint.json"
    path.write_text(json.dumps({"version": 1, "ignore": ["everything"]}))
    with pytest.raises(BaselineError, match="unknown keys"):
        Baseline.load(str(path))


def test_baseline_fingerprint_is_line_number_free(tmp_path):
    # moving the violation within the file must not invalidate the
    # suppression — that is the whole point of symbol fingerprints
    repo = _mk(tmp_path, _LOCKS_REPO)
    fp1 = {v.fingerprint for v in locks.check(repo)}
    shifted = {"megatron_llm_tpu/serving/engine.py":
               "# a comment pushing every line down\n\n"
               + textwrap.dedent(_LOCKS_REPO[
                   "megatron_llm_tpu/serving/engine.py"])}
    repo2 = _mk(tmp_path / "shifted", shifted)
    assert fp1 == {v.fingerprint for v in locks.check(repo2)}


# ---------------------------------------------------------------------------
# CLI: non-zero on injected violations, zero over the real repo
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("checker,files", [
    ("recompile", {"megatron_llm_tpu/ops/hot.py":
                   _JIT_HOT.format(body="y.item()")}),
    ("flags", _FLAGS_REPO),
    ("telemetry", None),  # built by _telemetry_repo below
    ("stdlib", {"tools/serve_report.py": "import jax\n"}),
    ("locks", _LOCKS_REPO),
])
def test_cli_exits_nonzero_on_each_checker(tmp_path, checker, files):
    if files is None:
        _telemetry_repo(tmp_path, ["event", "drifted"], ["event"])
    else:
        _mk(tmp_path, files)
    res = _cli(tmp_path, "--checkers", checker)
    assert res.returncode == 1, res.stdout + res.stderr
    assert checker in res.stdout


def test_cli_exit_2_on_malformed_baseline(tmp_path):
    (tmp_path / ".graftlint.json").write_text("{not json")
    res = _cli(tmp_path)
    assert res.returncode == 2


def test_graft_lint_is_green_over_this_repo():
    """Tier-1 acceptance: the checked-in baseline keeps the real repo
    clean — every violation is either fixed or suppressed with a
    justification.  A red run here means a hot-path host sync, a dead
    flag, a schema drift, a jax import in a stdlib tool, a lock
    violation, or a thread-topology race landed since the last
    ratchet.  --expect-checkers pins the full set (incl. threads) so
    the gate cannot silently narrow."""
    res = subprocess.run([sys.executable, LINT_CLI,
                          "--expect-checkers", "7"],
                         capture_output=True, text=True, timeout=300,
                         cwd=REPO_ROOT)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "0 violation(s)" in res.stdout
    assert "7 checker(s) ran" in res.stdout


def test_cli_expect_checkers_guards_narrowed_set(tmp_path):
    _mk(tmp_path, {"megatron_llm_tpu/empty.py": "x = 1\n"})
    res = _cli(tmp_path, "--checkers", "locks", "--expect-checkers", "7")
    assert res.returncode == 2
    assert "expected >= 7" in res.stderr


def test_cli_threads_table_and_doc_agree(tmp_path):
    """--threads output is embedded verbatim in docs/guide/serving.md
    ("Threading model"); diffing doc against tool keeps the doc honest
    when a thread root is added, renamed, or removed."""
    table = threads.threads_table(Repo(REPO_ROOT))
    doc = open(os.path.join(REPO_ROOT, "docs", "guide",
                            "serving.md")).read()
    missing = [row for row in table.splitlines() if row not in doc]
    assert not missing, (
        "docs/guide/serving.md 'Threading model' table is stale; "
        "regenerate with `python tools/graft_lint.py --threads` and "
        "paste.  Missing rows:\n" + "\n".join(missing))
    # CLI smoke on a small fixture root: a second full-repo parse in a
    # subprocess would add no coverage over the in-process table above.
    repo = _mk(tmp_path, _TH001_REPO)
    res = subprocess.run([sys.executable, LINT_CLI, "--threads",
                          "--root", str(tmp_path)],
                         capture_output=True, text=True, timeout=300)
    assert res.returncode == 0
    assert res.stdout.strip() == threads.threads_table(repo).strip()


def test_cli_changed_only_reports_only_changed_files(tmp_path):
    """--changed-only parity: the reported set is exactly the full
    run's violations intersected with the files changed vs the ref
    (checkers still analyze the whole repo)."""
    _mk(tmp_path, {
        "megatron_llm_tpu/serving/engine.py":
            _LOCKS_REPO["megatron_llm_tpu/serving/engine.py"],
        "tools/serve_report.py": "import jax\n",
    })
    git = ["git", "-c", "user.email=t@t", "-c", "user.name=t"]
    subprocess.run(["git", "init", "-q"], cwd=tmp_path, check=True)
    subprocess.run([*git, "add", "-A"], cwd=tmp_path, check=True)
    subprocess.run([*git, "commit", "-q", "-m", "seed"], cwd=tmp_path,
                   check=True)
    # touch only the locks fixture
    p = tmp_path / "megatron_llm_tpu" / "serving" / "engine.py"
    p.write_text(p.read_text() + "\n# touched\n")

    full = _cli(tmp_path, "--checkers", "locks,stdlib")
    assert full.returncode == 1
    assert "LD001" in full.stdout and "SG001" in full.stdout

    res = _cli(tmp_path, "--checkers", "locks,stdlib",
               "--changed-only", "HEAD")
    assert res.returncode == 1, res.stdout + res.stderr
    assert "LD001" in res.stdout and "LD002" in res.stdout
    assert "SG001" not in res.stdout     # unchanged file not reported
    # parity: reported lines == full-run lines for the changed file
    want = sorted(ln for ln in full.stdout.splitlines()
                  if ln.startswith("megatron_llm_tpu/serving/engine.py"))
    got = sorted(ln for ln in res.stdout.splitlines()
                 if ": LD" in ln or ": SG" in ln)
    assert got == want


def test_cli_changed_only_clean_when_no_violating_file_changed(tmp_path):
    _mk(tmp_path, {"tools/serve_report.py": "import jax\n",
                   "megatron_llm_tpu/ok.py": "x = 1\n"})
    git = ["git", "-c", "user.email=t@t", "-c", "user.name=t"]
    subprocess.run(["git", "init", "-q"], cwd=tmp_path, check=True)
    subprocess.run([*git, "add", "-A"], cwd=tmp_path, check=True)
    subprocess.run([*git, "commit", "-q", "-m", "seed"], cwd=tmp_path,
                   check=True)
    p = tmp_path / "megatron_llm_tpu" / "ok.py"
    p.write_text("x = 2\n")
    res = _cli(tmp_path, "--checkers", "stdlib",
               "--changed-only", "HEAD")
    assert res.returncode == 0, res.stdout + res.stderr


def test_sweep_wave0_pins_the_checker_count():
    """tools/tpu_sweep.py's wave-0 static gate must assert the full
    checker set ran — a narrowed set silently skipping the threads
    checker would pass an otherwise red sweep."""
    sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))
    try:
        import tpu_sweep
    finally:
        sys.path.pop(0)
    step = next(s for s in tpu_sweep.MANIFEST if s.name == "graft_lint")
    assert step.wave == 0
    assert "--expect-checkers 7" in step.cmd
