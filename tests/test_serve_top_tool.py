"""tools/serve_top.py: snapshot building from router-fleet and bare
replica /metrics shapes, frame-delta token rates, the --once/--json CLI
against a canned stdlib stub, and (slow) one live frame from a real
2-replica, 2-router front door."""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))
import serve_top  # noqa: E402

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _replica_snap(requests=10, tokens=500, bubble=None):
    """A minimal ServerMetrics.snapshot() twin."""
    snap = {
        "uptime_secs": 60.0, "requests": requests, "errors": 0,
        "tokens_generated": tokens,
        "slo": {"ttft_secs_p95": 0.12, "tpot_secs_p95": 0.034},
        "histograms": {},
        "engine": {
            "queue_depth": 1, "mean_batch_occupancy": 2.5,
            "prefix_cache_hits": 6, "prefix_cache_misses": 2,
            "engine_restarts": 1,
            "cache": {
                "probes": 8, "hits": 6,
                "evictions_capacity": 1, "evictions_churn": 3,
                "ghost": {"x10": {"hit_rate": 0.9}},
                "host_hits": 2,
                "host": {"enabled": 1, "spills_completed": 4},
            },
        },
    }
    if bubble is not None:
        snap["engine"]["loop"] = {
            "device_busy_pct": round(100.0 - bubble, 3),
            "host_bubble_pct": bubble, "stalls": 2,
        }
    return snap


def _fleet_doc():
    """A router fleet /metrics document: backend_0 healthy, backend_1
    unreachable this probe, backend_2 draining."""
    return {
        "router": {
            "router_id": "r0", "backends_total": 3, "backends_alive": 2,
            "requests_total": 30, "failovers_total": 1,
            "inflight_requests": 4, "brownout_active": True,
            "brownout_remaining_secs": 2.5,
            "backends": {
                "backend_0": {"url": "127.0.0.1:7001", "alive": True,
                              "draining": False},
                "backend_1": {"url": "127.0.0.1:7002", "alive": False,
                              "draining": False},
                "backend_2": {"url": "127.0.0.1:7003", "alive": True,
                              "draining": True},
            },
        },
        "router_tier": {"routers_total": 2, "routers_reporting": 2},
        "aggregate": {"requests": 30},
        "backends": {
            "backend_0": _replica_snap(bubble=35.5),
            "backend_1": None,
            "backend_2": _replica_snap(requests=5, tokens=100),
        },
    }


def test_build_snapshot_router_view():
    snap = serve_top.build_snapshot("http://x", _fleet_doc())
    assert snap["source"] == "router"
    assert snap["router"]["brownout_active"] is True
    assert snap["router_tier"] == {"routers_total": 2,
                                   "routers_reporting": 2}
    rows = {r["name"]: r for r in snap["replicas"]}
    assert set(rows) == {"backend_0", "backend_1", "backend_2"}
    r0 = rows["backend_0"]
    assert r0["alive"] and not r0["draining"]
    assert r0["occupancy"] == 2.5
    assert r0["ttft_p95_secs"] == 0.12
    assert r0["cache_hit_rate"] == pytest.approx(0.75)
    # cache observatory cumulative counters ride into the row; the
    # windowed rates need a previous frame (add_rates)
    assert r0["cache_probes"] == 8 and r0["cache_hits"] == 6
    assert r0["cache_evictions"] == 4
    assert r0["ghost_x10_hit_rate"] == pytest.approx(0.9)
    assert r0["cache_hit_rate_window"] is None
    # host spill tier counters ride into the row; windowed rates need
    # a previous frame too
    assert r0["cache_host_hits"] == 2 and r0["host_spills"] == 4
    assert r0["host_hit_rate_window"] is None
    assert r0["host_spills_per_sec"] is None
    assert r0["host_bubble_pct"] == 35.5
    assert r0["loop_stalls"] == 2
    assert r0["engine_restarts"] == 1
    # unreachable this probe: present, dead, all-None metrics
    assert rows["backend_1"]["alive"] is False
    assert rows["backend_1"]["requests"] is None
    assert rows["backend_2"]["draining"] is True
    # no loop block on backend_2: bubble stays None, row still renders
    assert rows["backend_2"]["host_bubble_pct"] is None
    assert snap["fleet"]["replicas_total"] == 3
    assert snap["fleet"]["replicas_alive"] == 2
    assert snap["fleet"]["tokens_generated"] == 600


def test_build_snapshot_bare_replica_view():
    snap = serve_top.build_snapshot("http://x", _replica_snap(bubble=10.0))
    assert snap["source"] == "replica"
    assert snap["router"] is None
    [row] = snap["replicas"]
    assert row["alive"] and row["host_bubble_pct"] == 10.0


def test_add_rates_from_frame_deltas():
    prev = serve_top.build_snapshot("http://x", _fleet_doc())
    prev["time_unix"] = 100.0
    doc = _fleet_doc()
    doc["backends"]["backend_0"]["tokens_generated"] += 50
    doc["backends"]["backend_2"]["tokens_generated"] += 30
    cache0 = doc["backends"]["backend_0"]["engine"]["cache"]
    cache0["probes"] += 10                  # this frame: 5/10 hit
    cache0["hits"] += 5
    cache0["evictions_churn"] += 6          # 6 evictions / 2s
    cache0["host_hits"] += 3                # this frame: 3/10 host-tier
    cache0["host"]["spills_completed"] += 8  # 8 spills / 2s
    cur = serve_top.build_snapshot("http://x", doc)
    cur["time_unix"] = 102.0
    serve_top.add_rates(cur, prev)
    rows = {r["name"]: r for r in cur["replicas"]}
    assert rows["backend_0"]["tokens_per_sec"] == pytest.approx(25.0)
    assert rows["backend_2"]["tokens_per_sec"] == pytest.approx(15.0)
    assert rows["backend_1"]["tokens_per_sec"] is None
    assert cur["fleet"]["tokens_per_sec"] == pytest.approx(40.0)
    # windowed cache hit rate is THIS frame's delta, not lifetime
    assert rows["backend_0"]["cache_hit_rate_window"] == pytest.approx(0.5)
    assert rows["backend_0"]["evictions_per_sec"] == pytest.approx(3.0)
    assert rows["backend_2"]["cache_hit_rate_window"] is None  # no delta
    assert rows["backend_1"]["evictions_per_sec"] is None
    # host tier: windowed hit share of this frame's probes, spills/sec
    assert rows["backend_0"]["host_hit_rate_window"] == pytest.approx(0.3)
    assert rows["backend_0"]["host_spills_per_sec"] == pytest.approx(4.0)
    assert rows["backend_2"]["host_hit_rate_window"] is None
    assert rows["backend_1"]["host_spills_per_sec"] is None
    # first frame: no previous, rates stay None
    fresh = serve_top.build_snapshot("http://x", _fleet_doc())
    serve_top.add_rates(fresh, {})
    assert fresh["fleet"]["tokens_per_sec"] is None


def test_hist_pct_matches_telemetry_estimator():
    from megatron_llm_tpu import telemetry
    h = telemetry.Histogram((0.1, 0.5, 1.0))
    for v in (0.05, 0.3, 0.3, 0.7, 2.0):
        h.observe(v)
    snap = h.snapshot()
    for q in (0.5, 0.95):
        assert serve_top._hist_pct(snap, q) == pytest.approx(
            telemetry.histogram_percentile(snap, q))
    assert serve_top._hist_pct({}, 0.5) is None
    assert serve_top._hist_pct({"buckets": {}, "count": 0}, 0.5) is None


@pytest.fixture()
def stub_fleet():
    doc = _fleet_doc()

    class H(BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path == "/metrics":
                data = json.dumps(doc).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
            else:
                self.send_response(404)
                self.end_headers()

        def log_message(self, *a):
            pass

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{httpd.server_address[1]}"
    httpd.shutdown()


def test_cli_once_json_against_stub(stub_fleet):
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "serve_top.py"),
         "--url", stub_fleet, "--once", "--json"],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    snap = json.loads(out.stdout)
    assert snap["source"] == "router"
    assert snap["fleet"]["replicas_alive"] == 2
    rows = {r["name"]: r for r in snap["replicas"]}
    assert rows["backend_0"]["host_bubble_pct"] == 35.5


def test_cli_once_table_renders(stub_fleet, capsys):
    assert serve_top.main(["--url", stub_fleet, "--once"]) == 0
    out = capsys.readouterr().out
    assert "replicas 2/3" in out
    assert "routers 2/2" in out
    assert "BROWNOUT" in out
    for col in ("replica", "occ", "tok/s", "ttft_p95", "hit%", "whit%",
                "g10%", "hhit%", "ev/s", "sp/s", "bubble%", "stalls",
                "restarts"):
        assert col in out
    assert "DOWN" in out and "DRAIN" in out


def test_cli_once_fetch_failure_exits_1(capsys):
    # a port nothing listens on: --once reports and exits non-zero
    assert serve_top.main(["--url", "http://127.0.0.1:9",
                           "--once", "--timeout", "0.5"]) == 1
    assert "cannot fetch" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# slow tier: one live frame from a real 2-replica, 2-router front door
# ---------------------------------------------------------------------------

def _spawn_replica(timeout=240.0):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)      # single-device child, no 8-dev mesh
    proc = subprocess.Popen(
        [sys.executable, os.path.join(ROOT, "tests", "_serve_replica.py")],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=env,
        text=True, cwd=ROOT)
    deadline = time.monotonic() + timeout
    port = None
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if line.startswith("PORT "):
            port = int(line.split()[1])
            break
        if proc.poll() is not None:
            raise RuntimeError("replica died during startup")
    assert port, "replica did not report a port in time"
    return proc, port


@pytest.mark.slow
def test_serve_top_once_json_live_router_tier():
    """Acceptance: ``serve_top --once --json`` against one router of a
    live 2-router / 2-replica front door reports both replicas alive
    with engine-loop goodput populated by real traffic."""
    from megatron_llm_tpu.serving import ReplicaRouter, RouterServer

    procs, servers = [], []
    try:
        p0, port0 = _spawn_replica()
        procs.append(p0)
        p1, port1 = _spawn_replica()
        procs.append(p1)
        backends = [f"127.0.0.1:{port0}", f"127.0.0.1:{port1}"]

        def start_router():
            router = ReplicaRouter(backends, health_interval_secs=0.5,
                                   request_timeout_secs=120.0)
            srv = RouterServer(router)
            threading.Thread(target=srv.run,
                             kwargs={"host": "127.0.0.1", "port": 0},
                             daemon=True).start()
            for _ in range(100):
                if srv.httpd is not None:
                    break
                time.sleep(0.05)
            servers.append(srv)
            return router, f"127.0.0.1:{srv.httpd.server_address[1]}"

        router_a, addr_a = start_router()
        router_b, addr_b = start_router()
        router_a.set_peers([addr_b])
        router_b.set_peers([addr_a])
        url = f"http://{addr_a}"

        # real traffic through the front door so loop goodput populates
        # on both replicas (distinct prompts defeat sticky affinity)
        for i in range(8):
            req = urllib.request.Request(
                url + "/api",
                data=json.dumps({"prompts": [f"{i + 1} 2 3 4 5"],
                                 "tokens_to_generate": 8,
                                 "temperature": 0.0,
                                 "no_log": True}).encode(),
                method="PUT")
            with urllib.request.urlopen(req, timeout=120) as resp:
                assert resp.status == 200

        out = subprocess.run(
            [sys.executable, os.path.join(ROOT, "tools", "serve_top.py"),
             "--url", url, "--once", "--json"],
            capture_output=True, text=True, timeout=120)
        assert out.returncode == 0, out.stderr
        snap = json.loads(out.stdout)
        assert snap["source"] == "router"
        assert snap["router_tier"]["routers_total"] == 2
        assert snap["fleet"]["replicas_total"] == 2
        assert snap["fleet"]["replicas_alive"] == 2
        assert snap["fleet"]["requests"] >= 8
        served = [r for r in snap["replicas"] if (r["requests"] or 0) > 0]
        assert served, "no replica reports traffic"
        for row in served:
            assert row["occupancy"] is not None
            assert row["device_busy_pct"] is not None
            assert row["host_bubble_pct"] == pytest.approx(
                100.0 - row["device_busy_pct"], abs=0.01)
            assert row["engine_restarts"] == 0
    finally:
        for srv in servers:
            try:
                srv.httpd.shutdown()
            except Exception:
                pass
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                p.kill()
