"""RNG seed-domain semantics (mirrors the intent of the reference's
``tests/tensor_parallel/test_random.py`` for ``CudaRNGStatesTracker``).

The TPU design replaces the stateful tracker with key-folding discipline
(``megatron_llm_tpu/random.py``): these tests pin down the properties the
reference machinery exists to provide — streams that never collide across
purposes/layers/steps, dropout that is deterministic per key, and random
bits that are *sharding-invariant* (the GSPMD equivalent of "DP-uniform,
TP-distinct slices": every rank materialises its shard of one global
stream, so replicated tensors see identical bits and sharded tensors see
their own slice)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from megatron_llm_tpu.random import (
    KeySeq,
    RngDomain,
    base_key,
    domain_key,
    dropout_key,
)


def _bits(key):
    return np.asarray(jax.random.key_data(key)).tolist()


def test_domain_and_fold_separation():
    k = base_key(1234)
    # distinct across domains, deterministic per domain
    per_domain = [_bits(domain_key(k, d)) for d in RngDomain]
    assert len({tuple(b) for b in per_domain}) == len(list(RngDomain))
    assert _bits(domain_key(k, RngDomain.DROPOUT)) == _bits(
        domain_key(base_key(1234), RngDomain.DROPOUT))

    # dropout streams never collide across (layer, step, micro)
    seen = set()
    for layer in range(3):
        for step in range(3):
            for micro in range(3):
                seen.add(tuple(_bits(dropout_key(k, layer, step, micro))))
    assert len(seen) == 27

    # KeySeq hands out fresh keys
    seq = KeySeq(1234)
    assert _bits(seq.next()) != _bits(seq.next())


def test_dropout_deterministic_and_train_gated():
    from megatron_llm_tpu.models.llama import LlamaModel, llama_config

    cfg = llama_config(
        "tiny", num_layers=2, hidden_size=64, num_attention_heads=4,
        ffn_hidden_size=128, padded_vocab_size=128, seq_length=32,
        max_position_embeddings=32, hidden_dropout=0.3,
        attention_dropout=0.0,
    )
    model = LlamaModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    toks = jnp.asarray(np.random.RandomState(0).randint(0, 128, (2, 32)))
    labels = jnp.roll(toks, -1, axis=-1)

    k1 = dropout_key(base_key(7), layer=0, step=1)
    k2 = dropout_key(base_key(7), layer=0, step=2)
    l1a = model(params, toks, labels=labels, train=True, rng_key=k1)
    l1b = model(params, toks, labels=labels, train=True, rng_key=k1)
    l2 = model(params, toks, labels=labels, train=True, rng_key=k2)
    # same key -> same mask; different step key -> different mask
    np.testing.assert_allclose(np.asarray(l1a), np.asarray(l1b))
    assert float(jnp.max(jnp.abs(l1a - l2))) > 0

    # eval ignores dropout entirely (same loss as a dropout-free config)
    e1 = model(params, toks, labels=labels, train=False, rng_key=k1)
    nodrop = LlamaModel(dataclasses.replace(cfg, hidden_dropout=0.0))
    e0 = nodrop(params, toks, labels=labels, train=False, rng_key=k1)
    np.testing.assert_allclose(np.asarray(e1), np.asarray(e0))


def test_random_bits_sharding_invariant(utils):
    """The property the reference's two seed domains emulate: one logical
    stream, each device materialising its slice.  bernoulli() over a
    dp-sharded batch must equal the single-device result (replicated
    tensors therefore see identical bits on every rank — "DP-uniform" —
    and each shard of a sharded tensor sees its own distinct slice —
    "TP-distinct")."""
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    key = dropout_key(base_key(3), layer=1, step=4)
    shape = (8, 16, 32)
    ref = jax.random.bernoulli(key, 0.9, shape)

    mesh = Mesh(np.array(devs).reshape(8), ("dp",))
    sharded = jax.jit(
        lambda k: jax.random.bernoulli(k, 0.9, shape),
        out_shardings=NamedSharding(mesh, P("dp", None, None)),
    )(key)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(sharded))
