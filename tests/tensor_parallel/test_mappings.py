"""Collective-mapping fwd/bwd tests under shard_map on the 8-device CPU mesh
(reference: tests/tensor_parallel/test_mappings.py — each mapping checked
against hand-built expected tensors)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P
from jax.experimental.shard_map import shard_map

from megatron_llm_tpu.parallel import mappings
from megatron_llm_tpu import topology


def _shmap(fn, mesh, in_spec, out_spec):
    return shard_map(fn, mesh=mesh, in_specs=(in_spec,), out_specs=out_spec,
                     check_rep=False)


@pytest.fixture
def mesh(utils):
    return utils.initialize_model_parallel(tp=8, pp=1)


def test_copy_fwd_bwd(mesh):
    x = jnp.arange(8.0 * 4).reshape(8, 4)

    f = _shmap(lambda v: mappings.copy_to_tensor_model_parallel_region("tp", v),
               mesh, P(), P())
    np.testing.assert_allclose(f(x), x)

    # bwd: grad should be allreduced (sum over 8 tp ranks)
    g = jax.grad(lambda v: f(v).sum())(x)
    np.testing.assert_allclose(g, 8.0 * jnp.ones_like(x))


def test_reduce_fwd_bwd(mesh):
    # input sharded over rows; psum makes all ranks hold the sum
    x = jnp.ones((8, 4))

    f = _shmap(lambda v: mappings.reduce_from_tensor_model_parallel_region("tp", v),
               mesh, P("tp", None), P("tp", None))
    np.testing.assert_allclose(f(x), 8.0 * jnp.ones((8, 4)))
    g = jax.grad(lambda v: f(v).sum())(x)
    np.testing.assert_allclose(g, jnp.ones_like(x))


def test_scatter_gather_roundtrip(mesh):
    x = jnp.arange(2.0 * 16).reshape(2, 16)

    def rt(v):
        s = mappings.scatter_to_tensor_model_parallel_region("tp", v)
        return mappings.gather_from_tensor_model_parallel_region("tp", s)

    f = _shmap(rt, mesh, P(), P())
    np.testing.assert_allclose(f(x), x)
    g = jax.grad(lambda v: f(v).sum())(x)
    # gather bwd splits, scatter bwd gathers -> identity grad
    np.testing.assert_allclose(g, jnp.ones_like(x))


def test_sequence_parallel_scatter_gather(mesh):
    x = jnp.arange(16.0 * 2).reshape(16, 2)

    def rt(v):
        s = mappings.scatter_to_sequence_parallel_region("tp", v)
        return mappings.gather_from_sequence_parallel_region("tp", s)

    f = _shmap(rt, mesh, P(), P())
    np.testing.assert_allclose(f(x), x)
    # gather bwd is reduce-scatter; scatter bwd is all-gather -> each grad
    # element accumulates tp-fold through the replicated output sum
    g = jax.grad(lambda v: f(v).sum())(x)
    np.testing.assert_allclose(g, 8.0 * jnp.ones_like(x))


def test_reduce_scatter_fwd(mesh):
    # each rank holds a distinct full-length partial tensor: global [8, 16, 2]
    # sharded over the leading rank axis (mirrors the reference test where
    # every rank's local input differs)
    x = jnp.arange(8.0 * 16 * 2).reshape(8, 16, 2)

    f = _shmap(
        lambda v: mappings.reduce_scatter_to_sequence_parallel_region("tp", v[0]),
        mesh, P("tp", None, None), P("tp", None))
    out = f(x)
    assert out.shape == (16, 2)
    # rank r's output block = sum over ranks of that block
    expected = np.asarray(x).sum(0).reshape(8, 2, 2)
    np.testing.assert_allclose(np.asarray(out).reshape(8, 2, 2), expected)

    g = jax.grad(lambda v: f(v).sum())(x)
    # bwd is all-gather -> every element of every rank's input gets grad 1
    np.testing.assert_allclose(g, jnp.ones_like(x))
