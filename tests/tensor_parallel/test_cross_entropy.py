"""Vocab-parallel CE tests (reference: tests/tensor_parallel/test_cross_entropy.py).

Checks: GSPMD version vs pure-numpy log-softmax CE; explicit shard_map
version vs GSPMD version; argmax across shards; label smoothing."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P
from jax.experimental.shard_map import shard_map

from megatron_llm_tpu.ops.cross_entropy import (
    shard_vocab_parallel_cross_entropy,
    shard_vocab_parallel_max_indices,
    vocab_parallel_cross_entropy,
    vocab_parallel_max_indices,
)


def numpy_ce(logits, labels):
    logits = np.asarray(logits, np.float64)
    m = logits.max(-1, keepdims=True)
    lse = np.log(np.exp(logits - m).sum(-1)) + m[..., 0]
    tgt = np.take_along_axis(logits, labels[..., None], -1)[..., 0]
    return lse - tgt


@pytest.fixture
def data():
    rng = np.random.RandomState(42)
    logits = jnp.asarray(rng.randn(4, 8, 64).astype(np.float32) * 3)
    labels = jnp.asarray(rng.randint(0, 64, size=(4, 8)).astype(np.int32))
    return logits, labels


def test_ce_matches_numpy(data):
    logits, labels = data
    loss = vocab_parallel_cross_entropy(logits, labels)
    np.testing.assert_allclose(loss, numpy_ce(logits, np.asarray(labels)), rtol=1e-5)


def test_ce_grad_is_softmax_minus_onehot(data):
    logits, labels = data
    g = jax.grad(lambda l: vocab_parallel_cross_entropy(l, labels).sum())(logits)
    probs = jax.nn.softmax(logits, axis=-1)
    onehot = jax.nn.one_hot(labels, 64)
    np.testing.assert_allclose(g, probs - onehot, atol=1e-5)


def test_shard_ce_matches_global(utils, data):
    mesh = utils.initialize_model_parallel(tp=8)
    logits, labels = data

    f = shard_map(
        lambda l, y: shard_vocab_parallel_cross_entropy(l, y, "tp"),
        mesh=mesh,
        in_specs=(P(None, None, "tp"), P()),
        out_specs=P(),
        check_rep=False,
    )
    loss = f(logits, labels)
    np.testing.assert_allclose(
        loss, vocab_parallel_cross_entropy(logits, labels), rtol=1e-5
    )


def test_shard_ce_label_smoothing(utils, data):
    mesh = utils.initialize_model_parallel(tp=8)
    logits, labels = data
    f = shard_map(
        lambda l, y: shard_vocab_parallel_cross_entropy(l, y, "tp", 0.1),
        mesh=mesh,
        in_specs=(P(None, None, "tp"), P()),
        out_specs=P(),
        check_rep=False,
    )
    np.testing.assert_allclose(
        f(logits, labels),
        vocab_parallel_cross_entropy(logits, labels, 0.1),
        rtol=1e-5,
    )


def test_shard_max_indices(utils, data):
    mesh = utils.initialize_model_parallel(tp=8)
    logits, _ = data
    f = shard_map(
        lambda l: shard_vocab_parallel_max_indices(l, "tp"),
        mesh=mesh,
        in_specs=(P(None, None, "tp"),),
        out_specs=P(),
        check_rep=False,
    )
    np.testing.assert_array_equal(f(logits), vocab_parallel_max_indices(logits))
