"""Cache observatory (serving/cache_observatory.py).

The load-bearing test is the ghost oracle: a churny operation trace is
recorded against a 1x BlockManager (whose observatory simulates 2x/4x
ghost tiers synchronously), then the SAME trace is replayed against a
real BlockManager with 2x (resp. 4x) the usable blocks — the ghost's
hit/hit-token/eviction counters must equal the real big manager's
lifetime counters EXACTLY.  The ghost is not an estimate.

Also covered: per-prefix heat attribution + salted-key privacy,
eviction forensics (capacity vs churn) and the evicted-then-wanted
regret counter, heat-table bounding, fleet heat merge, the periodic
cache_stats emission cadence, and the <2% dispatch-overhead gate
(slow; run by tools/tpu_sweep.py's serve_cache_overhead step).
"""

import json
import random
import time

import pytest

from megatron_llm_tpu import telemetry
from megatron_llm_tpu.serving import BlockManager, merge_heat_tops
from megatron_llm_tpu.serving.cache_observatory import (
    CacheObservatory,
    _GhostTier,
)

BS = 4


def _bm(num_blocks=13, num_slots=3, **kw):
    kw.setdefault("prefix_cache", True)
    return BlockManager(num_blocks=num_blocks, block_size=BS,
                        num_slots=num_slots, max_blocks_per_slot=8, **kw)


# ---------------------------------------------------------------------------
# the ghost oracle: ghost xN counters == a real Nx manager, exactly
# ---------------------------------------------------------------------------

def _record_trace(steps=600, seed=7, num_blocks=13, num_slots=3):
    """Drive a 1x manager with random churn, recording every operation.
    Allocations are pre-gated on a conservative fit test so the trace
    never raises NoCapacity — a failed alloc counts match probes but
    admits nothing, which a replay cannot reproduce op-for-op."""
    rng = random.Random(seed)
    bm = _bm(num_blocks=num_blocks, num_slots=num_slots)
    prompts = [[rng.randrange(1, 6) for _ in range(rng.randrange(3, 17))]
               for _ in range(6)]
    trace = []
    live = {}
    for _ in range(steps):
        op = rng.random()
        if op < 0.45 and len(live) < num_slots:
            p = rng.choice(prompts)
            total = len(p) + rng.randrange(1, 8)
            st = bm.stats()
            if bm.blocks_needed(total) > (st["blocks_free"]
                                          + st["blocks_cached_reusable"]):
                continue                        # would raise NoCapacity
            s = bm.alloc(total, prompt_tokens=p)
            trace.append(("alloc", s, total, p))
            live[s] = (p, bm.slot_cached_tokens(s))
        elif op < 0.65 and live:
            s = rng.choice(list(live))
            p, cached = live[s]
            n_written = rng.randrange(cached, len(p) + 1)
            bm.commit_prefix(s, p, n_written)
            trace.append(("commit", s, p, n_written))
        elif op < 0.8 and live:
            s = rng.choice(list(live))
            p, _ = live[s]
            idx = rng.randrange(0, bm.blocks_needed(len(p)))
            bm.ensure_writable(s, idx)
            trace.append(("cow", s, idx))
        elif live:
            s = rng.choice(list(live))
            p, _ = live[s]
            n_written = rng.randrange(0, len(p) + 1)
            bm.free(s, token_ids=p, n_written=n_written)
            trace.append(("free", s, p, n_written))
            del live[s]
        bm.check_invariants()
    for s, (p, _) in list(live.items()):
        bm.free(s, token_ids=p, n_written=len(p))
        trace.append(("free", s, p, len(p)))
    bm.check_invariants()
    return bm, trace


def _replay(trace, mult, num_blocks=13, num_slots=3):
    """Apply a recorded trace to a real manager with ``mult`` times the
    usable blocks.  Slot ids are remapped (the big manager hands out
    its own)."""
    big = _bm(num_blocks=mult * (num_blocks - 1) + 1, num_slots=num_slots)
    slot_map = {}
    for rec in trace:
        if rec[0] == "alloc":
            _, s, total, p = rec
            slot_map[s] = big.alloc(total, prompt_tokens=p)
        elif rec[0] == "commit":
            _, s, p, n_written = rec
            big.commit_prefix(slot_map[s], p, n_written)
        elif rec[0] == "cow":
            _, s, idx = rec
            big.ensure_writable(slot_map[s], idx)
        else:
            _, s, p, n_written = rec
            big.free(slot_map.pop(s), token_ids=p, n_written=n_written)
        big.check_invariants()
    return big


@pytest.mark.parametrize("mult", [2, 4])
def test_ghost_oracle_exact_vs_real_big_manager(mult):
    """Acceptance: ghost x2 (x4) hit counters equal a REAL 2x (4x)
    BlockManager's lifetime counters on the same operation trace —
    exact equality, not approximation."""
    bm, trace = _record_trace()
    assert any(r[0] == "cow" for r in trace)     # the hard cases ran
    assert bm.stats()["prefix_cache_evictions"] > 0
    ghost = bm.cache_stats()["ghost"][f"x{mult}"]
    big = _replay(trace, mult)
    st = big.stats()
    assert ghost["hits"] == st["prefix_cache_hits"]
    assert ghost["hit_tokens"] == st["prefix_cache_hit_tokens"]
    assert ghost["evictions"] == st["prefix_cache_evictions"]
    # a bigger pool can only help on this trace
    assert ghost["hits"] >= bm.stats()["prefix_cache_hits"]


def test_ghost_oracle_many_seeds():
    """The x2 oracle across a spread of churn seeds — guards against a
    single-seed fluke hiding an economy-rule mismatch."""
    for seed in (0, 1, 2, 3, 11):
        bm, trace = _record_trace(steps=300, seed=seed)
        ghost = bm.cache_stats()["ghost"]["x2"]
        st = _replay(trace, 2).stats()
        assert ghost["hits"] == st["prefix_cache_hits"], f"seed {seed}"
        assert ghost["evictions"] == st["prefix_cache_evictions"], \
            f"seed {seed}"


# ---------------------------------------------------------------------------
# heat attribution + privacy
# ---------------------------------------------------------------------------

def test_heat_attribution_and_salted_privacy(monkeypatch):
    monkeypatch.setenv("MEGATRON_CACHE_SALT", "fleet-salt")
    bm = _bm(num_blocks=33)
    hot = list(range(1, 10))                     # 2 full blocks
    cold = list(range(21, 30))
    s = bm.alloc(16, prompt_tokens=hot)
    bm.commit_prefix(s, hot, n_written=9)
    bm.free(s, token_ids=hot, n_written=9)
    for _ in range(3):                           # 3 warm hits on `hot`
        s = bm.alloc(16, prompt_tokens=hot)
        bm.free(s, token_ids=hot, n_written=9)
    s = bm.alloc(16, prompt_tokens=cold)
    bm.free(s, token_ids=cold, n_written=9)
    stats = bm.cache_stats()
    top = stats["heat_top"]
    assert top and top[0]["hits"] == 3           # hottest first
    # heat entries are per BLOCK digest: 3 warm allocs x one block each
    assert top[0]["hit_tokens"] == 3 * BS
    assert top[0]["peak_refcount"] >= 1
    assert "last_access_age" in top[0]
    # privacy: keys are 16-hex-char salted digests; no token ids, no
    # raw chain digests anywhere in the exported record
    dumped = json.dumps(stats)
    for e in top:
        assert len(e["prefix"]) == 16 and int(e["prefix"], 16) >= 0
    assert "token" not in dumped.replace("hit_tokens", "")
    # same salt => same keyspace (fleet-mergeable); different salt
    # => unlinkable keys for the same digest
    obs_a = CacheObservatory(8, BS, salt=b"a")
    obs_b = CacheObservatory(8, BS, salt=b"b")
    obs_fleet = CacheObservatory(8, BS)          # env salt
    d = b"\x01" * 16
    assert obs_a.salted_key(d) != obs_b.salted_key(d)
    assert obs_fleet.salted_key(d) == CacheObservatory(4, BS).salted_key(d)


def test_heat_table_bounded_evicts_coldest():
    obs = CacheObservatory(8, BS, heat_cap=4)
    digests = [bytes([i]) * 16 for i in range(8)]
    for i, d in enumerate(digests):
        # touch digest i (i+1) times so later digests are hotter
        obs.record_match([d], 1)
        for _ in range(i):
            obs.record_match([d], 1)
    assert len(obs.heat_top(k=100)) == 4
    st = obs.stats()
    assert st["heat_entries"] == 4
    assert st["heat_evicted"] == 4
    # the survivors are the hottest tail
    keys = {e["prefix"] for e in obs.heat_top(k=100)}
    assert keys == {obs.salted_key(d) for d in digests[-4:]}


# ---------------------------------------------------------------------------
# eviction forensics + regret
# ---------------------------------------------------------------------------

def test_eviction_forensics_churn_and_regret():
    """One-shot prefixes cycling an idle pool are churn evictions; a
    re-request of an evicted prefix is a miss_evicted (regret), not a
    cold miss."""
    bm = _bm(num_blocks=9, num_slots=2)          # 8 usable blocks
    pa = list(range(1, 9))                       # 2 full blocks each
    pb = list(range(11, 19))
    pc = list(range(21, 29))
    for p in (pa, pb, pc):
        s = bm.alloc(8, prompt_tokens=p)
        bm.commit_prefix(s, p, n_written=8)
        bm.free(s, token_ids=p, n_written=8)
    # 6 of 8 blocks parked; demand 8 fresh -> evicts pa (LRU oldest)
    s = bm.alloc(32, prompt_tokens=list(range(90, 98)))
    st = bm.cache_stats()
    assert st["evictions_churn"] >= 2            # parked pages dominated
    bm.free(s)
    # want pa again: the miss is classified as regret (the match cap
    # probes (8-1)//4 = 1 block of the 2-block chain)
    s = bm.alloc(8, prompt_tokens=pa)
    st = bm.cache_stats()
    assert st["miss_evicted"] >= 1
    assert st["miss_cold"] > 0                   # the genuinely new ones
    assert st["miss_cold"] + st["miss_evicted"] == st["misses"]
    bm.free(s, token_ids=pa, n_written=8)
    bm.check_invariants()


def test_eviction_forensics_capacity_reason():
    """Evictions while live refcounted blocks dominate the pool are
    capacity evictions — the pool is genuinely too small."""
    bm = _bm(num_blocks=9, num_slots=3)
    pa = list(range(1, 9))
    s0 = bm.alloc(8, prompt_tokens=pa)
    bm.commit_prefix(s0, pa, n_written=8)
    pb = list(range(11, 19))
    s1 = bm.alloc(8, prompt_tokens=pb)
    bm.commit_prefix(s1, pb, n_written=8)
    bm.free(s1, token_ids=pb, n_written=8)       # 2 parked, 2 live+held
    # 4 free; demand 6 -> evicts pb's pages with live blocks majority
    s2 = bm.alloc(24, prompt_tokens=list(range(41, 47)))
    st = bm.cache_stats()
    assert st["evictions_capacity"] >= 2
    bm.free(s0, token_ids=pa, n_written=8)
    bm.free(s2)
    bm.check_invariants()


def test_slot_miss_causes_feed_request_records():
    bm = _bm(num_blocks=33)
    p = list(range(1, 14))                       # 3 full blocks + tail
    s = bm.alloc(16, prompt_tokens=p)
    assert bm.slot_miss_causes(s) == (3, 0)      # all cold
    bm.commit_prefix(s, p, n_written=13)
    bm.free(s, token_ids=p, n_written=13)
    s = bm.alloc(16, prompt_tokens=p)
    assert bm.slot_miss_causes(s) == (0, 0)      # warm
    bm.free(s, token_ids=p, n_written=13)


# ---------------------------------------------------------------------------
# fleet merge
# ---------------------------------------------------------------------------

def test_merge_heat_tops_sums_same_salt_keys():
    a = [{"prefix": "aa", "hits": 5, "hit_tokens": 40, "residency": 2,
          "evictions": 1, "regret": 0, "peak_refcount": 3,
          "last_access_age": 10},
         {"prefix": "bb", "hits": 2, "hit_tokens": 16, "residency": 1,
          "evictions": 0, "regret": 1, "peak_refcount": 1,
          "last_access_age": 4}]
    b = [{"prefix": "aa", "hits": 7, "hit_tokens": 56, "residency": 1,
          "evictions": 0, "regret": 2, "peak_refcount": 5,
          "last_access_age": 2}]
    merged = merge_heat_tops([a, b], k=16)
    assert merged[0]["prefix"] == "aa"           # 12 hits, hottest first
    assert merged[0]["hits"] == 12
    assert merged[0]["hit_tokens"] == 96
    assert merged[0]["peak_refcount"] == 5       # max, not sum
    assert merged[0]["last_access_age"] == 2     # most recent wins
    assert merged[0]["regret"] == 2
    assert merged[1]["prefix"] == "bb" and merged[1]["hits"] == 2
    # top-K truncation + junk tolerance
    assert merge_heat_tops([a, b], k=1) == [merged[0]]
    assert merge_heat_tops([None, "x", [{"nope": 1}], a], k=16)[0][
        "prefix"] == "aa"


# ---------------------------------------------------------------------------
# cache_stats emission cadence (schema 11)
# ---------------------------------------------------------------------------

class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_cache_stats_emit_cadence(tmp_path):
    clock = _Clock()
    obs = CacheObservatory(8, BS, emit_every_matches=4,
                           emit_interval_secs=15.0, clock=clock)
    stream = telemetry.TelemetryStream(str(tmp_path))
    telemetry.install_stream(stream)
    try:
        d = b"\x02" * 16
        assert obs.maybe_emit() is False         # nothing fresh
        for _ in range(4):
            obs.record_match([d], 0)
        assert obs.maybe_emit() is True          # count cadence
        assert obs.maybe_emit() is False
        obs.record_match([d], 0)
        clock.t += 20.0
        assert obs.maybe_emit() is True          # time cadence, fresh
        clock.t += 20.0
        assert obs.maybe_emit() is False         # time alone, no traffic
        assert obs.maybe_emit(force=True) is True
    finally:
        telemetry.install_stream(None)
        stream.close()
    recs = []
    for f in tmp_path.glob("*.jsonl"):
        with open(f) as fh:
            recs += [json.loads(ln) for ln in fh if ln.strip()]
    cache = [r for r in recs if r.get("event") == "cache_stats"]
    assert len(cache) == 3
    rec = cache[-1]
    assert rec["kind"] == "serve"
    assert rec["schema"] == telemetry.TELEMETRY_SCHEMA_VERSION
    for key in ("probes", "hits", "miss_cold", "miss_evicted",
                "evictions_capacity", "evictions_churn", "heat_top",
                "ghost", "inclusion_divergences"):
        assert key in rec, key
    assert set(rec["ghost"]) == {"x2", "x4", "x10"}


def test_emit_survives_broken_stream(monkeypatch):
    class _Boom:
        def emit(self, rec):
            raise RuntimeError("boom")

    obs = CacheObservatory(8, BS)
    obs.record_match([b"\x03" * 16], 0)
    monkeypatch.setattr(telemetry, "_ACTIVE_STREAM", _Boom())
    assert obs.maybe_emit(force=True) is False   # swallowed, loop lives


def test_pool_reset_keeps_ghost_residency():
    """Engine restart: ghost tiers release every slot but keep parked
    digests resident (a host-RAM tier would survive the restart), and
    the strict-inclusion asserts disarm."""
    obs = CacheObservatory(8, BS, ghost_multiples=(2,))
    d = [b"\x04" * 16, b"\x05" * 16]
    t = obs.record_match(d, 0)
    obs.record_admit(0, t, 3, [])
    obs.record_commit(0, d, ["reg", "reg"])
    obs.on_pool_reset()
    obs.check_invariants()
    assert obs.stats()["pool_resets"] == 1
    tier = obs._tiers[0]
    assert not tier.slots and set(tier.lru) == set(d)
    # next epoch still matches what the tier retained
    assert len(tier.lookup_locked(d)) == 2


# ---------------------------------------------------------------------------
# overhead gate (slow; run by tools/tpu_sweep.py's serve_cache_overhead)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_serve_cache_overhead_under_2pct():
    """Per-alloc observatory bookkeeping (match + ghost lookups + admit
    + commit + free across 3 tiers, with a live telemetry stream — the
    worst case) must cost < 2% of a real CPU dispatch of the tiny
    engine.  The observatory may not become the overhead it meters."""
    import jax

    from megatron_llm_tpu.models.llama import LlamaModel, llama_config
    from megatron_llm_tpu.serving import (EngineConfig, InferenceEngine,
                                          SamplingParams)

    # arm A: the real engine under traffic — mean dispatch wall-clock
    cfg = llama_config("tiny", num_layers=2, seq_length=64,
                       max_position_embeddings=64, padded_vocab_size=64,
                       use_flash_attn=False)
    model = LlamaModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    eng = InferenceEngine(model, params, EngineConfig(
        num_slots=4, block_size=8, prefill_chunk=16, max_model_len=64,
        max_queue_depth=32, default_deadline_secs=0.0))
    eng.warmup()
    eng.start()
    try:
        reqs = [eng.submit([1 + i, 2, 3, 4],
                           SamplingParams(max_new_tokens=12,
                                          temperature=0.0, eod_id=63))
                for i in range(8)]
        for r in reqs:
            r.result(timeout=180)
        loop = eng.stats()["loop"]
    finally:
        eng.stop()
    assert loop["dispatches"] > 0
    mean_dispatch_secs = loop["wall_secs"] / loop["dispatches"]

    # arm B: the observatory alone, one full request lifecycle per
    # iteration (match -> admit -> commit -> free), warm-hit path
    stream = telemetry.TelemetryStream(None)    # no file, worst-case code
    telemetry.install_stream(stream)
    try:
        obs = CacheObservatory(255, 8)
        digests = [bytes([i, 0]) * 8 for i in range(4)]
        tok = obs.record_match(digests, 0)
        obs.record_admit(0, tok, 6, [])
        obs.record_commit(0, digests, ["reg"] * 4)
        obs.record_free(0)
        n = 2000
        t0 = time.perf_counter()
        for _ in range(n):
            tok = obs.record_match(digests, len(digests))
            obs.record_admit(0, tok, 6, [2, 2, 2, 2])
            obs.record_commit(0, digests, ["live"] * 4)
            obs.record_free(0)
            obs.maybe_emit()
        cost_per_alloc = (time.perf_counter() - t0) / n
    finally:
        telemetry.install_stream(None)
        stream.close()
    frac = cost_per_alloc / mean_dispatch_secs
    assert frac < 0.02, (
        f"observatory bookkeeping {cost_per_alloc * 1e6:.1f}us/alloc "
        f"= {frac * 100:.2f}% of a {mean_dispatch_secs * 1e3:.2f}ms "
        f"CPU dispatch (gate: < 2%)")


def test_ghost_tier_unit_economy():
    """Micro-checks on one tier: lookup counts at match time, admit
    adopts, commit registers, release parks in insertion order, a
    take beyond free evicts LRU-oldest."""
    t = _GhostTier(1, 4)
    d = [bytes([i]) * 16 for i in range(3)]
    assert t.lookup_locked(d) == [] and t.misses == 3
    t.admit_locked(0, [], 3, BS)
    assert t.free == 1
    t.commit_locked(0, d)
    assert set(t.table) == set(d)
    t.release_locked(0)                                 # parks d0, d1, d2 (oldest first)
    assert list(t.lru) == d
    assert t.free == 1
    # a 4-block demand: 1 free + evict d0, d1, d2 in LRU order
    m = t.lookup_locked([bytes([9]) * 16])
    t.admit_locked(1, m, 4, BS)
    assert t.evictions == 3 and not t.table and t.free == 0
    assert t.lookup_locked(d) == []                     # the chains are gone
