"""int8 KV cache: quantized decode tracks the fp cache within the
per-entry quantization error, at half (vs bf16) / quarter (vs fp32)
the cache bytes."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megatron_llm_tpu.models.llama import LlamaModel, llama_config
from megatron_llm_tpu.text_generation.generation import (
    _forward_with_cache,
    generate_tokens,
    init_kv_caches,
)


@pytest.fixture(scope="module")
def model_and_params():
    cfg = llama_config("tiny", num_layers=2, seq_length=64,
                       max_position_embeddings=64, padded_vocab_size=64,
                       use_flash_attn=False)
    model = LlamaModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    return model, params


def test_init_shapes_and_bytes(model_and_params):
    model, _ = model_and_params
    cfg = model.cfg
    fp = init_kv_caches(cfg, 2, 32)
    q8 = init_kv_caches(cfg, 2, 32, quantized=True)
    assert q8[0]["k_q"].dtype == jnp.int8
    assert q8[0]["k_q"].shape == fp[0]["k"].shape
    assert q8[0]["k_scale"].shape == fp[0]["k"].shape[:-1]
    d = fp[0]["k"].shape[-1]
    # int8 payload = 1 byte/entry + scales (1 fp32 per d entries):
    # vs fp32 k/v that is a 4x -> ~(1 + 4/d)x reduction
    q_bytes = q8[0]["k_q"].nbytes + q8[0]["k_scale"].nbytes
    assert q_bytes < fp[0]["k"].nbytes / 2


def test_forward_drift_bounded(model_and_params):
    """Prefill + one decode step through the int8 cache stays close to
    the fp cache logits."""
    model, params = model_and_params
    toks = jnp.asarray([[3, 5, 7, 9, 11, 13]], jnp.int32)
    nxt = jnp.asarray([[2]], jnp.int32)
    lf_all = []
    for quant in (False, True):
        caches = init_kv_caches(model.cfg, 1, 16, quantized=quant)
        _, caches = _forward_with_cache(model, params, toks, caches, 0)
        logits, _ = _forward_with_cache(model, params, nxt, caches,
                                        toks.shape[1])
        lf_all.append(np.asarray(logits[0, -1], np.float32))
    fp, q8 = lf_all
    scale = float(np.std(fp)) + 1e-6
    assert float(np.max(np.abs(q8 - fp))) / scale < 0.2


def test_generation_runs_and_keeps_prompt(model_and_params):
    model, params = model_and_params
    toks = jnp.asarray([[1, 2, 3, 4], [5, 6, 7, 0]], jnp.int32)
    lens = jnp.asarray([4, 3], jnp.int32)
    out, n, _ = generate_tokens(
        model, params, toks, lens, jax.random.PRNGKey(0),
        max_new_tokens=8, min_prompt_len=3, greedy=True,
        int8_kv_cache=True)
    assert out.shape == (2, 12)
    # prompt survives (row 1's 4th slot is generated, not the pad)
    np.testing.assert_array_equal(np.asarray(out[0, :4]),
                                  np.asarray(toks[0]))
    assert int(jnp.asarray(n).reshape(-1)[0]) > 0


def test_chunked_prefill_path(model_and_params):
    """The micro-batched prefill reshape handles the quantized cache
    layout (generic over cache keys)."""
    model, params = model_and_params
    toks = jnp.asarray([[1, 2, 3, 4]] * 4, jnp.int32)
    lens = jnp.full((4,), 4, jnp.int32)
    out_plain, _, _ = generate_tokens(
        model, params, toks, lens, jax.random.PRNGKey(0),
        max_new_tokens=4, min_prompt_len=4, greedy=True,
        int8_kv_cache=True)
    out_chunked, _, _ = generate_tokens(
        model, params, toks, lens, jax.random.PRNGKey(0),
        max_new_tokens=4, min_prompt_len=4, greedy=True,
        int8_kv_cache=True, batch_times_seqlen_threshold=8)
    np.testing.assert_array_equal(np.asarray(out_plain),
                                  np.asarray(out_chunked))


def test_rolling_plus_int8_refused(model_and_params):
    model, _ = model_and_params
    cfg = model.cfg.replace(sliding_window_size=8)
    with pytest.raises(AssertionError):
        init_kv_caches(cfg, 1, 32, rolling=True, quantized=True)


def test_paged_int8_sliding_window_drift_bounded(model_and_params):
    """int8 PAGED pools combined with a sliding window (the serving
    engine's XLA gather branch) track the float linear cache within the
    quantization drift bound — the window mask and the in-gather
    dequant compose."""
    from megatron_llm_tpu.models.language_model import language_model_forward
    from megatron_llm_tpu.models.llama import LlamaModel
    from megatron_llm_tpu.text_generation.generation import (
        init_paged_kv_caches,
    )

    model, params = model_and_params
    wcfg = model.cfg.replace(sliding_window_size=8,
                             paged_attention_kernel="off")
    toks = jnp.asarray([[3, 5, 7, 9, 11, 13, 2, 4, 6, 8, 10, 12]],
                       jnp.int32)                  # 12 tokens > window 8
    nxt = jnp.asarray([[2]], jnp.int32)
    # baseline: float LINEAR cache through the same windowed config
    wmodel = LlamaModel(wcfg)
    caches = init_kv_caches(wcfg, 1, 16)
    _, caches = _forward_with_cache(wmodel, params, toks, caches, 0)
    logits_fp, _ = _forward_with_cache(wmodel, params, nxt, caches,
                                       toks.shape[1])
    fp = np.asarray(logits_fp[0, -1], np.float32)
    # int8 paged pools: prefill then one decode step through the paged
    # branch (block table covers 13 tokens at block_size 8 -> 2 pages)
    bs, M = 8, 2
    pages = init_paged_kv_caches(wcfg, 1 + M, bs, quantized=True)
    bt = jnp.asarray(np.arange(1, M + 1)[None, :], jnp.int32)
    caches = [dict(p, block_tables=bt,
                   context_lens=jnp.zeros((1,), jnp.int32),
                   valid_lens=jnp.asarray([toks.shape[1]], jnp.int32))
              for p in pages]
    positions = jnp.arange(toks.shape[1])[None, :]
    _, caches = language_model_forward(params, toks, positions, None,
                                       wcfg, rng_key=None, train=False,
                                       kv_caches=caches)
    pages2 = [{k: v for k, v in c.items() if "pages" in k}
              for c in caches]
    caches = [dict(p, block_tables=bt,
                   context_lens=jnp.asarray([toks.shape[1]], jnp.int32),
                   valid_lens=jnp.ones((1,), jnp.int32))
              for p in pages2]
    logits_q, _ = language_model_forward(
        params, nxt, jnp.asarray([[toks.shape[1]]], jnp.int32), None,
        wcfg, rng_key=None, train=False, kv_caches=caches)
    q8 = np.asarray(logits_q[0, -1], np.float32)
    scale = float(np.std(fp)) + 1e-6
    assert float(np.max(np.abs(q8 - fp))) / scale < 0.2
