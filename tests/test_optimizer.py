"""Optimizer stack tests: AdamW math vs numpy, fp16 master-param flow,
dynamic loss scaler growth/backoff/hysteresis, global-norm clip, inf skip
(reference semantics: megatron/optimizer/)."""

import jax
import jax.numpy as jnp
import numpy as np

from megatron_llm_tpu.config import TrainConfig
from megatron_llm_tpu.optimizer import DynamicGradScaler, MegatronOptimizer
from megatron_llm_tpu.optimizer.optimizer import global_grad_norm
from megatron_llm_tpu.optimizer.scheduler import OptimizerParamScheduler


def _params():
    rng = np.random.RandomState(0)
    return {
        "layer": {"kernel": jnp.asarray(rng.randn(4, 4), jnp.float32),
                  "bias": jnp.zeros((4,), jnp.float32)}
    }


def test_adamw_matches_numpy():
    tc = TrainConfig(optimizer="adam", lr=0.1, clip_grad=0.0, weight_decay=0.0)
    opt = MegatronOptimizer(tc)
    params = _params()
    state = opt.init(params)
    grads = jax.tree_util.tree_map(jnp.ones_like, params)

    p1, s1, stats = opt.step(params, grads, state, 0.1, 0.0)
    # numpy adam step 1: m=0.1*g? no: m=(1-b1)*g=0.1, v=(1-b2)*g^2=0.001
    # mhat=0.1/0.1=1, vhat=0.001/0.001=1 -> update=1/(1+eps)≈1
    expected = np.asarray(params["layer"]["kernel"]) - 0.1 * 1.0 / (1.0 + 1e-8)
    np.testing.assert_allclose(p1["layer"]["kernel"], expected, atol=2e-6)
    assert not bool(stats["found_inf"])


def test_weight_decay_skips_bias():
    tc = TrainConfig(optimizer="adam", lr=0.0, clip_grad=0.0, weight_decay=0.5)
    opt = MegatronOptimizer(tc)
    params = _params()
    state = opt.init(params)
    zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
    # lr=0 -> no update at all regardless of wd (wd couples through lr)
    p1, _, _ = opt.step(params, zeros, state, 0.0, 0.5)
    np.testing.assert_allclose(p1["layer"]["kernel"], params["layer"]["kernel"])
    # lr>0, zero grads: kernel decays, bias must not
    p2, _, _ = opt.step(params, zeros, state, 0.1, 0.5)
    assert np.all(np.abs(p2["layer"]["kernel"]) < np.abs(params["layer"]["kernel"]))
    np.testing.assert_allclose(p2["layer"]["bias"], params["layer"]["bias"])


def test_inf_grad_skips_step():
    tc = TrainConfig(optimizer="adam", lr=0.1, fp16=True)
    opt = MegatronOptimizer(tc, params_dtype=jnp.float16)
    params = jax.tree_util.tree_map(lambda p: p.astype(jnp.float16), _params())
    state = opt.init(params)
    grads = jax.tree_util.tree_map(
        lambda p: jnp.full_like(p, jnp.inf, dtype=jnp.float32), params
    )
    p1, s1, stats = opt.step(params, grads, state, 0.1, 0.0)
    assert bool(stats["found_inf"])
    np.testing.assert_allclose(
        np.asarray(p1["layer"]["kernel"], np.float32),
        np.asarray(params["layer"]["kernel"], np.float32),
    )
    assert int(s1.step) == 0
    # hysteresis consumed
    assert int(s1.grad_scaler.hysteresis_tracker) == 1


def test_fp16_master_params_preserve_precision():
    tc = TrainConfig(optimizer="adam", lr=1e-4, fp16=True, loss_scale=128.0,
                     clip_grad=0.0)
    opt = MegatronOptimizer(tc, params_dtype=jnp.float16)
    params = jax.tree_util.tree_map(lambda p: p.astype(jnp.float16), _params())
    state = opt.init(params)
    assert state.master_params is not None
    g = jax.tree_util.tree_map(
        lambda p: 128.0 * 1e-3 * jnp.ones_like(p, jnp.float32), params
    )
    p1, s1, _ = opt.step(params, g, state, 1e-4, 0.0)
    # master moved even though fp16 cast may round
    assert float(jnp.max(jnp.abs(
        s1.master_params["layer"]["kernel"]
        - state.master_params["layer"]["kernel"]))) > 0


def test_dynamic_scaler_backoff_and_growth():
    sc = DynamicGradScaler(initial_scale=2.0 ** 10, growth_interval=2, hysteresis=1)
    st = sc.init()
    st = sc.update(st, jnp.array(True))  # inf -> halve (hysteresis 1)
    assert float(st.scale) == 2.0 ** 9
    st = sc.update(st, jnp.array(False))
    st = sc.update(st, jnp.array(False))  # 2 clean -> double
    assert float(st.scale) == 2.0 ** 10


def test_global_grad_norm_and_clip():
    tc = TrainConfig(optimizer="sgd", lr=1.0, clip_grad=1.0, weight_decay=0.0,
                     sgd_momentum=0.0)
    opt = MegatronOptimizer(tc)
    params = {"w": jnp.zeros((3,), jnp.float32)}
    grads = {"w": jnp.asarray([3.0, 4.0, 0.0])}
    assert float(global_grad_norm(grads)) == 5.0
    p1, _, stats = opt.step(params, grads, opt.init(params), 1.0, 0.0)
    np.testing.assert_allclose(float(stats["grad_norm"]), 5.0, rtol=1e-5)
    # clipped to norm 1 -> step = g/5
    np.testing.assert_allclose(p1["w"], [-0.6, -0.8, 0.0], rtol=1e-4)


def test_scheduler_styles():
    s = OptimizerParamScheduler(max_lr=1.0, min_lr=0.1, lr_warmup_steps=10,
                                lr_decay_steps=110, lr_decay_style="linear")
    assert s.get_lr(5) == 0.5
    assert s.get_lr(10) == 1.0
    np.testing.assert_allclose(s.get_lr(60), 0.55)
    assert s.get_lr(110) == 0.1
    assert s.get_lr(1000) == 0.1

    c = OptimizerParamScheduler(max_lr=1.0, min_lr=0.0, lr_warmup_steps=0,
                                lr_decay_steps=100, lr_decay_style="cosine")
    np.testing.assert_allclose(c.get_lr(50), 0.5, atol=1e-6)

    r = OptimizerParamScheduler(max_lr=1.0, min_lr=0.0, lr_warmup_steps=4,
                                lr_decay_steps=100,
                                lr_decay_style="inverse-square-root")
    np.testing.assert_allclose(r.get_lr(16), 0.5)


def test_scheduler_state_roundtrip():
    s = OptimizerParamScheduler(max_lr=1.0, min_lr=0.1, lr_warmup_steps=10,
                                lr_decay_steps=110)
    s.step(7)
    sd = s.state_dict()
    s2 = OptimizerParamScheduler(max_lr=1.0, min_lr=0.1, lr_warmup_steps=10,
                                 lr_decay_steps=110)
    s2.load_state_dict(sd)
    assert s2.num_steps == 7
    assert s2.get_lr() == s.get_lr()


def test_zero1_realized_shardings(utils):
    """ZeRO-1 state_specs must produce *actually* dp-sharded adam/master
    leaves, and verify_zero1_sharding must fail loudly on a replicated
    fallback (reference distrib_optimizer.py:63-171 semantics)."""
    import pytest

    from megatron_llm_tpu.models.llama import LlamaModel, llama_config
    from megatron_llm_tpu.parallel import sharding as sh

    utils.initialize_model_parallel(tp=2)  # dp = 4 on the 8-device mesh
    cfg = llama_config("tiny", seq_length=16, max_position_embeddings=16,
                       padded_vocab_size=128)
    model = LlamaModel(cfg)
    p0 = model.init(jax.random.PRNGKey(0))
    params = sh.shard_params(p0, model.param_specs(p0))
    tc = TrainConfig(micro_batch_size=1, global_batch_size=1, lr=1e-3,
                     bf16=True)
    opt = MegatronOptimizer(tc, params_dtype=jnp.float32)
    opt_state = opt.init(params)
    specs = opt.state_specs(model.param_specs(params), params,
                            zero1=True, dp_size=4)
    sharded = opt_state._replace(
        exp_avg=sh.shard_params(opt_state.exp_avg, specs.exp_avg),
        exp_avg_sq=sh.shard_params(opt_state.exp_avg_sq, specs.exp_avg_sq),
    )
    # every leaf above threshold carries the dp axis
    opt.verify_zero1_sharding(sharded, min_bytes=32 << 10)
    big = [l for l in jax.tree_util.tree_leaves(sharded.exp_avg)
           if l.size * 4 >= (32 << 10)]
    assert big, "test model too small to exercise the threshold"
    # the pre-sharding state (replicated) must fail loudly
    with pytest.raises(RuntimeError, match="not dp-sharded"):
        opt.verify_zero1_sharding(opt_state, min_bytes=32 << 10)


def test_bf16_optimizer_state_dtype():
    """optimizer_state_dtype='bf16' stores moments in bf16 (half the
    state bytes) while the update math stays fp32: a short training
    trajectory stays close to the fp32-state run, and the first step
    (zero-initialized moments, exactly representable) matches it."""
    def run(state_dtype, steps=20):
        tc = TrainConfig(optimizer="adam", lr=0.0, clip_grad=0.0,
                         weight_decay=0.0,
                         optimizer_state_dtype=state_dtype)
        opt = MegatronOptimizer(tc)
        params = _params()
        state = opt.init(params)
        key = jax.random.PRNGKey(7)
        traj = []
        for i in range(steps):
            key, k = jax.random.split(key)
            grads = jax.tree_util.tree_map(
                lambda p, k=k: jax.random.normal(k, p.shape, jnp.float32),
                params)
            params, state, _ = opt.step(params, grads, state, 0.05, 0.0)
            traj.append(np.asarray(params["layer"]["kernel"]).copy())
        return state, traj

    s32, t32 = run("fp32")
    s16, t16 = run("bf16")
    # storage dtype + leaf-wise byte halving
    m32 = s32.exp_avg["layer"]["kernel"]
    m16 = s16.exp_avg["layer"]["kernel"]
    assert m32.dtype == jnp.float32 and m16.dtype == jnp.bfloat16
    assert s16.exp_avg_sq["layer"]["kernel"].dtype == jnp.bfloat16
    assert m16.nbytes * 2 == m32.nbytes
    # master params stay fp32 regardless (here params are fp32 -> None)
    # step 1 exact (moments start at zero: no accumulated rounding yet,
    # and the step-1 Adam update is sign(g)-scaled so storage precision
    # cancels), later steps track within bf16 accumulation error
    np.testing.assert_allclose(t16[0], t32[0], atol=1e-6)
    np.testing.assert_allclose(t16[-1], t32[-1], rtol=0.0, atol=5e-2)
    # the trajectories must not be identical arrays by accident of an
    # unwired knob: assert the bf16 state really is coarser somewhere
    assert any(not np.array_equal(a, b) for a, b in zip(t16[1:], t32[1:]))


def test_bf16_state_with_low_precision_params():
    """bf16 moments compose with bf16 params + fp32 masters (the bench
    configuration): masters remain fp32 and training still converges
    on the quadratic toy problem."""
    tc = TrainConfig(optimizer="adam", lr=0.0, clip_grad=0.0,
                     weight_decay=0.0, bf16=True,
                     optimizer_state_dtype="bf16")
    opt = MegatronOptimizer(tc, params_dtype=jnp.bfloat16)
    params = jax.tree_util.tree_map(
        lambda p: p.astype(jnp.bfloat16), _params())
    state = opt.init(params)
    assert state.master_params["layer"]["kernel"].dtype == jnp.float32
    assert state.exp_avg["layer"]["kernel"].dtype == jnp.bfloat16
    target = jax.tree_util.tree_map(jnp.zeros_like, params)
    loss0 = None
    for i in range(30):
        grads = jax.tree_util.tree_map(
            lambda p, t: (p - t).astype(jnp.float32), params, target)
        loss = float(sum(jnp.sum(jnp.square(g))
                         for g in jax.tree_util.tree_leaves(grads)))
        loss0 = loss0 if loss0 is not None else loss
        params, state, _ = opt.step(params, grads, state, 0.05, 0.0)
    assert loss < 0.5 * loss0
