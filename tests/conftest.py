"""Test harness: run everything on a virtual 8-device CPU mesh.

The reference's distributed tests require >= 8 real GPUs under torchrun
(``tests/test_utilities.py:6-30`` — real NCCL, no simulation).  We do
better (as SURVEY.md §4 prescribes): XLA's host platform is forced to
expose 8 virtual CPU devices, so every TP/PP/DP/SP test runs in CI with no
hardware.
"""

import os

# Must happen before jax initializes its backends.  The collective-call
# rendezvous timeouts default to 20s/40s; on a loaded or few-core CI box
# the 8 virtual device threads can legitimately take longer to converge
# (compilation runs on the same cores), and the default *aborts the
# process*.  Raise them — slow is fine, SIGABRT mid-suite is not.
#
# XLA aborts the whole process on an UNKNOWN flag in XLA_FLAGS
# (parse_flags_from_env.cc), and the collective-call timeout flags do not
# exist in every jaxlib — probe the extension binary for each flag's name
# and only pass the ones this build knows about.


def _xla_flag_supported(name: str) -> bool:
    try:
        import jaxlib

        so = os.path.join(os.path.dirname(jaxlib.__file__),
                          "xla_extension.so")
        with open(so, "rb") as f:
            return name.encode() in f.read()
    except Exception:
        return True     # can't probe: keep the flag (pre-probe behavior)


_WANTED_FLAGS = [
    "--xla_force_host_platform_device_count=8",
    "--xla_cpu_collective_call_warn_stuck_timeout_seconds=300",
    "--xla_cpu_collective_call_terminate_timeout_seconds=7200",
]
_flags = os.environ.get("XLA_FLAGS", "")
for _f in _WANTED_FLAGS:
    _name = _f.lstrip("-").split("=")[0]
    if _name not in _flags and _xla_flag_supported(_name):
        _flags = (_flags + " " + _f).strip()
os.environ["XLA_FLAGS"] = _flags
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

# The image's sitecustomize force-registers the axon TPU plugin; route the
# test session back to the virtual-device CPU backend (must run before any
# backend is initialized).
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

from megatron_llm_tpu import topology  # noqa: E402


def pytest_configure(config):
    # tier-1 CI runs `-m 'not slow'` (ROADMAP.md); slow = multi-process /
    # subprocess-spawning suites (router failover, replica fleets)
    config.addinivalue_line(
        "markers", "slow: long multi-process tests excluded from tier-1")
    config.addinivalue_line(
        "markers", "chaos: serving fault-injection tests (fast chaos "
                   "units run in tier-1; the multi-process fleet e2e is "
                   "additionally marked slow)")


class Utils:
    """Analogue of the reference's tests/test_utilities.py Utils."""

    world_size = 8

    @staticmethod
    def initialize_model_parallel(tp=1, pp=1, vpp=None, cp=1, num_slices=1):
        topology.destroy_model_parallel()
        return topology.initialize_model_parallel(
            tp, pp, vpp, context_parallel_size=cp, num_slices=num_slices)

    @staticmethod
    def destroy_model_parallel():
        topology.destroy_model_parallel()


@pytest.fixture
def utils():
    yield Utils
    Utils.destroy_model_parallel()


@pytest.fixture(autouse=True)
def _reset_topology():
    yield
    topology.destroy_model_parallel()


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_per_module():
    """Drop compiled executables + tracing caches between test modules.

    A full-suite run accumulates hundreds of compiled shard_map programs;
    on a small CI box the later heavyweight modules (test_pipeline's 1F1B
    engines) then slow to the point of tripping XLA's collective-call
    terminate timeout — a SIGABRT, not a failure.  Per-module cache
    clearing keeps each module's footprint what it is when run alone."""
    yield
    jax.clear_caches()
