"""tools/aot_decode_memcheck.py CI smoke: the tiny rows compile through
the real libtpu AOT path and report bytes + a fits verdict, with the
int8 row's argument bytes strictly below bf16's."""

import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_tiny_rows():
    r = subprocess.run(
        [sys.executable,
         os.path.join(ROOT, "tools", "aot_decode_memcheck.py"), "tiny"],
        capture_output=True, text=True, timeout=1200, cwd=ROOT,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    rows = [json.loads(l) for l in r.stdout.splitlines()
            if l.startswith("{")]
    by_name = {x["row"]: x for x in rows}
    assert by_name["tiny-bf16"]["fits"] and by_name["tiny-int8"]["fits"]
    assert (by_name["tiny-int8"]["arg_gb"]
            < by_name["tiny-bf16"]["arg_gb"])
    # the speculative row AOT-compiles the [b, K+1] verify window
    # through the same path and stays resident
    spec = by_name["tiny-int8-spec4"]
    assert spec["fits"] and spec["spec_k"] == 4
    # same weights + cache as the int8 row: only activation temp grows
    assert spec["arg_gb"] == by_name["tiny-int8"]["arg_gb"]
