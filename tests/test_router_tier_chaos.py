"""Sharded-front-door chaos e2e (slow tier; tools/tpu_sweep.py runs
this file as the wave-2 ``router_kill_chaos`` step).

Real processes all the way down: 2 tiny-model engine replicas
(tests/_serve_replica.py) behind 2 ``tools/serve_router.py --dynamic``
router subprocesses, with a live :class:`FleetSupervisor` managing BOTH
tiers through :class:`RouterTierClient`.

The drill: SIGKILL one router mid-burst.

* clients hold the multi-URL list and retry the sibling on a transport
  error — every request answers exactly once;
* the supervisor notices the dead router, emits ``router_died``, and
  respawns it under the same slot (``router_respawned``), peers and
  replica membership resynced;
* the replicas never notice: zero engine restarts, zero deaths — a
  front-door crash is invisible one layer down;
* fleet-wide /metrics keeps answering at the surviving router
  throughout (tier merge degrades to routers_reporting=1, then heals).
"""

import json
import os
import signal
import sys
import threading
import time
from pathlib import Path

import pytest

from megatron_llm_tpu.serving.supervisor import (
    FleetSupervisor,
    LocalProcessBackend,
    PolicyConfig,
    RouterTierClient,
)

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))
import serve_bench  # noqa: E402

pytestmark = [pytest.mark.slow, pytest.mark.chaos]

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _child_env():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)  # single-device children, no 8-dev mesh
    return env


def _replica_backend():
    return LocalProcessBackend(
        [sys.executable, os.path.join(ROOT, "tests", "_serve_replica.py"),
         "--serve_max_queue_depth", "2048",
         "--serve_deadline_secs", "600"],
        env=_child_env(), cwd=ROOT, spawn_eta_secs=90.0)


def _router_backend():
    """Router subprocesses: supervisor-managed membership (--dynamic),
    free ports, fast probing so a killed replica is noticed quickly.
    They speak the same ``PORT <n>`` handshake replicas do."""
    return LocalProcessBackend(
        [sys.executable, os.path.join(ROOT, "tools", "serve_router.py"),
         "--dynamic", "--host", "127.0.0.1", "--port", "0",
         "--probe_interval_secs", "1.0", "--fail_threshold", "2",
         "--breaker_backoff_secs", "5.0"],
        env=_child_env(), cwd=ROOT, spawn_eta_secs=60.0)


def _wait(predicate, timeout, what):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.25)
    raise AssertionError(f"timed out waiting for {what}")


def test_router_kill_mid_burst_exactly_once_and_respawn(tmp_path):
    """Acceptance: the front door loses a shard mid-burst and nothing
    above or below it can tell afterwards."""
    client = RouterTierClient()
    cfg = PolicyConfig(
        ttft_p95_slo_secs=1e9, queue_depth_high=10 ** 9,
        scale_cooldown_secs=3600.0, scale_down_idle_secs=3600.0,
        min_replicas=2, max_replicas=2,
        min_routers=2, max_routers=2,
        router_dispatch_p95_slo_secs=1e9, router_inflight_high=10 ** 9,
        respawn_backoff_secs=0.5, dead_confirmation_secs=5.0)
    log = tmp_path / "fleet.jsonl"
    sup = FleetSupervisor(client, _replica_backend(), config=cfg,
                          poll_interval_secs=0.5,
                          event_log_path=str(log),
                          router_backend=_router_backend())
    try:
        sup.spawn_initial(2)
        sup.spawn_initial_routers(2)
        sup.start()

        def tier_ready():
            snaps = [s for s in client.router_snapshots().values()
                     if isinstance(s, dict)]
            return (len(client.routers_list()) == 2 and len(snaps) == 2
                    and all(s.get("backends_alive") == 2 for s in snaps)
                    and all(s.get("peers_total") == 1 for s in snaps))

        _wait(tier_ready, 300.0,
              "2 routers ready, each seeing 2 live replicas + 1 peer")
        urls = sup.router_urls()
        assert len(urls) == 2

        victim_proc = sup.routers["router-0"].handle.proc
        n = 24
        results = []
        lock = threading.Lock()
        tail = " ".join(["2"] * 13) + " 3"

        def one(i):
            # the client half of the crash contract: multi-URL list,
            # round-robin start, retry the sibling on transport error
            r = serve_bench._one_request(
                urls,
                {"prompts": [f"{i} {tail}"], "tokens_to_generate": 16,
                 "temperature": 0.0, "no_log": True},
                stream=False, timeout=280.0, start=i % len(urls))
            with lock:
                results.append((i, r))

        threads = [threading.Thread(target=one, args=(i,))
                   for i in range(n)]
        killer = threading.Timer(
            1.0, lambda: victim_proc.send_signal(signal.SIGKILL))
        for t in threads:
            t.start()
        killer.start()
        for t in threads:
            t.join(timeout=300)
        killer.join()

        # exactly once: every ticket answered, answered 200, no dupes
        assert sorted(i for i, _ in results) == list(range(n))
        bad = [(i, r) for i, r in results if not r["ok"]]
        assert not bad, f"requests failed under router kill: {bad}"
        # ~half the tickets started at the dead router and failed over
        assert sum(r["failovers"] for _, r in results) >= 1
        assert all(r["served_by"] == urls[1]
                   for _, r in results if r["failovers"])

        # the surviving router kept answering fleet /metrics alone...
        m = client.aggregated_metrics()
        assert m.get("aggregate", {}).get("requests", 0) >= n

        # ...and the supervisor healed the slot under its own name
        _wait(lambda: sup.counters["router_respawns_total"] >= 1, 300.0,
              "router respawn")
        _wait(lambda: len(sup.router_urls()) == 2, 120.0,
              "respawned router serving")
        _wait(tier_ready, 120.0, "respawned tier fully rewired")
        assert sup.routers["router-0"].state == "ready"
        assert sup.counters["router_deaths_total"] >= 1

        # the replica tier never noticed the front-door crash
        agg = client.aggregated_metrics()["aggregate"]
        assert agg["engine"]["engine_restarts"] == 0
        assert sup.counters["deaths_total"] == 0
        assert sup.counters["respawns_total"] == 0

        # schema-stamped fleet events tell the whole story
        events = [json.loads(line)
                  for line in log.read_text().splitlines()]
        names = [e["event"] for e in events]
        assert names.count("router_spawned") == 2
        assert "router_died" in names and "router_respawned" in names
        assert all(e.get("schema") is not None for e in events)

        # fleet-wide view from EITHER router now merges both siblings
        # again: histograms bucket-wise, percentiles recomputed
        def tier_merged():
            for url in sup.router_urls():
                snap = client._request(url, "GET", "/metrics")
                tier = (snap or {}).get("router_tier")
                if not tier or tier.get("routers_reporting") != 2:
                    return False
                merged = tier["merged"]
                hist = merged["histograms"]["router_dispatch_secs"]
                # the victim's pre-kill counters died with it; the
                # survivor alone handled at least its own 12 starts
                if hist["count"] < n // 2:
                    return False
                assert merged["slo"]["router_dispatch_secs_p95"] \
                    is not None
            return True

        _wait(tier_merged, 120.0, "tier-merged /metrics at both routers")
    finally:
        sup.stop(kill_replicas=True)
