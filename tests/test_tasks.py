"""Downstream-task harness: data utils, GLUE/RACE parsing, zero-shot LM
datasets, detokenizer, finetune accuracy path."""

import json

import numpy as np
import pytest

from tasks.data_utils import (
    build_sample,
    build_tokens_types_paddings_from_ids,
    clean_text,
    truncate_pair,
)


class IntTok:
    """Whitespace-int tokenizer for fixtures."""
    cls, sep, pad, mask, eod = 1, 2, 0, 3, 2

    def tokenize(self, text):
        return [int(t) % 400 + 5 for t in text.split()]

    def detokenize(self, ids):
        return " ".join(str(i) for i in ids)


def test_clean_text():
    assert clean_text("  a\t b \n c  ") == "a b c"
    assert clean_text("x\x00y") == "x y"


def test_truncate_pair():
    a, b = list(range(10)), list(range(6))
    truncate_pair(a, b, 12)
    assert len(a) + len(b) == 12
    a2 = list(range(20))
    truncate_pair(a2, None, 7)
    assert len(a2) == 7


def test_build_tokens_types_paddings():
    ids, types, pads = build_tokens_types_paddings_from_ids(
        [10, 11], [20, 21, 22], 12, cls_id=1, sep_id=2, pad_id=0)
    assert len(ids) == len(types) == len(pads) == 12
    assert ids[:4] == [1, 10, 11, 2]
    assert ids[4:8] == [20, 21, 22, 2]
    assert types[:4] == [0, 0, 0, 0] and types[4:8] == [1, 1, 1, 1]
    assert pads[:8] == [1] * 8 and pads[8:] == [0] * 4
    s = build_sample(ids, types, pads, 2, 7)
    assert s["label"] == 2 and s["uid"] == 7


def test_mnli_parsing(tmp_path):
    from tasks.glue.mnli import MNLIDataset

    p = tmp_path / "dev.tsv"
    with open(p, "w") as f:
        f.write("\t".join(["index"] + ["c"] * 7
                          + ["sentence1", "sentence2", "gold_label"]) + "\n")
        f.write("\t".join(["0"] + ["x"] * 7
                          + ["10 11 12", "20 21", "entailment"]) + "\n")
        f.write("\t".join(["1"] + ["x"] * 7
                          + ["30 31", "40", "neutral"]) + "\n")
    ds = MNLIDataset("dev", [str(p)], IntTok(), 16)
    assert len(ds) == 2
    s = ds[0]
    assert s["label"] == 1  # entailment
    assert s["text"][0] == 1  # [CLS]


def test_qqp_parsing(tmp_path):
    from tasks.glue.qqp import QQPDataset

    p = tmp_path / "train.tsv"
    with open(p, "w") as f:
        f.write("id\tqid1\tqid2\tquestion1\tquestion2\tis_duplicate\n")
        f.write("0\ta\tb\t10 11\t12 13\t1\n")
        f.write("1\ta\tb\t14\t15 16\t0\n")
        f.write("bad row\n")  # malformed: dropped
    ds = QQPDataset("train", [str(p)], IntTok(), 16)
    assert len(ds) == 2
    assert ds[0]["label"] == 1 and ds[1]["label"] == 0


def test_race_parsing(tmp_path):
    from tasks.race.data import RaceDataset

    d = tmp_path / "race"
    d.mkdir()
    with open(d / "doc.txt", "w") as f:
        f.write(json.dumps({
            "article": "10 11 12 13",
            "questions": ["20 _ 21", "22 23"],
            "options": [["30", "31", "32", "33"], ["40", "41", "42", "43"]],
            "answers": ["B", "D"],
        }) + "\n")
    ds = RaceDataset("train", [str(d)], IntTok(), 32)
    assert len(ds) == 2
    s = ds[0]
    assert s["text"].shape == (4, 32)  # 4 choices
    assert s["label"] == 1
    assert ds[1]["label"] == 3


def test_lm_dataset_windows():
    from tasks.zeroshot_gpt.datasets import LMDataset

    tokens = list(range(100, 160))  # 60 tokens
    ds = LMDataset(tokens, seq_len=16, pad_idx=0, num_original_tokens=55,
                   num_tokenized_tokens=60, overlapping_eval=8)
    s0 = ds[0]
    assert s0["text"].shape == (17,)
    assert s0["pad_mask"].sum() == 16
    s1 = ds[1]
    # overlapped window: only the last 8 targets are scored
    assert s1["pad_mask"][:8].sum() == 0
    assert s1["pad_mask"][8:].sum() == 8
    # every target position is scored exactly once across windows
    scored = 0
    for i in range(len(ds)):
        scored += int(ds[i]["pad_mask"].sum())
    assert scored == len(tokens) - 1


def test_lambada_dataset(tmp_path):
    from tasks.zeroshot_gpt.datasets import LambadaDataset

    p = tmp_path / "l.jsonl"
    with open(p, "w") as f:
        f.write(json.dumps({"text": "10 11 12 13 14"}) + "\n")
    ds = LambadaDataset(str(p), pad_idx=0, tokenizer=IntTok(), seq_len=16)
    s = ds[0]
    # only the final-word target is scored
    assert s["pad_mask"].sum() == 1
    n = len(IntTok().tokenize("10 11 12 13 14"))
    assert s["pad_mask"][n - 2] == 1


def test_detokenizer():
    from tasks.zeroshot_gpt.detokenizer import (
        get_detokenizer,
        wikitext_detokenizer,
    )

    assert wikitext_detokenizer(" @-@ ") == "-"
    assert wikitext_detokenizer("a @,@ b") == "a,b"
    assert wikitext_detokenizer("( x )") == "(x)"
    assert wikitext_detokenizer("= = heading = =") == "== heading =="
    assert get_detokenizer("/data/wiki.valid.tokens")("x @.@ y") == "x.y"
    assert get_detokenizer("/data/lambada.jsonl")("as is") == "as is"


def test_orqa_answer_match():
    from tasks.orqa.evaluate_orqa import answer_in_block, load_qa_pairs

    assert answer_in_block(["Paris"], "the capital is paris .")
    assert not answer_in_block(["Rome"], "the capital is paris .")
    assert answer_in_block(["par.s"], "paris", match="regex")


def test_finetune_classification_accuracy(tmp_path):
    """End-to-end: tiny classifier learns a separable toy task."""
    import jax

    from megatron_llm_tpu.models.bert import bert_config
    from megatron_llm_tpu.models.classification import ClassificationModel
    from tasks.finetune_utils import accuracy_func_provider

    cfg = bert_config(num_layers=1, hidden_size=32, num_attention_heads=4,
                      ffn_hidden_size=64, padded_vocab_size=64,
                      seq_length=8, max_position_embeddings=8)
    model = ClassificationModel(cfg, num_classes=2)
    params = model.init(jax.random.PRNGKey(0))

    samples = []
    rng = np.random.RandomState(0)
    for i in range(16):
        label = i % 2
        tok = np.full(8, 10 + label, np.int64)
        samples.append({"text": tok, "types": np.zeros(8, np.int64),
                        "padding_mask": np.ones(8, np.int64),
                        "label": np.int64(label), "uid": np.int64(i)})
    acc_fn = accuracy_func_provider(model, lambda: params, samples, 4)
    acc = acc_fn()
    assert 0.0 <= acc <= 1.0  # random init: just exercises the path
