"""Multiple-choice zero-shot tasks (PIQA/HellaSwag/ARC/BoolQ/Winogrande
— beyond-reference): parser formats, loglikelihood-ranking math with a
rigged scorer, and the tasks/main.py route end to end."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from tasks.zeroshot_gpt.mc_tasks import (
    LENGTH_NORMALIZED,
    load_mc_samples,
    score_choices,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORDS = ["the", "cat", "sat", "good", "bad", "yes", "no", "big", "dog"]


class _Tok:
    pad = 0

    def tokenize(self, text):
        return [5 + WORDS.index(w) for w in text.lower().split()
                if w in WORDS]


def test_parsers(tmp_path):
    cases = {
        "PIQA": ({"goal": "g", "sol1": "a", "sol2": "b", "label": 1}, 2, 1),
        "HELLASWAG": ({"ctx": "c", "endings": ["x", "y", "z", "w"],
                       "label": 2}, 4, 2),
        "ARC-EASY": ({"question": "q",
                      "choices": {"text": ["a", "b", "c"],
                                  "label": ["A", "B", "C"]},
                      "answerKey": "B"}, 3, 1),
        "BOOLQ": ({"passage": "p", "question": "q", "answer": True}, 2, 1),
        "WINOGRANDE": ({"sentence": "the _ sat", "option1": "cat",
                        "option2": "dog", "answer": "2"}, 2, 1),
    }
    partial = {
        "WINOGRANDE": {"sentence": "the _ sat", "option1": "cat",
                       "option2": "dog", "answer": "2"},
    }
    for task, (rec, n_choices, gold) in cases.items():
        p = tmp_path / f"{task}.jsonl"
        p.write_text(json.dumps(rec) + "\n")
        (s,) = load_mc_samples(task, str(p))
        assert len(s["choices"]) == n_choices, task
        assert s["gold"] == gold, task
    # winogrande partial evaluation: per-choice contexts carry the
    # substituted option; the scored continuation is the shared suffix
    p = tmp_path / "wg.jsonl"
    p.write_text(json.dumps(partial["WINOGRANDE"]) + "\n")
    (s,) = load_mc_samples("WINOGRANDE", str(p))
    assert s["contexts"] == ["the cat", "the dog"]
    assert s["choices"] == [" sat", " sat"]
    assert "HELLASWAG" in LENGTH_NORMALIZED


class _RiggedModel:
    """Assigns high prob to one 'good' token id; everything else uniform
    low — makes the loglikelihood argmax analytically known."""

    class cfg:
        num_experts = 0

    def __init__(self, vocab=32, good_id=8):
        self.vocab, self.good_id = vocab, good_id

    def __call__(self, params, tokens, **kw):
        import jax.numpy as jnp

        b, s = tokens.shape
        logits = jnp.zeros((b, s, self.vocab))
        return logits.at[:, :, self.good_id].set(5.0)


def test_score_choices_picks_higher_likelihood():
    """The choice made of the rigged 'good' token must win."""
    model = _RiggedModel(good_id=5 + WORDS.index("good"))
    samples = [
        {"context": "the cat", "choices": [" good good", " bad bad"],
         "gold": 0},
        {"context": "the dog", "choices": [" bad", " good"], "gold": 1},
    ]
    acc, scores = score_choices(model, None, _Tok(), samples, seq_len=8,
                                batch_size=4)
    assert acc == 1.0
    assert scores[0, 0] > scores[0, 1] and scores[1, 1] > scores[1, 0]


def test_length_normalization_changes_ranking():
    """Unnormalized scoring penalizes long continuations; acc_norm does
    not: a 3x-long all-'good' continuation beats a short one only under
    normalization... and ties per-token otherwise."""
    model = _RiggedModel(good_id=5 + WORDS.index("good"))
    samples = [{"context": "the cat",
                "choices": [" good good good", " bad"], "gold": 0}]
    acc_raw, s_raw = score_choices(model, None, _Tok(), samples, seq_len=8,
                                   batch_size=2, length_normalize=False)
    acc_norm, s_norm = score_choices(model, None, _Tok(), samples,
                                     seq_len=8, batch_size=2,
                                     length_normalize=True)
    # raw: 3 good tokens still sum higher than 1 bad token here, but the
    # normalized margin per token must be >= the raw margin / 3
    assert acc_raw == 1.0 and acc_norm == 1.0
    assert s_norm[0, 0] == pytest.approx(s_raw[0, 0] / 3, rel=1e-5)


def test_mc_task_via_tasks_main(tmp_path):
    vocab = tmp_path / "vocab.txt"
    vocab.write_text("\n".join(
        ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"] + WORDS) + "\n")
    data = tmp_path / "piqa.jsonl"
    recs = [{"goal": "the cat", "sol1": "good", "sol2": "bad", "label": 0},
            {"goal": "the dog", "sol1": "bad", "sol2": "good", "label": 1}]
    data.write_text("\n".join(json.dumps(r) for r in recs) + "\n")

    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tasks", "main.py"),
         "--task", "PIQA", "--valid_data", str(data),
         "--tokenizer_type", "BertWordPieceLowerCase",
         "--vocab_file", str(vocab),
         "--num_layers", "2", "--hidden_size", "32",
         "--num_attention_heads", "4", "--ffn_hidden_size", "64",
         "--seq_length", "16", "--max_position_embeddings", "16",
         "--micro_batch_size", "2"],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert "PIQA: acc =" in proc.stdout, proc.stdout[-1000:]
