"""Speculative prompt-lookup decoding == vanilla greedy, token for token."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megatron_llm_tpu.models.llama import LlamaModel, llama_config
from megatron_llm_tpu.text_generation.generation import generate_tokens
from megatron_llm_tpu.text_generation.speculative import (
    speculative_greedy_generate,
)


@pytest.fixture(scope="module")
def model_and_params():
    cfg = llama_config("tiny", num_layers=2, seq_length=128,
                       max_position_embeddings=128, padded_vocab_size=64,
                       use_flash_attn=False)
    model = LlamaModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    return model, params


def _vanilla(model, params, toks, n_new, eod=None):  # params may be quantized
    lens = jnp.asarray([toks.shape[1]], jnp.int32)
    out, n, _ = generate_tokens(
        model, params, toks, lens, jax.random.PRNGKey(0),
        max_new_tokens=n_new, min_prompt_len=toks.shape[1], greedy=True,
        eod_id=eod)
    return np.asarray(out[0]), int(jnp.asarray(n).reshape(-1)[0])


@pytest.mark.parametrize("k", [1, 4, 8])
@pytest.mark.parametrize("prompt", [
    # repetitive prompt: lookup drafting should accept often
    [5, 9, 5, 9, 5, 9, 5, 9],
    # arbitrary prompt: acceptance may be zero — result must STILL match
    [3, 17, 42, 8, 11, 2, 29],
])
def test_matches_vanilla_greedy(model_and_params, k, prompt):
    model, params = model_and_params
    toks = jnp.asarray([prompt], jnp.int32)
    n_new = 24
    want, _ = _vanilla(model, params, toks, n_new)
    got, n = speculative_greedy_generate(
        model, params, toks, jnp.asarray([len(prompt)], jnp.int32),
        max_new_tokens=n_new, draft_k=k)
    np.testing.assert_array_equal(np.asarray(got[0]), want)
    assert int(jnp.asarray(n).reshape(-1)[0]) == n_new


def test_padded_prompt_refused(model_and_params):
    model, params = model_and_params
    toks = jnp.asarray([[5, 9, 5, 9, 0, 0]], jnp.int32)
    with pytest.raises(Exception):
        speculative_greedy_generate(
            model, params, toks, jnp.asarray([4], jnp.int32),
            max_new_tokens=4)


def test_eod_stops_early(model_and_params):
    """With eod_id set to a token the model actually produces, both
    decoders stop at the same place; tokens agree through the stop."""
    model, params = model_and_params
    toks = jnp.asarray([[5, 9, 5, 9, 5, 9]], jnp.int32)
    n_new = 24
    # find a token the vanilla run produces, use it as the "eod"
    full, _ = _vanilla(model, params, toks, n_new)
    eod = int(full[toks.shape[1] + 4])  # the 5th generated token
    want, want_n = _vanilla(model, params, toks, n_new, eod=eod)
    got, got_n = speculative_greedy_generate(
        model, params, toks, jnp.asarray([6], jnp.int32),
        max_new_tokens=n_new, draft_k=4, eod_id=eod)
    got_n = int(jnp.asarray(got_n).reshape(-1)[0])
    # vanilla's gen length counts through the eod token
    assert got_n <= n_new
    stop = toks.shape[1] + got_n
    np.testing.assert_array_equal(np.asarray(got[0][:stop]), want[:stop])
    assert int(np.asarray(got[0][stop - 1])) == eod


def test_composes_with_int8_weights(model_and_params):
    """Speculative decode over int8-quantized params matches vanilla
    greedy over the SAME quantized params (exactness is vs the same
    weights, whatever their precision)."""
    from megatron_llm_tpu.quantization import quantize_linear_weights_int8
    model, params = model_and_params
    qparams = quantize_linear_weights_int8(params)
    toks = jnp.asarray([[5, 9, 5, 9, 5, 9, 5, 9]], jnp.int32)
    want, _ = _vanilla(model, qparams, toks, 16)
    got, n = speculative_greedy_generate(
        model, qparams, toks, jnp.asarray([8], jnp.int32),
        max_new_tokens=16, draft_k=6)
    np.testing.assert_array_equal(np.asarray(got[0]), want)
