"""BiEncoder / ICT retrieval stack: towers, in-batch loss, MIPS index,
IndexBuilder round trip."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from megatron_llm_tpu.models.bert import bert_config
from megatron_llm_tpu.models.biencoder import (
    BiEncoderModel,
    ict_retrieval_loss,
)


def tiny_cfg(**kw):
    base = dict(
        num_layers=2, hidden_size=32, num_attention_heads=4,
        ffn_hidden_size=64, padded_vocab_size=96, seq_length=24,
        max_position_embeddings=24)
    base.update(kw)
    return bert_config(**base)


def test_biencoder_towers():
    model = BiEncoderModel(tiny_cfg(), projection_dim=16)
    params = model.init(jax.random.PRNGKey(0))
    assert set(params) == {"query", "context"}
    toks = jnp.asarray(
        np.random.RandomState(0).randint(0, 96, (3, 24)), jnp.int32)
    mask = jnp.ones((3, 24), jnp.int32)
    q, c = model(params, toks, mask, toks, mask)
    assert q.shape == (3, 16) and c.shape == (3, 16)
    # separate towers -> different embeddings for identical input
    assert not np.allclose(np.asarray(q), np.asarray(c))


def test_biencoder_shared_tower():
    model = BiEncoderModel(tiny_cfg(), shared_query_context=True)
    params = model.init(jax.random.PRNGKey(0))
    assert set(params) == {"shared"}
    toks = jnp.asarray(
        np.random.RandomState(0).randint(0, 96, (2, 24)), jnp.int32)
    mask = jnp.ones((2, 24), jnp.int32)
    q, c = model(params, toks, mask, toks, mask)
    np.testing.assert_allclose(np.asarray(q), np.asarray(c), rtol=1e-5)


def test_ict_retrieval_loss_perfect():
    # orthogonal embeddings -> each query matches its own context
    d = 8
    q = jnp.eye(d) * 10.0
    loss, stats = ict_retrieval_loss(q, q, topk=(1, 5))
    assert float(stats["top1_acc"]) == 100.0
    assert float(loss) < 1e-3
    # adversarial: query 0 matches context 1
    perm = q[jnp.array([1, 0] + list(range(2, d)))]
    loss2, stats2 = ict_retrieval_loss(q, perm, topk=(1,))
    assert float(stats2["top1_acc"]) < 100.0
    assert float(loss2) > float(loss)


def test_ict_loss_score_scaling():
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(4, 16), jnp.float32)
    c = jnp.asarray(rng.randn(4, 16), jnp.float32)
    l1, _ = ict_retrieval_loss(q, c)
    l2, _ = ict_retrieval_loss(q, c, score_scaling=True, hidden_size=256)
    assert not np.isclose(float(l1), float(l2))


def test_mips_index():
    from megatron_llm_tpu.data.realm_index import BruteForceMIPSIndex

    rng = np.random.RandomState(3)
    embeds = {i: rng.randn(16).astype(np.float32) for i in range(50)}
    index = BruteForceMIPSIndex(16, embeds, use_jax=False)
    assert len(index) == 50
    # query = exact copy of block 7's embedding -> top1 must be id 7
    dists, ids = index.search_mips_index(embeds[7][None, :] * 5, top_k=3)
    assert ids[0, 0] == 7
    assert dists.shape == (1, 3)
    assert dists[0, 0] >= dists[0, 1] >= dists[0, 2]
    # reconstruct returns embeddings
    _, recon = index.search_mips_index(embeds[7][None, :], 2,
                                       reconstruct=True)
    np.testing.assert_allclose(recon[0, 0], embeds[7], rtol=1e-5)


def test_datastore_shard_merge(tmp_path):
    from megatron_llm_tpu.data.realm_index import OpenRetrievalDataStore

    path = str(tmp_path / "embeds.pkl")
    s0 = OpenRetrievalDataStore(path, load_from_path=False, rank=0)
    s0.add_block_data([0, 1], np.ones((2, 4), np.float32))
    s0.save_shard()
    s1 = OpenRetrievalDataStore(path, load_from_path=False, rank=1)
    s1.add_block_data([2, 3], np.full((2, 4), 2.0, np.float32))
    s1.save_shard()
    merged = OpenRetrievalDataStore(path, load_from_path=False, rank=0)
    merged.merge_shards_and_save()
    loaded = OpenRetrievalDataStore(path, load_from_path=True)
    assert set(loaded.embed_data) == {0, 1, 2, 3}
    assert loaded.embed_data[2].dtype == np.float16

    with pytest.raises(ValueError):
        loaded.add_block_data([2], np.zeros((1, 4)))


def test_index_builder(tmp_path):
    from megatron_llm_tpu.data.ict_dataset import ICTDataset
    from megatron_llm_tpu.indexer import IndexBuilder
    from tests.test_bert_t5_data import ToyTok, _write_corpus, _write_titles

    prefix, blocks = _write_corpus(tmp_path, n_docs=8)
    _, titles = _write_titles(tmp_path, n_docs=8)
    ict = ICTDataset(name="full", block_dataset=blocks,
                     title_dataset=titles, data_prefix=prefix,
                     num_epochs=1, max_num_samples=None, max_seq_length=24,
                     query_in_block_prob=1.0, seed=5, tokenizer=ToyTok(),
                     use_one_sent_docs=True)
    model = BiEncoderModel(tiny_cfg(padded_vocab_size=512), projection_dim=8)
    params = model.init(jax.random.PRNGKey(1))
    builder = IndexBuilder(model, params, ict,
                           str(tmp_path / "embed.pkl"), batch_size=4)
    builder.build_and_save_index()

    from megatron_llm_tpu.data.realm_index import (
        BruteForceMIPSIndex,
        OpenRetrievalDataStore,
    )
    store = OpenRetrievalDataStore(str(tmp_path / "embed.pkl"))
    assert len(store.embed_data) == len(ict)
    index = BruteForceMIPSIndex(8, store)
    # exact MIPS: whatever is retrieved at rank 1 scores >= the query's own
    # block (a tiny random model may embed blocks near-identically, so
    # requiring ids[0,0] == bid would be flaky)
    bid = next(iter(store.embed_data))
    q = np.asarray(store.embed_data[bid], np.float32)[None, :]
    dists, ids = index.search_mips_index(q, top_k=len(index))
    own = float(q @ np.asarray(store.embed_data[bid], np.float32))
    assert float(dists[0, 0]) >= own - 1e-3
    assert bid in ids[0]  # self is somewhere in the full ranking
