"""Meta consolidated.*.pth shard merging -> HF naming -> TPU conversion."""

import json

import numpy as np
import pytest

torch = pytest.importorskip("torch")

from weights_conversion.merge_llama import (  # noqa: E402
    merge_llama,
    meta_to_hf_names,
)

DIM, FFN, HEADS, LAYERS, VOCAB = 16, 40, 4, 2, 64


def _full_meta_state(rng):
    sd = {}
    sd["tok_embeddings.weight"] = rng.randn(VOCAB, DIM)
    sd["norm.weight"] = rng.randn(DIM)
    sd["output.weight"] = rng.randn(VOCAB, DIM)
    for i in range(LAYERS):
        p = f"layers.{i}."
        sd[p + "attention.wq.weight"] = rng.randn(DIM, DIM)
        sd[p + "attention.wk.weight"] = rng.randn(DIM, DIM)
        sd[p + "attention.wv.weight"] = rng.randn(DIM, DIM)
        sd[p + "attention.wo.weight"] = rng.randn(DIM, DIM)
        sd[p + "feed_forward.w1.weight"] = rng.randn(FFN, DIM)
        sd[p + "feed_forward.w2.weight"] = rng.randn(DIM, FFN)
        sd[p + "feed_forward.w3.weight"] = rng.randn(FFN, DIM)
        sd[p + "attention_norm.weight"] = rng.randn(DIM)
        sd[p + "ffn_norm.weight"] = rng.randn(DIM)
    return {k: v.astype(np.float32) for k, v in sd.items()}


def _shard(sd, n, which):
    """Split like Meta: dim-0 for column-parallel keys, dim-1 for
    row-parallel, replicate norms."""
    from weights_conversion.merge_llama import MERGE_DIM, _short_name

    out = {}
    for name, arr in sd.items():
        dim = MERGE_DIM.get(_short_name(name))
        if dim is None:
            out[name] = arr
        elif dim == 0:
            out[name] = np.split(arr, n, axis=0)[which]
        else:
            out[name] = np.split(arr, n, axis=1)[which]
    return {k: torch.from_numpy(v.copy()) for k, v in out.items()}


def _write_meta_dir(tmp_path, sd, n_shards=2):
    for s in range(n_shards):
        torch.save(_shard(sd, n_shards, s),
                   tmp_path / f"consolidated.{s:02d}.pth")
    with open(tmp_path / "params.json", "w") as f:
        json.dump({"dim": DIM, "n_layers": LAYERS, "n_heads": HEADS,
                   "norm_eps": 1e-5, "vocab_size": VOCAB}, f)


def test_merge_llama_roundtrip(tmp_path):
    rng = np.random.RandomState(0)
    full = _full_meta_state(rng)
    _write_meta_dir(tmp_path, full, n_shards=2)
    merged = merge_llama(str(tmp_path))
    assert set(merged) == set(full)
    for name in full:
        np.testing.assert_array_equal(merged[name], full[name]), name


def test_meta_to_hf_names(tmp_path):
    rng = np.random.RandomState(1)
    full = _full_meta_state(rng)
    _write_meta_dir(tmp_path, full)
    hf = meta_to_hf_names(merge_llama(str(tmp_path)), HEADS, HEADS)
    assert "model.embed_tokens.weight" in hf
    assert "lm_head.weight" in hf
    assert f"model.layers.{LAYERS-1}.mlp.down_proj.weight" in hf
    assert hf["model.layers.0.self_attn.q_proj.weight"].shape == (DIM, DIM)


def test_meta_rotary_layout_roundtrip(tmp_path):
    """Meta wq/wk are interleaved; meta_to_hf_names must emit the HF
    half-split layout so the converter's rotary_hf_to_interleaved recovers
    the ORIGINAL Meta weights (regression: double-permutation scrambled
    q/k)."""
    from weights_conversion.util import rotary_hf_to_interleaved

    rng = np.random.RandomState(3)
    full = _full_meta_state(rng)
    _write_meta_dir(tmp_path, full)
    hf = meta_to_hf_names(merge_llama(str(tmp_path)), HEADS, HEADS)
    head_dim = DIM // HEADS
    for i in range(LAYERS):
        for meta_key, hf_key in [
                (f"layers.{i}.attention.wq.weight",
                 f"model.layers.{i}.self_attn.q_proj.weight"),
                (f"layers.{i}.attention.wk.weight",
                 f"model.layers.{i}.self_attn.k_proj.weight")]:
            np.testing.assert_array_equal(
                rotary_hf_to_interleaved(hf[hf_key].copy(), head_dim),
                full[meta_key])
    # v is untouched
    np.testing.assert_array_equal(
        hf["model.layers.0.self_attn.v_proj.weight"],
        full["layers.0.attention.wv.weight"])


def test_meta_shim_llama1_context(tmp_path):
    from weights_conversion.hf_to_megatron import MetaLlamaShim

    rng = np.random.RandomState(4)
    _write_meta_dir(tmp_path, _full_meta_state(rng))
    assert MetaLlamaShim(str(tmp_path), "llama").config \
        .max_position_embeddings == 2048
    assert MetaLlamaShim(str(tmp_path), "llama2").config \
        .max_position_embeddings == 4096


def test_meta_shim_converts(tmp_path):
    from weights_conversion.hf_to_megatron import CONVERTERS, MetaLlamaShim

    rng = np.random.RandomState(2)
    _write_meta_dir(tmp_path, _full_meta_state(rng))
    shim = MetaLlamaShim(str(tmp_path))
    assert shim.config.num_hidden_layers == LAYERS
    assert shim.config.intermediate_size == FFN
    params, config = CONVERTERS["llama2"](shim)
    qkv = params["transformer"]["layers"]["attention"]["query_key_value"]["kernel"]
    assert qkv.shape[0] == LAYERS
    assert config["num_layers"] == LAYERS
