"""finetune.py --lora_rank end to end: adapters train over a frozen
base, and the saved checkpoint is a standard MERGED one that a plain
(non-LoRA) run can load."""

import os
import re
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _env():
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return env


def _run(extra):
    return subprocess.run(
        [sys.executable, os.path.join(ROOT, "finetune.py"),
         "--model_name=llama2", "--num_layers=2", "--hidden_size=64",
         "--num_attention_heads=4", "--seq_length=32",
         "--max_position_embeddings=32", "--micro_batch_size=2",
         "--global_batch_size=16", "--lr=1e-2", "--vocab_size=128",
         "--log_interval=1", "--lr_decay_style=constant"] + extra,
        cwd=ROOT, env=_env(), capture_output=True, text=True,
        timeout=1200)


def test_lora_cli_train_and_merged_checkpoint(tmp_path):
    ck = str(tmp_path / "ck")
    r = _run(["--train_iters=8", "--lora_rank=2", "--lora_alpha=8",
              f"--save={ck}", "--save_interval=8", "--seed=3"])
    assert r.returncode == 0, r.stderr[-3000:]
    assert "LoRA rank 2" in r.stdout
    losses = [float(m) for m in re.findall(r"lm loss: ([0-9.E+-]+)",
                                           r.stdout)]
    assert len(losses) >= 8, losses
    # 8 iters of a rank-2 adapter moves the loss by ~1e-2 — comparable to
    # per-step noise, so last-vs-first flakes.  Compare window means: the
    # trend survives the noise.
    assert (sum(losses[-4:]) / 4) < (sum(losses[:4]) / 4), losses

    # the exported checkpoint is MERGED: a plain non-LoRA run loads it
    r2 = _run(["--train_iters=2", f"--load={ck}", "--finetune",
               "--seed=4"])
    assert r2.returncode == 0, r2.stderr[-3000:]
    assert "loaded checkpoint" in r2.stdout
