"""Worker for tests/test_multihost_cpu.py — runs as one of two REAL
processes (jax.distributed over localhost gloo, one CPU device each).
Not collected by pytest (underscore prefix)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    # the documented launch contract: torchrun-style env vars
    # (docs/guide/faq.md "Multi-host launch?")
    import jax

    from megatron_llm_tpu import topology

    topology.initialize_distributed()
    rank = jax.process_index()
    assert jax.process_count() == 2

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from megatron_llm_tpu import random as mrandom
    from megatron_llm_tpu.config import ParallelConfig, TrainConfig
    from megatron_llm_tpu.data.data_samplers import place_host_batch
    from megatron_llm_tpu.models.llama import LlamaModel, llama_config
    from megatron_llm_tpu.optimizer import MegatronOptimizer
    from megatron_llm_tpu.parallel import sharding as sh
    from megatron_llm_tpu.training import build_train_step

    mesh = topology.initialize_model_parallel()   # dp = 2 (1 dev/process)
    assert topology.get_data_parallel_world_size() == 2

    cfg = llama_config("tiny", num_layers=2, seq_length=32,
                       max_position_embeddings=32, padded_vocab_size=128)
    model = LlamaModel(cfg)
    params = model.init(jax.random.PRNGKey(0))     # same seed -> identical
    params = sh.shard_params(params, model.param_specs(params))

    M, dp = 2, 2
    tc = TrainConfig(micro_batch_size=1, global_batch_size=M * dp, lr=1e-3)
    pc = ParallelConfig(data_parallel_size=dp)
    opt = MegatronOptimizer(tc)
    opt_state = opt.init(params)
    step = build_train_step(model, opt, pc, M)

    # every process builds the SAME global batch (the multi-host data
    # contract); place_host_batch transfers only addressable shards
    rng = np.random.RandomState(0)
    toks = rng.randint(0, 128, (M, dp, 32)).astype(np.int32)
    dsh = NamedSharding(mesh, P(None, "dp", None))
    batch = {
        "tokens": place_host_batch(toks, dsh),
        "labels": place_host_batch(np.roll(toks, -1, axis=-1), dsh),
        "loss_mask": place_host_batch(
            np.ones_like(toks, np.float32), dsh),
    }
    _, _, metrics = step(params, opt_state, batch, jax.random.PRNGKey(0),
                         1e-3, 0.0)
    loss = float(metrics["lm loss"])
    assert np.isfinite(loss)
    print(f"RANK{rank} LOSS {loss:.6f}", flush=True)

    # cross-host checksum guard: identical batches pass...
    os.environ["MEGATRON_TPU_DATA_CHECKSUM"] = "1"
    place_host_batch(toks, dsh)
    print(f"RANK{rank} CHECKSUM_OK", flush=True)
    # ...and a rank-divergent batch is caught on every process
    bad = toks + rank
    try:
        place_host_batch(bad, dsh)
        print(f"RANK{rank} DIVERGENCE_MISSED", flush=True)
        sys.exit(2)
    except RuntimeError as e:
        assert "DIVERGE" in str(e)
        print(f"RANK{rank} DIVERGENCE_CAUGHT", flush=True)


if __name__ == "__main__":
    main()
