"""Multi-replica router (serving/router.py).

Fast tier: stub HTTP backends (no model) cover dispatch policy, sticky
affinity, circuit breaking, 429 aggregation, metrics aggregation, the
RouterServer HTTP surface, and ~linear scaling over serial stubs.

Slow tier (``-m slow``; excluded from tier-1): two REAL tiny-model
engine subprocesses behind the router — aggregate throughput vs one
replica, and SIGKILL failover with zero dropped in-flight requests.
"""

import io
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from megatron_llm_tpu.serving.router import (
    AllBackendsThrottled,
    Backend,
    NoBackendAvailable,
    ReplicaRouter,
    RouterServer,
    _prompt_affinity_digest,
    _sum_numeric,
    rendezvous_order,
)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _prompt_on(target_url, all_urls, tail="t"):
    """A prompt whose rendezvous order puts ``target_url`` first.

    Routing is a pure function of (prompt digest, live URLs), so tests
    that need a specific backend tried first (e.g. the dead one, to
    exercise failover) pick their prompt with the same function the
    router uses instead of relying on list order."""
    urls = [Backend(u).url for u in all_urls]
    want = Backend(target_url).url
    for i in range(4096):
        p = f"{i} {tail}"
        if rendezvous_order(_prompt_affinity_digest(p), urls)[0] == want:
            return p
    raise AssertionError("no prompt rendezvoused onto " + want)


class _Stub:
    """Minimal engine-replica lookalike: /api (+stream), /health,
    /metrics — enough surface for the router."""

    def __init__(self, name: str, sleep: float = 0.0,
                 throttle_body=None, serial: bool = False,
                 metrics_extra=None, stream_die: bool = False):
        self.name = name
        self.sleep = sleep
        self.throttle_body = throttle_body
        self.metrics_extra = metrics_extra or {}
        self.stream_die = stream_die
        self.hits = []
        self.trace_headers = []
        self.healthy = True
        self.draining = False
        lock = threading.Lock()
        stub = self

        class Handler(BaseHTTPRequestHandler):
            def _json(self, code, body):
                data = json.dumps(body).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                if code == 429:
                    self.send_header("Retry-After", "1")
                self.end_headers()
                self.wfile.write(data)

            def do_PUT(self):
                n = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(n) or b"{}")
                stub.hits.append(payload)
                stub.trace_headers.append(
                    self.headers.get("X-Request-Trace"))
                if stub.throttle_body is not None:
                    self._json(429, stub.throttle_body)
                    return
                if self.path == "/api/stream":
                    if stub.stream_die:
                        # chunked framing so the client can tell an abrupt
                        # close from a normal end-of-body: first event goes
                        # out, then the socket dies without the terminating
                        # 0-length chunk (models a replica crashing after
                        # the first byte of a stream)
                        self.protocol_version = "HTTP/1.1"
                        self.send_response(200)
                        self.send_header("Content-Type",
                                         "text/event-stream")
                        self.send_header("Transfer-Encoding", "chunked")
                        self.end_headers()
                        ev = {"token": 1, "segment": "1"}
                        payload = (b"data: " + json.dumps(ev).encode()
                                   + b"\n\n")
                        self.wfile.write(b"%x\r\n" % len(payload)
                                         + payload + b"\r\n")
                        self.wfile.flush()
                        try:
                            self.connection.shutdown(socket.SHUT_RDWR)
                            self.connection.close()
                        except OSError:
                            pass
                        # let finish() flush/close harmlessly
                        self.wfile = io.BytesIO()
                        self.rfile = io.BytesIO()
                        self.close_connection = True
                        return
                    self.send_response(200)
                    self.send_header("Content-Type", "text/event-stream")
                    self.end_headers()
                    for ev in ({"token": 1, "segment": "1"},
                               {"done": True, "backend": stub.name}):
                        self.wfile.write(b"data: " + json.dumps(ev).encode()
                                         + b"\n\n")
                        self.wfile.flush()
                    return
                if serial:
                    with lock:
                        time.sleep(stub.sleep)
                elif stub.sleep:
                    time.sleep(stub.sleep)
                self._json(200, {"backend": stub.name,
                                 "text": ["ok"], "tokens": [[1, 2, 3]]})

            do_POST = do_PUT

            def do_GET(self):
                if self.path == "/health":
                    self._json(200 if stub.healthy else 503,
                               {"status": "draining" if stub.draining
                                else "ok"})
                elif self.path.startswith("/metrics"):
                    engine = {"tokens_generated": 10, "queue_depth": 1}
                    body = {"requests": len(stub.hits), "engine": engine}
                    for k, v in stub.metrics_extra.items():
                        if k == "engine":
                            engine.update(v)
                        else:
                            body[k] = v
                    self._json(200, body)
                else:
                    self.send_error(404)

            def log_message(self, fmt, *args):
                pass

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.httpd.server_address[1]
        self.url = f"127.0.0.1:{self.port}"
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    def close(self):
        self.httpd.shutdown()


@pytest.fixture
def stubs():
    made = []

    def make(*a, **kw):
        s = _Stub(*a, **kw)
        made.append(s)
        return s

    yield make
    for s in made:
        s.close()


def _payload(prompt: str) -> bytes:
    return json.dumps({"prompts": [prompt],
                       "tokens_to_generate": 4}).encode()


def test_backend_url_parsing():
    b = Backend("localhost:5000")
    assert b.host == "localhost" and b.port == 5000
    assert Backend("http://10.0.0.1:81").url == "http://10.0.0.1:81"
    with pytest.raises(ValueError):
        Backend("nonsense")


def test_rendezvous_spread_across_backends(stubs):
    a, b = stubs("a", sleep=0.05), stubs("b", sleep=0.05)
    router = ReplicaRouter([a.url, b.url], health_interval_secs=999)
    errs = []
    # distinct prompts spread by rendezvous hash, not by load; pick 4
    # landing on each backend so the expected split is exact
    prompts = ([_prompt_on(a.url, [a.url, b.url], tail=f"a{i}")
                for i in range(4)]
               + [_prompt_on(b.url, [a.url, b.url], tail=f"b{i}")
                  for i in range(4)])

    def client(i):
        try:
            router.dispatch("PUT", "/api", _payload(prompts[i]))
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    assert len(a.hits) == 4 and len(b.hits) == 4, \
        f"no spread: a={len(a.hits)} b={len(b.hits)}"
    assert router.requests_total == 8


def test_keyless_requests_stay_least_loaded(stubs):
    a, b = stubs("a"), stubs("b")
    router = ReplicaRouter([a.url, b.url], health_interval_secs=999)
    # no "prompts" field -> no affinity digest -> least-loaded rotation
    for _ in range(6):
        status, _, _ = router.dispatch(
            "PUT", "/api", json.dumps({"tokens_to_generate": 1}).encode())
        assert status == 200
    assert len(a.hits) == 3 and len(b.hits) == 3, \
        f"least-loaded rotation broken: a={len(a.hits)} b={len(b.hits)}"


def test_sticky_affinity_routes_repeats_to_same_backend(stubs):
    a, b = stubs("a"), stubs("b")
    router = ReplicaRouter([a.url, b.url], health_interval_secs=999)
    for _ in range(4):
        status, _, data = router.dispatch("PUT", "/api",
                                          _payload("7 7 7 session-x"))
        assert status == 200
    owner = json.loads(data)["backend"]
    hits = a.hits if owner == "a" else b.hits
    assert len(hits) == 4, "affinity did not stick"
    assert router.affinity_hits >= 3


def test_failover_and_circuit_breaker(stubs):
    live = stubs("live")
    dead_url = f"127.0.0.1:{_free_port()}"
    router = ReplicaRouter([dead_url, live.url], fail_threshold=2,
                           cooldown_secs=30.0, health_interval_secs=999)
    # prompts that rendezvous onto the dead backend: it is tried first
    # (and fails over) until the breaker opens
    for i in range(4):
        status, _, data = router.dispatch(
            "PUT", "/api",
            _payload(_prompt_on(dead_url, [dead_url, live.url],
                                tail=f"cb{i}")))
        assert status == 200
        assert json.loads(data)["backend"] == "live"
    dead = router.backends[0]
    assert dead.consecutive_failures >= 2
    assert not dead.available(router.fail_threshold)
    assert router.failovers_total == 2        # breaker stops the retries
    snap = router.snapshot()
    assert snap["backends_alive"] == 1
    assert snap["backends"]["backend_0"]["alive"] == 0


def test_no_live_backend_raises_503_path(stubs):
    router = ReplicaRouter([f"127.0.0.1:{_free_port()}"],
                           fail_threshold=1, cooldown_secs=60.0,
                           health_interval_secs=999)
    with pytest.raises(NoBackendAvailable):
        router.dispatch("PUT", "/api", _payload("1"))
    assert router.no_backend_total == 1


def test_429_most_optimistic_aggregation(stubs):
    a = stubs("a", throttle_body={"retry_after_secs": 4.0,
                                  "queue_depth": 40,
                                  "estimated_wait_secs": 9.0})
    b = stubs("b", throttle_body={"retry_after_secs": 2.0,
                                  "queue_depth": 10,
                                  "estimated_wait_secs": 3.0})
    router = ReplicaRouter([a.url, b.url], health_interval_secs=999)
    with pytest.raises(AllBackendsThrottled) as ei:
        router.dispatch("PUT", "/api", _payload("1 2"))
    body = ei.value.body
    assert body["backends_throttled"] == 2
    assert body["retry_after_secs"] == 2.0        # min across replicas
    assert body["queue_depth"] == 10
    assert body["estimated_wait_secs"] == 3.0
    assert router.throttled_total == 1


def test_health_probe_trips_and_revives_breaker(stubs):
    a = stubs("a")
    router = ReplicaRouter([a.url], fail_threshold=2,
                           health_interval_secs=999)
    backend = router.backends[0]
    # trip the breaker artificially (as consecutive failures would)
    backend.consecutive_failures = 5
    backend.dead_until = time.monotonic() + 300
    assert router.alive_count() == 0
    assert router.probe_once() == 1               # /health 200 -> revived
    assert router.alive_count() == 1
    assert backend.consecutive_failures == 0


def test_sum_numeric_and_aggregated_metrics(stubs):
    agg = {}
    _sum_numeric(agg, {"a": 1, "nested": {"x": 2.5}, "s": "skip"})
    _sum_numeric(agg, {"a": 2, "nested": {"x": 1.5, "y": 1}})
    assert agg == {"a": 3, "nested": {"x": 4.0, "y": 1}}

    a, b = stubs("a"), stubs("b")
    router = ReplicaRouter([a.url, b.url], health_interval_secs=999)
    router.dispatch("PUT", "/api", _payload("1 2"))
    m = router.aggregated_metrics()
    assert m["aggregate"]["engine"]["tokens_generated"] == 20
    assert m["router"]["backends_total"] == 2
    assert set(m["backends"]) == {"backend_0", "backend_1"}


def _heat(prefix, hits):
    return {"prefix": prefix, "hits": hits, "hit_tokens": hits * 4,
            "residency": hits, "peak_refcount": 1, "evictions": 0,
            "regret": 0, "last_access_age": 1}


def test_aggregated_metrics_merges_cache_heat_tables(stubs):
    """Numeric cache counters sum via _sum_numeric, but heat_top is a
    list (silently dropped by the numeric fold) — the router must merge
    it explicitly by salted prefix across replicas."""
    a = stubs("a", metrics_extra={"engine": {"cache": {
        "probes": 10, "hits": 6,
        "heat_top": [_heat("aaaa", 5), _heat("bbbb", 1)]}}})
    b = stubs("b", metrics_extra={"engine": {"cache": {
        "probes": 4, "hits": 2,
        "heat_top": [_heat("aaaa", 2)]}}})
    router = ReplicaRouter([a.url, b.url], health_interval_secs=999)
    cache = router.aggregated_metrics()["aggregate"]["engine"]["cache"]
    assert cache["probes"] == 14 and cache["hits"] == 8
    top = {e["prefix"]: e["hits"] for e in cache["heat_top"]}
    assert top == {"aaaa": 7, "bbbb": 1}


@pytest.fixture
def router_server(stubs):
    a, b = stubs("a"), stubs("b")
    router = ReplicaRouter([a.url, b.url], health_interval_secs=999)
    srv = RouterServer(router)
    t = threading.Thread(target=srv.run,
                         kwargs={"host": "127.0.0.1", "port": 0},
                         daemon=True)
    t.start()
    for _ in range(100):
        if srv.httpd is not None:
            break
        time.sleep(0.05)
    assert srv.httpd is not None
    url = f"http://127.0.0.1:{srv.httpd.server_address[1]}"
    yield url, router, (a, b)
    router.stop()
    srv.httpd.shutdown()


def test_router_server_http_surface(router_server):
    url, router, (a, b) = router_server
    req = urllib.request.Request(url + "/api", data=_payload("1 2 3"),
                                 method="PUT")
    with urllib.request.urlopen(req, timeout=30) as resp:
        assert resp.status == 200
        assert json.loads(resp.read())["backend"] in ("a", "b")
    with urllib.request.urlopen(url + "/health", timeout=30) as resp:
        health = json.loads(resp.read())
        assert resp.status == 200 and health["backends_alive"] == 2
    with urllib.request.urlopen(url + "/metrics", timeout=30) as resp:
        m = json.loads(resp.read())
        assert m["router"]["requests_total"] == 1
    with urllib.request.urlopen(url + "/metrics?format=prometheus",
                                timeout=30) as resp:
        text = resp.read().decode()
        assert "megatron_router_router_requests_total 1" in text
        assert "megatron_router_aggregate_" in text


def test_router_server_stream_passthrough(router_server):
    url, _, _ = router_server
    req = urllib.request.Request(url + "/api/stream",
                                 data=_payload("5 6"), method="PUT")
    with urllib.request.urlopen(req, timeout=30) as resp:
        assert resp.headers.get("Content-Type", "").startswith(
            "text/event-stream")
        events = [json.loads(line[len(b"data: "):])
                  for line in resp if line.startswith(b"data: ")]
    assert {"token": 1, "segment": "1"} in events
    assert events[-1]["done"] is True


def test_linear_scaling_over_serial_stubs(stubs):
    """Each stub serializes its requests (a lock + sleep models one
    engine's capacity); two replicas should cut wall time ~in half.
    Prompts are picked to rendezvous 4/4 across the pair so the
    measured speedup reflects capacity, not hash luck."""
    def run_fleet(urls, n=8):
        if len(urls) == 1:
            prompts = [f"{i} 9" for i in range(n)]
        else:
            prompts = [_prompt_on(urls[i % len(urls)], urls,
                                  tail=f"sc{i}") for i in range(n)]
        router = ReplicaRouter(urls, health_interval_secs=999)
        t0 = time.perf_counter()
        threads = [threading.Thread(
            target=router.dispatch,
            args=("PUT", "/api", _payload(prompts[i]))) for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return time.perf_counter() - t0

    single = stubs("s0", sleep=0.06, serial=True)
    t_one = run_fleet([single.url])
    pair = [stubs(f"p{i}", sleep=0.06, serial=True) for i in range(2)]
    t_two = run_fleet([p.url for p in pair])
    assert t_one / t_two >= 1.3, \
        f"no scaling: 1 replica {t_one:.3f}s vs 2 replicas {t_two:.3f}s"


# ---------------------------------------------------------------------------
# request-lifecycle tracing + fleet SLO histograms
# ---------------------------------------------------------------------------

class _RecordingTracer:
    """Duck-typed span recorder standing in for tracing.SpanTracer (the
    router takes anything with completed()/instant())."""

    def __init__(self):
        self.events = []

    def completed(self, name, category, start, dur_secs, **attrs):
        self.events.append(("X", name, attrs))

    def instant(self, name, category="other", **attrs):
        self.events.append(("i", name, attrs))


def test_trace_header_minted_and_propagated(stubs):
    a = stubs("a")
    router = ReplicaRouter([a.url], health_interval_secs=999)
    router.dispatch("PUT", "/api", _payload("1 2"))
    minted = a.trace_headers[0]
    assert minted and len(minted) == 16
    int(minted, 16)                            # hex-parseable
    # a caller-supplied id is forwarded verbatim, never re-minted
    router.dispatch("PUT", "/api", _payload("1 2"), trace_id="cafe" * 4)
    assert a.trace_headers[1] == "cafe" * 4


def test_router_server_echoes_trace_header(router_server):
    url, _, (a, b) = router_server
    explicit = "deadbeef00112233"
    req = urllib.request.Request(
        url + "/api", data=_payload("1 2 3"), method="PUT",
        headers={"X-Request-Trace": explicit})
    with urllib.request.urlopen(req, timeout=30) as resp:
        assert resp.headers["X-Request-Trace"] == explicit
    assert (a.trace_headers + b.trace_headers).count(explicit) == 1
    # no client header: the router mints one and reports it back
    req = urllib.request.Request(url + "/api", data=_payload("9 8 7"),
                                 method="PUT")
    with urllib.request.urlopen(req, timeout=30) as resp:
        minted = resp.headers["X-Request-Trace"]
    assert minted and len(minted) == 16
    assert minted in a.trace_headers + b.trace_headers


def test_trace_id_survives_failover_with_spans(stubs):
    """Acceptance: a request requeued onto another replica after a
    transport failure keeps ONE trace id fleet-wide, and the router's
    spans record both the failover and the eventual route."""
    live = stubs("live")
    dead_url = f"127.0.0.1:{_free_port()}"
    tracer = _RecordingTracer()
    router = ReplicaRouter([dead_url, live.url], fail_threshold=2,
                           cooldown_secs=30.0, health_interval_secs=999,
                           tracer=tracer)
    tid = "feedface01234567"
    status, _, _ = router.dispatch(
        "PUT", "/api",
        _payload(_prompt_on(dead_url, [dead_url, live.url], tail="tr")),
        trace_id=tid)
    assert status == 200
    assert router.failovers_total >= 1
    assert live.trace_headers[-1] == tid       # replay kept its identity
    fo = next(attrs for ph, name, attrs in tracer.events
              if name == "failover")
    assert fo["trace"] == tid
    rr = next(attrs for ph, name, attrs in tracer.events
              if name == "route_request")
    assert rr["trace"] == tid and rr["attempts"] == 2


def test_stream_failover_before_first_byte_keeps_trace_id(stubs):
    live = stubs("live")
    dead_url = f"127.0.0.1:{_free_port()}"
    tracer = _RecordingTracer()
    router = ReplicaRouter([dead_url, live.url], fail_threshold=2,
                           cooldown_secs=30.0, health_interval_secs=999,
                           tracer=tracer)
    tid = "beefbeefbeefbeef"
    status, headers, body_iter = router.dispatch_stream(
        "PUT", "/api/stream",
        _payload(_prompt_on(dead_url, [dead_url, live.url], tail="st")),
        trace_id=tid)
    assert status == 200
    b"".join(body_iter)                        # drain -> span closes
    assert live.trace_headers[-1] == tid
    rs = next(attrs for ph, name, attrs in tracer.events
              if name == "route_stream")
    assert rs["trace"] == tid and rs["attempts"] == 2


def test_health_probe_distinguishes_draining_from_dead(stubs):
    """Resilience satellite: a replica answering /health with
    ``{"status": "draining"}`` is alive (no breaker involvement) but
    receives no new dispatches until it reports ``ok`` again."""
    a, b = stubs("a"), stubs("b")
    router = ReplicaRouter([a.url, b.url], fail_threshold=2,
                           health_interval_secs=999)
    a.draining = True
    assert router.probe_once() == 2            # draining is NOT dead
    ba = router.backends[0]
    assert ba.draining and not router.backends[1].draining
    assert ba.consecutive_failures == 0        # breaker untouched
    assert ba.available(router.fail_threshold)
    # new work all lands on the non-draining replica
    for i in range(3):
        status, _, data = router.dispatch("PUT", "/api",
                                          _payload(f"{i} 1"))
        assert status == 200
        assert json.loads(data)["backend"] == "b"
    assert not a.hits
    snap = router.snapshot()
    assert snap["backends_draining"] == 1
    assert snap["backends"]["backend_0"]["draining"] == 1
    # drain finished (replica restarted, reports ok): back in rotation
    a.draining = False
    router.probe_once()
    assert not router.backends[0].draining


def test_mid_stream_replica_death_yields_sse_error_event(stubs):
    """Resilience satellite: a replica dying AFTER the first streamed
    byte cannot be failed over (a replay could diverge) — the client
    must see a well-formed SSE ``event: error`` frame, and the failure
    must feed the breaker + mid-stream counter."""
    dying = stubs("dying", stream_die=True)
    tracer = _RecordingTracer()
    router = ReplicaRouter([dying.url], fail_threshold=2,
                           health_interval_secs=999, tracer=tracer)
    tid = "0123456789abcdef"
    status, headers, body_iter = router.dispatch_stream(
        "PUT", "/api/stream", _payload("4 5"), trace_id=tid)
    assert status == 200
    body = b"".join(body_iter)                 # never raises to client
    assert body.startswith(b"data: ")          # first byte got out
    assert b"event: error\ndata: " in body
    err = json.loads(body.split(b"event: error\ndata: ")[1]
                     .split(b"\n\n")[0])
    assert err["trace_id"] == tid
    assert err["backend"].endswith(dying.url)   # normalized w/ scheme
    assert "died mid-stream" in err["message"]
    assert router.mid_stream_failures_total == 1
    assert router.snapshot()["mid_stream_failures_total"] == 1
    # the failure attempt is recorded against the backend
    assert router.backends[0].consecutive_failures >= 1
    assert any(name == "mid_stream_failure"
               for _, name, _ in tracer.events)


def test_aggregated_metrics_passes_through_non_numeric(stubs):
    """Bugfix satellite: replica fields that cannot be summed (e.g. one
    replica on the Pallas kernel, one on the XLA fallback) surface as a
    per-replica map instead of being silently dropped."""
    a = stubs("a", metrics_extra={"engine": {"paged_kernel": "pallas"}})
    b = stubs("b", metrics_extra={"engine": {"paged_kernel": "xla"}})
    router = ReplicaRouter([a.url, b.url], health_interval_secs=999)
    m = router.aggregated_metrics()
    assert m["aggregate"]["per_replica"]["engine.paged_kernel"] == \
        {"backend_0": "pallas", "backend_1": "xla"}
    # numeric fleet sums are unaffected
    assert m["aggregate"]["engine"]["tokens_generated"] == 20


def test_fleet_histogram_merge_and_slo_recompute(stubs):
    """Histogram buckets sum across replicas (bucket counts are
    additive); fleet percentiles are recomputed from the merged buckets
    — never summed (a p95 of 0.99s from 0.09 + 0.9 would be nonsense)."""
    h_a = {"buckets": {"0.1": 4, "1": 0, "+Inf": 0},
           "count": 4, "sum": 0.2}
    h_b = {"buckets": {"0.1": 0, "1": 4, "+Inf": 0},
           "count": 4, "sum": 2.0}
    a = stubs("a", metrics_extra={"histograms": {"ttft_secs": h_a},
                                  "slo": {"ttft_secs_p95": 0.09}})
    b = stubs("b", metrics_extra={"histograms": {"ttft_secs": h_b},
                                  "slo": {"ttft_secs_p95": 0.9}})
    router = ReplicaRouter([a.url, b.url], health_interval_secs=999)
    m = router.aggregated_metrics()
    merged = m["aggregate"]["histograms"]["ttft_secs"]
    assert merged["buckets"] == {"0.1": 4, "1": 4, "+Inf": 0}
    assert merged["count"] == 8
    from megatron_llm_tpu.telemetry import histogram_percentile
    p95 = m["aggregate"]["slo"]["ttft_secs_p95"]
    assert p95 == pytest.approx(histogram_percentile(merged, 0.95))
    assert 0.1 < p95 <= 1.0                    # not 0.99 (the naive sum)


# ---------------------------------------------------------------------------
# tools/serve_router.py CLI: breaker/probe flags + SIGTERM teardown
# ---------------------------------------------------------------------------

def test_router_tool_flags_and_aliases():
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools"))
    import serve_router as tool
    a = tool.parse_args(["--backends", "x:1", "--fail_threshold", "5",
                         "--breaker_backoff_secs", "2.5",
                         "--probe_interval_secs", "0.7"])
    assert a.fail_threshold == 5
    assert a.breaker_backoff_secs == 2.5
    assert a.probe_interval_secs == 0.7
    # pre-PR-13 spellings keep working
    legacy = tool.parse_args(["--backends", "x:1",
                              "--cooldown_secs", "1.5",
                              "--health_interval_secs", "0.3"])
    assert legacy.breaker_backoff_secs == 1.5
    assert legacy.probe_interval_secs == 0.3
    # a static empty fleet is a usage error (serve_fleet.py owns the
    # dynamic-membership case)
    with pytest.raises(SystemExit):
        tool.main(["--backends", " ", "--port", "0"])


def test_router_tool_sigterm_clean_exit():
    """SIGTERM stops the probe thread and breaks serve_forever: the
    tool exits 0 instead of dying mid-daemon-thread."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.Popen(
        [sys.executable, os.path.join(root, "tools", "serve_router.py"),
         "--backends", "127.0.0.1:1", "--host", "127.0.0.1",
         "--port", "0", "--probe_interval_secs", "0.2"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=env,
        text=True, cwd=root)
    try:
        deadline = time.monotonic() + 120.0
        up = False
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if " * routing" in line:
                up = True
                break
            if proc.poll() is not None:
                raise RuntimeError("router tool died during startup")
        assert up, "router tool did not start in time"
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=30) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)


# ---------------------------------------------------------------------------
# slow tier: real engine subprocesses
# ---------------------------------------------------------------------------

def _spawn_replica(timeout=180.0):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)      # single-device child, no 8-dev mesh
    proc = subprocess.Popen(
        [sys.executable,
         os.path.join(os.path.dirname(__file__), "_serve_replica.py")],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=env,
        text=True, cwd=os.path.dirname(os.path.dirname(__file__)))
    deadline = time.monotonic() + timeout
    port = None
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if line.startswith("PORT "):
            port = int(line.split()[1])
            break
        if proc.poll() is not None:
            raise RuntimeError("replica died during startup")
    assert port, "replica did not report a port in time"
    return proc, port


def _bench(url, n=48, clients=12, tokens=32):
    results = []
    lock = threading.Lock()
    # long prompt (31 tok) + 32 generated: enough engine work per request
    # that replica capacity, not HTTP overhead, bounds throughput.  Prompts
    # are distinct per request so sticky affinity can't funnel the fleet
    # onto one backend.
    tail = " ".join(["2"] * 29) + " 3"

    def client(i):
        req = urllib.request.Request(
            url + "/api",
            data=json.dumps({"prompts": [f"{i} {tail}"],
                             "tokens_to_generate": tokens,
                             "temperature": 0.0,
                             "no_log": True}).encode(),
            method="PUT")
        try:
            with urllib.request.urlopen(req, timeout=120) as resp:
                r = (resp.status, json.loads(resp.read()))
        except urllib.error.HTTPError as e:
            e.read()
            r = (e.code, None)
        with lock:
            results.append(r)

    t0 = time.perf_counter()
    threads = []
    for i in range(n):
        t = threading.Thread(target=client, args=(i,))
        t.start()
        threads.append(t)
        if len(threads) >= clients:
            threads.pop(0).join()
    for t in threads:
        t.join()
    return time.perf_counter() - t0, results


@pytest.mark.slow
def test_two_replica_fleet_throughput_and_sigkill_failover():
    """Acceptance: ~linear aggregate throughput across 2 real engine
    replicas, and zero dropped in-flight requests when one replica is
    SIGKILLed mid-run."""
    p0, port0 = _spawn_replica()
    p1, port1 = _spawn_replica()
    servers = []
    try:
        def start_router(urls):
            router = ReplicaRouter(urls, fail_threshold=2,
                                   cooldown_secs=5.0,
                                   health_interval_secs=0.5,
                                   request_timeout_secs=120.0)
            srv = RouterServer(router)
            threading.Thread(target=srv.run,
                             kwargs={"host": "127.0.0.1", "port": 0},
                             daemon=True).start()
            for _ in range(100):
                if srv.httpd is not None:
                    break
                time.sleep(0.05)
            servers.append(srv)
            return (router,
                    f"http://127.0.0.1:{srv.httpd.server_address[1]}")

        # warm both replicas through a 2-backend router first
        router2, url2 = start_router(
            [f"127.0.0.1:{port0}", f"127.0.0.1:{port1}"])
        _bench(url2, n=4, clients=2)

        router1, url1 = start_router([f"127.0.0.1:{port0}"])
        t_one, res_one = _bench(url1)
        t_two, res_two = _bench(url2)
        assert all(s == 200 for s, _ in res_one + res_two)
        speedup = t_one / t_two
        assert speedup >= 1.2, \
            f"fleet not scaling: 1 replica {t_one:.2f}s, " \
            f"2 replicas {t_two:.2f}s ({speedup:.2f}x)"

        # SIGKILL one replica while requests are in flight: the router
        # must requeue onto the survivor — zero dropped requests
        killed = {"done": False}

        def killer():
            time.sleep(0.3)
            p1.send_signal(signal.SIGKILL)
            killed["done"] = True

        kt = threading.Thread(target=killer)
        kt.start()
        _, res_kill = _bench(url2, n=32, clients=8)
        kt.join()
        assert killed["done"]
        bad = [s for s, _ in res_kill if s != 200]
        assert not bad, f"dropped requests during failover: {bad}"
        assert router2.failovers_total >= 1
        assert router2.alive_count() == 1
    finally:
        for srv in servers:
            if srv.httpd is not None:
                srv.httpd.shutdown()
        for p in (p0, p1):
            if p.poll() is None:
                p.kill()
            p.wait(timeout=30)
