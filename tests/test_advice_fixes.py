"""Regression tests for the round-1 advisor findings (ADVICE.md r1):

1. tasks finetune built the optimizer *before* the fp16/bf16 cast, so
   half-precision params silently lost fp32 master weights.
2. ORQA answer lists were parsed with ``eval`` (arbitrary code execution
   from a data file).
3. ``data/helpers.py`` rebuilt libhelpers.so in place with no lock —
   a concurrent loader could dlopen a half-written file.
4. WordPiece bos/eos aliased CLS/SEP/eod instead of the reference's
   dedicated [BOS]/[EOS] tokens.
5. LambadaDataset produced ragged rows for passages longer than seq_len.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# 1. finetune optimizer must be constructed from the post-cast param dtype
# ---------------------------------------------------------------------------

def test_finetune_optimizer_sees_post_cast_dtype(monkeypatch):
    import jax
    import jax.numpy as jnp

    import tasks.finetune_utils as fu
    from megatron_llm_tpu.arguments import parse_args, validate_args
    from megatron_llm_tpu.models.bert import bert_config
    from megatron_llm_tpu.models.classification import ClassificationModel
    from megatron_llm_tpu.optimizer import MegatronOptimizer

    captured = {}

    class SpyOptimizer(MegatronOptimizer):
        def __init__(self, tc, params_dtype=jnp.float32, **kw):
            captured["params_dtype"] = params_dtype
            super().__init__(tc, params_dtype=params_dtype, **kw)

    monkeypatch.setattr(fu, "MegatronOptimizer", SpyOptimizer)

    from megatron_llm_tpu import topology
    topology.initialize_model_parallel(1, 1)
    args = parse_args(args_list=[
        "--bf16", "--micro_batch_size=1",
        "--global_batch_size=8", "--lr=1e-4", "--seq_length=8",
        "--max_position_embeddings=8",
    ])
    validate_args(args)
    args.epochs = 0  # task-harness flag (tasks/main.py); none needed here
    cfg = bert_config(num_layers=1, hidden_size=32, num_attention_heads=4,
                      ffn_hidden_size=64, padded_vocab_size=64,
                      seq_length=8, max_position_embeddings=8)
    model = ClassificationModel(cfg, num_classes=2)
    fu.finetune(args, model, train_dataset=[], valid_dataset=None)

    # the regression: optimizer used to be built before the cast with the
    # default fp32 params_dtype, so no fp32 masters were kept for bf16 runs
    assert captured["params_dtype"] == jnp.bfloat16


def test_low_precision_optimizer_keeps_fp32_masters():
    import jax
    import jax.numpy as jnp

    from megatron_llm_tpu.config import TrainConfig
    from megatron_llm_tpu.optimizer import MegatronOptimizer

    tc = TrainConfig(micro_batch_size=1, global_batch_size=1, train_iters=0,
                     lr=1e-4, optimizer="adam", bf16=True)
    opt = MegatronOptimizer(tc, params_dtype=jnp.bfloat16)
    params = {"w": jnp.ones((4, 4), jnp.bfloat16)}
    state = opt.init(params)
    masters = [l for l in jax.tree_util.tree_leaves(state)
               if hasattr(l, "dtype") and l.dtype == jnp.float32]
    assert masters, "bf16 params must produce fp32 optimizer state"


# ---------------------------------------------------------------------------
# 2. ORQA answers: literal_eval only, no code execution
# ---------------------------------------------------------------------------

def test_orqa_load_qa_pairs_no_eval(tmp_path):
    from tasks.orqa.evaluate_orqa import load_qa_pairs

    canary = tmp_path / "pwned"
    p = tmp_path / "qa.tsv"
    with open(p, "w") as f:
        f.write("who?\t['Paris', 'paris']\n")
        # a hostile "answer" that eval would have executed
        f.write(f"evil?\topen({str(canary)!r}, 'w').close()\n")
        f.write("plain?\tjust a plain string\n")
    pairs = load_qa_pairs(str(p))
    assert pairs[0] == ("who?", ["Paris", "paris"])
    assert pairs[1][1] == ["open(" + repr(str(canary)) + ", 'w').close()"]
    assert pairs[2][1] == ["just a plain string"]
    assert not canary.exists(), "data file expression must never execute"


# ---------------------------------------------------------------------------
# 3. libhelpers.so: concurrent builds never expose a half-written file
# ---------------------------------------------------------------------------

def test_helpers_concurrent_build():
    from megatron_llm_tpu.data import helpers

    so = helpers._SO
    if os.path.exists(so):
        os.unlink(so)
    code = ("from megatron_llm_tpu.data import helpers; "
            "import sys; sys.exit(0 if helpers._load() is not None else 1)")
    procs = [subprocess.Popen([sys.executable, "-c", code], cwd=REPO)
             for _ in range(3)]
    rcs = [p.wait(timeout=300) for p in procs]
    assert rcs == [0, 0, 0]
    assert os.path.exists(so)
    leftovers = [f for f in os.listdir(os.path.dirname(so))
                 if ".so.tmp." in f]
    assert leftovers == []


# ---------------------------------------------------------------------------
# 4. WordPiece [BOS]/[EOS] are dedicated tokens, not CLS/SEP aliases
# ---------------------------------------------------------------------------

def _write_vocab(path):
    toks = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]",
            "the", "cat", "sat", "##s", "a", "b", "c"]
    with open(path, "w") as f:
        f.write("\n".join(toks) + "\n")


def test_wordpiece_bos_eos_dedicated(tmp_path):
    from megatron_llm_tpu.tokenizer.tokenizer import _BertWordPieceTokenizer

    vf = tmp_path / "vocab.txt"
    _write_vocab(vf)
    tok = _BertWordPieceTokenizer(str(vf))
    assert tok.bos_token_id is not None and tok.eos_token_id is not None
    # the reference adds [BOS]/[EOS] as their own ids (tokenizer.py:156-200);
    # they must not collide with cls/sep/eod
    assert tok.bos_token_id != tok.cls
    assert tok.eos_token_id != tok.sep
    assert tok.eos_token_id != tok.eod
    assert tok.bos_token_id != tok.eos_token_id
    assert tok.vocab_size > 12  # grew by the added special tokens


# ---------------------------------------------------------------------------
# 5. LAMBADA: over-long passages are left-truncated, never ragged
# ---------------------------------------------------------------------------

class IntTok:
    cls, sep, pad, mask, eod = 1, 2, 0, 3, 2

    def tokenize(self, text):
        return [int(t) % 400 + 5 for t in text.split()]


def test_lambada_long_passage_truncated(tmp_path):
    from tasks.zeroshot_gpt.datasets import LambadaDataset

    seq_len = 16
    long_text = " ".join(str(i) for i in range(50))   # 50 tokens > 17
    short_text = "10 11 12 13 14"
    p = tmp_path / "l.jsonl"
    with open(p, "w") as f:
        f.write(json.dumps({"text": long_text}) + "\n")
        f.write(json.dumps({"text": short_text}) + "\n")
    ds = LambadaDataset(str(p), pad_idx=0, tokenizer=IntTok(),
                        seq_len=seq_len)
    rows = [ds[i] for i in range(len(ds))]
    for s in rows:
        assert s["text"].shape == (seq_len + 1,)
        assert s["pad_mask"].shape == (seq_len,)
        assert s["pad_mask"].sum() == 1
    # the long row keeps the *suffix* of the prefix plus the label token
    toks = IntTok().tokenize(long_text)
    assert rows[0]["text"][-1] == toks[-1]
    assert rows[0]["text"][0] == toks[len(toks) - (seq_len + 1)]
    # batch assembly must not be ragged
    np.stack([s["text"] for s in rows])
