"""tools/serve_report.py + tools/trace_report.py --merge smoke tests on
synthetic fixtures — stdlib-only (no model, no jax), including subprocess
CLI invocations so CI exercises exactly what an operator runs."""

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT / "tools"))
import serve_report  # noqa: E402


def _record(i, cached=0, ttft=0.2, e2e=1.0, tpot=0.02,
            finish="length", trace=True, drafted=0, accepted=0):
    return {
        "schema": 8, "kind": "serve", "event": "request_done",
        "time_unix": 1700000000 + i, "request": f"req-{i}",
        "trace_id": f"{i:016x}" if trace else None,
        "prompt_tokens": 16, "cached_prompt_tokens": cached,
        "prefill_computed_tokens": 16 - cached, "new_tokens": 8,
        "decode_tokens": 8, "finish_reason": finish,
        "drafted_tokens": drafted, "accepted_tokens": accepted,
        "accept_rate": (round(accepted / drafted, 4) if drafted
                        else None),
        "ttft_secs": ttft, "latency_secs": e2e, "tpot_secs": tpot,
        "phases": {"queue_secs": 0.05, "admission_secs": 0.001,
                   "prefill_secs": 0.1, "decode_secs": tpot * 8,
                   "stream_write_secs": 0.002},
        "paged_kernel": "xla", "prefill_kernel": "xla",
        "queue_depth": 0, "blocks_free": 10,
        "blocks_in_use": 2, "blocks_cached_reusable": 1,
    }


def _write_log(dirpath, records):
    os.makedirs(dirpath, exist_ok=True)
    with open(os.path.join(dirpath, "telemetry.jsonl"), "w") as f:
        f.write("not json\n")                    # parser must skip junk
        f.write(json.dumps({"kind": "log", "iteration": 1}) + "\n")
        for r in records:
            f.write(json.dumps(r) + "\n")
    return dirpath


@pytest.fixture
def serve_log(tmp_path):
    recs = [_record(i, cached=8 if i < 4 else 0,
                    ttft=0.1 + 0.1 * i, e2e=0.5 + 0.25 * i,
                    tpot=0.01 + 0.01 * i) for i in range(8)]
    recs.append(_record(8, finish="deadline", trace=False))
    return _write_log(str(tmp_path / "replica0"), recs)


def test_analyze_summary_phases_and_cache_split(serve_log):
    r = serve_report.analyze([serve_log], ttft_slo=0.45, tpot_slo=0.045)
    assert r["summary"]["requests"] == 9
    assert r["traced"] == 8
    assert r["finish_reasons"] == {"length": 8, "deadline": 1}
    # percentiles over raw values (nearest-rank, same as serve_bench)
    e2e = sorted(0.5 + 0.25 * i for i in range(8)) + [1.0]
    assert r["summary"]["e2e_p50_secs"] == serve_report._percentile(e2e, .5)
    # phase shares computed against mean e2e
    assert r["phases"]["prefill_secs"]["mean_secs"] == pytest.approx(0.1)
    assert 0 < r["phases"]["prefill_secs"]["share"] < 1
    assert r["phases"]["unattributed_secs"] >= 0
    # cache strata: i<4 carried cached pages
    assert r["by_cache"]["cache_hit"]["requests"] == 4
    assert r["by_cache"]["cache_miss"]["requests"] == 5
    assert r["by_cache"]["cache_hit"]["e2e_mean_secs"] < \
        r["by_cache"]["cache_miss"]["e2e_mean_secs"]
    # SLO attainment: ttft <= 0.45 -> i in 0..3 (0.1..0.4) plus the
    # deadline record (0.2) = 5 of 9; tpot <= 0.045 -> i in 0..3 + 0.02
    assert r["slo"]["ttft_attained"] == pytest.approx(5 / 9)
    assert r["slo"]["joint_attained"] == pytest.approx(5 / 9)
    # prefill throughput: computed tokens over prefill compute seconds,
    # attributed to the serving attention path
    assert r["prefill"]["computed_tokens"] == 4 * 8 + 5 * 16
    assert r["prefill"]["compute_secs"] == pytest.approx(0.9)
    assert r["prefill"]["tokens_per_sec"] == pytest.approx(112 / 0.9)
    assert r["prefill"]["kernel"] == {"xla": 9}


def test_analyze_multi_log_per_replica(tmp_path):
    a = _write_log(str(tmp_path / "ra"),
                   [_record(i, e2e=0.5) for i in range(3)])
    b = _write_log(str(tmp_path / "rb"),
                   [_record(i, e2e=2.0) for i in range(3)])
    r = serve_report.analyze([a, b])
    assert r["summary"]["requests"] == 6
    assert set(r["replicas"]) == {a, b}
    assert r["replicas"][a]["e2e_mean_secs"] == pytest.approx(0.5)
    assert r["replicas"][b]["e2e_mean_secs"] == pytest.approx(2.0)


def test_speculative_summary_and_tpot_split(tmp_path):
    """Schema-8 speculative attribution: fleet accept rate is total
    accepted / total drafted, and the TPOT means are split by whether
    the request drafted at all."""
    recs = [_record(i, drafted=8, accepted=6, tpot=0.01)
            for i in range(3)]
    recs += [_record(10 + i, drafted=0, accepted=0, tpot=0.03)
             for i in range(2)]
    log = _write_log(str(tmp_path / "spec"), recs)
    r = serve_report.analyze([log])
    sp = r["speculative"]
    assert sp["drafted_tokens"] == 24
    assert sp["accepted_tokens"] == 18
    assert sp["accept_rate"] == pytest.approx(0.75)
    assert sp["requests_drafting"] == 3
    assert sp["tpot_mean_secs_drafting"] == pytest.approx(0.01)
    assert sp["tpot_mean_secs_plain"] == pytest.approx(0.03)
    # rendered + --json forms both carry the section
    out = serve_report.render(r)
    assert "speculative decoding: accepted 18/24" in out
    assert "75.0% accept rate" in out
    cli = subprocess.run(
        [sys.executable, str(ROOT / "tools" / "serve_report.py"),
         log, "--json"],
        capture_output=True, text=True, cwd=str(ROOT))
    assert cli.returncode == 0, cli.stderr
    assert json.loads(cli.stdout)["speculative"]["accept_rate"] == \
        pytest.approx(0.75)
    # a fleet that never drafted renders no speculative section and a
    # null accept rate (never a divide-by-zero)
    plain = _write_log(str(tmp_path / "plain"),
                       [_record(i) for i in range(2)])
    r2 = serve_report.analyze([plain])
    assert r2["speculative"]["accept_rate"] is None
    assert "speculative decoding" not in serve_report.render(r2)


def test_slo_counts_unmeasured_dimension_as_met(tmp_path):
    rec = _record(0)
    rec["tpot_secs"] = None                      # 1-token answer
    log = _write_log(str(tmp_path / "r"), [rec])
    r = serve_report.analyze([log], ttft_slo=10.0, tpot_slo=1e-9)
    assert r["slo"]["tpot_attained"] == 1.0


def test_cli_table_json_and_empty_exit_codes(serve_log, tmp_path):
    env = dict(os.environ)
    out = subprocess.run(
        [sys.executable, str(ROOT / "tools" / "serve_report.py"),
         serve_log, "--ttft_slo", "0.45"],
        capture_output=True, text=True, env=env, cwd=str(ROOT))
    assert out.returncode == 0, out.stderr
    assert "phase breakdown" in out.stdout
    assert "SLO attainment" in out.stdout
    assert "cache_hit" in out.stdout
    assert "prefill compute:" in out.stdout

    out = subprocess.run(
        [sys.executable, str(ROOT / "tools" / "serve_report.py"),
         serve_log, "--json"],
        capture_output=True, text=True, env=env, cwd=str(ROOT))
    assert out.returncode == 0, out.stderr
    assert json.loads(out.stdout)["summary"]["requests"] == 9

    empty = _write_log(str(tmp_path / "empty"), [])
    out = subprocess.run(
        [sys.executable, str(ROOT / "tools" / "serve_report.py"), empty],
        capture_output=True, text=True, env=env, cwd=str(ROOT))
    assert out.returncode == 2
    out = subprocess.run(
        [sys.executable, str(ROOT / "tools" / "serve_report.py"),
         str(tmp_path / "missing")],
        capture_output=True, text=True, env=env, cwd=str(ROOT))
    assert out.returncode == 2


# ---------------------------------------------------------------------------
# cache observatory section (cache_stats rollups, telemetry schema 11)
# ---------------------------------------------------------------------------

def _cache_stats(probes=100, hits=60, miss_cold=30, miss_evicted=10,
                 hit_tokens=240, heat=None, x2_hits=80, x2_tokens=320,
                 host_hits=0, host_hit_tokens=0, host=None):
    if host is not None:
        return {**_cache_stats(probes, hits, miss_cold, miss_evicted,
                               hit_tokens, heat, x2_hits, x2_tokens),
                "schema": 12, "host_hits": host_hits,
                "host_hit_tokens": host_hit_tokens,
                "swap_in_blocks": host.get("swap_in_blocks", 0),
                "host": host}
    return {
        "schema": 11, "kind": "serve", "event": "cache_stats",
        "time_unix": 1700000050.0,
        "match_calls": 40, "probes": probes, "hits": hits,
        "misses": probes - hits, "hit_tokens": hit_tokens,
        "hit_rate": round(hits / probes, 4),
        "miss_cold": miss_cold, "miss_evicted": miss_evicted,
        "evictions_capacity": 3, "evictions_churn": 7,
        "pool_resets": 0, "inclusion_divergences": 0,
        "heat_entries": len(heat or ()), "heat_evicted": 0,
        "heat_top": heat or [],
        "ghost": {
            "x2": {"capacity_blocks": 24, "hits": x2_hits,
                   "misses": probes - x2_hits, "hit_tokens": x2_tokens,
                   "evictions": 1, "entries": 5,
                   "hit_rate": round(x2_hits / probes, 4)},
            "x10": {"capacity_blocks": 120, "hits": 95,
                    "misses": probes - 95, "hit_tokens": 380,
                    "evictions": 0, "entries": 9, "hit_rate": 0.95},
        },
    }


def _heat_entry(prefix, hits, regret=0):
    return {"prefix": prefix, "hits": hits, "hit_tokens": hits * 4,
            "residency": hits, "peak_refcount": 2, "evictions": 1,
            "regret": regret, "last_access_age": 3}


def test_analyze_cache_observatory_section(tmp_path):
    """Schema-11 cache_stats rollups: final-record totals, merged heat
    top-K, the miss-cause split, and the ghost capacity projection
    priced at the log's measured prefill throughput."""
    recs = [_record(i) for i in range(4)]
    log = _write_log(str(tmp_path / "r"), recs)
    with open(os.path.join(log, "telemetry.jsonl"), "a") as f:
        # two rollups: cumulative, so only the final one counts
        f.write(json.dumps(_cache_stats(probes=50, hits=20)) + "\n")
        f.write(json.dumps(_cache_stats(
            heat=[_heat_entry("aaaa", 12, regret=2),
                  _heat_entry("bbbb", 3)])) + "\n")
    r = serve_report.analyze([log])
    cache = r["cache"]
    assert cache["probes"] == 100 and cache["hits"] == 60
    assert cache["hit_rate"] == pytest.approx(0.6)
    assert cache["miss_cold"] == 30 and cache["miss_evicted"] == 10
    assert cache["evictions_capacity"] == 3
    assert cache["evictions_churn"] == 7
    assert [e["prefix"] for e in cache["heat_top"]] == ["aaaa", "bbbb"]
    # ghost projection: x2 gains 320-240=80 tokens, priced at the
    # prefill throughput measured from the request_done records
    tps = r["prefill"]["tokens_per_sec"]
    x2 = cache["ghost"]["x2"]
    assert x2["hit_rate"] == pytest.approx(0.8)
    assert x2["extra_hit_tokens"] == 80
    assert x2["prefill_saved_secs_total"] == pytest.approx(80 / tps)
    assert x2["ttft_saved_secs_per_request"] == pytest.approx(
        80 / tps / 4)
    # tiers come out ordered by capacity
    assert list(cache["ghost"]) == ["x2", "x10"]


def test_analyze_host_tier_section(tmp_path):
    """Schema-12 hierarchical-cache rollups: host-tier hit attribution
    out of the two-tier rate, spill/swap-in volume from the ``host``
    sub-block, and the TTFT-saved projection priced NET of the
    measured swap-in seconds."""
    recs = [_record(i) for i in range(4)]
    # two requests swapped 2 blocks each out of host RAM
    for r in recs[:2]:
        r["host_hit_blocks"] = 2
        r["swap_in_secs"] = 0.003
    log = _write_log(str(tmp_path / "r"), recs)
    with open(os.path.join(log, "telemetry.jsonl"), "a") as f:
        f.write(json.dumps(_cache_stats(
            host_hits=10, host_hit_tokens=80,
            host={"enabled": 1, "capacity_blocks": 256, "entries": 40,
                  "spills_queued": 30, "spills_completed": 25,
                  "spills_dropped": 5, "evictions": 2, "swap_ins": 4,
                  "swap_in_blocks": 10, "swap_in_secs": 0.02,
                  "pool_resets": 0})) + "\n")
    r = serve_report.analyze([log])
    # request-level aggregation rides the prefill summary
    assert r["prefill"]["host_hit_blocks"] == 4
    assert r["prefill"]["swap_in_secs"] == pytest.approx(0.006)
    assert r["prefill"]["requests_swapping"] == 2
    host = r["cache"]["host_tier"]
    assert host["hits"] == 10 and host["hit_tokens"] == 80
    assert host["hit_rate"] == pytest.approx(0.10)      # 10/100 probes
    assert host["hbm_hit_rate"] == pytest.approx(0.50)  # (60-10)/100
    assert host["spills_completed"] == 25
    assert host["spills_dropped"] == 5
    assert host["swap_ins"] == 4
    assert host["swap_in_secs"] == pytest.approx(0.02)
    # pricing: 80 host-hit tokens at measured prefill throughput,
    # minus the 0.02s the swap-in scatters actually cost
    tps = r["prefill"]["tokens_per_sec"]
    assert host["prefill_saved_secs_total"] == pytest.approx(80 / tps)
    assert host["net_saved_secs_total"] == pytest.approx(80 / tps - 0.02)
    assert host["ttft_saved_secs_per_request"] == pytest.approx(
        (80 / tps - 0.02) / 4)
    # a schema-11 log (no host sub-block) reports no host tier
    old = _write_log(str(tmp_path / "old"), [_record(0)])
    with open(os.path.join(old, "telemetry.jsonl"), "a") as f:
        f.write(json.dumps(_cache_stats()) + "\n")
    assert serve_report.analyze([old])["cache"]["host_tier"] is None


def test_cli_renders_host_tier(tmp_path):
    log = _write_log(str(tmp_path / "r"), [_record(i) for i in range(4)])
    with open(os.path.join(log, "telemetry.jsonl"), "a") as f:
        f.write(json.dumps(_cache_stats(
            host_hits=10, host_hit_tokens=80,
            host={"enabled": 1, "spills_completed": 25,
                  "spills_dropped": 5, "evictions": 2, "swap_ins": 4,
                  "swap_in_blocks": 10, "swap_in_secs": 0.02})) + "\n")
    out = subprocess.run(
        [sys.executable, str(ROOT / "tools" / "serve_report.py"), log],
        capture_output=True, text=True, cwd=str(ROOT))
    assert out.returncode == 0, out.stderr
    assert "host spill tier" in out.stdout
    assert "two-tier hit rate" in out.stdout
    assert "ghost projection" in out.stdout
    assert "net of measured swap-in time" in out.stdout


def test_analyze_cache_merges_replicas_and_heat(tmp_path):
    a = _write_log(str(tmp_path / "ra"), [_record(0)])
    b = _write_log(str(tmp_path / "rb"), [_record(1)])
    for log, heat in ((a, [_heat_entry("aaaa", 10, regret=1)]),
                      (b, [_heat_entry("aaaa", 5),
                           _heat_entry("cccc", 2)])):
        with open(os.path.join(log, "telemetry.jsonl"), "a") as f:
            f.write(json.dumps(_cache_stats(heat=heat)) + "\n")
    cache = serve_report.analyze([a, b])["cache"]
    assert cache["probes"] == 200                # summed across replicas
    top = {e["prefix"]: e for e in cache["heat_top"]}
    assert top["aaaa"]["hits"] == 15             # same-salt keys merge
    assert top["aaaa"]["regret"] == 1
    assert top["cccc"]["hits"] == 2


def test_cli_renders_cache_observatory(tmp_path):
    log = _write_log(str(tmp_path / "r"), [_record(i) for i in range(4)])
    with open(os.path.join(log, "telemetry.jsonl"), "a") as f:
        f.write(json.dumps(_cache_stats(
            heat=[_heat_entry("aaaa", 12, regret=2)])) + "\n")
    out = subprocess.run(
        [sys.executable, str(ROOT / "tools" / "serve_report.py"), log],
        capture_output=True, text=True, cwd=str(ROOT))
    assert out.returncode == 0, out.stderr
    assert "cache observatory" in out.stdout
    assert "miss causes" in out.stdout
    assert "evicted-then-wanted" in out.stdout
    assert "capacity projection" in out.stdout
    assert "x2" in out.stdout and "x10" in out.stdout
    assert "aaaa" in out.stdout
    # a pre-schema-11 log renders without the section
    plain = _write_log(str(tmp_path / "old"), [_record(0)])
    out = subprocess.run(
        [sys.executable, str(ROOT / "tools" / "serve_report.py"), plain],
        capture_output=True, text=True, cwd=str(ROOT))
    assert out.returncode == 0, out.stderr
    assert "cache observatory" not in out.stdout


# ---------------------------------------------------------------------------
# fleet-event timeline (kind "fleet", supervisor / serve_fleet.py)
# ---------------------------------------------------------------------------

def _fleet_event(event, t, **fields):
    return {"schema": 7, "kind": "fleet", "event": event,
            "time_unix": 1700000100.0 + t, **fields}


def _fleet_fixture_events():
    return [
        _fleet_event("replica_spawned", 0.0, slot="replica-0",
                     url="http://127.0.0.1:5000", spawn_secs=2.5),
        _fleet_event("scale_up", 10.0, slot="replica-1",
                     reason="ttft_p95", ttft_p95_secs=2.1,
                     queue_depth=30),
        _fleet_event("brownout", 10.5, slot="replica-1", eta_secs=12.0),
        _fleet_event("replica_spawned", 21.0, slot="replica-1",
                     url="http://127.0.0.1:5001", spawn_secs=11.0),
        _fleet_event("replica_died", 30.0, slot="replica-0",
                     url="http://127.0.0.1:5000", exited_while="ready"),
        _fleet_event("replica_respawned", 34.0, slot="replica-0",
                     url="http://127.0.0.1:5002", spawn_secs=4.0),
        _fleet_event("scale_down", 80.0, slot="replica-1",
                     url="http://127.0.0.1:5001"),
        _fleet_event("router_spawned", 90.0, slot="router-0",
                     url="http://127.0.0.1:6000", spawn_secs=1.5),
        _fleet_event("router_died", 95.0, slot="router-0",
                     url="http://127.0.0.1:6000", exited_while="ready"),
        _fleet_event("router_respawned", 99.0, slot="router-0",
                     url="http://127.0.0.1:6001", spawn_secs=1.0),
    ]


def test_fleet_summary_counters_and_timeline(tmp_path):
    log = tmp_path / "fleet.jsonl"
    with open(log, "w") as f:
        f.write("not json\n")
        # out of order on disk: the timeline must sort by time_unix
        for e in reversed(_fleet_fixture_events()):
            f.write(json.dumps(e) + "\n")
    assert len(serve_report.load_fleet_events(str(log))) == 10
    r = serve_report.analyze([str(log)])
    fs = r["fleet"]
    assert fs["events"] == {
        "replica_spawned": 2, "replica_died": 1,
        "replica_respawned": 1, "scale_up": 1, "scale_down": 1,
        "brownout": 1, "router_spawned": 1, "router_died": 1,
        "router_respawned": 1, "router_scale_up": 0,
        "router_scale_down": 0}
    tl = fs["timeline"]
    assert [e["event"] for e in tl] == [
        "replica_spawned", "scale_up", "brownout", "replica_spawned",
        "replica_died", "replica_respawned", "scale_down",
        "router_spawned", "router_died", "router_respawned"]
    # offsets relative to the first fleet event
    assert tl[0]["t_secs"] == pytest.approx(0.0)
    assert tl[1]["t_secs"] == pytest.approx(10.0)
    assert tl[-1]["t_secs"] == pytest.approx(99.0)
    # per-event detail fields survive when present
    assert tl[1]["reason"] == "ttft_p95"
    assert tl[2]["eta_secs"] == 12.0
    assert tl[4]["exited_while"] == "ready"


def test_fleet_events_coexist_with_request_records(tmp_path):
    log_dir = tmp_path / "replica0"
    _write_log(str(log_dir), [_record(i) for i in range(3)])
    with open(log_dir / serve_report.STREAM_FILENAME, "a") as f:
        for e in _fleet_fixture_events():
            f.write(json.dumps(e) + "\n")
    r = serve_report.analyze([str(log_dir)])
    assert r["summary"]["requests"] == 3
    assert r["fleet"]["events"]["scale_up"] == 1


def test_cli_fleet_only_log_renders_timeline(tmp_path):
    """A --fleet_event_log JSONL with zero request_done records is a
    valid input: exit 0, counters plus the chronological timeline."""
    log = tmp_path / "fleet.jsonl"
    with open(log, "w") as f:
        for e in _fleet_fixture_events():
            f.write(json.dumps(e) + "\n")
    out = subprocess.run(
        [sys.executable, str(ROOT / "tools" / "serve_report.py"),
         str(log)],
        capture_output=True, text=True, cwd=str(ROOT))
    assert out.returncode == 0, out.stderr
    assert "fleet events:" in out.stdout
    assert "scale_up=1" in out.stdout
    assert "router_respawned=1" in out.stdout
    # zero-count event names stay out of the rendered counters
    assert "router_scale_up" not in out.stdout
    assert "reason=ttft_p95" in out.stdout
    assert "exited_while=ready" in out.stdout


# ---------------------------------------------------------------------------
# trace_report.py --merge on synthetic router + replica traces
# ---------------------------------------------------------------------------

TID = "cafe0123cafe0123"


def _router_trace():
    return {
        "traceEvents": [
            {"ph": "M", "name": "process_name", "pid": 0, "tid": 0,
             "args": {"name": "host0"}},
            {"ph": "X", "name": "route_request", "cat": "serve",
             "ts": 0.0, "dur": 500_000.0, "pid": 0, "tid": 0,
             "args": {"trace": TID, "backend": "127.0.0.1:5000",
                      "attempts": 1, "status": 200}},
        ],
        "displayTimeUnit": "ms",
        "otherData": {"trace_start_unix": 1000.0},
    }


def _replica_trace():
    return {
        "traceEvents": [
            {"ph": "M", "name": "process_name", "pid": 0, "tid": 0,
             "args": {"name": "host0"}},
            {"ph": "X", "name": "queue_wait", "cat": "serve",
             "ts": 0.0, "dur": 30_000.0, "pid": 0, "tid": 0,
             "args": {"trace": TID, "request": "r1"}},
            {"ph": "X", "name": "prefill_chunk", "cat": "serve",
             "ts": 40_000.0, "dur": 120_000.0, "pid": 0, "tid": 0,
             "args": {"trace": TID, "request": "r1", "tokens": 16}},
            {"ph": "X", "name": "decode_step", "cat": "serve",
             "ts": 200_000.0, "dur": 50_000.0, "pid": 0, "tid": 0,
             "args": {"traces": [TID, "ffff000011112222"]}},
        ],
        "displayTimeUnit": "ms",
        # the replica's clock started 0.1s after the router's
        "otherData": {"trace_start_unix": 1000.1},
    }


@pytest.fixture
def trace_files(tmp_path):
    router = tmp_path / "router_trace.json"
    replica = tmp_path / "replica_trace.json"
    router.write_text(json.dumps(_router_trace()))
    replica.write_text(json.dumps(_replica_trace()))
    return str(router), str(replica)


def test_merge_cli_stitches_one_timeline(trace_files, tmp_path):
    """Acceptance: one trace id threads router -> replica, and --merge
    renders both processes' spans on a single timeline."""
    router, replica = trace_files
    out_path = str(tmp_path / "merged.json")
    out = subprocess.run(
        [sys.executable, str(ROOT / "tools" / "trace_report.py"),
         router, replica, "--merge", "--out", out_path,
         "--trace", TID],
        capture_output=True, text=True, cwd=str(ROOT))
    assert out.returncode == 0, out.stderr
    assert "merged 2 traces" in out.stdout
    assert TID in out.stdout                     # the request timeline

    merged = json.loads(Path(out_path).read_text())
    evs = merged["traceEvents"]
    spans = [e for e in evs if e["ph"] != "M"]
    # both source processes present, distinct pids
    assert {e["pid"] for e in spans} == {0, 1}
    names = {e["name"]: e for e in spans}
    # clock alignment: the replica file's 0.1s unix skew became a
    # +100_000us shift, so queue_wait starts inside route_request
    assert names["queue_wait"]["ts"] == pytest.approx(100_000.0)
    assert names["route_request"]["ts"] == pytest.approx(0.0)
    # per-file process_name metadata labels both sides
    labels = [e["args"]["name"] for e in evs
              if e["ph"] == "M" and e["name"] == "process_name"]
    assert any("router_trace" in l for l in labels)
    assert any("replica_trace" in l for l in labels)
    # the one trace id appears on spans from BOTH processes
    tagged_pids = {e["pid"] for e in spans
                   if e.get("args", {}).get("trace") == TID
                   or TID in (e.get("args", {}).get("traces") or ())}
    assert tagged_pids == {0, 1}


def test_merge_requires_flag_for_multiple_inputs(trace_files):
    router, replica = trace_files
    out = subprocess.run(
        [sys.executable, str(ROOT / "tools" / "trace_report.py"),
         router, replica],
        capture_output=True, text=True, cwd=str(ROOT))
    assert out.returncode == 2
    assert "--merge" in out.stderr


def test_merge_json_timeline_output(trace_files):
    router, replica = trace_files
    out = subprocess.run(
        [sys.executable, str(ROOT / "tools" / "trace_report.py"),
         router, replica, "--merge", "--trace", TID, "--json"],
        capture_output=True, text=True, cwd=str(ROOT))
    assert out.returncode == 0, out.stderr
    rows = json.loads(out.stdout)
    assert [r["name"] for r in rows] == \
        ["route_request", "queue_wait", "prefill_chunk", "decode_step"]
    assert rows[0]["at_secs"] == pytest.approx(0.0)
    assert rows[1]["at_secs"] == pytest.approx(0.1)
