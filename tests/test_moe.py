"""Mixture-of-experts: routing semantics, dense parity, sharding, and the
model/trainer integration (TPU-native extension — the reference has no MoE,
SURVEY §2.2 "expert parallel: absent")."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megatron_llm_tpu.config import TransformerConfig
from megatron_llm_tpu.models.moe import (
    init_moe_mlp_params,
    moe_capacity,
    moe_mlp,
    moe_mlp_specs,
)
from megatron_llm_tpu.models.transformer import mlp as dense_mlp


def _cfg(**kw):
    base = dict(
        num_layers=2, hidden_size=32, num_attention_heads=4,
        ffn_hidden_size=64, num_experts=4, moe_top_k=2,
        glu_activation="swiglu", add_bias_linear=False,
        # ample capacity: every token always fits its expert buffer
        moe_capacity_factor=8.0,
    )
    base.update(kw)
    return TransformerConfig(**base)


def test_identical_experts_match_dense_mlp():
    """With every expert holding the same weights and top-1 routing (gate
    renormalizes to 1.0), the MoE layer must equal the dense MLP."""
    cfg = _cfg(moe_top_k=1)
    p = init_moe_mlp_params(jax.random.PRNGKey(0), cfg, jnp.float32)
    # copy expert 0 into all experts
    p["experts"]["w_in"] = jnp.broadcast_to(
        p["experts"]["w_in"][:1], p["experts"]["w_in"].shape)
    p["experts"]["w_out"] = jnp.broadcast_to(
        p["experts"]["w_out"][:1], p["experts"]["w_out"].shape)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 32))

    dense_p = {
        "dense_h_to_4h": {"kernel": p["experts"]["w_in"][0]},
        "dense_4h_to_h": {"kernel": p["experts"]["w_out"][0]},
    }
    want = dense_mlp(x, dense_p, cfg)
    got, aux = moe_mlp(x, p, cfg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)
    assert aux.shape == (2,) and np.isfinite(np.asarray(aux)).all()


def test_uniform_router_aux_loss_is_one():
    """Zero router weights -> uniform probs; Switch load balance
    E * sum_e(frac_e * 1/E) == sum_e frac_e == 1 exactly."""
    cfg = _cfg()
    p = init_moe_mlp_params(jax.random.PRNGKey(0), cfg, jnp.float32)
    p["router"]["kernel"] = jnp.zeros_like(p["router"]["kernel"])
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 32))
    _, aux = moe_mlp(x, p, cfg)
    np.testing.assert_allclose(float(aux[0]), 1.0, atol=1e-5)


def test_capacity_dropping_zeroes_overflow_tokens():
    """A capacity of 1 with a router forced to a single expert keeps only
    the first token per batch row; every later token's MLP output is 0."""
    cfg = _cfg(moe_top_k=1, moe_capacity_factor=1e-9, moe_min_capacity=1)
    p = init_moe_mlp_params(jax.random.PRNGKey(0), cfg, jnp.float32)
    # bias router hard toward expert 2 via a huge weight column
    wr = np.zeros(p["router"]["kernel"].shape, np.float32)
    wr[0, 2] = 1e6          # logits ~ x[..., 0] * 1e6 -> same sign everywhere
    p["router"]["kernel"] = jnp.asarray(wr)
    x = jnp.abs(jax.random.normal(jax.random.PRNGKey(1), (2, 8, 32))) + 0.1
    assert moe_capacity(cfg, 8) == 1
    out, _ = moe_mlp(x, p, cfg)
    out = np.asarray(out)
    # token 0 got the buffer slot; tokens 1.. were dropped (zero output)
    assert np.abs(out[:, 0]).max() > 0
    np.testing.assert_allclose(out[:, 1:], 0.0, atol=1e-6)


def test_grads_reach_router_and_all_experts():
    cfg = _cfg()
    p = init_moe_mlp_params(jax.random.PRNGKey(0), cfg, jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32))

    def loss(p):
        out, aux = moe_mlp(x, p, cfg)
        return jnp.sum(out * out) + aux[0]

    g = jax.grad(loss)(p)
    assert float(jnp.linalg.norm(g["router"]["kernel"])) > 0
    per_expert = jnp.linalg.norm(
        g["experts"]["w_in"].reshape(cfg.num_experts, -1), axis=-1)
    assert (np.asarray(per_expert) > 0).all(), per_expert


def test_sharded_matches_unsharded(utils):
    """dp-sharded experts + batch-sharded tokens produce the same numbers
    as the single-device run (GSPMD all-to-all dispatch is semantics-free)."""
    from megatron_llm_tpu import topology
    from megatron_llm_tpu.parallel import sharding as sh

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    cfg = _cfg(num_experts=8)
    p = init_moe_mlp_params(jax.random.PRNGKey(0), cfg, jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 16, 32))
    want, aux_want = moe_mlp(x, p, cfg)         # no mesh constraints active

    topology.initialize_model_parallel()        # dp=8 mesh
    try:
        specs = moe_mlp_specs(p, stacked=False)
        p_sh = sh.shard_params(p, specs)
        # expert dim (8) really lands on the dp axis
        w_in_shard = p_sh["experts"]["w_in"].sharding.spec
        assert w_in_shard[0] == "dp", w_in_shard
        x_sh = jax.device_put(
            x, sh.make_shardings(("batch", None, None)))
        got, aux_got = jax.jit(lambda x, p: moe_mlp(x, p, cfg))(x_sh, p_sh)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-5, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(aux_got), np.asarray(aux_want),
                                   atol=1e-6)
    finally:
        topology.destroy_model_parallel()


def test_gpt_model_moe_train_and_decode(utils):
    """GPTModel integration: (loss, aux) contract, flops accounting, and
    the kv-cache decode path (aux dropped)."""
    from megatron_llm_tpu.models.gpt import GPTModel

    cfg = _cfg(
        seq_length=32, max_position_embeddings=32, padded_vocab_size=64,
        tie_embed_logits=True, hidden_dropout=0.0, attention_dropout=0.0,
        use_flash_attn=False,
    )
    dense_cfg = dataclasses.replace(cfg, num_experts=0)
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    toks = jnp.asarray(np.random.RandomState(0).randint(0, 64, (2, 32)))
    labels = jnp.roll(toks, -1, -1)

    loss_tok, aux = model(params, toks, labels=labels, train=True)
    assert loss_tok.shape == (2, 32)
    assert aux.shape == (2,)
    # aux accumulates one lb term per layer, each ~1 near-uniform at init
    assert 0.5 * cfg.num_layers < float(aux[0]) < 2.0 * cfg.num_layers

    assert model.flops_per_token() > GPTModel(dense_cfg).flops_per_token()

    # generation contract: logits without labels, aux dropped
    logits = model(params, toks)
    assert logits.shape == (2, 32, 64)

    g = jax.grad(
        lambda p: jnp.mean(model(p, toks, labels=labels)[0])
        + 1e-2 * model(p, toks, labels=labels)[1][0]
    )(params)
    layers = g["transformer"]["layers"]
    assert float(jnp.linalg.norm(layers["mlp"]["router"]["kernel"])) > 0
    assert float(jnp.linalg.norm(layers["mlp"]["experts"]["w_in"])) > 0


def test_non_gpt_families_reject_moe():
    from megatron_llm_tpu.models.bert import BertModel
    from megatron_llm_tpu.models.t5 import T5Model

    cfg = _cfg(num_tokentypes=2)
    with pytest.raises(NotImplementedError, match="GPT family"):
        BertModel(cfg)
    with pytest.raises(NotImplementedError, match="GPT family"):
        T5Model(cfg)


def test_moe_kv_cache_decode_matches_full_forward(utils):
    """Incremental MoE decode (capacity floor covers s=1 routing) must
    reproduce the one-shot causal forward logits."""
    from megatron_llm_tpu.models.mixtral import MixtralModel, mixtral_config
    from megatron_llm_tpu.text_generation.generation import (
        _forward_with_cache,
        init_kv_caches,
    )

    cfg = mixtral_config(
        "tiny", num_layers=2, seq_length=64, max_position_embeddings=64,
        padded_vocab_size=64, use_flash_attn=False,
        moe_capacity_factor=8.0,
    )
    model = MixtralModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    toks = jnp.asarray(rng.randint(0, 64, (2, 10)))

    full_logits = model(params, toks, train=False)

    caches = init_kv_caches(model.cfg, 2, 16)
    logits_p, caches = _forward_with_cache(model, params, toks[:, :4],
                                           caches, 0)
    parts = [logits_p]
    for t in range(4, 10):
        lg, caches = _forward_with_cache(model, params, toks[:, t:t + 1],
                                         caches, t)
        parts.append(lg)
    inc_logits = jnp.concatenate(parts, axis=1)
    np.testing.assert_allclose(np.asarray(inc_logits),
                               np.asarray(full_logits), atol=2e-4)


def test_zero1_shards_moe_expert_state(utils):
    """ZeRO-1 state sharding must dp-shard the (large) expert optimizer
    moments, not silently replicate them."""
    from megatron_llm_tpu import topology
    from megatron_llm_tpu.config import TrainConfig
    from megatron_llm_tpu.models.mixtral import MixtralModel, mixtral_config
    from megatron_llm_tpu.optimizer import MegatronOptimizer
    from megatron_llm_tpu.parallel import sharding as sh

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    cfg = mixtral_config(
        "tiny", num_layers=2, seq_length=32, max_position_embeddings=32,
        padded_vocab_size=256, num_experts=8, hidden_size=64,
        ffn_hidden_size=176, num_attention_heads=4,
        num_attention_heads_kv=2, use_flash_attn=False,
    )
    model = MixtralModel(cfg)
    topology.initialize_model_parallel(tensor_model_parallel_size=2)  # dp=4
    try:
        params = model.init(jax.random.PRNGKey(0))
        params = sh.shard_params(params, model.param_specs(params))
        opt = MegatronOptimizer(TrainConfig(lr=1e-3))
        opt_state = opt.init(params)
        opt_state = opt.shard_zero1(opt_state, model.param_specs(params),
                                    params, 4, min_bytes=16 << 10)
        w_in_spec = opt_state.exp_avg[
            "transformer"]["layers"]["mlp"]["experts"]["w_in"].sharding.spec
        assert "dp" in jax.tree_util.tree_leaves(list(w_in_spec)), w_in_spec
    finally:
        topology.destroy_model_parallel()
