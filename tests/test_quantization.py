"""Weight-only int8 inference quantization (megatron_llm_tpu/quantization.py)."""

import jax
import jax.numpy as jnp
import numpy as np

from megatron_llm_tpu.models.llama import LlamaModel, llama_config
from megatron_llm_tpu.quantization import (
    dequantize_kernel,
    quantize_linear_weights_int8,
    quantized_weight_bytes,
)


def test_roundtrip_error_bounded():
    k = jax.random.normal(jax.random.PRNGKey(0), (256, 128), jnp.float32)
    q = quantize_linear_weights_int8({"kernel": k})
    assert q["kernel_q"].dtype == jnp.int8
    assert q["kernel_scale"].shape == (128,)
    rec = dequantize_kernel(q, jnp.float32)
    # symmetric absmax int8: per-channel max error <= scale/2
    err = jnp.abs(rec - k)
    bound = q["kernel_scale"][None, :] * 0.5 + 1e-8
    assert bool(jnp.all(err <= bound))


def test_stacked_scan_kernels():
    """Scanned layer stacks ([L, in, out]) get per-(layer, channel)
    scales, and slicing layer l reproduces the 2-D quantization."""
    k = jax.random.normal(jax.random.PRNGKey(1), (3, 64, 96), jnp.float32)
    q = quantize_linear_weights_int8({"kernel": k})
    assert q["kernel_q"].shape == (3, 64, 96)
    assert q["kernel_scale"].shape == (3, 96)
    full = dequantize_kernel(q, jnp.float32)
    sliced = dequantize_kernel(
        {"kernel_q": q["kernel_q"][1], "kernel_scale": q["kernel_scale"][1]},
        jnp.float32)
    np.testing.assert_allclose(np.asarray(full[1]), np.asarray(sliced),
                               rtol=0, atol=0)


def test_tree_walk_scope():
    """Norm scales (1-D), small kernels, and non-kernel dicts untouched."""
    params = {
        "norm": {"scale": jnp.ones((64,))},
        "small": {"kernel": jnp.ones((4, 4))},
        "big": {"kernel": jnp.ones((128, 64)), "bias": jnp.zeros((64,))},
        "stack": [{"kernel": jnp.ones((128, 64))}],
    }
    q = quantize_linear_weights_int8(params)
    assert "kernel" in q["small"] and "kernel_q" not in q["small"]
    assert q["norm"]["scale"].dtype == jnp.float32
    assert "kernel" not in q["big"] and q["big"]["kernel_q"].dtype == jnp.int8
    assert q["big"]["bias"].dtype == jnp.float32
    assert q["stack"][0]["kernel_q"].dtype == jnp.int8


def _tiny_model():
    cfg = llama_config("tiny", num_layers=2, seq_length=64,
                       max_position_embeddings=64, padded_vocab_size=64,
                       use_flash_attn=False)
    model = LlamaModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    return model, params


def test_quantized_forward_close_and_decode_runs():
    from megatron_llm_tpu.text_generation.generation import generate_tokens
    model, params = _tiny_model()
    qparams = quantize_linear_weights_int8(params)

    toks = jnp.array([[3, 5, 7, 9, 11, 13, 2, 4]], jnp.int32)
    logits_fp = model(params, toks, train=False)
    logits_q = model(qparams, toks, train=False)
    # int8 per-channel weight error is <0.4% per matmul; through 2
    # layers the logit drift stays small relative to the logit scale
    scale = float(jnp.std(logits_fp)) + 1e-6
    assert float(jnp.max(jnp.abs(logits_q - logits_fp))) / scale < 0.15

    lens = jnp.array([8], jnp.int32)
    out_q, n_q, _ = generate_tokens(
        model, qparams, toks, lens, jax.random.PRNGKey(0),
        max_new_tokens=8, min_prompt_len=8, greedy=True)
    out_fp, n_fp, _ = generate_tokens(
        model, params, toks, lens, jax.random.PRNGKey(0),
        max_new_tokens=8, min_prompt_len=8, greedy=True)
    assert out_q.shape == out_fp.shape
    assert int(jnp.asarray(n_q).reshape(-1)[0]) > 0
    # greedy tokens usually agree on a trained-free random model; do not
    # assert exact equality (argmax ties can flip) — prompt must survive
    np.testing.assert_array_equal(np.asarray(out_q[:, :8]),
                                  np.asarray(toks))


def test_weight_bytes_exact_accounting():
    model, params = _tiny_model()
    qparams = quantize_linear_weights_int8(params)
    # the quantizable population: stacked 3-D linear kernels (the tiny
    # llama stores the scanned layer stack; embeddings/head are 2-D and
    # carry no 'kernel' key, so they must NOT be counted)
    n_lin = sum(l.size for l in jax.tree_util.tree_leaves(params)
                if hasattr(l, "ndim") and l.ndim == 3)
    assert n_lin > 0
    qb, fb = quantized_weight_bytes(qparams)
    qb0, fb0 = quantized_weight_bytes(params)
    assert qb0 == 0
    # every linear element became exactly 1 int8 byte...
    assert qb == n_lin
    # ...and the float side shrank by 4 bytes per element, minus the
    # per-(layer, channel) fp32 scales that were added
    assert fb0 - fb == 4 * n_lin - 4 * sum(
        l.size for p, l in jax.tree_util.tree_leaves_with_path(qparams)
        if "kernel_scale" in jax.tree_util.keystr(p))


def test_sharded_int8_decode_matches_unsharded(utils):
    """tp=2 sharded int8 decode == unsharded int8 decode (the spec
    transform quantize_param_specs keeps qparams shardable)."""
    from megatron_llm_tpu.parallel import sharding as sh
    from megatron_llm_tpu.quantization import quantize_param_specs
    from megatron_llm_tpu.text_generation.generation import generate_tokens
    model, params = _tiny_model()
    qparams = quantize_linear_weights_int8(params)
    toks = jnp.asarray([[1, 2, 3, 4], [5, 6, 7, 0]])
    lens = jnp.asarray([4, 3])
    want, want_n, _ = generate_tokens(
        model, qparams, toks, lens, jax.random.PRNGKey(0),
        max_new_tokens=8, min_prompt_len=3, greedy=True)
    utils.initialize_model_parallel(tp=2)
    try:
        qspecs = quantize_param_specs(model.param_specs(params), qparams)
        qp_sh = sh.shard_params(qparams, qspecs)
        got, got_n, _ = generate_tokens(
            model, qp_sh, toks, lens, jax.random.PRNGKey(0),
            max_new_tokens=8, min_prompt_len=3, greedy=True)
    finally:
        utils.destroy_model_parallel()
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_moe_expert_banks_quantized_router_intact():
    """MoE: expert banks (w_in/w_out) quantize; the router never does
    (routing logits are decision variables, per-expert scaling would
    perturb top-k choices)."""
    from megatron_llm_tpu.models.mixtral import mixtral_config
    cfg = mixtral_config(
        "tiny", num_layers=2, hidden_size=128, num_attention_heads=4,
        ffn_hidden_size=256, padded_vocab_size=64, seq_length=32,
        max_position_embeddings=32, num_experts=4, use_flash_attn=False)
    model = LlamaModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    q = quantize_linear_weights_int8(params)
    mlp = q["transformer"]["layers"]["mlp"]
    assert mlp["experts"]["w_in_q"].dtype == jnp.int8
    assert mlp["experts"]["w_out_q"].dtype == jnp.int8
    assert mlp["router"]["kernel"].dtype == jnp.float32
    toks = jnp.arange(8)[None]
    drift = jnp.max(jnp.abs(model(params, toks, train=False)
                            - model(q, toks, train=False)))
    scale = float(jnp.std(model(params, toks, train=False))) + 1e-6
    assert float(drift) / scale < 0.15
