"""Serving resilience (serving/resilience.py + engine/server/router
wiring): deterministic fault injection, slot-level non-finite isolation,
watchdog restart + requeue, pool-pressure preemption, graceful drain,
and the extended zero-recompile guard with the whole stack armed.

Fast tier (``chaos`` marker, tier-1): injector grammar, watchdog unit,
and single-engine chaos against the tiny llama.

Slow tier (``chaos`` + ``slow``): 2-replica fleet e2e — NaN injection +
watchdog restart on one replica behind the router, every request
finishing exactly once, then an HTTP-driven graceful drain to a clean
process exit.
"""

import json
import os
import queue
import re
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

import jax
import pytest

from megatron_llm_tpu import tracing
from megatron_llm_tpu.models.llama import LlamaModel, llama_config
from megatron_llm_tpu.serving import (
    EngineConfig,
    EngineWatchdog,
    InferenceEngine,
    SamplingParams,
    ServingFaultInjector,
)
from megatron_llm_tpu.serving.request import EngineError

pytestmark = pytest.mark.chaos


# ---------------------------------------------------------------------------
# fault-spec grammar + one-shot hook semantics (pure host-side)
# ---------------------------------------------------------------------------

def test_fault_spec_parse_grammar():
    assert ServingFaultInjector.from_spec("") is None
    assert ServingFaultInjector.from_spec("   ") is None
    inj = ServingFaultInjector.from_spec("nan@12,hang@30:5,slow@7:250,oom@3")
    assert inj.nan_at == 12
    assert inj.hang_at == 30 and inj.hang_secs == 5.0
    assert inj.slow_at == 7 and inj.slow_ms == 250.0
    assert inj.oom_at == 3
    # defaults when the optional suffix is omitted
    assert ServingFaultInjector.from_spec("hang@9").hang_secs == 30.0
    with pytest.raises(ValueError, match="grammar"):
        ServingFaultInjector.from_spec("nuke@5")


def test_fault_hooks_fire_exactly_once():
    inj = ServingFaultInjector.from_spec("nan@3,oom@2,slow@1:1")
    assert not inj.poison_nonfinite(2)       # before the armed index
    assert inj.poison_nonfinite(5)           # first check at-or-after
    assert not inj.poison_nonfinite(5)       # disarmed after firing
    assert not inj.maybe_oom(1)
    assert inj.maybe_oom(2)
    assert not inj.maybe_oom(99)
    inj.before_dispatch(1)                   # 1ms slow window, consumed
    assert inj.slow_at is None


def test_watchdog_fires_rearms_and_gates_on_idle():
    fires = []
    lines = []
    busy = {"v": True}
    wd = EngineWatchdog(0.15, has_work=lambda: busy["v"],
                        on_fire=lambda: fires.append(time.monotonic()),
                        printer=lines.append)
    wd.start()
    try:
        deadline = time.monotonic() + 20.0
        while len(fires) < 2 and time.monotonic() < deadline:
            time.sleep(0.02)
        # re-armable: after a fire (and the engine "restart") it keeps
        # watching and fires again on the next stall
        assert wd.fires >= 2
        assert any("restarting the engine" in ln for ln in lines)
        # idle gate: an engine with no work makes no progress by design
        busy["v"] = False
        time.sleep(0.1)                      # let the poller see idle
        n = wd.fires
        time.sleep(0.5)
        assert wd.fires == n
    finally:
        wd.stop()


# ---------------------------------------------------------------------------
# engine-level chaos (tiny model)
# ---------------------------------------------------------------------------

class _FakeTokenizer:
    vocab_size = 64
    eod = 63
    pad = 0

    def tokenize(self, text):
        return [int(t) % 64 for t in text.split()]

    def detokenize(self, ids):
        return " ".join(str(i) for i in ids)


GREEDY = dict(temperature=0.0, eod_id=63)
PROMPT_A = [5, 6, 7, 8, 9]
PROMPT_B = [1, 2, 3]
PROMPT_LONG = [9, 8, 7, 6]


@pytest.fixture(scope="module")
def model_and_params():
    cfg = llama_config("tiny", num_layers=2, seq_length=64,
                       max_position_embeddings=64, padded_vocab_size=64,
                       use_flash_attn=False)
    model = LlamaModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    return model, params


def _make_engine(model_and_params, **overrides):
    model, params = model_and_params
    kw = dict(num_slots=4, block_size=8, prefill_chunk=16,
              max_model_len=64, max_queue_depth=32,
              default_deadline_secs=0.0)
    kw.update(overrides)
    eng = InferenceEngine(model, params, EngineConfig(**kw))
    eng.warmup()
    eng.start()
    return eng


@pytest.fixture(scope="module")
def baselines(model_and_params):
    """Greedy tokens from a clean engine (no faults, full backing) — the
    identity reference for every chaos run below."""
    eng = _make_engine(model_and_params)
    try:
        out = {}
        for key, prompt, n in (("a", PROMPT_A, 12), ("b", PROMPT_B, 12),
                               ("long", PROMPT_LONG, 40)):
            r = eng.submit(prompt, SamplingParams(max_new_tokens=n,
                                                  **GREEDY))
            out[key] = r.result(timeout=180).tokens
    finally:
        eng.stop()
    return out


def test_nonfinite_sentinel_isolates_poisoned_slot(model_and_params,
                                                   baselines):
    """Acceptance: the poisoned slot alone is evicted with a structured
    ``nonfinite`` failure; its batch-mate decodes token-identically to
    an uninjected run (the injection flips only the fetched host flag,
    so identity holds by construction — this guards the eviction path
    against collateral damage)."""
    eng = _make_engine(model_and_params)
    try:
        # armed post-warmup: indices land mid-batch deterministically
        eng.fault_injector = ServingFaultInjector(
            nan_at=eng._dispatches + 4)
        sp = SamplingParams(max_new_tokens=12, **GREEDY)
        ra, rb = eng.submit_many([PROMPT_A, PROMPT_B], [sp, sp])
        ra.result(timeout=180)
        rb.result(timeout=180)
        poisoned = [r for r in (ra, rb) if r.finish_reason == "nonfinite"]
        assert len(poisoned) == 1, (ra.finish_reason, rb.finish_reason)
        assert "non-finite" in poisoned[0].error
        survivor = rb if poisoned[0] is ra else ra
        assert survivor.finish_reason in ("stop", "length")
        assert survivor.tokens == \
            baselines["b" if survivor is rb else "a"]
        assert eng.slots_evicted_nonfinite == 1
        assert eng.stats()["slots_evicted_nonfinite"] == 1
        eng.blocks.check_invariants()
    finally:
        eng.stop()


def test_watchdog_restart_requeues_and_completes(model_and_params,
                                                 baselines):
    """A hang trips the watchdog; the engine restarts in-process and the
    interrupted (pre-first-byte) requests requeue at the queue head and
    finish token-identically — re-admission prefills over the full
    context, so a greedy continuation cannot diverge."""
    eng = _make_engine(model_and_params, watchdog_secs=0.4,
                       restart_backoff_secs=0.0)
    try:
        eng.fault_injector = ServingFaultInjector(
            hang_at=eng._dispatches + 3, hang_secs=4.0)
        sp = SamplingParams(max_new_tokens=12, **GREEDY)
        ra, rb = eng.submit_many([PROMPT_A, PROMPT_B], [sp, sp])
        ra.result(timeout=180)
        rb.result(timeout=180)
        assert eng.engine_restarts >= 1
        assert eng.stats()["engine_restarts"] >= 1
        assert ra.finish_reason in ("stop", "length")
        assert rb.finish_reason in ("stop", "length")
        assert ra.tokens == baselines["a"]
        assert rb.tokens == baselines["b"]
        eng.blocks.check_invariants()
    finally:
        eng.stop()


def test_restart_fails_midstream_requests_cleanly(model_and_params):
    """A streamed request that already produced bytes cannot be silently
    replayed (the client would see duplicate tokens) — a restart fails
    it with a structured error instead."""
    eng = _make_engine(model_and_params, restart_backoff_secs=0.0)
    try:
        # wedge the engine right after the stream's first tokens so the
        # request is deterministically mid-flight when restart() runs
        inj = ServingFaultInjector(hang_at=eng._dispatches + 5,
                                   hang_secs=8.0)
        eng.fault_injector = inj
        r = eng.submit(PROMPT_A,
                       SamplingParams(max_new_tokens=24, **GREEDY),
                       stream=True)
        deadline = time.monotonic() + 60.0
        while ((inj.hang_at is not None or r.t_first_token is None)
               and time.monotonic() < deadline):
            time.sleep(0.01)
        assert r.t_first_token is not None
        eng.restart("test")
        with pytest.raises(EngineError, match="restarted mid-stream"):
            r.result(timeout=60)
        assert r.finish_reason == "error"
        assert eng.engine_restarts == 1
        # the restarted engine serves fresh traffic normally
        r2 = eng.submit(PROMPT_B,
                        SamplingParams(max_new_tokens=4, **GREEDY))
        assert r2.result(timeout=120).finish_reason in ("stop", "length")
    finally:
        eng.stop()


def test_oom_injection_via_config_spec(model_and_params):
    """``fault_spec`` plumbs from EngineConfig; an injected pool-OOM
    skips one admission round and the head retries next step."""
    eng = _make_engine(model_and_params, fault_spec="oom@1")
    try:
        assert eng.fault_injector is not None
        r = eng.submit(PROMPT_B, SamplingParams(max_new_tokens=6, **GREEDY))
        assert r.result(timeout=120).finish_reason in ("stop", "length")
        deadline = time.monotonic() + 10.0
        while (eng.fault_injector.oom_at is not None
               and time.monotonic() < deadline):
            time.sleep(0.02)
        assert eng.fault_injector.oom_at is None    # fired and disarmed
    finally:
        eng.stop()


def test_preemption_relieves_pool_pressure(model_and_params, baselines):
    """Acceptance: on a deliberately oversubscribed pool (6 usable pages;
    the long request's worst-case reservation takes all of them) a small
    request starves behind the running reservation even though slots are
    free.  With preemption the victim releases its pages, the small
    request runs to completion first, and the victim resumes exactly
    where it stopped — greedy continuation token-identical to an
    uninterrupted run."""
    model_params = model_and_params

    def run(preemption):
        eng = _make_engine(model_params, num_blocks=7,
                           preemption=preemption)
        try:
            long_r = eng.submit(PROMPT_LONG,
                                SamplingParams(max_new_tokens=40, **GREEDY))
            deadline = time.monotonic() + 120.0
            while (len(long_r.out_tokens) < 2
                   and time.monotonic() < deadline):
                time.sleep(0.005)
            assert len(long_r.out_tokens) >= 2
            small = eng.submit([1, 2],
                               SamplingParams(max_new_tokens=4, **GREEDY))
            small.result(timeout=180)
            t_small_done = time.monotonic()
            long_done_first = long_r.finish_reason is not None
            long_r.result(timeout=180)
            eng.blocks.check_invariants()
            return (eng.scheduler.preemptions, long_r, small,
                    long_done_first, t_small_done)
        finally:
            eng.stop()

    # seed behavior (preemption off): the small request is stuck behind
    # the long reservation until the long request fully finishes
    n_pre, long_r, small, long_first, _ = run(preemption=False)
    assert n_pre == 0
    assert long_first, "small admitted despite an exhausted pool?"
    assert long_r.tokens == baselines["long"]

    # preemption on: the victim yields, the small request finishes first,
    # and the victim's continuation is token-identical
    n_pre, long_r, small, long_first, _ = run(preemption=True)
    assert n_pre >= 1
    assert not long_first, "preemption never let the small request ahead"
    assert long_r.preempt_count >= 1
    assert small.finish_reason in ("stop", "length")
    assert long_r.finish_reason in ("stop", "length")
    assert long_r.tokens == baselines["long"]


def test_resilience_stack_zero_recompiles(model_and_params):
    """Acceptance: sentinel + armed watchdog + preemption + fault
    injection together add ZERO steady-state compiles — the whole
    resilience layer is host-side bookkeeping riding the already-jitted
    programs."""
    eng = _make_engine(model_and_params, num_blocks=7, watchdog_secs=30.0,
                       preemption=True)
    tracer = tracing.SpanTracer()
    det = tracing.RecompileDetector(tracer)
    tracing.install_tracing(tracing.Tracing(tracer=tracer, recompile=det))
    try:
        det.mark_steady()
        long_r = eng.submit(PROMPT_LONG,
                            SamplingParams(max_new_tokens=40, **GREEDY))
        deadline = time.monotonic() + 120.0
        while len(long_r.out_tokens) < 2 and time.monotonic() < deadline:
            time.sleep(0.005)
        small = eng.submit([1, 2],
                           SamplingParams(max_new_tokens=4, **GREEDY))
        small.result(timeout=180)
        eng.fault_injector = ServingFaultInjector(
            nan_at=eng._dispatches + 2)
        long_r.result(timeout=180)
        # fresh traffic after the chaos (guarantees the armed NaN fires)
        r2 = eng.submit(PROMPT_B, SamplingParams(max_new_tokens=8, **GREEDY))
        r2.result(timeout=180)
        assert det.recompiles == 0, \
            f"{det.recompiles} recompiles: {list(det.events)}"
        assert eng.scheduler.preemptions >= 1
        assert eng.slots_evicted_nonfinite >= 1
        eng.blocks.check_invariants()
    finally:
        tracing.install_tracing(None)
        eng.stop()


# ---------------------------------------------------------------------------
# graceful drain over HTTP (in-process server, real engine)
# ---------------------------------------------------------------------------

def test_graceful_drain_http_lifecycle(model_and_params):
    """POST /drain: /health flips to ``draining`` (still 200 — the
    replica is alive), admission answers 503 + Retry-After, in-flight
    work finishes, and the server thread exits cleanly."""
    from megatron_llm_tpu.text_generation_server import MegatronServer

    model, params = model_and_params
    eng = _make_engine(model_and_params)
    server = MegatronServer(model, params, _FakeTokenizer(), engine=eng,
                            max_prompts=4, max_tokens=32)
    t = threading.Thread(target=server.run,
                         kwargs={"host": "127.0.0.1", "port": 0},
                         daemon=True)
    t.start()
    for _ in range(200):
        if server.httpd is not None:
            break
        time.sleep(0.05)
    assert server.httpd is not None
    url = f"http://127.0.0.1:{server.httpd.server_address[1]}"
    try:
        # a backlog of in-flight engine work keeps the drain waiter busy
        # long enough to observe the draining surface
        sp = SamplingParams(max_new_tokens=32, **GREEDY)
        backlog = eng.submit_many([[2, 3, 4, 1 + i] for i in range(8)],
                                  [sp] * 8)
        req = urllib.request.Request(url + "/drain", data=b"{}",
                                     method="POST")
        with urllib.request.urlopen(req, timeout=30) as resp:
            body = json.loads(resp.read())
            assert resp.status == 200
            assert body["status"] == "draining" and body["started"] is True
        with urllib.request.urlopen(req, timeout=30) as resp:
            assert json.loads(resp.read())["started"] is False  # idempotent
        with urllib.request.urlopen(url + "/health", timeout=30) as resp:
            assert resp.status == 200                 # alive, not dead
            assert json.loads(resp.read())["status"] == "draining"
        api = urllib.request.Request(
            url + "/api",
            data=json.dumps({"prompts": ["1 2"],
                             "tokens_to_generate": 2}).encode(),
            method="PUT")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(api, timeout=30)
        assert ei.value.code == 503
        assert ei.value.headers.get("Retry-After")
        body = json.loads(ei.value.read())
        assert body["draining"] is True
        # in-flight work finishes, then the server shuts itself down
        for r in backlog:
            assert r.result(timeout=180).finish_reason in ("stop", "length")
        t.join(timeout=120)
        assert not t.is_alive(), "server did not exit after draining"
        assert server.metrics.drained == 1
        assert server.metrics.snapshot()["drained"] == 1
    finally:
        eng.stop()
        if t.is_alive() and server.httpd is not None:
            server.httpd.shutdown()


# ---------------------------------------------------------------------------
# slow tier: 2-replica chaos fleet e2e
# ---------------------------------------------------------------------------

def _spawn_replica(extra=(), timeout=240.0):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONFAULTHANDLER="1")
    env.pop("XLA_FLAGS", None)      # single-device child, no 8-dev mesh
    errlog = tempfile.NamedTemporaryFile(
        mode="w+", prefix="replica_err_", suffix=".log", delete=False)
    proc = subprocess.Popen(
        [sys.executable,
         os.path.join(os.path.dirname(__file__), "_serve_replica.py"),
         *extra],
        stdout=subprocess.PIPE, stderr=errlog, env=env,
        text=True, cwd=os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
    # readline() in the main thread would block past the deadline on a
    # silent-but-alive child (and select() on the raw fd misses lines the
    # TextIOWrapper already buffered), so a reader thread scans stdout and
    # hands the port over a queue the main thread waits on with a timeout
    portq = queue.Queue()

    def _scan():
        for line in proc.stdout:
            # search, don't startswith: the replica's banner print can
            # interleave with the PORT line when both threads write at once
            m = re.search(r"PORT (\d+)", line)
            if m:
                portq.put(int(m.group(1)))
                # keep draining so the child never blocks on a full pipe
        portq.put(None)

    threading.Thread(target=_scan, daemon=True).start()
    try:
        port = portq.get(timeout=timeout)
    except queue.Empty:
        port = None
    if port is None:
        proc.kill()
        errlog.flush()
        errlog.seek(0)
        tail = errlog.read()[-3000:]
        raise AssertionError(
            "replica did not report a port in time; stderr tail:\n" + tail)
    return proc, port


@pytest.mark.slow
def test_chaos_fleet_every_request_finishes_exactly_once():
    """Acceptance e2e: replica A runs with NaN injection and a hang that
    trips its watchdog; behind the router every request finishes exactly
    once (the single injected NaN surfaces as one structured 500, the
    watchdog restart requeues the rest to success), the fleet /metrics
    aggregate reports ``engine_restarts >= 1`` and
    ``slots_evicted_nonfinite >= 1``, and an HTTP-driven drain of A
    finishes its in-flight work and exits the process cleanly while the
    router keeps the breaker closed."""
    from megatron_llm_tpu.serving.router import ReplicaRouter, RouterServer

    pa, port_a = _spawn_replica(["--serve_fault_inject", "nan@20,hang@60:6",
                                 "--serve_watchdog_secs", "1.0"])
    pb, port_b = _spawn_replica()
    srv = None
    try:
        router = ReplicaRouter(
            [f"127.0.0.1:{port_a}", f"127.0.0.1:{port_b}"],
            fail_threshold=2, cooldown_secs=5.0,
            health_interval_secs=999,       # probed explicitly below
            request_timeout_secs=120.0)
        srv = RouterServer(router)
        threading.Thread(target=srv.run,
                         kwargs={"host": "127.0.0.1", "port": 0},
                         daemon=True).start()
        for _ in range(100):
            if srv.httpd is not None:
                break
            time.sleep(0.05)
        assert srv.httpd is not None
        url = f"http://127.0.0.1:{srv.httpd.server_address[1]}"

        # -- chaos burst ------------------------------------------------
        n = 48
        results = []
        lock = threading.Lock()
        tail = " ".join(["2"] * 13) + " 3"

        def client(i):
            req = urllib.request.Request(
                url + "/api",
                data=json.dumps({"prompts": [f"{i} {tail}"],
                                 "tokens_to_generate": 24,
                                 "temperature": 0.0,
                                 "no_log": True}).encode(),
                method="PUT")
            try:
                with urllib.request.urlopen(req, timeout=120) as resp:
                    r = (resp.status, json.loads(resp.read()))
            except urllib.error.HTTPError as e:
                r = (e.code, json.loads(e.read() or b"{}"))
            with lock:
                results.append(r)

        threads = []
        for i in range(n):
            th = threading.Thread(target=client, args=(i,))
            th.start()
            threads.append(th)
            if len(threads) >= 12:
                threads.pop(0).join()
        for th in threads:
            th.join()

        # exactly one response per request; the injected NaN is the only
        # permitted failure and it is a structured 500
        assert len(results) == n
        bad = [(s, b) for s, b in results if s != 200]
        assert len(bad) <= 1, f"unexpected failures: {bad}"
        for s, b in bad:
            assert s == 500 and b.get("finish_reason") == "nonfinite", b

        # -- fleet-aggregated resilience counters -----------------------
        m = router.aggregated_metrics()
        agg_engine = m["aggregate"]["engine"]
        assert agg_engine["engine_restarts"] >= 1, agg_engine
        assert agg_engine["slots_evicted_nonfinite"] >= 1, agg_engine

        # -- graceful drain of replica A, mid-traffic -------------------
        # a second burst keeps the fleet busy; /drain lands while A has
        # in-flight work.  Requests A rejects with 503+draining are
        # retried by the client (the Retry-After contract) — a rejected
        # admission never executed, so exactly-once still holds.
        a_url = f"http://127.0.0.1:{port_a}"
        drain_results = []

        def retry_client(i):
            req = urllib.request.Request(
                url + "/api",
                data=json.dumps({"prompts": [f"7 {i} 5 1"],
                                 "tokens_to_generate": 16,
                                 "temperature": 0.0,
                                 "no_log": True}).encode(),
                method="PUT")
            for _ in range(40):
                try:
                    with urllib.request.urlopen(req, timeout=120) as resp:
                        r = (resp.status, json.loads(resp.read()))
                        break
                except urllib.error.HTTPError as e:
                    body = json.loads(e.read() or b"{}")
                    r = (e.code, body)
                    if e.code == 503 and body.get("draining"):
                        time.sleep(0.25)
                        continue
                    break
            with lock:
                drain_results.append(r)

        d_threads = [threading.Thread(target=retry_client, args=(i,))
                     for i in range(24)]
        for th in d_threads:
            th.start()
        # wait until the burst is demonstrably mid-flight, then drain A
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            with lock:
                if len(drain_results) >= 4:
                    break
            time.sleep(0.01)
        drain = urllib.request.Request(a_url + "/drain", data=b"{}",
                                       method="POST")
        with urllib.request.urlopen(drain, timeout=30) as resp:
            assert resp.status == 200
            assert json.loads(resp.read())["status"] == "draining"
        # probe immediately, while A is still finishing in-flight work:
        # draining is NOT dead — excluded from dispatch, breaker closed
        router.probe_once()
        ba = router.backends[0]
        assert ba.draining
        assert ba.available(router.fail_threshold)
        for th in d_threads:
            th.join(timeout=150)
        assert len(drain_results) == 24
        assert all(s == 200 for s, _ in drain_results), drain_results
        assert pa.wait(timeout=150) == 0            # clean process exit

        # post-drain traffic all lands on the survivor
        router.probe_once()                 # A now unreachable -> dead
        for i in range(4):
            status, _, _ = router.dispatch(
                "PUT", "/api",
                json.dumps({"prompts": [f"9 {i} 1"],
                            "tokens_to_generate": 4,
                            "temperature": 0.0,
                            "no_log": True}).encode())
            assert status == 200
    finally:
        if srv is not None and srv.httpd is not None:
            srv.httpd.shutdown()
        for p in (pa, pb):
            if p.poll() is None:
                p.kill()
            p.wait(timeout=30)
