"""Smoke test (reference: tests/test_basic.py — `import megatron`)."""


def test_import():
    import megatron_llm_tpu  # noqa: F401

    assert megatron_llm_tpu.__version__
