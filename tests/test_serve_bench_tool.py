"""tools/serve_bench.py smoke tests against a canned stdlib HTTP stub —
no model, no jax: the bench must measure and aggregate correctly, and
its CLI must emit the table and --json forms."""

import json
import os
import subprocess
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))
import serve_bench  # noqa: E402


def _start_stub(paged_kernel="xla", prefill_kernel="xla"):
    """Mimics the /api, /api/stream and /metrics contract with canned
    responses (every request generates 3 tokens on a 2-token prompt)."""
    metrics = {"requests": 0, "errors": 0, "throttled": 0}

    class H(BaseHTTPRequestHandler):
        def _json(self, code, body):
            data = json.dumps(body).encode()
            self.send_response(code)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_PUT(self):
            n = int(self.headers.get("Content-Length", 0))
            self.rfile.read(n)
            metrics["requests"] += 1
            if self.path == "/api":
                self._json(200, {"text": ["1 2 9 9 9"],
                                 "tokens": [[1, 2, 9, 9, 9]]})
            elif self.path == "/api/stream":
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.end_headers()
                for t in (9, 9, 9):
                    self.wfile.write(
                        b"data: " + json.dumps({"token": t}).encode()
                        + b"\n\n")
                self.wfile.write(
                    b"data: " + json.dumps(
                        {"done": True, "finish_reason": "length",
                         "tokens": [1, 2, 9, 9, 9]}).encode() + b"\n\n")
            else:
                metrics["errors"] += 1
                self._json(404, {"message": "nope"})

        def do_GET(self):
            if self.path == "/metrics":
                body = dict(metrics)
                # engine counters scale with request count so the bench's
                # prefill/prefix-cache deltas are non-trivial
                n = metrics["requests"]
                body["engine"] = {
                    "prefill_tokens_submitted": 10 * n,
                    "prefill_tokens_computed": 4 * n,
                    "prefill_tokens_cached": 6 * n,
                    "prefix_cache_hits": 2 * n,
                    "prefix_cache_misses": n,
                    "prefix_cache_evictions": 0,
                    "drafted_tokens": 3 * n,
                    "accepted_tokens": 2 * n,
                    "paged_kernel": paged_kernel,
                    "prefill_kernel": prefill_kernel,
                    # loop-goodput counters: 64% device busy by
                    # construction (0.008 / (0.010 + 0.0025))
                    "loop": {
                        "dispatches": 5 * n,
                        "wall_secs": 0.010 * n,
                        "gap_secs": 0.0025 * n,
                        "device_secs": 0.008 * n,
                    },
                    # observatory + host spill tier: 2 host-rescued
                    # blocks and 3 device->host spills per request
                    "cache": {
                        "miss_cold": n,
                        "miss_evicted": 0,
                        "evictions_capacity": 0,
                        "evictions_churn": 0,
                        "host_hits": 2 * n,
                        "swap_in_blocks": 2 * n,
                        "host": {
                            "spills_completed": 3 * n,
                            "swap_in_secs": 0.004 * n,
                        },
                    },
                }
                self._json(200, body)
            else:
                self._json(404, {"message": "nope"})

        def log_message(self, *a):
            pass

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    return httpd, f"http://127.0.0.1:{httpd.server_address[1]}"


@pytest.fixture()
def stub_server():
    httpd, url = _start_stub()
    yield url
    httpd.shutdown()


def test_run_bench_aggregates(stub_server):
    r = serve_bench.run_bench(stub_server, clients=3, requests=7, tokens=3)
    assert r["requests"] == 7 and r["ok"] == 7 and r["errors"] == 0
    assert r["status_counts"] == {"200": 7}
    assert r["tokens_total"] == 7 * 5
    assert r["tokens_per_sec"] > 0 and r["requests_per_sec"] > 0
    assert r["latency_p50_secs"] is not None
    assert r["latency_p99_secs"] >= r["latency_p95_secs"] \
        >= r["latency_p50_secs"]
    assert r["server_metrics_delta"]["requests"] == 7


def test_run_bench_stream_measures_ttft(stub_server):
    r = serve_bench.run_bench(stub_server, clients=2, requests=4,
                              tokens=3, stream=True)
    assert r["ok"] == 4
    assert r["tokens_total"] == 4 * 3        # streamed tokens only
    assert r["ttft_mean_secs"] is not None and r["ttft_p50_secs"] >= 0
    # TPOT is client-observed inter-token latency, stream-only
    assert r["tpot_mean_secs"] is not None and r["tpot_mean_secs"] >= 0
    assert r["tpot_p95_secs"] >= r["tpot_p50_secs"] >= 0


def test_run_bench_poisson_arrivals(stub_server):
    r = serve_bench.run_bench(stub_server, clients=2, requests=4,
                              tokens=3, rate=200.0)
    assert r["ok"] == 4 and r["rate"] == 200.0


def test_cli_json_and_table(stub_server, capsys):
    rc = serve_bench.main(["--url", stub_server, "--clients", "2",
                           "--requests", "3", "--tokens", "3", "--json"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["ok"] == 3
    rc = serve_bench.main(["--url", stub_server, "--clients", "2",
                           "--requests", "3", "--tokens", "3"])
    assert rc == 0
    table = capsys.readouterr().out
    assert "latency p95" in table and "throughput" in table


def test_json_schema_keys_always_present(stub_server):
    """Every key in JSON_SCHEMA_KEYS is present in every run_bench
    result (values may be None), so downstream dashboards can rely on
    the shape — this is the documented --json contract."""
    r = serve_bench.run_bench(stub_server, clients=2, requests=3, tokens=3)
    for key in serve_bench.JSON_SCHEMA_KEYS:
        assert key in r, f"missing --json schema key: {key}"
    # and the schema tuple itself has no duplicates
    assert len(set(serve_bench.JSON_SCHEMA_KEYS)) == \
        len(serve_bench.JSON_SCHEMA_KEYS)


def test_build_prompt_shared_prefix():
    # shared-fraction tickets agree on the header, differ in the tail
    a = serve_bench.build_prompt(0, "x", prefix_tokens=16,
                                 shared_prefix_frac=1.0, seed=7)
    b = serve_bench.build_prompt(1, "x", prefix_tokens=16,
                                 shared_prefix_frac=1.0, seed=7)
    assert a != b
    assert a.split()[:16] == b.split()[:16]
    # deterministic per (seed, ticket)
    assert a == serve_bench.build_prompt(0, "x", prefix_tokens=16,
                                         shared_prefix_frac=1.0, seed=7)
    # frac=0: unique same-length header, no sharing
    c = serve_bench.build_prompt(0, "x", prefix_tokens=16,
                                 shared_prefix_frac=0.0, seed=7)
    d = serve_bench.build_prompt(1, "x", prefix_tokens=16,
                                 shared_prefix_frac=0.0, seed=7)
    assert c.split()[:16] != d.split()[:16]
    assert len(c.split()) == len(a.split())
    # prefix_tokens=0 leaves the base prompt untouched
    assert serve_bench.build_prompt(0, "x", prefix_tokens=0,
                                    shared_prefix_frac=1.0, seed=7) == "x"


def test_build_prompt_zipf_skewed_popularity():
    """--prefix_zipf draws the shared header from a pool with Zipf
    popularity: a few hot prefixes dominate, a long tail churns."""
    heads = [serve_bench.build_prompt(
                 t, "x", prefix_tokens=8, shared_prefix_frac=1.0,
                 seed=3, prefix_zipf=1.2, prefix_pool=8).split()[0]
             for t in range(400)]
    counts = {}
    for h in heads:
        counts[h] = counts.get(h, 0) + 1
    assert 1 < len(counts) <= 8                  # a pool, not one prefix
    ranked = sorted(counts.values(), reverse=True)
    assert ranked[0] > 2 * ranked[-1]            # genuinely skewed
    # deterministic per (seed, ticket)
    again = serve_bench.build_prompt(5, "x", prefix_tokens=8,
                                     shared_prefix_frac=1.0, seed=3,
                                     prefix_zipf=1.2, prefix_pool=8)
    assert again == serve_bench.build_prompt(
        5, "x", prefix_tokens=8, shared_prefix_frac=1.0, seed=3,
        prefix_zipf=1.2, prefix_pool=8)
    # zipf ranks are uniform within the header (one prefix per ticket)
    assert len(set(again.split()[:8])) == 1


def test_prefix_workload_reports_engine_deltas(stub_server):
    r = serve_bench.run_bench(stub_server, clients=2, requests=4, tokens=3,
                              prefix_tokens=8, shared_prefix_frac=0.5)
    assert r["prefix_tokens"] == 8
    assert r["shared_prefix_frac"] == 0.5
    # the stub's engine counters advance 10/4/6 per request
    assert r["prefill_tokens_submitted"] == 40
    assert r["prefill_tokens_computed"] == 16
    assert r["prefill_tokens_cached"] == 24
    assert r["prefill_computed_frac"] == pytest.approx(0.4)
    assert r["prefix_cache_hits"] == 8
    assert r["prefix_cache_misses"] == 4
    assert r["prefix_cache_evictions"] == 0
    # computed-prefill throughput = computed delta / wall clock
    assert r["prefill_tokens_per_sec"] > 0
    assert r["prefill_tokens_per_sec"] == pytest.approx(
        16 / r["wall_secs"], rel=0.01)


def test_bench_reports_speculative_deltas(stub_server):
    # the stub's engine drafts 3 and accepts 2 tokens per request
    r = serve_bench.run_bench(stub_server, clients=2, requests=4, tokens=3)
    assert r["drafted_tokens"] == 12
    assert r["accepted_tokens"] == 8
    assert r["accept_rate"] == pytest.approx(8 / 12, abs=1e-4)
    assert r["accepted_tokens_per_sec"] == pytest.approx(
        8 / r["wall_secs"], rel=0.01)


def test_bench_reports_host_tier_deltas(stub_server):
    """The hierarchical-cache keys delta the observatory's two-tier
    attribution counters (cache.host_hits / cache.swap_in_blocks) and
    the spill tier's own sub-block (cache.host.spills_completed /
    swap_in_secs)."""
    r = serve_bench.run_bench(stub_server, clients=2, requests=4, tokens=3)
    assert r["cache_host_hits"] == 8
    assert r["cache_swap_in_blocks"] == 8
    assert r["cache_host_spills"] == 12
    assert r["cache_swap_in_secs"] == pytest.approx(0.016, abs=1e-6)
    assert r["cache_miss_cold"] == 4


def test_bench_reports_loop_goodput_delta(stub_server):
    """device_busy_pct / host_bubble_pct come from the engine's loop
    counter deltas over the bench window (never from deltaing the
    server's own percentages)."""
    r = serve_bench.run_bench(stub_server, clients=2, requests=4, tokens=3)
    assert r["device_busy_pct"] == pytest.approx(64.0, abs=0.01)
    assert r["host_bubble_pct"] == pytest.approx(36.0, abs=0.01)


def test_percentile_helper():
    assert serve_bench._percentile([], 0.5) is None
    assert serve_bench._percentile([3.0], 0.99) == 3.0
    vals = [float(i) for i in range(1, 101)]
    assert serve_bench._percentile(vals, 0.50) == pytest.approx(50.0, abs=1)
    assert serve_bench._percentile(vals, 0.95) == pytest.approx(95.0, abs=1)


# ---------------------------------------------------------------------------
# piecewise-rate workloads (--rate_schedule)
# ---------------------------------------------------------------------------

def test_parse_rate_schedule():
    assert serve_bench.parse_rate_schedule("2:1.5, 0:2 ,10:0.5") == \
        [(2.0, 1.5), (0.0, 2.0), (10.0, 0.5)]
    for bad in ("2", "-1:2", "2:0", "2:-1", " , ", "a:b"):
        with pytest.raises(ValueError):
            serve_bench.parse_rate_schedule(bad)


def test_build_arrivals_deterministic_and_segmented():
    sched = serve_bench.parse_rate_schedule("50:1,0:1,200:0.5")
    a = serve_bench.build_arrivals(sched, seed=3)
    assert a == serve_bench.build_arrivals(sched, seed=3)
    assert a != serve_bench.build_arrivals(sched, seed=4)
    ts = [t for t, _ in a]
    assert ts == sorted(ts)
    # arrivals land inside their segment's window; the 0-rate segment
    # is a silent pause (no arrivals at all in [1, 2))
    for t, seg in a:
        assert seg in (0, 2)
        if seg == 0:
            assert 0.0 <= t < 1.0
        else:
            assert 2.0 <= t < 2.5
    assert any(seg == 2 for _, seg in a)


def test_run_bench_rate_schedule_reports_segments(stub_server):
    r = serve_bench.run_bench(stub_server, clients=4, requests=999,
                              tokens=3, seed=5,
                              rate_schedule="30:0.4,0:0.2,80:0.3")
    assert r["rate_schedule"] == "30:0.4,0:0.2,80:0.3"
    segs = r["segments"]
    assert [s["segment"] for s in segs] == [0, 1, 2]
    assert [s["rate"] for s in segs] == [30.0, 0.0, 80.0]
    # request count comes from the schedule, not --requests
    assert r["requests"] == sum(s["requests"] for s in segs)
    assert segs[1]["requests"] == 0          # the silent pause
    for s in segs:
        assert s["ok"] == s["requests"] and s["errors"] == 0
        if s["requests"]:
            assert s["requests_per_sec"] > 0
            assert s["latency_p95_secs"] is not None
    # unscheduled runs keep the keys, valued None (schema stability)
    r2 = serve_bench.run_bench(stub_server, clients=2, requests=3,
                               tokens=3)
    assert r2["rate_schedule"] is None and r2["segments"] is None


def test_cli_rate_schedule_json_and_table(stub_server, capsys):
    rc = serve_bench.main(["--url", stub_server, "--clients", "4",
                           "--tokens", "3", "--rate_schedule",
                           "40:0.3,80:0.2", "--json"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert len(out["segments"]) == 2
    rc = serve_bench.main(["--url", stub_server, "--clients", "4",
                           "--tokens", "3", "--rate_schedule",
                           "40:0.3,80:0.2"])
    assert rc == 0
    table = capsys.readouterr().out
    assert "rate schedule" in table


# ---------------------------------------------------------------------------
# kernel A/B (--ab <server_flag>)
# ---------------------------------------------------------------------------

def test_bench_reports_paged_kernel(stub_server):
    r = serve_bench.run_bench(stub_server, clients=2, requests=3, tokens=3)
    assert r["paged_kernel"] == "xla"     # the stub's engine attribution
    assert r["prefill_kernel"] == "xla"


def test_run_ab_tags_arms():
    """run_ab runs the identical workload once per arm and tags every
    row with its arm label plus the server's self-reported attention
    path — the full --json schema holds per row."""
    on_httpd, on_url = _start_stub("pallas")
    off_httpd, off_url = _start_stub("xla")
    try:
        rows = serve_bench.run_ab([on_url, off_url], ["on", "off"],
                                  clients=2, requests=3, tokens=3)
        assert [r["ab_arm"] for r in rows] == ["on", "off"]
        assert [r["paged_kernel"] for r in rows] == ["pallas", "xla"]
        for r in rows:
            assert r["ok"] == 3 and r["errors"] == 0
            for key in serve_bench.JSON_SCHEMA_KEYS:
                assert key in r, f"missing --json schema key: {key}"
    finally:
        on_httpd.shutdown()
        off_httpd.shutdown()


def test_cli_ab_json_and_table(capsys):
    on_httpd, on_url = _start_stub("pallas")
    off_httpd, off_url = _start_stub("xla")
    try:
        rc = serve_bench.main(["--url", on_url, "--ab",
                               "serve_paged_kernel", "--ab_url", off_url,
                               "--clients", "2", "--requests", "3",
                               "--tokens", "3", "--json"])
        assert rc == 0
        out = json.loads(capsys.readouterr().out)
        assert out["ab"] == "serve_paged_kernel"
        assert [r["ab_arm"] for r in out["rows"]] == ["on", "off"]
        rc = serve_bench.main(["--url", on_url, "--ab",
                               "serve_paged_kernel", "--ab_url", off_url,
                               "--clients", "2", "--requests", "3",
                               "--tokens", "3"])
        assert rc == 0
        table = capsys.readouterr().out
        assert "serve_paged_kernel=on" in table
        assert "serve_paged_kernel=off" in table
        assert "A/B token throughput" in table
    finally:
        on_httpd.shutdown()
        off_httpd.shutdown()


def test_cli_ab_any_flag_name(capsys):
    """--ab is a free-form server-flag name, not an enum: the prefill
    kernel A/B (and any future boolean flag) reuses the same machinery,
    with the header attributing both attention paths."""
    on_httpd, on_url = _start_stub("xla", prefill_kernel="pallas")
    off_httpd, off_url = _start_stub("xla", prefill_kernel="xla")
    try:
        rc = serve_bench.main(["--url", on_url, "--ab",
                               "serve_prefill_kernel", "--ab_url", off_url,
                               "--clients", "2", "--requests", "3",
                               "--tokens", "3", "--json"])
        assert rc == 0
        out = json.loads(capsys.readouterr().out)
        assert out["ab"] == "serve_prefill_kernel"
        assert [r["prefill_kernel"] for r in out["rows"]] == \
            ["pallas", "xla"]
        rc = serve_bench.main(["--url", on_url, "--ab",
                               "serve_prefill_kernel", "--ab_url", off_url,
                               "--clients", "2", "--requests", "3",
                               "--tokens", "3"])
        assert rc == 0
        table = capsys.readouterr().out
        assert "serve_prefill_kernel=on" in table
        assert "prefill=pallas" in table and "prefill=xla" in table
        assert "A/B prefill throughput" in table
    finally:
        on_httpd.shutdown()
        off_httpd.shutdown()


def test_cli_ab_requires_ab_url():
    with pytest.raises(SystemExit):
        serve_bench.main(["--url", "http://127.0.0.1:1", "--ab",
                          "serve_paged_kernel", "--requests", "1"])


def _spawn_replica(paged_kernel, timeout=240.0, extra_args=()):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)      # single-device child, no 8-dev mesh
    here = os.path.dirname(__file__)
    proc = subprocess.Popen(
        [sys.executable, os.path.join(here, "_serve_replica.py"),
         "--paged_kernel", paged_kernel, *extra_args],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=env,
        text=True, cwd=os.path.dirname(here))
    deadline = time.monotonic() + timeout
    port = None
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if line.startswith("PORT "):
            port = int(line.split()[1])
            break
        if proc.poll() is not None:
            raise RuntimeError("replica died during startup")
    assert port, "replica did not report a port in time"
    return proc, port


@pytest.mark.slow
def test_ab_end_to_end_two_engines(capsys):
    """Acceptance: the one-flag kernel A/B runs end-to-end on CPU — two
    real engine subprocesses (Pallas interpret-mode kernel vs XLA
    gather), one serve_bench invocation, one throughput row per path."""
    p_on, port_on = _spawn_replica("on")
    p_off, port_off = _spawn_replica("off")
    try:
        rc = serve_bench.main([
            "--url", f"http://127.0.0.1:{port_on}",
            "--ab", "serve_paged_kernel",
            "--ab_url", f"http://127.0.0.1:{port_off}",
            "--clients", "2", "--requests", "4", "--tokens", "8",
            "--timeout", "180", "--json"])
        out = json.loads(capsys.readouterr().out)
        assert rc == 0
        rows = out["rows"]
        assert [r["ab_arm"] for r in rows] == ["on", "off"]
        assert rows[0]["paged_kernel"] == "pallas"
        assert rows[1]["paged_kernel"] == "xla"
        for r in rows:
            assert r["errors"] == 0 and r["tokens_per_sec"] > 0
    finally:
        for p in (p_on, p_off):
            p.kill()
            p.wait()


@pytest.mark.slow
def test_ab_speculative_end_to_end_two_replicas(capsys):
    """Acceptance: --ab serve_speculative runs end-to-end on CPU — two
    real engine subprocesses (prompt-lookup drafting + K+1 verify step
    vs plain decode), one serve_bench invocation.  The repeated-suffix
    prompt makes bigram lookup land, so the ON arm reports a non-zero
    accept rate; the OFF arm reports zero drafting."""
    p_on, port_on = _spawn_replica(
        "off", extra_args=("--serve_speculative", "1",
                           "--serve_draft_k", "4"))
    p_off, port_off = _spawn_replica("off")
    try:
        rc = serve_bench.main([
            "--url", f"http://127.0.0.1:{port_on}",
            "--ab", "serve_speculative",
            "--ab_url", f"http://127.0.0.1:{port_off}",
            "--clients", "2", "--requests", "4", "--tokens", "12",
            "--prompt", "5 6 7 8 5 6 7 8 5 6 7",
            "--temperature", "0",        # greedy: the drafting mode
            "--timeout", "180", "--json"])
        out = json.loads(capsys.readouterr().out)
        assert rc == 0
        rows = out["rows"]
        assert [r["ab_arm"] for r in rows] == ["on", "off"]
        on, off = rows
        for r in rows:
            assert r["errors"] == 0 and r["tokens_per_sec"] > 0
        # greedy spec-on output matches spec-off token-for-token: the
        # stub-free replicas share weights, so identical prompts yield
        # identical throughput-bearing token counts
        assert on["tokens_total"] == off["tokens_total"]
        # the ON arm drafted and accepted on the repeated-suffix prompt
        assert on["drafted_tokens"] > 0
        assert on["accepted_tokens"] > 0
        assert on["accept_rate"] > 0
        assert on["accepted_tokens_per_sec"] > 0
        # the OFF arm never drafts
        assert off["drafted_tokens"] == 0
        assert off["accept_rate"] is None
    finally:
        for p in (p_on, p_off):
            p.kill()
            p.wait()


@pytest.mark.slow
def test_ab_prefill_end_to_end_two_replicas(capsys):
    """Acceptance: --ab serve_prefill_kernel runs end-to-end on CPU —
    two real engine subprocesses (Pallas interpret-mode ragged prefill
    vs XLA dense gather, decode pinned to XLA in both so only prefill
    differs), one serve_bench invocation, per-arm prefill tokens/sec
    and TTFT."""
    p_on, port_on = _spawn_replica(
        "off", extra_args=("--prefill_kernel", "on"))
    p_off, port_off = _spawn_replica(
        "off", extra_args=("--prefill_kernel", "off"))
    try:
        rc = serve_bench.main([
            "--url", f"http://127.0.0.1:{port_on}",
            "--ab", "serve_prefill_kernel",
            "--ab_url", f"http://127.0.0.1:{port_off}",
            "--clients", "2", "--requests", "4", "--tokens", "8",
            "--prompt", "1 2 3 4 5 6 7 8 9 10 11 12",
            "--timeout", "180", "--json"])
        out = json.loads(capsys.readouterr().out)
        assert rc == 0
        rows = out["rows"]
        assert [r["ab_arm"] for r in rows] == ["on", "off"]
        assert rows[0]["prefill_kernel"] == "pallas"
        assert rows[1]["prefill_kernel"] == "xla"
        for r in rows:
            assert r["errors"] == 0 and r["tokens_per_sec"] > 0
            # the arm's prompt tokens all ran through chunked prefill
            assert r["prefill_tokens_per_sec"] > 0
            assert r["ttft_mean_secs"] is None or r["ttft_mean_secs"] >= 0
    finally:
        for p in (p_on, p_off):
            p.kill()
            p.wait()


@pytest.mark.slow
def test_ab_host_cache_end_to_end_two_replicas(capsys):
    """Acceptance: --ab serve_host_cache_bytes runs end-to-end on CPU —
    two real engine subprocesses with a 13-block HBM pool (96 cacheable
    tokens) under a Zipf prefix workload whose pool (12 prefixes x 2
    blocks) is twice the HBM budget.  The ON arm rescues evicted
    prefixes from host RAM (host-tier hits, device->host spills); the
    OFF arm recomputes them."""
    p_on, port_on = _spawn_replica(
        "off", extra_args=("--serve_num_blocks", "13",
                           "--serve_host_cache_bytes", str(64 << 20)))
    p_off, port_off = _spawn_replica(
        "off", extra_args=("--serve_num_blocks", "13"))
    try:
        rc = serve_bench.main([
            "--url", f"http://127.0.0.1:{port_on}",
            "--ab", "serve_host_cache_bytes",
            "--ab_url", f"http://127.0.0.1:{port_off}",
            "--clients", "2", "--requests", "32", "--tokens", "4",
            "--prefix_tokens", "16", "--prefix_zipf", "1.0",
            "--prefix_pool", "12", "--shared_prefix_frac", "1.0",
            "--temperature", "0",
            "--timeout", "180", "--json"])
        out = json.loads(capsys.readouterr().out)
        assert rc == 0
        rows = out["rows"]
        assert [r["ab_arm"] for r in rows] == ["on", "off"]
        on, off = rows
        for r in rows:
            assert r["errors"] == 0 and r["tokens_per_sec"] > 0
        # the ON arm spilled evicted pages to host RAM and rescued
        # some of them on re-admission
        assert on["cache_host_spills"] > 0
        assert on["cache_host_hits"] > 0
        assert on["cache_swap_in_blocks"] > 0
        assert on["cache_swap_in_secs"] >= 0
        # the OFF arm has no host tier: its counters never move
        assert off["cache_host_hits"] == 0
        assert off["cache_host_spills"] is None
        # host-tier rescues count as prefix-cache hits: the two-tier
        # arm serves at least as many cached prefix blocks
        assert on["prefix_cache_hits"] >= off["prefix_cache_hits"]
    finally:
        for p in (p_on, p_off):
            p.kill()
            p.wait()
