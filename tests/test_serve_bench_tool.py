"""tools/serve_bench.py smoke tests against a canned stdlib HTTP stub —
no model, no jax: the bench must measure and aggregate correctly, and
its CLI must emit the table and --json forms."""

import json
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))
import serve_bench  # noqa: E402


@pytest.fixture()
def stub_server():
    """Mimics the /api, /api/stream and /metrics contract with canned
    responses (every request generates 3 tokens on a 2-token prompt)."""
    metrics = {"requests": 0, "errors": 0, "throttled": 0}

    class H(BaseHTTPRequestHandler):
        def _json(self, code, body):
            data = json.dumps(body).encode()
            self.send_response(code)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_PUT(self):
            n = int(self.headers.get("Content-Length", 0))
            self.rfile.read(n)
            metrics["requests"] += 1
            if self.path == "/api":
                self._json(200, {"text": ["1 2 9 9 9"],
                                 "tokens": [[1, 2, 9, 9, 9]]})
            elif self.path == "/api/stream":
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.end_headers()
                for t in (9, 9, 9):
                    self.wfile.write(
                        b"data: " + json.dumps({"token": t}).encode()
                        + b"\n\n")
                self.wfile.write(
                    b"data: " + json.dumps(
                        {"done": True, "finish_reason": "length",
                         "tokens": [1, 2, 9, 9, 9]}).encode() + b"\n\n")
            else:
                metrics["errors"] += 1
                self._json(404, {"message": "nope"})

        def do_GET(self):
            if self.path == "/metrics":
                self._json(200, dict(metrics))
            else:
                self._json(404, {"message": "nope"})

        def log_message(self, *a):
            pass

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{httpd.server_address[1]}"
    httpd.shutdown()


def test_run_bench_aggregates(stub_server):
    r = serve_bench.run_bench(stub_server, clients=3, requests=7, tokens=3)
    assert r["requests"] == 7 and r["ok"] == 7 and r["errors"] == 0
    assert r["status_counts"] == {"200": 7}
    assert r["tokens_total"] == 7 * 5
    assert r["tokens_per_sec"] > 0 and r["requests_per_sec"] > 0
    assert r["latency_p50_secs"] is not None
    assert r["latency_p99_secs"] >= r["latency_p95_secs"] \
        >= r["latency_p50_secs"]
    assert r["server_metrics_delta"]["requests"] == 7


def test_run_bench_stream_measures_ttft(stub_server):
    r = serve_bench.run_bench(stub_server, clients=2, requests=4,
                              tokens=3, stream=True)
    assert r["ok"] == 4
    assert r["tokens_total"] == 4 * 3        # streamed tokens only
    assert r["ttft_mean_secs"] is not None and r["ttft_p50_secs"] >= 0


def test_run_bench_poisson_arrivals(stub_server):
    r = serve_bench.run_bench(stub_server, clients=2, requests=4,
                              tokens=3, rate=200.0)
    assert r["ok"] == 4 and r["rate"] == 200.0


def test_cli_json_and_table(stub_server, capsys):
    rc = serve_bench.main(["--url", stub_server, "--clients", "2",
                           "--requests", "3", "--tokens", "3", "--json"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["ok"] == 3
    rc = serve_bench.main(["--url", stub_server, "--clients", "2",
                           "--requests", "3", "--tokens", "3"])
    assert rc == 0
    table = capsys.readouterr().out
    assert "latency p95" in table and "throughput" in table


def test_percentile_helper():
    assert serve_bench._percentile([], 0.5) is None
    assert serve_bench._percentile([3.0], 0.99) == 3.0
    vals = [float(i) for i in range(1, 101)]
    assert serve_bench._percentile(vals, 0.50) == pytest.approx(50.0, abs=1)
    assert serve_bench._percentile(vals, 0.95) == pytest.approx(95.0, abs=1)
