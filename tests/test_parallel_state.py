"""Mesh topology tests (reference: tests/test_parallel_state.py:9-105 —
group construction and rank math for tp=2 x pp=4 on 8 devices)."""

import pytest

from megatron_llm_tpu import topology


def test_initialize_and_destroy_model_parallel(utils):
    utils.initialize_model_parallel(tp=2, pp=4)
    assert topology.model_parallel_is_initialized()
    assert topology.get_tensor_model_parallel_world_size() == 2
    assert topology.get_pipeline_model_parallel_world_size() == 4
    assert topology.get_data_parallel_world_size() == 1
    assert topology.get_world_size() == 8
    utils.destroy_model_parallel()
    assert not topology.model_parallel_is_initialized()


def test_dp_derivation(utils):
    utils.initialize_model_parallel(tp=2, pp=1)
    assert topology.get_data_parallel_world_size() == 4


def test_invalid_sizes(utils):
    with pytest.raises(RuntimeError):
        utils.initialize_model_parallel(tp=3, pp=1)


def test_vpp_state(utils):
    utils.initialize_model_parallel(tp=1, pp=4, vpp=2)
    assert topology.get_virtual_pipeline_model_parallel_world_size() == 2


def test_mesh_rank_order(utils):
    """TP groups are contiguous device blocks (reference:
    parallel_state.py:146-151 — rank order pp outer, dp middle, tp inner)."""
    mesh = utils.initialize_model_parallel(tp=2, pp=2)
    devs = mesh.devices  # [slice, pp, dp, cp, tp]
    assert devs.shape == (1, 2, 2, 1, 2)
    ids = devs.reshape(2, 2, 2)
    ids = [[[d.id for d in row] for row in plane] for plane in ids]
    # tp neighbours adjacent, dp strides tp, pp strides dp*tp
    assert ids[0][0] == [0, 1]
    assert ids[0][1] == [2, 3]
    assert ids[1][0] == [4, 5]
