"""Continuous-batching serving engine: block manager, admission queue,
batched sampling, and the engine acceptance properties — single-request
parity with ``generate_and_post_process``, decode co-batching
(occupancy > 1), streaming, deadlines, and zero recompiles after warmup.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megatron_llm_tpu import tracing
from megatron_llm_tpu.models.llama import LlamaModel, llama_config
from megatron_llm_tpu.serving import (
    BlockManager,
    EngineConfig,
    InferenceEngine,
    NoCapacity,
    QueueFull,
    Request,
    RequestQueue,
    SamplingParams,
    derive_num_blocks,
)
from megatron_llm_tpu.serving.kv_blocks import GARBAGE_BLOCK
from megatron_llm_tpu.text_generation.api import generate_and_post_process
from megatron_llm_tpu.text_generation.sampling import (
    modify_logits,
    modify_logits_batched,
    sample_batched,
)


# ---------------------------------------------------------------------------
# block manager (pure host-side, no model)
# ---------------------------------------------------------------------------

def test_block_manager_alloc_free_roundtrip():
    bm = BlockManager(num_blocks=9, block_size=4, num_slots=2,
                      max_blocks_per_slot=4)
    s0 = bm.alloc(total_tokens=10)          # 3 blocks
    assert bm.stats()["blocks_in_use"] == 3
    row = bm.tables[s0]
    assert (row[:3] > 0).all()              # real blocks, never the garbage
    assert (row[3:] == GARBAGE_BLOCK).all()
    s1 = bm.alloc(total_tokens=4)           # 1 block
    assert s1 != s0
    with pytest.raises(NoCapacity):         # no slots left
        bm.alloc(total_tokens=4)
    bm.free(s0)
    assert (bm.tables[s0] == GARBAGE_BLOCK).all()
    assert bm.stats()["blocks_in_use"] == 1
    s2 = bm.alloc(total_tokens=16)          # 4 blocks fit again
    assert bm.stats()["slots_in_use"] == 2
    bm.free(s1)
    bm.free(s2)
    end = bm.stats()
    assert end["blocks_total"] == 8
    assert end["blocks_in_use"] == 0
    assert end["blocks_free"] == 8
    assert end["slots_total"] == 2
    assert end["slots_in_use"] == 0


def test_block_manager_block_exhaustion():
    bm = BlockManager(num_blocks=4, block_size=4, num_slots=4,
                      max_blocks_per_slot=4)
    bm.alloc(total_tokens=12)               # 3 of 3 usable blocks
    with pytest.raises(NoCapacity):
        bm.alloc(total_tokens=4)
    # needs more blocks than a slot can ever hold: permanent, not capacity
    with pytest.raises(ValueError):
        bm.alloc(total_tokens=100)


def test_derive_num_blocks():
    # full backing: every slot can hold max_model_len, + garbage block
    assert derive_num_blocks(4, 8, 64) == 4 * 8 + 1
    assert derive_num_blocks(4, 8, 64, requested=10) == 10


def test_request_queue_bounded_and_atomic():
    q = RequestQueue(max_depth=2)
    r = [Request([1], SamplingParams()) for _ in range(3)]
    q.put(r[0])
    with pytest.raises(QueueFull):
        q.put_many([r[1], r[2]])            # atomic: neither admitted
    assert q.depth() == 1
    q.put(r[1])
    with pytest.raises(QueueFull):
        q.put(r[2])
    assert [q.pop().id for _ in range(2)] == [r[0].id, r[1].id]


# ---------------------------------------------------------------------------
# per-slot batched sampling
# ---------------------------------------------------------------------------

def test_modify_logits_batched_matches_scalar_rows():
    rng = np.random.RandomState(0)
    logits = jnp.asarray(rng.randn(4, 32).astype(np.float32))
    knobs = [(0, 0.0, 1.0), (5, 0.0, 0.7), (0, 0.8, 1.3), (10, 0.5, 0.9)]
    got = modify_logits_batched(
        logits,
        jnp.asarray([k for k, _, _ in knobs], jnp.int32),
        jnp.asarray([p for _, p, _ in knobs], jnp.float32),
        jnp.asarray([t for _, _, t in knobs], jnp.float32))
    for i, (k, p, t) in enumerate(knobs):
        want = modify_logits(logits[i:i + 1], top_k=k, top_p=p,
                             temperature=t)
        np.testing.assert_allclose(np.asarray(got[i:i + 1]),
                                   np.asarray(want), atol=1e-5)


def test_sample_batched_greedy_rows_argmax():
    logits = jnp.asarray([[0.1, 2.0, -1.0], [3.0, 0.0, 1.0]])
    keys = jnp.zeros((2, 2), jnp.uint32)
    # row 0 greedy via temperature 0, row 1 via top_k 1
    out = sample_batched(logits, keys,
                         jnp.asarray([0, 1], jnp.int32),
                         jnp.asarray([0.0, 0.0], jnp.float32),
                         jnp.asarray([0.0, 1.0], jnp.float32))
    assert out.tolist() == [1, 0]


# ---------------------------------------------------------------------------
# engine (tiny model)
# ---------------------------------------------------------------------------

class _FakeTokenizer:
    vocab_size = 64
    eod = 63
    pad = 0

    def tokenize(self, text):
        return [int(t) % 64 for t in text.split()]

    def detokenize(self, ids):
        return " ".join(str(i) for i in ids)


@pytest.fixture(scope="module")
def model_and_params():
    cfg = llama_config("tiny", num_layers=2, seq_length=64,
                       max_position_embeddings=64, padded_vocab_size=64,
                       use_flash_attn=False)
    model = LlamaModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    return model, params


@pytest.fixture(scope="module")
def legacy_tokens(model_and_params):
    """Legacy greedy baseline — ALSO compiles the legacy jit programs
    before the recompile test marks steady state."""
    model, params = model_and_params
    _, _, _, tokens = generate_and_post_process(
        model, params, _FakeTokenizer(), ["5 6 7 8 9"],
        tokens_to_generate=12, top_k_sampling=1)
    return tokens[0]


@pytest.fixture(scope="module")
def engine(model_and_params, legacy_tokens):
    model, params = model_and_params
    eng = InferenceEngine(model, params, EngineConfig(
        num_slots=4, block_size=8, prefill_chunk=16, max_model_len=64,
        max_queue_depth=32, default_deadline_secs=0.0))
    eng.warmup()
    eng.start()
    yield eng
    eng.stop()


GREEDY = dict(temperature=0.0, eod_id=63)


def test_engine_parity_with_generate(engine, legacy_tokens):
    """Acceptance: single-request engine response token-identical to
    generate_and_post_process (prompt + generated, stop token
    included)."""
    r = engine.submit(_FakeTokenizer().tokenize("5 6 7 8 9"),
                      SamplingParams(max_new_tokens=12, **GREEDY))
    r.result(timeout=120)
    assert r.tokens == legacy_tokens


def test_engine_cobatching_occupancy_and_isolation(engine):
    """Acceptance: under concurrent load the decode batch runs more than
    one request per step, and co-batching does not change any request's
    tokens (vs running the same prompt alone)."""
    occ0, dec0 = engine.occupancy_sum, engine.decode_steps
    results = [None] * 8

    def client(i):
        r = engine.submit([1 + i, 2, 3, 4],
                          SamplingParams(max_new_tokens=16, **GREEDY))
        results[i] = r.result(timeout=180)

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    occ = (engine.occupancy_sum - occ0) / max(engine.decode_steps - dec0, 1)
    assert occ > 1.0, f"no co-batching: mean occupancy {occ}"
    solo = engine.submit([1, 2, 3, 4],
                         SamplingParams(max_new_tokens=16, **GREEDY))
    solo.result(timeout=120)
    assert solo.out_tokens == results[0].out_tokens


def test_engine_seed_determinism(engine):
    sp = SamplingParams(max_new_tokens=8, temperature=0.9, top_k=20,
                        seed=7, eod_id=63)
    a = engine.submit([5, 6, 7], sp).result(timeout=120)
    b = engine.submit([5, 6, 7], sp).result(timeout=120)
    assert a.out_tokens == b.out_tokens


def test_engine_streaming_yields_incremental_chunks(engine):
    """Acceptance: streaming yields per-token events, then a final
    done."""
    r = engine.submit([3, 4, 5], SamplingParams(max_new_tokens=5, **GREEDY),
                      stream=True)
    events = list(r.events(timeout=60))
    kinds = [k for k, _ in events]
    assert kinds[-1] == "done"
    assert kinds[:-1] == ["token"] * (len(events) - 1)
    assert len(events) - 1 == len(r.out_tokens) >= 1


def test_engine_deadline_eviction(engine):
    r = engine.submit([1, 2, 3, 4, 5, 6, 7, 8],
                      SamplingParams(max_new_tokens=32, **GREEDY),
                      deadline_secs=1e-4)
    r.result(timeout=60)
    assert r.finish_reason == "deadline"


def test_engine_rejects_over_length(engine):
    with pytest.raises(ValueError):
        engine.submit(list(range(1, 50)),
                      SamplingParams(max_new_tokens=32, **GREEDY))


def test_engine_admission_control_queue_full(model_and_params):
    model, params = model_and_params
    eng = InferenceEngine(model, params, EngineConfig(
        num_slots=2, block_size=8, prefill_chunk=16, max_model_len=64,
        max_queue_depth=2))
    # engine never started: the queue only fills
    eng.submit([1, 2], SamplingParams(max_new_tokens=4))
    eng.submit([1, 2], SamplingParams(max_new_tokens=4))
    with pytest.raises(QueueFull) as ei:
        eng.submit([1, 2], SamplingParams(max_new_tokens=4))
    assert ei.value.retry_after_secs > 0
    eng.stop()


def test_engine_zero_recompiles_after_warmup(engine, model_and_params,
                                             tmp_path):
    """Acceptance: after warmup, arbitrary traffic (ragged prompt
    lengths, mixed sampling params, churn through slots) triggers ZERO
    XLA compiles — the continuous-batching property the fixed-shape
    step design exists for.  The full observability stack (JSONL stream,
    per-request phase attribution, SLO histograms, and the cache
    observatory's heat/forensics/ghost-tier bookkeeping — prefix
    caching is on by default) runs during the traffic: it is
    host-side-only bookkeeping and must stay free."""
    from megatron_llm_tpu import telemetry
    from megatron_llm_tpu.text_generation_server import ServerMetrics

    tracer = tracing.SpanTracer()
    det = tracing.RecompileDetector(tracer)
    tr = tracing.Tracing(tracer=tracer, recompile=det)
    tracing.install_tracing(tr)
    stream = telemetry.TelemetryStream(str(tmp_path))
    telemetry.install_stream(stream)
    metrics = ServerMetrics()
    engine.request_done_hook = metrics.observe_request_done
    metrics.engine_stats_fn = engine.stats
    # the SLO sentinel rides along: its evaluator is pure host-side
    # arithmetic over metrics snapshots and must also stay compile-free
    from megatron_llm_tpu.serving.alerts import AlertEngine
    sentinel = AlertEngine(metrics_fn=metrics.snapshot)
    metrics.alert_engine = sentinel
    try:
        det.mark_steady()
        reqs = []
        for i in range(10):
            sp = SamplingParams(
                max_new_tokens=3 + (i % 5),
                temperature=0.0 if i % 2 == 0 else 0.8,
                top_k=0 if i % 3 == 0 else 5 + i,
                top_p=0.0 if i % 2 == 0 else 0.9,
                seed=i, eod_id=63)
            reqs.append(engine.submit(list(range(1, 2 + (i % 7))), sp,
                                      trace_id=f"{i:016x}"))
        for r in reqs:
            r.result(timeout=180)
            sentinel.evaluate()     # pump the alert evaluator mid-traffic
        assert det.recompiles == 0, \
            f"{det.recompiles} recompiles after warmup: {list(det.events)}"
        assert sentinel.counters["evaluations"] == 10
        assert not sentinel.snapshot()["firing"]
        # the observability stack saw every request while staying free
        # (results signal before the engine thread finishes retiring the
        # request, so give the last hook call a moment to land)
        for _ in range(100):
            if metrics.histograms["e2e_secs"].count == 10:
                break
            time.sleep(0.05)
        assert metrics.histograms["e2e_secs"].count == 10
        assert metrics.histograms["ttft_secs"].count == 10
        snap = metrics.snapshot()
        assert snap["slo"]["e2e_secs_p95"] > 0
        # the loop profiler tiled dispatch sub-spans onto the trace
        # (category serve_loop), also without costing a compile
        loop_evs = [e for e in tracer.chrome_trace()["traceEvents"]
                    if str(e.get("name", "")).startswith("loop.")]
        assert loop_evs
        assert all(e["cat"] == "serve_loop" for e in loop_evs)
    finally:
        engine.request_done_hook = None
        tracing.install_tracing(None)
        telemetry.install_stream(None)
        stream.close()
    import json as _json
    records = [_json.loads(line) for line in
               (tmp_path / "telemetry.jsonl").read_text().splitlines()]
    done = [r for r in records if r.get("event") == "request_done"]
    assert len(done) == 10
    assert {r["trace_id"] for r in done} == {f"{i:016x}"
                                             for i in range(10)}
    for r in done:
        assert r["phases"]["prefill_secs"] > 0


def test_request_done_schema_golden(engine, tmp_path):
    """Golden record for the serve JSONL contract: bumping the schema or
    the request_done shape must be a conscious act (update this test AND
    the schema history comment in telemetry.py)."""
    from megatron_llm_tpu import telemetry

    assert telemetry.TELEMETRY_SCHEMA_VERSION == 13
    captured = []
    engine.request_done_hook = captured.append
    stream = telemetry.TelemetryStream(str(tmp_path))
    telemetry.install_stream(stream)
    try:
        engine.submit([7, 8, 9], SamplingParams(max_new_tokens=4, **GREEDY),
                      trace_id="aaaabbbbccccdddd").result(timeout=120)
        for _ in range(100):        # retire (and the hook) lands async
            if captured:
                break
            time.sleep(0.05)
    finally:
        engine.request_done_hook = None
        telemetry.install_stream(None)
        stream.close()
    assert len(captured) == 1
    rec = captured[0]
    assert frozenset(rec) == frozenset((
        "kind", "event", "request", "trace_id", "prompt_tokens",
        "cached_prompt_tokens", "prefill_computed_tokens", "new_tokens",
        "decode_tokens", "drafted_tokens", "accepted_tokens",
        "accept_rate", "finish_reason", "ttft_secs", "latency_secs",
        "tpot_secs", "phases", "paged_kernel", "prefill_kernel",
        "queue_depth", "blocks_free", "blocks_in_use",
        "blocks_cached_reusable", "miss_cold_blocks",
        "miss_evicted_blocks", "host_hit_blocks", "swap_in_secs"))
    assert frozenset(rec["phases"]) == frozenset((
        "queue_secs", "admission_secs", "prefill_secs", "decode_secs",
        "stream_write_secs"))
    # the streamed form gains exactly the envelope stamps
    import json as _json
    line = [_json.loads(ln) for ln in
            (tmp_path / "telemetry.jsonl").read_text().splitlines()
            if "request_done" in ln][0]
    assert frozenset(line) == frozenset(rec) | {"schema", "time_unix"}
    assert line["schema"] == telemetry.TELEMETRY_SCHEMA_VERSION


def test_engine_int8_kv_cache_serves(model_and_params):
    model, params = model_and_params
    eng = InferenceEngine(model, params, EngineConfig(
        num_slots=2, block_size=8, prefill_chunk=16, max_model_len=64,
        int8_kv_cache=True))
    eng.warmup()
    eng.start()
    try:
        r = eng.submit([5, 6, 7, 8],
                       SamplingParams(max_new_tokens=6, **GREEDY))
        r.result(timeout=120)
        assert r.finish_reason in ("stop", "length")
        assert 1 <= len(r.out_tokens) <= 6
        assert r.tokens[:4] == [5, 6, 7, 8]
    finally:
        eng.stop()


def test_engine_stats_shape(engine):
    s = engine.stats()
    for key in ("queue_depth", "mean_batch_occupancy", "decode_steps",
                "prefill_chunks", "tokens_generated", "prefill_secs",
                "decode_secs", "blocks_in_use", "finished", "warmed_up",
                "paged_kernel", "prefill_kernel", "speculative",
                "draft_k", "drafted_tokens", "accepted_tokens"):
        assert key in s
    assert s["warmed_up"] is True
    # resolved attention paths, not the requested modes
    assert s["paged_kernel"] in ("pallas", "xla")
    assert s["prefill_kernel"] in ("pallas", "xla")
    assert s["speculative"] is False and s["draft_k"] == 0
    # the engine-loop goodput block (loop_profiler.py) rides along,
    # populated by the traffic the earlier tests pushed through
    loop = s["loop"]
    assert loop["dispatches"] > 0
    assert loop["dispatches_by_kind"]["prefill"] > 0
    assert loop["dispatches_by_kind"]["decode"] > 0
    assert set(loop["phase_secs"]) == {"schedule", "draft",
                                       "build_inputs", "device", "emit"}
    assert loop["device_secs"] > 0
    # marks tile each dispatch: phases sum to dispatch wall-clock
    assert sum(loop["phase_secs"].values()) == \
        pytest.approx(loop["wall_secs"], rel=0.05)
    assert 0.0 <= loop["device_busy_pct"] <= 100.0
    assert loop["device_busy_pct"] + loop["host_bubble_pct"] == \
        pytest.approx(100.0, abs=0.01)
    assert loop["window"]["dispatches"] > 0
    assert "loop_device_secs" in loop["histograms"]
    # the cache observatory block (cache_observatory.py) rides along too
    cache = s["cache"]
    assert cache["probes"] == cache["hits"] + cache["misses"]
    assert cache["misses"] == cache["miss_cold"] + cache["miss_evicted"]
    assert set(cache["ghost"]) == {"x2", "x4", "x10"}
    for tier in cache["ghost"].values():
        assert tier["hits"] >= 0 and tier["capacity_blocks"] > 0
    assert isinstance(cache["heat_top"], list)


# ---------------------------------------------------------------------------
# in-engine speculative decoding (serving/drafter.py + the [S, K+1]
# verify step; docs/guide/serving.md "Speculative decoding")
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def spec_engine(model_and_params):
    model, params = model_and_params
    eng = InferenceEngine(model, params, EngineConfig(
        num_slots=4, block_size=8, prefill_chunk=16, max_model_len=64,
        max_queue_depth=32, default_deadline_secs=0.0,
        speculative=True, draft_k=4))
    eng.warmup()
    eng.start()
    yield eng
    eng.stop()


# repetitive greedy prompts (prompt-lookup fires), a non-repeating
# greedy prompt (usually no usable draft), and a sampled slot (drafts
# K=0 by design) — all co-batched into the same verify steps
SPEC_MIX = [
    ([1, 2, 3, 4, 1, 2, 3], SamplingParams(max_new_tokens=16, **GREEDY)),
    ([2, 3, 2, 3, 2, 3], SamplingParams(max_new_tokens=12, **GREEDY)),
    ([5, 6, 7, 8, 9], SamplingParams(max_new_tokens=16, **GREEDY)),
    ([5, 6, 7], SamplingParams(max_new_tokens=8, temperature=0.9,
                               top_k=20, seed=7, eod_id=63)),
]


def _run_spec_mix(eng):
    outs = [None] * len(SPEC_MIX)

    def client(i):
        prompt, sp = SPEC_MIX[i]
        outs[i] = eng.submit(prompt, sp).result(timeout=180).out_tokens

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(len(SPEC_MIX))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return outs


def test_engine_speculative_greedy_parity_cobatched(engine, spec_engine):
    """Acceptance: engine greedy output with speculation on is token-
    identical to spec-off for the same seeds/prompts at occupancy > 1,
    co-batched with sampled + non-drafting slots — and the speculative
    arm really drafted and accepted (the parity is not vacuous)."""
    occ0, dec0 = spec_engine.occupancy_sum, spec_engine.decode_steps
    drafted0 = spec_engine.drafted_tokens
    accepted0 = spec_engine.accepted_tokens
    want = _run_spec_mix(engine)
    got = _run_spec_mix(spec_engine)
    assert got == want
    occ = ((spec_engine.occupancy_sum - occ0)
           / max(spec_engine.decode_steps - dec0, 1))
    assert occ > 1.0, f"no co-batching: mean occupancy {occ}"
    assert spec_engine.drafted_tokens > drafted0
    assert spec_engine.accepted_tokens > accepted0
    assert spec_engine.accepted_tokens <= spec_engine.drafted_tokens
    s = spec_engine.stats()
    assert s["speculative"] is True and s["draft_k"] == 4


def test_engine_speculative_zero_recompiles(spec_engine, tmp_path):
    """The zero-recompile guard with speculation on: mixed drafting /
    non-drafting / sampled traffic all rides the one [S, K+1] verify
    program — per-slot draft tokens and valid counts are traced inputs,
    so proposal churn never compiles — and the request_done records
    carry the accept attribution."""
    from megatron_llm_tpu import telemetry

    tracer = tracing.SpanTracer()
    det = tracing.RecompileDetector(tracer)
    tracing.install_tracing(tracing.Tracing(tracer=tracer, recompile=det))
    stream = telemetry.TelemetryStream(str(tmp_path))
    telemetry.install_stream(stream)
    try:
        det.mark_steady()
        reqs = []
        for i in range(10):
            if i % 3 == 2:      # sampled: drafts K=0 by design
                sp = SamplingParams(max_new_tokens=3 + (i % 5),
                                    temperature=0.8, top_k=5 + i,
                                    seed=i, eod_id=63)
            else:
                sp = SamplingParams(max_new_tokens=3 + (i % 5), **GREEDY)
            prompt = ([1 + i, 2, 1 + i, 2, 1 + i] if i % 2 == 0
                      else list(range(1, 2 + (i % 7))))
            reqs.append(spec_engine.submit(prompt, sp,
                                           trace_id=f"{i:016x}"))
        for r in reqs:
            r.result(timeout=180)
        assert det.recompiles == 0, \
            f"{det.recompiles} recompiles after warmup: {list(det.events)}"
        # the loop profiler ran through the same traffic (verify-step
        # dispatches with a draft phase) without costing a compile
        loop = spec_engine.stats()["loop"]
        assert loop["dispatches_by_kind"]["verify"] > 0
        assert loop["phase_secs"]["draft"] > 0
    finally:
        tracing.install_tracing(None)
        telemetry.install_stream(None)
        stream.close()
    import json as _json
    done = [_json.loads(ln) for ln in
            (tmp_path / "telemetry.jsonl").read_text().splitlines()
            if "request_done" in ln]
    assert len(done) == 10
    for r in done:
        assert r["accepted_tokens"] <= r["drafted_tokens"]
        assert (r["accept_rate"] is None) == (r["drafted_tokens"] == 0)
    drafted = [r for r in done if r["drafted_tokens"] > 0]
    assert drafted, "no request drafted — the guard run is vacuous"
    for r in drafted:
        assert 0.0 <= r["accept_rate"] <= 1.0


def test_engine_paged_kernel_token_identity(model_and_params):
    """Acceptance: greedy decode through the Pallas ragged kernel
    (interpret mode on CPU) is token-identical to the XLA gather
    branch, the engine reports the resolved path, and the kernel-on
    engine stays zero-recompile after warmup."""
    from megatron_llm_tpu.ops.pallas import paged_attention as pa
    model, params = model_and_params
    prompts = [[5, 6, 7, 8, 9], [1, 2, 3]]
    outs = []
    old = pa._INTERPRET
    try:
        for mode in ("off", "on"):
            pa._INTERPRET = mode == "on"
            eng = InferenceEngine(model, params, EngineConfig(
                num_slots=2, block_size=8, prefill_chunk=16,
                max_model_len=64, default_deadline_secs=0.0,
                paged_kernel=mode))
            assert eng.paged_kernel == ("pallas" if mode == "on" else "xla")
            eng.warmup()
            eng.start()
            det = None
            if mode == "on":
                tracer = tracing.SpanTracer()
                det = tracing.RecompileDetector(tracer)
                tracing.install_tracing(
                    tracing.Tracing(tracer=tracer, recompile=det))
                det.mark_steady()
            try:
                rs = [eng.submit(p, SamplingParams(max_new_tokens=8,
                                                   **GREEDY))
                      for p in prompts]
                outs.append([r.result(timeout=180).tokens for r in rs])
                if det is not None:
                    # loop profiler accounted the kernel-path dispatches
                    loop = eng.stats()["loop"]
                    assert loop["dispatches_by_kind"]["decode"] > 0
                    assert loop["device_secs"] > 0
            finally:
                eng.stop()
                if det is not None:
                    tracing.install_tracing(None)
            if det is not None:
                assert det.recompiles == 0, \
                    f"{det.recompiles} recompiles: {list(det.events)}"
    finally:
        pa._INTERPRET = old
    assert outs[0] == outs[1]


def test_engine_prefill_kernel_token_identity(model_and_params):
    """Acceptance: greedy generation with the Pallas ragged *prefill*
    kernel (interpret mode on CPU) is token-identical to the XLA dense
    branch, the engine reports the resolved prefill path, and with BOTH
    kernels enabled the engine stays zero-recompile after warmup —
    prompts here straddle prefill chunks (len > prefill_chunk) so the
    cached-prefix tail-chunk shape is exercised, not just chunk 0."""
    from megatron_llm_tpu.ops.pallas import paged_attention as pa
    model, params = model_and_params
    prompts = [list(range(1, 12)), [5, 6, 7], list(range(3, 13))]
    outs = []
    old = pa._INTERPRET
    try:
        for mode in ("off", "on"):
            pa._INTERPRET = mode == "on"
            eng = InferenceEngine(model, params, EngineConfig(
                num_slots=2, block_size=8, prefill_chunk=8,
                max_model_len=64, default_deadline_secs=0.0,
                paged_kernel=mode, prefill_kernel=mode))
            assert eng.prefill_kernel == \
                ("pallas" if mode == "on" else "xla")
            eng.warmup()
            eng.start()
            det = None
            if mode == "on":        # both kernels live: still 0 recompiles
                tracer = tracing.SpanTracer()
                det = tracing.RecompileDetector(tracer)
                tracing.install_tracing(
                    tracing.Tracing(tracer=tracer, recompile=det))
                det.mark_steady()
            try:
                rs = [eng.submit(p, SamplingParams(max_new_tokens=8,
                                                   **GREEDY))
                      for p in prompts]
                outs.append([r.result(timeout=180).tokens for r in rs])
                if det is not None:
                    # both kernels live: the loop profiler saw prefill
                    # AND decode dispatches without costing a compile
                    loop = eng.stats()["loop"]
                    assert loop["dispatches_by_kind"]["prefill"] > 0
                    assert loop["dispatches_by_kind"]["decode"] > 0
            finally:
                eng.stop()
                if det is not None:
                    tracing.install_tracing(None)
            if det is not None:
                assert det.recompiles == 0, \
                    f"{det.recompiles} recompiles: {list(det.events)}"
    finally:
        pa._INTERPRET = old
    assert outs[0] == outs[1]
