"""tools/decode_bench.py runs end-to-end and prints decode/e2e rates."""

import os
import re
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_decode_bench_tiny():
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "decode_bench.py"),
         "--preset", "tiny"],
        capture_output=True, text=True, timeout=1200, cwd=ROOT,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    # the differenced decode rate may legitimately be INVALID on a fast
    # host (the tiny preset's extra steps can sit inside run-to-run
    # jitter — that's the guard working); e2e must always be real
    m = re.search(
        r"decode\s+(?:([0-9.]+) tok/s|INVALID \(t2-t1 jitter\)) "
        r"\| e2e\s+([0-9.]+) tok/s", r.stdout)
    assert m, r.stdout
    if m.group(1) is not None:
        assert float(m.group(1)) > 0
    assert float(m.group(2)) > 0
