"""Tests for the masked-LM data family: build_mapping / build_blocks_mapping
native helpers, BertDataset, T5Dataset, ICTDataset.

Mirrors the reference's coverage gap (it has none for these!) per SURVEY.md
§4's "do better" note: everything runs on CPU with synthetic corpora.
"""

import numpy as np
import pytest

from megatron_llm_tpu.data import helpers
from megatron_llm_tpu.data.indexed_dataset import (
    MMapIndexedDataset,
    MMapIndexedDatasetBuilder,
    data_file_path,
    index_file_path,
)


class ToyTok:
    """Minimal tokenizer: ids 0..9 special, 10..vocab_size-1 words."""

    def __init__(self, vocab_size=100, n_sentinels=20):
        self.vocab_size_ = vocab_size
        self.cls = 1
        self.sep = 2
        self.pad = 0
        self.mask = 3
        self._sentinels = list(range(vocab_size, vocab_size + n_sentinels))

    @property
    def vocab_size(self):
        return self.vocab_size_

    @property
    def inv_vocab(self):
        d = {i: f"w{i}" for i in range(self.vocab_size_)}
        for s in self._sentinels:
            d[s] = f"<extra_id_{s}>"
        return d

    @property
    def bos_token_id(self):
        return self.cls

    @property
    def eos_token_id(self):
        return self.sep

    @property
    def additional_special_tokens_ids(self):
        return self._sentinels


def _write_corpus(tmp_path, n_docs=20, sent_per_doc=6, sent_len=12, seed=0):
    """Sentence-level mmap dataset: each "document" is sent_per_doc sentences."""
    rng = np.random.RandomState(seed)
    prefix = str(tmp_path / "corpus")
    builder = MMapIndexedDatasetBuilder(data_file_path(prefix), np.int32)
    for _ in range(n_docs):
        for _ in range(sent_per_doc):
            n = int(rng.randint(max(2, sent_len - 4), sent_len + 5))
            builder.add_item(rng.randint(10, 90, n).astype(np.int32))
        builder.end_document()
    builder.finalize(index_file_path(prefix))
    return prefix, MMapIndexedDataset(prefix)


def _write_titles(tmp_path, n_docs=20, seed=1):
    rng = np.random.RandomState(seed)
    prefix = str(tmp_path / "titles")
    builder = MMapIndexedDatasetBuilder(data_file_path(prefix), np.int32)
    for _ in range(n_docs):
        builder.add_item(rng.randint(10, 90, 3).astype(np.int32))
        builder.end_document()
    builder.finalize(index_file_path(prefix))
    return prefix, MMapIndexedDataset(prefix)


def test_build_mapping_native_matches_python(tmp_path):
    _, ds = _write_corpus(tmp_path)
    kw = dict(num_epochs=3, max_num_samples=10**6, max_seq_length=64,
              short_seq_prob=0.0, seed=3, min_num_sent=2)
    native = helpers.build_mapping(ds.doc_idx, ds.sizes, **kw)
    py = helpers._build_mapping_py(ds.doc_idx, ds.sizes, *kw.values())
    # with short_seq_prob=0 the RNG never affects content -> same rows
    # (shuffle order differs between mt19937 and numpy RandomState)
    assert native.shape == py.shape
    assert np.array_equal(np.sort(native, axis=0), np.sort(py, axis=0))
    # spans are within bounds, end > start, targets == max_seq_length
    assert (native[:, 1] > native[:, 0]).all()
    assert (native[:, 2] == 64).all()
    assert native[:, 1].max() <= len(ds.sizes)


def test_build_mapping_short_seqs():
    docs = np.array([0, 4, 8], np.int64)
    sizes = np.full(8, 10, np.int32)
    m = helpers.build_mapping(docs, sizes, num_epochs=200,
                              max_num_samples=10**6, max_seq_length=25,
                              short_seq_prob=0.5, seed=7, min_num_sent=2)
    assert len(m) > 0
    assert (m[:, 2] >= 2).all() and (m[:, 2] <= 25).all()
    # with p=0.5 some draws must be short
    shorts = m[m[:, 2] < 25][:, 2]
    assert len(shorts) > 0
    # short lengths must cover both parities (regression: a single RNG draw
    # reused for decision+length restricted lengths to one residue class)
    assert {int(x) % 2 for x in shorts} == {0, 1}


def test_build_blocks_mapping(tmp_path):
    _, ds = _write_corpus(tmp_path)
    title_sizes = np.full(len(ds.doc_idx) - 1, 3, np.int32)
    m = helpers.build_blocks_mapping(ds.doc_idx, ds.sizes, title_sizes,
                                     num_epochs=1, max_num_samples=10**6,
                                     max_seq_length=61, seed=5)
    assert m.shape[1] == 4
    assert (m[:, 1] > m[:, 0]).all()
    ndocs = len(ds.doc_idx) - 1
    assert (m[:, 2] < ndocs).all()
    # block ids are unique even across epochs (REALM retrieval key)
    m3 = helpers.build_blocks_mapping(ds.doc_idx, ds.sizes, title_sizes,
                                      num_epochs=3, max_num_samples=10**6,
                                      max_seq_length=61, seed=5)
    assert len(np.unique(m3[:, 3])) == len(m3)
    # every block's sentences stay within its document
    for start, end, doc, _bid in m[:50]:
        assert ds.doc_idx[doc] <= start and end <= ds.doc_idx[doc + 1]


def test_bert_dataset(tmp_path):
    from megatron_llm_tpu.data.bert_dataset import BertDataset, bert_collate

    prefix, ds = _write_corpus(tmp_path)
    tok = ToyTok()
    bert = BertDataset(name="train", indexed_dataset=ds, data_prefix=prefix,
                       num_epochs=2, max_num_samples=None,
                       masked_lm_prob=0.15, max_seq_length=128,
                       short_seq_prob=0.1, seed=11, binary_head=True,
                       tokenizer=tok)
    assert len(bert) > 0
    s = bert[0]
    assert s["tokens"].shape == (128,)
    assert s["tokens"][0] == tok.cls
    # determinism
    s2 = bert[0]
    for k in s:
        assert np.array_equal(s[k], s2[k]), k
    # masked positions carry the original token in labels
    n_masked = int(s["loss_mask"].sum())
    assert n_masked >= 1
    assert (s["labels"][s["loss_mask"] == 1] >= 0).all()
    assert (s["labels"][s["loss_mask"] == 0] == -1).all()
    # mask token appears where loss_mask is set (80% of positions)
    masked_toks = s["tokens"][s["loss_mask"] == 1]
    assert (masked_toks == tok.mask).sum() >= max(1, int(0.4 * n_masked))
    # padding mask consistent with pad tokens
    assert (s["tokens"][s["attention_mask"] == 0] == tok.pad).all()
    # collate
    batch = bert_collate([[bert[0], bert[1]], [bert[2], bert[3]]])
    assert batch["tokens"].shape == (2, 2, 128)
    assert batch["labels"].min() >= 0
    assert batch["sentence_order"].shape == (2, 2)


def test_bert_dataset_entrypoint(tmp_path):
    from megatron_llm_tpu.data.bert_dataset import (
        build_train_valid_test_datasets,
    )

    prefix, _ = _write_corpus(tmp_path, n_docs=30)
    tr, va, te = build_train_valid_test_datasets(
        [prefix], "8,1,1", [200, 20, 20], max_seq_length=96,
        masked_lm_prob=0.15, short_seq_prob=0.1, seed=3, binary_head=True,
        tokenizer=ToyTok())
    assert tr is not None and len(tr) > 0
    assert va is not None and te is not None
    _ = tr[0]


def test_t5_dataset(tmp_path):
    from megatron_llm_tpu.data.t5_dataset import T5Dataset, t5_collate

    prefix, ds = _write_corpus(tmp_path)
    tok = ToyTok()
    t5 = T5Dataset(name="train", indexed_dataset=ds, data_prefix=prefix,
                   num_epochs=2, max_num_samples=None, masked_lm_prob=0.15,
                   max_seq_length=128, max_seq_length_dec=64,
                   short_seq_prob=0.1, seed=19, tokenizer=tok)
    assert len(t5) > 0
    s = t5[1]
    assert s["text_enc"].shape == (128,)
    assert s["text_dec"].shape == (64,)
    assert s["labels"].shape == (64,)
    # decoder teacher forcing: labels are decoder input shifted left
    n_dec = int(s["loss_mask"].sum())
    assert n_dec >= 2
    assert s["text_dec"][0] == tok.bos_token_id
    assert np.array_equal(s["text_dec"][1:n_dec], s["labels"][: n_dec - 1])
    assert s["labels"][n_dec - 1] == tok.eos_token_id
    # sentinels appear in encoder input and decoder stream in order
    sent_set = set(tok.additional_special_tokens_ids)
    enc_sent = [t for t in s["text_enc"] if int(t) in sent_set]
    dec_sent = [t for t in s["text_dec"] if int(t) in sent_set]
    assert enc_sent == dec_sent
    assert len(enc_sent) >= 1
    # lengths consistent with padding
    assert int(s["enc_len"]) == int((s["text_enc"] != tok.pad).sum())
    assert int(s["dec_len"]) == n_dec
    # determinism
    s2 = t5[1]
    assert np.array_equal(s["text_enc"], s2["text_enc"])
    batch = t5_collate([[t5[0], t5[1]]])
    assert batch["tokens"].shape == (1, 2, 128)
    assert batch["decoder_input_ids"].shape == (1, 2, 64)
    assert batch["encoder_decoder_attn_mask"].shape == (1, 2, 64, 128)
    dm = batch["decoder_attn_mask"][0, 1]
    assert np.array_equal(dm, np.tril(dm))  # causal
    assert dm.dtype == np.int8
    # masks match the per-sample lengths
    nd = int(t5[1]["dec_len"])
    assert dm[nd - 1, nd - 1] == 1 and (dm[nd:, :] == 0).all()


def test_ict_dataset(tmp_path):
    from megatron_llm_tpu.data.ict_dataset import ICTDataset

    prefix, blocks = _write_corpus(tmp_path)
    _, titles = _write_titles(tmp_path)
    tok = ToyTok()
    ict = ICTDataset(name="train", block_dataset=blocks,
                     title_dataset=titles, data_prefix=prefix,
                     num_epochs=1, max_num_samples=None, max_seq_length=128,
                     query_in_block_prob=0.5, seed=13, tokenizer=tok)
    assert len(ict) > 0
    s = ict[0]
    assert s["query_tokens"].shape == (128,)
    assert s["context_tokens"].shape == (128,)
    assert s["query_tokens"][0] == tok.cls
    assert s["context_tokens"][0] == tok.cls
    assert s["block_data"].shape == (4,)
    # query is real content (some non-special tokens)
    n_q = int(s["query_pad_mask"].sum())
    assert n_q >= 3
    # evidence block accessor
    start, end, doc, _ = (int(v) for v in s["block_data"])
    btok, bmask = ict.get_block(start, end, doc)
    assert btok.shape == (128,)
    nulltok, nullmask = ict.get_null_block()
    assert int(nullmask.sum()) == 3  # [CLS] [SEP] [SEP]


def test_bert_blended_prefixes(tmp_path):
    """Two weighted corpora through the blend path (reference:
    dataset_utils.py:444-479)."""
    from megatron_llm_tpu.data.bert_dataset import (
        build_train_valid_test_datasets,
    )

    p1, _ = _write_corpus(tmp_path, n_docs=20, seed=0)
    (tmp_path / "b").mkdir()
    p2, _ = _write_corpus(tmp_path / "b", n_docs=20, seed=4)
    tr, va, _ = build_train_valid_test_datasets(
        ["0.7", p1, "0.3", p2], "8,2,0", [100, 10, 0], max_seq_length=96,
        masked_lm_prob=0.15, short_seq_prob=0.1, seed=3, binary_head=True,
        tokenizer=ToyTok())
    assert tr is not None and len(tr) == 100
    assert va is not None and len(va) == 10
    s = tr[0]
    assert s["tokens"].shape == (96,)
    # roughly 70/30 split across the blend
    counts = np.bincount(tr.dataset_index, minlength=2)
    assert counts[0] > counts[1] > 0


def test_ict_split_title_alignment(tmp_path):
    """A valid-split ICT dataset must index titles with GLOBAL doc ids
    (regression: the blocks map doc column is slice-relative)."""
    from megatron_llm_tpu.data.dataset_utils import _DocSlice
    from megatron_llm_tpu.data.ict_dataset import ICTDataset

    prefix, blocks = _write_corpus(tmp_path, n_docs=20)
    _, titles = _write_titles(tmp_path, n_docs=20)
    n_docs = len(blocks.doc_idx) - 1
    lo = n_docs // 2
    view = _DocSlice(blocks, lo, n_docs)
    ict = ICTDataset(name="valid", block_dataset=view, title_dataset=titles,
                     data_prefix=prefix, num_epochs=1, max_num_samples=None,
                     max_seq_length=128, query_in_block_prob=0.5, seed=13,
                     tokenizer=ToyTok())
    s = ict[0]
    start, end, doc, _ = (int(v) for v in s["block_data"])
    # doc is global: the block's sentences lie inside that global document
    assert lo <= doc < n_docs
    assert blocks.doc_idx[doc] <= start and end <= blocks.doc_idx[doc + 1]
    # context begins with [CLS] title(3 tokens) [SEP]
    title = titles[doc]
    assert np.array_equal(s["context_tokens"][1:4], title)
    # per-index RNG: same sample regardless of access order
    _ = ict[1]
    s2 = ict[0]
    assert np.array_equal(s["query_tokens"], s2["query_tokens"])


def test_empty_mapping_fails_fast(tmp_path):
    """All-ineligible corpus must raise, not spin 2^31 epochs."""
    prefix = str(tmp_path / "single")
    builder = MMapIndexedDatasetBuilder(data_file_path(prefix), np.int32)
    for _ in range(5):  # single-sentence docs: ineligible with min_num_sent=2
        builder.add_item(np.arange(10, 20, dtype=np.int32))
        builder.end_document()
    builder.finalize(index_file_path(prefix))
    ds = MMapIndexedDataset(prefix)
    m = helpers.build_mapping(ds.doc_idx, ds.sizes, num_epochs=2**31 - 2,
                              max_num_samples=10**6, max_seq_length=64,
                              short_seq_prob=0.1, seed=3, min_num_sent=2)
    assert m.shape[0] == 0

    from megatron_llm_tpu.data.dataset_utils import get_samples_mapping
    with pytest.raises(RuntimeError, match="empty"):
        get_samples_mapping(ds, prefix, None, 100, 64, 0.1, 3, "train", True)


def test_using_native():
    assert helpers.using_native()
