"""Engine-loop goodput profiler (serving/loop_profiler.py): scripted-
clock phase accounting (marks tile the dispatch, phases sum to wall by
construction), gap/idle/stall semantics with the flight recorder,
periodic ``engine_loop_stats`` emission, tracer sub-spans, agreement
across the three surfaces (``stats()`` / JSONL / serve_report), and the
slow overhead gate the sweep's ``serve_loop_overhead`` step runs.
"""

import json
import os
import sys
import time

import pytest

from megatron_llm_tpu import telemetry, tracing
from megatron_llm_tpu.serving import LOOP_PHASES, LoopProfiler

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

import serve_report  # noqa: E402


class _Clock:
    """Scripted monotonic clock (the GoodputAccounter test pattern)."""

    def __init__(self):
        self.t = 100.0

    def __call__(self) -> float:
        return self.t

    def tick(self, dt: float) -> float:
        self.t += dt
        return self.t


def _dispatch(prof, clock, kind="decode",
              schedule=0.001, build=0.002, device=0.010, emit=0.0005,
              draft=None):
    d = prof.begin()
    d.kind = kind
    clock.tick(schedule)
    d.mark("schedule")
    if draft is not None:
        clock.tick(draft)
        d.mark("draft")
    clock.tick(build)
    d.mark("build_inputs")
    clock.tick(device)
    d.mark("device")
    clock.tick(emit)
    prof.finish(d)


def test_scripted_clock_exact_phase_accounting():
    clock = _Clock()
    prof = LoopProfiler(clock=clock)
    _dispatch(prof, clock, kind="prefill")
    _dispatch(prof, clock, kind="verify", draft=0.003)

    assert prof.dispatches == 2
    assert prof.dispatches_by_kind == {"prefill": 1, "decode": 0,
                                       "verify": 1}
    assert prof.phase_secs["schedule"] == pytest.approx(0.002)
    assert prof.phase_secs["draft"] == pytest.approx(0.003)
    assert prof.phase_secs["build_inputs"] == pytest.approx(0.004)
    assert prof.phase_secs["device"] == pytest.approx(0.020)
    assert prof.phase_secs["emit"] == pytest.approx(0.001)
    # marks tile [begin, finish]: the phases sum to wall EXACTLY, far
    # inside the 5% acceptance bound
    assert sum(prof.phase_secs.values()) == pytest.approx(
        prof.wall_secs, rel=1e-9)
    # back-to-back dispatches on a scripted clock: zero gap
    assert prof.gap_secs == 0.0

    s = prof.stats()
    assert s["device_secs"] == pytest.approx(0.020)
    assert s["host_secs"] == pytest.approx(s["wall_secs"] - 0.020)
    want_busy = 100.0 * 0.020 / s["wall_secs"]
    assert s["device_busy_pct"] == pytest.approx(want_busy, abs=1e-3)
    assert s["host_bubble_pct"] == pytest.approx(100 - want_busy,
                                                 abs=1e-3)


def test_gap_idle_and_stall_semantics(tmp_path):
    stream = telemetry.TelemetryStream(str(tmp_path))
    telemetry.install_stream(stream)
    clock = _Clock()
    prof = LoopProfiler(clock=clock, stall_threshold_secs=0.5,
                        emit_every_dispatches=10_000,
                        emit_interval_secs=10_000.0)
    try:
        _dispatch(prof, clock)
        # a sub-threshold gap accumulates but is not a stall
        clock.tick(0.3)
        _dispatch(prof, clock)
        assert prof.gap_secs == pytest.approx(0.3)
        assert prof.stalls == 0

        # unarmed (pre-warmup): even a huge gap is not a stall
        clock.tick(5.0)
        _dispatch(prof, clock)
        assert prof.stalls == 0

        # idle() breaks the chain: an empty-queue wait is not a gap
        prof.idle()
        clock.tick(60.0)
        gaps_before = prof.gap_secs
        _dispatch(prof, clock)
        assert prof.gap_secs == pytest.approx(gaps_before)

        # armed + over threshold: counted and flight-recorded
        prof.stall_armed = True
        clock.tick(0.8)
        _dispatch(prof, clock, kind="prefill")
        assert prof.stalls == 1
        stallrecs = [r for r in stream.flight_recorder.records()
                     if r.get("kind") == "loop_stall"]
        assert len(stallrecs) == 1
        assert stallrecs[0]["gap_secs"] == pytest.approx(0.8)
        assert stallrecs[0]["threshold_secs"] == 0.5
        assert stallrecs[0]["dispatch_kind"] == "prefill"
    finally:
        telemetry.install_stream(None)
        stream.close()


def test_finish_tail_folds_into_emit_and_double_mark_accumulates():
    clock = _Clock()
    prof = LoopProfiler(clock=clock)
    d = prof.begin()
    clock.tick(0.001)
    d.mark("device")
    clock.tick(0.002)
    d.mark("emit")          # explicit emit mark ...
    clock.tick(0.003)
    prof.finish(d)          # ... and the tail folds into the same phase
    assert prof.phase_secs["emit"] == pytest.approx(0.005)
    assert prof.wall_secs == pytest.approx(0.006)


def test_maybe_emit_cadence_and_jsonl_schema(tmp_path):
    stream = telemetry.TelemetryStream(str(tmp_path))
    telemetry.install_stream(stream)
    clock = _Clock()
    prof = LoopProfiler(clock=clock, emit_every_dispatches=2,
                        emit_interval_secs=10_000.0)
    try:
        _dispatch(prof, clock)          # 1 fresh: not due
        _dispatch(prof, clock)          # 2 fresh: due at finish
        _dispatch(prof, clock)          # 1 fresh again: not due
        assert not prof.maybe_emit()    # still not due, no new record
        assert prof.maybe_emit(force=True)      # what engine.stop() does
    finally:
        telemetry.install_stream(None)
        stream.close()
    lines = [json.loads(ln) for ln in
             (tmp_path / "telemetry.jsonl").read_text().splitlines()]
    loops = [r for r in lines if r.get("event") == "engine_loop_stats"]
    assert len(loops) >= 2
    first = loops[0]
    assert first["schema"] == telemetry.TELEMETRY_SCHEMA_VERSION
    assert first["kind"] == "serve"
    assert first["dispatches"] == 2
    # scalar p50/p95 travel; the bulky histogram snapshots do not
    assert "histograms" not in first
    assert set(first["phase_secs"]) == set(LOOP_PHASES)
    # the forced (engine-stop) record carries the final totals
    assert loops[-1]["dispatches"] == 3


def test_emit_interval_path(tmp_path):
    stream = telemetry.TelemetryStream(str(tmp_path))
    telemetry.install_stream(stream)
    clock = _Clock()
    prof = LoopProfiler(clock=clock, emit_every_dispatches=10_000,
                        emit_interval_secs=15.0)
    try:
        _dispatch(prof, clock)
        assert not prof.maybe_emit()            # fresh but interval not up
        clock.tick(20.0)
        assert prof.maybe_emit()                # interval elapsed
        clock.tick(20.0)
        assert not prof.maybe_emit()            # no new dispatch: not due
    finally:
        telemetry.install_stream(None)
        stream.close()


def test_tracer_subspans_tile_the_dispatch():
    tracer = tracing.SpanTracer()
    tracing.install_tracing(tracing.Tracing(tracer=tracer))
    clock = _Clock()
    prof = LoopProfiler(clock=clock)
    try:
        _dispatch(prof, clock, kind="verify", draft=0.003)
    finally:
        tracing.install_tracing(None)
    evs = [e for e in tracer.chrome_trace()["traceEvents"]
           if str(e.get("name", "")).startswith("loop.")]
    assert [e["name"] for e in evs] == [
        "loop.schedule", "loop.draft", "loop.build_inputs",
        "loop.device", "loop.emit"]
    assert all(e["cat"] == "serve_loop" for e in evs)
    # sub-spans tile: no overlap, no double counting — each starts where
    # the previous ended and durations sum to the dispatch wall-clock
    for prev, cur in zip(evs, evs[1:]):
        assert cur["ts"] == pytest.approx(prev["ts"] + prev["dur"],
                                          abs=1e-3)
    total_us = sum(e["dur"] for e in evs)
    assert total_us == pytest.approx(prof.wall_secs * 1e6, rel=1e-6)


def test_surfaces_agree_stats_jsonl_serve_report(tmp_path):
    """Acceptance: ``/metrics`` (stats()), the final ``engine_loop_stats``
    JSONL record, and serve_report's loop-goodput section report the
    same ``device_busy_pct``."""
    stream = telemetry.TelemetryStream(str(tmp_path))
    telemetry.install_stream(stream)
    clock = _Clock()
    prof = LoopProfiler(clock=clock, emit_every_dispatches=3,
                        emit_interval_secs=10_000.0)
    try:
        for i in range(7):
            _dispatch(prof, clock, kind="decode" if i % 2 else "prefill",
                      device=0.005 * (1 + i % 3))
            clock.tick(0.01)        # a little inter-dispatch gap
        prof.maybe_emit(force=True)     # what engine.stop() does
        stats = prof.stats()
    finally:
        telemetry.install_stream(None)
        stream.close()

    loops = serve_report.load_loop_stats(str(tmp_path))
    assert loops, "no engine_loop_stats records written"
    final = loops[-1]
    assert final["dispatches"] == stats["dispatches"] == 7
    assert final["device_busy_pct"] == stats["device_busy_pct"]
    assert final["host_bubble_pct"] == stats["host_bubble_pct"]

    report = serve_report.analyze([str(tmp_path)])
    lp = report["loop"]
    assert lp["dispatches"] == 7
    assert lp["device_busy_pct"] == pytest.approx(
        stats["device_busy_pct"], abs=1e-3)
    assert lp["stalls"] == stats["stalls"] == 0
    # phase shares cover the whole dispatch wall-clock
    assert sum(lp["phase_share"].values()) == pytest.approx(1.0, rel=1e-6)
    assert lp["bubble_trend"], "windowed trend missing"
    # and the rendering carries the section
    text = serve_report.render(report)
    assert "engine loop goodput" in text
    assert "device busy" in text


def test_serve_report_unchanged_on_pre_schema_10_logs(tmp_path):
    """A log with only request_done records (pre-10 shape) gets no
    ``loop`` key and renders exactly as before."""
    rec = {"schema": 9, "kind": "serve", "event": "request_done",
           "time_unix": 1.0, "latency_secs": 0.5, "ttft_secs": 0.1,
           "tpot_secs": 0.01, "finish_reason": "stop",
           "phases": {"queue_secs": 0.01, "admission_secs": 0.0,
                      "prefill_secs": 0.1, "decode_secs": 0.3,
                      "stream_write_secs": 0.01}}
    p = tmp_path / "telemetry.jsonl"
    p.write_text(json.dumps(rec) + "\n")
    report = serve_report.analyze([str(p)])
    assert "loop" not in report
    assert "engine loop goodput" not in serve_report.render(report)


def test_stats_shape_and_histograms():
    clock = _Clock()
    prof = LoopProfiler(clock=clock)
    _dispatch(prof, clock)
    s = prof.stats()
    for key in ("dispatches", "dispatches_by_kind", "wall_secs",
                "gap_secs", "device_secs", "host_secs", "phase_secs",
                "device_busy_pct", "host_bubble_pct", "stalls",
                "stall_threshold_secs", "window", "phase_p50_secs",
                "phase_p95_secs", "histograms"):
        assert key in s
    assert set(s["histograms"]) == {f"loop_{p}_secs" for p in LOOP_PHASES}
    snap = s["histograms"]["loop_device_secs"]
    assert snap["count"] == 1
    # the mergeable Histogram shape rides the Prometheus exposition
    text = telemetry.prometheus_exposition({"loop": s["histograms"]})
    assert "megatron_serve_loop_loop_device_secs_bucket" in text
    assert "megatron_serve_loop_loop_device_secs_count 1" in text
    # empty profiler: percentages are None, never a ZeroDivisionError
    empty = LoopProfiler(clock=clock).stats()
    assert empty["device_busy_pct"] is None
    assert empty["host_bubble_pct"] is None
    assert empty["window"]["device_busy_pct"] is None


def test_finish_survives_broken_telemetry(monkeypatch):
    """Diagnostics never kill the engine loop: a throwing flight
    recorder / stream is swallowed."""
    class _Boom:
        flight_recorder = property(lambda self: (_ for _ in ()).throw(
            RuntimeError("boom")))

        def emit(self, rec):
            raise RuntimeError("boom")

    clock = _Clock()
    prof = LoopProfiler(clock=clock, stall_threshold_secs=0.1,
                        emit_every_dispatches=1)
    prof.stall_armed = True
    monkeypatch.setattr(telemetry, "_ACTIVE_STREAM", _Boom())
    _dispatch(prof, clock)
    clock.tick(1.0)
    _dispatch(prof, clock)          # stall + emit paths both throw inside
    assert prof.dispatches == 2
    assert prof.stalls == 1


# ---------------------------------------------------------------------------
# overhead gate (slow; run by tools/tpu_sweep.py's serve_loop_overhead)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_serve_loop_overhead_under_2pct():
    """Per-dispatch profiler bookkeeping (begin + a full set of phase
    marks + finish, with a live telemetry stream installed — the worst
    case) must cost < 2% of a real CPU dispatch of the tiny engine.
    The attribution may not become the bubble it measures."""
    import jax

    from megatron_llm_tpu.models.llama import LlamaModel, llama_config
    from megatron_llm_tpu.serving import (EngineConfig, InferenceEngine,
                                          SamplingParams)

    # arm A: the real engine under traffic — mean dispatch wall-clock
    cfg = llama_config("tiny", num_layers=2, seq_length=64,
                       max_position_embeddings=64, padded_vocab_size=64,
                       use_flash_attn=False)
    model = LlamaModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    eng = InferenceEngine(model, params, EngineConfig(
        num_slots=4, block_size=8, prefill_chunk=16, max_model_len=64,
        max_queue_depth=32, default_deadline_secs=0.0))
    eng.warmup()
    eng.start()
    try:
        reqs = [eng.submit([1 + i, 2, 3, 4],
                           SamplingParams(max_new_tokens=12,
                                          temperature=0.0, eod_id=63))
                for i in range(8)]
        for r in reqs:
            r.result(timeout=180)
        loop = eng.stats()["loop"]
    finally:
        eng.stop()
    assert loop["dispatches"] > 0
    mean_dispatch_secs = loop["wall_secs"] / loop["dispatches"]

    # arm B: the profiler alone, same dispatch protocol, tight loop
    stream = telemetry.TelemetryStream(None)    # no file, worst-case code
    telemetry.install_stream(stream)
    try:
        prof = LoopProfiler()
        prof.stall_armed = True
        n = 2000
        t0 = time.perf_counter()
        for _ in range(n):
            d = prof.begin()
            d.mark("schedule")
            d.mark("draft")
            d.mark("build_inputs")
            d.mark("device")
            prof.finish(d)
        cost_per_dispatch = (time.perf_counter() - t0) / n
    finally:
        telemetry.install_stream(None)
        stream.close()
    frac = cost_per_dispatch / mean_dispatch_secs
    assert frac < 0.02, (
        f"profiler bookkeeping {cost_per_dispatch * 1e6:.1f}us/dispatch "
        f"= {frac * 100:.2f}% of a {mean_dispatch_secs * 1e3:.2f}ms "
        f"CPU dispatch (gate: < 2%)")
