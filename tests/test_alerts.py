"""SLO sentinel (serving/alerts.py): burn-rate alerting, incident
lifecycle, and postmortem bundles.

Fast tier (tier-1): window/burn-rate arithmetic on an injectable clock,
lifecycle hysteresis/dedup/storm-cap, counter-reset clamping, rule
parsing, the fleet merge, atomic snapshot-bundle writing, the schema-13
``alert_transition`` golden record, the Prometheus ``megatron_alert_
firing`` gauge, and the serve_top/serve_report alert surfaces over
synthesized documents.

Slow tier (``-m slow``; excluded from tier-1):

* chaos e2e — a 2-replica fleet of REAL tiny-model engine subprocesses
  behind the router; faults injected into one replica drive exactly one
  firing -> resolved cycle whose state agrees across the replica
  /metrics, the router's fleet-merged view, the JSONL stream, and
  serve_top, with a readable postmortem bundle on disk and the incident
  rendered by serve_report.
* overhead gate — one full default-rule evaluation over a live engine's
  metrics snapshot must cost < 2% of a measured dispatch.
"""

import contextlib
import io
import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from megatron_llm_tpu.serving.alerts import (
    AlertEngine,
    DEFAULT_RULES,
    _frac_over,
    _hist_delta,
    merge_alert_blocks,
    normalize_rule,
    parse_rules_arg,
)

TOOLS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools")
if TOOLS not in sys.path:
    sys.path.insert(0, TOOLS)


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, secs):
        self.t += secs
        return self.t


def _hist(over, under, slo_label="1", over_label="+Inf"):
    """Histogram.snapshot() shape with ``under`` observations in the
    bucket bounded by ``slo_label`` and ``over`` in ``over_label``."""
    return {"buckets": {slo_label: under, over_label: over},
            "count": over + under, "sum": float(over + under)}


def _rate_rule(window=60.0, value=0.05, clear=60.0, for_secs=0.0,
               min_den=1):
    return {"name": "error_rate", "kind": "rate", "num_path": "errors",
            "den_path": "requests", "window_secs": window, "op": ">=",
            "value": value, "min_den": min_den, "for_secs": for_secs,
            "clear_secs": clear, "severity": "page"}


def _threshold_rule(name="qd", path="engine.queue_depth", value=8.0,
                    for_secs=0.0, clear=0.0):
    return {"name": name, "kind": "threshold", "path": path, "op": ">=",
            "value": value, "for_secs": for_secs, "clear_secs": clear,
            "severity": "warn"}


def _burn_rule(**kw):
    rule = {"name": "ttft_burn", "kind": "burn_rate",
            "path": "histograms.ttft_secs", "slo_secs": 1.0,
            "objective": 0.99, "fast_window_secs": 60.0,
            "slow_window_secs": 900.0, "burn_threshold": 14.4,
            "min_count": 20, "for_secs": 0.0, "clear_secs": 0.0,
            "severity": "page"}
    rule.update(kw)
    return rule


# ---------------------------------------------------------------------------
# window + burn arithmetic
# ---------------------------------------------------------------------------

def test_window_sample_requires_full_history():
    """A fresh engine must not false-fire on a partial window: the rate
    rule stays inactive until a ring snapshot is >= window_secs old,
    even while every request is erroring."""
    clock = FakeClock()
    eng = AlertEngine(rules=[_rate_rule(window=60.0)], clock=clock)
    for i in range(5):
        bad = {"errors": i * 2, "requests": i * 2}   # 100% error rate
        assert eng.evaluate(snapshot=bad) == []
        clock.advance(10.0)         # ring spans only 0..40s: no sample
    assert eng.snapshot()["firing"] == []
    clock.advance(25.0)             # oldest entry is now 65s old
    trs = eng.evaluate(snapshot={"errors": 20, "requests": 20})
    assert [t["state"] for t in trs] == ["firing"]
    assert trs[0]["value"] == pytest.approx(1.0)


def test_rate_window_math_on_counter_deltas():
    """The windowed rate is (num delta)/(den delta) between now and the
    newest ring entry at least window_secs old — not lifetime ratios."""
    clock = FakeClock()
    eng = AlertEngine(rules=[_rate_rule(window=30.0, value=0.5,
                                        clear=0.0)], clock=clock)
    eng.evaluate(snapshot={"errors": 100, "requests": 1000})
    clock.advance(31.0)
    # lifetime ratio is 102/1010 ~ 0.1, but the WINDOW saw 2 errors in
    # 10 requests = 0.2 < 0.5: no fire
    assert eng.evaluate(snapshot={"errors": 102, "requests": 1010}) == []
    clock.advance(31.0)
    # window: 8 errors / 10 requests = 0.8 >= 0.5: fire, value = rate
    trs = eng.evaluate(snapshot={"errors": 110, "requests": 1020})
    assert [t["state"] for t in trs] == ["firing"]
    assert trs[0]["value"] == pytest.approx(0.8)
    assert trs[0]["threshold"] == 0.5
    assert trs[0]["window_secs"] == 30.0


def test_rate_counter_reset_clamps_to_empty_window():
    """An engine restart rewinds counters; the delta clamps to the
    post-reset value instead of going negative and must not fire on
    garbage arithmetic."""
    clock = FakeClock()
    eng = AlertEngine(rules=[_rate_rule(window=30.0, value=0.5)],
                      clock=clock)
    eng.evaluate(snapshot={"errors": 50, "requests": 500})
    clock.advance(31.0)
    # restart: counters rewound below the ring sample; deltas read as
    # the raw post-reset values (1 error / 10 requests = 0.1 < 0.5)
    assert eng.evaluate(snapshot={"errors": 1, "requests": 10}) == []


def test_burn_rate_arithmetic_and_two_window_gate():
    """Burn = (windowed fraction over SLO) / error budget, and a page
    needs BOTH the fast and slow windows burning — a brief spike that
    only pollutes the fast window must not fire."""
    clock = FakeClock()
    eng = AlertEngine(rules=[_burn_rule()], clock=clock)
    h0 = _hist(over=0, under=100)
    eng.evaluate(snapshot={"histograms": {"ttft_secs": h0}})
    clock.advance(901.0)            # one sample old enough for BOTH windows
    # 50 of the 100 new observations exceed the 1s SLO: frac 0.5,
    # budget 0.01 -> burn 50 >= 14.4 in both windows -> firing
    h1 = _hist(over=50, under=150)
    trs = eng.evaluate(snapshot={"histograms": {"ttft_secs": h1}})
    assert [t["state"] for t in trs] == ["firing"]
    assert trs[0]["value"] == pytest.approx(50.0)
    assert trs[0]["threshold"] == 14.4

    # fresh engine, same traffic shape but the slow window's sample is
    # missing: strict history means no verdict, no false page
    eng2 = AlertEngine(rules=[_burn_rule()], clock=clock)
    eng2.evaluate(snapshot={"histograms": {"ttft_secs": h0}})
    clock.advance(61.0)             # fast window satisfied, slow not
    assert eng2.evaluate(
        snapshot={"histograms": {"ttft_secs": h1}}) == []


def test_burn_rate_min_count_guard():
    """Tiny windows don't page: fewer than min_count observations in
    either window means no verdict."""
    clock = FakeClock()
    eng = AlertEngine(rules=[_burn_rule(min_count=20)], clock=clock)
    eng.evaluate(snapshot={"histograms": {"ttft_secs": _hist(0, 10)}})
    clock.advance(901.0)
    # only 10 new observations, all over SLO — under min_count
    assert eng.evaluate(snapshot={
        "histograms": {"ttft_secs": _hist(10, 10)}}) == []


def test_frac_over_and_hist_delta_primitives():
    delta = _hist_delta(_hist(over=30, under=70), _hist(over=10, under=50))
    assert delta["count"] == 40
    assert _frac_over(delta, 1.0) == pytest.approx(0.5)
    # +Inf is always bad; a bucket at the SLO bound is good
    assert _frac_over({"buckets": {"1": 5, "+Inf": 5}, "count": 10,
                       "sum": 0.0}, 1.0) == pytest.approx(0.5)
    # reset clamp: negative per-bucket deltas read as zero
    clamped = _hist_delta(_hist(over=0, under=1), _hist(over=10, under=50))
    assert clamped["count"] == 0


# ---------------------------------------------------------------------------
# lifecycle: hysteresis, dedup, storm cap
# ---------------------------------------------------------------------------

def test_hysteresis_pending_firing_resolved():
    clock = FakeClock()
    sink = []
    eng = AlertEngine(rules=[_threshold_rule(value=8.0, for_secs=10.0,
                                             clear=5.0)],
                      clock=clock, transition_sink=sink.append)
    bad = {"engine": {"queue_depth": 20}}
    good = {"engine": {"queue_depth": 1}}
    trs = eng.evaluate(snapshot=bad)
    assert [t["state"] for t in trs] == ["pending"]
    clock.advance(5.0)
    assert eng.evaluate(snapshot=bad) == []       # still pending
    clock.advance(6.0)
    trs = eng.evaluate(snapshot=bad)              # for_secs elapsed
    assert [t["state"] for t in trs] == ["firing"]
    assert eng.snapshot()["firing_count"] == 1
    clock.advance(1.0)
    assert eng.evaluate(snapshot=good) == []      # clear hysteresis starts
    assert eng.snapshot()["firing_count"] == 1    # still firing
    clock.advance(6.0)
    trs = eng.evaluate(snapshot=good)
    assert [t["state"] for t in trs] == ["resolved"]
    assert eng.snapshot()["firing_count"] == 0
    assert [t["state"] for t in sink] == ["pending", "firing", "resolved"]


def test_pending_flap_emits_nothing():
    """pending -> ok (breach vanished before for_secs) is flap noise:
    suppressed entirely, no resolved for something that never fired."""
    clock = FakeClock()
    sink = []
    eng = AlertEngine(rules=[_threshold_rule(for_secs=10.0)],
                      clock=clock, transition_sink=sink.append)
    eng.evaluate(snapshot={"engine": {"queue_depth": 20}})
    clock.advance(2.0)
    assert eng.evaluate(snapshot={"engine": {"queue_depth": 1}}) == []
    assert [t["state"] for t in sink] == ["pending"]
    assert eng.snapshot()["firing_count"] == 0


def test_dedup_steady_breach_single_transition():
    """A breach that persists across many evaluation turns emits ONE
    firing transition — dedup is inherent to the per-rule state."""
    clock = FakeClock()
    sink = []
    eng = AlertEngine(rules=[_threshold_rule()], clock=clock,
                      transition_sink=sink.append)
    for _ in range(10):
        eng.evaluate(snapshot={"engine": {"queue_depth": 20}})
        clock.advance(2.0)
    assert [t["state"] for t in sink] == ["firing"]
    assert eng.counters["transitions_total"] == 1
    assert eng.counters["firing_total"] == 1


def test_storm_cap_suppresses_bundles_not_transitions():
    """When more rules fire than max_firing, the overflow transitions
    still reach the sink (marked storm_suppressed) but skip bundle and
    webhook side effects — an alert storm must not write N bundles."""
    clock = FakeClock()
    sink, bundles = [], []

    def bundle_fn(tr):
        bundles.append(tr["rule"])
        return f"/tmp/{tr['rule']}"

    rules = [_threshold_rule(name=f"r{i:02d}") for i in range(5)]
    eng = AlertEngine(rules=rules, clock=clock, max_firing=3,
                      transition_sink=sink.append, bundle_fn=bundle_fn)
    eng.evaluate(snapshot={"engine": {"queue_depth": 20}})
    assert len(sink) == 5
    suppressed = [t for t in sink if t.get("storm_suppressed")]
    assert len(suppressed) == 2
    assert len(bundles) == 3
    assert eng.counters["storm_suppressed"] == 2
    assert eng.counters["bundles_written"] == 3
    # the capped rules fired without a bundle path
    snap = eng.snapshot()
    assert snap["firing_count"] == 5
    assert sum(1 for f in snap["firing"] if f["bundle"]) == 3


def test_duplicate_rule_names_rejected():
    with pytest.raises(ValueError, match="duplicate"):
        AlertEngine(rules=[_threshold_rule(), _threshold_rule()])


# ---------------------------------------------------------------------------
# rule parsing + fleet merge
# ---------------------------------------------------------------------------

def test_parse_rules_arg_forms(tmp_path):
    rules, opts = parse_rules_arg(json.dumps([_threshold_rule()]))
    assert rules[0]["name"] == "qd" and opts == {}
    rules, opts = parse_rules_arg(json.dumps(
        {"rules": [_rate_rule()], "interval_secs": 0.5, "max_bundles": 2}))
    assert rules[0]["kind"] == "rate"
    assert opts == {"interval_secs": 0.5, "max_bundles": 2}
    # defaults filled by kind
    assert rules[0]["min_den"] == 1
    p = tmp_path / "rules.json"
    p.write_text(json.dumps([_burn_rule()]))
    rules, _ = parse_rules_arg(str(p))
    assert rules[0]["kind"] == "burn_rate"
    with pytest.raises(ValueError, match="unknown kind"):
        parse_rules_arg('[{"name": "x", "kind": "nope"}]')
    with pytest.raises(ValueError, match="unknown op"):
        normalize_rule({"name": "x", "kind": "threshold", "path": "a",
                        "op": "!=", "value": 1})
    with pytest.raises(ValueError, match="missing required"):
        normalize_rule({"name": "x", "kind": "burn_rate", "path": "a"})


def test_default_rules_normalize():
    names = [normalize_rule(r)["name"] for r in DEFAULT_RULES]
    assert len(set(names)) == len(names) == 10


def test_merge_alert_blocks_rewrites_scope_and_sums_counters():
    a = AlertEngine(rules=[_threshold_rule()], scope="replica")
    b = AlertEngine(rules=[_threshold_rule()], scope="replica")
    a.evaluate(snapshot={"engine": {"queue_depth": 20}})
    b.evaluate(snapshot={"engine": {"queue_depth": 1}})
    merged = merge_alert_blocks({"http://a:1": a.snapshot(),
                                 "http://b:2": b.snapshot()})
    assert merged["firing_count"] == 1
    assert merged["firing"][0]["scope"] == "http://a:1"
    assert merged["counters"]["evaluations"] == 2
    assert merged["rules_total"] == 1


# ---------------------------------------------------------------------------
# schema-13 golden record + Prometheus surface
# ---------------------------------------------------------------------------

def test_alert_transition_schema13_golden(tmp_path):
    """Golden record for the alert_transition JSONL contract: changing
    the envelope or payload shape must be a conscious act (update this
    test AND the schema history comment in telemetry.py)."""
    from megatron_llm_tpu import telemetry

    assert telemetry.TELEMETRY_SCHEMA_VERSION == 13
    stream = telemetry.TelemetryStream(str(tmp_path))

    def sink(payload):
        # mirror of the replica wiring in build_server_alerts: the sink
        # stamps kind="serve"; emit() adds schema + time_unix
        stream.emit({"kind": "serve", **payload})

    clock = FakeClock()
    eng = AlertEngine(rules=[_threshold_rule()], clock=clock,
                      transition_sink=sink)
    try:
        eng.evaluate(snapshot={"engine": {"queue_depth": 20}})
    finally:
        stream.close()
    recs = [json.loads(line) for line in
            (tmp_path / "telemetry.jsonl").read_text().splitlines()]
    trs = [r for r in recs if r.get("event") == "alert_transition"]
    assert len(trs) == 1
    rec = trs[0]
    assert frozenset(rec) == frozenset((
        "schema", "kind", "time_unix", "event", "rule", "scope", "state",
        "severity", "value", "threshold", "window_secs", "since_unix",
        "bundle"))
    assert rec["schema"] == 13
    assert rec["kind"] == "serve"
    assert rec["rule"] == "qd"
    assert rec["scope"] == "replica"
    assert rec["state"] == "firing"
    assert rec["severity"] == "warn"
    assert rec["value"] == 20.0
    assert rec["threshold"] == 8.0
    assert rec["bundle"] is None


def test_prometheus_alert_firing_gauge():
    from megatron_llm_tpu import telemetry

    eng = AlertEngine(rules=[_threshold_rule()])
    eng.evaluate(snapshot={"engine": {"queue_depth": 20}})
    text = telemetry.prometheus_exposition(
        {"requests": 3, "alerts": eng.snapshot()})
    assert ('megatron_alert_firing{rule="qd",scope="replica",'
            'severity="warn"} 1') in text
    assert "# TYPE megatron_alert_firing gauge" in text
    # the non-list alert scalars still walk under the alerts_ prefix
    assert "megatron_serve_alerts_firing_count 1" in text
    assert "megatron_serve_requests 3" in text


def test_snapshot_bundle_atomic_and_bounded(tmp_path):
    from megatron_llm_tpu import telemetry

    dest = str(tmp_path / "incidents" / "rule-0001")
    parts = {"metrics": {"a": 1}, "stacks": "thread dump\n",
             "big": {"blob": "x" * 10000}}
    path = telemetry.write_snapshot_bundle(dest, parts,
                                           max_bytes_per_part=1024,
                                           manifest_extra={"rule": "r"})
    assert path == dest and os.path.isdir(dest)
    man = json.load(open(os.path.join(dest, "manifest.json")))
    assert man["rule"] == "r"
    assert set(man["parts"]) == {"metrics", "stacks", "big"}
    assert man["parts"]["big"]["truncated"] is True
    assert {"metrics.json", "stacks.txt", "big.json",
            "manifest.json"} <= set(os.listdir(dest))
    big = open(os.path.join(dest, "big.json")).read()
    assert len(big.encode()) <= 1024 + 64      # truncation marker slack
    assert "truncated" in big
    # no stray staging dirs, and re-capture into the same name works
    assert os.listdir(str(tmp_path / "incidents")) == ["rule-0001"]
    telemetry.write_snapshot_bundle(dest, {"metrics": {"a": 2}})
    assert json.load(open(os.path.join(dest, "metrics.json")))["a"] == 2


def test_capture_thread_stacks_lists_all_threads():
    from megatron_llm_tpu import telemetry

    ev = threading.Event()
    t = threading.Thread(target=ev.wait, name="stack-probe", daemon=True)
    t.start()
    try:
        text = telemetry.capture_thread_stacks()
    finally:
        ev.set()
        t.join()
    assert "stack-probe" in text
    assert "MainThread" in text


# ---------------------------------------------------------------------------
# tool surfaces over synthesized documents
# ---------------------------------------------------------------------------

def _firing_entry(rule="error_rate", scope="replica", severity="page"):
    return {"rule": rule, "scope": scope, "severity": severity,
            "since_unix": 1.0, "value": 0.5, "threshold": 0.05,
            "window_secs": 60.0, "bundle": None}


def test_serve_top_alert_badges():
    import serve_top as st

    rep = {"requests": 5, "tokens_generated": 10, "histograms": {},
           "alerts": {"firing": [_firing_entry()], "pending": []}}
    snap = st.build_snapshot("http://x", rep)
    assert snap["alerts"]["firing_count"] == 1
    assert snap["replicas"][0]["alert_rules"] == ["error_rate"]
    text = st.render(snap)
    assert "ALERT[1]" in text and "error_rate" in text
    # router doc: replica-merged + supervisor fleet blocks both surface
    doc = {"router": {"router_id": "r0", "brownout_active": False,
                      "backends": {"b0": {"url": "u", "alive": 1}},
                      "fleet": {"alerts": {
                          "firing": [_firing_entry("ttft_burn", "fleet")]}}},
           "aggregate": {"alerts": {"firing": [_firing_entry()]}},
           "backends": {"b0": rep}}
    snap = st.build_snapshot("http://r", doc)
    assert snap["alerts"]["firing_count"] == 2
    assert "ALERT[2]" in st.render(snap)
    # quiet fleet: no badge
    assert "ALERT" not in st.render(
        st.build_snapshot("http://x", {"requests": 1, "histograms": {}}))


def test_serve_report_incident_timeline(tmp_path):
    import serve_report as sr

    recs = [
        {"kind": "serve", "event": "request_done", "e2e_secs": 0.5,
         "ttft_secs": 0.1, "tpot_secs": 0.01, "time_unix": 100.0,
         "finish_reason": "stop"},
        {"kind": "serve", "event": "alert_transition", "schema": 13,
         "rule": "error_rate", "scope": "replica", "state": "firing",
         "severity": "page", "value": 0.5, "threshold": 0.05,
         "window_secs": 60.0, "since_unix": 101.0, "time_unix": 101.0,
         "bundle": "/logs/incidents/error_rate-0001"},
        {"kind": "serve", "event": "engine_restart", "reason": "watchdog",
         "requeued": 2, "failed": 0, "time_unix": 103.0},
        {"kind": "fleet", "event": "replica_died", "slot": 0,
         "time_unix": 104.0},
        {"kind": "serve", "event": "alert_transition", "schema": 13,
         "rule": "error_rate", "scope": "replica", "state": "resolved",
         "severity": "page", "value": 0.0, "threshold": 0.05,
         "window_secs": 60.0, "since_unix": 101.0, "time_unix": 140.0,
         "bundle": None},
    ]
    (tmp_path / "telemetry.jsonl").write_text(
        "".join(json.dumps(r) + "\n" for r in recs))
    report = sr.analyze([str(tmp_path)])
    inc = report["incidents"]
    assert inc["transitions"] == {"pending": 0, "firing": 1, "resolved": 1}
    assert inc["unresolved"] == 0
    (incident,) = inc["incidents"]
    assert incident["duration_secs"] == pytest.approx(39.0)
    assert incident["bundle"] == "/logs/incidents/error_rate-0001"
    correlated = {e["event"] for e in incident["correlated"]}
    assert {"engine_restart", "replica_died"} <= correlated
    text = sr.render(report)
    assert "incidents: 1" in text
    assert "error_rate@replica" in text
    assert "engine_restart" in text


def test_serve_bench_slo_gate_exit_code(tmp_path):
    """--slo_gate turns attainment into exit code 3 (distinct from 1 =
    request errors) without touching the happy-path exit codes."""
    import serve_bench as sb

    rows_good = {"slo_joint_attainment": 0.99}
    rows_bad = {"slo_joint_attainment": 0.5}
    # gate arithmetic via the documented JSON keys
    assert set(("ttft_slo_secs", "tpot_slo_secs", "slo_joint_attainment",
                "slo_gate")) <= set(sb.JSON_SCHEMA_KEYS)

    # run_bench against a dead URL: every request errors, attainment 0
    r = sb.run_bench("http://127.0.0.1:1", clients=1, requests=2,
                     tokens=1, timeout=0.2)
    assert r["errors"] == 2
    assert r["slo_joint_attainment"] == 0.0
    assert r["ttft_slo_secs"] == 1.0 and r["tpot_slo_secs"] == 0.25
    rc = sb.main(["--url", "http://127.0.0.1:1", "--clients", "1",
                  "--requests", "1", "--timeout", "0.2", "--json",
                  "--slo_gate", "0.9"])
    assert rc == 3
    rc = sb.main(["--url", "http://127.0.0.1:1", "--clients", "1",
                  "--requests", "1", "--timeout", "0.2", "--json"])
    assert rc == 1
    del rows_good, rows_bad


# ---------------------------------------------------------------------------
# slow tier: chaos e2e + overhead gate
# ---------------------------------------------------------------------------

CHAOS_RULES = json.dumps({
    "interval_secs": 0.25,
    "rules": [{"name": "error_rate", "kind": "rate",
               "num_path": "errors", "den_path": "requests",
               "window_secs": 3.0, "op": ">=", "value": 0.02,
               "min_den": 1, "for_secs": 0.0, "clear_secs": 3.0,
               "severity": "page"}],
})


def _spawn_replica(extra_args=(), timeout=180.0):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    proc = subprocess.Popen(
        [sys.executable,
         os.path.join(os.path.dirname(__file__), "_serve_replica.py"),
         *extra_args],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=env,
        text=True, cwd=os.path.dirname(os.path.dirname(__file__)))
    deadline = time.monotonic() + timeout
    port = None
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if line.startswith("PORT "):
            port = int(line.split()[1])
            break
        if proc.poll() is not None:
            raise RuntimeError("replica died during startup")
    assert port, "replica did not report a port in time"
    return proc, port


def _get_json(url, timeout=10.0):
    req = urllib.request.Request(url, headers={"Accept": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def _generate(url, prompt, tokens=8, timeout=120.0):
    req = urllib.request.Request(
        url + "/api",
        data=json.dumps({"prompts": [prompt], "tokens_to_generate": tokens,
                         "temperature": 0.0, "no_log": True}).encode(),
        method="PUT")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status
    except urllib.error.HTTPError as e:
        e.read()
        return e.code


def _wait(predicate, deadline_secs, what):
    deadline = time.monotonic() + deadline_secs
    while time.monotonic() < deadline:
        v = predicate()
        if v:
            return v
        time.sleep(0.25)
    raise AssertionError(f"timed out waiting for {what}")


@pytest.mark.slow
@pytest.mark.chaos
def test_alert_chaos_two_replica_fleet(tmp_path):
    """Acceptance e2e: nan@/hang@ faults on one replica of a 2-replica
    fleet drive exactly one firing -> resolved incident whose state
    agrees across the replica /metrics, the router's fleet merge, the
    schema-13 JSONL, and serve_top; the postmortem bundle is readable
    on disk; serve_report renders the incident correlated with the
    watchdog engine restart."""
    from megatron_llm_tpu.serving.router import ReplicaRouter, RouterServer
    import serve_top as st
    import serve_report as sr

    log_a = tmp_path / "ra"
    log_b = tmp_path / "rb"
    # replica A: one poisoned dispatch (-> one structured 500) plus one
    # watchdog-length hang (-> one engine restart in the log); alerts on
    pa, port_a = _spawn_replica([
        "--serve_alerts", "1", "--alert_rules", CHAOS_RULES,
        "--structured_log_dir", str(log_a),
        "--serve_fault_inject", "nan@30,hang@60:30",
        "--serve_watchdog_secs", "2.0"])
    pb, port_b = _spawn_replica([
        "--serve_alerts", "1", "--alert_rules", CHAOS_RULES,
        "--structured_log_dir", str(log_b)])
    url_a = f"http://127.0.0.1:{port_a}"
    url_b = f"http://127.0.0.1:{port_b}"
    router = ReplicaRouter([url_a, url_b], fail_threshold=10,
                           cooldown_secs=1.0, health_interval_secs=0.5,
                           request_timeout_secs=120.0)
    srv = RouterServer(router)
    threading.Thread(target=srv.run,
                     kwargs={"host": "127.0.0.1", "port": 0},
                     daemon=True).start()
    try:
        for _ in range(100):
            if srv.httpd is not None:
                break
            time.sleep(0.05)
        router_url = f"http://127.0.0.1:{srv.httpd.server_address[1]}"

        # drive replica A until the poisoned dispatch surfaces as a 500
        def drive_until_error():
            for i in range(8):
                if _generate(url_a, f"{i} 2 3 4") >= 500:
                    return True
            return _get_json(url_a + "/metrics").get("errors", 0) > 0

        assert _wait(drive_until_error, 120.0, "injected nan error")

        # 1) replica /metrics: the alert fires with a bundle on disk
        def replica_firing():
            snap = _get_json(url_a + "/metrics")
            firing = (snap.get("alerts") or {}).get("firing") or []
            return firing[0] if firing else None

        firing = _wait(replica_firing, 30.0, "replica alert firing")
        assert firing["rule"] == "error_rate"

        def bundle_ready():
            f = replica_firing()
            return f and f.get("bundle")

        bundle = _wait(bundle_ready, 15.0, "postmortem bundle path")
        assert os.path.isdir(bundle)
        man = json.load(open(os.path.join(bundle, "manifest.json")))
        assert {"transition", "metrics", "thread_stacks",
                "recent_requests"} <= set(man["parts"])
        stacks = open(os.path.join(bundle, "thread_stacks.txt")).read()
        assert "alert-eval" in stacks
        bundle_metrics = json.load(
            open(os.path.join(bundle, "metrics.json")))
        assert bundle_metrics.get("errors", 0) >= 1

        # 2) fleet merge: the router's aggregate carries the same alert
        #    keyed by the replica's URL
        def router_firing():
            doc = _get_json(router_url + "/metrics")
            firing = ((doc.get("aggregate") or {}).get("alerts")
                      or {}).get("firing") or []
            return [f for f in firing if f["rule"] == "error_rate"]

        merged = _wait(router_firing, 30.0, "fleet-merged alert")
        assert merged[0]["scope"] == url_a

        # 3) serve_top badge agrees (one frame, machine-readable)
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = st.main(["--url", router_url, "--once", "--json"])
        assert rc == 0
        frame = json.loads(buf.getvalue())
        assert frame["alerts"]["firing_count"] >= 1
        assert "error_rate" in {f["rule"]
                                for f in frame["alerts"]["firing"]}
        row_a = [r for r in frame["replicas"]
                 if r["url"] == url_a or (r["alive"] and r["alert_rules"])]
        assert any("error_rate" in r["alert_rules"] for r in row_a)

        # 4) healthy traffic pushes the error out of the window; the
        #    hang fires along the way and the watchdog restart heals it
        def drive_and_check_resolved():
            for i in range(4):
                _generate(url_a, f"9{i} 2 3 4")
            snap = _get_json(url_a + "/metrics")
            return not (snap.get("alerts") or {}).get("firing")

        _wait(drive_and_check_resolved, 120.0, "alert resolution")
        assert _get_json(url_a + "/metrics")["engine"][
            "engine_restarts"] >= 1
    finally:
        for proc in (pa, pb):
            proc.kill()
            proc.wait(timeout=30)
        router.stop()
        if srv.httpd is not None:
            srv.httpd.shutdown()

    # 5) JSONL: exactly one firing -> resolved cycle, schema 13
    lines = (log_a / "telemetry.jsonl").read_text().splitlines()
    trs = [json.loads(line) for line in lines
           if '"alert_transition"' in line]
    states = [t["state"] for t in trs if t["rule"] == "error_rate"]
    assert states == ["firing", "resolved"]
    assert all(t["schema"] == 13 and t["kind"] == "serve" for t in trs)
    assert trs[0]["bundle"] == bundle

    # 6) serve_report renders the incident, correlated with the restart
    report = sr.analyze([str(log_a)])
    inc = report["incidents"]
    assert inc["transitions"]["firing"] == 1
    assert inc["transitions"]["resolved"] == 1
    assert inc["unresolved"] == 0
    (incident,) = inc["incidents"]
    assert incident["rule"] == "error_rate"
    assert incident["bundle"] == bundle
    assert "engine_restart" in {e["event"]
                                for e in incident["correlated"]}
    text = sr.render(report)
    assert "incidents: 1" in text and "error_rate@replica" in text


@pytest.mark.slow
def test_alert_overhead_under_two_pct_of_dispatch():
    """Overhead gate: one full default-rule evaluation over a live
    engine's /metrics snapshot must cost < 2% of a measured dispatch —
    the sentinel may not become the incident it watches for."""
    import jax
    from megatron_llm_tpu.models.llama import LlamaModel, llama_config
    from megatron_llm_tpu.serving import (EngineConfig, InferenceEngine,
                                          SamplingParams)
    from megatron_llm_tpu.text_generation_server import ServerMetrics

    cfg = llama_config("tiny", num_layers=2, seq_length=64,
                       max_position_embeddings=64, padded_vocab_size=64,
                       use_flash_attn=False)
    model = LlamaModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    engine = InferenceEngine(model, params, EngineConfig(
        num_slots=4, block_size=8, prefill_chunk=16, max_model_len=64))
    engine.warmup()
    engine.start()
    metrics = ServerMetrics()
    metrics.engine_stats_fn = engine.stats
    engine.request_done_hook = metrics.observe_request_done
    sentinel = AlertEngine(metrics_fn=metrics.snapshot)
    metrics.alert_engine = sentinel
    try:
        reqs = [engine.submit([1 + i % 7, 2, 3],
                              SamplingParams(max_new_tokens=8,
                                             temperature=0.0, eod_id=63))
                for i in range(8)]
        for r in reqs:
            r.result(timeout=180)
        loop = engine.stats()["loop"]
        assert loop["dispatches"] > 0
        mean_dispatch = loop["wall_secs"] / loop["dispatches"]
        for _ in range(50):
            sentinel.evaluate()
        mean_eval = (sentinel.counters["eval_secs_total"]
                     / sentinel.counters["evaluations"])
    finally:
        engine.stop()
    assert mean_eval < 0.02 * mean_dispatch, (
        f"alert evaluation {mean_eval * 1e6:.1f}us vs dispatch "
        f"{mean_dispatch * 1e6:.1f}us: over the 2% budget")
