"""tools/profile_step.py writes a real xplane trace around the train step.

Beyond-reference capability (SURVEY.md §5.1: the reference has no
profiler integration); on CPU the trace carries the host plane, on TPU
the device plane as well — the tool and the assertion are
backend-agnostic.
"""

import glob
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_profile_step_writes_xplane(tmp_path):
    logdir = str(tmp_path / "trace")
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "profile_step.py"),
         "--preset", "tiny", "--logdir", logdir, "--steps", "2"],
        capture_output=True, text=True, timeout=600, cwd=ROOT,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "trace written" in r.stdout
    planes = glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                       recursive=True)
    assert planes, r.stdout
    assert os.path.getsize(planes[0]) > 0
