"""Sharded == unsharded parity for every model family and parallel layout.

This is the TPU-native upgrade of the reference's distributed unit tests
(which require 8 real GPUs): the same model params produce bit-identical
losses under (tp), (tp + sequence-parallel), (dp x tp) on the virtual CPU
mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from megatron_llm_tpu import topology
from megatron_llm_tpu.models import (
    FalconModel,
    GemmaModel,
    GPTModel,
    GPTNeoXModel,
    LlamaModel,
    MistralModel,
    Qwen2Model,
    falcon_config,
    gemma_config,
    gpt2_config,
    gpt_neox_config,
    llama_config,
    mistral_config,
    qwen2_config,
)
from megatron_llm_tpu.parallel import sharding as sh

CASES = [
    ("llama", LlamaModel, llama_config),
    ("gpt2", GPTModel, gpt2_config),
    ("falcon", FalconModel, falcon_config),
    ("mistral", MistralModel, mistral_config),
    ("qwen2", Qwen2Model, qwen2_config),
    ("gemma", GemmaModel, gemma_config),
    ("gpt_neox", GPTNeoXModel, gpt_neox_config),
]


@pytest.mark.parametrize("name,Model,cfg_fn", CASES, ids=[c[0] for c in CASES])
@pytest.mark.parametrize("tp,seq_par", [(4, False), (4, True), (2, True)])
def test_tp_parity(utils, name, Model, cfg_fn, tp, seq_par):
    cfg = cfg_fn("tiny", seq_length=32, max_position_embeddings=32)
    model = Model(cfg)
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, cfg.padded_vocab_size, (4, 32)))
    labels = jnp.roll(tokens, -1, axis=1)

    base = model(params, tokens, labels=labels, train=False)

    mesh = utils.initialize_model_parallel(tp=tp)
    ps = sh.shard_params(params, model.param_specs(params))
    dsh = NamedSharding(mesh, P("dp", None))
    t, l = jax.device_put(tokens, dsh), jax.device_put(labels, dsh)

    @jax.jit
    def f(p, t, l):
        return model(p, t, labels=l, train=False, sequence_parallel=seq_par)

    out = f(ps, t, l)
    np.testing.assert_allclose(np.asarray(out), np.asarray(base), atol=2e-5)


def test_grad_parity_tp_sp(utils):
    """Gradients must also match between sharded and unsharded execution."""
    cfg = llama_config("tiny", seq_length=32, max_position_embeddings=32)
    model = LlamaModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(1)
    tokens = jnp.asarray(rng.randint(0, cfg.padded_vocab_size, (4, 32)))
    labels = jnp.roll(tokens, -1, axis=1)

    def loss(p, t, l, seq_par):
        return model(p, t, labels=l, train=False, sequence_parallel=seq_par).mean()

    g_base = jax.grad(loss)(params, tokens, labels, False)

    mesh = utils.initialize_model_parallel(tp=4)
    ps = sh.shard_params(params, model.param_specs(params))
    dsh = NamedSharding(mesh, P("dp", None))
    g_shard = jax.jit(jax.grad(loss), static_argnums=3)(
        ps, jax.device_put(tokens, dsh), jax.device_put(labels, dsh), True
    )
    for (pa, a), (pb, b) in zip(
        jax.tree_util.tree_flatten_with_path(g_base)[0][:6],
        jax.tree_util.tree_flatten_with_path(g_shard)[0][:6],
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-5,
                                   err_msg=str(pa))


def test_tp_parity_with_pallas_flash(utils):
    """Model-level tp+sp parity with the PALLAS flash kernel engaged
    (interpret mode): exercises the transformer dispatch ->
    sharded_flash_attention -> nested shard_map integration that the
    op-level tests cover in isolation.  num_attention_heads (and the
    GQA kv groups) must divide tp or the wrapper demotes to the XLA
    fallback — a spy asserts the pallas shard_map leg actually ran."""
    import megatron_llm_tpu.ops.pallas.flash_attention as F

    cfg = llama_config("tiny", num_attention_heads_kv=2,
                       seq_length=64, max_position_embeddings=64,
                       padded_vocab_size=128, use_flash_attn=True)
    model = LlamaModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(
        rng.randint(0, cfg.padded_vocab_size, (4, 64)))
    labels = jnp.roll(tokens, -1, axis=1)

    flash_calls = []
    real_flash = F.flash_attention

    def spy(*a, **kw):
        flash_calls.append(a[0].shape)
        return real_flash(*a, **kw)

    F._INTERPRET = True
    F.flash_attention = spy
    try:
        base = model(params, tokens, labels=labels, train=False)

        mesh = utils.initialize_model_parallel(tp=2)
        ps = sh.shard_params(params, model.param_specs(params))
        dsh = NamedSharding(mesh, P("dp", None))
        t, l = jax.device_put(tokens, dsh), jax.device_put(labels, dsh)

        out = jax.jit(lambda p, t, l: model(
            p, t, labels=l, train=False, sequence_parallel=True))(ps, t, l)
    finally:
        F._INTERPRET = False
        F.flash_attention = real_flash
    # the sharded run must have reached the pallas kernel with LOCAL
    # shapes (heads/tp), not the XLA fallback
    assert any(shape[2] == cfg.num_attention_heads // 2
               for shape in flash_calls), flash_calls
    np.testing.assert_allclose(np.asarray(out), np.asarray(base),
                               atol=2e-5)
