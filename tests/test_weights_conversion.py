"""Golden-model conversion tests.

Reference: ``tests/test_llama_weights.py`` — converts Meta/HF weights,
runs verify_correctness (mean max-abs logit error <= 1e-3 vs HF), reshards,
converts back.  Here the golden model is a small *random-init* HF model
(no network / no 7B download in CI), which exercises the identical layout
transforms.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from megatron_llm_tpu.config import TransformerConfig  # noqa: E402
from megatron_llm_tpu.models.llama import LlamaModel  # noqa: E402
from megatron_llm_tpu.models.mistral import MistralModel  # noqa: E402
from weights_conversion.hf_to_megatron import (  # noqa: E402
    convert_falcon,
    convert_llama_family,
)
from weights_conversion.megatron_to_hf import (  # noqa: E402
    falcon_state_dict,
    hf_config_for,
    llama_family_state_dict,
)
from weights_conversion.util import (  # noqa: E402
    pack_qkv,
    rotary_hf_to_interleaved,
    rotary_interleaved_to_hf,
    unpack_qkv,
)


def _tiny_llama_cfg(**kw):
    from transformers import LlamaConfig

    base = dict(vocab_size=128, hidden_size=64, intermediate_size=176,
                num_hidden_layers=2, num_attention_heads=4,
                num_key_value_heads=2, max_position_embeddings=64,
                rms_norm_eps=1e-5, tie_word_embeddings=False)
    base.update(kw)
    return LlamaConfig(**base)


def test_rotary_permutation_roundtrip():
    w = np.random.RandomState(0).randn(4 * 8, 16).astype(np.float32)
    np.testing.assert_array_equal(
        rotary_interleaved_to_hf(rotary_hf_to_interleaved(w, 8), 8), w
    )


def test_qkv_pack_roundtrip():
    rng = np.random.RandomState(1)
    nh, ng, d, hid = 8, 2, 4, 16
    q = rng.randn(nh * d, hid).astype(np.float32)
    k = rng.randn(ng * d, hid).astype(np.float32)
    v = rng.randn(ng * d, hid).astype(np.float32)
    q2, k2, v2 = unpack_qkv(pack_qkv(q, k, v, nh, ng, d), nh, ng, d)
    np.testing.assert_array_equal(q, q2)
    np.testing.assert_array_equal(k, k2)
    np.testing.assert_array_equal(v, v2)


def test_hf_llama_logit_parity():
    """The core golden test: converted weights reproduce HF logits
    (reference tolerance 1e-3; we hold 1e-5 at fp32)."""
    from transformers import LlamaForCausalLM

    torch.manual_seed(0)
    hf = LlamaForCausalLM(_tiny_llama_cfg()).eval()
    params, config = convert_llama_family(hf)
    cfg = TransformerConfig(**config, use_flash_attn=False)
    model = LlamaModel(cfg)

    toks = np.random.RandomState(0).randint(0, 128, (2, 16))
    with torch.no_grad():
        hf_logits = hf(torch.tensor(toks)).logits.numpy()
    my_logits = np.asarray(model(params, jnp.asarray(toks), train=False))
    assert np.abs(hf_logits - my_logits).max() < 1e-5


def test_hf_llama3_logit_parity_rope_scaling():
    """Llama-3.1-style checkpoint: GQA + theta 5e5 + the llama3
    NTK-by-parts rope remap.  The converted config must carry
    rope_llama3_scaling and reproduce HF logits (which exercises
    ops.rope.llama3_scale_freqs against HF's
    _compute_llama3_parameters)."""
    from transformers import LlamaForCausalLM

    torch.manual_seed(0)
    hf_cfg = _tiny_llama_cfg(
        rope_theta=500000.0, max_position_embeddings=128,
        rope_scaling={"rope_type": "llama3", "factor": 8.0,
                      "low_freq_factor": 1.0, "high_freq_factor": 4.0,
                      "original_max_position_embeddings": 32})
    hf = LlamaForCausalLM(hf_cfg).eval()
    params, config = convert_llama_family(hf)
    assert config["rope_theta"] == 500000.0
    assert config["rope_llama3_scaling"] == (8.0, 1.0, 4.0, 32)
    cfg = TransformerConfig(**config, use_flash_attn=False)
    model = LlamaModel(cfg)

    # positions past original_max (32) are exactly where the remap bites
    toks = np.random.RandomState(0).randint(0, 128, (2, 96))
    with torch.no_grad():
        hf_logits = hf(torch.tensor(toks)).logits.numpy()
    my_logits = np.asarray(model(params, jnp.asarray(toks), train=False))
    assert np.abs(hf_logits - my_logits).max() < 2e-5

    # round trip: the regenerated HF config carries the same rope_scaling
    hf_cfg2 = hf_config_for("llama3", config)
    assert hf_cfg2.rope_scaling["rope_type"] == "llama3"
    assert hf_cfg2.rope_scaling["factor"] == 8.0
    assert hf_cfg2.rope_theta == 500000.0
    sd_back = llama_family_state_dict(params, config)
    sd_orig = hf.state_dict()
    for k, v in sd_back.items():
        np.testing.assert_allclose(
            v.numpy(), sd_orig[k].numpy(), atol=1e-6, err_msg=k)


def test_hf_mistral_logit_parity_sliding_window():
    from transformers import MistralConfig, MistralForCausalLM

    torch.manual_seed(0)
    hf_cfg = MistralConfig(
        vocab_size=128, hidden_size=64, intermediate_size=176,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, sliding_window=8,
        tie_word_embeddings=False,
    )
    hf = MistralForCausalLM(hf_cfg).eval()
    params, config = convert_llama_family(hf)
    config["sliding_window_size"] = 8
    cfg = TransformerConfig(**config, use_flash_attn=False)

    class _M(MistralModel):
        def __init__(self, cfg):
            # bypass the ==4096 assert for the tiny window
            from megatron_llm_tpu.models.gpt import GPTModel

            GPTModel.__init__(self, cfg)

    model = _M(cfg)
    # sequence long enough that the window matters
    toks = np.random.RandomState(0).randint(0, 128, (1, 32))
    with torch.no_grad():
        hf_logits = hf(torch.tensor(toks)).logits.numpy()
    my_logits = np.asarray(model(params, jnp.asarray(toks), train=False))
    assert np.abs(hf_logits - my_logits).max() < 1e-4


def test_falcon_logit_parity():
    from transformers import FalconConfig, FalconForCausalLM

    torch.manual_seed(0)
    hf_cfg = FalconConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_kv_heads=2, multi_query=True,
        new_decoder_architecture=True, parallel_attn=True, bias=False,
        max_position_embeddings=64, tie_word_embeddings=True,
        alibi=False,
    )
    hf = FalconForCausalLM(hf_cfg).eval()
    params, config = convert_falcon(hf)
    from megatron_llm_tpu.models.falcon import FalconModel

    cfg = TransformerConfig(**config, use_flash_attn=False,
                            seq_length=64, max_position_embeddings=64)
    model = FalconModel(cfg)
    toks = np.random.RandomState(0).randint(0, 128, (2, 16))
    with torch.no_grad():
        hf_logits = hf(torch.tensor(toks)).logits.numpy()
    my_logits = np.asarray(model(params, jnp.asarray(toks), train=False))
    assert np.abs(hf_logits - my_logits).max() < 1e-4


def test_megatron_to_hf_roundtrip():
    """HF -> TPU -> HF round trip preserves every tensor exactly."""
    from transformers import LlamaForCausalLM

    torch.manual_seed(0)
    hf = LlamaForCausalLM(_tiny_llama_cfg()).eval()
    params, config = convert_llama_family(hf)
    sd_back = llama_family_state_dict(params, config)
    sd_orig = hf.state_dict()
    for k, v in sd_back.items():
        np.testing.assert_allclose(
            v.numpy(), sd_orig[k].numpy(), atol=1e-6, err_msg=k
        )

    hf_cfg2 = hf_config_for("llama2", config)
    assert hf_cfg2.num_key_value_heads == 2


def test_falcon_to_hf_roundtrip():
    """HF falcon -> TPU -> HF preserves every tensor exactly, and the
    regenerated HF config reloads the state dict cleanly (reference
    write_falcon_model, megatron_to_hf.py:333-475)."""
    from transformers import FalconConfig, FalconForCausalLM

    torch.manual_seed(0)
    hf_cfg = FalconConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_kv_heads=2, multi_query=True,
        new_decoder_architecture=True, parallel_attn=True, bias=False,
        max_position_embeddings=64, tie_word_embeddings=True, alibi=False,
    )
    hf = FalconForCausalLM(hf_cfg).eval()
    params, config = convert_falcon(hf)
    sd_back = falcon_state_dict(params, config)
    sd_orig = hf.state_dict()
    for k, v in sd_back.items():
        if k == "lm_head.weight" and k not in sd_orig:
            continue                   # tied head may be absent from sd
        np.testing.assert_allclose(
            v.numpy(), sd_orig[k].numpy(), atol=1e-6, err_msg=k)

    hf_cfg2 = hf_config_for("falcon", config)
    assert hf_cfg2.new_decoder_architecture
    assert hf_cfg2.num_kv_heads == 2
    hf2 = FalconForCausalLM(hf_cfg2)
    missing, unexpected = hf2.load_state_dict(sd_back, strict=False)
    assert not unexpected
    toks = torch.tensor(np.random.RandomState(0).randint(0, 128, (1, 16)))
    with torch.no_grad():
        np.testing.assert_allclose(hf2(toks).logits.numpy(),
                                   hf(toks).logits.numpy(), atol=1e-5)


def test_checkpoint_reshard_roundtrip(tmp_path, utils):
    """Save under one mesh, load under another (reference: reshard
    tp=2,pp=2 and back, test_llama_weights.py:181-192)."""
    from megatron_llm_tpu import checkpointing
    from megatron_llm_tpu.models.llama import llama_config
    from megatron_llm_tpu.parallel import sharding as sh

    cfg = llama_config("tiny", num_layers=4, seq_length=32,
                       max_position_embeddings=32, padded_vocab_size=128)
    model = LlamaModel(cfg)
    params = model.init(jax.random.PRNGKey(0))

    utils.initialize_model_parallel(tp=4, pp=1)
    p_tp4 = sh.shard_params(params, model.param_specs(params))
    checkpointing.save_checkpoint(str(tmp_path), 5, p_tp4)

    utils.initialize_model_parallel(tp=2, pp=2)
    loaded, _, meta = checkpointing.load_checkpoint(str(tmp_path))
    p_tp2 = sh.shard_params(loaded, model.param_specs(loaded))
    assert meta["iteration"] == 5
    for a, b in zip(jax.tree_util.tree_leaves(params),
                    jax.tree_util.tree_leaves(p_tp2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_hf_mixtral_logit_parity_and_roundtrip():
    """Mixtral (sparse MoE): converted weights reproduce HF logits, and the
    inverse writer round-trips back to an identical HF model.  Capacity is
    oversized so our capacity-style routing matches HF's dropless top-2."""
    from transformers import MixtralConfig, MixtralForCausalLM

    from megatron_llm_tpu.models.mixtral import MixtralModel
    from weights_conversion.hf_to_megatron import convert_mixtral
    from weights_conversion.megatron_to_hf import mixtral_state_dict

    torch.manual_seed(0)
    hf_cfg = MixtralConfig(
        vocab_size=128, hidden_size=64, intermediate_size=176,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2,
        max_position_embeddings=64, sliding_window=None,
        tie_word_embeddings=False,
    )
    hf = MixtralForCausalLM(hf_cfg).eval()
    params, config = convert_mixtral(hf)
    # the converted config must itself carry dropless capacity (E/top_k)
    assert config["moe_capacity_factor"] == 2.0
    cfg = TransformerConfig(**config, use_flash_attn=False)
    model = MixtralModel(cfg)

    toks = np.random.RandomState(0).randint(0, 128, (2, 16))
    with torch.no_grad():
        hf_logits = hf(torch.tensor(toks)).logits.numpy()
    my_logits = np.asarray(model(params, jnp.asarray(toks), train=False))
    assert np.abs(hf_logits - my_logits).max() < 1e-4

    # inverse writer round trip: exported HF model reproduces the source
    sd = mixtral_state_dict(params, config)
    hf2 = MixtralForCausalLM(hf_cfg).eval()
    missing, unexpected = hf2.load_state_dict(sd, strict=False)
    assert not unexpected, unexpected
    with torch.no_grad():
        rt_logits = hf2(torch.tensor(toks)).logits.numpy()
    np.testing.assert_allclose(rt_logits, hf_logits, atol=1e-5)


def test_hf_qwen2_logit_parity():
    """Qwen2 golden test: QKV-bias packing (incl. the rotary bias
    permutation) reproduces HF logits exactly."""
    from transformers import Qwen2Config, Qwen2ForCausalLM

    from megatron_llm_tpu.models.qwen2 import Qwen2Model
    from weights_conversion.hf_to_megatron import convert_qwen2

    torch.manual_seed(0)
    hf_cfg = Qwen2Config(
        vocab_size=128, hidden_size=64, intermediate_size=176,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rms_norm_eps=1e-6,
        tie_word_embeddings=False, rope_theta=1e6,
    )
    hf = Qwen2ForCausalLM(hf_cfg).eval()
    params, config = convert_qwen2(hf)
    assert config["add_qkv_bias"] is True
    assert config["sliding_window_size"] is None
    cfg = TransformerConfig(**config, use_flash_attn=False)
    model = Qwen2Model(cfg)
    # the packed QKV carries a bias, nothing else does
    layers = params["transformer"]["layers"]
    assert "bias" in layers["attention"]["query_key_value"]
    assert "bias" not in layers["attention"]["dense"]
    assert "bias" not in layers["mlp"]["dense_h_to_4h"]

    toks = np.random.RandomState(0).randint(0, 128, (2, 16))
    with torch.no_grad():
        hf_logits = hf(torch.tensor(toks)).logits.numpy()
    my_logits = np.asarray(model(params, jnp.asarray(toks), train=False))
    assert np.abs(hf_logits - my_logits).max() < 1e-5


def test_qwen2_fresh_init_matches_converted_structure():
    """A fresh qwen2_config init has the same pytree structure as the
    HF conversion (so checkpoints/optimizers line up)."""
    import jax

    from megatron_llm_tpu.models.qwen2 import Qwen2Model, qwen2_config

    cfg = qwen2_config("tiny", seq_length=32, max_position_embeddings=32)
    model = Qwen2Model(cfg)
    params = model.init(jax.random.PRNGKey(0))
    qkv = params["transformer"]["layers"]["attention"]["query_key_value"]
    assert "bias" in qkv and qkv["bias"].shape[-1] == qkv["kernel"].shape[-1]
    assert "bias" not in params["transformer"]["layers"]["mlp"]["dense_h_to_4h"]


def test_hf_qwen2_tied_embeddings_conversion():
    """Tied Qwen2 (0.5B-style) converts WITHOUT an lm_head leaf, matching
    the tied fresh-init structure, and still reproduces HF logits."""
    import jax

    from transformers import Qwen2Config, Qwen2ForCausalLM

    from megatron_llm_tpu.models.qwen2 import Qwen2Model, qwen2_config
    from weights_conversion.hf_to_megatron import convert_qwen2

    torch.manual_seed(1)
    hf_cfg = Qwen2Config(
        vocab_size=128, hidden_size=64, intermediate_size=176,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rms_norm_eps=1e-6,
        tie_word_embeddings=True, rope_theta=1e6,
    )
    hf = Qwen2ForCausalLM(hf_cfg).eval()
    params, config = convert_qwen2(hf)
    assert "lm_head" not in params
    assert config["tie_embed_logits"] is True
    cfg = TransformerConfig(**config, use_flash_attn=False)
    model = Qwen2Model(cfg)
    # structure identical to a tied fresh init
    fresh = Qwen2Model(qwen2_config(
        "tiny", num_layers=2, hidden_size=64, num_attention_heads=4,
        num_attention_heads_kv=2, ffn_hidden_size=176,
        padded_vocab_size=128, seq_length=64, max_position_embeddings=64,
        tie_embed_logits=True)).init(jax.random.PRNGKey(0))
    import jax.tree_util as jtu

    assert (jtu.tree_structure(params) == jtu.tree_structure(fresh))

    toks = np.random.RandomState(0).randint(0, 128, (2, 16))
    with torch.no_grad():
        hf_logits = hf(torch.tensor(toks)).logits.numpy()
    my_logits = np.asarray(model(params, jnp.asarray(toks), train=False))
    assert np.abs(hf_logits - my_logits).max() < 1e-5


def test_qwen2_hf_export_round_trip(tmp_path):
    """ours -> HF state dict (with QKV biases) -> back through
    convert_qwen2: logits identical."""
    import jax

    from transformers import Qwen2ForCausalLM

    from megatron_llm_tpu.models.qwen2 import Qwen2Model, qwen2_config
    from weights_conversion.hf_to_megatron import convert_qwen2
    from weights_conversion.megatron_to_hf import (
        hf_config_for,
        llama_family_state_dict,
    )
    from megatron_llm_tpu.checkpointing import config_to_args

    cfg = qwen2_config("tiny", num_layers=2, hidden_size=64,
                       num_attention_heads=4, num_attention_heads_kv=2,
                       ffn_hidden_size=176, padded_vocab_size=128,
                       seq_length=64, max_position_embeddings=64,
                       use_flash_attn=False)
    model = Qwen2Model(cfg)
    params = model.init(jax.random.PRNGKey(3))
    conf = config_to_args(cfg)

    hf_cfg = hf_config_for("qwen2", conf)
    hf = Qwen2ForCausalLM(hf_cfg).eval()
    sd = llama_family_state_dict(params, conf)
    missing, unexpected = hf.load_state_dict(sd, strict=False)
    assert not [m for m in missing if "rotary" not in m], missing
    assert not unexpected, unexpected

    back, _ = convert_qwen2(hf)
    toks = np.random.RandomState(0).randint(0, 128, (1, 16))
    a = np.asarray(model(params, jnp.asarray(toks), train=False))
    b = np.asarray(model(back, jnp.asarray(toks), train=False))
    assert np.abs(a - b).max() < 1e-5


def test_hf_gemma_logit_parity():
    """Gemma golden test: 1+w norm folding, sqrt(hidden) embedding
    multiplier, GeGLU, decoupled head_dim (d != hidden/heads), MQA, tied
    head — all reproduce HF logits."""
    import jax

    from transformers import GemmaConfig, GemmaForCausalLM

    from megatron_llm_tpu.models.gemma import GemmaModel
    from weights_conversion.hf_to_megatron import convert_gemma

    torch.manual_seed(0)
    hf_cfg = GemmaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=176,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=1,
        head_dim=32,  # != hidden/heads = 16: the decoupled case
        max_position_embeddings=64, rms_norm_eps=1e-6,
        hidden_act="gelu_pytorch_tanh",
    )
    hf = GemmaForCausalLM(hf_cfg).eval()
    params, config = convert_gemma(hf)
    assert "lm_head" not in params          # tied
    assert config["kv_channels"] == 32
    assert abs(config["embedding_multiplier"] - 8.0) < 1e-9
    cfg = TransformerConfig(**config, use_flash_attn=False)
    model = GemmaModel(cfg)

    toks = np.random.RandomState(0).randint(0, 256, (2, 16))
    with torch.no_grad():
        hf_logits = hf(torch.tensor(toks)).logits.numpy()
    my_logits = np.asarray(model(params, jnp.asarray(toks), train=False))
    assert np.abs(hf_logits - my_logits).max() < 2e-5


def test_gemma_hf_export_round_trip():
    """ours -> HF (norm scales re-centered to 0) -> back: logits equal."""
    import jax

    from transformers import GemmaForCausalLM

    from megatron_llm_tpu.models.gemma import GemmaModel, gemma_config
    from megatron_llm_tpu.checkpointing import config_to_args
    from weights_conversion.hf_to_megatron import convert_gemma
    from weights_conversion.megatron_to_hf import (
        gemma_state_dict,
        hf_config_for,
    )

    cfg = gemma_config("tiny", seq_length=64, max_position_embeddings=64,
                       use_flash_attn=False)
    model = GemmaModel(cfg)
    params = model.init(jax.random.PRNGKey(5))
    conf = config_to_args(cfg)

    hf = GemmaForCausalLM(hf_config_for("gemma", conf)).eval()
    missing, unexpected = hf.load_state_dict(
        gemma_state_dict(params, conf), strict=False)
    assert not unexpected, unexpected

    back, _ = convert_gemma(hf)
    toks = np.random.RandomState(0).randint(0, 256, (1, 16))
    a = np.asarray(model(params, jnp.asarray(toks), train=False))
    b = np.asarray(model(back, jnp.asarray(toks), train=False))
    assert np.abs(a - b).max() < 2e-5


def test_hf_gpt_neox_logit_parity():
    """Pythia/GPT-NeoX golden test: per-head [nh,3,d] QKV packing with
    the rotate-half -> interleaved permutation on the PARTIAL rotary dims
    (rotary_pct=0.25), parallel residual with separate MLP norm,
    LayerNorm biases, exact gelu."""
    import jax

    from transformers import GPTNeoXConfig, GPTNeoXForCausalLM

    from megatron_llm_tpu.models.gpt_neox import GPTNeoXModel
    from weights_conversion.hf_to_megatron import convert_gpt_neox

    torch.manual_seed(0)
    hf_cfg = GPTNeoXConfig(
        vocab_size=256, hidden_size=64, intermediate_size=256,
        num_hidden_layers=2, num_attention_heads=4,
        max_position_embeddings=64, rotary_pct=0.25,
        use_parallel_residual=True, layer_norm_eps=1e-5,
        hidden_act="gelu",
    )
    hf = GPTNeoXForCausalLM(hf_cfg).eval()
    params, config = convert_gpt_neox(hf)
    assert config["rotary_percent"] == 0.25
    layers = params["transformer"]["layers"]
    assert "bias" in layers["attention"]["query_key_value"]
    assert "bias" in layers["mlp"]["dense_h_to_4h"]
    assert "mlp_norm" in layers
    cfg = TransformerConfig(**config, use_flash_attn=False)
    model = GPTNeoXModel(cfg)

    toks = np.random.RandomState(0).randint(0, 256, (2, 16))
    with torch.no_grad():
        hf_logits = hf(torch.tensor(toks)).logits.numpy()
    my_logits = np.asarray(model(params, jnp.asarray(toks), train=False))
    assert np.abs(hf_logits - my_logits).max() < 2e-5
