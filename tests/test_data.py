"""Data pipeline tests: mmap round-trip, merge, packing index math (native
vs python fallback), blending, samplers with exact resume, GPT dataset
end-to-end, instruction collator masks."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from megatron_llm_tpu.data import helpers
from megatron_llm_tpu.data.blendable_dataset import BlendableDataset
from megatron_llm_tpu.data.data_samplers import (
    MegatronPretrainingRandomSampler,
    MegatronPretrainingSampler,
    build_pretraining_data_loader,
)
from megatron_llm_tpu.data.gpt_dataset import (
    GPTDataset,
    get_train_valid_test_split_,
)
from megatron_llm_tpu.data.indexed_dataset import (
    MMapIndexedDataset,
    MMapIndexedDatasetBuilder,
    best_fitting_dtype,
)
from megatron_llm_tpu.data.instruction_dataset import (
    ROLE_ASSISTANT,
    ROLE_PAD,
    ROLE_USER,
    instruction_collator,
)


def _write_dataset(tmp_path, docs, dtype=np.int32, name="ds"):
    prefix = str(tmp_path / name)
    b = MMapIndexedDatasetBuilder(prefix + ".bin", dtype=dtype)
    for d in docs:
        b.add_item(d)
        b.end_document()
    b.finalize(prefix + ".idx")
    return prefix


def test_mmap_roundtrip(tmp_path):
    docs = [np.arange(10), np.arange(5) + 100, np.asarray([7])]
    prefix = _write_dataset(tmp_path, docs)
    ds = MMapIndexedDataset(prefix)
    assert len(ds) == 3
    np.testing.assert_array_equal(ds[0], docs[0])
    np.testing.assert_array_equal(ds[1], docs[1])
    np.testing.assert_array_equal(ds[2], docs[2])
    np.testing.assert_array_equal(ds.get(0, offset=2, length=3), [2, 3, 4])
    np.testing.assert_array_equal(ds.doc_idx, [0, 1, 2, 3])


def test_mmap_merge(tmp_path):
    p1 = _write_dataset(tmp_path, [np.arange(4)], name="a")
    p2 = _write_dataset(tmp_path, [np.arange(3) + 50, np.arange(2)], name="b")
    out = str(tmp_path / "merged")
    b = MMapIndexedDatasetBuilder(out + ".bin", dtype=np.int32)
    b.merge_file_(p1)
    b.merge_file_(p2)
    b.finalize(out + ".idx")
    ds = MMapIndexedDataset(out)
    assert len(ds) == 3
    np.testing.assert_array_equal(ds[1], np.arange(3) + 50)
    np.testing.assert_array_equal(ds.doc_idx, [0, 1, 2, 3])


def test_best_fitting_dtype():
    assert best_fitting_dtype(32000) == np.uint16
    assert best_fitting_dtype(100000) == np.int32


def test_build_sample_idx_native_matches_python():
    rng = np.random.RandomState(0)
    sizes = rng.randint(5, 50, size=200).astype(np.int32)
    doc_idx = np.arange(200, dtype=np.int64)
    rng.shuffle(doc_idx)
    seq = 32
    n = (int(sizes.sum()) - 1) // seq - 1
    out_py = helpers._build_sample_idx_py(sizes, doc_idx, seq, n)
    out = helpers.build_sample_idx(sizes, doc_idx, seq, n)
    np.testing.assert_array_equal(out, out_py)
    if helpers.using_native():
        assert True  # native path exercised


def test_gpt_dataset_packing(tmp_path):
    rng = np.random.RandomState(1)
    docs = [rng.randint(0, 100, size=rng.randint(5, 40)) for _ in range(50)]
    prefix = _write_dataset(tmp_path, docs)
    ds = MMapIndexedDataset(prefix)
    g = GPTDataset("train", prefix, np.arange(50), ds, num_samples=20,
                   seq_length=16, seed=0)
    assert len(g) == 20
    # every sample is seq+1 tokens and consecutive samples overlap by 1 in
    # the underlying stream (label/input shift)
    for i in range(20):
        assert g[i]["text"].shape == (17,)
    # deterministic across re-instantiation (cache)
    g2 = GPTDataset("train", prefix, np.arange(50), ds, num_samples=20,
                    seq_length=16, seed=0)
    np.testing.assert_array_equal(g[3]["text"], g2[3]["text"])


def test_split_parsing():
    assert get_train_valid_test_split_("969,30,1", 1000) == [0, 969, 999, 1000]
    assert get_train_valid_test_split_("100,0,0", 10) == [0, 10, 10, 10]


def test_blendable(tmp_path):
    class Fake:
        def __init__(self, tag, n):
            self.tag, self.n = tag, n

        def __len__(self):
            return self.n

        def __getitem__(self, i):
            return (self.tag, i)

    b = BlendableDataset([Fake("a", 100), Fake("b", 100)], [0.7, 0.3], 100)
    tags = [b[i][0] for i in range(100)]
    assert tags.count("a") == 70
    assert tags.count("b") == 30
    # per-dataset sample indices are sequential
    a_idx = [b[i][1] for i in range(100) if b[i][0] == "a"]
    assert a_idx == sorted(a_idx)


def test_sampler_resume():
    s1 = MegatronPretrainingSampler(100, 0, micro_batch_size=2,
                                    data_parallel_size=2)
    batches = list(s1)
    # resume from consumed=40 reproduces the tail exactly
    s2 = MegatronPretrainingSampler(100, 40, micro_batch_size=2,
                                    data_parallel_size=2)
    np.testing.assert_array_equal(batches[10], next(iter(s2)))


def test_random_sampler_resume():
    s1 = MegatronPretrainingRandomSampler(100, 0, 2, 2, seed=7)
    it1 = iter(s1)
    first10 = [next(it1) for _ in range(10)]
    s2 = MegatronPretrainingRandomSampler(100, 24, 2, 2, seed=7)
    np.testing.assert_array_equal(first10[6], next(iter(s2)))


def test_loader_batch_shapes(tmp_path):
    rng = np.random.RandomState(2)
    docs = [rng.randint(0, 100, size=30) for _ in range(40)]
    prefix = _write_dataset(tmp_path, docs)
    ds = MMapIndexedDataset(prefix)
    g = GPTDataset("train", prefix, np.arange(40), ds, num_samples=32,
                   seq_length=16, seed=0)
    loader = build_pretraining_data_loader(
        g, consumed_samples=0, micro_batch_size=2, data_parallel_size=2,
        num_microbatches=2, prefetch=0,
    )
    batch = next(iter(loader))
    assert batch["tokens"].shape == (2, 4, 16)
    assert batch["labels"].shape == (2, 4, 16)
    np.testing.assert_array_equal(batch["tokens"][0, 0, 1:],
                                  batch["labels"][0, 0, :-1])


def test_instruction_collator_masks():
    sample = {
        "text": np.asarray([1, 2, 3, 4, 5, 6]),
        "role": np.asarray([ROLE_USER, ROLE_USER, ROLE_ASSISTANT,
                            ROLE_ASSISTANT, ROLE_ASSISTANT, ROLE_ASSISTANT]),
    }
    out = instruction_collator([[sample, sample]], seq_length=8,
                               pad_token_id=0, scalar_loss_mask=0.25)
    assert out["tokens"].shape == (1, 2, 8)
    # labels are text shifted; mask: assistant->1, user->0.25, pad->0
    lm = out["loss_mask"][0, 0]
    np.testing.assert_allclose(lm[:5], [0.25, 1, 1, 1, 1])
    np.testing.assert_allclose(lm[5:], [0, 0, 0])


def test_preprocess_cli(tmp_path):
    jsonl = tmp_path / "in.jsonl"
    with open(jsonl, "w") as f:
        for i in range(5):
            f.write(json.dumps({"text": " ".join(str(j) for j in range(i + 2))})
                    + "\n")
    out_prefix = str(tmp_path / "out")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "preprocess_data.py"),
         "--input", str(jsonl), "--output_prefix", out_prefix,
         "--tokenizer_type", "NullTokenizer", "--vocab_size", "100",
         "--append_eod"],
        capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 0, r.stderr
    ds = MMapIndexedDataset(out_prefix)
    assert len(ds) == 5
    np.testing.assert_array_equal(ds[0], [0, 1, 100])  # eod appended


def test_place_host_batch_matches_device_put(utils, monkeypatch):
    """The multi-host placement branch (make_array_from_callback, taken
    when process_count > 1) must assemble the same global array as the
    single-host device_put branch — exercised by patching process_count so
    the real multi-host code path runs."""
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from megatron_llm_tpu import topology
    from megatron_llm_tpu.data.data_samplers import place_host_batch

    if len(jax.devices()) < 8:
        import pytest

        pytest.skip("needs the 8-device CPU mesh")
    mesh = topology.initialize_model_parallel()     # dp=8
    try:
        sh_ = NamedSharding(mesh, P(None, "dp", None))
        b = np.arange(2 * 8 * 4, dtype=np.int32).reshape(2, 8, 4)
        a1 = place_host_batch(b, sh_)               # process_count==1 branch
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        a2 = place_host_batch(b, sh_)               # multi-host branch
        assert a1.sharding == sh_ and a2.sharding == sh_
        np.testing.assert_array_equal(np.asarray(a1), b)
        np.testing.assert_array_equal(np.asarray(a2), b)
    finally:
        topology.destroy_model_parallel()
