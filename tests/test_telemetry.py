"""Unified telemetry (megatron_llm_tpu/telemetry.py): MFU arithmetic vs
the model-level flops_per_token, the >0.95 fabrication guard, structured
JSONL schema, in-loop profiler xplane capture, flight-recorder dump on an
injected hang@ watchdog fire, --timing_log_option handling, the folded
timers.report(), and the tools/telemetry_report.py summarizer."""

import argparse
import glob
import json
import os
import subprocess
import sys

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from megatron_llm_tpu import global_vars, telemetry
from megatron_llm_tpu.config import ParallelConfig, TrainConfig
from megatron_llm_tpu.models.language_model import flops_per_token
from megatron_llm_tpu.models.llama import LlamaModel, llama_config
from megatron_llm_tpu.parallel import sharding as sh
from megatron_llm_tpu.resilience import (
    FaultInjector,
    HangWatchdog,
    ResilienceConfig,
    ResilienceManager,
)
from megatron_llm_tpu.telemetry import (
    MFU_SANITY_LIMIT,
    FlightRecorder,
    TELEMETRY_SCHEMA_VERSION,
    ThroughputCalculator,
    build_telemetry,
    peak_flops_for_kind,
)
from megatron_llm_tpu.timers import Timers
from megatron_llm_tpu.training import pretrain, training_log

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_telemetry_state():
    global_vars.reset_counters()
    telemetry.install_stream(None)
    yield
    telemetry.install_stream(None)
    global_vars.reset_counters()


def _setup(utils):
    cfg = llama_config("tiny", seq_length=16, max_position_embeddings=16,
                       padded_vocab_size=64, num_layers=1, hidden_size=32,
                       num_attention_heads=4, ffn_hidden_size=64)
    model = LlamaModel(cfg)
    utils.initialize_model_parallel(tp=1)
    params = model.init(jax.random.PRNGKey(0))
    params = sh.shard_params(params, model.param_specs(params))

    def it():
        rng = np.random.RandomState(0)
        while True:
            toks = jnp.asarray(rng.randint(0, 64, size=(1, 8, 16)))
            yield {
                "tokens": toks,
                "labels": jnp.roll(toks, -1, axis=-1),
                "loss_mask": jnp.ones_like(toks, jnp.float32),
            }

    return model, params, it


def _tc(iters):
    return TrainConfig(micro_batch_size=8, global_batch_size=8,
                       train_iters=iters, lr=1e-2, optimizer="adam", seed=3)


def _telemetry_args(**kw):
    """A parsed-args stand-in with just the telemetry group's fields."""
    base = dict(structured_log_dir=None, flight_recorder_size=64,
                profile=False, profile_step_start=2, profile_step_end=3,
                profile_dir=None, profiler_port=None)
    base.update(kw)
    return argparse.Namespace(**base)


# ---------------------------------------------------------------------------
# Throughput / MFU arithmetic
# ---------------------------------------------------------------------------

def test_peak_flops_lookup():
    assert peak_flops_for_kind("TPU v4") == 275e12
    assert peak_flops_for_kind("TPU v5 lite") == 197e12
    assert peak_flops_for_kind("TPU v5p chip") == 459e12
    assert peak_flops_for_kind("TPU v6e") == 918e12
    # unknown TPU spelling: conservative v5e default, never None
    assert peak_flops_for_kind("TPU v9 mega") == 197e12
    assert peak_flops_for_kind("cpu") is None
    assert peak_flops_for_kind("cpu", assume_tpu=True) == 197e12


def test_mfu_arithmetic_matches_hand_computed_flops():
    cfg = llama_config("tiny", seq_length=16, max_position_embeddings=16,
                       padded_vocab_size=64, num_layers=1, hidden_size=32,
                       num_attention_heads=4, ffn_hidden_size=64)
    model = LlamaModel(cfg)
    fpt = model.flops_per_token()
    assert fpt == flops_per_token(cfg)
    # hand-computed for this exact tiny config: per-layer matmul params
    # (qkv + out-proj + glu mlp) + tied embedding, 6 flops/param/token
    # fwd+bwd, plus the 3x attention term
    qkv = 32 * (4 + 2 * 4) * 8
    proj = 4 * 8 * 32
    mlp_p = 32 * 64 * 2 + 64 * 32
    dense = 1 * (qkv + proj + mlp_p)
    emb = 64 * 32
    attn = 1 * 2 * 2 * 16 * 4 * 8
    assert fpt == pytest.approx(6.0 * (dense + emb) + 3.0 * attn)

    calc = ThroughputCalculator(flops_per_token=fpt, device_count=8,
                                peak_flops=1e12)
    out = calc.compute(tokens=4096, elapsed_secs=0.5)
    tps = 4096 / 0.5
    assert out["tokens_per_sec"] == pytest.approx(tps)
    assert out["tokens_per_sec_per_device"] == pytest.approx(tps / 8)
    assert out["tflops_per_device"] == pytest.approx(
        tps * fpt / 8 / 1e12)
    assert out["mfu"] == pytest.approx(tps * fpt / 8 / 1e12 / 1.0)


def test_mfu_guard_and_unknown_peak():
    # impossible MFU (the bench's >0.95 fabrication guard): reported null,
    # never a made-up number — but the achieved TFLOPs stays (it is a
    # measurement, not a ratio against a peak)
    calc = ThroughputCalculator(flops_per_token=1e9, device_count=1,
                                peak_flops=1e9)
    out = calc.compute(tokens=100, elapsed_secs=0.001)   # mfu would be 1e5
    assert out["mfu"] is None
    assert out["tflops_per_device"] is not None
    assert MFU_SANITY_LIMIT == 0.95
    # unknown peak (CPU): mfu null, throughput still reported
    calc = ThroughputCalculator(flops_per_token=1e9, device_count=1,
                                peak_flops=None)
    out = calc.compute(tokens=100, elapsed_secs=1.0)
    assert out["mfu"] is None
    assert out["tokens_per_sec"] == pytest.approx(100.0)


def test_from_model_on_cpu_never_fabricates(utils):
    model, _, _ = _setup(utils)
    calc = ThroughputCalculator.from_model(model)
    assert calc.flops_per_token == pytest.approx(model.flops_per_token())
    assert calc.peak_flops is None          # CPU backend
    assert calc.compute(1000, 0.1)["mfu"] is None


def test_training_log_prints_throughput(capsys):
    training_log(5, 10, {"lm loss": 1.0}, elapsed_per_iter=0.5,
                 tokens_per_iter=1000, lr=1e-3,
                 throughput={"tokens_per_sec": 2000.0,
                             "tokens_per_sec_per_device": 250.0,
                             "tflops_per_device": 12.5, "mfu": 0.42})
    out = capsys.readouterr().out
    assert "tokens per second per device: 250.0" in out
    assert "TFLOPs per device: 12.5" in out
    assert "MFU: 42.0%" in out
    # null mfu (CPU / guard): the field is omitted, not printed as 0
    training_log(5, 10, {"lm loss": 1.0}, 0.5, 1000, 1e-3,
                 throughput={"tokens_per_sec": 2000.0,
                             "tokens_per_sec_per_device": 250.0,
                             "tflops_per_device": None, "mfu": None})
    assert "MFU" not in capsys.readouterr().out


# ---------------------------------------------------------------------------
# Flight recorder
# ---------------------------------------------------------------------------

def test_flight_recorder_bounded(tmp_path):
    fr = FlightRecorder(capacity=4)
    for i in range(10):
        fr.record({"iteration": i})
    assert len(fr) == 4
    assert [r["iteration"] for r in fr.records()] == [6, 7, 8, 9]
    path = fr.dump(str(tmp_path / "fr.json"), reason="unit test")
    payload = json.loads(open(path).read())
    assert payload["reason"] == "unit test"
    assert [r["iteration"] for r in payload["records"]] == [6, 7, 8, 9]


# ---------------------------------------------------------------------------
# JSONL stream + in-loop profiler (the acceptance-criteria tiny run)
# ---------------------------------------------------------------------------

def test_structured_stream_schema_and_profiler_xplane(utils, tmp_path):
    """CPU tiny run with --structured_log_dir + --profile_step_start 2
    --profile_step_end 3: JSONL records carry tokens_per_sec_per_device
    and mfu (null on CPU, never fabricated) and the profiler leaves an
    xplane under --profile_dir."""
    model, params, it = _setup(utils)
    log_dir = str(tmp_path / "telemetry")
    prof_dir = str(tmp_path / "trace")
    tel = build_telemetry(
        _telemetry_args(structured_log_dir=log_dir, profile=True,
                        profile_step_start=2, profile_step_end=3,
                        profile_dir=prof_dir, flight_recorder_size=8),
        model)
    try:
        pretrain(model, params, _tc(4), ParallelConfig(), it(),
                 log_interval=1, telemetry=tel)
    finally:
        tel.close()

    planes = glob.glob(os.path.join(prof_dir, "**", "*.xplane.pb"),
                       recursive=True)
    assert planes and os.path.getsize(planes[0]) > 0

    lines = open(os.path.join(log_dir, "telemetry.jsonl")).readlines()
    records = [json.loads(l) for l in lines]
    assert [r["iteration"] for r in records] == [1, 2, 3, 4]
    golden_keys = {"schema", "kind", "time_unix", "iteration",
                   "train_iters", "lm_loss", "grad_norm", "loss_scale",
                   "skipped_iter", "learning_rate", "step_time_secs",
                   "tokens_per_iter", "tokens_per_sec",
                   "tokens_per_sec_per_device", "tflops_per_device",
                   "mfu", "memory", "recovery"}
    for r in records:
        assert golden_keys <= set(r), golden_keys - set(r)
        assert r["schema"] == TELEMETRY_SCHEMA_VERSION
        assert r["kind"] == "log"
        assert r["mfu"] is None                      # CPU: never fabricated
        assert r["tokens_per_sec_per_device"] > 0
        assert r["step_time_secs"] > 0
        assert isinstance(r["memory"], dict)
        assert set(r["recovery"]) == {"rewinds", "save_retries",
                                      "watchdog_fires", "signal_saves"}
    # the flight recorder saw both per-iteration dispatch entries and the
    # full log records
    kinds = {rec["kind"] for rec in tel.stream.flight_recorder.records()}
    assert kinds == {"dispatch", "log"}
    # run aggregates for the wandb/TB finish() summary
    s = tel.stream.summary()
    assert s["log_boundaries"] == 4 and s["mean_mfu"] is None
    assert s["mean_tokens_per_sec_per_device"] > 0


def test_flight_recorder_dump_on_watchdog_fire(utils, tmp_path):
    """An injected hang@3 fires the watchdog, whose stack-dump path dumps
    the flight recorder (last K step records) next to the JSONL stream."""
    model, params, it = _setup(utils)
    log_dir = str(tmp_path / "telemetry")
    tel = build_telemetry(
        _telemetry_args(structured_log_dir=log_dir,
                        flight_recorder_size=8), model)
    wd = HangWatchdog(timeout_secs=0.5, hard_exit=False,
                      poll_interval=0.05, printer=lambda s: None)
    rm = ResilienceManager(ResilienceConfig(snapshot_interval=1),
                           injector=FaultInjector.from_spec("hang@3:2.0"),
                           watchdog=wd)
    try:
        pretrain(model, params, _tc(4), ParallelConfig(), it(),
                 log_interval=1, resilience=rm, telemetry=tel)
    finally:
        rm.close()
        tel.close()
    assert wd.fired
    dump_path = os.path.join(log_dir, "flight_recorder.json")
    assert os.path.exists(dump_path)
    payload = json.loads(open(dump_path).read())
    assert payload["reason"] == "stack dump"
    assert payload["records"]
    # the dump happened mid-hang: its newest record predates iteration 3's
    # completion, proving it captured the state at fire time
    iters = [r.get("iteration") for r in payload["records"]
             if r.get("iteration") is not None]
    assert iters and max(iters) <= 3
    # and the printed report inlines the recorder section
    assert "flight recorder" in wd.last_dump


# ---------------------------------------------------------------------------
# --timing_log_option + timers.report
# ---------------------------------------------------------------------------

def _spin(timers, name, secs=0.01):
    import time as _t
    t = timers(name, log_level=0)
    t.start()
    _t.sleep(secs)
    t.stop()


def test_timing_log_option_changes_output():
    outs = {}
    for opt in ("minmax", "max", "all"):
        tm = Timers(log_level=2, log_option=opt)
        _spin(tm, "train-step")
        lines = []
        tm.log(printer=lines.append)
        outs[opt] = lines[0]
    assert outs["minmax"].startswith("(min, max) time (ms)")
    assert outs["max"].startswith("max time (ms)")
    assert outs["all"].startswith("time (ms) across hosts")
    # demonstrably different outputs, same timers
    assert len({o.split("|")[0] for o in outs.values()}) == 3
    # greppability contract (test_train_flags relies on it): every variant
    # keeps the literal "time (ms)"
    assert all("time (ms)" in o for o in outs.values())
    # single host: the entry degenerates to the plain value, no tuple
    assert "(min" not in outs["minmax"].split("|")[1]
    with pytest.raises(ValueError):
        Timers(log_option="median")


def test_timers_write_single_host_plain_keys():
    tm = Timers(log_level=2, log_option="minmax")
    _spin(tm, "train-step")
    rows = []

    class W:
        def add_scalar(self, k, v, it):
            rows.append((k, v, it))

    tm.write(["train-step"], W(), iteration=7)
    assert len(rows) == 1
    k, v, it = rows[0]
    assert k == "train-step-time" and v > 0 and it == 7


def test_timers_report_single_snapshot():
    """report() feeds writer + console from ONE elapsed read and resets —
    the write()-before-log() ordering trap is gone."""
    tm = Timers(log_level=2, log_option="minmax")
    _spin(tm, "train-step")
    rows, lines = [], []

    class W:
        def add_scalar(self, k, v, it):
            rows.append((k, v, it))

    tm.report(W(), iteration=3, normalizer=2.0, printer=lines.append)
    assert rows and lines
    written = rows[0][1]
    printed_ms = float(lines[0].split("train-step:")[1].strip())
    # the printed value is rounded to 2 decimals
    assert printed_ms == pytest.approx(written * 1000.0, abs=0.006)
    # the snapshot reset the accumulator: a second report is a no-op
    rows.clear()
    lines.clear()
    tm.report(W(), iteration=4, printer=lines.append)
    assert tm.get_elapsed(["train-step"], reset=False)["train-step"] == 0.0


# ---------------------------------------------------------------------------
# tools/telemetry_report.py
# ---------------------------------------------------------------------------

def _synthetic_stream(path, n=6):
    with open(path, "w") as f:
        for i in range(1, n + 1):
            rec = {
                "schema": 1, "kind": "log", "iteration": i,
                "lm_loss": 2.0 / i, "grad_norm": 1.0,
                "step_time_secs": 0.1 * i,
                "tokens_per_sec_per_device": 100.0 + i,
                "mfu": 0.4 if i != 3 else None,
                "memory": {"bytes_in_use": 1 << 20},
                "recovery": {"rewinds": 1 if i >= 4 else 0,
                             "save_retries": 0, "watchdog_fires": 0,
                             "signal_saves": 0},
            }
            f.write(json.dumps(rec) + "\n")
        f.write("{truncated-by-crash\n")


def test_telemetry_report_tool(tmp_path):
    stream = tmp_path / "telemetry.jsonl"
    _synthetic_stream(str(stream))
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "telemetry_report.py"),
         str(tmp_path)],
        capture_output=True, text=True, timeout=120, cwd=ROOT)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "step time p50:" in r.stdout and "p95:" in r.stdout
    assert "mean MFU: 0.4" in r.stdout
    assert "recovery events:" in r.stdout
    assert "iteration 4: rewinds+1" in r.stdout
    assert "skipped 1 unparseable line" in r.stderr

    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "telemetry_report.py"),
         str(stream), "--json"],
        capture_output=True, text=True, timeout=120, cwd=ROOT)
    assert r.returncode == 0, r.stdout + r.stderr
    agg = json.loads(r.stdout)["aggregates"]
    assert agg["log_boundaries"] == 6
    assert agg["p50_step_time_secs"] == pytest.approx(0.3, abs=0.11)
    assert agg["p95_step_time_secs"] == pytest.approx(0.6, abs=0.11)
    assert agg["mean_mfu"] == pytest.approx(0.4)
    r2 = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "telemetry_report.py"),
         str(tmp_path / "missing")],
        capture_output=True, text=True, timeout=120, cwd=ROOT)
    assert r2.returncode == 2
