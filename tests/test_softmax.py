"""Scale-mask-softmax semantics vs the reference CUDA kernel families
(megatron/fused_kernels/tests/test_fused_kernels.py): padding masks,
causal (upper-triangular) masks, all-masked rows, dtype round trip."""

import numpy as np
import jax.numpy as jnp

from megatron_llm_tpu.ops.softmax import (
    NEG_INF,
    causal_mask,
    fused_scale_mask_softmax,
    sliding_window_mask,
)


def _ref_softmax(scores, mask, scale):
    s = scores.astype(np.float32)
    if scale is not None:
        s = s * scale
    if mask is not None:
        s = np.where(mask, NEG_INF, s)
    s = s - s.max(axis=-1, keepdims=True)
    e = np.exp(s)
    return e / e.sum(axis=-1, keepdims=True)


def test_padding_mask_parity():
    rng = np.random.RandomState(0)
    scores = rng.randn(2, 4, 8, 8).astype(np.float32)
    # padding mask: keys 5.. masked for batch 0 (True = masked away)
    mask = np.zeros((2, 1, 1, 8), bool)
    mask[0, ..., 5:] = True
    out = np.asarray(fused_scale_mask_softmax(
        jnp.asarray(scores, jnp.bfloat16), jnp.asarray(mask), scale=0.5))
    ref = _ref_softmax(scores, mask, 0.5)
    assert np.abs(out.astype(np.float32) - ref).max() < 1e-2  # bf16 I/O
    # masked keys get (numerically) zero probability
    assert out[0, ..., 5:].max() < 1e-3
    np.testing.assert_allclose(out.astype(np.float32).sum(-1), 1.0,
                               atol=2e-2)


def test_upper_triangular_parity():
    rng = np.random.RandomState(1)
    scores = rng.randn(2, 4, 16, 16).astype(np.float32)
    mask = np.asarray(causal_mask(16, 16)).astype(bool)
    out = np.asarray(fused_scale_mask_softmax(
        jnp.asarray(scores), jnp.asarray(mask)[None, None]))
    ref = _ref_softmax(scores, mask[None, None], None)
    np.testing.assert_allclose(out, ref, atol=1e-6)
    # strictly causal: no probability above the diagonal
    assert out[..., np.triu_indices(16, 1)[0], np.triu_indices(16, 1)[1]] \
        .max() == 0.0 or np.abs(
        out * mask[None, None]).max() < 1e-7


def test_all_masked_row_is_finite():
    """The reference kernels emit a uniform distribution for a fully
    masked row (softmax over all -10000s), never NaN — e.g. the first
    row under a causal mask with sk > sq history, or a fully padded
    sample in a batch."""
    scores = jnp.ones((1, 1, 2, 4), jnp.float32)
    mask = jnp.ones((1, 1, 2, 4), bool)       # everything masked
    out = np.asarray(fused_scale_mask_softmax(scores, mask))
    assert np.isfinite(out).all()
    np.testing.assert_allclose(out, 0.25, atol=1e-6)  # uniform


def test_causal_mask_offset_history():
    """sq < sk: the mask must align the q rows to the END of the key
    history (incremental decode with a KV cache)."""
    m = np.asarray(causal_mask(2, 6)).astype(bool)
    # row 0 attends keys 0..4, row 1 attends keys 0..5
    assert not m[0, :5].any() and m[0, 5]
    assert not m[1, :].any()


def test_sliding_window_mask():
    m = np.asarray(sliding_window_mask(8, 8, window=3)).astype(bool)
    for i in range(8):
        visible = [j for j in range(8) if not m[i, j]]
        assert visible == list(range(max(0, i - 2), i + 1))


def test_dtype_round_trip():
    scores = jnp.asarray(np.random.RandomState(2).randn(2, 2, 4, 4),
                         jnp.bfloat16)
    out = fused_scale_mask_softmax(scores, None, softmax_in_fp32=True)
    assert out.dtype == jnp.bfloat16
    out32 = fused_scale_mask_softmax(scores.astype(jnp.float32), None)
    assert out32.dtype == jnp.float32
