"""Fault-tolerance runtime (megatron_llm_tpu/resilience.py): fault-injector
spec parsing, spike sentinel + rewind, hang watchdog, samples accounting,
signal-save resume parity, and the end-to-end chaos run (NaN grads +
transient save IOErrors + SIGTERM in one training run)."""

import os
import signal
import time

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from megatron_llm_tpu import checkpointing, global_vars
from megatron_llm_tpu.config import ParallelConfig, TrainConfig
from megatron_llm_tpu.dist_signal_handler import DistributedSignalHandler
from megatron_llm_tpu.models.llama import LlamaModel, llama_config
from megatron_llm_tpu.optimizer import MegatronOptimizer
from megatron_llm_tpu.parallel import sharding as sh
from megatron_llm_tpu.resilience import (
    FaultInjector,
    HangWatchdog,
    ResilienceConfig,
    ResilienceManager,
    recovery_counters,
    set_save_fault_hook,
)
from megatron_llm_tpu.training import pretrain


@pytest.fixture(autouse=True)
def _clean_resilience_state():
    global_vars.reset_counters()
    checkpointing.configure_save(total_limit=0, retries=2,
                                 retry_backoff=0.01)
    yield
    set_save_fault_hook(None)
    global_vars.reset_counters()
    checkpointing.configure_save(total_limit=0, retries=2,
                                 retry_backoff=0.25)


def _setup(utils):
    cfg = llama_config("tiny", seq_length=16, max_position_embeddings=16,
                       padded_vocab_size=64, num_layers=1, hidden_size=32,
                       num_attention_heads=4, ffn_hidden_size=64)
    model = LlamaModel(cfg)
    utils.initialize_model_parallel(tp=1)
    # shard at init (as the CLI drivers do): the train step then compiles
    # exactly once, instead of re-tracing when step-1 outputs come back
    # with mesh shardings the init params lacked
    params = model.init(jax.random.PRNGKey(0))
    params = sh.shard_params(params, model.param_specs(params))

    def it():
        # per-generator RNG: every it() call replays the same stream, so
        # an interrupted run can rebuild its data position exactly
        rng = np.random.RandomState(0)
        while True:
            toks = jnp.asarray(rng.randint(0, 64, size=(1, 8, 16)))
            yield {
                "tokens": toks,
                "labels": jnp.roll(toks, -1, axis=-1),
                "loss_mask": jnp.ones_like(toks, jnp.float32),
            }

    return model, params, it


def _tc(iters):
    return TrainConfig(micro_batch_size=8, global_batch_size=8,
                       train_iters=iters, lr=1e-2, optimizer="adam", seed=3)


def _flat(params):
    return np.concatenate([np.asarray(jnp.asarray(l)).ravel()
                           for l in jax.tree_util.tree_leaves(params)])


def _load_with_opt(load_dir, train_cfg, model):
    """Host-restore params + optimizer state (the CLI resume shape: the
    optimizer exists only after params do, so load goes in two phases),
    re-placed onto the current mesh exactly as finetune.py's resume does."""
    from jax.sharding import NamedSharding, PartitionSpec

    pl, _, meta = checkpointing.load_checkpoint(load_dir)
    assert pl is not None
    pl = sh.shard_params(jax.tree_util.tree_map(jnp.asarray, pl),
                         model.param_specs(pl))
    opt = MegatronOptimizer(train_cfg)
    tmpl = jax.eval_shape(opt.init, pl)
    _, ol, _ = checkpointing.load_checkpoint(
        load_dir, load_params=False, opt_state_template=tmpl)
    mesh = jax.tree_util.tree_leaves(pl)[0].sharding.mesh

    def _replicated(t):
        return jax.device_put(
            t, NamedSharding(mesh, PartitionSpec(*([None] * t.ndim))))

    psh = jax.tree_util.tree_map(lambda p: p.sharding, pl)

    def _like_params(tree):
        if tree is None:
            return None
        return jax.tree_util.tree_map(jax.device_put, tree, psh)

    ol = ol._replace(
        step=_replicated(ol.step),
        master_params=_like_params(ol.master_params),
        exp_avg=_like_params(ol.exp_avg),
        exp_avg_sq=_like_params(ol.exp_avg_sq),
        grad_scaler=jax.tree_util.tree_map(_replicated, ol.grad_scaler),
    )
    return pl, ol, opt, meta


# ---------------------------------------------------------------------------
# FaultInjector
# ---------------------------------------------------------------------------

def test_fault_injector_spec_parsing():
    inj = FaultInjector.from_spec("nan@3,save_io*2,hang@5:2.0,sigterm@7")
    assert inj.nan_iters == {3}
    assert inj.save_io_failures == 2
    assert inj.hang_at == 5 and inj.hang_secs == 2.0
    assert inj.sigterm_at == 7
    assert FaultInjector.from_spec("") is None
    assert FaultInjector.from_spec(None) is None
    assert FaultInjector.from_spec("hang@4").hang_secs == 1.0
    with pytest.raises(ValueError):
        FaultInjector.from_spec("explode@9")


def test_fault_injector_poison_once_and_save_io_budget():
    inj = FaultInjector.from_spec("nan@2,save_io*1")
    batch = {"loss_mask": np.ones((2, 2), np.float32)}
    assert inj.poison_batch(1, batch) is batch          # untouched
    poisoned = inj.poison_batch(2, batch)
    assert np.all(np.isnan(poisoned["loss_mask"]))
    assert np.all(batch["loss_mask"] == 1.0)            # original intact
    # one-shot: the replayed iteration 2 after a rewind stays clean
    assert inj.poison_batch(2, batch) is batch
    with pytest.raises(IOError):
        inj.maybe_fail_save()
    inj.maybe_fail_save()                               # budget spent


# ---------------------------------------------------------------------------
# Sentinel / rewind units
# ---------------------------------------------------------------------------

def test_sentinel_flags_nonfinite_and_spike():
    rm = ResilienceManager(ResilienceConfig(spike_factor=3.0, patience=1))
    assert not rm.record_metrics(1, 1.0)
    assert not rm.record_metrics(2, 1.1)                # mild rise: fine
    assert rm.record_metrics(3, float("nan"))
    assert rm.record_metrics(4, 1.0, grad_norm=float("inf"))
    assert rm.record_metrics(5, 50.0)                   # spike vs ~1.0 EMA
    # no snapshot yet -> never rewind, however bad the streak
    assert not rm.should_rewind()


def test_sentinel_patience_and_streak_reset():
    rm = ResilienceManager(ResilienceConfig(spike_factor=0.0, patience=2))
    rm.take_snapshot(0, {"w": jnp.zeros((2,), jnp.float32)}, None)
    rm.record_metrics(1, 1.0)
    assert rm.record_metrics(2, float("nan"))
    assert not rm.should_rewind()        # streak 1 < patience 2
    rm.record_metrics(3, 1.0)            # good step resets the streak
    assert rm.record_metrics(4, float("nan"))
    assert not rm.should_rewind()
    assert rm.record_metrics(5, float("nan"))
    assert rm.should_rewind()            # streak reached patience


def test_snapshot_rejects_nonfinite_params():
    rm = ResilienceManager(ResilienceConfig())
    good = {"w": jnp.ones((2, 2), jnp.float32)}
    bad = {"w": jnp.full((2, 2), jnp.nan, jnp.float32)}
    assert rm.take_snapshot(1, good, None)
    assert rm.snapshot_iteration == 1
    assert not rm.take_snapshot(2, bad, None)
    assert rm.snapshot_iteration == 1    # old known-good snapshot kept


def test_rewind_restores_snapshot_and_scales_lr():
    rm = ResilienceManager(
        ResilienceConfig(patience=1, rewind_lr_factor=0.5, spike_factor=0))
    rm.take_snapshot(3, {"w": jnp.ones((2, 2), jnp.float32)}, None)
    live = {"w": jnp.full((2, 2), 7.0, jnp.float32)}
    rm.record_metrics(4, float("nan"))
    assert rm.should_rewind()
    p, o, it = rm.rewind(live, None)
    assert it == 3 and o is None
    np.testing.assert_array_equal(np.asarray(p["w"]), np.ones((2, 2)))
    assert rm.lr_scale == 0.5
    assert recovery_counters()["rewinds"] == 1


def test_rewind_hard_stops_at_max_rewinds():
    rm = ResilienceManager(
        ResilienceConfig(patience=1, max_rewinds=1, spike_factor=0))
    p0 = {"w": jnp.zeros((2,), jnp.float32)}
    rm.take_snapshot(0, p0, None)
    rm.rewind(p0, None)
    with pytest.raises(RuntimeError, match="max_rewinds"):
        rm.rewind(p0, None)


# ---------------------------------------------------------------------------
# Watchdog
# ---------------------------------------------------------------------------

def test_watchdog_fires_and_dumps():
    fired = []
    lines = []
    wd = HangWatchdog(timeout_secs=0.15, on_fire=lambda: fired.append(1),
                      hard_exit=False, poll_interval=0.03,
                      printer=lines.append)
    wd.start()
    try:
        deadline = time.monotonic() + 3.0
        while not wd.fired and time.monotonic() < deadline:
            time.sleep(0.05)
    finally:
        wd.stop()
    assert wd.fired and fired == [1]
    assert wd.last_dump and "python stacks" in wd.last_dump
    assert any("device memory" in l for l in lines)
    assert recovery_counters()["watchdog_fires"] == 1


def test_watchdog_progress_and_pause_prevent_fire():
    wd = HangWatchdog(timeout_secs=0.25, hard_exit=False,
                      poll_interval=0.03, printer=lambda s: None)
    wd.start()
    try:
        for _ in range(8):
            time.sleep(0.05)
            wd.progress()
        assert not wd.fired
        wd.pause()                       # disarmed: no fire while paused
        time.sleep(0.4)
        assert not wd.fired
    finally:
        wd.stop()


# ---------------------------------------------------------------------------
# Train-loop integration
# ---------------------------------------------------------------------------

def test_pretrain_counts_samples(utils):
    model, params, it = _setup(utils)
    pretrain(model, params, _tc(3), ParallelConfig(), it(), log_interval=0)
    c = global_vars.get_counters()
    assert c["samples"] == 3 * 8          # batch [1 micro, 8 seqs, 16 toks]
    assert c["tokens"] == 3 * 8 * 16


def test_nan_injection_triggers_rewind_and_run_completes(utils):
    model, params, it = _setup(utils)
    rm = ResilienceManager(
        ResilienceConfig(snapshot_interval=1, patience=1, spike_factor=0),
        injector=FaultInjector.from_spec("nan@3"))
    try:
        p, o, n = pretrain(model, params, _tc(6), ParallelConfig(), it(),
                           log_interval=1, resilience=rm)
    finally:
        rm.close()
    assert n == 6
    assert recovery_counters()["rewinds"] == 1
    assert np.all(np.isfinite(_flat(p)))


def test_watchdog_rescue_save_in_pretrain(utils, tmp_path):
    """A step stalled past the watchdog budget rescue-saves the latest host
    snapshot (hard_exit off so the test can inspect the aftermath)."""
    model, params, it = _setup(utils)
    wd = HangWatchdog(timeout_secs=0.5, hard_exit=False,
                      poll_interval=0.05, printer=lambda s: None)
    rm = ResilienceManager(
        ResilienceConfig(snapshot_interval=1),
        injector=FaultInjector.from_spec("hang@3:2.0"),
        watchdog=wd)
    try:
        pretrain(model, params, _tc(4), ParallelConfig(), it(),
                 log_interval=1, save_dir=str(tmp_path), resilience=rm)
    finally:
        rm.close()
    assert recovery_counters()["watchdog_fires"] == 1
    # the rescue checkpoint holds the snapshot taken before the stall
    pl, _, meta = checkpointing.load_checkpoint(str(tmp_path))
    assert pl is not None and meta["iteration"] == 2


def test_signal_save_resume_parity(utils, tmp_path):
    """straight N iters == (SIGTERM save-and-exit at k) + (restore + skip
    consumed data + finish), bit-close params.  The save goes through the
    hardened path (tmp dir + atomic rename + manifest) and the resume
    through validation."""
    pc = ParallelConfig()

    model, params, it = _setup(utils)
    p_straight, _, _ = pretrain(model, params, _tc(4), pc, it(),
                                log_interval=0)
    straight = _flat(p_straight)

    # interrupted run: SIGTERM lands before iteration 3 runs; the loop
    # finishes the iteration, saves at 3 at the boundary, and exits
    model_b, params_b, it_b = _setup(utils)
    rm = ResilienceManager(ResilienceConfig(),
                           injector=FaultInjector.from_spec("sigterm@3"),
                           rewind_enabled=False)
    with DistributedSignalHandler() as handler:
        with pytest.raises(SystemExit):
            try:
                pretrain(model_b, params_b, _tc(4), pc, it_b(),
                         log_interval=1, save_dir=str(tmp_path),
                         exit_signal_handler=handler, resilience=rm)
            finally:
                rm.close()
    assert recovery_counters()["signal_saves"] == 1

    pl, ol, opt, meta = _load_with_opt(str(tmp_path), _tc(4), model_b)
    assert meta["iteration"] == 3
    gen = it_b()
    for _ in range(meta["iteration"]):    # data the first run consumed
        next(gen)
    p_resumed, _, _ = pretrain(model_b, pl, _tc(4), pc, gen,
                               log_interval=0, start_iteration=3,
                               opt_state=ol, optimizer=opt)
    np.testing.assert_allclose(_flat(p_resumed), straight, atol=1e-6)


@pytest.mark.parametrize("consensus", [False, True])
def test_signals_received_single_host(consensus):
    with DistributedSignalHandler() as h:
        assert h.signals_received(consensus=consensus) is False
        os.kill(os.getpid(), signal.SIGTERM)
        # single host: the local flag is the answer with or without
        # consensus (the allgather only exists for process_count > 1)
        assert h.signals_received(consensus=consensus) is True


def test_chaos_end_to_end(utils, tmp_path):
    """ISSUE acceptance: one run absorbs a NaN-grad iteration, two
    transient save IOErrors, and a SIGTERM — and still reaches
    train_iters with a loadable final checkpoint, reporting exactly
    1 rewind, 2 save retries, 1 signal save."""
    pc = ParallelConfig()
    model, params, it = _setup(utils)
    rm = ResilienceManager(
        ResilienceConfig(snapshot_interval=1, patience=1, spike_factor=0),
        injector=FaultInjector.from_spec("nan@2,save_io*2,sigterm@5"))
    gen = it()
    with DistributedSignalHandler() as handler:
        with pytest.raises(SystemExit):
            try:
                pretrain(model, params, _tc(8), pc, gen,
                         log_interval=1, save_dir=str(tmp_path),
                         exit_signal_handler=handler, resilience=rm)
            finally:
                rm.close()

    # phase-1 verdict: rewound once, the signal save survived 2 IOErrors
    c = recovery_counters()
    assert c["rewinds"] == 1
    assert c["save_retries"] == 2
    assert c["signal_saves"] == 1
    assert not list(tmp_path.glob("*.tmp"))       # atomic publish, no debris

    pl, ol, opt, meta = _load_with_opt(str(tmp_path), _tc(8), model)
    resume_at = meta["iteration"]
    assert 0 < resume_at < 8

    # phase 2: restore and run to completion (same data stream object)
    p_final, o_final, n = pretrain(model, pl, _tc(8), pc, gen,
                                   log_interval=1,
                                   start_iteration=resume_at,
                                   opt_state=ol, optimizer=opt)
    assert n == 8
    assert np.all(np.isfinite(_flat(p_final)))
    checkpointing.save_checkpoint(str(tmp_path), n, p_final, o_final)
    _, _, meta2 = checkpointing.load_checkpoint(str(tmp_path))
    assert meta2["iteration"] == 8
