"""T5 encoder-decoder tests.

Covers the cross-attention path added to ``models/transformer.py``
(reference: megatron/model/transformer.py:695-714,813-825) and the
``T5Model`` wrapper (reference: megatron/model/t5_model.py).
"""

import jax
import jax.numpy as jnp
import numpy as np

from megatron_llm_tpu.config import ParallelConfig, TrainConfig
from megatron_llm_tpu.models.t5 import T5Model, t5_config, t5_position_ids

VOCAB = 128
S_ENC, S_DEC = 24, 16


def tiny_cfg(**kw):
    return t5_config(
        num_layers=2, hidden_size=64, num_attention_heads=4,
        ffn_hidden_size=128, padded_vocab_size=VOCAB, seq_length=S_ENC,
        max_position_embeddings=max(S_ENC, S_DEC),
        hidden_dropout=0.0, attention_dropout=0.0, **kw,
    )


def make_batch(rs, b=2):
    enc = rs.randint(0, VOCAB, (b, S_ENC)).astype(np.int32)
    dec = rs.randint(0, VOCAB, (b, S_DEC)).astype(np.int32)
    labels = rs.randint(0, VOCAB, (b, S_DEC)).astype(np.int32)
    ee = np.ones((b, S_ENC, S_ENC), np.int32)
    dd = np.broadcast_to(
        np.tril(np.ones((S_DEC, S_DEC), np.int32)), (b, S_DEC, S_DEC)
    ).copy()
    de = np.ones((b, S_DEC, S_ENC), np.int32)
    return tuple(jnp.asarray(x) for x in (enc, dec, labels, ee, dd, de))


def test_t5_forward_and_loss_shapes():
    model = T5Model(tiny_cfg())
    params = model.init(jax.random.PRNGKey(0))
    enc, dec, labels, ee, dd, de = make_batch(np.random.RandomState(0))
    logits = model(params, enc, dec, ee, dd, de)
    assert logits.shape == (2, S_DEC, VOCAB)
    loss = model(params, enc, dec, ee, dd, de, lm_labels=labels)
    assert loss.shape == (2, S_DEC)
    assert abs(float(loss.mean()) - np.log(VOCAB)) < 1.0


def test_t5_decoder_params_have_cross_attention():
    model = T5Model(tiny_cfg())
    params = model.init(jax.random.PRNGKey(0))
    assert "inter_attention" in params["decoder"]["layers"]
    assert "inter_attention" not in params["encoder"]["layers"]
    q = params["decoder"]["layers"]["inter_attention"]["query"]["kernel"]
    assert q.shape == (2, 64, 64)  # [L, h, nh*d]
    kv = params["decoder"]["layers"]["inter_attention"]["key_value"]["kernel"]
    assert kv.shape == (2, 64, 128)  # [L, h, 2*nh*d]
    # specs cover every leaf
    specs = model.param_specs(params)
    jax.tree_util.tree_map(lambda p, s: None, params, specs)


def test_t5_decoder_is_causal():
    """Changing a late decoder token must not affect earlier logits."""
    model = T5Model(tiny_cfg())
    params = model.init(jax.random.PRNGKey(1))
    enc, dec, _, ee, dd, de = make_batch(np.random.RandomState(1), b=1)
    out1 = model(params, enc, dec, ee, dd, de)
    dec2 = np.asarray(dec).copy()
    dec2[0, -1] = (dec2[0, -1] + 3) % VOCAB
    out2 = model(params, enc, jnp.asarray(dec2), ee, dd, de)
    np.testing.assert_allclose(
        np.asarray(out1[0, : S_DEC - 1]), np.asarray(out2[0, : S_DEC - 1]),
        rtol=1e-5, atol=1e-5,
    )


def test_t5_decoder_attends_encoder():
    """Changing any encoder token must change decoder logits (cross-attn)."""
    model = T5Model(tiny_cfg())
    params = model.init(jax.random.PRNGKey(2))
    enc, dec, _, ee, dd, de = make_batch(np.random.RandomState(2), b=1)
    out1 = model(params, enc, dec, ee, dd, de)
    enc2 = np.asarray(enc).copy()
    enc2[0, 0] = (enc2[0, 0] + 5) % VOCAB
    out2 = model(params, jnp.asarray(enc2), dec, ee, dd, de)
    assert float(jnp.abs(out1 - out2).max()) > 1e-4


def test_t5_enc_dec_mask_blocks_cross_attention():
    """Masking an encoder position out of the cross mask hides changes to it
    (the encoder itself must also not mix it in, so pad it everywhere)."""
    model = T5Model(tiny_cfg())
    params = model.init(jax.random.PRNGKey(3))
    enc, dec, _, ee, dd, de = make_batch(np.random.RandomState(3), b=1)
    ee = np.asarray(ee).copy()
    de = np.asarray(de).copy()
    ee[0, :, -1] = 0   # nobody in the encoder attends the last token
    de[0, :, -1] = 0   # decoder cross-attn skips it too
    out1 = model(params, enc, dec, jnp.asarray(ee), dd, jnp.asarray(de))
    enc2 = np.asarray(enc).copy()
    enc2[0, -1] = (enc2[0, -1] + 9) % VOCAB
    out2 = model(params, jnp.asarray(enc2), dec, jnp.asarray(ee), dd, jnp.asarray(de))
    np.testing.assert_allclose(
        np.asarray(out1), np.asarray(out2), rtol=1e-5, atol=1e-5
    )


def test_t5_position_ids():
    toks = jnp.zeros((3, 7), jnp.int32)
    pos = t5_position_ids(toks)
    assert pos.shape == (3, 7)
    np.testing.assert_array_equal(np.asarray(pos[1]), np.arange(7))


def test_t5_train_step_decreases_loss():
    """Two jitted train steps on one repeated batch lower the loss."""
    from megatron_llm_tpu.optimizer import MegatronOptimizer
    from megatron_llm_tpu.training import build_train_step

    model = T5Model(tiny_cfg())
    params = model.init(jax.random.PRNGKey(4))
    tc = TrainConfig(lr=1e-3, train_iters=4, micro_batch_size=2,
                     global_batch_size=2)
    opt = MegatronOptimizer(tc)
    opt_state = opt.init(params)
    step = build_train_step(model, opt, ParallelConfig(), num_microbatches=1)

    rs = np.random.RandomState(5)
    enc, dec, labels, ee, dd, de = make_batch(rs)
    batch = {
        "tokens": enc[None], "decoder_input_ids": dec[None],
        "labels": labels[None],
        "loss_mask": jnp.ones((1, 2, S_DEC), jnp.float32),
        "encoder_attn_mask": ee[None], "decoder_attn_mask": dd[None],
        "encoder_decoder_attn_mask": de[None],
    }
    key = jax.random.PRNGKey(0)
    losses = []
    for i in range(4):
        params, opt_state, metrics = step(
            params, opt_state, batch, key, 1e-3, 0.0
        )
        losses.append(float(metrics["lm loss"]))
    assert losses[-1] < losses[0]
