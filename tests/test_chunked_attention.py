"""Q-chunked exact attention (the long-context XLA fallback,
VERDICT r3 #6): exactness vs the unchunked path, window/GQA handling,
and an 8k fwd+bwd that the [s, s] path could not survive on the TPU
remote compiler."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megatron_llm_tpu.ops.chunked_attention import chunked_causal_attention
from megatron_llm_tpu.ops.pallas.flash_attention import _reference_attention


def _qkv(b=2, s=256, nh=4, ng=2, d=32, seed=0):
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(b, s, nh, d).astype(np.float32)) * 0.3
    k = jnp.asarray(rng.randn(b, s, ng, d).astype(np.float32)) * 0.3
    v = jnp.asarray(rng.randn(b, s, ng, d).astype(np.float32)) * 0.3
    return q, k, v


@pytest.mark.parametrize("window", [None, 64])
def test_chunked_matches_reference(window):
    q, k, v = _qkv()
    ref = _reference_attention(q, k, v, True, window, 0.125)
    got = chunked_causal_attention(
        q, k, v, causal=True, sliding_window=window, softmax_scale=0.125,
        q_chunk_size=64)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-5)


def test_chunked_grads_match_reference():
    q, k, v = _qkv()
    ref_fn = lambda *a: (_reference_attention(*a, True, None, 0.125) ** 2).sum()
    got_fn = lambda *a: (chunked_causal_attention(
        *a, causal=True, softmax_scale=0.125, q_chunk_size=64) ** 2).sum()
    gr = jax.grad(ref_fn, argnums=(0, 1, 2))(q, k, v)
    gg = jax.grad(got_fn, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gg):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_chunked_nondivisible_chunk_falls_to_divisor():
    q, k, v = _qkv(s=96)  # 96 % 64 != 0 -> chunk shrinks to 48
    ref = _reference_attention(q, k, v, True, None, 0.125)
    got = chunked_causal_attention(
        q, k, v, causal=True, softmax_scale=0.125, q_chunk_size=64)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-5)


def test_chunked_8k_fwd_bwd_survives():
    """The actual degradation scenario: seq 8192, where the unchunked
    [s, s] score tensor is 256 MB fp32 per (b, head-group) and kills the
    remote compiler.  Chunked must produce finite grads."""
    q, k, v = _qkv(b=1, s=8192, nh=2, ng=1, d=16)
    fn = lambda q, k, v: (chunked_causal_attention(
        q, k, v, causal=True, q_chunk_size=1024) ** 2).sum()
    g = jax.grad(fn)(q, k, v)
    assert g.shape == q.shape
    assert np.isfinite(np.asarray(g)).all()


def test_model_dispatch_uses_chunked_at_long_seq(monkeypatch):
    """attention() must route flash-eligible long-seq inputs through the
    chunked path when flash is off."""
    import megatron_llm_tpu.ops.chunked_attention as ca
    from megatron_llm_tpu.config import TransformerConfig
    from megatron_llm_tpu.models import transformer as T

    monkeypatch.setattr(ca, "CHUNKED_ATTENTION_MIN_SEQ", 64)
    called = {}
    real = ca.chunked_causal_attention

    def spy(*a, **kw):
        called["yes"] = True
        return real(*a, **kw)

    # attention() imports the symbol from the module at call time
    monkeypatch.setattr(ca, "chunked_causal_attention", spy)

    cfg = TransformerConfig(
        num_layers=1, hidden_size=32, num_attention_heads=4,
        ffn_hidden_size=64, padded_vocab_size=64, seq_length=128,
        max_position_embeddings=128, use_flash_attn=False,
        position_embedding_type="rotary",
    )
    params = T.init_layer_params(jax.random.PRNGKey(0), cfg, jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(1), (1, 128, 32))
    freqs = T.rotary_freqs(cfg)
    T.attention(
        x, params["attention"], cfg, freqs=freqs, attention_mask=None,
        position_ids=None, dropout_key=None, train=False)
    assert called.get("yes")
