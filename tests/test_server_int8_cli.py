"""tools/run_text_generation_server.py --int8_weights --int8_kv_cache e2e:
model presets applied from --model_name, weights quantized at load,
REST API serves generation."""

import json
import os
import socket
import subprocess
import sys
import threading
import time
import urllib.request

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_server_int8_cli(tmp_path):
    vocab = tmp_path / "vocab.txt"
    toks = (["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]", "hello",
             "world", "##s"] + [f"tok{i}" for i in range(120)])
    vocab.write_text("\n".join(toks))
    port = _free_port()
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    # the pytest conftest forces an 8-device CPU mesh via XLA_FLAGS;
    # this server smoke is the single-device case (dp=8 would demand
    # global_batch_size % 8 == 0)
    env.pop("XLA_FLAGS", None)
    proc = subprocess.Popen(
        [sys.executable,
         os.path.join(ROOT, "tools", "run_text_generation_server.py"),
         "--model_name=llama2", "--num_layers=2", "--hidden_size=64",
         "--num_attention_heads=4", "--seq_length=64",
         "--max_position_embeddings=64", "--micro_batch_size=1",
         "--global_batch_size=1",
         "--tokenizer_type=BertWordPieceLowerCase",
         f"--vocab_file={vocab}", "--int8_weights", "--int8_kv_cache",
         f"--port={port}", "--host=127.0.0.1"],
        cwd=ROOT, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    # drain the merged output continuously: chatty XLA compilation can
    # fill the ~64KB pipe buffer and deadlock the child before it binds
    chunks = []
    drain = threading.Thread(
        target=lambda: chunks.extend(iter(proc.stdout.readline, "")),
        daemon=True)
    drain.start()
    out = last = None
    try:
        body = json.dumps({"prompts": ["hello world"],
                           "tokens_to_generate": 4}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/api", data=body,
            headers={"Content-Type": "application/json"}, method="PUT")
        deadline = time.time() + 540
        while time.time() < deadline and proc.poll() is None:
            try:
                with urllib.request.urlopen(req, timeout=10) as r:
                    out = json.loads(r.read())
                break
            except Exception as e:  # server still compiling/binding
                last = e
                time.sleep(5)
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=30)
        except Exception:
            proc.kill()
        drain.join(timeout=10)
        out_text = "".join(chunks)
    assert out is not None, (
        f"server never answered: {last}\n--- server output ---\n"
        f"{out_text[-3000:]}")
    assert isinstance(out["text"][0], str) and len(out["tokens"][0]) > 2
    assert "int8 weights:" in out_text, out_text[-2000:]
