"""CLI surface: reference flag spellings resolve to real behavior
(reference arguments.py cross-derivations)."""

import jax.numpy as jnp
import pytest

from megatron_llm_tpu.arguments import (
    parse_args,
    transformer_config_from_args,
    validate_args,
)


def _args(*argv):
    a = parse_args(args_list=list(argv))
    return validate_args(a, world_size=8)


def test_encoder_spellings_fall_back():
    a = _args("--encoder_num_layers=6", "--encoder_seq_length=128",
              "--hidden_size=64", "--num_attention_heads=4",
              "--micro_batch_size=1")
    assert a.num_layers == 6
    assert a.seq_length == 128
    # and the canonical names back-fill the encoder spellings
    b = _args("--num_layers=4", "--seq_length=64", "--hidden_size=64",
              "--num_attention_heads=4", "--micro_batch_size=1")
    assert b.encoder_num_layers == 4
    assert b.encoder_seq_length == 64


def test_recompute_spellings():
    a = _args("--recompute_activations", "--num_layers=2",
              "--hidden_size=64", "--num_attention_heads=4",
              "--seq_length=32", "--micro_batch_size=1")
    assert a.recompute_granularity == "selective"
    b = _args("--recompute_method=uniform", "--num_layers=2",
              "--hidden_size=64", "--num_attention_heads=4",
              "--seq_length=32", "--micro_batch_size=1")
    assert b.recompute_granularity == "uniform"


def test_use_bias_and_postln_aliases():
    a = _args("--use_bias", "--apply_residual_connection_post_layernorm",
              "--num_layers=2", "--hidden_size=64",
              "--num_attention_heads=4", "--seq_length=32",
              "--micro_batch_size=1")
    assert a.use_bias is True
    assert a.use_post_ln is True
    cfg = transformer_config_from_args(a)
    assert cfg.add_bias_linear and cfg.use_post_ln


def test_attention_softmax_fp32_toggle():
    a = _args("--no_attention_softmax_in_fp32", "--num_layers=2",
              "--hidden_size=64", "--num_attention_heads=4",
              "--seq_length=32", "--micro_batch_size=1")
    assert transformer_config_from_args(a).attention_softmax_in_fp32 is False
    b = _args("--attention_softmax_in_fp32", "--num_layers=2",
              "--hidden_size=64", "--num_attention_heads=4",
              "--seq_length=32", "--micro_batch_size=1")
    assert transformer_config_from_args(b).attention_softmax_in_fp32 is True


def test_xavier_init_reaches_params():
    import jax

    from megatron_llm_tpu.models.llama import LlamaModel, llama_config

    cfg = llama_config("tiny", seq_length=16, max_position_embeddings=16,
                       padded_vocab_size=64,
                       init_method_xavier_uniform=True,
                       use_scaled_init_method=False)
    model = LlamaModel(cfg)
    p = model.init(jax.random.PRNGKey(0))
    k = p["transformer"]["layers"]["mlp"]["dense_h_to_4h"]["kernel"]
    fan_in, fan_out = k.shape[-2], k.shape[-1]
    bound = (6.0 / (fan_in + fan_out)) ** 0.5
    assert float(abs(k).max()) <= bound + 1e-6   # uniform, not normal


def test_reference_launch_flags_accepted():
    """A reference A100 launch line parses cleanly: CUDA-only flags are
    accepted (documented no-ops), behavioral ones resolve."""
    a = _args(
        "--num_layers=2", "--hidden_size=64", "--num_attention_heads=4",
        "--seq_length=32", "--micro_batch_size=1", "--bf16",
        "--no_gradient_accumulation_fusion", "--use_cpu_initialization",
        "--no_persist_layer_norm", "--fp32_residual_connection",
        "--no_async_tensor_model_parallel_allreduce",
        "--fp8_margin=1", "--adlr_autoresume_interval=100",
        "--log_params_norm", "--log_num_zeros_in_grad",
        "--timing_log_option=max", "--load_iters=7", "--eval_only",
    )
    assert a.log_params_norm and a.log_num_zeros_in_grad
    assert a.load_iters == 7 and a.eval_only
    assert a.timing_log_option == "max"


def test_use_checkpoint_args_overrides_cli(tmp_path):
    """--use_checkpoint_args: architecture recorded in the checkpoint wins
    over the CLI (reference checkpointing.py:520-560)."""
    import jax

    from finetune import _apply_checkpoint_args
    from megatron_llm_tpu import checkpointing
    from megatron_llm_tpu.models.llama import LlamaModel, llama_config

    cfg = llama_config("tiny", num_layers=2, hidden_size=64,
                       num_attention_heads=4, ffn_hidden_size=96,
                       padded_vocab_size=128, seq_length=32,
                       max_position_embeddings=32)
    model = LlamaModel(cfg)
    checkpointing.save_checkpoint(
        str(tmp_path), 3, model.init(jax.random.PRNGKey(0)),
        args=checkpointing.config_to_args(cfg))

    a = _args("--num_layers=6", "--hidden_size=32",
              "--num_attention_heads=2", "--seq_length=16",
              "--micro_batch_size=1")
    a.load = str(tmp_path)
    a.load_iters = None
    _apply_checkpoint_args(a)
    assert a.num_layers == 2
    assert a.hidden_size == 64
    assert a.num_attention_heads == 4
    assert a.use_rms_norm is True
    assert a.use_bias is False


def test_fused_ce_auto_policy():
    """VERDICT r4 #7: fused_lm_cross_entropy auto-enables at >= 128k
    vocab (compile-evidence flip), stays off at 32k, and an explicit
    --no_fused_lm_cross_entropy always wins."""
    base = ["--num_layers=2", "--hidden_size=64",
            "--num_attention_heads=4", "--seq_length=32",
            "--micro_batch_size=1"]
    small = _args(*base, "--vocab_size=32000")
    assert small.fused_lm_cross_entropy is False
    assert small.fused_ce_user_explicit is False
    big = _args(*base, "--vocab_size=131072")
    assert big.fused_lm_cross_entropy is True
    veto = _args(*base, "--vocab_size=131072",
                 "--no_fused_lm_cross_entropy")
    assert veto.fused_lm_cross_entropy is False
    assert veto.fused_ce_user_explicit is True
    forced = _args(*base, "--vocab_size=32000",
                   "--fused_lm_cross_entropy")
    assert forced.fused_lm_cross_entropy is True


def test_fused_ce_auto_policy_via_tokenizer_padding():
    """The tokenizer-derived vocab only exists after validate_args; the
    policy re-fires at padding time for non-explicit users."""
    from megatron_llm_tpu.tokenizer.tokenizer import (
        _vocab_size_with_padding)
    a = _args("--num_layers=2", "--hidden_size=64",
              "--num_attention_heads=4", "--seq_length=32",
              "--micro_batch_size=1", "--vocab_size=32000")
    assert a.fused_lm_cross_entropy is False
    _vocab_size_with_padding(140000, a)
    assert a.fused_lm_cross_entropy is True
    # explicit opt-out survives the tokenizer hook too
    b = _args("--num_layers=2", "--hidden_size=64",
              "--num_attention_heads=4", "--seq_length=32",
              "--micro_batch_size=1", "--vocab_size=32000",
              "--no_fused_lm_cross_entropy")
    _vocab_size_with_padding(140000, b)
    assert b.fused_lm_cross_entropy is False


def test_fused_ce_policy_tp_sharded_vocab_is_inert():
    """tp>1 shards the vocab; the fused path never engages there
    (models/gpt.py gates on an unsharded vocab), so the policy must not
    advertise it."""
    a = _args("--num_layers=2", "--hidden_size=64",
              "--num_attention_heads=4", "--seq_length=32",
              "--micro_batch_size=1", "--vocab_size=131072",
              "--tensor_model_parallel_size=8")
    assert a.fused_lm_cross_entropy is False


def test_fused_ce_policy_survives_second_validate():
    """--use_checkpoint_args re-runs validate_args after the checkpoint
    restores a big vocab: the policy must re-fire, not be fossilized by
    the first pass's small-vocab resolution."""
    a = _args("--num_layers=2", "--hidden_size=64",
              "--num_attention_heads=4", "--seq_length=32",
              "--micro_batch_size=1", "--vocab_size=32000")
    assert a.fused_lm_cross_entropy is False
    a.padded_vocab_size = 131072  # as _apply_checkpoint_args would
    a = validate_args(a, world_size=8)
    assert a.fused_lm_cross_entropy is True
    assert a.fused_ce_user_explicit is False
