"""ORQA/REALM evidence pipeline (VERDICT r3 #5): DPR wiki TSV ->
OpenRetrievalEvidenceDataset -> EvidenceIndexBuilder embedding run ->
RETRIEVER-EVAL recall@k, end to end through tasks/main.py.

Reference behavior: megatron/data/orqa_wiki_dataset.py:1-193 +
megatron/data/biencoder_dataset_utils.py:1-209 + tasks RETRIEVER-EVAL.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORDS = ["paris", "capital", "france", "rome", "italy", "berlin",
         "germany", "cat", "dog", "moon", "cheese", "king"]


def _write_vocab(path):
    toks = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"] + WORDS
    path.write_text("\n".join(toks) + "\n")


def _write_evidence(path):
    rows = [
        (1, "paris is the capital of france", "france"),
        (2, "rome is the capital of italy", "italy"),
        (3, "berlin is the capital of germany", "germany"),
        (4, "the cat chased the dog", "animals"),
        (5, "the moon is not made of cheese", "moon"),
        (6, "the king lives in the capital", "royalty"),
    ]
    with open(path, "w") as f:
        f.write("id\ttext\ttitle\n")
        for doc_id, text, title in rows:
            f.write(f"{doc_id}\t{text}\t{title}\n")
    return rows


class _Tok:
    """Whitespace tokenizer over the fixture vocab (cls=2, sep=3, pad=0)."""
    cls, sep, pad, mask = 2, 3, 0, 4

    def tokenize(self, text):
        base = 5
        return [base + WORDS.index(w) for w in text.lower().split()
                if w in WORDS]

    def detokenize(self, ids):
        return " ".join(WORDS[i - 5] for i in ids if 5 <= i < 5 + len(WORDS))


def test_evidence_dataset_rows(tmp_path):
    from megatron_llm_tpu.data.orqa_wiki_dataset import (
        OpenRetrievalEvidenceDataset,
        evidence_batches,
    )

    tsv = tmp_path / "wiki.tsv"
    rows = _write_evidence(tsv)
    ds = OpenRetrievalEvidenceDataset(str(tsv), _Tok(), max_seq_length=12)
    assert len(ds) == len(rows)
    assert ds.id2text[1] == ("paris is the capital of france", "france")

    s = ds[0]
    assert s["row_id"] == 1
    # [CLS] title [SEP] text... [SEP] then pad
    assert s["context"][0] == _Tok.cls
    assert _Tok.sep in s["context"].tolist()
    assert s["context"].shape == (12,)
    n_real = int(s["context_pad_mask"].sum())
    assert (s["context"][n_real:] == _Tok.pad).all()

    batches = list(evidence_batches(ds, batch_size=4))
    assert [b["context"].shape[0] for b in batches] == [4, 2]
    assert batches[0]["row_id"].tolist() == [1, 2, 3, 4]


def test_trim_overlong_context():
    from megatron_llm_tpu.data.orqa_wiki_dataset import (
        build_tokens_types_paddings_from_ids,
    )

    ids, types, mask = build_tokens_types_paddings_from_ids(
        list(range(5, 25)), 8, cls_id=2, sep_id=3, pad_id=0)
    assert len(ids) == 8 and ids[0] == 2 and ids[-1] == 3
    assert mask.sum() == 8


def test_evidence_index_builder_roundtrip(tmp_path):
    import jax

    from megatron_llm_tpu.data.orqa_wiki_dataset import (
        OpenRetrievalEvidenceDataset,
    )
    from megatron_llm_tpu.data.realm_index import (
        BruteForceMIPSIndex,
        OpenRetrievalDataStore,
    )
    from megatron_llm_tpu.indexer import EvidenceIndexBuilder
    from megatron_llm_tpu.models.bert import bert_config
    from megatron_llm_tpu.models.biencoder import BiEncoderModel

    tsv = tmp_path / "wiki.tsv"
    _write_evidence(tsv)
    ds = OpenRetrievalEvidenceDataset(str(tsv), _Tok(), max_seq_length=12)

    cfg = bert_config(num_layers=1, hidden_size=32, num_attention_heads=4,
                      ffn_hidden_size=64, padded_vocab_size=32,
                      seq_length=12, max_position_embeddings=12)
    model = BiEncoderModel(cfg, projection_dim=8)
    params = model.init(jax.random.PRNGKey(0))

    emb_path = str(tmp_path / "emb.pkl")
    EvidenceIndexBuilder(model, params, ds, emb_path,
                         batch_size=4).build_and_save_index()

    store = OpenRetrievalDataStore(emb_path)
    assert set(store.embed_data) == {1, 2, 3, 4, 5, 6}
    # the stored embedding must be exactly the context-tower output for
    # the same row (the builder embedded what the dataset produced)
    want = np.asarray(model.embed_context(
        params,
        np.stack([ds[0]["context"]]).astype(np.int32),
        np.stack([ds[0]["context_pad_mask"]]).astype(np.int32)))[0]
    # the store quantizes to fp16 (realm_index.add_block_data, matching
    # the reference's hashed-index memory format)
    np.testing.assert_allclose(
        np.asarray(store.embed_data[1], np.float32), want, atol=2e-3)
    # and MIPS over the store returns valid doc ids
    index = BruteForceMIPSIndex(8, store)
    _, top = index.search_mips_index(want[None], top_k=6)
    assert set(int(i) for i in top[0]) == {1, 2, 3, 4, 5, 6}


def test_retriever_eval_end_to_end_via_tasks_main(tmp_path):
    """tasks/main.py --task RETRIEVER-EVAL on a tiny wiki TSV: builds the
    evidence embedding store, retrieves, and reports NONZERO recall@k
    (answers present in the corpus; k = corpus size makes recall@k = 1
    even for a random retriever — the assertion is the pipeline, not the
    model quality)."""
    tsv = tmp_path / "wiki.tsv"
    _write_evidence(tsv)
    vocab = tmp_path / "vocab.txt"
    _write_vocab(vocab)
    qa = tmp_path / "qa.jsonl"
    qa.write_text(
        json.dumps({"question": "capital of france", "answers": ["paris"]})
        + "\n"
        + json.dumps({"question": "capital of italy", "answers": ["rome"]})
        + "\n")
    emb = tmp_path / "emb.pkl"

    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tasks", "main.py"),
         "--task", "RETRIEVER-EVAL",
         "--evidence_data_path", str(tsv),
         "--embedding_path", str(emb),
         "--qa_data_dev", str(qa),
         "--tokenizer_type", "BertWordPieceLowerCase",
         "--vocab_file", str(vocab),
         "--num_layers", "1", "--hidden_size", "32",
         "--num_attention_heads", "4", "--ffn_hidden_size", "64",
         "--seq_length", "16", "--max_position_embeddings", "16",
         "--micro_batch_size", "1",
         "--biencoder_projection_dim", "8",
         "--retriever_report_topk_accuracies", "1", "6"],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert os.path.exists(emb), "embedding store was not built"
    out = proc.stdout
    assert "recall@6" in out, out[-2000:]
    import re

    m = re.search(r"recall@6: ([0-9.]+)%", out)
    assert m, out[-2000:]
    assert float(m.group(1)) > 0.0, "recall@6 must be nonzero"
