"""Declarative sweep manifest (tools/tpu_sweep.py): manifest validity,
plan/settle-state logic, the fresh-launch reset policy, and the step
runner's done / gave-up marking.  No TPU and no real sweep commands —
the runner is exercised with stub commands and zero backoff."""

import os
import subprocess
import sys

import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

import tpu_sweep  # noqa: E402
from tpu_sweep import Step  # noqa: E402


def test_manifest_is_valid_and_has_multislice_smoke():
    tpu_sweep.validate_manifest()
    names = [s.name for s in tpu_sweep.MANIFEST]
    assert len(names) == len(set(names))
    smoke = next(s for s in tpu_sweep.MANIFEST
                 if s.name == "multislice_smoke")
    assert not smoke.needs_tpu                  # runs on the CPU mesh
    assert "--num_slices=2" in smoke.cmd
    assert smoke.env.get("JAX_PLATFORMS") == "cpu"
    # the original shell playbook's steps all survived the refactor
    for legacy in ("fusedbwd", "seq4096", "bigvocab", "bench_final",
                   "moe", "long", "decode", "optstate"):
        assert legacy in names


def test_validate_rejects_bad_manifests():
    with pytest.raises(ValueError):
        tpu_sweep.validate_manifest(
            [Step("a", "true", 10), Step("a", "true", 10)])
    with pytest.raises(ValueError):
        tpu_sweep.validate_manifest([Step("a", "true", 0)])
    with pytest.raises(ValueError):
        tpu_sweep.validate_manifest([Step("a", "true", 10, wave=3)])
    with pytest.raises(ValueError):
        tpu_sweep.validate_manifest([Step("a", "  ", 10)])


def test_ordered_runs_wave1_first():
    order = tpu_sweep.ordered()
    waves = [s.wave for s in order]
    assert waves == sorted(waves)
    # stable within a wave: manifest order preserved
    w1 = [s.name for s in order if s.wave == 1]
    assert w1 == [s.name for s in tpu_sweep.MANIFEST if s.wave == 1]


def test_plan_and_settle_state(tmp_path):
    marks = str(tmp_path)
    manifest = [Step("x", "true", 10), Step("y", "true", 10, wave=2)]
    assert [s.name for s in tpu_sweep.plan(marks, manifest)] == ["x", "y"]
    open(os.path.join(marks, "x.done"), "w").close()
    assert [s.name for s in tpu_sweep.plan(marks, manifest)] == ["y"]
    assert tpu_sweep.step_state(marks, "x") == "done"
    assert not tpu_sweep.all_settled(marks, manifest)
    open(os.path.join(marks, "y.gaveup"), "w").close()
    assert tpu_sweep.step_state(marks, "y") == "gave-up"
    assert tpu_sweep.all_settled(marks, manifest)
    assert tpu_sweep.plan(marks, manifest) == []


def test_reset_for_launch_retries_exhausted_honors_done(tmp_path):
    marks = str(tmp_path)
    manifest = [Step("x", "true", 10), Step("y", "true", 10)]
    open(os.path.join(marks, "x.done"), "w").close()
    open(os.path.join(marks, "y.gaveup"), "w").close()
    with open(os.path.join(marks, "y.attempts"), "w") as f:
        f.write("4")
    tpu_sweep.reset_for_launch(marks, manifest)
    assert tpu_sweep.step_state(marks, "x") == "done"       # honored
    assert tpu_sweep.step_state(marks, "y") == "never-ran"  # retried
    assert tpu_sweep.attempts(marks, "y") == 0


def test_run_step_marks_done_and_gaveup(tmp_path):
    marks = str(tmp_path / "marks")
    logs = str(tmp_path / "logs")
    os.makedirs(marks)
    os.makedirs(logs)

    ok = Step("ok", "true", 30, needs_tpu=False)
    assert tpu_sweep.run_step(ok, marks, logs, backoff_secs=0)
    assert tpu_sweep.step_state(marks, "ok") == "done"
    # settled steps are not re-run
    assert tpu_sweep.run_step(ok, marks, logs, backoff_secs=0)
    assert tpu_sweep.attempts(marks, "ok") == 1

    bad = Step("bad", "false", 30, needs_tpu=False)
    for i in range(2):
        assert not tpu_sweep.run_step(bad, marks, logs, max_attempts=2,
                                      backoff_secs=0)
    assert tpu_sweep.attempts(marks, "bad") == 2
    # attempt 3 > max_attempts: marked gave-up (settled), no command run
    assert tpu_sweep.run_step(bad, marks, logs, max_attempts=2,
                              backoff_secs=0)
    assert tpu_sweep.step_state(marks, "bad") == "gave-up"


def test_run_step_env_and_log(tmp_path):
    marks = str(tmp_path / "marks")
    logs = str(tmp_path / "logs")
    os.makedirs(marks)
    os.makedirs(logs)
    s = Step("echoer", 'sh -c "echo VAL=$SWEEP_PROBE_VAR"', 30,
             needs_tpu=False, env={"SWEEP_PROBE_VAR": "hello"})
    assert tpu_sweep.run_step(s, marks, logs, backoff_secs=0)
    with open(os.path.join(logs, "hunt_echoer.log")) as f:
        assert "VAL=hello" in f.read()


def test_cli_list_and_dry_run(tmp_path):
    env = dict(os.environ)
    tools = os.path.dirname(os.path.abspath(tpu_sweep.__file__))
    out = subprocess.run(
        [sys.executable, os.path.join(tools, "tpu_sweep.py"),
         "--list", "--marks", str(tmp_path)],
        capture_output=True, text=True, env=env, timeout=60)
    assert out.returncode == 0, out.stderr
    assert "multislice_smoke" in out.stdout
    assert "never-ran" in out.stdout

    out = subprocess.run(
        [sys.executable, os.path.join(tools, "tpu_sweep.py"),
         "--dry-run", "--marks", str(tmp_path)],
        capture_output=True, text=True, env=env, timeout=60)
    assert out.returncode == 0, out.stderr
    assert "multislice_smoke" in out.stdout
    for s in tpu_sweep.MANIFEST:
        assert s.name in out.stdout
