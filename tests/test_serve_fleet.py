"""Fleet supervisor chaos e2e (slow tier; tools/tpu_sweep.py runs this
file as the wave-2 ``serve_fleet_chaos`` step).

Real tiny-model engine subprocesses (tests/_serve_replica.py) under a
live :class:`FleetSupervisor`:

* a piecewise-rate spike (serve_bench ``--rate_schedule``) breaches the
  queue-depth SLO -> the supervisor spawns a replica -> post-scale-up
  TTFT p95 recovers, with zero dropped requests and zero engine
  restarts;
* a mid-burst SIGKILL is healed by respawn under the same slot while
  the router's failover finishes the burst exactly once;
* the supervisor control loop itself (observe/decide/act + brownout)
  adds ZERO steady-state compiles to an in-process engine it manages.
"""

import json
import os
import signal
import sys
import threading
import time
from pathlib import Path

import pytest

from megatron_llm_tpu.serving.router import ReplicaRouter, RouterServer
from megatron_llm_tpu.serving.supervisor import (
    FleetSupervisor,
    LocalProcessBackend,
    PolicyConfig,
    ReplicaBackend,
)

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))
import serve_bench  # noqa: E402

pytestmark = [pytest.mark.slow, pytest.mark.chaos]

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _replica_backend(spawn_eta_secs=90.0):
    """LocalProcessBackend over the tiny-model replica, queue bound
    raised so a spike backlogs (visible queue depth) instead of 429ing."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)  # single-device child, no 8-dev mesh
    return LocalProcessBackend(
        [sys.executable, os.path.join(ROOT, "tests", "_serve_replica.py"),
         "--serve_max_queue_depth", "2048",
         "--serve_deadline_secs", "600"],
        env=env, cwd=ROOT, spawn_eta_secs=spawn_eta_secs)


def _start_router_server(router):
    srv = RouterServer(router)
    threading.Thread(target=srv.run,
                     kwargs={"host": "127.0.0.1", "port": 0},
                     daemon=True).start()
    for _ in range(100):
        if srv.httpd is not None:
            break
        time.sleep(0.05)
    assert srv.httpd is not None
    return srv, f"http://127.0.0.1:{srv.httpd.server_address[1]}"


def _wait(predicate, timeout, what):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.25)
    raise AssertionError(f"timed out waiting for {what}")


def test_autoscale_spike_recovers_with_zero_drops(tmp_path):
    """Acceptance: spike -> sustained queue-depth breach -> scale_up +
    brownout events -> new replica registers -> post-scale-up TTFT p95
    back under the pre-spike level.  Every request answers 200 (the
    backlog absorbs the spike; the router requeues nothing away) and
    the fleet aggregate reports zero engine restarts."""
    backend = _replica_backend()
    router = ReplicaRouter([], fail_threshold=3, cooldown_secs=2.0,
                           health_interval_secs=1.0,
                           request_timeout_secs=300.0)
    cfg = PolicyConfig(
        ttft_p95_slo_secs=1e9,      # breach on queue depth, not TTFT
        queue_depth_high=4, breach_secs=0.75,
        scale_cooldown_secs=3600.0,  # at most one scale-up
        scale_down_idle_secs=3600.0,
        min_replicas=1, max_replicas=2,
        respawn_backoff_secs=0.5, dead_confirmation_secs=5.0)
    log = tmp_path / "fleet.jsonl"
    sup = FleetSupervisor(router, backend, config=cfg,
                          poll_interval_secs=0.25,
                          event_log_path=str(log))
    srv = None
    try:
        sup.spawn_initial(1)
        sup.start()
        _wait(lambda: router.snapshot()["backends_total"] == 1, 240.0,
              "first replica ready")
        srv, url = _start_router_server(router)

        # spike: a dense 2s burst (~400 arrivals) against a single
        # 4-slot tiny-model replica — the backlog outlives the burst,
        # so the engine queue stays past the breach while it drains
        spike = serve_bench.run_bench(
            url, clients=64, requests=999, tokens=16, stream=True,
            timeout=280.0, seed=11, rate_schedule="1:3,200:2")
        assert spike["errors"] == 0, spike["status_counts"]
        assert set(spike["status_counts"]) == {"200"}

        assert sup.counters["scale_ups_total"] >= 1, \
            "spike never triggered a scale-up"
        _wait(lambda: router.snapshot()["backends_total"] == 2, 240.0,
              "scaled-up replica ready")
        assert router.brownout_remaining() == 0.0   # closed on arrival

        # post-scale-up: the same light load now spreads over 2
        # replicas with an empty queue — p95 TTFT recovers
        calm = serve_bench.run_bench(
            url, clients=4, requests=999, tokens=16, stream=True,
            timeout=280.0, seed=12, rate_schedule="1:6")
        assert calm["errors"] == 0, calm["status_counts"]
        assert calm["ttft_p95_secs"] < spike["ttft_p95_secs"], \
            (calm["ttft_p95_secs"], spike["ttft_p95_secs"])

        # healing never happened and no engine restarted underneath us
        agg = router.aggregated_metrics()["aggregate"]
        assert agg["engine"]["engine_restarts"] == 0
        assert sup.counters["deaths_total"] == 0
        events = [json.loads(l)["event"]
                  for l in log.read_text().splitlines()]
        assert events.count("replica_spawned") == 2
        assert "scale_up" in events and "brownout" in events
    finally:
        if srv is not None:
            srv.shutdown()
            srv.httpd.server_close()
        sup.stop(kill_replicas=True)


def test_sigkill_mid_burst_respawned_and_exactly_once():
    """Acceptance: SIGKILL one of two replicas mid-burst — the router
    fails the in-flight work over (zero drops, one answer per request)
    and the supervisor respawns the dead slot back to a 2-replica
    fleet."""
    import urllib.request

    backend = _replica_backend()
    router = ReplicaRouter([], fail_threshold=2, cooldown_secs=5.0,
                           health_interval_secs=1.0,
                           request_timeout_secs=300.0)
    cfg = PolicyConfig(
        ttft_p95_slo_secs=1e9, queue_depth_high=10 ** 9,
        scale_cooldown_secs=3600.0, scale_down_idle_secs=3600.0,
        min_replicas=2, max_replicas=2,
        respawn_backoff_secs=0.5, dead_confirmation_secs=5.0)
    sup = FleetSupervisor(router, backend, config=cfg,
                          poll_interval_secs=0.5)
    srv = None
    try:
        sup.spawn_initial(2)
        sup.start()
        _wait(lambda: router.snapshot()["backends_total"] == 2, 300.0,
              "both replicas ready")
        srv, url = _start_router_server(router)

        victim_proc = sup.replicas["replica-0"].handle.proc
        n = 24
        results = []
        lock = threading.Lock()
        tail = " ".join(["2"] * 13) + " 3"

        def client(i):
            req = urllib.request.Request(
                url + "/api",
                data=json.dumps({"prompts": [f"{i} {tail}"],
                                 "tokens_to_generate": 16,
                                 "temperature": 0.0,
                                 "no_log": True}).encode(),
                method="PUT")
            with urllib.request.urlopen(req, timeout=280) as resp:
                r = (i, resp.status, json.loads(resp.read()))
            with lock:
                results.append(r)

        def killer():
            time.sleep(1.0)
            victim_proc.send_signal(signal.SIGKILL)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(n)]
        kt = threading.Thread(target=killer)
        for t in threads:
            t.start()
        kt.start()
        for t in threads:
            t.join(timeout=300)
        kt.join()

        # exactly once: every ticket answered, answered 200, no dupes
        assert sorted(i for i, _, _ in results) == list(range(n))
        assert all(s == 200 for _, s, _ in results)
        assert router.failovers_total >= 1

        # self-healing: the dead slot comes back under its own name
        _wait(lambda: sup.counters["respawns_total"] >= 1, 300.0,
              "respawn of the SIGKILLed replica")
        _wait(lambda: router.snapshot()["backends_total"] == 2, 120.0,
              "respawned replica registered")
        assert sup.counters["deaths_total"] >= 1
        assert sup.replicas["replica-0"].state == "ready"
        names = [e["event"] for e in sup.events]
        assert "replica_died" in names and "replica_respawned" in names
    finally:
        if srv is not None:
            srv.shutdown()
            srv.httpd.server_close()
        sup.stop(kill_replicas=True)


# ---------------------------------------------------------------------------
# zero-recompile guard with the supervisor in the loop
# ---------------------------------------------------------------------------

class _InProcessBackend(ReplicaBackend):
    """Adapter for an already-running in-process server: the supervisor
    exercises its full observe/decide/act loop against it without
    owning a child process."""

    spawn_eta_secs = 1.0

    def __init__(self, url):
        self.url = url

    def spawn(self):
        return object()

    def poll(self, handle):
        return "ready", self.url

    def kill(self, handle):
        pass


def test_supervisor_loop_zero_steady_state_recompiles():
    """Acceptance: the control loop (merged-histogram observation,
    windowed percentiles, policy, brownout bookkeeping) is host-side
    only — with a RecompileDetector armed after warmup, serving through
    a supervised router triggers zero compiles."""
    import jax

    from megatron_llm_tpu import tracing
    from megatron_llm_tpu.models.llama import LlamaModel, llama_config
    from megatron_llm_tpu.serving import EngineConfig, InferenceEngine
    from megatron_llm_tpu.text_generation_server import MegatronServer

    class _Tok:
        vocab_size = 64
        eod = 63
        pad = 0

        def tokenize(self, text):
            return [int(t) % 64 for t in text.split()]

        def detokenize(self, ids):
            return " ".join(str(i) for i in ids)

    cfg = llama_config("tiny", num_layers=2, seq_length=64,
                       max_position_embeddings=64, padded_vocab_size=64,
                       use_flash_attn=False)
    model = LlamaModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    eng = InferenceEngine(model, params, EngineConfig(
        num_slots=4, block_size=8, prefill_chunk=16, max_model_len=64,
        max_queue_depth=64, default_deadline_secs=0.0))
    eng.warmup()
    eng.start()
    server = MegatronServer(model, params, _Tok(), engine=eng,
                            max_prompts=4, max_tokens=32)
    st = threading.Thread(target=server.run,
                          kwargs={"host": "127.0.0.1", "port": 0},
                          daemon=True)
    st.start()
    for _ in range(200):
        if server.httpd is not None:
            break
        time.sleep(0.05)
    assert server.httpd is not None
    url = f"http://127.0.0.1:{server.httpd.server_address[1]}"

    router = ReplicaRouter([], health_interval_secs=999.0)
    sup = FleetSupervisor(router, _InProcessBackend(url),
                          config=PolicyConfig(
                              ttft_p95_slo_secs=1e9,
                              queue_depth_high=10 ** 9,
                              scale_cooldown_secs=3600.0,
                              scale_down_idle_secs=3600.0,
                              min_replicas=1, max_replicas=1))
    tracer = tracing.SpanTracer()
    det = tracing.RecompileDetector(tracer)
    tracing.install_tracing(tracing.Tracing(tracer=tracer,
                                            recompile=det))
    try:
        sup.spawn_initial(1)
        sup.run_once()
        assert router.snapshot()["backends_total"] == 1
        det.mark_steady()
        for i in range(6):
            status, _, body = router.dispatch(
                "PUT", "/api",
                json.dumps({"prompts": [f"{i} 2 3 4"],
                            "tokens_to_generate": 8,
                            "temperature": 0.0,
                            "no_log": True}).encode())
            assert status == 200, body
            sup.run_once()      # observe (metrics + histograms) + decide
        assert det.recompiles == 0, \
            f"{det.recompiles} recompiles: {list(det.events)}"
    finally:
        tracing.install_tracing(None)
        sup.stop(kill_replicas=False)
        router.stop()
        eng.stop()
        if server.httpd is not None:
            server.httpd.shutdown()
