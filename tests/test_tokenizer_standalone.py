"""Standalone (transformers-free) tokenizer backends must match the HF
fast backends token-for-token on the same vocab files — WordPiece
(tokenizer/wordpiece.py) and GPT-2 byte-level BPE (tokenizer/bpe.py)."""

import json

import pytest

from megatron_llm_tpu.tokenizer.bpe import StandaloneGPT2BPE
from megatron_llm_tpu.tokenizer.wordpiece import StandaloneWordPiece

WP_VOCAB = [
    "[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]",
    "the", "quick", "brown", "fox", "jump", "##s", "##ed", "##ing",
    "over", "lazy", "dog", "run", "##ner", "!", ",", ".", "$", "@",
    "2", "##0", "##2", "##4", "cafe", "中", "文",
]

TEXTS_WP = [
    "The quick brown fox jumps over the lazy dog.",
    "runner running, jumped!",
    "Café CAFE cafe",           # accent strip + lowercase
    "$2024 @the",               # symbol splitting
    "中文 the dog",              # CJK per-character
    "unknownword the",          # [UNK] path
    "the [MASK] fox [SEP]",     # special tokens stay atomic
    "",
]


@pytest.fixture(scope="module")
def wp_pair(tmp_path_factory):
    p = tmp_path_factory.mktemp("wp") / "vocab.txt"
    p.write_text("\n".join(WP_VOCAB) + "\n")
    standalone = StandaloneWordPiece(str(p))
    hf = pytest.importorskip("transformers").BertTokenizerFast(
        vocab_file=str(p), do_lower_case=True)
    return standalone, hf


def test_wordpiece_matches_hf(wp_pair):
    standalone, hf = wp_pair
    for text in TEXTS_WP:
        got = standalone.encode(text, add_special_tokens=False)
        want = hf.encode(text, add_special_tokens=False)
        assert got == want, (text, got, want)


def test_wordpiece_special_token_growth(wp_pair):
    standalone, _ = wp_pair
    n0 = len(standalone)
    standalone.add_special_tokens({"bos_token": "[BOS]",
                                   "eos_token": "[EOS]"})
    assert standalone.bos_token_id == n0
    assert standalone.eos_token_id == n0 + 1
    standalone.add_special_tokens(
        {"additional_special_tokens": ["<extra_id_0>", "<extra_id_1>"]})
    assert standalone.additional_special_tokens_ids == [n0 + 2, n0 + 3]


def test_wordpiece_decode_joins_continuations(wp_pair):
    standalone, _ = wp_pair
    ids = standalone.encode("jumps", add_special_tokens=False)
    assert standalone.decode(ids) == "jumps"


# ---------------------------------------------------------------------------
# GPT-2 byte-level BPE
# ---------------------------------------------------------------------------

def _mini_bpe_files(tmp_path):
    """A miniature but complete GPT-2-format vocab: every base
    byte-unicode symbol + a few merges + <|endoftext|>."""
    from megatron_llm_tpu.tokenizer.bpe import bytes_to_unicode

    base = list(bytes_to_unicode().values())
    merges = [("h", "e"), ("l", "l"), ("he", "ll"), ("o", "w"),
              ("Ġ", "w"), ("Ġw", "o"), ("hell", "o"), ("Ġwo", "rld")]
    # merge outputs must exist in the vocab; 'rld' pieces come from base
    extra = ["he", "ll", "hell", "ow", "Ġw", "Ġwo", "hello", "rl",
             "Ġworld", "rld"]
    merges.insert(0, ("r", "l"))
    merges.insert(1, ("rl", "d"))
    vocab = {t: i for i, t in enumerate(base + extra + ["<|endoftext|>"])}
    vf = tmp_path / "vocab.json"
    vf.write_text(json.dumps(vocab))
    mf = tmp_path / "merges.txt"
    mf.write_text("#version: 0.2\n"
                  + "\n".join(" ".join(m) for m in merges) + "\n")
    return str(vf), str(mf)


TEXTS_BPE = [
    "hello world",
    "hello <|endoftext|> world",   # special token stays atomic
    "hello hello world!",
    "  spaces   and\nnewlines",
    "unicode: café 中文 🙂",
    "",
]


def test_gpt2_bpe_matches_hf(tmp_path):
    vf, mf = _mini_bpe_files(tmp_path)
    standalone = StandaloneGPT2BPE(vf, mf)
    transformers = pytest.importorskip("transformers")
    hf = transformers.GPT2TokenizerFast(vocab_file=vf, merges_file=mf)
    for text in TEXTS_BPE:
        got = standalone.encode(text)
        want = hf.encode(text)
        assert got == want, (text, got, want)
        assert standalone.decode(got) == hf.decode(want)


def test_gpt2_bpe_roundtrip_arbitrary_bytes(tmp_path):
    vf, mf = _mini_bpe_files(tmp_path)
    standalone = StandaloneGPT2BPE(vf, mf)
    text = "hello world \t ~ § ß 中"
    assert standalone.decode(standalone.encode(text)) == text


def test_wrapper_uses_standalone_when_transformers_missing(tmp_path,
                                                          monkeypatch):
    """_BertWordPieceTokenizer / _GPT2BPETokenizer fall back to the
    standalone backends when transformers cannot import."""
    import builtins

    real_import = builtins.__import__

    def no_transformers(name, *a, **kw):
        if name == "transformers" or name.startswith("transformers."):
            raise ImportError("blocked for test")
        return real_import(name, *a, **kw)

    monkeypatch.setattr(builtins, "__import__", no_transformers)

    from megatron_llm_tpu.tokenizer.tokenizer import (
        _BertWordPieceTokenizer,
        _GPT2BPETokenizer,
    )

    wp_vf = tmp_path / "v.txt"
    wp_vf.write_text("\n".join(WP_VOCAB) + "\n")
    tok = _BertWordPieceTokenizer(str(wp_vf))
    ids = tok.tokenize("the quick fox")
    assert ids and all(isinstance(i, int) for i in ids)
    assert tok.bos_token_id is not None and tok.cls is not None

    vf, mf = _mini_bpe_files(tmp_path)
    tok2 = _GPT2BPETokenizer(vf, mf)
    ids2 = tok2.tokenize("hello world")
    assert ids2 and tok2.detokenize(ids2) == "hello world"
    assert tok2.eod == tok2.vocab["<|endoftext|>"]
