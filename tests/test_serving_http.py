"""HTTP serving tests against the REAL MegatronServer handler with the
continuous-batching engine behind it: N concurrent clients all get 200s,
metrics carry request/error counts and sane percentiles, 400 paths
return JSON (never a dead socket), admission control returns 429 +
Retry-After, /api/stream serves SSE, and request logging is gated behind
--log_requests."""

import json
import threading
import time
import urllib.error
import urllib.request

import jax
import pytest

from megatron_llm_tpu.models.llama import LlamaModel, llama_config
from megatron_llm_tpu.serving import (
    EngineConfig,
    InferenceEngine,
    SamplingParams,
)
from megatron_llm_tpu.text_generation_server import MegatronServer


class _FakeTokenizer:
    vocab_size = 64
    eod = 63
    pad = 0

    def tokenize(self, text):
        return [int(t) % 64 for t in text.split()]

    def detokenize(self, ids):
        return " ".join(str(i) for i in ids)


@pytest.fixture(scope="module")
def model_and_params():
    cfg = llama_config("tiny", num_layers=2, seq_length=64,
                       max_position_embeddings=64, padded_vocab_size=64,
                       use_flash_attn=False)
    model = LlamaModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    return model, params


@pytest.fixture(scope="module")
def served(model_and_params):
    """MegatronServer.run (the real handler) on an ephemeral port, with
    an engine doing the generating."""
    model, params = model_and_params
    engine = InferenceEngine(model, params, EngineConfig(
        num_slots=4, block_size=8, prefill_chunk=16, max_model_len=64,
        max_queue_depth=32, default_deadline_secs=60.0))
    engine.warmup()
    engine.start()
    server = MegatronServer(model, params, _FakeTokenizer(),
                            engine=engine, max_prompts=4, max_tokens=32)
    t = threading.Thread(target=server.run,
                         kwargs={"host": "127.0.0.1", "port": 0},
                         daemon=True)
    t.start()
    for _ in range(100):
        if getattr(server, "httpd", None) is not None:
            break
        time.sleep(0.05)
    assert getattr(server, "httpd", None) is not None
    port = server.httpd.server_address[1]
    yield server, engine, f"http://127.0.0.1:{port}"
    server.httpd.shutdown()
    engine.stop()


def _put(url, payload, path="/api"):
    req = urllib.request.Request(
        url + path, data=json.dumps(payload).encode(), method="PUT")
    with urllib.request.urlopen(req, timeout=120) as resp:
        return resp.status, json.loads(resp.read())


def _put_expect_error(url, payload, path="/api"):
    try:
        _put(url, payload, path)
        raise AssertionError("expected HTTPError")
    except urllib.error.HTTPError as e:
        body = e.read()
        return e.code, (json.loads(body) if body else None), e.headers


def test_concurrent_clients_all_200_and_metrics(served):
    server, engine, url = served
    n = 16
    occ0, dec0 = engine.occupancy_sum, engine.decode_steps
    results = [None] * n

    def client(i):
        results[i] = _put(url, {"prompts": [f"{1 + i} 2 3"],
                                "tokens_to_generate": 12,
                                "temperature": 0.0, "no_log": True})

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for status, body in results:
        assert status == 200
        assert len(body["tokens"]) == 1 and len(body["text"]) == 1
        assert len(body["tokens"][0]) > 3    # prompt + generated
    # acceptance: decode batch occupancy > 1 under 16-client load
    occ = (engine.occupancy_sum - occ0) / max(engine.decode_steps - dec0, 1)
    assert occ > 1.0, f"no co-batching over HTTP: occupancy {occ}"
    with urllib.request.urlopen(url + "/metrics", timeout=30) as resp:
        m = json.loads(resp.read())
    assert m["requests"] >= n and m["errors"] == 0
    assert m["latency_p50_secs"] is not None
    assert m["latency_p95_secs"] >= m["latency_p50_secs"] > 0
    # engine counters ride /metrics
    assert m["engine"]["decode_steps"] > 0
    assert m["engine"]["mean_batch_occupancy"] > 0
    assert "queue_depth" in m["engine"]


def test_engine_response_matches_legacy_contract_shape(served):
    _, _, url = served
    status, body = _put(url, {"prompts": ["5 6 7"],
                              "tokens_to_generate": 4,
                              "temperature": 0.0, "no_log": True})
    assert status == 200
    assert set(body) == {"text", "segments", "tokens"}
    row = body["tokens"][0]
    assert row[:3] == [5, 6, 7]
    assert body["text"][0] == " ".join(str(t) for t in row)
    assert body["segments"][0] == [str(t) for t in row]


def test_temperature_zero_is_greedy_and_message_fixed(served):
    """Satellite: temperature 0.0 is an accepted, explicit greedy knob;
    the rejection message matches the actual range."""
    _, _, url = served
    s0, b0 = _put(url, {"prompts": ["5 6 7"], "tokens_to_generate": 6,
                        "temperature": 0.0, "no_log": True})
    s1, b1 = _put(url, {"prompts": ["5 6 7"], "tokens_to_generate": 6,
                        "top_k": 1, "no_log": True})
    assert s0 == s1 == 200
    assert b0["tokens"] == b1["tokens"]      # both greedy
    code, body, _ = _put_expect_error(
        url, {"prompts": ["1"], "tokens_to_generate": 4,
              "temperature": -0.5, "no_log": True})
    assert code == 400
    assert "[0, 100]" in body["message"]
    code, body, _ = _put_expect_error(
        url, {"prompts": ["1"], "tokens_to_generate": 4,
              "temperature": 101.0, "no_log": True})
    assert code == 400


def test_400_paths_return_json_not_dead_socket(served):
    _, _, url = served
    cases = [
        {"prompts": []},
        {"prompts": ["1 2"], "top_k": None},
        {"prompts": ["1 2"], "tokens_to_generate": -1},
        {"prompts": ["1 2"], "tokens_to_generate": 33},   # > max_tokens=32
        {"prompts": ["a", "b", "c", "d", "e"]},           # > max_prompts=4
        {"max_len": 5},
    ]
    for payload in cases:
        code, body, _ = _put_expect_error(url, payload)
        assert code == 400, payload
        assert isinstance(body, dict) and "message" in body, payload


def test_streaming_sse_over_http(served):
    _, _, url = served
    req = urllib.request.Request(
        url + "/api/stream",
        data=json.dumps({"prompts": ["5 6 7"], "tokens_to_generate": 6,
                         "temperature": 0.0, "no_log": True}).encode(),
        method="PUT")
    events = []
    with urllib.request.urlopen(req, timeout=120) as resp:
        assert resp.headers["Content-Type"] == "text/event-stream"
        for raw in resp:
            line = raw.strip()
            if line.startswith(b"data: "):
                events.append(json.loads(line[len(b"data: "):]))
    assert len(events) >= 2                 # incremental chunks + done
    assert all("token" in e for e in events[:-1])
    last = events[-1]
    assert last["done"] and last["finish_reason"] in ("stop", "length")
    assert last["tokens"][:3] == [5, 6, 7]
    streamed_ids = [e["token"] for e in events[:-1]]
    assert last["tokens"][3:] == streamed_ids


def test_streaming_multi_prompt_rejected(served):
    _, _, url = served
    code, body, _ = _put_expect_error(
        url, {"prompts": ["1", "2"], "tokens_to_generate": 4,
              "no_log": True}, path="/api/stream")
    assert code == 400 and "single prompt" in body["message"]


def test_admission_control_429_with_retry_after(model_and_params):
    """A saturated engine queue maps to HTTP 429 + Retry-After (the
    engine is never started, so the queue only fills)."""
    model, params = model_and_params
    engine = InferenceEngine(model, params, EngineConfig(
        num_slots=2, block_size=8, prefill_chunk=16, max_model_len=64,
        max_queue_depth=1))
    engine.submit([1, 2], SamplingParams(max_new_tokens=4))  # fill queue
    server = MegatronServer(model, params, _FakeTokenizer(), engine=engine)
    t = threading.Thread(target=server.run,
                         kwargs={"host": "127.0.0.1", "port": 0},
                         daemon=True)
    t.start()
    for _ in range(100):
        if getattr(server, "httpd", None) is not None:
            break
        time.sleep(0.05)
    url = f"http://127.0.0.1:{server.httpd.server_address[1]}"
    try:
        code, body, headers = _put_expect_error(
            url, {"prompts": ["1 2"], "tokens_to_generate": 4,
                  "no_log": True})
        assert code == 429
        assert "message" in body and "retry_after_secs" in body
        assert int(headers["Retry-After"]) >= 1
        with urllib.request.urlopen(url + "/metrics", timeout=30) as resp:
            m = json.loads(resp.read())
        assert m["throttled"] == 1
    finally:
        server.httpd.shutdown()
        engine.stop()


def test_log_requests_gating(served, capsys):
    """Satellite: payload logging is off by default, on with
    --log_requests, and still suppressible per-request via no_log."""
    server, _, _ = served
    gen = server.generator
    payload = {"prompts": ["5 6"], "tokens_to_generate": 2,
               "temperature": 0.0}
    assert gen.log_requests is False
    code, _ = gen.handle(dict(payload))
    assert code == 200
    assert json.dumps(payload) not in capsys.readouterr().out
    gen.log_requests = True
    try:
        code, _ = gen.handle(dict(payload))
        assert code == 200
        assert '"prompts": ["5 6"]' in capsys.readouterr().out
        code, _ = gen.handle(dict(payload, no_log=True))
        assert code == 200
        assert '"prompts": ["5 6"]' not in capsys.readouterr().out
    finally:
        gen.log_requests = False


def test_prometheus_exposition_format():
    """Unit: the text-exposition renderer flattens nested dicts, skips
    bools/None/non-numerics, sanitizes names, and types every sample."""
    from megatron_llm_tpu.text_generation_server import prometheus_exposition

    text = prometheus_exposition({
        "requests": 3,
        "latency_p50_secs": 0.5,
        "latency_p95_secs": None,          # empty-window percentile
        "flag": True,                      # bools are not gauges
        "note": "hi",                      # nor strings
        "engine": {"queue_depth": 2, "completed": {"eos!": 1}},
    })
    assert text.endswith("\n")
    lines = text.splitlines()
    assert "# TYPE megatron_serve_requests gauge" in lines
    assert "megatron_serve_requests 3" in lines
    assert "megatron_serve_latency_p50_secs 0.5" in lines
    assert "megatron_serve_engine_queue_depth 2" in lines
    assert "megatron_serve_engine_completed_eos_ 1" in lines   # sanitized
    assert not any("p95" in l or "flag" in l or "note" in l for l in lines)
    # every sample line is preceded by its TYPE line
    for i, l in enumerate(lines):
        if not l.startswith("#"):
            name = l.split()[0]
            assert lines[i - 1] == f"# TYPE {name} gauge"


def test_metrics_content_negotiation(served):
    """/metrics serves JSON by default, Prometheus text exposition with
    ?format=prometheus or an Accept: text/plain header."""
    _, _, url = served
    _put(url, {"prompts": ["1 2"], "tokens_to_generate": 2,
               "temperature": 0.0, "no_log": True})

    with urllib.request.urlopen(url + "/metrics", timeout=30) as resp:
        assert resp.headers["Content-Type"].startswith("application/json")
        json.loads(resp.read())

    with urllib.request.urlopen(url + "/metrics?format=prometheus",
                                timeout=30) as resp:
        assert resp.status == 200
        assert resp.headers["Content-Type"] == \
            "text/plain; version=0.0.4; charset=utf-8"
        body = resp.read().decode()
    assert "# TYPE megatron_serve_requests gauge" in body
    assert "megatron_serve_uptime_secs" in body
    assert "megatron_serve_engine_queue_depth" in body

    req = urllib.request.Request(url + "/metrics",
                                 headers={"Accept": "text/plain"})
    with urllib.request.urlopen(req, timeout=30) as resp:
        assert resp.headers["Content-Type"].startswith("text/plain")
        assert b"megatron_serve_requests" in resp.read()


# ---------------------------------------------------------------------------
# request-lifecycle tracing + SLO histograms
# ---------------------------------------------------------------------------

def _put_raw(url, payload, path="/api", headers=None):
    req = urllib.request.Request(
        url + path, data=json.dumps(payload).encode(), method="PUT",
        headers=headers or {})
    with urllib.request.urlopen(req, timeout=120) as resp:
        return resp.status, dict(resp.headers), json.loads(resp.read())


def test_trace_header_minted_and_echoed(served):
    _, _, url = served
    payload = {"prompts": ["5 6"], "tokens_to_generate": 2,
               "temperature": 0.0, "no_log": True}
    _, headers, _ = _put_raw(url, payload)
    minted = headers.get("X-Request-Trace")
    assert minted and len(minted) == 16
    int(minted, 16)                             # hex-parseable
    _, headers, _ = _put_raw(url, payload,
                             headers={"X-Request-Trace": "abcd" * 4})
    assert headers.get("X-Request-Trace") == "abcd" * 4


def test_request_done_record_carries_trace_and_phases(served, tmp_path):
    """The replica's request_done JSONL record carries the router-visible
    trace id plus the full phase attribution of the request's wall-clock
    (queue wait, admission, prefill compute, amortized decode, stream
    write) and a true engine-side TPOT."""
    from megatron_llm_tpu import telemetry

    _, _, url = served
    stream = telemetry.TelemetryStream(str(tmp_path))
    telemetry.install_stream(stream)
    tid = "0123456789abcdef"
    done = []
    try:
        _put_raw(url, {"prompts": ["5 6 7"], "tokens_to_generate": 6,
                       "temperature": 0.0, "no_log": True},
                 headers={"X-Request-Trace": tid})
        # the result signals before the engine thread retires the
        # request, so poll for the JSONL record before tearing down
        path = tmp_path / "telemetry.jsonl"
        for _ in range(100):
            if path.exists():
                records = [json.loads(line) for line
                           in path.read_text().splitlines()]
                done = [r for r in records
                        if r.get("event") == "request_done"
                        and r.get("trace_id") == tid]
                if done:
                    break
            time.sleep(0.05)
    finally:
        telemetry.install_stream(None)
        stream.close()
    assert len(done) == 1
    rec = done[0]
    assert rec["schema"] == telemetry.TELEMETRY_SCHEMA_VERSION
    assert rec["prompt_tokens"] == 3 and rec["new_tokens"] >= 1
    assert rec["prefill_computed_tokens"] == \
        rec["prompt_tokens"] - rec["cached_prompt_tokens"]
    phases = rec["phases"]
    assert set(phases) == {"queue_secs", "admission_secs", "prefill_secs",
                           "decode_secs", "stream_write_secs"}
    assert phases["queue_secs"] >= 0 and phases["prefill_secs"] > 0
    if rec["decode_tokens"] > 0:
        assert rec["tpot_secs"] > 0
        assert rec["tpot_secs"] * rec["decode_tokens"] == pytest.approx(
            phases["decode_secs"], rel=1e-3)


def test_spans_carry_trace_id(served):
    """Every engine span of a request carries its trace id, so the
    replica's Chrome trace can be stitched to the router's by id."""
    from megatron_llm_tpu import tracing

    _, _, url = served
    tracer = tracing.SpanTracer()
    tracing.install_tracing(tracing.Tracing(tracer=tracer))
    tid = "fedcba9876543210"
    try:
        _put_raw(url, {"prompts": ["6 7 8"], "tokens_to_generate": 6,
                       "temperature": 0.0, "no_log": True},
                 headers={"X-Request-Trace": tid})
        for _ in range(100):        # the final span lands at retire
            if any(ev["name"] == "request"
                   and ev["args"].get("trace") == tid
                   for ev in list(tracer._events)):
                break
            time.sleep(0.05)
    finally:
        tracing.install_tracing(None)
    events = list(tracer._events)
    by_name = {}
    for ev in events:
        by_name.setdefault(ev["name"], []).append(ev)
    for name in ("queue_wait", "prefill_chunk", "request"):
        tagged = [ev for ev in by_name.get(name, ())
                  if ev["args"].get("trace") == tid]
        assert tagged, f"no {name} span tagged with the trace id"
    # decode steps are batched: they carry the id in a `traces` list
    decode = [ev for ev in by_name.get("decode_step", ())
              if tid in (ev["args"].get("traces") or ())]
    assert decode, "no decode_step span listing the trace id"


def test_metrics_histograms_and_slo(served):
    _, _, url = served
    _put(url, {"prompts": ["3 4 5"], "tokens_to_generate": 4,
               "temperature": 0.0, "no_log": True})
    with urllib.request.urlopen(url + "/metrics", timeout=30) as resp:
        m = json.loads(resp.read())
    for name in ("ttft_secs", "tpot_secs", "e2e_secs", "queue_wait_secs"):
        h = m["histograms"][name]
        assert set(h) == {"buckets", "count", "sum"}
        assert h["count"] >= 1 and "+Inf" in h["buckets"]
        assert sum(h["buckets"].values()) == h["count"]
    assert m["slo"]["e2e_secs_p95"] > 0
    assert m["slo"]["ttft_secs_p50"] is not None
    with urllib.request.urlopen(url + "/metrics?format=prometheus",
                                timeout=30) as resp:
        body = resp.read().decode()
    assert "# TYPE megatron_serve_histograms_ttft_secs histogram" in body
    assert 'megatron_serve_histograms_ttft_secs_bucket{le="+Inf"}' in body
    assert "megatron_serve_histograms_e2e_secs_count" in body
    assert "megatron_serve_histograms_e2e_secs_sum" in body


def test_serve_report_matches_serve_bench(served, tmp_path):
    """Acceptance: on one mixed cached/uncached workload, the offline
    serve_report reproduces serve_bench's e2e p95 from the same run's
    JSONL (engine-side timing excludes HTTP overhead, hence the
    tolerance), with a phase breakdown and SLO attainment."""
    import sys as _sys
    from pathlib import Path as _Path

    _sys.path.insert(0, str(_Path(__file__).resolve().parent.parent
                            / "tools"))
    import serve_bench
    import serve_report
    from megatron_llm_tpu import telemetry

    _, _, url = served
    stream = telemetry.TelemetryStream(str(tmp_path))
    telemetry.install_stream(stream)
    try:
        bench = serve_bench.run_bench(
            url, clients=4, requests=12, tokens=8, prefix_tokens=12,
            shared_prefix_frac=0.5, seed=3)
        path = tmp_path / "telemetry.jsonl"
        for _ in range(100):        # wait for the last retire to land
            if path.exists() and sum(
                    1 for line in path.read_text().splitlines()
                    if "request_done" in line) >= 12:
                break
            time.sleep(0.05)
    finally:
        telemetry.install_stream(None)
        stream.close()
    assert bench["errors"] == 0

    report = serve_report.analyze([str(tmp_path)], ttft_slo=1000.0,
                                  tpot_slo=1000.0)
    assert report["summary"]["requests"] == 12
    assert report["traced"] == 12              # every request got an id
    # mixed workload: the shared 12-token header fills a block, so
    # repeats hit the prefix cache while unique-header requests miss
    assert report["by_cache"]["cache_hit"]["requests"] >= 1
    assert report["by_cache"]["cache_miss"]["requests"] >= 1
    # e2e p95 agreement within tolerance
    bench_p95 = bench["latency_p95_secs"]
    report_p95 = report["summary"]["e2e_p95_secs"]
    assert report_p95 is not None
    assert abs(report_p95 - bench_p95) <= max(0.5 * bench_p95, 0.3), \
        f"serve_report p95 {report_p95} vs serve_bench p95 {bench_p95}"
    # phase breakdown is populated
    assert report["phases"]["prefill_secs"]["mean_secs"] > 0
    assert report["phases"]["decode_secs"]["mean_secs"] > 0
    # unreachable SLOs attain 100%, impossible ones 0%
    assert report["slo"]["joint_attained"] == 1.0
    strict = serve_report.analyze([str(tmp_path)], ttft_slo=0.0,
                                  tpot_slo=0.0)
    assert strict["slo"]["ttft_attained"] == 0.0


def test_deadline_maps_to_503(model_and_params):
    """A request whose deadline expires mid-flight is a 503, not a 200
    with silently truncated output."""
    model, params = model_and_params
    engine = InferenceEngine(model, params, EngineConfig(
        num_slots=2, block_size=8, prefill_chunk=16, max_model_len=64,
        default_deadline_secs=1e-4))
    engine.warmup()
    engine.start()
    server = MegatronServer(model, params, _FakeTokenizer(), engine=engine)
    try:
        code, body = server.generator.handle(
            {"prompts": ["1 2 3 4"], "tokens_to_generate": 32,
             "no_log": True})
        assert code == 503
        assert "deadline" in body["message"]
    finally:
        engine.stop()
