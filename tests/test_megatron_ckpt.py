"""Megatron mp_rank checkpoint interop (reference checkpointing.py layout):
export -> re-import round trip, TP-shard merge, PP-stage merge, v<2.0 QKV
fixups, and logit parity through the model."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

torch = pytest.importorskip("torch")

from megatron_llm_tpu.models.llama import LlamaModel, llama_config
from weights_conversion.megatron_ckpt import (
    fix_qkv_ordering,
    load_reference_checkpoint,
    read_tracker,
    save_reference_checkpoint,
    )


def _tiny_model():
    cfg = llama_config("tiny", num_layers=2, hidden_size=64,
                       num_attention_heads=4, ffn_hidden_size=96,
                       padded_vocab_size=128, seq_length=32,
                       max_position_embeddings=32)
    return cfg, LlamaModel(cfg)


def _leaves_equal(a, b):
    la = jax.tree_util.tree_leaves_with_path(a)
    lb = dict(jax.tree_util.tree_leaves_with_path(b))
    assert len(la) == len(lb)
    for path, leaf in la:
        np.testing.assert_allclose(np.asarray(leaf, np.float32),
                                   np.asarray(lb[path], np.float32),
                                   rtol=0, atol=1e-6, err_msg=str(path))


def test_export_import_round_trip(tmp_path):
    cfg, model = _tiny_model()
    params = model.init(jax.random.PRNGKey(0))
    save_reference_checkpoint(str(tmp_path), 7, params, cfg)
    assert read_tracker(str(tmp_path)) == "7"
    assert (tmp_path / "iter_0000007" / "mp_rank_00"
            / "model_optim_rng.pt").exists()

    loaded, config, meta = load_reference_checkpoint(str(tmp_path))
    assert meta["checkpoint_version"] == 3.0
    assert config["num_layers"] == 2
    assert config["padded_vocab_size"] == 128
    assert not config["tie_embed_logits"]
    _leaves_equal(params, loaded)


def test_logit_parity_after_round_trip(tmp_path):
    cfg, model = _tiny_model()
    params = model.init(jax.random.PRNGKey(1))
    toks = jnp.asarray(
        np.random.RandomState(0).randint(0, 128, (1, 32)))
    ref_logits = model(params, toks)

    save_reference_checkpoint(str(tmp_path), 3, params, cfg)
    loaded, _, _ = load_reference_checkpoint(str(tmp_path))
    out = model(loaded, toks)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_logits),
                               rtol=0, atol=1e-5)


def test_tp_sharded_export_imports_identically(tmp_path):
    cfg, model = _tiny_model()
    params = model.init(jax.random.PRNGKey(2))
    save_reference_checkpoint(str(tmp_path / "tp2"), 1, params, cfg,
                              tensor_parallel=2)
    names = sorted(p.name for p in (tmp_path / "tp2"
                                    / "iter_0000001").iterdir())
    assert names == ["mp_rank_00", "mp_rank_01"]
    loaded, _, _ = load_reference_checkpoint(str(tmp_path / "tp2"))
    _leaves_equal(params, loaded)


def test_pp_sharded_import(tmp_path):
    """Synthesize a pp=2 reference checkpoint by re-filing a pp=1 export's
    layers into mp_rank_00_000 / mp_rank_00_001 with local indices."""
    cfg, model = _tiny_model()
    params = model.init(jax.random.PRNGKey(3))
    save_reference_checkpoint(str(tmp_path / "flat"), 1, params, cfg)
    sd = torch.load(tmp_path / "flat" / "iter_0000001" / "mp_rank_00"
                    / "model_optim_rng.pt", weights_only=False)
    lm = sd["model"]["language_model"]

    def stage_sd(stage):
        enc = {}
        for k, v in lm["encoder"].items():
            if k.startswith(f"layers.{stage}."):
                enc[k.replace(f"layers.{stage}.", "layers.0.")] = v
        out = {"model": {"language_model": {"encoder": enc}},
               "checkpoint_version": 3.0, "iteration": 1, "args": sd["args"]}
        if stage == 0:
            out["model"]["language_model"]["embedding"] = lm["embedding"]
        else:
            out["model"]["language_model"]["lm_head"] = lm["lm_head"]
            enc["final_layernorm.weight"] = \
                lm["encoder"]["final_layernorm.weight"]
        return out

    pp_dir = tmp_path / "pp2" / "iter_0000001"
    for stage in (0, 1):
        d = pp_dir / f"mp_rank_00_{stage:03d}"
        d.mkdir(parents=True)
        torch.save(stage_sd(stage), d / "model_optim_rng.pt")
    with open(tmp_path / "pp2" / "latest_checkpointed_iteration.txt",
              "w") as f:
        f.write("1")

    loaded, config, _ = load_reference_checkpoint(str(tmp_path / "pp2"))
    assert config["num_layers"] == 2
    _leaves_equal(params, loaded)


@pytest.mark.parametrize("version", [0, 1.0])
def test_qkv_version_fixup_import(tmp_path, version):
    """A v<2.0 checkpoint (old interleaved qkv row order) must import to
    the same params as its v2 counterpart."""
    cfg, model = _tiny_model()
    params = model.init(jax.random.PRNGKey(4))
    save_reference_checkpoint(str(tmp_path), 1, params, cfg)
    path = tmp_path / "iter_0000001" / "mp_rank_00" / "model_optim_rng.pt"
    sd = torch.load(path, weights_only=False)
    enc = sd["model"]["language_model"]["encoder"]
    nh, hd = 4, 64 // 4
    for k in list(enc):
        if k.endswith("attention.query_key_value.weight"):
            w = enc[k].numpy()          # v2 grouped layout [np,3,hn,...]
            x = w.reshape(nh, 3, hd, -1)
            if version == 0:            # v0 stored [3, np, hn, ...]
                old = np.swapaxes(x, 0, 1).reshape(w.shape)
            else:                       # v1 stored [np, hn, 3, ...]
                old = np.transpose(x, (0, 2, 1, 3)).reshape(w.shape)
            enc[k] = torch.from_numpy(np.ascontiguousarray(old))
    sd["checkpoint_version"] = version
    torch.save(sd, path)

    loaded, _, meta = load_reference_checkpoint(str(tmp_path))
    assert meta["checkpoint_version"] == float(version)
    _leaves_equal(params, loaded)


def test_fix_qkv_ordering_skips_gqa():
    w = np.arange(4 * 3 * 2 * 5, dtype=np.float32).reshape(-1, 5)
    out = fix_qkv_ordering(w, 1.0, num_heads=4, num_heads_kv=2, head_dim=2)
    np.testing.assert_array_equal(w, out)


def test_checkpoint_util_format_bridge(tmp_path):
    """tools/checkpoint_util.py converts megatron torch <-> orbax in one
    CLI call: megatron -> orbax -> megatron with identical weights."""
    import os
    import subprocess
    import sys

    REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cfg, model = _tiny_model()
    params = model.init(jax.random.PRNGKey(0))
    meg1 = tmp_path / "meg1"
    save_reference_checkpoint(str(meg1), 7, params, cfg)

    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"

    def run(src_fmt, dst_fmt, src, dst):
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "checkpoint_util.py"),
             "--load_dir", str(src), "--save_dir", str(dst),
             "--input_format", src_fmt, "--output_format", dst_fmt],
            env=env, cwd=REPO, capture_output=True, text=True, timeout=300)
        assert proc.returncode == 0, proc.stderr[-2000:]

    orb = tmp_path / "orb"
    run("megatron", "orbax", meg1, orb)
    meg2 = tmp_path / "meg2"
    run("orbax", "megatron", orb, meg2)

    got, _, meta = load_reference_checkpoint(str(meg2))
    _leaves_equal(got, params)
    assert int(meta["iteration"]) == 7


def test_qkv_bias_export_import_round_trip(tmp_path):
    """qwen2-style QKV biases survive the reference-layout export/import
    (TP-sharded both ways)."""
    from megatron_llm_tpu.models.qwen2 import Qwen2Model, qwen2_config

    cfg = qwen2_config("tiny", num_layers=2, hidden_size=64,
                       num_attention_heads=4, num_attention_heads_kv=4,
                       ffn_hidden_size=96, padded_vocab_size=128,
                       seq_length=32, max_position_embeddings=32)
    model = Qwen2Model(cfg)
    params = model.init(jax.random.PRNGKey(0))
    d = tmp_path / "meg"
    save_reference_checkpoint(str(d), 3, params, cfg, tensor_parallel=2)
    got, conf, meta = load_reference_checkpoint(str(d))
    _leaves_equal(got, params)
