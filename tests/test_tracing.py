"""Span tracing + goodput + straggler/recompile diagnostics
(megatron_llm_tpu/tracing.py): span nesting and ring eviction, the
Chrome trace_event export schema, goodput arithmetic on a synthetic
timeline, straggler flagging on synthetic per-host times, recompile
counting on a forced shape change, the tools/trace_report.py
summarizer, the acceptance-criteria tiny pretrain with --trace_dir,
rewind/rescue spans under injected faults, and the generation server's
/metrics + /health endpoints."""

import argparse
import importlib.util
import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from megatron_llm_tpu import global_vars, telemetry, tracing
from megatron_llm_tpu.config import ParallelConfig, TrainConfig
from megatron_llm_tpu.global_vars import get_counters
from megatron_llm_tpu.models.llama import LlamaModel, llama_config
from megatron_llm_tpu.parallel import sharding as sh
from megatron_llm_tpu.resilience import (
    FaultInjector,
    HangWatchdog,
    ResilienceConfig,
    ResilienceManager,
    recovery_counters,
)
from megatron_llm_tpu.telemetry import build_telemetry
from megatron_llm_tpu.text_generation_server import (
    MegatronServer,
    ServerMetrics,
)
from megatron_llm_tpu.tracing import (
    GOODPUT_CATEGORIES,
    GoodputAccounter,
    RecompileDetector,
    SpanTracer,
    StragglerDetector,
    Tracing,
    build_tracing,
    install_detector,
    install_tracing,
)
from megatron_llm_tpu.training import pretrain

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_trace_report():
    spec = importlib.util.spec_from_file_location(
        "trace_report", os.path.join(ROOT, "tools", "trace_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _clean_tracing_state():
    global_vars.reset_counters()
    telemetry.install_stream(None)
    install_tracing(None)
    yield
    install_tracing(None)
    install_detector(None)
    telemetry.install_stream(None)
    global_vars.reset_counters()


def _setup(utils):
    cfg = llama_config("tiny", seq_length=16, max_position_embeddings=16,
                       padded_vocab_size=64, num_layers=1, hidden_size=32,
                       num_attention_heads=4, ffn_hidden_size=64)
    model = LlamaModel(cfg)
    utils.initialize_model_parallel(tp=1)
    params = model.init(jax.random.PRNGKey(0))
    params = sh.shard_params(params, model.param_specs(params))

    def it():
        rng = np.random.RandomState(0)
        while True:
            toks = jnp.asarray(rng.randint(0, 64, size=(1, 8, 16)))
            yield {
                "tokens": toks,
                "labels": jnp.roll(toks, -1, axis=-1),
                "loss_mask": jnp.ones_like(toks, jnp.float32),
            }

    return model, params, it


def _tc(iters):
    return TrainConfig(micro_batch_size=8, global_batch_size=8,
                       train_iters=iters, lr=1e-2, optimizer="adam", seed=3)


def _telemetry_args(**kw):
    """A parsed-args stand-in with the telemetry group's fields
    (including the tracing flags this PR adds)."""
    base = dict(structured_log_dir=None, flight_recorder_size=64,
                profile=False, profile_step_start=2, profile_step_end=3,
                profile_dir=None, profiler_port=None, trace_dir=None,
                trace_buffer_size=100_000, straggler_threshold=1.5)
    base.update(kw)
    return argparse.Namespace(**base)


# ---------------------------------------------------------------------------
# SpanTracer: nesting, ring eviction, Chrome export schema
# ---------------------------------------------------------------------------

def test_span_nesting_and_ring_eviction():
    tr = SpanTracer(capacity=4)
    with tr.span("outer", "step"):
        with tr.span("inner", "checkpoint"):
            pass
    assert len(tr) == 2
    # the ring keeps the freshest events and counts evictions
    for i in range(10):
        with tr.span(f"s{i}", "other"):
            pass
    assert len(tr) == 4
    assert tr.dropped == 8            # 2 originals + s0..s5 evicted
    names = [e["name"] for e in tr.chrome_trace()["traceEvents"]
             if e["ph"] == "X"]
    assert names == ["s6", "s7", "s8", "s9"]


def test_span_handle_attaches_args():
    tr = SpanTracer()
    with tr.span("save", "checkpoint", iteration=3) as h:
        h.args["bytes"] = 1024
    (ev,) = [e for e in tr.chrome_trace()["traceEvents"] if e["ph"] == "X"]
    assert ev["args"]["iteration"] == 3
    assert ev["args"]["bytes"] == 1024
    # outermost goodput span is tagged with the category it fed
    assert ev["args"]["goodput"] == "checkpoint"


def test_chrome_trace_schema():
    """The export is the Chrome trace_event JSON Perfetto loads: X/i
    events with µs ts/dur, small remapped tids, M metadata rows naming
    the process and threads, and otherData carrying the diagnostics."""
    tr = SpanTracer()
    with tr.span("step", "step", iteration=1):
        time.sleep(0.01)
    tr.instant("marker", "other", detail="x")
    doc = tr.chrome_trace(reason="unit test")
    assert doc["displayTimeUnit"] == "ms"
    evs = doc["traceEvents"]
    assert {e["ph"] for e in evs} == {"M", "X", "i"}
    meta = [e for e in evs if e["ph"] == "M"]
    assert {m["name"] for m in meta} == {"process_name", "thread_name"}
    (x,) = [e for e in evs if e["ph"] == "X"]
    assert x["name"] == "step" and x["cat"] == "step"
    assert x["ts"] >= 0 and x["dur"] >= 10_000          # µs: >= 10 ms sleep
    assert isinstance(x["pid"], int) and x["tid"] == 0  # remapped small tid
    (i,) = [e for e in evs if e["ph"] == "i"]
    assert i["name"] == "marker" and i["s"] == "p"
    assert i["args"]["detail"] == "x"
    od = doc["otherData"]
    assert od["reason"] == "unit test"
    assert od["dropped_events"] == 0
    assert set(od["goodput"]) == ({f"{c}_secs" for c in GOODPUT_CATEGORIES}
                                  | {"other_secs", "wall_secs",
                                     "goodput_pct"})
    assert od["recompiles"] == 0 and od["straggler_events"] == 0
    # round-trips through json (Perfetto's parser reads a file)
    json.loads(json.dumps(doc))


def test_trace_write_atomic(tmp_path):
    tr = SpanTracer()
    with tr.span("step", "step"):
        pass
    path = tr.write(str(tmp_path / "trace.json"), reason="t")
    doc = json.loads(open(path).read())
    assert doc["otherData"]["reason"] == "t"
    assert not [p for p in os.listdir(tmp_path) if ".tmp." in p]


# ---------------------------------------------------------------------------
# Goodput arithmetic
# ---------------------------------------------------------------------------

def test_goodput_arithmetic_synthetic_timeline():
    """Injectable clock: 100s of wall, 60 step + 15 compile + 10
    checkpoint + 5 eval -> 10 unattributed, goodput 60%."""
    t = [0.0]
    g = GoodputAccounter(clock=lambda: t[0])
    g.add("step", 60.0)
    g.add("compile", 15.0)
    g.add("checkpoint", 10.0)
    g.add("eval", 5.0)
    t[0] = 100.0
    s = g.summary()
    assert s["wall_secs"] == pytest.approx(100.0)
    assert s["step_secs"] == pytest.approx(60.0)
    assert s["other_secs"] == pytest.approx(10.0)
    assert s["goodput_pct"] == pytest.approx(60.0)
    # move() reattributes (a compile inside a step span) and clamps
    assert g.move("step", "compile", 20.0) == pytest.approx(20.0)
    s = g.summary()
    assert s["step_secs"] == pytest.approx(40.0)
    assert s["compile_secs"] == pytest.approx(35.0)
    assert s["goodput_pct"] == pytest.approx(40.0)
    assert g.move("step", "compile", 1e9) == pytest.approx(40.0)  # clamp
    assert g.summary()["step_secs"] == 0.0


def test_nested_goodput_spans_never_double_count():
    """Outermost goodput span wins: a checkpoint_write inside a step
    span attributes nothing to 'checkpoint'; a non-goodput root (the
    'train' run span) does not shadow its children."""
    tr = SpanTracer()
    with tr.span("train", "run"):                 # trace-only category
        with tr.span("step", "step"):
            with tr.span("checkpoint_write", "checkpoint"):
                time.sleep(0.01)
    s = tr.goodput.summary()
    assert s["checkpoint_secs"] == 0.0
    assert s["step_secs"] >= 0.01
    with tr.span("checkpoint_save", "checkpoint"):
        time.sleep(0.01)
    assert tr.goodput.summary()["checkpoint_secs"] >= 0.01


# ---------------------------------------------------------------------------
# Straggler detection
# ---------------------------------------------------------------------------

def test_straggler_flagging_synthetic_hosts():
    lines = []
    tr = SpanTracer()
    det = StragglerDetector(threshold=1.5, tracer=tr,
                            printer=lines.append)
    found = det.check({"train-step": [0.1, 0.1, 0.5, 0.1]}, iteration=7)
    assert len(found) == 1
    ev = found[0]
    assert ev["host"] == 2 and ev["section"] == "train-step"
    assert ev["iteration"] == 7
    assert ev["ratio"] == pytest.approx(5.0)
    assert ev["median_secs"] == pytest.approx(0.1)
    assert det.total == 1
    assert get_counters()["straggler_events"] == 1
    assert "STRAGGLER host 2" in lines[0]
    (i,) = [e for e in tr.chrome_trace()["traceEvents"] if e["ph"] == "i"]
    assert i["name"] == "straggler" and i["args"]["host"] == 2


def test_straggler_no_flag_cases():
    det = StragglerDetector(threshold=1.5, printer=lambda s: None)
    # single host: no median to lag
    assert det.check({"train-step": [9.9]}, 1) == []
    # balanced hosts
    assert det.check({"train-step": [0.1, 0.1, 0.1, 0.1]}, 2) == []
    # above threshold but inside the min_secs noise floor
    assert det.check({"train-step": [0.001, 0.001, 0.004, 0.001]}, 3) == []
    assert det.total == 0 and get_counters()["straggler_events"] == 0


# ---------------------------------------------------------------------------
# Recompile detection
# ---------------------------------------------------------------------------

def test_recompile_counting_on_forced_shape_change():
    """A second input shape after mark_steady() retraces the jitted fn;
    the jax.monitoring listener counts it as a recompile (>= 1 — the
    backend may also compile auxiliary constant programs)."""
    if not (hasattr(jax, "monitoring") and hasattr(
            jax.monitoring, "register_event_duration_secs_listener")):
        pytest.skip("jax.monitoring not available")
    tr = SpanTracer()
    det = RecompileDetector(tracer=tr)
    assert det.use_monitoring
    install_detector(det)
    try:
        f = jax.jit(lambda x: x * 2.0 + 1.0)
        f(jnp.ones((4,))).block_until_ready()        # expected compile
        assert det.compiles >= 1 and det.recompiles == 0
        det.mark_steady()
        f(jnp.ones((8,))).block_until_ready()        # forced retrace
        assert det.recompiles >= 1
        assert get_counters()["recompiles"] == det.recompiles
        assert det.events and det.events[-1]["kind"] == "recompile"
        names = {e["name"] for e in tr.chrome_trace()["traceEvents"]
                 if e["ph"] == "X"}
        assert "recompile" in names
        n, secs = det.drain()
        assert n == det.compiles and secs >= 0.0
        assert det.drain() == (0, 0.0)
    finally:
        install_detector(None)


def test_recompile_pause_suppresses_expected_compiles():
    if not (hasattr(jax, "monitoring") and hasattr(
            jax.monitoring, "register_event_duration_secs_listener")):
        pytest.skip("jax.monitoring not available")
    det = RecompileDetector()
    install_detector(det)
    try:
        det.mark_steady()
        det.pause()
        jax.jit(lambda x: x - 3.0)(jnp.ones((5,))).block_until_ready()
        assert det.recompiles == 0 and det.compiles == 0
        det.resume()
    finally:
        install_detector(None)


def test_recompile_outlier_fallback():
    """Without jax.monitoring, a steady-state step beyond 3x the rolling
    median is a *suspected* recompile."""
    tr = SpanTracer()
    det = RecompileDetector(tracer=tr, use_monitoring=False)
    for _ in range(5):
        assert not det.observe_step_time(0.1)        # builds the baseline
    det.mark_steady()
    assert not det.observe_step_time(0.12)           # normal jitter
    assert det.observe_step_time(1.0)                # 10x the median
    assert det.recompiles == 1
    assert get_counters()["recompiles"] == 1
    assert det.events[-1]["kind"] == "suspected_recompile"
    assert [e for e in tr.chrome_trace()["traceEvents"]
            if e["ph"] == "i" and e["name"] == "suspected_recompile"]
    # the exact path no-ops the fallback entirely
    assert not RecompileDetector(use_monitoring=True).observe_step_time(99)


# ---------------------------------------------------------------------------
# build_tracing wiring
# ---------------------------------------------------------------------------

def test_build_tracing_wiring(tmp_path):
    assert build_tracing(_telemetry_args()) is None       # no --trace_dir
    t = build_tracing(_telemetry_args(trace_dir=str(tmp_path),
                                      trace_buffer_size=123,
                                      straggler_threshold=2.5))
    assert tracing.get_tracing() is t
    assert t.tracer.capacity == 123
    assert t.straggler.threshold == 2.5
    with tracing.span("step", "step"):
        pass
    t.close()
    assert tracing.get_tracing() is None
    doc = json.loads(open(tmp_path / "trace.json").read())
    assert doc["otherData"]["reason"] == "close"
    # module-level span() is a no-op once uninstalled
    with tracing.span("ignored", "step") as h:
        assert h is None
    assert tracing.dump_trace() is None


# ---------------------------------------------------------------------------
# tools/trace_report.py
# ---------------------------------------------------------------------------

def _synthetic_trace_dir(tmp_path):
    tr = SpanTracer()
    with tr.span("train", "run"):
        with tr.span("step", "step", iteration=1):
            time.sleep(0.02)
        with tr.span("checkpoint_save", "checkpoint", iteration=1):
            time.sleep(0.01)
    tr.instant("straggler", "straggler", iteration=1, host=2,
               section="train-step", secs=0.5, median_secs=0.1, ratio=5.0)
    get_counters()["straggler_events"] += 1
    tr.write(str(tmp_path / "trace.json"))
    with open(tmp_path / "telemetry.jsonl", "w") as f:
        for i in (1, 2):
            f.write(json.dumps({"kind": "log", "iteration": i,
                                "goodput_pct": 50.0 + i}) + "\n")


def test_trace_report_tool(tmp_path):
    _synthetic_trace_dir(tmp_path)
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "trace_report.py"),
         str(tmp_path)],
        capture_output=True, text=True, timeout=120, cwd=ROOT)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "goodput breakdown" in r.stdout
    assert "span coverage of traced wall-clock:" in r.stdout
    assert "straggler events: 1" in r.stdout
    assert "host 2" in r.stdout
    assert "goodput_pct per log boundary:" in r.stdout

    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "trace_report.py"),
         str(tmp_path / "trace.json"), "--json"],
        capture_output=True, text=True, timeout=120, cwd=ROOT)
    assert r.returncode == 0, r.stdout + r.stderr
    doc = json.loads(r.stdout)
    assert doc["coverage"] and doc["coverage"] > 0.9
    assert doc["straggler_timeline"][0]["host"] == 2
    # the root span is excluded from the top-spans list
    assert all(s["name"] != "train" for s in doc["top_spans"])
    assert doc["goodput_trend"] == [
        {"iteration": 1, "goodput_pct": 51.0},
        {"iteration": 2, "goodput_pct": 52.0}]

    r2 = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "trace_report.py"),
         str(tmp_path / "missing.json")],
        capture_output=True, text=True, timeout=120, cwd=ROOT)
    assert r2.returncode == 2


# ---------------------------------------------------------------------------
# Acceptance: tiny pretrain with --trace_dir
# ---------------------------------------------------------------------------

def test_pretrain_trace_acceptance(utils, tmp_path):
    """The acceptance-criteria run: tiny CPU pretrain with --trace_dir
    writes a Perfetto-loadable trace whose spans cover >= 95% of the
    traced wall-clock, the JSONL stream carries goodput_pct (plus the
    recompile/straggler counters and the new interval_time_secs), and
    trace_report renders the breakdown."""
    model, params, it = _setup(utils)
    d = str(tmp_path)
    tel = build_telemetry(
        _telemetry_args(structured_log_dir=d, trace_dir=d), model)
    assert tel.tracing is not None
    try:
        pretrain(model, params, _tc(6), ParallelConfig(), it(),
                 log_interval=1, telemetry=tel,
                 save_dir=os.path.join(d, "ckpt"), save_interval=3)
        # run summary (the wandb/TB finish payload) carries the
        # aggregates while the run's tracing is still installed
        s = telemetry.run_summary()
    finally:
        tel.close()
    assert 0.0 < s["goodput_pct"] <= 100.0
    assert "recompiles" in s and "straggler_events" in s

    doc = json.loads(open(os.path.join(d, "trace.json")).read())
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    names = {e["name"] for e in xs}
    # the loop's phases all opened spans
    assert {"train", "step", "data_next", "checkpoint_save",
            "checkpoint_write"} <= names
    assert len([e for e in xs if e["name"] == "train"]) == 1   # one root
    assert len([e for e in xs if e["name"] == "step"]) == 6

    report = _load_trace_report()
    assert report.coverage(doc) >= 0.95
    g = report.goodput_breakdown(doc)
    assert 0.0 < g["goodput_pct"] <= 100.0
    assert g["step_secs"] > 0 and g["checkpoint_secs"] > 0
    # wall-clock conservation: categories + other == wall
    parts = sum(g[f"{c}_secs"] for c in GOODPUT_CATEGORIES) + g["other_secs"]
    assert parts == pytest.approx(g["wall_secs"], rel=1e-6)

    records = [json.loads(l) for l in
               open(os.path.join(d, "telemetry.jsonl"))]
    assert [r["iteration"] for r in records] == [1, 2, 3, 4, 5, 6]
    for r in records:
        assert 0.0 < r["goodput_pct"] <= 100.0
        assert set(r["goodput"]) >= {f"{c}_secs" for c in GOODPUT_CATEGORIES}
        assert r["recompiles"] >= 0 and r["straggler_events"] >= 0
        assert r["interval_time_secs"] >= r["step_time_secs"] > 0


def test_rewind_span_under_nan_injection(utils, tmp_path):
    """An injected nan@3 triggers a rewind; the trace shows it as a
    'rewind' span and the goodput breakdown bills the recovery time."""
    model, params, it = _setup(utils)
    tel = build_telemetry(_telemetry_args(trace_dir=str(tmp_path)), model)
    rm = ResilienceManager(
        ResilienceConfig(snapshot_interval=1, patience=1, spike_factor=0),
        injector=FaultInjector.from_spec("nan@3"))
    try:
        pretrain(model, params, _tc(6), ParallelConfig(), it(),
                 log_interval=1, telemetry=tel, resilience=rm)
    finally:
        rm.close()
        tel.close()
    assert recovery_counters()["rewinds"] == 1
    doc = json.loads(open(tmp_path / "trace.json").read())
    rewinds = [e for e in doc["traceEvents"]
               if e["ph"] == "X" and e["name"] == "rewind"]
    assert len(rewinds) == 1
    assert rewinds[0]["args"]["goodput"] == "rewind"
    assert doc["otherData"]["goodput"]["rewind_secs"] > 0


def test_rescue_and_watchdog_spans_under_hang(utils, tmp_path):
    """An injected hang@3 fires the watchdog: the trace records the
    'watchdog_fire' instant and the rescue checkpoint's 'rescue_save'
    span, and the stack-dump path exports the trace mid-run."""
    model, params, it = _setup(utils)
    tel = build_telemetry(_telemetry_args(trace_dir=str(tmp_path)), model)
    wd = HangWatchdog(timeout_secs=0.5, hard_exit=False,
                      poll_interval=0.05, printer=lambda s: None)
    rm = ResilienceManager(
        ResilienceConfig(snapshot_interval=1),
        injector=FaultInjector.from_spec("hang@3:2.0"),
        watchdog=wd)
    try:
        pretrain(model, params, _tc(4), ParallelConfig(), it(),
                 log_interval=1, save_dir=str(tmp_path / "ckpt"),
                 telemetry=tel, resilience=rm)
    finally:
        rm.close()
        tel.close()
    assert wd.fired
    doc = json.loads(open(tmp_path / "trace.json").read())
    names = {e["name"] for e in doc["traceEvents"] if e["ph"] == "X"}
    assert "rescue_save" in names
    fires = [e for e in doc["traceEvents"]
             if e["ph"] == "i" and e["name"] == "watchdog_fire"]
    assert len(fires) == 1
    assert fires[0]["args"]["stalled_secs"] >= 0.5
    # the watchdog's stack dump mentioned the trace export
    assert "trace" in wd.last_dump


# ---------------------------------------------------------------------------
# Generation server /metrics + /health
# ---------------------------------------------------------------------------

def test_server_metrics_accounting():
    m = ServerMetrics(window=4)
    m.observe(0.1, 200, tokens=10)
    m.observe(0.2, 200, tokens=5)
    m.observe(0.3, 400)
    s = m.snapshot()
    assert s["requests"] == 3 and s["errors"] == 1
    assert s["tokens_generated"] == 15
    assert s["latency_p50_secs"] == pytest.approx(0.2)
    assert s["latency_p95_secs"] == pytest.approx(0.3)
    assert s["uptime_secs"] >= 0
    # bounded latency window
    for i in range(10):
        m.observe(float(i), 200)
    assert len(m._latencies) == 4
    assert ServerMetrics().snapshot()["latency_p50_secs"] is None


def test_server_metrics_concurrent_hooks_and_drain():
    """ServerMetrics is fed by the engine loop (request_done hook),
    bumped from HTTP-handler/signal contexts (note_drained), and read
    by /metrics threads — graft-lint threads/TH001 forced all three
    under ``_lock``.  Hammer them concurrently and require exact
    totals plus internally consistent snapshots."""
    m = ServerMetrics()
    rec = {"ttft_secs": 0.01, "tpot_secs": 0.002, "latency_secs": 0.05,
           "phases": {"queue_secs": 0.001}}
    n, feeders = 200, 4
    snaps = []

    def feed():
        for _ in range(n):
            m.observe_request_done(rec)

    def drain():
        for _ in range(n):
            m.note_drained()

    def read():
        for _ in range(50):
            snaps.append(m.snapshot())

    workers = [threading.Thread(target=feed) for _ in range(feeders)]
    workers += [threading.Thread(target=drain),
                threading.Thread(target=read)]
    for t in workers:
        t.start()
    for t in workers:
        t.join()
    s = m.snapshot()
    assert s["drained"] == n
    for name in ("ttft_secs", "tpot_secs", "e2e_secs",
                 "queue_wait_secs"):
        assert s["histograms"][name]["count"] == n * feeders
    # every mid-flight snapshot saw a consistent histogram: the bucket
    # counts it carries sum to the count it reports
    for sn in snaps:
        h = sn["histograms"]["e2e_secs"]
        assert sum(h["buckets"].values()) == h["count"]


def test_engine_watchdog_heartbeat_is_cross_thread_safe():
    """EngineWatchdog._last_progress is written by the engine loop and
    read by the watchdog's own thread (TH001 fix: both sides under
    ``_lock``).  A heartbeating 'engine' must never trip the watchdog;
    silencing the heartbeat must."""
    from megatron_llm_tpu.serving.resilience import EngineWatchdog

    fired = threading.Event()
    wd = EngineWatchdog(timeout_secs=0.2, has_work=lambda: True,
                        on_fire=fired.set, printer=lambda *_: None)
    wd.start()
    beating = threading.Event()
    beating.set()

    def engine_loop():
        while beating.is_set():
            wd.progress()
            time.sleep(0.01)

    t = threading.Thread(target=engine_loop, daemon=True)
    t.start()
    try:
        time.sleep(0.6)
        assert not fired.is_set(), \
            "watchdog fired despite a live heartbeat"
        beating.clear()
        t.join()
        assert fired.wait(timeout=5.0), \
            "watchdog never fired after the heartbeat stopped"
        assert wd.fires >= 1
    finally:
        beating.clear()
        wd.stop()


def test_server_health_and_metrics_endpoints():
    """GET /health and /metrics answer without touching the model (the
    generator is never invoked), so a None model is fine."""
    srv = MegatronServer(None, None, None)
    th = threading.Thread(
        target=lambda: srv.run(host="127.0.0.1", port=0), daemon=True)
    th.start()
    for _ in range(100):
        if getattr(srv, "httpd", None) is not None:
            break
        time.sleep(0.02)
    assert srv.httpd is not None
    port = srv.httpd.server_address[1]
    try:
        srv.metrics.observe(0.05, 200, tokens=7)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/health", timeout=5) as r:
            health = json.loads(r.read())
        assert health["status"] == "ok" and health["uptime_secs"] >= 0
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5) as r:
            snap = json.loads(r.read())
        assert snap["requests"] == 1 and snap["errors"] == 0
        assert snap["tokens_generated"] == 7
        assert snap["latency_p50_secs"] == pytest.approx(0.05)
    finally:
        srv.httpd.shutdown()
        th.join(timeout=5)
