"""Pipeline engine tests: pipelined loss/grads match unpipelined execution
(the TPU-native answer to the reference's schedules.py correctness, which
has no unit tests at all — only integration runs)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megatron_llm_tpu import topology
from megatron_llm_tpu.config import ParallelConfig, TrainConfig
from megatron_llm_tpu.models.llama import LlamaModel, llama_config
from megatron_llm_tpu.models.falcon import FalconModel, falcon_config
from megatron_llm_tpu.optimizer import MegatronOptimizer
from megatron_llm_tpu.parallel import sharding as sh
from megatron_llm_tpu.parallel.pipeline import (
    build_pipeline_grad_fn,
    build_pipeline_loss_fn,
    build_pipeline_train_step,
    permute_layer_stack,
    unpermute_layer_stack,
    vpp_stage_major_permutation,
)


def _batch(M, mb, s, vocab, seed=0):
    rng = np.random.RandomState(seed)
    toks = jnp.asarray(rng.randint(0, vocab, (M, mb, s)))
    return {
        "tokens": toks,
        "labels": jnp.roll(toks, -1, axis=-1),
        "loss_mask": jnp.ones((M, mb, s), jnp.float32),
    }


def _unpiped_loss(model, params, batch):
    tot, den = 0.0, 0.0
    M = batch["tokens"].shape[0]
    for i in range(M):
        lt = model(params, batch["tokens"][i], labels=batch["labels"][i],
                   train=False)
        tot = tot + lt.sum()
        den = den + lt.size
    return tot / den


@pytest.mark.parametrize("pp,tp,seq_par", [(2, 2, True), (4, 2, False), (2, 1, False)])
def test_pipeline_loss_parity(utils, pp, tp, seq_par):
    cfg = llama_config("tiny", num_layers=4, seq_length=32,
                       max_position_embeddings=32, padded_vocab_size=128)
    model = LlamaModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    batch = _batch(4, 4, 32, 128)
    base = float(_unpiped_loss(model, params, batch))

    utils.initialize_model_parallel(tp=tp, pp=pp)
    ps = sh.shard_params(params, model.param_specs(params))
    loss_fn = build_pipeline_loss_fn(model, pp, 4, sequence_parallel=seq_par)
    out = jax.jit(lambda p, b, k: loss_fn(p, b, k, train=False)[1])(
        ps, batch, jax.random.PRNGKey(0)
    )
    assert abs(float(out) - base) < 1e-4


def test_pipeline_grad_parity(utils):
    cfg = llama_config("tiny", num_layers=4, seq_length=32,
                       max_position_embeddings=32, padded_vocab_size=128)
    model = LlamaModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    batch = _batch(4, 4, 32, 128)

    g_base = jax.grad(lambda p: _unpiped_loss(model, p, batch))(params)

    utils.initialize_model_parallel(tp=2, pp=2)
    ps = sh.shard_params(params, model.param_specs(params))
    loss_fn = build_pipeline_loss_fn(model, 2, 4, sequence_parallel=True)
    g_pipe = jax.jit(
        jax.grad(lambda p: loss_fn(p, batch, jax.random.PRNGKey(0),
                                   train=False)[1])
    )(ps)
    for (pa, a), (_, b) in zip(
        jax.tree_util.tree_flatten_with_path(g_base)[0],
        jax.tree_util.tree_flatten_with_path(g_pipe)[0],
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5,
                                   err_msg=str(pa))


def test_pipeline_tied_embedding_grad(utils):
    """Embedding used by both stage-0 lookup and last-stage head: its grad
    must equal the unpipelined tied grad (reference embedding-tie sync,
    optimizer.py:203-229)."""
    cfg = falcon_config("tiny", num_layers=4, seq_length=32,
                        max_position_embeddings=32, padded_vocab_size=128)
    model = FalconModel(cfg)   # falcon ties embeddings
    params = model.init(jax.random.PRNGKey(0))
    batch = _batch(2, 4, 32, 128)

    g_base = jax.grad(lambda p: _unpiped_loss(model, p, batch))(params)

    utils.initialize_model_parallel(tp=1, pp=2)
    ps = sh.shard_params(params, model.param_specs(params))
    loss_fn = build_pipeline_loss_fn(model, 2, 2)
    g_pipe = jax.jit(
        jax.grad(lambda p: loss_fn(p, batch, jax.random.PRNGKey(0),
                                   train=False)[1])
    )(ps)
    np.testing.assert_allclose(
        np.asarray(g_base["embedding"]["word"]["embedding"]),
        np.asarray(g_pipe["embedding"]["word"]["embedding"]),
        atol=1e-5,
    )


def test_vpp_permutation_roundtrip():
    perm = vpp_stage_major_permutation(8, 2, 2)
    # device 0 rows: chunks v=0 (layers 0,1) then v=1 (layers 4,5)
    assert list(perm) == [0, 1, 4, 5, 2, 3, 6, 7]
    x = {"w": jnp.arange(8.0)}
    y = permute_layer_stack(x, 8, 2, 2)
    z = unpermute_layer_stack(y, 8, 2, 2)
    np.testing.assert_array_equal(np.asarray(z["w"]), np.asarray(x["w"]))


@pytest.mark.parametrize("pp,vpp", [(2, 2), (2, 4)])
def test_interleaved_vpp_loss_parity(utils, pp, vpp):
    """Interleaved virtual-pipeline schedule matches unpipelined loss
    (reference interleaved 1F1B: schedules.py:253-502)."""
    cfg = llama_config("tiny", num_layers=2 * pp * vpp, seq_length=32,
                       max_position_embeddings=32, padded_vocab_size=128)
    model = LlamaModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    batch = _batch(4, 2, 32, 128)
    base = float(_unpiped_loss(model, params, batch))

    utils.initialize_model_parallel(tp=2, pp=pp)
    params["transformer"]["layers"] = permute_layer_stack(
        params["transformer"]["layers"], cfg.num_layers, pp, vpp)
    ps = sh.shard_params(params, model.param_specs(params))
    loss_fn = build_pipeline_loss_fn(model, pp, 4, num_virtual=vpp)
    out = jax.jit(lambda p, b, k: loss_fn(p, b, k, train=False)[1])(
        ps, batch, jax.random.PRNGKey(0)
    )
    assert abs(float(out) - base) < 1e-4


def test_interleaved_vpp_grad_parity(utils):
    cfg = llama_config("tiny", num_layers=8, seq_length=32,
                       max_position_embeddings=32, padded_vocab_size=128)
    model = LlamaModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    batch = _batch(4, 2, 32, 128)
    g_base = jax.grad(lambda p: _unpiped_loss(model, p, batch))(params)
    # compare in stage-major order
    g_base["transformer"]["layers"] = permute_layer_stack(
        g_base["transformer"]["layers"], 8, 2, 2)

    utils.initialize_model_parallel(tp=1, pp=2)
    params["transformer"]["layers"] = permute_layer_stack(
        params["transformer"]["layers"], 8, 2, 2)
    ps = sh.shard_params(params, model.param_specs(params))
    loss_fn = build_pipeline_loss_fn(model, 2, 4, num_virtual=2)
    g_pipe = jax.jit(
        jax.grad(lambda p: loss_fn(p, batch, jax.random.PRNGKey(0),
                                   train=False)[1])
    )(ps)
    for (pa, a), (_, b) in zip(
        jax.tree_util.tree_flatten_with_path(g_base)[0],
        jax.tree_util.tree_flatten_with_path(g_pipe)[0],
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5,
                                   err_msg=str(pa))


@pytest.mark.parametrize("pp,tp", [(2, 2), (4, 1)])
def test_manual_1f1b_matches_unpipelined(utils, pp, tp):
    """Hand-written 1F1B backward (O(S) stash) reproduces autodiff loss and
    grads (reference 1F1B: schedules.py:606-722)."""
    cfg = llama_config("tiny", num_layers=4, seq_length=32,
                       max_position_embeddings=32, padded_vocab_size=128)
    model = LlamaModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    batch = _batch(4, 2, 32, 128)
    base = float(_unpiped_loss(model, params, batch))
    g_base = jax.grad(lambda p: _unpiped_loss(model, p, batch))(params)

    utils.initialize_model_parallel(tp=tp, pp=pp)
    ps = sh.shard_params(params, model.param_specs(params))
    grad_fn = build_pipeline_grad_fn(model, pp, 4,
                                     sequence_parallel=tp > 1)
    loss, grads = jax.jit(
        lambda p, b, k: grad_fn(p, b, k, train=False)
    )(ps, batch, jax.random.PRNGKey(0))
    assert abs(float(loss) - base) < 1e-4
    for (pa, a), (_, b) in zip(
        jax.tree_util.tree_flatten_with_path(g_base)[0],
        jax.tree_util.tree_flatten_with_path(grads)[0],
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5,
                                   err_msg=str(pa))


def test_manual_1f1b_tied_embedding(utils):
    cfg = falcon_config("tiny", num_layers=4, seq_length=32,
                        max_position_embeddings=32, padded_vocab_size=128)
    model = FalconModel(cfg)   # falcon ties embeddings
    params = model.init(jax.random.PRNGKey(0))
    batch = _batch(2, 4, 32, 128)
    g_base = jax.grad(lambda p: _unpiped_loss(model, p, batch))(params)

    utils.initialize_model_parallel(tp=1, pp=2)
    ps = sh.shard_params(params, model.param_specs(params))
    grad_fn = build_pipeline_grad_fn(model, 2, 2)
    _, grads = jax.jit(lambda p, b, k: grad_fn(p, b, k, train=False))(
        ps, batch, jax.random.PRNGKey(0))
    np.testing.assert_allclose(
        np.asarray(g_base["embedding"]["word"]["embedding"]),
        np.asarray(grads["embedding"]["word"]["embedding"]),
        atol=2e-5,
    )


def test_manual_1f1b_memory_flat_in_microbatches(utils):
    """The 1F1B engine's activation memory must not grow with M (the
    reference's in-flight cap, schedules.py:606-722): compiled temp-buffer
    usage at M=8 stays within 15% of M=2."""
    cfg = llama_config("tiny", num_layers=4, seq_length=64,
                       max_position_embeddings=64, padded_vocab_size=128,
                       hidden_dropout=0.0, attention_dropout=0.0)
    model = LlamaModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    utils.initialize_model_parallel(tp=1, pp=2)
    ps = sh.shard_params(params, model.param_specs(params))

    def temp_bytes(M):
        grad_fn = build_pipeline_grad_fn(model, 2, M)
        batch = _batch(M, 2, 64, 128)
        lowered = jax.jit(
            lambda p, b, k: grad_fn(p, b, k, train=False)
        ).lower(ps, batch, jax.random.PRNGKey(0))
        ma = lowered.compile().memory_analysis()
        return ma.temp_size_in_bytes

    small, large = temp_bytes(2), temp_bytes(8)
    assert large <= small * 1.15, (small, large)


def test_pipeline_train_step_runs(utils):
    cfg = llama_config("tiny", num_layers=4, seq_length=32,
                       max_position_embeddings=32, padded_vocab_size=128)
    model = LlamaModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    utils.initialize_model_parallel(tp=2, pp=2)
    params = sh.shard_params(params, model.param_specs(params))

    tc = TrainConfig(micro_batch_size=2, global_batch_size=8, lr=1e-3)
    pc = ParallelConfig(tensor_model_parallel_size=2,
                        pipeline_model_parallel_size=2,
                        data_parallel_size=2, sequence_parallel=True)
    opt = MegatronOptimizer(tc)
    opt_state = opt.init(params)
    step = build_pipeline_train_step(model, opt, pc, 4)
    batch = _batch(4, 2, 32, 128)
    params0 = jax.tree_util.tree_map(np.asarray, params)  # donation-safe copy
    p1, o1, m = step(params, opt_state, batch, jax.random.PRNGKey(0), 1e-3, 0.0)
    assert np.isfinite(float(m["lm loss"]))
    assert int(o1.step) == 1
    # params actually moved
    moved = any(
        float(np.max(np.abs(np.asarray(a) - b))) > 0
        for a, b in zip(jax.tree_util.tree_leaves(p1),
                        jax.tree_util.tree_leaves(params0))
    )
    assert moved


# ---------------------------------------------------------------------------
# MoE under pipeline parallelism (TPU-native extension)
# ---------------------------------------------------------------------------

def _moe_cfg(**kw):
    base = dict(num_layers=4, seq_length=32, max_position_embeddings=32,
                padded_vocab_size=128, num_experts=4, moe_top_k=2,
                moe_capacity_factor=8.0)
    base.update(kw)
    return llama_config("tiny", **base)


def _unpiped_moe_objective(model, params, batch):
    """total CE / total tokens + coeff . mean-per-microbatch routing aux —
    exactly the pipelined objective."""
    cfg = model.cfg
    M = batch["tokens"].shape[0]
    tot, den = 0.0, 0.0
    aux_sum = jnp.zeros((2,), jnp.float32)
    for i in range(M):
        lt, aux = model(params, batch["tokens"][i],
                        labels=batch["labels"][i], train=False)
        tot = tot + lt.sum()
        den = den + lt.size
        aux_sum = aux_sum + aux
    lm = tot / den
    aux_mean = aux_sum / M
    total = (lm + cfg.moe_aux_loss_coeff * aux_mean[0]
             + cfg.moe_z_loss_coeff * aux_mean[1])
    return total, (lm, aux_mean)


@pytest.mark.parametrize("vpp", [1, 2])
def test_moe_pipeline_loss_parity(utils, vpp):
    """Streaming engine with MoE layers: loss AND routing aux match the
    unpipelined model (experts dp-sharded, pp=2 x tp=2)."""
    cfg = _moe_cfg()
    model = LlamaModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    batch = _batch(4, 4, 32, 128)
    _, (lm_base, aux_base) = _unpiped_moe_objective(model, params, batch)

    utils.initialize_model_parallel(tp=2, pp=2)
    if vpp > 1:
        params = dict(params)
        params["transformer"] = dict(params["transformer"])
        params["transformer"]["layers"] = permute_layer_stack(
            params["transformer"]["layers"], cfg.num_layers, 2, vpp)
    ps = sh.shard_params(params, model.param_specs(params))
    loss_fn = build_pipeline_loss_fn(model, 2, 4, num_virtual=vpp,
                                     sequence_parallel=True)
    lm, aux = jax.jit(lambda p, b, k: loss_fn(p, b, k, train=False)[1])(
        ps, batch, jax.random.PRNGKey(0))
    assert abs(float(lm) - float(lm_base)) < 1e-4
    np.testing.assert_allclose(np.asarray(aux), np.asarray(aux_base),
                               atol=1e-4)


def test_moe_pipeline_grad_parity_stream(utils):
    """Autodiff through the streaming schedule must produce the gradients
    of the full MoE objective (CE + weighted routing losses), router
    included."""
    cfg = _moe_cfg(num_layers=2)
    model = LlamaModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    batch = _batch(2, 4, 32, 128)
    g_base = jax.grad(
        lambda p: _unpiped_moe_objective(model, p, batch)[0])(params)

    utils.initialize_model_parallel(tp=1, pp=2)
    ps = sh.shard_params(params, model.param_specs(params))
    loss_fn = build_pipeline_loss_fn(model, 2, 2)
    g_pipe = jax.jit(
        jax.grad(lambda p: loss_fn(p, batch, jax.random.PRNGKey(0),
                                   train=False)[0])
    )(ps)
    for (pa, a), (_, b) in zip(
        jax.tree_util.tree_flatten_with_path(g_base)[0],
        jax.tree_util.tree_flatten_with_path(g_pipe)[0],
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5,
                                   err_msg=str(pa))


def test_moe_pipeline_grad_parity_1f1b(utils):
    """The hand-written 1F1B backward seeds the routing-aux cotangent on
    every stage; its grads must match jax.grad of the full objective."""
    cfg = _moe_cfg(num_layers=2)
    model = LlamaModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    batch = _batch(2, 4, 32, 128)
    g_base = jax.grad(
        lambda p: _unpiped_moe_objective(model, p, batch)[0])(params)

    utils.initialize_model_parallel(tp=1, pp=2)
    ps = sh.shard_params(params, model.param_specs(params))
    grad_fn = build_pipeline_grad_fn(model, 2, 2)
    _, g_pipe, aux = jax.jit(
        lambda p, b, k: grad_fn(p, b, k, train=False))(
        ps, batch, jax.random.PRNGKey(0))
    assert np.isfinite(np.asarray(aux)).all()
    for (pa, a), (_, b) in zip(
        jax.tree_util.tree_flatten_with_path(g_base)[0],
        jax.tree_util.tree_flatten_with_path(g_pipe)[0],
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5,
                                   err_msg=str(pa))


def test_pipeline_with_context_parallelism(utils):
    """pp=2 x cp=2 x dp=2: ring attention (a cp shard_map nested inside
    the pp-manual region, using the abstract context mesh) matches the
    unpipelined, unsharded loss."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    cfg = llama_config("tiny", num_layers=4, seq_length=64,
                       max_position_embeddings=64, padded_vocab_size=128)
    model = LlamaModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    batch = _batch(2, 2, 64, 128)
    base = float(_unpiped_loss(model, params, batch))

    mesh = utils.initialize_model_parallel(tp=1, pp=2, cp=2)
    ps = sh.shard_params(params, model.param_specs(params))
    dsh = NamedSharding(mesh, P(None, "dp", "cp"))
    batch_s = {k: jax.device_put(v, dsh) for k, v in batch.items()}
    loss_fn = build_pipeline_loss_fn(model, 2, 2)
    out = jax.jit(lambda p, b, k: loss_fn(p, b, k, train=False)[1])(
        ps, batch_s, jax.random.PRNGKey(0))
    assert abs(float(out) - base) < 1e-3
