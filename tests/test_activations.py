"""GLU activation math vs reference formulas
(reference: tests/test_activations.py:12-54)."""

import jax
import jax.numpy as jnp
import numpy as np

from megatron_llm_tpu.ops.activations import (
    bias_gelu,
    geglu,
    gelu,
    liglu,
    reglu,
    swiglu,
)


def _data():
    rng = np.random.RandomState(0)
    return jnp.asarray(rng.randn(4, 10).astype(np.float32))


def test_shapes_halved():
    x = _data()
    for fn in (liglu, geglu, reglu, swiglu):
        assert fn(x).shape == (4, 5)


def test_liglu_values():
    x = _data()
    a, b = np.split(np.asarray(x), 2, axis=-1)
    np.testing.assert_allclose(liglu(x), a * b, rtol=1e-6)


def test_reglu_values():
    x = _data()
    a, b = np.split(np.asarray(x), 2, axis=-1)
    np.testing.assert_allclose(reglu(x), np.maximum(a, 0) * b, rtol=1e-6)


def test_swiglu_values():
    x = _data()
    a, b = np.split(np.asarray(x), 2, axis=-1)
    silu = a / (1 + np.exp(-a))
    np.testing.assert_allclose(swiglu(x), silu * b, rtol=1e-5)


def test_geglu_values():
    x = _data()
    a, b = np.split(np.asarray(x), 2, axis=-1)
    g = 0.5 * a * (1 + np.tanh(0.79788456 * a * (1 + 0.044715 * a * a)))
    np.testing.assert_allclose(geglu(x), g * b, rtol=1e-5)


def test_bias_gelu_matches_gelu():
    x = _data()
    bias = jnp.ones((10,))
    np.testing.assert_allclose(bias_gelu(bias, x), gelu(x + 1.0), rtol=1e-6)
