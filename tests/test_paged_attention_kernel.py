"""Ragged paged-attention Pallas kernels (decode and chunked prefill),
run in interpret mode on CPU: kernel vs the XLA dense-gather reference
vs a per-slot numpy oracle, across ragged context lengths, GQA group
counts, sliding window, and int8 KV quantization — plus model-level
parity of the transformer's paged branch with the kernels forced on vs
off.  Prefill cases cover the ragged edges: chunks straddling page
boundaries, context 0, cached-prefix tail chunks starting mid-page,
windows shorter than the chunk, and multi-q-block grids."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megatron_llm_tpu.models.language_model import language_model_forward
from megatron_llm_tpu.models.llama import LlamaModel, llama_config
from megatron_llm_tpu.ops.pallas import paged_attention as pa
from megatron_llm_tpu.quantization import absmax_quantize_int8
from megatron_llm_tpu.text_generation.generation import init_paged_kv_caches


@pytest.fixture(autouse=True)
def _interpret_mode():
    old = pa._INTERPRET
    pa._INTERPRET = True
    yield
    pa._INTERPRET = old


def _build_case(rng, S, M, bs, g, nh, d, lens):
    """Linear per-slot K/V [S, M*bs, g, d] scattered into a shared page
    pool through ragged block tables.  Unowned pages (including the
    reserved garbage block 0 that pads every table) are filled with
    large garbage so a kernel that reads or fails to mask them diverges
    loudly from the oracle."""
    L = M * bs
    q = rng.standard_normal((S, nh, d)).astype(np.float32)
    k_lin = rng.standard_normal((S, L, g, d)).astype(np.float32)
    v_lin = rng.standard_normal((S, L, g, d)).astype(np.float32)
    P = 1 + S * M
    k_pages = (rng.standard_normal((P, bs, g, d)) * 100.0).astype(np.float32)
    v_pages = (rng.standard_normal((P, bs, g, d)) * 100.0).astype(np.float32)
    bt = np.zeros((S, M), np.int32)
    nxt = 1
    for s in range(S):
        for j in range(int(lens[s]) // bs + 1):   # pages live at decode pos
            bt[s, j] = nxt
            k_pages[nxt] = k_lin[s, j * bs:(j + 1) * bs]
            v_pages[nxt] = v_lin[s, j * bs:(j + 1) * bs]
            nxt += 1
    return q, k_lin, v_lin, k_pages, v_pages, bt


def _oracle(q, k_lin, v_lin, lens, scale, window):
    """Per-(slot, head) dense softmax attention over the linear K/V —
    independent of both the kernel and the jnp reference."""
    S, L, g, d = k_lin.shape
    nh = q.shape[1]
    qpg = nh // g
    out = np.zeros((S, nh, d), np.float32)
    pos = np.arange(L)
    for s in range(S):
        valid = pos <= lens[s]
        if window is not None:
            valid &= pos > lens[s] - window
        for h in range(nh):
            grp = h // qpg
            sc = (k_lin[s, :, grp] @ q[s, h]) * scale
            sc = np.where(valid, sc, -np.inf)
            p = np.exp(sc - sc[valid].max())
            p = np.where(valid, p, 0.0)
            p /= p.sum()
            out[s, h] = p @ v_lin[s, :, grp]
    return out


S, M, BS, D = 4, 4, 8, 16
LENS = np.asarray([0, 5, 17, 31], np.int32)   # ragged: 1/1/3/4 live pages


@pytest.mark.parametrize("window", [None, 12])
@pytest.mark.parametrize("g,nh", [(1, 1), (2, 4), (4, 4)])
def test_kernel_matches_oracle_and_reference(g, nh, window):
    rng = np.random.default_rng(7 * g + nh + (window or 0))
    q, k_lin, v_lin, kp, vp, bt = _build_case(rng, S, M, BS, g, nh, D, LENS)
    scale = 1.0 / math.sqrt(D)
    got = np.asarray(pa.paged_attention_decode(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(bt), jnp.asarray(LENS), sliding_window=window))
    ref = np.asarray(pa._reference_paged_attention(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(bt), jnp.asarray(LENS), None, None, scale, window))
    want = _oracle(q, k_lin, v_lin, LENS, scale, window)
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)
    np.testing.assert_allclose(ref, want, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("window", [None, 12])
def test_kernel_int8_dequant(window):
    """int8 pools + per-(page, position, group) scales: the in-kernel
    dequant matches the reference dequant bit-for-bit-ish (same
    quantized inputs), and both stay within the quantization drift
    bound of the float oracle."""
    g, nh = 2, 4
    rng = np.random.default_rng(42 + (window or 0))
    q, k_lin, v_lin, kp, vp, bt = _build_case(rng, S, M, BS, g, nh, D, LENS)
    scale = 1.0 / math.sqrt(D)
    kq, ks = absmax_quantize_int8(jnp.asarray(kp), axis=-1)
    vq, vs = absmax_quantize_int8(jnp.asarray(vp), axis=-1)
    got = np.asarray(pa.paged_attention_decode(
        jnp.asarray(q), kq, vq, jnp.asarray(bt), jnp.asarray(LENS),
        k_scales=ks, v_scales=vs, sliding_window=window))
    ref = np.asarray(pa._reference_paged_attention(
        jnp.asarray(q), kq, vq, jnp.asarray(bt), jnp.asarray(LENS),
        ks, vs, scale, window))
    np.testing.assert_allclose(got, ref, atol=2e-5, rtol=2e-5)
    want = _oracle(q, k_lin, v_lin, LENS, scale, window)
    drift = np.max(np.abs(got - want)) / (np.std(want) + 1e-6)
    assert drift < 0.2, drift


def test_availability_tracks_backend(monkeypatch):
    assert pa.decode_kernel_available()   # interpret fixture is on
    assert pa.prefill_kernel_available()
    monkeypatch.setattr(pa, "_INTERPRET", False)
    monkeypatch.delenv("MLT_FORCE_PALLAS", raising=False)
    if jax.default_backend() != "tpu":
        assert not pa.decode_kernel_available()
        assert not pa.prefill_kernel_available()


# ---------------------------------------------------------------------------
# chunked prefill: ragged-edge parity
# ---------------------------------------------------------------------------

# chunk C = 16 on bs = 8 pages; contexts hit the ragged edges: 0 (no
# history), 3 (chunk straddles the page-0/1 boundary mid-chunk), 8
# (chunk starts exactly on a page boundary), 17 (cached-prefix tail
# chunk starting mid-page, spilling into a 5th page)
CTX = np.asarray([0, 3, 8, 17], np.int32)
C = 16
MP = 6                    # pages per table; max live = 5, so dead tails


def _build_prefill_case(rng, S, M, bs, g, nh, d, ctx, C):
    """Engine-shaped prefill state: each slot's history (ctx keys) AND
    its in-flight chunk (C keys, scatter-before-read) live in the pool;
    linear positions past ctx+C — including tail positions of live
    pages — hold amplified garbage so an unmasked read diverges
    loudly."""
    L = M * bs
    q = rng.standard_normal((S, C, nh, d)).astype(np.float32)
    k_lin = rng.standard_normal((S, L, g, d)).astype(np.float32)
    v_lin = rng.standard_normal((S, L, g, d)).astype(np.float32)
    for s in range(S):
        k_lin[s, int(ctx[s]) + C:] *= 100.0
        v_lin[s, int(ctx[s]) + C:] *= 100.0
    P = 1 + S * M
    k_pages = (rng.standard_normal((P, bs, g, d)) * 100.0).astype(np.float32)
    v_pages = (rng.standard_normal((P, bs, g, d)) * 100.0).astype(np.float32)
    bt = np.zeros((S, M), np.int32)
    nxt = 1
    for s in range(S):
        for j in range((int(ctx[s]) + C + bs - 1) // bs):
            bt[s, j] = nxt
            k_pages[nxt] = k_lin[s, j * bs:(j + 1) * bs]
            v_pages[nxt] = v_lin[s, j * bs:(j + 1) * bs]
            nxt += 1
    return q, k_lin, v_lin, k_pages, v_pages, bt


def _prefill_oracle(q, k_lin, v_lin, ctx, scale, window):
    """Per-(slot, row, head) dense causal attention: row j of a chunk
    attends keys 0..ctx+j (window-clipped) — independent of both the
    kernel and the jnp reference."""
    S, Cq, nh, d = q.shape
    L, g = k_lin.shape[1], k_lin.shape[2]
    qpg = nh // g
    out = np.zeros((S, Cq, nh, d), np.float32)
    kpos = np.arange(L)
    for s in range(S):
        for j in range(Cq):
            pos = int(ctx[s]) + j
            valid = kpos <= pos
            if window is not None:
                valid &= kpos > pos - window
            for h in range(nh):
                grp = h // qpg
                sc = (k_lin[s, :, grp] @ q[s, j, h]) * scale
                sc = np.where(valid, sc, -np.inf)
                p = np.exp(sc - sc[valid].max())
                p = np.where(valid, p, 0.0)
                p /= p.sum()
                out[s, j, h] = p @ v_lin[s, :, grp]
    return out


@pytest.mark.parametrize("block_q", [None, 8])
@pytest.mark.parametrize("window", [None, 5])
@pytest.mark.parametrize("g,nh", [(1, 1), (2, 4), (4, 4)])
def test_prefill_kernel_matches_oracle_and_reference(g, nh, window,
                                                     block_q):
    """window=5 < C exercises windows shorter than the chunk;
    block_q=8 splits C=16 across two q-grid steps so the online-softmax
    scratch carries across both page and q-block boundaries."""
    rng = np.random.default_rng(11 * g + nh + (window or 0)
                                + (block_q or 0))
    q, k_lin, v_lin, kp, vp, bt = _build_prefill_case(
        rng, len(CTX), MP, BS, g, nh, D, CTX, C)
    scale = 1.0 / math.sqrt(D)
    got = np.asarray(pa.paged_attention_prefill(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(bt), jnp.asarray(CTX), sliding_window=window,
        block_q=block_q))
    ref = np.asarray(pa._reference_paged_prefill(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(bt), jnp.asarray(CTX), None, None, scale, window))
    want = _prefill_oracle(q, k_lin, v_lin, CTX, scale, window)
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)
    np.testing.assert_allclose(ref, want, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("window", [None, 5])
def test_prefill_kernel_int8_dequant(window):
    g, nh = 2, 4
    rng = np.random.default_rng(99 + (window or 0))
    q, k_lin, v_lin, kp, vp, bt = _build_prefill_case(
        rng, len(CTX), MP, BS, g, nh, D, CTX, C)
    scale = 1.0 / math.sqrt(D)
    kq, ks = absmax_quantize_int8(jnp.asarray(kp), axis=-1)
    vq, vs = absmax_quantize_int8(jnp.asarray(vp), axis=-1)
    got = np.asarray(pa.paged_attention_prefill(
        jnp.asarray(q), kq, vq, jnp.asarray(bt), jnp.asarray(CTX),
        k_scales=ks, v_scales=vs, sliding_window=window, block_q=8))
    ref = np.asarray(pa._reference_paged_prefill(
        jnp.asarray(q), kq, vq, jnp.asarray(bt), jnp.asarray(CTX),
        ks, vs, scale, window))
    np.testing.assert_allclose(got, ref, atol=2e-5, rtol=2e-5)
    want = _prefill_oracle(q, k_lin, v_lin, CTX, scale, window)
    drift = np.max(np.abs(got - want)) / (np.std(want) + 1e-6)
    assert drift < 0.2, drift


def test_prefill_decode_consistency():
    """The decode entry point is literally the C == 1 instance of the
    ragged prefill: a one-row chunk through paged_attention_prefill
    equals paged_attention_decode on the same state."""
    g, nh = 2, 4
    rng = np.random.default_rng(5)
    q, _, _, kp, vp, bt = _build_case(rng, S, M, BS, g, nh, D, LENS)
    dec = np.asarray(pa.paged_attention_decode(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(bt), jnp.asarray(LENS)))
    pre = np.asarray(pa.paged_attention_prefill(
        jnp.asarray(q)[:, None], jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(bt), jnp.asarray(LENS)))[:, 0]
    np.testing.assert_allclose(pre, dec, atol=1e-6, rtol=1e-6)


# ---------------------------------------------------------------------------
# model-level: transformer paged branch, kernel on vs off
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def model_and_params():
    cfg = llama_config("tiny", num_layers=2, seq_length=64,
                       max_position_embeddings=64, padded_vocab_size=64,
                       use_flash_attn=False)
    model = LlamaModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    return model, params


def _prefilled_pages(model, params, cfg_off, bt, lens, quantized):
    """XLA-branch prefill (multi-token calls never take the kernel)
    filling the shared pools through the block tables."""
    Sl, C = bt.shape[0], 16
    pages = init_paged_kv_caches(model.cfg, 1 + int(bt.max()), BS,
                                 quantized=quantized)
    toks = jnp.asarray(np.arange(Sl * C).reshape(Sl, C) % 60 + 1, jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(C)[None, :], (Sl, C))
    caches = [dict(p, block_tables=bt,
                   context_lens=jnp.zeros((Sl,), jnp.int32),
                   valid_lens=lens) for p in pages]
    _, caches = language_model_forward(params, toks, positions, None,
                                       cfg_off, rng_key=None, train=False,
                                       kv_caches=caches)
    return [{k: v for k, v in c.items() if "pages" in k} for c in caches]


@pytest.mark.parametrize("quantized", [False, True])
def test_transformer_paged_kernel_parity(model_and_params, quantized):
    """A decode step through the paged branch with the Pallas kernel
    forced on (interpret) produces the same logits as the XLA gather
    branch, on plain and int8 pools."""
    model, params = model_and_params
    cfg_off = model.cfg.replace(paged_attention_kernel="off")
    cfg_on = model.cfg.replace(paged_attention_kernel="on")
    Sl = 2
    bt = jnp.asarray(
        np.arange(1, 1 + Sl * M).reshape(Sl, M), jnp.int32)
    lens = jnp.asarray([5, 9], jnp.int32)
    pages = _prefilled_pages(model, params, cfg_off, bt, lens, quantized)
    nxt = jnp.asarray([[7], [11]], jnp.int32)
    outs = []
    for cfg in (cfg_off, cfg_on):
        caches = [dict(p, block_tables=bt, context_lens=lens,
                       valid_lens=jnp.ones((Sl,), jnp.int32))
                  for p in pages]
        logits, _ = language_model_forward(params, nxt, lens[:, None],
                                           None, cfg, rng_key=None,
                                           train=False, kv_caches=caches)
        outs.append(np.asarray(logits[:, 0], np.float32))
    np.testing.assert_allclose(outs[1], outs[0], atol=1e-4, rtol=1e-4)


@pytest.mark.parametrize("quantized", [False, True])
def test_transformer_prefill_kernel_parity(model_and_params, quantized):
    """Two engine-shaped prefill chunks — a ragged first chunk from
    empty caches, then a ragged cached-prefix tail chunk — through the
    paged branch with the Pallas prefill kernel forced on (interpret)
    match the XLA gather branch per valid row, on plain and int8 pools.
    Padded tail rows (j >= valid_lens) are garbage in both paths and
    excluded."""
    model, params = model_and_params
    cfg_off = model.cfg.replace(paged_attention_kernel="off",
                                paged_prefill_kernel="off")
    cfg_on = model.cfg.replace(paged_attention_kernel="off",
                               paged_prefill_kernel="on")
    Sl, Cc = 2, 16
    bt = jnp.asarray(np.arange(1, 1 + Sl * M).reshape(Sl, M), jnp.int32)
    v0 = jnp.asarray([5, 16], jnp.int32)     # ragged first chunk
    v1 = jnp.asarray([9, 7], jnp.int32)      # ragged tail chunk
    toks0 = jnp.asarray(np.arange(Sl * Cc).reshape(Sl, Cc) % 60 + 1,
                        jnp.int32)
    toks1 = jnp.asarray((np.arange(Sl * Cc).reshape(Sl, Cc) * 3) % 60 + 1,
                        jnp.int32)
    outs = []
    for cfg in (cfg_off, cfg_on):
        pages = init_paged_kv_caches(model.cfg, 1 + int(bt.max()), BS,
                                     quantized=quantized)
        caches = [dict(p, block_tables=bt,
                       context_lens=jnp.zeros((Sl,), jnp.int32),
                       valid_lens=v0) for p in pages]
        pos0 = jnp.broadcast_to(jnp.arange(Cc)[None, :], (Sl, Cc))
        lg0, caches = language_model_forward(params, toks0, pos0, None,
                                             cfg, rng_key=None,
                                             train=False,
                                             kv_caches=caches)
        caches = [dict(c, valid_lens=v1) for c in caches]
        pos1 = v0[:, None] + jnp.arange(Cc)[None, :]
        lg1, _ = language_model_forward(params, toks1, pos1, None, cfg,
                                        rng_key=None, train=False,
                                        kv_caches=caches)
        outs.append((np.asarray(lg0, np.float32),
                     np.asarray(lg1, np.float32)))
    (a0, a1), (b0, b1) = outs
    for s in range(Sl):
        np.testing.assert_allclose(b0[s, :int(v0[s])], a0[s, :int(v0[s])],
                                   atol=2e-4, rtol=2e-4)
        np.testing.assert_allclose(b1[s, :int(v1[s])], a1[s, :int(v1[s])],
                                   atol=2e-4, rtol=2e-4)
